"""Endpoint-chaos gate: concurrent clients against the Arrow-over-TCP query
endpoint, with a client killed mid-flight, a submission shed over the wire,
and a SIGTERM graceful drain under load.

The serving contract (runtime/endpoint.py), proven end to end in one
process:

  - q5 is submitted over TCP and its client is KILLED while the query is
    mid-aggregation (a ``slow:agg.update`` fault pins the race): the server
    detects the half-close, fires the query's CancelToken
    (``client.disconnected`` + ``query.cancelled`` in the event log), and
    the drain leaks nothing — threads, catalog buffers, semaphore permits.
  - q1 and q3 are the survivors: their endpoint results are bit-identical
    to direct in-process collects, with every query-scoped resilience
    counter zero (the wire's summary frame carries the scoped counters).
  - a submission against a deterministically full scheduler sheds with a
    retryable QueryRejectedError whose ``backoff_hint_s`` arrives TYPED at
    the client — the pickle round-trip is the wire itself.
  - SIGTERM (the real signal, via install_signal_handlers) drains the
    endpoint under load: an in-flight q1 finishes bit-identically, a
    submission arriving mid-drain sheds with reason ``draining`` and a
    backoff hint, and ``server.drain`` begin/end land in the event log.

Usage:
  python tools/endpoint_chaos.py --data-dir DIR --eventlog-dir DIR
"""

from __future__ import annotations

import argparse
import gc
import json
import os
import pathlib
import signal
import sys
import threading
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="endpoint_chaos.py", description=__doc__)
    p.add_argument("--data-dir", required=True)
    p.add_argument("--eventlog-dir", required=True)
    p.add_argument("--sf", type=float, default=0.01)
    args = p.parse_args(argv)

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.runtime import eventlog
    from spark_rapids_tpu.runtime import faults
    from spark_rapids_tpu.runtime import metrics as M
    from spark_rapids_tpu.runtime import scheduler as SCHED
    from spark_rapids_tpu.runtime.endpoint import EndpointClient
    from spark_rapids_tpu.runtime.memory import DeviceManager
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.sql.tpch_queries import SQL_QUERIES

    paths = tpch.generate(args.sf, args.data_dir)
    base_conf = {
        "spark.rapids.tpu.sql.format.parquet.reader.type": "COALESCING",
        "spark.rapids.tpu.pipeline.enabled": True,
    }

    # -- solo baselines (no faults, before the event log opens) --------------
    solo_spark = TpuSession(base_conf)
    tpch.load(solo_spark, paths, files_per_partition=4)
    solo = {q: solo_spark.sql(SQL_QUERIES[q]).collect().to_pylist()
            for q in ("q1", "q3", "q5")}

    cat = DeviceManager.get().catalog
    buffers_base = cat.num_buffers

    # -- the serving session: event log armed, endpoint up --------------------
    server_spark = TpuSession(dict(base_conf, **{
        "spark.rapids.tpu.eventLog.dir": args.eventlog_dir,
        "spark.rapids.tpu.scheduler.maxConcurrent": 4,
    }))
    tpch.load(server_spark, paths, files_per_partition=4)
    ep = server_spark.serve()
    addr = ("127.0.0.1", ep.port)

    outcomes: dict = {}
    lock = threading.Lock()

    def record(name, **kv):
        with lock:
            outcomes[name] = kv

    def run_client(name, q, delay_s):
        time.sleep(delay_s)
        cli = EndpointClient(addr, timeout_s=120)
        try:
            rows = cli.submit(SQL_QUERIES[q]).to_pylist()
            record(name, rows=rows, summary=cli.last_summary)
        except BaseException as e:  # noqa: BLE001 — reported, asserted below
            record(name, error=type(e).__name__, detail=repr(e)[:200])

    # -- wave 1: kill victim (head start) + two survivors ---------------------
    # the slow faults land in the victim's aggregation (it runs alone during
    # its head start), holding it mid-query while its socket is killed; any
    # leftover slow hits in a survivor only add 250ms sleeps, never errors
    faults.configure("slow:agg.update:4", seed=3)
    killed = {}

    def kill_victim():
        from spark_rapids_tpu.runtime.endpoint import MSG_SUBMIT
        from spark_rapids_tpu.shuffle.transport import send_frame
        cli = EndpointClient(addr, timeout_s=120)
        sock = cli.connect()
        send_frame(sock, MSG_SUBMIT,
                   json.dumps({"sql": SQL_QUERIES["q5"],
                               "description": "kill-victim"}).encode())
        time.sleep(0.3)            # mid-aggregation (slowed ~1s)
        sock.close()               # the kill: half-close mid-flight
        killed["closed_at"] = time.time()

    threads = [
        threading.Thread(target=kill_victim, daemon=True),
        threading.Thread(target=run_client, args=("q1", "q1", 0.5),
                         daemon=True),
        threading.Thread(target=run_client, args=("q3", "q3", 0.6),
                         daemon=True),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    # the cancelled victim must fully drain off the endpoint
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and ep.active_queries():
        time.sleep(0.05)
    faults.reset()

    # -- shed over the wire: deterministically full scheduler -----------------
    sched = SCHED.QueryScheduler.get()
    occupant = f"occupant-{id(sched):x}"
    sched.submit(occupant, 1, description="endpoint-shed occupant")
    saved_max = sched.max_concurrent
    sched.max_concurrent = 1
    shed_err = None
    try:
        EndpointClient(addr, timeout_s=120).submit(
            SQL_QUERIES["q1"], queue_timeout_s=0.05)
    except SCHED.QueryRejectedError as e:
        shed_err = e
    except BaseException as e:  # noqa: BLE001
        shed_err = e
    finally:
        sched.max_concurrent = saved_max
        sched.release(occupant)

    # -- SIGTERM drain under load ---------------------------------------------
    # q5 is the in-flight victim: its 4 join builds + aggregation give the
    # slow faults enough sites to hold it mid-query for several seconds, so
    # the mid-drain probe deterministically lands while it is still running
    ep.install_signal_handlers(grace_s=60)
    faults.configure("slow:joins.build:8,slow:agg.update:8", seed=3)
    drain_flight = {}

    def drain_client():
        cli = EndpointClient(addr, timeout_s=120)
        try:
            drain_flight["rows"] = cli.submit(SQL_QUERIES["q5"]).to_pylist()
        except BaseException as e:  # noqa: BLE001
            drain_flight["error"] = repr(e)[:200]

    dt = threading.Thread(target=drain_client, daemon=True)
    dt.start()
    time.sleep(0.5)                       # in-flight mid-aggregation
    os.kill(os.getpid(), signal.SIGTERM)  # the real signal path
    t0 = time.monotonic()
    while time.monotonic() - t0 < 10 and not ep.draining:
        time.sleep(0.02)
    drain_shed = None
    try:
        EndpointClient(addr, timeout_s=120).submit(SQL_QUERIES["q3"])
    except BaseException as e:  # noqa: BLE001
        drain_shed = e
    dt.join(timeout=120)
    # the drain thread closes the endpoint once in-flight queries finish
    t0 = time.monotonic()
    while time.monotonic() - t0 < 60 and ep._thread.is_alive():
        time.sleep(0.05)
    faults.reset()
    eventlog.shutdown()

    # -- assertions -----------------------------------------------------------
    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    # survivors bit-identical over the wire, scoped counters clean
    for name in ("q1", "q3"):
        o = outcomes.get(name, {})
        check(o.get("rows") == solo[name],
              f"{name} endpoint rows differ from solo "
              f"({o.get('error', 'rows mismatch')})")
        check(not (o.get("summary") or {}).get("resilience"),
              f"{name} scoped resilience leaked: {o.get('summary')}")
    # the killed client's query was cancelled by the disconnect path
    snap = M.resilience_snapshot()
    check(snap.get("clientDisconnects", 0) >= 1,
          f"no client disconnect counted: {snap}")
    check(snap.get("queriesCancelled", 0) >= 1,
          f"no query cancelled by the kill: {snap}")
    # the shed submission arrived typed with its backoff hint intact
    check(isinstance(shed_err, SCHED.QueryRejectedError),
          f"shed outcome was {shed_err!r}, wanted QueryRejectedError")
    if isinstance(shed_err, SCHED.QueryRejectedError):
        check(shed_err.retryable and shed_err.backoff_hint_s > 0,
              f"shed error lost its contract: {vars(shed_err)}")
    # drain: in-flight finished bit-identical, mid-drain submission shed
    check(drain_flight.get("rows") == solo["q5"],
          f"in-flight query diverged under drain: {drain_flight}")
    check(isinstance(drain_shed, SCHED.QueryRejectedError)
          and getattr(drain_shed, "reason", "") == "draining"
          and drain_shed.backoff_hint_s > 0,
          f"mid-drain submission outcome was {drain_shed!r}")
    check(not ep._thread.is_alive(), "endpoint listener thread survived drain")

    # nothing leaked: threads, device buffers, semaphore permits
    gc.collect()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (
            cat.num_buffers > buffers_base
            or any(t.name.startswith(("srt-pipe-", "srt-endpoint"))
                   for t in threading.enumerate())):
        time.sleep(0.1)
    check(cat.num_buffers <= buffers_base,
          f"leaked {cat.num_buffers - buffers_base} catalog buffers")
    check(not TpuSemaphore.get()._holders,
          f"leaked semaphore permits: {TpuSemaphore.get()._holders}")
    stragglers = [t.name for t in threading.enumerate()
                  if t.name.startswith(("srt-pipe-", "srt-endpoint"))]
    check(not stragglers, f"leaked endpoint/pipeline threads: {stragglers}")

    print(json.dumps({
        "outcomes": {k: {kk: vv for kk, vv in v.items() if kk != "rows"}
                     for k, v in outcomes.items()},
        "shed": (None if not isinstance(shed_err, SCHED.QueryRejectedError)
                 else {"backoff_hint_s": shed_err.backoff_hint_s,
                       "reason": shed_err.reason}),
        "drain_shed": (None if not isinstance(drain_shed,
                                              SCHED.QueryRejectedError)
                       else {"backoff_hint_s": drain_shed.backoff_hint_s,
                             "reason": drain_shed.reason}),
        "resilience": {k: v for k, v in snap.items() if v},
        "failures": failures,
    }, default=str))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
