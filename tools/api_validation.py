"""api_validation — coverage diff against the reference's override surface.

Reference: the api_validation module (reference tools/) walks Spark's
expression/exec catalog and reports what the plugin covers. Standalone analog:
diff THIS engine's rule registry against the expression/exec rule lists
extracted from the reference's GpuOverrides.scala:773-2987 (`expr[...]` /
`exec[...]` registrations @ reference snapshot 2025-01-14) and write
docs/api_coverage.md. CI runs this so silent coverage regressions fail fast.
"""

from __future__ import annotations

import pathlib
import sys

# `expr[X]` names in reference GpuOverrides.scala (sorted, deduplicated)
REFERENCE_EXPRS = """
Abs Acos Acosh Add AggregateExpression Alias And ArrayContains Asin Asinh
AtLeastNNonNulls Atan Atanh AttributeReference Average BRound BitwiseAnd
BitwiseNot BitwiseOr BitwiseXor CaseWhen Cbrt Ceil CheckOverflow Coalesce
CollectList Concat Contains Cos Cosh Cot Count CreateArray CreateNamedStruct
DateAdd DateAddInterval DateDiff DateFormatClass DateSub DayOfMonth DayOfWeek
DayOfYear Divide ElementAt EndsWith EqualNullSafe EqualTo Exp Explode Expm1
First Floor FromUnixTime GetArrayItem GetJsonObject GetMapValue GetStructField
GreaterThan GreaterThanOrEqual Greatest Hour If In InSet InitCap
InputFileBlockLength InputFileBlockStart InputFileName IntegralDivide IsNaN
IsNotNull IsNull KnownFloatingPointNormalized Lag Last LastDay Lead Least
Length LessThan LessThanOrEqual Like Literal Log Log10 Log1p Log2 Logarithm
Lower MakeDecimal Max Md5 Min Minute MonotonicallyIncreasingID Month Multiply
Murmur3Hash NaNvl NormalizeNaNAndZero Not Or PivotFirst Pmod PosExplode Pow
PromotePrecision PythonUDF Quarter Rand Remainder Rint Round RowNumber
ScalarSubquery Second ShiftLeft ShiftRight ShiftRightUnsigned Signum Sin Sinh
Size SortOrder SparkPartitionID SpecifiedWindowFrame Sqrt StartsWith
StringLPad StringLocate StringRPad StringReplace StringSplit StringTrim
StringTrimLeft StringTrimRight Substring SubstringIndex Subtract Sum Tan Tanh
TimeAdd ToDegrees ToRadians ToUnixTimestamp UnaryMinus UnaryPositive
UnixTimestamp UnscaledValue Upper WeekDay WindowExpression
WindowSpecDefinition Year
""".split()

# `exec[X]` names in reference GpuOverrides.scala
REFERENCE_EXECS = """
BatchScanExec BroadcastExchangeExec BroadcastNestedLoopJoinExec
CartesianProductExec CoalesceExec CollectLimitExec CustomShuffleReaderExec
DataWritingCommandExec ExpandExec FilterExec FlatMapCoGroupsInPandasExec
GenerateExec GlobalLimitExec HashAggregateExec LocalLimitExec ProjectExec
RangeExec ShuffleExchangeExec SortAggregateExec SortExec
TakeOrderedAndProjectExec UnionExec WindowExec
""".split()

# reference name → this engine's covering construct, where names differ.
# None (in the map) = deliberately not applicable, with the reason.
EXPR_ALIASES = {
    "AggregateExpression": "AggregateFunction (expr/aggregates.py)",
    "Explode": "GenerateNode/GenerateExec (plan/nodes.py, exec/generate.py)",
    "PosExplode": "GenerateNode(pos=True)",
    "SortOrder": "ops/sorting.py SortOrder",
    "SpecifiedWindowFrame": "expr/windows.py WindowFrame",
    "WindowSpecDefinition": "expr/windows.py WindowSpec",
    "KnownFloatingPointNormalized": "implicit: engine canonicalizes -0.0/NaN "
                                    "at ingestion (columnar/vector.py)",
    "NormalizeNaNAndZero": "implicit: engine canonicalizes -0.0/NaN at "
                           "ingestion (columnar/vector.py)",
    "BRound": "Round (HALF_UP; HALF_EVEN flavor pending)",
    "StringTrim": "Trim (expr/strings.py)",
    "StringTrimLeft": "LTrim (expr/strings.py)",
    "StringTrimRight": "RTrim (expr/strings.py)",
    "InSet": "In (the engine keeps literal lists in the In expression)",
}

EXEC_ALIASES = {
    "BatchScanExec": "FileScanNode/FileSourceScanExec (io/filescan.py)",
    "BroadcastExchangeExec": "BroadcastExchangeExec (exec/broadcast.py)",
    "BroadcastNestedLoopJoinExec": "NestedLoopJoinExec (exec/joins.py)",
    "CartesianProductExec": "CartesianJoin (exec/joins.py)",
    "CoalesceExec": "CoalesceBatchesExec (exec/coalesce.py)",
    "CollectLimitExec": "LimitNode global (plan/nodes.py)",
    "CustomShuffleReaderExec": "AdaptiveShuffleReaderExec (exec/exchange.py)",
    "DataWritingCommandExec": "io/writer.py write_parquet/orc/csv",
    "FlatMapCoGroupsInPandasExec": "udf/python_runtime.py worker pool "
                                   "(cogroup shape pending)",
    "GlobalLimitExec": "LimitNode(global_limit=True)",
    "LocalLimitExec": "LimitNode(global_limit=False)",
    "SortAggregateExec": "HashAggregateExec (sort-based internally — the "
                         "TPU design is always sort-based)",
    "HashAggregateExec": "exec/aggregate.py HashAggregateExec",
    "RangeExec": "RangeNode (plan/nodes.py)",
}


def registry_names():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.plan.overrides import REGISTRY
    exprs = {cls.__name__ for cls in REGISTRY.expr_rules}
    execs = {cls.__name__ for cls in REGISTRY.exec_rules}
    return exprs, execs


def build_report() -> tuple[str, int]:
    exprs, execs = registry_names()
    lines = [
        "# API coverage vs reference GpuOverrides",
        "",
        "Generated by `python tools/api_validation.py` (reference rule lists "
        "extracted from GpuOverrides.scala:773-2987 `expr[...]`/`exec[...]`).",
        "",
        "## Expressions",
        "",
        "| Reference expression | Status |",
        "|---|---|",
    ]
    missing = 0
    for name in REFERENCE_EXPRS:
        if name in exprs:
            status = "supported"
        elif name in EXPR_ALIASES:
            status = f"covered by {EXPR_ALIASES[name]}"
        else:
            # second chance: registry may use a Gpu-free variant of the name
            alt = [e for e in exprs if e.lower() == name.lower()]
            if alt:
                status = f"supported (as {alt[0]})"
            else:
                status = "**missing**"
                missing += 1
        lines.append(f"| {name} | {status} |")
    lines += ["", "## Execs", "", "| Reference exec | Status |", "|---|---|"]
    exec_map = {
        "ExpandExec": "ExpandNode", "FilterExec": "FilterNode",
        "ProjectExec": "ProjectNode", "SortExec": "SortNode",
        "UnionExec": "UnionNode", "WindowExec": "WindowNode",
        "ShuffleExchangeExec": "ExchangeNode", "GenerateExec": "GenerateNode",
        "TakeOrderedAndProjectExec": "SortNode + LimitNode",
    }
    for name in REFERENCE_EXECS:
        ours = exec_map.get(name, name)
        if ours in execs or any(o in execs for o in ours.split(" + ")):
            status = f"supported ({ours})"
        elif name in EXEC_ALIASES:
            status = f"covered by {EXEC_ALIASES[name]}"
        else:
            status = "**missing**"
            missing += 1
        lines.append(f"| {name} | {status} |")
    n_expr = len(REFERENCE_EXPRS)
    n_sup = sum(1 for ln in lines if "| **missing** |" not in ln
                and ln.startswith("| "))
    lines += ["",
              f"Missing: **{missing}** of {n_expr + len(REFERENCE_EXECS)} "
              "reference rules.", ""]
    return "\n".join(lines), missing


def main():
    report, missing = build_report()
    out = pathlib.Path(__file__).resolve().parent.parent / "docs" / \
        "api_coverage.md"
    out.write_text(report)
    print(f"wrote {out} ({missing} missing)")
    # CI gate: fail only if coverage regresses below the checked-in floor
    floor = int(sys.argv[1]) if len(sys.argv) > 1 else None
    if floor is not None and missing > floor:
        print(f"FAIL: {missing} missing > allowed floor {floor}")
        sys.exit(1)


if __name__ == "__main__":
    main()
