"""api_validation — coverage diff against the reference's override surface.

Reference: the api_validation module (reference tools/) walks Spark's
expression/exec catalog and reports what the plugin covers. Standalone analog:
diff THIS engine's rule registry against the expression/exec rule lists
extracted from the reference's GpuOverrides.scala:773-2987 (`expr[...]` /
`exec[...]` registrations @ reference snapshot 2025-01-14) and write
docs/api_coverage.md. CI runs this so silent coverage regressions fail fast.
"""

from __future__ import annotations

import pathlib
import sys

# `expr[X]` names in reference GpuOverrides.scala (sorted, deduplicated)
REFERENCE_EXPRS = """
Abs Acos Acosh Add AggregateExpression Alias And ArrayContains Asin Asinh
AtLeastNNonNulls Atan Atanh AttributeReference Average BRound BitwiseAnd
BitwiseNot BitwiseOr BitwiseXor CaseWhen Cbrt Ceil CheckOverflow Coalesce
CollectList Concat Contains Cos Cosh Cot Count CreateArray CreateNamedStruct
DateAdd DateAddInterval DateDiff DateFormatClass DateSub DayOfMonth DayOfWeek
DayOfYear Divide ElementAt EndsWith EqualNullSafe EqualTo Exp Explode Expm1
First Floor FromUnixTime GetArrayItem GetJsonObject GetMapValue GetStructField
GreaterThan GreaterThanOrEqual Greatest Hour If In InSet InitCap
InputFileBlockLength InputFileBlockStart InputFileName IntegralDivide IsNaN
IsNotNull IsNull KnownFloatingPointNormalized Lag Last LastDay Lead Least
Length LessThan LessThanOrEqual Like Literal Log Log10 Log1p Log2 Logarithm
Lower MakeDecimal Max Md5 Min Minute MonotonicallyIncreasingID Month Multiply
Murmur3Hash NaNvl NormalizeNaNAndZero Not Or PivotFirst Pmod PosExplode Pow
PromotePrecision PythonUDF Quarter Rand Remainder Rint Round RowNumber
ScalarSubquery Second ShiftLeft ShiftRight ShiftRightUnsigned Signum Sin Sinh
Size SortOrder SparkPartitionID SpecifiedWindowFrame Sqrt StartsWith
StringLPad StringLocate StringRPad StringReplace StringSplit StringTrim
StringTrimLeft StringTrimRight Substring SubstringIndex Subtract Sum Tan Tanh
TimeAdd ToDegrees ToRadians ToUnixTimestamp UnaryMinus UnaryPositive
UnixTimestamp UnscaledValue Upper WeekDay WindowExpression
WindowSpecDefinition Year
""".split()

# `exec[X]` names in reference GpuOverrides.scala
REFERENCE_EXECS = """
BatchScanExec BroadcastExchangeExec BroadcastNestedLoopJoinExec
CartesianProductExec CoalesceExec CollectLimitExec CustomShuffleReaderExec
DataWritingCommandExec ExpandExec FilterExec FlatMapCoGroupsInPandasExec
GenerateExec GlobalLimitExec HashAggregateExec LocalLimitExec ProjectExec
RangeExec ShuffleExchangeExec SortAggregateExec SortExec
TakeOrderedAndProjectExec UnionExec WindowExec
""".split()

# reference name → ("aliased", covering construct): full semantics under a
# different name/construct. reference name → ("partial", what's missing):
# acknowledged gap — REPORTED AND GATED SEPARATELY, never counted as covered.
EXPR_ALIASES = {
    "AggregateExpression": ("aliased", "AggregateFunction (expr/aggregates.py)"),
    "Explode": ("aliased", "GenerateNode/GenerateExec (plan/nodes.py, exec/generate.py)"),
    "PosExplode": ("aliased", "GenerateNode(pos=True)"),
    "SortOrder": ("aliased", "ops/sorting.py SortOrder"),
    "SpecifiedWindowFrame": ("aliased", "expr/windows.py WindowFrame"),
    "WindowSpecDefinition": ("aliased", "expr/windows.py WindowSpec"),
    "KnownFloatingPointNormalized": ("aliased", "implicit: engine canonicalizes "
                                    "-0.0/NaN at ingestion (columnar/vector.py)"),
    "NormalizeNaNAndZero": ("aliased", "implicit: engine canonicalizes "
                            "-0.0/NaN at ingestion (columnar/vector.py)"),
    "StringTrim": ("aliased", "Trim (expr/strings.py)"),
    "StringTrimLeft": ("aliased", "LTrim (expr/strings.py)"),
    "StringTrimRight": ("aliased", "RTrim (expr/strings.py)"),
}

EXEC_ALIASES = {
    "BatchScanExec": ("aliased", "FileScanNode/FileSourceScanExec (io/filescan.py)"),
    "BroadcastExchangeExec": ("aliased", "BroadcastExchangeExec (exec/broadcast.py)"),
    "BroadcastNestedLoopJoinExec": ("aliased", "NestedLoopJoinExec (exec/joins.py)"),
    "CartesianProductExec": ("aliased", "CartesianJoin (exec/joins.py)"),
    "CoalesceExec": ("aliased", "CoalesceBatchesExec (exec/coalesce.py)"),
    "CollectLimitExec": ("aliased", "LimitNode global (plan/nodes.py)"),
    "CustomShuffleReaderExec": ("aliased", "AdaptiveShuffleReaderExec (exec/exchange.py)"),
    "DataWritingCommandExec": ("aliased", "io/writer.py write_parquet/orc/csv"),
    "FlatMapCoGroupsInPandasExec": ("aliased", "CoGroupedMapInPandasExec "
                                    "(udf/pandas_exec.py) over co-partitioned "
                                    "hash exchanges"),
    "GlobalLimitExec": ("aliased", "LimitNode(global_limit=True)"),
    "LocalLimitExec": ("aliased", "LimitNode(global_limit=False)"),
    "SortAggregateExec": ("aliased", "HashAggregateExec (sort-based internally "
                          "— the TPU design is always sort-based)"),
    "HashAggregateExec": ("aliased", "exec/aggregate.py HashAggregateExec"),
    "RangeExec": ("aliased", "RangeNode (plan/nodes.py)"),
}


def registry_names():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    from spark_rapids_tpu.plan.overrides import REGISTRY
    exprs = {cls.__name__ for cls in REGISTRY.expr_rules}
    execs = {cls.__name__ for cls in REGISTRY.exec_rules}
    return exprs, execs


def _classify(name, registered, aliases):
    """(kind, status-cell). Kinds: full | aliased | partial | missing."""
    if name in registered:
        return "full", "supported"
    if name in aliases:
        kind, what = aliases[name]
        label = "covered by" if kind == "aliased" else "**partial** —"
        return kind, f"{label} {what}"
    alt = [e for e in registered if e.lower() == name.lower()]
    if alt:
        return "full", f"supported (as {alt[0]})"
    return "missing", "**missing**"


def build_report() -> tuple[str, dict]:
    exprs, execs = registry_names()
    counts = {"full": 0, "aliased": 0, "partial": 0, "missing": 0}
    lines = [
        "# API coverage vs reference GpuOverrides",
        "",
        "Generated by `python tools/api_validation.py` (reference rule lists "
        "extracted from GpuOverrides.scala:773-2987 `expr[...]`/`exec[...]`).",
        "",
        "Status legend: **supported** = same-named rule in the registry; "
        "**covered by** = full semantics under a different construct; "
        "**partial** = acknowledged gap, counted separately and CI-gated; "
        "**missing** = no coverage.",
        "",
        "## Expressions",
        "",
        "| Reference expression | Status |",
        "|---|---|",
    ]
    for name in REFERENCE_EXPRS:
        kind, status = _classify(name, exprs, EXPR_ALIASES)
        counts[kind] += 1
        lines.append(f"| {name} | {status} |")
    lines += ["", "## Execs", "", "| Reference exec | Status |", "|---|---|"]
    exec_map = {
        "ExpandExec": "ExpandNode", "FilterExec": "FilterNode",
        "ProjectExec": "ProjectNode", "SortExec": "SortNode",
        "UnionExec": "UnionNode", "WindowExec": "WindowNode",
        "ShuffleExchangeExec": "ExchangeNode", "GenerateExec": "GenerateNode",
        "TakeOrderedAndProjectExec": "SortNode + LimitNode",
    }
    for name in REFERENCE_EXECS:
        ours = exec_map.get(name, name)
        if ours in execs or any(o in execs for o in ours.split(" + ")):
            counts["full"] += 1
            lines.append(f"| {name} | supported ({ours}) |")
        else:
            kind, status = _classify(name, execs, EXEC_ALIASES)
            counts[kind] += 1
            lines.append(f"| {name} | {status} |")
    total = len(REFERENCE_EXPRS) + len(REFERENCE_EXECS)
    lines += ["",
              f"Totals over {total} reference rules: "
              f"**{counts['full']} full**, {counts['aliased']} aliased "
              f"(full semantics, different construct), "
              f"**{counts['partial']} partial**, "
              f"**{counts['missing']} missing**.", ""]
    return "\n".join(lines), counts


def main():
    report, counts = build_report()
    out = pathlib.Path(__file__).resolve().parent.parent / "docs" / \
        "api_coverage.md"
    out.write_text(report)
    print(f"wrote {out} ({counts})")
    # CI gate: both the missing count AND the partial count have floors —
    # an acknowledged gap can never silently count as covered
    floor_missing = int(sys.argv[1]) if len(sys.argv) > 1 else None
    floor_partial = int(sys.argv[2]) if len(sys.argv) > 2 else None
    if floor_missing is not None and counts["missing"] > floor_missing:
        print(f"FAIL: {counts['missing']} missing > floor {floor_missing}")
        sys.exit(1)
    if floor_partial is not None and counts["partial"] > floor_partial:
        print(f"FAIL: {counts['partial']} partial > floor {floor_partial}")
        sys.exit(1)


if __name__ == "__main__":
    main()
