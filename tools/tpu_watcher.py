"""Background TPU-availability watcher (round 4).

The tunnel flaps: 16 probes failed over 6h, then it answered at 03:46 UTC,
then wedged again at 04:02 after an external kill. This watcher closes the
loop the VERDICT asked for — probe often, and the MOMENT the chip answers,
run the two on-chip deliverables before it can wedge again:

  1. tools/tpu_correctness.py  -> TPU_CORRECTNESS.json  (numeric-regime subset)
  2. bench.py                  -> BENCH_ONCHIP.json     (TPC-H ladder, value-checked)

Every attempt is logged to docs/perf_notes.md via tpu_probe.log_result.
Exits when both artifacts exist with platform=tpu, or when --max-hours is up.

Usage: python tools/tpu_watcher.py [--interval 240] [--max-hours 10]
"""

import argparse
import json
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))
from tpu_probe import probe, log_result  # noqa: E402


def _bench_paused() -> bool:
    """bench.py holds a pause file around timed sections — probing then
    would share the box with the measurement and inflate its spread (the
    r5 variance postmortem). Stale files (>1h: a killed bench) are ignored
    so a crash can never silence the watcher."""
    p = pathlib.Path(os.environ.get("SRT_BENCH_PAUSE_FILE",
                                    "/tmp/srt_bench_pause"))
    try:
        return (time.time() - p.stat().st_mtime) < 3600
    except OSError:
        return False


def _have_correctness():
    p = REPO / "TPU_CORRECTNESS.json"
    if not p.exists():
        return False
    try:
        return json.loads(p.read_text()).get("platform") == "tpu"
    except (ValueError, OSError):
        return False


def _have_bench():
    p = REPO / "BENCH_ONCHIP.json"
    if not p.exists():
        return False
    try:
        d = json.loads(p.read_text())
        return d.get("value", 0) > 0 and "degraded" not in d
    except (ValueError, OSError):
        return False


def _run_correctness():
    # generous budget, but bounded: a child hung on a wedged tunnel is not a
    # live dispatch (the tunnel is already gone), and an unbounded wait would
    # defeat --max-hours entirely
    try:
        proc = subprocess.run(
            [sys.executable, str(REPO / "tools" / "tpu_correctness.py"),
             "--out", str(REPO / "TPU_CORRECTNESS.json")],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, timeout=3600)
    except subprocess.TimeoutExpired:
        log_result(False, "correctness child hit 3600s watcher budget",
                   "watcher")
        return False
    tail = (proc.stdout or "")[-1500:]
    print(f"[watcher] correctness rc={proc.returncode}\n{tail}", flush=True)
    return proc.returncode == 0


def _run_bench():
    # bench.py is self-probing and always prints one JSON line; budget covers
    # its full ladder (2400s child + 1200s fallback + probes) with slack
    try:
        proc = subprocess.run(
            [sys.executable, str(REPO / "bench.py")],
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, timeout=7200)
    except subprocess.TimeoutExpired:
        log_result(False, "bench hit 7200s watcher budget", "watcher")
        return False
    out = proc.stdout or ""
    print(f"[watcher] bench rc={proc.returncode}: {out[-1000:]}", flush=True)
    for ln in reversed(out.splitlines()):
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                d = json.loads(ln)
            except ValueError:
                continue
            if "metric" in d:
                (REPO / "BENCH_ONCHIP.json").write_text(json.dumps(d, indent=1))
                ok = "degraded" not in d
                log_result(ok, f"bench {d['metric']} value={d['value']} "
                               f"{d['unit']} vs_baseline={d['vs_baseline']}"
                               + ("" if ok else f" DEGRADED {d['degraded'][:120]}"),
                           "watcher on-chip bench")
                return ok
    return False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--interval", type=float, default=240.0)
    ap.add_argument("--max-hours", type=float, default=10.0)
    args = ap.parse_args()
    deadline = time.time() + args.max_hours * 3600
    n = 0
    while time.time() < deadline:
        if _bench_paused():
            time.sleep(30)
            continue
        n += 1
        ok, detail = probe(75.0)
        if not ok:
            # log_result collapses consecutive timeout failures into one
            # `first → last ×N` line, so logging every probe stays bounded
            log_result(False, detail, f"watcher probe #{n}")
            time.sleep(args.interval)
            continue
        log_result(True, detail, f"watcher probe #{n}: chip is up")
        if not _have_correctness():
            _run_correctness()
        if _have_correctness() and not _have_bench():
            _run_bench()
        if _have_correctness() and _have_bench():
            print("[watcher] both on-chip artifacts captured; done", flush=True)
            return 0
        time.sleep(args.interval)
    print("[watcher] deadline reached", flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main())
