"""cluster_chaos — CI gate for the MiniCluster's fault-recovery ladder.

Runs one TPC-H query twice on a 3-executor MiniCluster — clean, then with
an injected executor SIGKILL (`exec_kill` fault, runtime/faults.py) — and
asserts the recovery contract end to end:

  - the killed run's result is bit-identical to the clean run's;
  - recovery was lineage-scoped: strictly fewer map tasks recomputed than
    the clean run executed (losing 1 of N executors costs ~1/N of a
    stage), and the whole-query `_heal()` fallback never fired;
  - the ladder is visible in the structured event log (`executor.lost`,
    `stage.recompute.partial`).

Must be a real script file, not a `python -` heredoc: the spawn-based
executor bootstrap re-imports __main__, and stdin cannot be re-imported.

`--mesh` switches both runs onto the UNIFIED MESH-CLUSTER PLANE
(spark.rapids.tpu.cluster.mesh.enabled): every executor drives a local
device mesh and map stages run as mesh task groups. The gate then also
asserts the mesh-specific recovery contract: the clean run used mesh tasks
with ZERO resilience noise (meshDegradedFallbacks included), and the
killed run — a participant SIGKILLed inside the mesh collective — degraded
transparently to the per-split TCP path (meshDegradedFallbacks >= 1,
`mesh.degraded` in the event log) while staying bit-identical.

Usage:
  python tools/cluster_chaos.py --data-dir /tmp/tpch_sf0.01 \
      [--eventlog-dir DIR] [--query q18] [--scale 0.01] [--executors 3] \
      [--fault exec_kill:cluster.result:1] [--mesh] [--mesh-devices 4]
"""

from __future__ import annotations

import argparse
import pathlib
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cluster_chaos.py", description=__doc__)
    p.add_argument("--data-dir", required=True)
    p.add_argument("--eventlog-dir", default=None)
    p.add_argument("--trace-dir", default=None,
                   help="span-file directory (spark.rapids.tpu.trace.dir) "
                        "for the distributed trace of both runs; defaults "
                        "to --eventlog-dir when that is set")
    p.add_argument("--query", default="q18")
    p.add_argument("--scale", type=float, default=0.01)
    p.add_argument("--executors", type=int, default=3)
    # default: SIGKILL executor 0 as it STARTS its result task — every map
    # stage's outputs exist by then, so recovery must rebuild exactly the
    # dead peer's splits; the task-start site fires even for a query whose
    # final stage emits zero batches (q18 at sf0.01 returns 0 rows)
    p.add_argument("--fault", default=None)
    p.add_argument("--mesh", action="store_true",
                   help="run both collections on the unified mesh-cluster "
                        "plane and assert the degraded-fallback contract")
    p.add_argument("--mesh-devices", type=int, default=4)
    args = p.parse_args(argv)
    if args.fault is None:
        # mesh default: SIGKILL whichever executor reaches its SECOND mesh
        # task's bring-up (@1 skips each process's first hit of the
        # non-indexed site) — inside the mesh-task region, after the
        # victim's first group parked outputs, so the loss exercises both
        # the degraded re-plan AND the lineage-scoped recompute. The site
        # is deliberately not executor-indexed: the two-level exchange
        # places each mesh group at its partition owner, so which executor
        # collects two groups first is a placement detail, not a contract.
        args.fault = ("exec_kill:cluster.mesh.begin:1@1" if args.mesh
                      else "exec_kill:cluster.result.begin.0:1")

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.cluster import MiniCluster
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.runtime import eventlog
    from spark_rapids_tpu.runtime import metrics as M
    from spark_rapids_tpu.session import TpuSession

    paths = tpch.generate(args.scale, args.data_dir)
    settings = {}
    if args.eventlog_dir:
        settings["spark.rapids.tpu.eventLog.dir"] = args.eventlog_dir
    trace_dir = args.trace_dir or args.eventlog_dir
    if trace_dir:
        settings["spark.rapids.tpu.trace.dir"] = trace_dir
    if args.mesh:
        settings["spark.rapids.tpu.cluster.mesh.enabled"] = "true"
        settings["spark.rapids.tpu.cluster.mesh.devicesPerExecutor"] = \
            str(args.mesh_devices)
    spark = TpuSession(settings)
    if args.mesh:
        # explicit sorted file lists, one file per split: directory loads
        # collapse to a single FilePartition, and single-split scans never
        # form mesh task groups — the @1-indexed kill site needs executor 1
        # to run a second mesh task with the first one's outputs parked
        import os
        dfs = {}
        for name, pth in paths.items():
            if os.path.isdir(pth):
                fs = sorted(os.path.join(pth, f) for f in os.listdir(pth)
                            if f.endswith(".parquet"))
                dfs[name] = spark.read_parquet(fs, files_per_partition=1)
            else:
                dfs[name] = spark.read_parquet(pth)
            spark.create_or_replace_temp_view(name, dfs[name])
    else:
        dfs = tpch.load(spark, paths, files_per_partition=4)
    df = tpch.QUERIES[args.query](dfs)

    clean_base = M.resilience_snapshot()
    clean_conf = RapidsConf(settings) if args.mesh else None
    with MiniCluster(n_executors=args.executors, conf=clean_conf,
                     platform="cpu") as c:
        clean = c.collect(df)
        clean_map_tasks = sum(1 for op, _ in c.task_log
                              if op in ("map", "map.mesh"))
        clean_mesh = dict(c.mesh_stats)
    clean_delta = {k: v - clean_base[k]
                   for k, v in M.resilience_snapshot().items()
                   if v - clean_base[k]}
    # the healthy plane (mesh or not) must be invisible to every recovery
    # ladder — meshDegradedFallbacks rides this all-zero assert too
    assert not clean_delta, \
        f"no-faults clean run left resilience noise: {clean_delta}"
    if args.mesh:
        assert clean_mesh["mesh_tasks"] >= 1, \
            f"mesh plane enabled but no mesh task ran: {clean_mesh}"
        assert clean_mesh["degraded"] == 0, clean_mesh

    base = M.resilience_snapshot()
    conf = RapidsConf(dict(settings,
                           **{"spark.rapids.tpu.test.faults": args.fault}))
    with MiniCluster(n_executors=args.executors, conf=conf,
                     platform="cpu") as c:
        heals = []
        orig = c._heal
        c._heal = lambda: (heals.append(1), orig())[-1]
        chaos = c.collect(df)
        chaos_mesh = dict(c.mesh_stats)
    delta = {k: v - base[k]
             for k, v in M.resilience_snapshot().items() if v - base[k]}
    eventlog.shutdown()

    assert chaos.equals(clean), \
        f"killed-executor {args.query} is not bit-identical to the clean run"
    assert not heals, \
        f"whole-query heal fired; partial recovery expected ({delta})"
    assert delta.get("executorsLost", 0) >= 1, delta
    if args.mesh:
        # a participant killed inside the collective must have degraded
        # its group onto the TCP path, and earlier stages' lost splits
        # must have recomputed lineage-scoped, not whole-query
        assert delta.get("meshDegradedFallbacks", 0) >= 1, delta
        assert chaos_mesh["degraded"] >= 1, chaos_mesh
        assert delta.get("mapTasksRecomputed", 0) >= 1, delta
    else:
        assert delta.get("stagePartialRecomputes", 0) >= 1, delta
        assert 1 <= delta.get("mapTasksRecomputed", 0) < clean_map_tasks, \
            (delta, clean_map_tasks)
    print(f"cluster chaos ok [{args.query}, {args.executors} executors, "
          f"mesh={args.mesh}, fault {args.fault}]: {delta} "
          f"(clean run map tasks: {clean_map_tasks}, "
          f"mesh stats: {clean_mesh})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
