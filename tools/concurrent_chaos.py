"""Concurrent-chaos gate: 4 concurrent TPC-H queries, one killed by its
deadline, one with injected join-build OOMs, one shed at the front door.

The multi-tenant isolation contract (runtime/scheduler.py), proven end to
end in one process:

  - q18 runs with ``oom:joins.build:2`` armed: both injected OOMs land in
    ITS join builds (it launches first, with a head start over the peers),
    the PR-2 retry ladder recovers, and its result is bit-identical to a
    solo run — with the recovery visible ONLY in q18's query-scoped
    resilience counters.
  - q5 runs under ``scheduler.query.deadlineSeconds`` sized to fire
    mid-query: it dies with QueryDeadlineError, draining its pipeline
    without leaking threads, device buffers, or semaphore permits.
  - q1 and q3 are the survivors: bit-identical to solo runs, with EVERY
    query-scoped resilience counter zero — a peer's OOM recovery and a
    peer's cancellation must not leak into their scopes.
  - a 5th submission sheds on queue timeout with a retryable
    QueryRejectedError whose backoff hint survives a pickle round-trip
    (the serving-endpoint contract).

All four lifecycle outcomes land in the structured event log
(query.admitted / query.deadline / query.shed / query.end-with-oom.retry),
which ci.sh then asserts on.

Usage:
  python tools/concurrent_chaos.py --data-dir DIR --eventlog-dir DIR
"""

from __future__ import annotations

import argparse
import gc
import json
import pathlib
import pickle
import sys
import threading
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="concurrent_chaos.py",
                                description=__doc__)
    p.add_argument("--data-dir", required=True)
    p.add_argument("--eventlog-dir", required=True)
    p.add_argument("--sf", type=float, default=0.01)
    args = p.parse_args(argv)

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.runtime import eventlog
    from spark_rapids_tpu.runtime import faults
    from spark_rapids_tpu.runtime import scheduler as SCHED
    from spark_rapids_tpu.runtime.memory import DeviceManager
    from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
    from spark_rapids_tpu.session import TpuSession

    paths = tpch.generate(args.sf, args.data_dir)
    base_conf = {
        "spark.rapids.tpu.sql.format.parquet.reader.type": "COALESCING",
        "spark.rapids.tpu.pipeline.enabled": True,
    }

    def query_df(spark, name):
        dfs = tpch.load(spark, paths, files_per_partition=4)
        return getattr(tpch, name)(dfs)

    # -- solo baselines (faults off, before the event log opens) -------------
    solo_spark = TpuSession(base_conf)
    solo = {name: query_df(solo_spark, name).collect().to_pylist()
            for name in ("q1", "q3", "q18")}
    # warm q5 (first run pays the compiles), THEN measure: the deadline must
    # be sized off the warm wall the chaos run will actually see
    query_df(solo_spark, "q5").collect()
    q5_wall0 = time.perf_counter()
    query_df(solo_spark, "q5").collect()
    q5_wall = time.perf_counter() - q5_wall0

    cat = DeviceManager.get().catalog
    buffers_base = cat.num_buffers

    # -- arm the chaos run ----------------------------------------------------
    TpuSession(dict(base_conf, **{
        "spark.rapids.tpu.eventLog.dir": args.eventlog_dir,
        "spark.rapids.tpu.scheduler.maxConcurrent": 4,
        "spark.rapids.tpu.test.faults": "oom:joins.build:2",
        "spark.rapids.tpu.test.faults.seed": 7,
    }))

    outcomes: dict = {}
    lock = threading.Lock()

    def record(name, **kv):
        with lock:
            outcomes[name] = kv

    def run_query(name, delay_s, conf_extra=None):
        time.sleep(delay_s)
        spark = TpuSession(dict(base_conf, **(conf_extra or {})))
        df = query_df(spark, name)
        try:
            rows = df.collect().to_pylist()
            qm = df._last_collector
            record(name, rows=rows, query_id=qm.query_id,
                   resilience={k: v for k, v in
                               qm.query_resilience().items() if v})
        except SCHED.QueryCancelledError as e:
            record(name, error=type(e).__name__, reason=e.reason)
        except BaseException as e:  # noqa: BLE001 — reported, asserted below
            record(name, error=type(e).__name__, detail=repr(e)[:200])

    # q18 first (alone for its head start) so the 2 armed join-build OOMs
    # land in ITS builds, not a survivor's; its split floor drops so the
    # sf0.01-sized build batches stay splittable (the PR-2 chaos test's
    # setting). The deadline is sized off the measured solo q5 wall so it
    # fires mid-query — under 4-way concurrency q5 only runs slower
    threads = [
        threading.Thread(target=run_query, args=("q18", 0.0), kwargs={
            "conf_extra": {
                "spark.rapids.tpu.memory.retry.splitFloorBytes": "1b"}},
            daemon=True),
        threading.Thread(target=run_query, args=("q5", 0.35), kwargs={
            "conf_extra": {
                "spark.rapids.tpu.scheduler.query.deadlineSeconds":
                    max(0.02, q5_wall / 3)}}, daemon=True),
        threading.Thread(target=run_query, args=("q1", 0.40), daemon=True),
        threading.Thread(target=run_query, args=("q3", 0.45), daemon=True),
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    faults.reset()

    # 5th submission against a deterministically full scheduler: a direct
    # occupant ticket holds the one slot, so the session's submission
    # queues and sheds at its 50ms queue timeout — no wall-clock race with
    # the (already finished) chaos queries
    sched = SCHED.QueryScheduler.get()
    occupant = f"occupant-{id(sched):x}"
    sched.submit(occupant, 1, description="shed-gate occupant")
    saved_max = sched.max_concurrent
    sched.max_concurrent = 1
    shed_err = None
    try:
        spark5 = TpuSession(dict(base_conf, **{
            "spark.rapids.tpu.scheduler.queue.timeoutSeconds": 0.05}))
        query_df(spark5, "q1").collect()
    except SCHED.QueryRejectedError as e:
        shed_err = e
    finally:
        sched.max_concurrent = saved_max
        sched.release(occupant)
    eventlog.shutdown()

    # -- assertions -----------------------------------------------------------
    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    # survivors bit-identical to solo, with clean query scopes
    for name in ("q1", "q3"):
        o = outcomes.get(name, {})
        check(o.get("rows") == solo[name], f"{name} rows differ from solo")
        check(not o.get("resilience"),
              f"{name} resilience leaked: {o.get('resilience')}")
    # the OOM victim recovered bit-identically, recovery in ITS scope only
    o18 = outcomes.get("q18", {})
    check(o18.get("rows") == solo["q18"], "q18 rows differ from solo")
    check(o18.get("resilience", {}).get("numOomRetries", 0) >= 1,
          f"q18 saw no oom retry in its scope: {o18.get('resilience')}")
    # the deadline victim died with the typed error
    o5 = outcomes.get("q5", {})
    check(o5.get("error") == "QueryDeadlineError",
          f"q5 outcome was {o5}, wanted QueryDeadlineError")
    # the 5th submission shed with a round-trippable backoff hint
    check(shed_err is not None, "5th submission was not shed")
    if shed_err is not None:
        rt = pickle.loads(pickle.dumps(shed_err))
        check(rt.retryable and rt.backoff_hint_s > 0
              and rt.backoff_hint_s == shed_err.backoff_hint_s,
              f"QueryRejectedError round-trip lost the hint: {vars(rt)}")
    # nothing leaked: threads, device buffers, semaphore permits
    gc.collect()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline and (
            cat.num_buffers > buffers_base
            or any(t.name.startswith("srt-pipe-")
                   for t in threading.enumerate())):
        time.sleep(0.1)
    check(cat.num_buffers <= buffers_base,
          f"leaked {cat.num_buffers - buffers_base} catalog buffers")
    check(not TpuSemaphore.get()._holders,
          f"leaked semaphore permits: {TpuSemaphore.get()._holders}")
    stragglers = [t.name for t in threading.enumerate()
                  if t.name.startswith("srt-pipe-")]
    check(not stragglers, f"leaked pipeline threads: {stragglers}")

    print(json.dumps({
        "outcomes": {k: {kk: vv for kk, vv in v.items() if kk != "rows"}
                     for k, v in outcomes.items()},
        "shed": (None if shed_err is None else {
            "backoff_hint_s": shed_err.backoff_hint_s,
            "reason": shed_err.reason}),
        "failures": failures,
    }, default=str))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
