"""Fleet-chaos gate: replicated warm-cache endpoints with a replica
SIGKILLed mid-stream, client failover to a survivor, and lease adoption.

The fleet contract (runtime/fleet.py + runtime/endpoint.py), proven with
real replica PROCESSES (tools/fleet_replica.py) over shared on-disk state:

  - **Warm-state sharing**: replica A compiles the workload into the shared
    stage cache (its STATS show traces > 0); replica B, started fresh
    afterwards, serves the same shapes with traces == 0 — the Theseus-style
    warm standby, hot from its first query.
  - **No-faults fleet run**: concurrent clients spread across both replicas
    get bit-identical results with every query-scoped resilience counter
    zero AND every process-wide resilience counter zero on both replicas —
    replication with no faults is invisible to every recovery ladder.
  - **Mid-stream SIGKILL failover**: a victim replica (wedged by an armed
    hang fault at its first result-frame send, so the kill
    deterministically lands mid-stream) is SIGKILLed while serving; the
    client's ``submit_with_retry`` sees a retryable TransportError,
    rotates to the survivor, and the result is bit-identical to the solo
    oracle.
  - **Lease adoption**: the survivor's sweeper adopts the victim's expired
    lease — membership record unlinked, the victim's orphaned shared-store
    write intents (``*.tmp.<pid>``) reclaimed, a ``fleet.adopt`` event in
    the event log, ``fleetAdoptions`` counted on the survivor.
  - **Survivor health**: after the chaos the survivor still serves
    bit-identically, with zero leaked buffers (memoryLeakedBuffers == 0),
    an idle scheduler, and zero active queries.
  - **Fleet-stats rollup**: with both replicas live, the fleet-aggregate
    counters (EndpointClient.fleet_stats) equal an INDEPENDENT re-sum of
    each replica's raw Prometheus text — the rollup invents and loses
    nothing.
  - **Black-box flight recorder**: the victim gets a request timeout, so
    its heartbeat watchdog detects the wedged query and dumps
    ``blackbox-<pid>.json`` BEFORE the SIGKILL lands; the dump names the
    in-flight query (journey id + SQL), and the survivor's ``fleet.adopt``
    event carries the dump's path.
  - **Cross-replica journey**: ``profiler.py journey`` over every
    replica's event log renders the failover under ONE journey id —
    attempt 1 replica_timeout on the victim, attempt 2 served on the
    survivor with traces == 0 — exiting 0 (no schema violations).
  - **Fleet roster**: ``profiler.py fleet`` lists the dead victim from its
    ``departed-`` tombstone — last-known health and blackbox path intact.

Usage:
  python tools/fleet_chaos.py --work-dir DIR [--sf 0.01]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import re
import signal
import subprocess
import sys
import threading
import time


def _stat_value(stats_text: str, pattern: str) -> float:
    """Last value of the first STATS line matching `pattern` (regex)."""
    for ln in stats_text.splitlines():
        if re.search(pattern, ln) and not ln.startswith("# "):
            return float(ln.rsplit(None, 1)[1])
    raise AssertionError(f"no STATS line matches {pattern!r}")


def _counter_series(stats_text: str) -> dict:
    """Independent counter parse of one raw Prometheus exposition —
    deliberately NOT endpoint.parse_stats_text, so comparing the fleet
    aggregate against a re-sum of these is a real cross-check."""
    out, family, kind = {}, None, None
    for ln in stats_text.splitlines():
        if ln.startswith("# TYPE "):
            _, _, family, kind = ln.split(None, 3)
            continue
        if not ln.strip() or ln.startswith("#"):
            continue
        series, val = ln.rsplit(None, 1)
        if kind == "counter" and series.split("{", 1)[0] == family:
            out[series] = out.get(series, 0.0) + float(val)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="fleet_chaos.py", description=__doc__)
    p.add_argument("--work-dir", required=True,
                   help="scratch root: fleet/stage-cache/history/eventlog/"
                        "data subdirs are created inside")
    p.add_argument("--sf", type=float, default=0.01)
    p.add_argument("--ready-timeout", type=float, default=240.0)
    args = p.parse_args(argv)

    root = pathlib.Path(args.work_dir)
    dirs = {name: root / name for name in
            ("fleet", "stage_cache", "history", "eventlog", "data")}
    for d in dirs.values():
        d.mkdir(parents=True, exist_ok=True)

    repo = pathlib.Path(__file__).resolve().parent.parent
    sys.path.insert(0, str(repo))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.runtime import metrics as M
    from spark_rapids_tpu.runtime.endpoint import EndpointClient
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.sql.tpch_queries import SQL_QUERIES

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    # -- solo oracle: same engine, same data, NO shared stores ---------------
    # (the solo session must not touch the stage cache, or "replica A
    # compiled the shapes" would be pre-warmed from this process)
    paths = tpch.generate(args.sf, str(dirs["data"]))
    solo_spark = TpuSession({
        "spark.rapids.tpu.sql.format.parquet.reader.type": "COALESCING",
        "spark.rapids.tpu.pipeline.enabled": True,
    })
    tpch.load(solo_spark, paths, files_per_partition=4)
    workload = ("q1", "q3", "q5")
    solo = {q: solo_spark.sql(SQL_QUERIES[q]).collect().to_pylist()
            for q in workload}

    # generous lease so a GIL stall during a replica's compile burst can't
    # transiently expire a LIVE member (spurious adoption would trip the
    # no-faults zero-counter gate); the victim's lease still expires within
    # seconds of the SIGKILL
    lease_timeout, heartbeat = 8.0, 1.0

    def spawn_replica(tag, faults=None, request_timeout=None):
        cmd = [sys.executable, str(repo / "tools" / "fleet_replica.py"),
               "--fleet-dir", str(dirs["fleet"]),
               "--data-dir", str(dirs["data"]), "--sf", str(args.sf),
               "--stage-cache-dir", str(dirs["stage_cache"]),
               "--history-dir", str(dirs["history"]),
               "--eventlog-dir", str(dirs["eventlog"]),
               "--lease-timeout", str(lease_timeout),
               "--heartbeat", str(heartbeat)]
        if faults:
            cmd += ["--faults", faults]
        if request_timeout is not None:
            cmd += ["--request-timeout", str(request_timeout)]
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True, env=env)
        deadline = time.monotonic() + args.ready_timeout
        port = None
        while time.monotonic() < deadline:
            ln = proc.stdout.readline()
            if ln.startswith("READY "):
                port = int(ln.split()[1])
                break
            if proc.poll() is not None:
                break
        if port is None:
            proc.kill()
            raise RuntimeError(f"replica {tag} never became READY")
        # drain the replica's stdout so a chatty child can't fill the pipe
        threading.Thread(target=proc.stdout.read, daemon=True).start()
        print(f"replica {tag}: pid={proc.pid} port={port}", file=sys.stderr)
        return proc, ("127.0.0.1", port)

    report = {}

    # -- phase 1: replica A compiles the workload into the shared cache ------
    proc_a, addr_a = spawn_replica("A")
    cli_a = EndpointClient(addr_a, timeout_s=300)
    for q in workload:
        rows = cli_a.submit(SQL_QUERIES[q]).to_pylist()
        check(rows == solo[q], f"warm {q} on A diverged from solo")
    a_traces = _stat_value(cli_a.stats(), r'srt_fuse_total\{kind="traces"\}')
    check(a_traces > 0, f"replica A compiled nothing (traces={a_traces})")
    report["a_traces"] = a_traces

    # -- phase 2: fresh replica B + no-faults fleet load ----------------------
    proc_b, addr_b = spawn_replica("B")
    outcomes = {}
    lock = threading.Lock()

    def fleet_client(name, q, primary):
        # each worker leads with its own primary replica so both serve load
        addrs = [addr_a, addr_b] if primary == 0 else [addr_b, addr_a]
        cli = EndpointClient(addrs, timeout_s=300)
        try:
            rows = cli.submit_with_retry(SQL_QUERIES[q]).to_pylist()
            with lock:
                outcomes[name] = {"rows": rows, "summary": cli.last_summary}
        except BaseException as e:  # noqa: BLE001 — reported, asserted below
            with lock:
                outcomes[name] = {"error": repr(e)[:200]}

    workers = [threading.Thread(target=fleet_client,
                                args=(f"{q}@{i}", q, i % 2), daemon=True)
               for i, q in enumerate(workload * 2)]
    for t in workers:
        t.start()
    for t in workers:
        t.join(timeout=300)
    for name, o in outcomes.items():
        q = name.split("@")[0]
        check(o.get("rows") == solo[q],
              f"no-faults fleet {name} diverged ({o.get('error', 'rows')})")
        check(not (o.get("summary") or {}).get("resilience"),
              f"no-faults fleet {name} leaked scoped resilience: "
              f"{o.get('summary')}")
    cli_b = EndpointClient(addr_b, timeout_s=300)
    stats_b = cli_b.stats()
    b_traces = _stat_value(stats_b, r'srt_fuse_total\{kind="traces"\}')
    check(b_traces == 0,
          f"replica B retraced {b_traces} shapes replica A had compiled")
    report["b_traces"] = b_traces
    for stats_text, tag in ((cli_a.stats(), "A"), (stats_b, "B")):
        for ln in stats_text.splitlines():
            if ln.startswith("srt_resilience_total"):
                check(ln.endswith(" 0"),
                      f"no-faults replica {tag} resilience nonzero: {ln}")
    check(_stat_value(stats_b, r"srt_fleet_live_members") == 2,
          "replica B does not see 2 live members")

    # -- phase 2b: fleet-stats rollup over the two live replicas -------------
    # the aggregate must equal an INDEPENDENT re-sum of each replica's raw
    # exposition for every counter series — the rollup invents nothing
    fleet_cli = EndpointClient([addr_a, addr_b], timeout_s=300)
    fs = fleet_cli.fleet_stats()
    check(fs["live"] == 2 and fs["total"] == 2,
          f"fleet-stats saw {fs['live']}/{fs['total']} replicas, want 2/2")
    resum = {}
    for rep in fs["replicas"].values():
        for series, v in _counter_series(rep.get("raw", "")).items():
            resum[series] = resum.get(series, 0.0) + v
    agg = fs["aggregate"]["counters"]
    check(set(agg) == set(resum),
          f"fleet aggregate counter families diverge from the re-sum: "
          f"{sorted(set(agg) ^ set(resum))[:8]}")
    for series in resum:
        if abs(agg.get(series, 0.0) - resum[series]) > 1e-9:
            check(False, f"fleet aggregate {series}={agg.get(series)} != "
                         f"sum of per-replica {resum[series]}")
    report["fleet_counter_series"] = len(resum)

    # -- phase 3: SIGKILL a victim mid-stream; client fails over --------------
    # the victim's armed hang fault wedges q5 forever at its first result
    # frame (endpoint.send is a maybe_inject_any site, so "hang" fires
    # there), so the kill deterministically lands while the client is
    # mid-stream (a timed slow fault loses the race when the shared stage
    # cache makes the query finish in under the kill delay). The victim
    # also gets a request timeout: its connection thread is the wedged one,
    # so the HEARTBEAT watchdog must detect the stuck query, close its
    # journey (replica_timeout) and dump the flight recorder — all before
    # the SIGKILL, which is exactly the post-mortem the dump exists for.
    proc_v, addr_v = spawn_replica("victim", faults="hang:endpoint.send:1",
                                   request_timeout=1.0)
    flight = {}
    retries = []

    def failover_client():
        cli = EndpointClient([addr_v, addr_b], timeout_s=300)
        try:
            flight["rows"] = cli.submit_with_retry(
                SQL_QUERIES["q5"],
                on_retry=lambda a, d: retries.append(a)).to_pylist()
            flight["summary"] = cli.last_summary
            flight["journey"] = cli.last_journey
        except BaseException as e:  # noqa: BLE001
            flight["error"] = repr(e)[:200]

    ft = threading.Thread(target=failover_client, daemon=True)
    ft.start()
    # long enough for the query to wedge, age past the 1s request timeout,
    # and a heartbeat (1s) to run the watchdog sweep + blackbox dump
    time.sleep(4.0)
    os.kill(proc_v.pid, signal.SIGKILL)
    killed_at = time.monotonic()
    # plant an orphaned write intent under the victim's pid: the mid-write
    # state a crash leaves in the shared store, reclaimed only by adoption
    orphan = dirs["stage_cache"] / f"deadbeef.xc.tmp.{proc_v.pid}-0"
    orphan.write_bytes(b"half-written executable")
    ft.join(timeout=300)
    check(flight.get("rows") == solo["q5"],
          f"failover result diverged: {flight.get('error', 'rows')}")
    check(retries, "client never retried — the kill missed the in-flight "
                   "window")
    snap = M.resilience_snapshot()
    check(snap.get("replicaFailovers", 0) >= 1,
          f"no replica failover counted client-side: {snap}")
    report["failover_retries"] = len(retries)

    # -- phase 4: a survivor adopts the victim's lease ------------------------
    victim_lease = dirs["fleet"] / f"replica-127.0.0.1-{addr_v[1]}-{proc_v.pid}.json"
    deadline = time.monotonic() + lease_timeout + 6 * heartbeat + 10
    while time.monotonic() < deadline and (victim_lease.exists()
                                           or orphan.exists()):
        time.sleep(0.1)
    report["adoption_s"] = round(time.monotonic() - killed_at, 2)
    check(not victim_lease.exists(), "victim lease never adopted")
    check(not orphan.exists(), "victim's orphaned write intent not reclaimed")
    adoptions = sum(_stat_value(c.stats(), r'srt_fleet_total\{event="adoptions"\}')
                    for c in (cli_a, cli_b))
    check(adoptions >= 1, f"no adoption counted on survivors ({adoptions})")
    adopt_events = []
    for f in dirs["eventlog"].glob("*.jsonl"):
        for ln in f.read_text().splitlines():
            try:
                rec = json.loads(ln)
            except ValueError:
                continue
            if rec.get("event") == "fleet.adopt":
                adopt_events.append(rec)
    check(adopt_events, "no fleet.adopt event in the event log")
    check(any(rec.get("dead_pid") == proc_v.pid for rec in adopt_events),
          f"fleet.adopt events name the wrong pid: {adopt_events}")

    # -- phase 4b: the victim's black-box dump survived the SIGKILL ----------
    bb_path = dirs["eventlog"] / f"blackbox-{proc_v.pid}.json"
    check(bb_path.exists(), "victim wrote no blackbox dump before dying")
    jny = flight.get("journey")
    check(jny, "client recorded no journey id for the failover flight")
    if bb_path.exists():
        bb = json.loads(bb_path.read_text())
        check(bb.get("reason") == "stuck_query",
              f"blackbox dumped for {bb.get('reason')!r}, want stuck_query")
        named = [i for i in bb.get("inflight", [])
                 if i.get("journey") == jny]
        check(named, f"blackbox in-flight registry does not name the "
                     f"wedged journey {jny}: {bb.get('inflight')}")
        check(named and named[0].get("sql"),
              "blackbox in-flight entry carries no SQL")
        check(bb.get("events"), "blackbox event ring is empty")
        check(any(rec.get("blackbox") == str(bb_path)
                  for rec in adopt_events),
              f"no fleet.adopt event carries the victim's blackbox path "
              f"{bb_path}")
        report["blackbox_inflight"] = len(bb.get("inflight", []))

    # -- phase 4c: profiler renders the cross-replica failover timeline ------
    logs = sorted(str(f) for f in dirs["eventlog"].glob("*.jsonl"))
    jr = subprocess.run(
        [sys.executable, str(repo / "tools" / "profiler.py"), "journey",
         *logs, "--journey", str(jny), "--json"],
        capture_output=True, text=True)
    check(jr.returncode == 0,
          f"profiler journey exited {jr.returncode}: {jr.stderr[:500]}")
    if jr.returncode == 0:
        ja = json.loads(jr.stdout)
        js = ja.get("journeys", [])
        check(len(js) == 1, f"journey {jny} rendered {len(js)} times")
        attempts = js[0]["attempts"] if js else []
        check(len(attempts) >= 2,
              f"failover journey has {len(attempts)} attempts, want >= 2")
        if len(attempts) >= 2:
            a1, a2 = attempts[0], attempts[-1]
            check(a1["outcome"] == "replica_timeout"
                  and str(proc_v.pid) in str(a1["replica"]),
                  f"attempt 1 should be replica_timeout on the victim: {a1}")
            check(a2["outcome"] == "served" and a2["traces"] == 0
                  and str(proc_v.pid) not in str(a2["replica"]),
                  f"attempt 2 should be served warm on a survivor: {a2}")
            check(js[0]["failovers"] >= 1,
                  f"no failover derived in the merged timeline: {js[0]}")

    # -- phase 4d: the fleet roster still explains the dead victim -----------
    fr = subprocess.run(
        [sys.executable, str(repo / "tools" / "profiler.py"), "fleet",
         str(dirs["fleet"]), "--json"],
        capture_output=True, text=True)
    check(fr.returncode == 0,
          f"profiler fleet exited {fr.returncode}: {fr.stderr[:500]}")
    if fr.returncode == 0:
        roster = json.loads(fr.stdout)
        dead = [r for r in roster["replicas"]
                if r["status"] == "departed" and r.get("pid") == proc_v.pid]
        check(dead, f"victim pid {proc_v.pid} missing from the departed "
                    f"roster: {[r.get('replica') for r in roster['replicas']]}")
        if dead:
            check(dead[0].get("health", {}).get("active_queries") is not None,
                  f"victim tombstone lost its last-known health: {dead[0]}")
            check(dead[0].get("blackbox") == str(bb_path),
                  f"victim tombstone lost its blackbox path: {dead[0]}")
        check(roster["live"] >= 2, f"live survivors missing from the "
                                   f"roster: {roster['live']}")

    # -- phase 5: survivor health after the chaos -----------------------------
    rows = cli_b.submit(SQL_QUERIES["q1"]).to_pylist()
    check(rows == solo["q1"], "survivor q1 diverged after the chaos")
    stats_b = cli_b.stats()
    check(_stat_value(stats_b,
                      r'srt_resilience_total\{counter="memoryLeakedBuffers"\}')
          == 0, "survivor leaked catalog buffers")
    check(_stat_value(stats_b, r"srt_scheduler_running") == 0,
          "survivor scheduler still busy")
    check(_stat_value(stats_b, r"srt_scheduler_queue_depth") == 0,
          "survivor queue not drained")

    # -- graceful shutdown of the survivors -----------------------------------
    for proc, tag in ((proc_a, "A"), (proc_b, "B")):
        proc.send_signal(signal.SIGTERM)
    for proc, tag in ((proc_a, "A"), (proc_b, "B")):
        try:
            rc = proc.wait(timeout=90)
            check(rc == 0, f"replica {tag} drain exited {rc}")
        except subprocess.TimeoutExpired:
            proc.kill()
            failures.append(f"replica {tag} did not drain within 90s")
    check(not list(dirs["fleet"].glob("replica-*.json")),
          "leases left behind after graceful drain")

    report["adopt_events"] = len(adopt_events)
    report["failures"] = failures
    print(json.dumps(report, default=str))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
