"""bench_compare — gate a bench JSON line against a committed baseline.

Perf work (ROADMAP item 1) must land against a guarded trajectory: this
tool compares the current ``bench.py`` output line against a committed
``BENCH_rNN.json`` baseline per query and computes the geomean ratio of the
oracle-normalized ``vs_baseline`` scores (engine speed relative to the
numpy-oracle e2e denominator on the SAME box — the most machine-portable
number a bench line carries). ci.sh wires it as a **soft gate**:

  - geomean regression > ``--warn``  (default 10%)  -> WARN, exit 0
  - geomean regression > ``--fail``  (default 25%)  -> FAIL, exit 1
  - lines not comparable (different scale factor / query set, a degraded
    marker on either side) -> SKIP, exit 0 with the reason printed — the
    CI dry-run at sf0.01 on CPU must not be judged against a committed
    sf0.1 accelerator line.

Memory trajectory rides along: per-query ``peak_device_bytes`` deltas are
printed when both lines carry them (bench.py embeds them from the
allocation-site heap profiler), so a perf win that doubles the high-water
mark is visible in the same report. Per-query ``estimate_error`` deltas
(runtime statistics plane: |admission estimate - observed peak| / peak)
ride the same way, so a change that degrades footprint estimation shows
up next to the perf numbers it would distort. So do per-query ``movement``
deltas (data-movement plane: total boundary-crossing bytes + movement
amplification) — a perf win that silently moves twice the data is visible
in the same report; a baseline committed before the movement fields
existed is skipped per-field, never treated as zero.

Serving-latency trajectories ride independently of the per-query gate:
when BOTH lines carry ``fleet_latency`` (bench.py --concurrent --endpoint
--replicas embeds client-observed p50/p95/p99 plus per-replica journey
counts), the percentile deltas and journey totals are printed even though
a fleet line has no per-query ``vs_baseline`` section to gate on.

Usage:
  python tools/bench_compare.py <current.json> [--baseline BENCH_r06.json]
                                [--warn 0.10] [--fail 0.25]

<current.json> may be a file whose LAST line is the bench JSON (bench.py
output redirected to a file works as-is), or ``-`` for stdin.
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys


def load_line(path: str) -> dict:
    """Bench JSON from `path`: a whole-file JSON document (the committed
    pretty-printed BENCH_rNN.json form) or the last parseable JSON line
    with a 'metric' key (raw bench.py output)."""
    text = sys.stdin.read() if path == "-" else open(path).read()
    try:
        d = json.loads(text)
        if isinstance(d, dict) and "metric" in d.get("parsed", {}):
            return d["parsed"]   # r05-and-earlier watcher wrapper form
        if isinstance(d, dict) and "metric" in d:
            return d
    except ValueError:
        pass
    for ln in reversed(text.splitlines()):
        ln = ln.strip()
        if ln.startswith("{") and ln.endswith("}"):
            try:
                d = json.loads(ln)
            except ValueError:
                continue
            if "metric" in d:
                return d
    raise SystemExit(f"no bench JSON line with a 'metric' key in {path}")


def _sf(metric: str) -> "str | None":
    m = re.search(r"sf([0-9.]+)", metric or "")
    return m.group(1) if m else None


def _platform(d: dict) -> str:
    deg = d.get("degraded") or ""
    return "cpu" if ("platform=cpu" in deg or "cpu-fallback" in deg) \
        else "tpu"


def comparable(cur: dict, base: dict) -> "str | None":
    """None when the two lines can be judged against each other, else the
    reason they cannot (SKIP, not FAIL — an incomparable pair proves
    nothing about the trajectory). A degraded marker alone does NOT skip:
    the committed baselines on this box carry platform=cpu, and two cpu
    lines at the same scale ARE comparable — only a platform or scale
    mismatch, or a noisy measurement, voids the comparison."""
    if _sf(cur.get("metric", "")) != _sf(base.get("metric", "")):
        return (f"scale factor differs: {cur.get('metric')} vs "
                f"{base.get('metric')}")
    if _platform(cur) != _platform(base):
        return (f"platform differs: {_platform(cur)} vs {_platform(base)}")
    if cur.get("variance_ok") is False:
        return f"current measurement too noisy (spread {cur.get('spread')})"
    if base.get("variance_ok") is False:
        return (f"baseline measurement too noisy "
                f"(spread {base.get('spread')})")
    common = set(cur.get("queries") or {}) & set(base.get("queries") or {})
    if not common:
        return "no common per-query entries"
    if any((cur["queries"][q].get("vs_baseline") or 0) <= 0
           or (base["queries"][q].get("vs_baseline") or 0) <= 0
           for q in common):
        return "missing/zero vs_baseline on a common query"
    return None


def compare(cur: dict, base: dict) -> dict:
    common = sorted(set(cur["queries"]) & set(base["queries"]))
    rows = []
    for q in common:
        c, b = cur["queries"][q], base["queries"][q]
        ratio = c["vs_baseline"] / b["vs_baseline"]
        row = {"query": q,
               "base_vs_baseline": b["vs_baseline"],
               "cur_vs_baseline": c["vs_baseline"],
               "ratio": round(ratio, 4)}
        if "peak_device_bytes" in c and "peak_device_bytes" in b:
            row["peak_device_bytes"] = c["peak_device_bytes"]
            row["peak_delta_bytes"] = (c["peak_device_bytes"]
                                       - b["peak_device_bytes"])
        if "estimate_error" in c and "estimate_error" in b:
            row["estimate_error"] = c["estimate_error"]
            row["estimate_error_delta"] = round(
                c["estimate_error"] - b["estimate_error"], 6)
        # movement trajectory (data-movement plane): total bytes the hot
        # rep moved across boundaries — only when BOTH lines carry the
        # section; a baseline committed before the movement plane existed
        # honestly skips rather than pretending a zero
        if "movement" in c and "movement" in b:
            cm, bm = c["movement"], b["movement"]
            moved = (lambda m: sum(v for k, v in m.items()
                                   if isinstance(v, (int, float))
                                   and k.endswith("_bytes")))
            row["moved_bytes"] = moved(cm)
            row["moved_delta_bytes"] = moved(cm) - moved(bm)
            if cm.get("movement_amplification") is not None \
                    and bm.get("movement_amplification") is not None:
                row["amplification"] = cm["movement_amplification"]
                row["amplification_delta"] = round(
                    cm["movement_amplification"]
                    - bm["movement_amplification"], 3)
            # h2d pricing (encoded-upload trajectory): the PCIe bytes a
            # scan actually shipped, and how much of that was encoded pages
            # rather than dense columns — only when both lines carry the
            # per-site split (bench.py embeds it from the movement ledger)
            if cm.get("h2d_sites") and bm.get("h2d_sites"):
                ch, bh = cm["h2d_sites"], bm["h2d_sites"]
                row["h2d_bytes"] = sum(ch.values())
                row["h2d_delta_bytes"] = (sum(ch.values())
                                          - sum(bh.values()))
                row["h2d_encoded_bytes"] = ch.get("scan.encoded", 0)
        rows.append(row)
    geomean = math.exp(sum(math.log(r["ratio"]) for r in rows) / len(rows))
    return {"queries": rows, "geomean_ratio": round(geomean, 4),
            "regression": round(max(0.0, 1.0 - geomean), 4)}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="bench_compare.py", description=__doc__)
    p.add_argument("current", help="bench JSON line (file or '-')")
    p.add_argument("--baseline", default="BENCH_r07.json",
                   help="committed baseline bench JSON")
    p.add_argument("--warn", type=float, default=0.10,
                   help="geomean regression fraction that warns")
    p.add_argument("--fail", type=float, default=0.25,
                   help="geomean regression fraction that fails (rc 1)")
    args = p.parse_args(argv)

    cur = load_line(args.current)
    base = load_line(args.baseline)
    # serving-latency trajectory (fleet observability plane): printed
    # BEFORE — and regardless of — the per-query comparability gate, since
    # a fleet line carries fleet_latency/journeys instead of "queries"
    if cur.get("fleet_latency") and base.get("fleet_latency"):
        cf, bf = cur["fleet_latency"], base["fleet_latency"]
        parts = []
        for k in ("p50", "p95", "p99"):
            c, b = cf.get(k), bf.get(k)
            if c is not None and b is not None:
                parts.append(f"{k} {b}s -> {c}s ({c - b:+.4f}s)")
        if parts:
            print("fleet serving latency: " + "  ".join(parts))

        def _tot(line, key):
            return sum(j.get(key, 0)
                       for j in (line.get("journeys") or {}).values())

        print(f"fleet journeys: "
              f"served {_tot(base, 'served')} -> {_tot(cur, 'served')}  "
              f"cached {_tot(base, 'cached')} -> {_tot(cur, 'cached')}  "
              f"failovers {_tot(base, 'failover')} -> "
              f"{_tot(cur, 'failover')}")
    reason = comparable(cur, base)
    if reason is not None:
        print(f"bench_compare SKIP (not comparable): {reason}")
        return 0
    d = compare(cur, base)
    for r in d["queries"]:
        extra = ""
        if "peak_delta_bytes" in r:
            extra = (f"  peak_dev {r['peak_device_bytes']}B "
                     f"({r['peak_delta_bytes']:+d}B vs baseline)")
        if "estimate_error_delta" in r:
            extra += (f"  est_err {r['estimate_error']} "
                      f"({r['estimate_error_delta']:+.3f} vs baseline)")
        if "moved_delta_bytes" in r:
            extra += (f"  moved {r['moved_bytes']}B "
                      f"({r['moved_delta_bytes']:+d}B vs baseline)")
        if "amplification_delta" in r:
            extra += (f"  amp {r['amplification']}x "
                      f"({r['amplification_delta']:+.3f} vs baseline)")
        if "h2d_delta_bytes" in r:
            extra += (f"  h2d {r['h2d_bytes']}B "
                      f"({r['h2d_delta_bytes']:+d}B, "
                      f"{r['h2d_encoded_bytes']}B encoded)")
        print(f"  {r['query']}: vs_baseline {r['base_vs_baseline']} -> "
              f"{r['cur_vs_baseline']}  (x{r['ratio']}){extra}")
    reg = d["regression"]
    verdict = (f"geomean ratio {d['geomean_ratio']} "
               f"(regression {reg:.1%}) vs {args.baseline}")
    if reg > args.fail:
        print(f"bench_compare FAIL: {verdict} exceeds fail "
              f"threshold {args.fail:.0%}")
        return 1
    if reg > args.warn:
        print(f"bench_compare WARN: {verdict} exceeds warn "
              f"threshold {args.warn:.0%}")
        return 0
    print(f"bench_compare OK: {verdict}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
