"""Streaming-chaos gate: a long micro-batch stream with a coordinator
process killed mid-epoch, replayed exactly once, bit-identically.

The continuous-ingestion contract (streaming/*), proven end to end:

  - **Long clean stream**: 20+ single-batch epochs of windowed incremental
    aggregation, every commit at attempt 1, every epoch's state matching
    the journal's own running record.
  - **Flat state**: watermark retirement (streaming.watermark.delaySeconds)
    holds state rows/bytes constant once the window horizon fills — the
    state of an infinite stream is bounded.
  - **Steady state compiles NOTHING**: after the two plan shapes (first
    epoch, union+merge) are traced, every further epoch commits with
    ``compiles == 0`` — micro-batches ride the compiled-stage cache.
  - **Kill mid-epoch, replay exactly once**: a REAL coordinator process is
    SIGKILLed inside the commit window (exec_kill armed at the
    ``streaming.epoch.commit`` fault site: epoch query run, state snapshot
    written, journal NOT advanced). A fresh coordinator adopting the
    stream replays the pending epoch under a bumped attempt and lands
    bit-identically — same state table, same state checksum — as an
    unkilled oracle that ingested the same batches, and the replay is the
    ONLY resilience event of the whole run.
  - **Associativity cross-check**: the oracle consumes ALL batches in one
    giant epoch; equality with the 21-epoch incremental state proves the
    partial/merge algebra (exec/aggregate.py AGG_MERGE_OPS) is grouping-
    independent.
  - **Journal schema**: ``profiler.py streaming`` validates the journal
    against the journal's own schema validator and renders the epoch
    timeline (exit 0); a deliberately corrupted copy must FAIL it
    (exit != 0) — the gate provably bites.

Usage:
  python tools/stream_chaos.py --work-dir DIR [--epochs 20]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import signal
import subprocess
import sys

_KILL_CHILD = r"""
import sys
import jax
jax.config.update("jax_platforms", "cpu")
import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu.runtime import faults
from spark_rapids_tpu.session import TpuSession
from spark_rapids_tpu.streaming import EpochCoordinator, StreamingSource

spark = TpuSession({"spark.rapids.tpu.streaming.maxBatchesPerEpoch": 1,
                    "spark.rapids.tpu.streaming.watermark.delaySeconds": 20})
src = StreamingSource("clicks", sys.argv[1])
coord = EpochCoordinator(spark, src, keys=["k"],
                         aggs=[("sum", "v"), ("count", "v"), ("max", "v")],
                         time_column="ts", window_seconds=10)
print("ADOPTED", coord.journal.committed_epoch(), flush=True)
faults.configure("exec_kill:streaming.epoch.commit:1", seed=1)
coord.run_epoch()
print("SURVIVED", flush=True)     # must never be reached
"""


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="stream_chaos.py", description=__doc__)
    p.add_argument("--work-dir", required=True,
                   help="scratch root: stream/oracle/eventlog subdirs are "
                        "created inside")
    p.add_argument("--epochs", type=int, default=20,
                   help="clean epochs before the kill (>= 20 for the gate)")
    args = p.parse_args(argv)

    root = pathlib.Path(args.work_dir)
    dirs = {name: root / name for name in ("stream", "oracle", "eventlog")}
    for d in dirs.values():
        d.mkdir(parents=True, exist_ok=True)

    repo = pathlib.Path(__file__).resolve().parent.parent
    sys.path.insert(0, str(repo))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pyarrow as pa
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    from spark_rapids_tpu.runtime import eventlog
    from spark_rapids_tpu.runtime import metrics as M
    from spark_rapids_tpu.session import TpuSession
    from spark_rapids_tpu.streaming import (EpochCoordinator, EpochJournal,
                                            StreamingSource)

    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)

    def batch(i, rows=8):
        base = i * 10
        return pa.table({
            "k": pa.array([j % 2 for j in range(rows)], type=pa.int64()),
            "v": pa.array([float(base + j) for j in range(rows)],
                          type=pa.float64()),
            "ts": pa.array([base + j for j in range(rows)],
                           type=pa.int64())})

    def coordinator(spark, src):
        return EpochCoordinator(
            spark, src, keys=["k"],
            aggs=[("sum", "v"), ("count", "v"), ("max", "v")],
            time_column="ts", window_seconds=10)

    report = {}
    res_before = M.resilience_snapshot()
    spark = TpuSession({
        "spark.rapids.tpu.streaming.maxBatchesPerEpoch": 1,
        "spark.rapids.tpu.streaming.watermark.delaySeconds": 20,
        "spark.rapids.tpu.eventLog.dir": str(dirs["eventlog"])})
    src = StreamingSource("clicks", str(dirs["stream"]))

    # -- phase 1: a long clean stream ----------------------------------------
    coord = coordinator(spark, src)
    commits = []
    for i in range(args.epochs):
        src.append_table(f"b-{i:04d}", batch(i))
        rec = coord.run_epoch()
        check(rec is not None and rec["epoch"] == i + 1,
              f"epoch {i + 1} did not commit: {rec}")
        if rec:
            commits.append(rec)
    check(len(commits) >= 20, f"only {len(commits)} epochs committed")
    check(all(r["attempt"] == 1 for r in commits),
          "a clean epoch committed above attempt 1")
    check(all(r["rows_in"] == 8 for r in commits),
          "an epoch ingested the wrong row count")
    # flat state: once the watermark horizon fills (3 live 10s windows at
    # delay 20), rows and bytes never grow again
    tail = commits[4:]
    check(all(r["state_rows"] == tail[0]["state_rows"] for r in tail),
          f"state rows not flat: {[r['state_rows'] for r in commits]}")
    check(all(r["state_bytes"] == tail[0]["state_bytes"] for r in tail),
          f"state bytes not flat: {[r['state_bytes'] for r in commits]}")
    check(all(r["retired_rows"] > 0 for r in tail),
          "steady-state epochs retired nothing despite the watermark")
    # steady state retraces nothing: the tail of the stream compiles ZERO
    # (early epochs trace the two plan shapes; a mid-stream one-off can
    # still land when a growing encoded batch crosses a capacity bucket)
    steady = commits[-10:]
    check(all(r.get("compiles") == 0 for r in steady),
          f"steady-state epochs compiled: "
          f"{[(r['epoch'], r.get('compiles')) for r in commits]}")
    total_compiles = sum(r.get("compiles") or 0 for r in commits)
    check(total_compiles <= 10,
          f"the stream compiled {total_compiles} times over "
          f"{len(commits)} epochs — the stage cache is not carrying it")
    report["epochs"] = len(commits)
    report["steady_state_rows"] = tail[0]["state_rows"]
    report["steady_state_bytes"] = tail[0]["state_bytes"]
    report["compiles_by_epoch"] = [r.get("compiles") for r in commits]
    check(M.resilience_snapshot() == res_before,
          "the clean stream tripped a resilience counter")
    coord.close()

    # -- phase 2: kill a real coordinator process mid-epoch ------------------
    kill_epoch = args.epochs + 1
    src.append_table(f"b-{args.epochs:04d}", batch(args.epochs))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = str(repo) + os.pathsep + env.get("PYTHONPATH", "")
    child = subprocess.Popen(
        [sys.executable, "-c", _KILL_CHILD, str(dirs["stream"])],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env)
    out, _ = child.communicate(timeout=600)
    check(f"ADOPTED {args.epochs}" in out,
          f"child never adopted the committed stream: {out[-500:]}")
    check("SURVIVED" not in out, "the armed exec_kill never fired")
    check(child.returncode == -signal.SIGKILL,
          f"child exited {child.returncode}, want SIGKILL")
    journal = EpochJournal(str(dirs["stream"] / "_state"), source="clicks")
    pending = journal.pending()
    check(pending is not None and pending["epoch"] == kill_epoch,
          f"no pending begin for epoch {kill_epoch} after the kill: "
          f"{pending}")
    report["killed_pid"] = child.pid

    # -- phase 3: recovery replays the pending epoch exactly once ------------
    recovered = coordinator(spark, src)
    rec = recovered.run_epoch()      # recovers, then replays the pending epoch
    check(rec is not None and rec["epoch"] == kill_epoch
          and rec["attempt"] == 2,
          f"recovery did not replay epoch {kill_epoch} at attempt 2: {rec}")
    check(recovered.run_epoch() is None,
          "a second run after recovery found phantom work")
    state = recovered.state_table()
    recovered.close()
    snap = M.resilience_snapshot()
    check(snap["streamEpochReplays"] == res_before["streamEpochReplays"] + 1,
          f"expected exactly one epoch replay, got "
          f"{snap['streamEpochReplays'] - res_before['streamEpochReplays']}")
    check(snap["streamStateRebuilds"] == res_before["streamStateRebuilds"],
          "recovery rebuilt state instead of loading the committed snapshot")

    # -- phase 4: the journal passes its schema gate (and a corrupt one
    #    fails it). Runs BEFORE the oracle phase: the event log is
    #    process-global, and the oracle's epoch must not pollute the
    #    stream's event counts ------------------------------------------------
    eventlog.shutdown()
    logs = sorted(str(f) for f in dirs["eventlog"].glob("*.jsonl"))
    pr = subprocess.run(
        [sys.executable, str(repo / "tools" / "profiler.py"), "streaming",
         str(dirs["stream"] / "_state"), "--eventlog", *logs, "--json"],
        capture_output=True, text=True, env=env)
    check(pr.returncode == 0,
          f"profiler streaming exited {pr.returncode}: {pr.stderr[:500]}")
    if pr.returncode == 0:
        pa_doc = json.loads(pr.stdout)
        doc = pa_doc["doc"]
        check(doc["committed_epoch"] == kill_epoch,
              f"journal committed {doc['committed_epoch']}, want "
              f"{kill_epoch}")
        check(len(doc["consumed"]) == kill_epoch,
              "consumed set does not cover every batch")
        ev = pa_doc["events"]
        check(ev.get("stream.epoch.commit") == kill_epoch,
              f"event log saw {ev.get('stream.epoch.commit')} commits")
        # 20 clean begins + the replay's begin; the killed attempt's begin
        # lives in the journal (attempt fencing), not this process's log
        check(ev.get("stream.epoch.begin") == kill_epoch,
              f"event log saw {ev.get('stream.epoch.begin')} begins")
    bad_dir = root / "corrupt"
    bad_dir.mkdir(exist_ok=True)
    good = (dirs["stream"] / "_state" / "epoch_journal.json").read_text()
    bad = json.loads(good)
    bad["committed_epoch"] += 1      # last commit no longer matches
    (bad_dir / "epoch_journal.json").write_text(json.dumps(bad))
    pr = subprocess.run(
        [sys.executable, str(repo / "tools" / "profiler.py"), "streaming",
         str(bad_dir)],
        capture_output=True, text=True, env=env)
    check(pr.returncode != 0, "profiler accepted a corrupted journal")

    # -- phase 5: bit-identity with the unkilled oracle ----------------------
    # the oracle ingests the SAME batches in ONE giant epoch: equality also
    # proves the partial/merge algebra is grouping-independent
    osrc = StreamingSource("clicks", str(dirs["oracle"]))
    for i in range(kill_epoch):
        osrc.append_table(f"b-{i:04d}", batch(i))
    ospark = TpuSession({
        "spark.rapids.tpu.streaming.watermark.delaySeconds": 20,
        "spark.rapids.tpu.streaming.maxBatchesPerEpoch": 0})
    oracle = coordinator(ospark, osrc)
    orec = oracle.run_epoch()
    ostate = oracle.state_table()
    oracle.close()
    check(state.equals(ostate),
          f"replayed state diverged from the oracle: "
          f"{state.num_rows} vs {ostate.num_rows} rows")
    check(rec["state_checksum"] == orec["state_checksum"],
          f"state checksum diverged: {rec['state_checksum']:#x} vs "
          f"{orec['state_checksum']:#x}")
    report["final_state_rows"] = state.num_rows
    report["final_watermark"] = rec["watermark"]

    report["failures"] = failures
    print(json.dumps(report, default=str))
    if failures:
        for f in failures:
            print(f"FAIL: {f}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
