"""profiler — replay structured logs into tuning reports and trace views.

The Profiling Tool analog (reference tools/ "Profiling Tool" post-processes
Spark event logs + Rapids metrics into per-query tuning reports). Input is
the JSONL event log written by spark_rapids_tpu/runtime/eventlog.py
(knob spark.rapids.tpu.eventLog.dir); output is a per-query report:

  - operator self-time table (top operators by self time, join builds as
    distinct line items, coverage vs measured query wall time)
  - spill hotspots (bytes/tier per plan node)
  - OOM retry/split hotspots and fetch retry/failover/recompute attribution
  - shuffle partition skew per exchange (max/mean of reduce-partition bytes)
  - scan readahead stall time (decode-bound scans)

``trace`` merges the per-process span files written under
spark.rapids.tpu.trace.dir (runtime/tracing.py) — driver, MiniCluster
executors (respawned incarnations included), endpoint workers — into ONE
Chrome-trace-event JSON that loads in Perfetto: one pid lane per process,
one tid lane per thread (pipeline edges appear as their srt-pipe-* worker
threads, task slots as executor main threads), zero-duration instants for
oom.retry / oom.split / fetch.recompute / spill. Per-process clock offsets
(measured by the driver's two-timestamp handshake, runtime/eventlog
set_clock_offset) are applied before merging so cross-process ordering is
correct. It also prints a **critical-path table**: the longest dependent
chain of spans bounding the query's wall time, with per-edge blame
(decode vs compute vs exchange vs queue-wait) — the direct input to the
fusion/concurrency/scale-out items on the roadmap.

``memory`` replays the memory observability plane (runtime/memory.py):
heap-snapshot tables of live bytes by allocation site/node/tier, per-query
peak attribution (which subsystem owned the high-water mark), the watermark
timeline, and end-of-query leak detections. ``--diff`` compares the final
heap snapshots of two logs per site (live/peak/cumulative deltas) — the
before/after view for hunting growth between runs.

``movement`` replays the data-movement plane (runtime/movement.py): the
last cumulative movement.sample per process is summed across every log
passed (driver + executor per-process files) into a source->destination
byte matrix, a top-flows table per (edge, link), the loopback-vs-remote
split of network-capable bytes, and per-query movement amplification
(bytes moved per result byte, from query.end's movement section).

``journey`` merges the ``query.journey`` records of ANY number of replica
event logs into cross-replica query timelines: one submission = one
journey id (stamped by EndpointClient, stable across submit_with_retry
failover), each replica that saw an attempt contributes one terminal
record, and the merged view orders attempts and derives the failover
transitions — ``submitted -> replica_timeout@A -> served@B`` — with
per-attempt latency, retrace counts and SLO breach totals.

``fleet`` reads a fleet membership directory (runtime/fleet.py): live
``replica-*.json`` lease records with the health summary each heartbeat
embeds (active queries, HBM watermark, cache hit rates, resilience
counters, SLO accounting), plus ``departed-*.json`` tombstones — a dead
replica's FINAL record, so the roster still explains what it was doing
when it died, including its black-box flight-recorder dump path.

Usage:
  python tools/profiler.py report <eventlog.jsonl> [--json] [--top N]
  python tools/profiler.py report <eventlog.jsonl> --compare <other.jsonl>
  python tools/profiler.py trace <logdir> [--query TRACE] [--out trace.json]
  python tools/profiler.py memory <eventlog.jsonl> [--diff <other.jsonl>]
  python tools/profiler.py movement <eventlog.jsonl> [more.jsonl ...]
  python tools/profiler.py journey <eventlog.jsonl> [more.jsonl ...]
  python tools/profiler.py fleet <fleet.dir> [--json]
  python tools/profiler.py streaming <state.dir> [--eventlog LOG ...]

Exit status is non-zero on schema violations, when no query in the log
carries a non-empty operator breakdown (report), on malformed span files
/ an empty merged trace (trace), when the log carries no memory-plane
events at all (memory), when no ``query.journey`` record exists in any
log passed (journey), when the fleet directory holds no membership
record or tombstone (fleet), or when an epoch journal violates its own
schema (streaming) — CI uses these as gates.

``streaming`` reads a stream's state directory (streaming/journal.py):
the epoch journal's commit timeline — per-epoch attempt, batch count,
rows in, state rows/bytes, retired rows, watermark, compiles — validated
against the journal's own schema validator, plus a pending-begin line
when a crashed epoch awaits replay, plus stream.* event counts from any
replica event logs passed with ``--eventlog``.
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import sys
import time


def _eventlog_module():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from spark_rapids_tpu.runtime import eventlog
    return eventlog


# ---------------------------------------------------------------------------
# parsing + validation
# ---------------------------------------------------------------------------

def load_log(path: str):
    """Parse one event log; returns (records, violations)."""
    eventlog = _eventlog_module()
    records, violations = [], []
    last_t = None
    with open(path, encoding="utf-8") as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError as e:
                violations.append(f"{path}:{lineno}: unparseable line ({e})")
                continue
            for v in eventlog.validate_record(rec):
                violations.append(f"{path}:{lineno}: {v}")
            t = rec.get("t")
            if isinstance(t, (int, float)):
                if last_t is not None and t < last_t:
                    violations.append(
                        f"{path}:{lineno}: monotonic timestamp regression "
                        f"({t} < {last_t})")
                last_t = t
            records.append(rec)
    return records, violations


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------

def _node_label(nodes_by_id: dict, nid) -> str:
    n = nodes_by_id.get(nid)
    if n is None:
        return f"node#{nid}" if nid is not None else "<driver>"
    return f"{n['name']}#{nid}"


def analyze(records: list) -> dict:
    """Group the log into per-query analyses keyed off query.end events."""
    by_query: dict = {}
    for rec in records:
        by_query.setdefault(rec.get("query"), []).append(rec)

    queries = []
    for rec in records:
        if rec["event"] != "query.end":
            continue
        qid = rec.get("query")
        evs = by_query.get(qid, [])
        nodes = rec.get("nodes") or []
        nodes_by_id = {n["id"]: n for n in nodes if n.get("id") is not None}
        wall_s = rec.get("wall_s") or 0.0

        # operator self-time table; the build region carries its own
        # attribution frame (buildSelfTime, disjoint from selfTime by
        # construction) and renders as a distinct "(build)" line item
        ops = []
        for n in nodes_by_id.values():
            m = n.get("metrics") or {}
            self_s = m.get("selfTime", 0) / 1e9
            build_s = m.get("buildSelfTime", 0) / 1e9
            row = {
                "op": _node_label(nodes_by_id, n["id"]),
                "args": n.get("args", ""),
                "self_s": round(self_s, 6),
                "rows": m.get("numOutputRows"),
                "batches": m.get("numOutputBatches"),
            }
            ops.append(row)
            if build_s > 0:
                ops.append({
                    "op": _node_label(nodes_by_id, n["id"]) + " (build)",
                    "args": "",
                    "self_s": round(build_s, 6),
                    "rows": None, "batches": None,
                })
        ops.sort(key=lambda r: -r["self_s"])
        total_self = sum(r["self_s"] for r in ops)

        # spill hotspots per node
        spills: dict = {}
        for e in evs:
            if e["event"] != "spill":
                continue
            key = _node_label(nodes_by_id, e.get("node"))
            s = spills.setdefault(key, {"events": 0, "bytes": 0, "tiers": {}})
            s["events"] += 1
            s["bytes"] += e.get("bytes", 0)
            tier = f"{e.get('tier_from')}->{e.get('tier_to')}"
            s["tiers"][tier] = s["tiers"].get(tier, 0) + e.get("bytes", 0)

        # OOM retry/split + fetch ladder attribution per node
        retries: dict = {}
        for e in evs:
            if e["event"] not in ("oom.retry", "oom.split", "fetch.error",
                                  "fetch.retry", "fetch.failover",
                                  "fetch.recompute"):
                continue
            key = _node_label(nodes_by_id, e.get("node"))
            r = retries.setdefault(key, {})
            r[e["event"]] = r.get(e["event"], 0) + 1
            if e["event"] == "oom.split" and e.get("site"):
                r.setdefault("sites", set()).add(e["site"])
        for r in retries.values():
            if "sites" in r:
                r["sites"] = sorted(r["sites"])

        # shuffle partition skew per exchange map stage. Unified on the
        # stats plane: stage.map.end events where present, backfilled from
        # the query's plan.stats record (which carries the same
        # per-reduce-partition sizes via the collector/MapOutputTracker) so
        # skew is reported even when the mesh plane ran the map stage and no
        # stage.map.end landed in this log
        def skew_row(node, sid, sizes):
            sizes = [int(s) for s in (sizes or [])]
            nonzero = [s for s in sizes if s] or [0]
            mean = sum(sizes) / len(sizes) if sizes else 0
            return {
                "node": _node_label(nodes_by_id, node),
                "shuffle": sid,
                "partitions": len(sizes),
                "total_bytes": sum(sizes),
                "max_bytes": max(sizes) if sizes else 0,
                "max_partition": sizes.index(max(sizes)) if sizes else None,
                "skew": round(max(sizes) / mean, 3) if mean else 1.0,
                "empty_partitions": sum(1 for s in sizes if not s),
                "largest_vs_median": round(
                    max(sizes) / max(sorted(nonzero)[len(nonzero) // 2], 1), 3)
                    if sizes else 1.0,
            }

        shuffles = []
        for e in evs:
            if e["event"] == "stage.map.end":
                shuffles.append(skew_row(e.get("node"), e.get("shuffle"),
                                         e.get("partition_sizes")))
        plan_stats = next((e for e in evs if e["event"] == "plan.stats"),
                          None)
        seen_sids = {s["shuffle"] for s in shuffles}
        for s in (plan_stats or {}).get("shuffles") or []:
            if s.get("shuffle") in seen_sids:
                continue
            shuffles.append(skew_row(s.get("node"), s.get("shuffle"),
                                     s.get("partition_sizes")))

        # readahead stall time per scan node
        stalls = []
        for n in nodes_by_id.values():
            st = (n.get("metrics") or {}).get("readaheadStallTime", 0)
            if st:
                stalls.append({"node": _node_label(nodes_by_id, n["id"]),
                               "stall_s": round(st / 1e9, 6)})
        stalls.sort(key=lambda r: -r["stall_s"])

        # pipeline queue stalls per edge (runtime/pipeline.py): metric names
        # are "<name>:<edge>" on the consuming node — wait = consumer
        # starved (upstream too slow), full = producer backed up
        # (downstream too slow); pipeline.stall events corroborate
        edges: dict = {}
        for n in nodes_by_id.values():
            for mname, v in (n.get("metrics") or {}).items():
                if ":" not in mname:
                    continue
                base, edge = mname.split(":", 1)
                if base not in ("queueWaitTime", "queueFullTime",
                                "queueDepthPeak"):
                    continue
                e = edges.setdefault(edge, {
                    "edge": edge, "node": _node_label(nodes_by_id, n["id"]),
                    "wait_s": 0.0, "full_s": 0.0, "depth_peak": 0,
                    "stall_events": 0})
                if base == "queueWaitTime":
                    e["wait_s"] = round(e["wait_s"] + v / 1e9, 6)
                elif base == "queueFullTime":
                    e["full_s"] = round(e["full_s"] + v / 1e9, 6)
                else:
                    e["depth_peak"] = max(e["depth_peak"], v)
        for ev in evs:
            if ev["event"] == "pipeline.stall" and ev.get("edge") in edges:
                edges[ev["edge"]]["stall_events"] += 1
        pipeline_edges = sorted(edges.values(),
                                key=lambda r: -(r["wait_s"] + r["full_s"]))

        # admission lifecycle (runtime/scheduler.py): queue wait + declared
        # footprint of this query's admission grant
        admission = None
        for e in evs:
            if e["event"] == "query.admitted":
                admission = {
                    "waited_s": e.get("waited_s", 0.0),
                    "estimate_bytes": e.get("estimate_bytes", 0),
                    "priority": e.get("priority", 0),
                    "running_at_admit": e.get("running", 1),
                }

        # whole-stage fusion plane (plan/stages.py): one stage.fused record
        # per fused stage at plan time; joined with the plan.stats node
        # ledger in render_stats for dispatches-per-batch
        fused_stages = [
            {"stage": e.get("stage"), "members": e.get("members") or [],
             "nodes": e.get("nodes") or [], "fused": e.get("fused") or []}
            for e in evs if e["event"] == "stage.fused"]

        queries.append({
            "query": qid,
            "description": rec.get("description", ""),
            "fused_stages": fused_stages,
            "admission": admission,
            "wall_s": wall_s,
            "total_self_s": round(total_self, 6),
            "coverage": round(total_self / wall_s, 3) if wall_s else None,
            "operators": ops,
            "spill": spills,
            "retries": retries,
            "shuffles": shuffles,
            "stats": plan_stats,
            "readahead_stalls": stalls,
            "pipeline_edges": pipeline_edges,
            "resilience": rec.get("resilience") or {},
            "batches": sum(1 for e in evs if e["event"] == "batch"),
        })

    # cluster recovery ladder (cluster/minicluster.py driver scheduler):
    # aggregated across the whole log, not per query — an executor death is
    # cluster state, and recovery events may land outside a query scope
    # (heartbeat polls between queries)
    attempts: dict = {}
    for r in records:
        if r["event"] == "task.attempt":
            reason = r.get("reason", "unknown")
            attempts[reason] = attempts.get(reason, 0) + 1
    recomputes = [{
        "shuffle": r.get("shuffle"), "epoch": r.get("epoch"),
        "splits": r.get("splits"), "total_splits": r.get("total_splits"),
    } for r in records if r["event"] == "stage.recompute.partial"]
    recovery = {
        "task_attempts": attempts,
        "executors_lost": sum(1 for r in records
                              if r["event"] == "executor.lost"),
        "lost_reasons": sorted({r.get("reason", "") for r in records
                                if r["event"] == "executor.lost"}),
        "executors_blacklisted": sum(
            1 for r in records if r["event"] == "executor.blacklisted"),
        "partial_recomputes": recomputes,
        "map_tasks_recomputed": sum(r["splits"] or 0 for r in recomputes),
        "speculation_won": sum(1 for r in records
                               if r["event"] == "speculation.won"),
        "speculation_lost": sum(1 for r in records
                                if r["event"] == "speculation.lost"),
    }

    # multi-tenant admission/lifecycle (runtime/scheduler.py): aggregated
    # across the whole log — shed submissions never reach query.end, and a
    # cancelled query's story is its lifecycle events, not an operator table
    waits = [r.get("waited_s", 0.0) for r in records
             if r["event"] == "query.admitted"]
    sheds = [{"query": r.get("query"), "reason": r.get("reason"),
              "backoff_hint_s": r.get("backoff_hint_s")}
             for r in records if r["event"] == "query.shed"]
    cancelled = [{"query": r.get("query"), "reason": r.get("reason"),
                  "kind": r["event"].split(".", 1)[1]}
                 for r in records
                 if r["event"] in ("query.cancelled", "query.deadline")]
    demotions = [{"query": r.get("query"),
                  "faulting_query": r.get("faulting_query"),
                  "bytes": r.get("bytes", 0)}
                 for r in records if r["event"] == "query.demoted"]
    admission = {
        "admitted": len(waits),
        "queued": sum(1 for r in records if r["event"] == "query.queued"),
        "max_wait_s": round(max(waits), 4) if waits else 0.0,
        "mean_wait_s": round(sum(waits) / len(waits), 4) if waits else 0.0,
        "shed": sheds,
        "cancelled": cancelled,
        "demotions": demotions,
    }

    health = [r for r in records if r["event"] == "executor.health"]
    hb_loss = [r for r in records if r["event"] == "heartbeat.loss"]
    return {
        "queries": queries,
        "recovery": recovery,
        "admission": admission,
        "events_total": len(records),
        "health_samples": len(health),
        "heartbeat_losses": len(hb_loss),
        "errors": sum(1 for r in records if r["event"] == "query.error"),
    }


# ---------------------------------------------------------------------------
# distributed trace: span-file merge + Chrome export + critical path
# ---------------------------------------------------------------------------

def _tracing_module():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from spark_rapids_tpu.runtime import tracing
    return tracing


def load_spans(logdir: str):
    """Parse every spans-*.jsonl under `logdir`; returns (records,
    violations). Each record gains `_t0`/`_t1`: clock-offset-corrected
    start/end epoch seconds (instants have _t0 == _t1)."""
    tracing = _tracing_module()
    records, violations = [], []
    paths = sorted(pathlib.Path(logdir).glob("spans-*.jsonl"))
    if not paths:
        violations.append(f"{logdir}: no spans-*.jsonl span files")
    for path in paths:
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError as e:
                    violations.append(
                        f"{path}:{lineno}: unparseable line ({e})")
                    continue
                errs = tracing.validate_span(rec)
                if errs:
                    violations.extend(f"{path}:{lineno}: {v}" for v in errs)
                    continue
                off = rec.get("off", 0.0) or 0.0
                rec["_t0"] = rec["ts"] + off
                rec["_t1"] = rec["_t0"] + (rec.get("dur") or 0.0)
                records.append(rec)
    return records, violations


def pick_trace(records: list, query: "str | None" = None):
    """Select one trace's spans. `query` matches the trace id exactly (a
    query id IS its default trace id). Default: the trace with the latest
    activity (the run the operator just finished)."""
    by_trace: dict = {}
    for r in records:
        if r.get("trace"):
            by_trace.setdefault(r["trace"], []).append(r)
    if query is not None:
        return query, by_trace.get(query, [])
    if not by_trace:
        return None, []
    tid = max(by_trace, key=lambda t: max(r["_t1"] for r in by_trace[t]))
    return tid, by_trace[tid]


def chrome_trace(spans: list) -> dict:
    """Chrome-trace-event JSON (Perfetto-loadable): one pid lane per
    process (labelled by its `proc`), one tid lane per thread, `X` complete
    events for ranges and `i` instants for span events; timestamps in
    microseconds relative to the earliest span, clock offsets applied."""
    if not spans:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    base = min(s["_t0"] for s in spans)
    events = []
    procs: dict = {}
    tids: dict = {}
    for s in spans:
        pid = s["pid"]
        if pid not in procs:
            procs[pid] = s.get("proc", f"pid{pid}")
            events.append({"name": "process_name", "ph": "M", "pid": pid,
                           "tid": 0, "args": {"name": procs[pid]}})
        tkey = (pid, s["tid"])
        if tkey not in tids:
            tids[tkey] = len([k for k in tids if k[0] == pid]) + 1
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tids[tkey], "args": {"name": s["tid"]}})
        ev = {"name": s["name"], "ph": s["ph"], "pid": pid,
              "tid": tids[tkey], "ts": round((s["_t0"] - base) * 1e6, 3)}
        if s["ph"] == "C":
            # counter track (memory lanes): args are numeric series only —
            # Perfetto plots one stacked lane per (process, name), so no
            # trace-id string may pollute the series dict
            ev["args"] = dict(s.get("args") or {})
        else:
            ev["args"] = dict(s.get("args") or {}, trace=s.get("trace"))
        if s["ph"] == "X":
            ev["dur"] = round((s.get("dur") or 0.0) * 1e6, 3)
        elif s["ph"] == "i":
            ev["s"] = "t"
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# span-name → blame category for the critical-path table. Deliberately
# name-based: every producer of spans (trace_range call sites, task/pipeline
# wrappers, the fetch/serve paths) is in-repo, so the mapping is total
# enough, and anything novel lands in "other" rather than crashing.
_BLAME = (
    ("queue-wait", ("queue", "wait", "admission", "semaphore", "stall")),
    ("decode", ("decode", "scan", "readahead", "parquet", "orc", "csv")),
    ("exchange", ("fetch", "exchange", "shuffle", "transport", "serve",
                  "h2d", "d2h", "broadcast", "spill")),
    ("compute", ("project", "filter", "agg", "join", "sort", "window",
                 "expand", "generate", "udf", "pandas", "python", "task.",
                 "pipeline.", "compute")),
)

# container/window spans excluded from the dependent-chain walk: they
# overlap everything inside them and carry no blame of their own
_WINDOW_NAMES = ("query", "cluster.query")


def _blame_category(name: str) -> str:
    n = name.lower()
    for cat, keys in _BLAME:
        if any(k in n for k in keys):
            return cat
    return "other"


def critical_path(spans: list):
    """The longest dependent chain of spans bounding the trace's wall time.

    Window = the trace's `query`/`cluster.query` span (fallback: the full
    span extent). Backward greedy walk: from the window's end, repeatedly
    take the span active at the cursor with the LATEST start (the innermost
    leaf — container spans lose ties by construction), jump to its start,
    and record uncovered gaps as idle. Returns (window, chain, blame) where
    chain entries carry their clipped contribution and blame sums
    contributions per category."""
    windows = [s for s in spans if s["ph"] == "X"
               and s["name"] in _WINDOW_NAMES]
    xs = [s for s in spans if s["ph"] == "X"
          and s["name"] not in _WINDOW_NAMES and (s.get("dur") or 0) > 0]
    if windows:
        w = max(windows, key=lambda s: s.get("dur") or 0.0)
        t_start, t_end = w["_t0"], w["_t1"]
        wname = w["name"]
    elif xs:
        t_start = min(s["_t0"] for s in xs)
        t_end = max(s["_t1"] for s in xs)
        wname = "(extent)"
    else:
        return None, [], {}
    window = {"name": wname, "start": t_start, "wall_s": t_end - t_start}
    eps = 1e-7
    chain = []
    cursor = t_end
    while cursor > t_start + eps and len(chain) < 1024:
        active = [s for s in xs
                  if s["_t0"] < cursor - eps and s["_t1"] >= cursor - eps]
        if active:
            s = max(active, key=lambda a: a["_t0"])
            lo = max(s["_t0"], t_start)
            chain.append({"name": s["name"], "proc": s.get("proc"),
                          "tid": s["tid"],
                          "category": _blame_category(s["name"]),
                          "start_s": lo - t_start,
                          "contrib_s": min(s["_t1"], cursor) - lo,
                          "span_dur_s": s.get("dur") or 0.0})
            cursor = s["_t0"]
        else:
            ends = [s["_t1"] for s in xs if s["_t1"] < cursor - eps]
            nxt = max(ends) if ends else t_start
            nxt = max(nxt, t_start)
            chain.append({"name": "(unattributed)", "proc": None,
                          "tid": None, "category": "other",
                          "start_s": nxt - t_start,
                          "contrib_s": cursor - nxt, "span_dur_s": 0.0})
            cursor = nxt
    chain.reverse()
    blame: dict = {}
    for c in chain:
        blame[c["category"]] = blame.get(c["category"], 0.0) + c["contrib_s"]
    return window, chain, blame


def render_critical_path(window, chain, blame, top: int = 15) -> str:
    out = [f"== critical path: window {window['name']} "
           f"wall={window['wall_s']:.4f}s, {len(chain)} chain segments"]
    total = sum(blame.values()) or 1.0
    ranked = sorted(blame.items(), key=lambda kv: -kv[1])
    out.append("  per-edge blame (chain seconds bounding wall time):")
    for cat, s in ranked:
        out.append(f"    {cat:>10}: {s:>9.4f}s  {s / total:>6.1%}")
    if ranked:
        out.append(f"  bounding edge: {ranked[0][0]} "
                   f"({ranked[0][1]:.4f}s of {window['wall_s']:.4f}s wall)")
    merged = sorted(chain, key=lambda c: -c["contrib_s"])[:top]
    out.append(f"  top chain segments (of {len(chain)}):")
    out.append(f"    {'start_s':>9}  {'contrib_s':>9}  {'category':>10}  "
               "span @ process/thread")
    for c in merged:
        loc = f"{c['proc']}/{c['tid']}" if c["proc"] else "-"
        out.append(f"    {c['start_s']:>9.4f}  {c['contrib_s']:>9.4f}  "
                   f"{c['category']:>10}  {c['name']} @ {loc}")
    return "\n".join(out)


def trace_main(args) -> int:
    records, violations = load_spans(args.logdir)
    rc = 0
    if violations:
        for v in violations:
            print(f"SPAN SCHEMA VIOLATION: {v}", file=sys.stderr)
        rc = 1
    trace_id, spans = pick_trace(records, args.query)
    if not spans:
        print(f"ERROR: no spans for trace {args.query or '<latest>'} in "
              f"{args.logdir}", file=sys.stderr)
        return 1
    trace = chrome_trace(spans)
    out_path = args.out or os.path.join(args.logdir, "trace.json")
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(trace, f)
    n_procs = len({s["pid"] for s in spans})
    print(f"trace {trace_id}: {len(spans)} spans from {n_procs} process(es) "
          f"-> {out_path} (load in Perfetto / chrome://tracing)")
    window, chain, blame = critical_path(spans)
    if window is None or not chain:
        print("ERROR: empty critical path (no complete spans in the trace)",
              file=sys.stderr)
        return 1
    print(render_critical_path(window, chain, blame, top=args.top))
    return rc


# ---------------------------------------------------------------------------
# memory plane: heap snapshots, watermark timeline, leak detections
# ---------------------------------------------------------------------------

UNATTRIBUTED_SITE = "catalog.add_batch"


def analyze_memory(records: list) -> dict:
    """Replay the memory-plane events of one log: watermark timeline per
    process, the final heap snapshot, per-query peak/site summaries (from
    query.end's embedded memory field), leak detections, and the peak
    attribution ratio — the fraction of the highest recorded device
    occupancy held by NAMED allocation sites (vs the unattributed
    bucket)."""
    watermarks = [{
        "t": r.get("t"), "pid": r.get("pid"), "query": r.get("query"),
        "device_bytes": r.get("device_bytes", 0),
        "host_bytes": r.get("host_bytes", 0),
        "disk_bytes": r.get("disk_bytes", 0),
        "watermark_bytes": r.get("watermark_bytes", 0),
        "sites": r.get("sites") or {},
    } for r in records if r["event"] == "memory.watermark"]
    snapshots = [r for r in records if r["event"] == "memory.snapshot"]
    leaks = [{
        "query": r.get("query"), "bytes": r.get("bytes", 0),
        "buffers": r.get("buffers", 0), "sites": r.get("sites") or {},
    } for r in records if r["event"] == "memory.leak"]
    queries = [{
        "query": r.get("query"), "description": r.get("description", ""),
        **(r.get("memory") or {}),
    } for r in records if r["event"] == "query.end" and r.get("memory")]

    peak = max(watermarks, key=lambda w: w["device_bytes"], default=None)
    attribution = None
    if peak and peak["device_bytes"]:
        named = sum(v for s, v in peak["sites"].items()
                    if s != UNATTRIBUTED_SITE)
        attribution = round(named / peak["device_bytes"], 4)

    snap = None
    if snapshots:
        s = snapshots[-1]
        snap = {k: s.get(k) for k in ("device_bytes", "host_bytes",
                                      "disk_bytes", "watermark_bytes",
                                      "device_budget", "buffers")}
        snap["sites"] = s.get("sites") or []
    return {
        "watermarks": watermarks,
        "snapshot": snap,
        "queries": queries,
        "leaks": leaks,
        "peak": peak,
        "peak_attribution": attribution,
    }


def diff_memory(a: dict, b: dict) -> dict:
    """Per-site deltas between two analyses' final heap snapshots (B - A):
    live/peak/cumulative bytes per site plus the tier totals — the
    before/after math of ``memory --diff``."""
    sa = {e["site"]: e for e in ((a.get("snapshot") or {}).get("sites") or [])}
    sb = {e["site"]: e for e in ((b.get("snapshot") or {}).get("sites") or [])}
    rows = []
    for site in sorted(set(sa) | set(sb)):
        ea, eb = sa.get(site, {}), sb.get(site, {})
        rows.append({
            "site": site,
            "live_a": ea.get("live_bytes", 0),
            "live_b": eb.get("live_bytes", 0),
            "delta_live": eb.get("live_bytes", 0) - ea.get("live_bytes", 0),
            "delta_peak": (eb.get("peak_device_bytes", 0)
                           - ea.get("peak_device_bytes", 0)),
            "delta_cumulative": (eb.get("cumulative_bytes", 0)
                                 - ea.get("cumulative_bytes", 0)),
        })
    rows.sort(key=lambda r: (-abs(r["delta_live"]), -abs(r["delta_peak"])))
    ta, tb = a.get("snapshot") or {}, b.get("snapshot") or {}
    totals = {k: (tb.get(k) or 0) - (ta.get(k) or 0)
              for k in ("device_bytes", "host_bytes", "disk_bytes",
                        "watermark_bytes", "buffers")}
    return {"sites": rows, "totals": totals,
            "leaks_a": len(a.get("leaks") or []),
            "leaks_b": len(b.get("leaks") or [])}


def render_memory(mem: dict, top: int = 15) -> str:
    out = []
    snap = mem.get("snapshot")
    if snap:
        out.append(f"== heap snapshot (final): device "
                   f"{_fmt_bytes(snap['device_bytes'])} / budget "
                   f"{_fmt_bytes(snap['device_budget'])}, host "
                   f"{_fmt_bytes(snap['host_bytes'])}, disk "
                   f"{_fmt_bytes(snap['disk_bytes'])}, watermark "
                   f"{_fmt_bytes(snap['watermark_bytes'])}, "
                   f"{snap['buffers']} live buffers")
        out.append(f"  {'live':>10}  {'peak_dev':>10}  {'cumulative':>11}  "
                   f"{'allocs':>7}  {'frees':>7}  site [tiers] nodes")
        for e in snap["sites"][:top]:
            tiers = ",".join(f"{t}={_fmt_bytes(v)}"
                             for t, v in sorted((e.get("tiers") or {}).items()))
            nodes = ",".join(str(n) for n in (e.get("nodes") or [])[:6])
            out.append(
                f"  {_fmt_bytes(e.get('live_bytes', 0)):>10}  "
                f"{_fmt_bytes(e.get('peak_device_bytes', 0)):>10}  "
                f"{_fmt_bytes(e.get('cumulative_bytes', 0)):>11}  "
                f"{e.get('allocs', 0):>7}  {e.get('frees', 0):>7}  "
                f"{e['site']}" + (f" [{tiers}]" if tiers else "")
                + (f" nodes={nodes}" if nodes else ""))
    for q in mem["queries"]:
        out.append(f"== query {q['query']} [{q.get('description', '')}]: "
                   f"peak {_fmt_bytes(q.get('peak_device_bytes', 0))}, "
                   f"cumulative {_fmt_bytes(q.get('cumulative_bytes', 0))}, "
                   f"{q.get('allocs', 0)} allocs")
        sites = sorted((q.get("sites") or {}).items(),
                       key=lambda kv: -kv[1].get("peak_bytes", 0))
        for site, s in sites[:top]:
            nodes = ",".join(str(n) for n in (s.get("nodes") or [])[:6])
            out.append(f"    {_fmt_bytes(s.get('peak_bytes', 0)):>10} peak  "
                       f"{_fmt_bytes(s.get('cumulative_bytes', 0)):>10} cum  "
                       f"{site}" + (f" nodes={nodes}" if nodes else ""))
    wm = mem["watermarks"]
    if wm:
        out.append(f"== watermark timeline ({len(wm)} samples):")
        shown = wm if len(wm) <= top else \
            [wm[i * (len(wm) - 1) // (top - 1)] for i in range(top)]
        out.append(f"    {'t':>12}  {'device':>10}  {'host':>10}  "
                   f"{'disk':>10}  {'watermark':>10}  top site")
        for w in shown:
            tops = max(w["sites"].items(), key=lambda kv: kv[1],
                       default=(None, 0))
            out.append(f"    {w['t']:>12.4f}  "
                       f"{_fmt_bytes(w['device_bytes']):>10}  "
                       f"{_fmt_bytes(w['host_bytes']):>10}  "
                       f"{_fmt_bytes(w['disk_bytes']):>10}  "
                       f"{_fmt_bytes(w['watermark_bytes']):>10}  "
                       + (f"{tops[0]}={_fmt_bytes(tops[1])}"
                          if tops[0] else "-"))
    peak = mem.get("peak")
    if peak:
        out.append(f"== peak: {_fmt_bytes(peak['device_bytes'])} device at "
                   f"t={peak['t']:.4f}"
                   + (f", attribution {mem['peak_attribution']:.0%} to "
                      "named sites"
                      if mem.get("peak_attribution") is not None else ""))
        for site, v in sorted(peak["sites"].items(), key=lambda kv: -kv[1]):
            out.append(f"    {_fmt_bytes(v):>10}  {site}")
    if mem["leaks"]:
        out.append(f"== LEAKS ({len(mem['leaks'])} detected):")
        for lk in mem["leaks"]:
            sites = ", ".join(f"{s}={_fmt_bytes(v)}"
                              for s, v in sorted(lk["sites"].items()))
            out.append(f"    query {lk['query']}: {_fmt_bytes(lk['bytes'])} "
                       f"in {lk['buffers']} buffer(s) [{sites}]")
    else:
        out.append("== no leaks detected")
    return "\n".join(out)


def render_memory_diff(d: dict, name_a: str, name_b: str,
                       top: int = 15) -> str:
    out = [f"== memory diff A={name_a} B={name_b}"]
    t = d["totals"]
    out.append("  totals (B-A): " + ", ".join(
        f"{k}={'+' if v >= 0 else ''}{_fmt_bytes(v)}"
        if k != "buffers" else f"{k}={v:+d}"
        for k, v in t.items()))
    out.append(f"  {'live A':>10}  {'live B':>10}  {'Δlive':>11}  "
               f"{'Δpeak':>11}  {'Δcumulative':>12}  site")
    for r in d["sites"][:top]:
        out.append(f"  {_fmt_bytes(r['live_a']):>10}  "
                   f"{_fmt_bytes(r['live_b']):>10}  "
                   f"{'+' if r['delta_live'] >= 0 else ''}"
                   f"{_fmt_bytes(r['delta_live']):>10}  "
                   f"{'+' if r['delta_peak'] >= 0 else ''}"
                   f"{_fmt_bytes(r['delta_peak']):>10}  "
                   f"{'+' if r['delta_cumulative'] >= 0 else ''}"
                   f"{_fmt_bytes(r['delta_cumulative']):>11}  {r['site']}")
    out.append(f"  leaks: {d['leaks_a']} -> {d['leaks_b']}")
    return "\n".join(out)


def memory_main(args) -> int:
    records, violations = load_log(args.eventlog)
    rc = 0
    if violations:
        for v in violations:
            print(f"SCHEMA VIOLATION: {v}", file=sys.stderr)
        rc = 1
    mem = analyze_memory(records)
    if not (mem["watermarks"] or mem["snapshot"] or mem["queries"]):
        print(f"ERROR: no memory-plane events in {args.eventlog} "
              "(memory.watermark / memory.snapshot / query.end memory)",
              file=sys.stderr)
        return 1
    if args.diff:
        other_records, other_violations = load_log(args.diff)
        if other_violations:
            for v in other_violations:
                print(f"SCHEMA VIOLATION: {v}", file=sys.stderr)
            rc = 1
        other = analyze_memory(other_records)
        d = diff_memory(mem, other)
        if args.json:
            print(json.dumps(d, indent=2, default=str))
        else:
            print(render_memory_diff(d, args.eventlog, args.diff,
                                     top=args.top))
        return rc
    if args.json:
        print(json.dumps(mem, indent=2, default=str))
    else:
        print(render_memory(mem, top=args.top))
    return rc


# ---------------------------------------------------------------------------
# movement plane (runtime/movement.py)
# ---------------------------------------------------------------------------

def _movement_module():
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from spark_rapids_tpu.runtime import movement
    return movement


def analyze_movement(records: list) -> dict:
    """Replay the movement plane of one or more merged per-process logs.
    movement.sample events are CUMULATIVE ledger snapshots, so the merged
    view is the LAST sample per pid summed across pids; per-query sections
    come from query.end's embedded movement field. The matrix speaks
    payload (block-store) units so its shuffle row cross-checks against
    registered partition sizes; the link ratio speaks wire bytes."""
    mv = _movement_module()
    last_sample: dict = {}
    for r in records:
        if r.get("event") == "movement.sample":
            last_sample[r.get("pid")] = r
    flows: dict = {}
    for rec in last_sample.values():
        for f in rec.get("flows") or []:
            k = (f.get("edge", "?"), f.get("link", "?"))
            cell = flows.setdefault(
                k, {"bytes": 0, "payload_bytes": 0, "transfers": 0})
            cell["bytes"] += int(f.get("bytes", 0))
            cell["payload_bytes"] += int(f.get("payload_bytes", 0))
            cell["transfers"] += int(f.get("transfers", 0))

    # source -> destination byte matrix in payload units
    matrix: dict = {}
    for (edge, _link), cell in flows.items():
        src, dst = mv.EDGES.get(edge, ("?", "?"))
        matrix[(src, dst)] = matrix.get((src, dst), 0) \
            + cell["payload_bytes"]

    # loopback-vs-remote split of the bytes that could have crossed a NIC
    # (wire units; h2d/d2h/ici/disk never ride the network)
    by_link = {"tcp": 0, "loopback": 0, "local": 0}
    for (edge, link), cell in flows.items():
        if edge in mv.NETWORK_EDGES and link in by_link:
            by_link[link] += cell["bytes"]

    # two-level exchange rollup: the intra-mesh level is everything that
    # rode ICI collectives (cluster/minicluster.py's exchange_wave path);
    # the block-store level splits into same-host (loopback TCP + in-process
    # short-circuit reads) and genuinely cross-host TCP. Separating the
    # levels at a glance is what shows a two-level run moving shuffle
    # content off the loopback rows and onto the ici row.
    levels = {k: {"bytes": 0, "payload_bytes": 0}
              for k in ("intra_mesh", "same_host", "cross_host")}
    for (edge, link), cell in flows.items():
        if link == "ici":
            lvl = "intra_mesh"
        elif edge.startswith("shuffle."):
            lvl = "cross_host" if link == "tcp" else "same_host"
        else:
            continue
        levels[lvl]["bytes"] += cell["bytes"]
        levels[lvl]["payload_bytes"] += cell["payload_bytes"]

    queries = [{
        "query": r.get("query"), "description": r.get("description", ""),
        **(r.get("movement") or {}),
    } for r in records if r.get("event") == "query.end" and r.get("movement")]

    top = sorted(
        ({"edge": e, "link": lk, **cell}
         for (e, lk), cell in flows.items()),
        key=lambda f: max(f["bytes"], f["payload_bytes"]), reverse=True)
    return {
        "processes": sorted(last_sample),
        "flows": top,
        "matrix": {f"{s}->{d}": v for (s, d), v in sorted(matrix.items())},
        "by_link": by_link,
        "exchange_levels": levels,
        "queries": queries,
        "total_bytes": sum(c["bytes"] for c in flows.values()),
        "total_payload_bytes": sum(c["payload_bytes"]
                                   for c in flows.values()),
    }


def render_movement(m: dict, top: int = 15) -> str:
    out = [f"== movement: {len(m['processes'])} process ledger(s) merged, "
           f"{_fmt_bytes(m['total_bytes'])} wire / "
           f"{_fmt_bytes(m['total_payload_bytes'])} payload"]
    if m["matrix"]:
        out.append("  byte matrix (payload units, source -> destination):")
        srcs = sorted({k.split("->")[0] for k in m["matrix"]})
        dsts = sorted({k.split("->")[1] for k in m["matrix"]})
        out.append("    " + f"{'':>8}" + "".join(f"{d:>12}" for d in dsts))
        for s in srcs:
            row = "".join(
                f"{_fmt_bytes(m['matrix'][f'{s}->{d}']):>12}"
                if f"{s}->{d}" in m["matrix"] else f"{'-':>12}"
                for d in dsts)
            out.append(f"    {s:>8}" + row)
    if m["flows"]:
        out.append("  top flows:")
        out.append(f"    {'wire':>10}  {'payload':>10}  {'transfers':>9}  "
                   "edge[link]")
        for f in m["flows"][:top]:
            out.append(f"    {_fmt_bytes(f['bytes']):>10}  "
                       f"{_fmt_bytes(f['payload_bytes']):>10}  "
                       f"{f['transfers']:>9}  {f['edge']}[{f['link']}]")
        heaviest = m["flows"][0]
        out.append(f"  heaviest flow: {heaviest['edge']}[{heaviest['link']}]"
                   f" — {_fmt_bytes(heaviest['bytes'])} wire / "
                   f"{_fmt_bytes(heaviest['payload_bytes'])} payload in "
                   f"{heaviest['transfers']} transfer(s)")
    lv = m.get("exchange_levels") or {}
    if any(v["bytes"] or v["payload_bytes"] for v in lv.values()):
        im, sh, xh = (lv.get(k, {"bytes": 0, "payload_bytes": 0})
                      for k in ("intra_mesh", "same_host", "cross_host"))
        out.append(
            "  exchange levels: "
            f"intra-mesh(ici)={_fmt_bytes(im['bytes'])} wire"
            f"/{_fmt_bytes(im['payload_bytes'])} payload  "
            f"same-host={_fmt_bytes(sh['bytes'])}"
            f"/{_fmt_bytes(sh['payload_bytes'])}  "
            f"cross-host={_fmt_bytes(xh['bytes'])}"
            f"/{_fmt_bytes(xh['payload_bytes'])}")
    lk = m["by_link"]
    net = lk["tcp"] + lk["loopback"] + lk["local"]
    if net:
        out.append(
            "  loopback-vs-remote: "
            f"tcp={_fmt_bytes(lk['tcp'])} "
            f"loopback={_fmt_bytes(lk['loopback'])} "
            f"local={_fmt_bytes(lk['local'])}"
            + (f" — {lk['tcp'] / net:.0%} of network-capable bytes "
               "crossed a host boundary" if lk["tcp"]
               else " — no cross-host traffic (everything stayed on-host)"))
    for q in m["queries"]:
        line = (f"  query {q['query']} [{q.get('description', '')}]: "
                f"{_fmt_bytes(q.get('total_bytes', 0))} moved")
        if q.get("result_bytes"):
            line += (f", result {_fmt_bytes(q['result_bytes'])}, "
                     f"amplification {q.get('amplification')}x")
        out.append(line)
    return "\n".join(out)


def movement_main(args) -> int:
    records, violations = [], []
    for path in args.eventlog:
        recs, viols = load_log(path)
        records.extend(recs)
        violations.extend(viols)
    rc = 0
    if violations:
        for v in violations:
            print(f"SCHEMA VIOLATION: {v}", file=sys.stderr)
        rc = 1
    m = analyze_movement(records)
    if not (m["flows"] or m["queries"]):
        print("ERROR: no movement-plane events in "
              f"{', '.join(args.eventlog)} (movement.sample / query.end "
              "movement)", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(m, indent=2, default=str))
    else:
        print(render_movement(m, top=args.top))
    return rc


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------

def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n}B"


def render(analysis: dict, top: int = 15) -> str:
    out = []
    for i, q in enumerate(analysis["queries"]):
        out.append(f"== query {i}: {q['query']} [{q['description']}] "
                   f"wall={q['wall_s']:.4f}s self-total={q['total_self_s']:.4f}s"
                   + (f" coverage={q['coverage']:.0%}"
                      if q["coverage"] is not None else ""))
        adm = q.get("admission")
        if adm is not None:
            out.append(
                f"  admission: waited {adm['waited_s']:.4f}s, estimate "
                f"{_fmt_bytes(adm['estimate_bytes'])}, priority "
                f"{adm['priority']}, {adm['running_at_admit']} running")
        out.append("  top operators by self time:")
        out.append(f"    {'self_s':>10}  {'rows':>12}  {'batches':>8}  operator")
        for r in q["operators"][:top]:
            rows = "" if r["rows"] is None else str(r["rows"])
            bat = "" if r["batches"] is None else str(r["batches"])
            out.append(f"    {r['self_s']:>10.4f}  {rows:>12}  {bat:>8}  "
                       f"{r['op']}"
                       + (f" {r['args']}" if r["args"] else ""))
        if q["spill"]:
            out.append("  spill hotspots:")
            for node, s in sorted(q["spill"].items(),
                                  key=lambda kv: -kv[1]["bytes"]):
                tiers = ", ".join(f"{t}={_fmt_bytes(b)}"
                                  for t, b in s["tiers"].items())
                out.append(f"    {node}: {s['events']} spills "
                           f"{_fmt_bytes(s['bytes'])} ({tiers})")
        if q["retries"]:
            out.append("  retry/fetch hotspots:")
            for node, r in sorted(q["retries"].items()):
                kv = ", ".join(f"{k}={v}" for k, v in sorted(r.items()))
                out.append(f"    {node}: {kv}")
        if q["shuffles"]:
            out.append("  shuffle partition skew:")
            for s in q["shuffles"]:
                out.append(
                    f"    {s['node']} shuffle={s['shuffle']}: "
                    f"{s['partitions']} partitions "
                    f"{_fmt_bytes(s['total_bytes'])} total, "
                    f"max={_fmt_bytes(s['max_bytes'])}"
                    + (f" (partition {s['max_partition']})"
                       if s.get("max_partition") is not None else "")
                    + f" skew(max/mean)={s['skew']} "
                    f"empty={s['empty_partitions']}")
        if q["readahead_stalls"]:
            out.append("  scan readahead stall time:")
            for s in q["readahead_stalls"]:
                out.append(f"    {s['node']}: {s['stall_s']:.4f}s")
        if q.get("pipeline_edges"):
            out.append("  pipeline queue stalls per edge "
                       "(wait=consumer starved, full=producer backed up):")
            for e in q["pipeline_edges"]:
                out.append(
                    f"    {e['edge']} @ {e['node']}: "
                    f"wait={e['wait_s']:.4f}s full={e['full_s']:.4f}s "
                    f"depth_peak={e['depth_peak']}"
                    + (f" stall_events={e['stall_events']}"
                       if e["stall_events"] else ""))
        if any(q["resilience"].values()):
            out.append(f"  resilience deltas: {q['resilience']}")
        out.append("")
    rec = analysis.get("recovery") or {}
    if (rec.get("executors_lost") or rec.get("task_attempts")
            or rec.get("speculation_won") or rec.get("speculation_lost")):
        out.append("== recovery (task attempt -> partial stage recompute -> "
                   "whole-query heal):")
        if rec["task_attempts"]:
            kv = ", ".join(f"{k}={v}"
                           for k, v in sorted(rec["task_attempts"].items()))
            out.append(f"  task attempts by reason: {kv}")
        if rec["executors_lost"]:
            out.append(f"  executors lost: {rec['executors_lost']} "
                       f"(reasons: {', '.join(rec['lost_reasons'])}); "
                       f"blacklisted: {rec['executors_blacklisted']}")
        for pr in rec["partial_recomputes"]:
            out.append(f"  partial recompute shuffle={pr['shuffle']} "
                       f"epoch={pr['epoch']}: {pr['splits']}/"
                       f"{pr['total_splits']} map splits re-run")
        if rec["speculation_won"] or rec["speculation_lost"]:
            out.append(f"  speculation: won={rec['speculation_won']} "
                       f"lost={rec['speculation_lost']}")
        out.append("")
    adm = analysis.get("admission") or {}
    if (adm.get("shed") or adm.get("cancelled") or adm.get("demotions")
            or (adm.get("admitted", 0) and adm.get("max_wait_s", 0) > 0)):
        out.append("== admission / lifecycle (driver-side query scheduler):")
        out.append(f"  admitted={adm['admitted']} queued={adm['queued']} "
                   f"wait mean={adm['mean_wait_s']:.4f}s "
                   f"max={adm['max_wait_s']:.4f}s")
        for s in adm.get("shed", []):
            out.append(f"  shed {s['query']}: {s['reason']} "
                       f"(retry after ~{s['backoff_hint_s']}s)")
        for c in adm.get("cancelled", []):
            out.append(f"  {c['kind']} {c['query']}: {c['reason']}")
        for d in adm.get("demotions", []):
            out.append(f"  demoted {d['query']} ({_fmt_bytes(d['bytes'])} "
                       f"spilled) for faulting peer {d['faulting_query']}")
        out.append("")
    out.append(f"{len(analysis['queries'])} queries, "
               f"{analysis['events_total']} events, "
               f"{analysis['health_samples']} health samples, "
               f"{analysis['heartbeat_losses']} heartbeat losses, "
               f"{analysis['errors']} query errors, "
               f"{len(adm.get('shed', []))} shed, "
               f"{len(adm.get('cancelled', []))} cancelled")
    return "\n".join(out)


def render_compare(a: dict, b: dict, name_a: str, name_b: str) -> str:
    """Diff two runs: matched by query order, operator self time aggregated
    by operator NAME (plan-node ids are not stable across runs)."""
    out = [f"== compare A={name_a} B={name_b}"]
    pairs = list(zip(a["queries"], b["queries"]))
    if len(a["queries"]) != len(b["queries"]):
        out.append(f"  (query count differs: {len(a['queries'])} vs "
                   f"{len(b['queries'])}; comparing the common prefix)")
    for i, (qa, qb) in enumerate(pairs):
        dw = qb["wall_s"] - qa["wall_s"]
        pct = (dw / qa["wall_s"] * 100) if qa["wall_s"] else 0.0
        out.append(f"-- query {i} [{qa['description']}]: wall "
                   f"{qa['wall_s']:.4f}s -> {qb['wall_s']:.4f}s "
                   f"({pct:+.1f}%)")

        def by_name(q):
            agg: dict = {}
            for r in q["operators"]:
                name = r["op"].split("#")[0] + (
                    " (build)" if r["op"].endswith("(build)") else "")
                agg[name] = agg.get(name, 0.0) + r["self_s"]
            return agg
        na, nb = by_name(qa), by_name(qb)
        rows = sorted(set(na) | set(nb),
                      key=lambda n: -abs(nb.get(n, 0) - na.get(n, 0)))
        for name in rows:
            va, vb = na.get(name, 0.0), nb.get(name, 0.0)
            if max(va, vb) < 1e-4:
                continue
            out.append(f"    {va:>10.4f}s -> {vb:>10.4f}s  "
                       f"({vb - va:+.4f}s)  {name}")
        sa = sum(s["bytes"] for s in qa["spill"].values())
        sb = sum(s["bytes"] for s in qb["spill"].values())
        if sa or sb:
            out.append(f"    spill bytes: {_fmt_bytes(sa)} -> {_fmt_bytes(sb)}")
        qa_stall = sum(e["wait_s"] + e["full_s"]
                       for e in qa.get("pipeline_edges", []))
        qb_stall = sum(e["wait_s"] + e["full_s"]
                       for e in qb.get("pipeline_edges", []))
        if qa_stall or qb_stall:
            out.append(f"    pipeline queue stall: {qa_stall:.4f}s -> "
                       f"{qb_stall:.4f}s")
        ra = {k: v for k, v in qa["resilience"].items() if v}
        rb = {k: v for k, v in qb["resilience"].items() if v}
        if ra or rb:
            out.append(f"    resilience: {ra or '{}'} -> {rb or '{}'}")
    return "\n".join(out)


# ---------------------------------------------------------------------------
# stats subcommand: runtime statistics plane read-out (plan.stats records)
# ---------------------------------------------------------------------------

def render_stats(analysis: dict, top: int = 15) -> str:
    """Estimate-error table, per-node dispatch/transfer ledger and shuffle
    skew tables from the plan.stats records in one event log."""
    out = []
    with_stats = [(i, q) for i, q in enumerate(analysis["queries"])
                  if q.get("stats")]

    out.append("== footprint estimate error (scheduler admission vs observed "
               "device peak):")
    out.append(f"  {'query':>5}  {'estimate':>10}  {'static':>10}  "
               f"{'observed':>10}  {'error':>8}  {'hit':>5}  description")
    for i, q in enumerate(analysis["queries"]):
        st = q.get("stats") or {}
        err = st.get("estimate_error")
        out.append(
            f"  {i:>5}  "
            f"{_fmt_bytes(st.get('estimate_bytes') or 0):>10}  "
            f"{_fmt_bytes(st.get('static_estimate_bytes') or 0):>10}  "
            f"{_fmt_bytes(st.get('peak_device_bytes') or 0):>10}  "
            f"{('' if err is None else format(err, '.3f')):>8}  "
            f"{str(bool(st.get('history_hit'))).lower():>5}  "
            f"{q['description']}"
            + ("" if st else "  [no plan.stats record]"))
    out.append("")

    for i, q in with_stats:
        st = q["stats"]
        out.append(f"== query {i}: {q['query']} [{q['description']}] "
                   f"fingerprint={st.get('fingerprint')}")
        nodes = st.get("nodes") or []
        if nodes:
            out.append("  node ledger (rows / selectivity / dispatch & "
                       "transfer counters):")
            out.append(f"    {'id':>4}  {'rows':>10}  {'batches':>7}  "
                       f"{'sel':>6}  {'disp':>5}  {'comp':>5}  "
                       f"{'output':>9}  {'h2d':>9}  {'d2h':>9}  node")
            def _cell(v, fmt=str):
                return "" if v is None else fmt(v)
            for n in nodes[:max(top, 1)]:
                out.append(
                    f"    {_cell(n.get('id')):>4}  "
                    f"{_cell(n.get('rows')):>10}  "
                    f"{_cell(n.get('batches')):>7}  "
                    f"{_cell(n.get('selectivity'), lambda v: format(v, '.3f')):>6}  "
                    f"{_cell(n.get('dispatches')):>5}  "
                    f"{_cell(n.get('compiles')):>5}  "
                    f"{_cell(n.get('output_bytes'), _fmt_bytes):>9}  "
                    f"{_cell(n.get('h2d_bytes'), _fmt_bytes):>9}  "
                    f"{_cell(n.get('d2h_bytes'), _fmt_bytes):>9}  "
                    f"{'  ' * (n.get('depth') or 0)}{n.get('name')}"
                    + (f" {n['args']}" if n.get("args") else ""))
            if len(nodes) > max(top, 1):
                out.append(f"    ... {len(nodes) - max(top, 1)} more nodes")
        if q.get("fused_stages"):
            by_id = {n.get("id"): n for n in nodes if n.get("id") is not None}
            out.append("  fused stages (members / absorbed operators / "
                       "dispatches per batch):")
            for fs in q["fused_stages"]:
                cells = []
                for name, nid in zip(fs["members"], fs["nodes"]):
                    n = by_id.get(nid) or {}
                    d, b = n.get("dispatches"), n.get("batches")
                    label = name
                    if d is not None:
                        label += f" [disp={d}"
                        if b:
                            label += f" ({d / b:.1f}/batch)"
                        label += "]"
                    cells.append(label)
                out.append(f"    *({fs['stage']}) " + ", ".join(cells))
                for f in fs["fused"]:
                    out.append(f"        fused: {f}")
        if q["shuffles"]:
            out.append("  shuffle partition skew:")
            for s in q["shuffles"]:
                out.append(
                    f"    {s['node']} shuffle={s['shuffle']}: "
                    f"{s['partitions']} partitions "
                    f"{_fmt_bytes(s['total_bytes'])} total, "
                    f"max={_fmt_bytes(s['max_bytes'])}"
                    + (f" at partition {s['max_partition']}"
                       if s.get("max_partition") is not None else "")
                    + f" skew(max/mean)={s['skew']} "
                    f"empty={s['empty_partitions']}")
        out.append("")

    hits = sum(1 for _, q in with_stats
               if (q["stats"] or {}).get("history_hit"))
    out.append(f"{len(analysis['queries'])} queries, {len(with_stats)} with "
               f"plan.stats, {hits} history hits")
    return "\n".join(out)


def stats_main(args) -> int:
    records, violations = load_log(args.eventlog)
    analysis = analyze(records)
    rc = 0
    if violations:
        for v in violations:
            print(f"SCHEMA VIOLATION: {v}", file=sys.stderr)
        rc = 1
    if not any(q.get("stats") for q in analysis["queries"]):
        print(f"ERROR: no plan.stats record in {args.eventlog} (stats plane "
              "disabled, or log predates it)", file=sys.stderr)
        rc = 1
    if args.json:
        payload = {
            "queries": [{"query": q["query"],
                         "description": q["description"],
                         "stats": q.get("stats"),
                         "fused_stages": q.get("fused_stages"),
                         "shuffles": q["shuffles"]}
                        for q in analysis["queries"]],
            "violations": violations,
        }
        print(json.dumps(payload, indent=2, default=str))
    else:
        print(render_stats(analysis, top=args.top))
    return rc


# ---------------------------------------------------------------------------
# fleet observability: cross-replica query journeys + fleet roster
# ---------------------------------------------------------------------------

_JOURNEY_OK = ("served", "cached")


def analyze_journeys(records: list) -> dict:
    """Group query.journey records — merged from any number of replica
    logs — into per-journey attempt timelines. Attempts are ordered by
    (attempt, wall-clock ts); a failover is DERIVED, not recorded: a
    non-success attempt followed by an attempt on a different replica."""
    journeys: dict = {}
    breaches = 0
    for rec in records:
        ev = rec.get("event")
        if ev == "slo.breach":
            breaches += 1
            continue
        if ev != "query.journey" or not rec.get("journey"):
            continue
        journeys.setdefault(rec["journey"], []).append(rec)
    out = []
    for jid, recs in journeys.items():
        recs.sort(key=lambda r: (r.get("attempt") or 0, r.get("ts") or 0.0))
        attempts, prev, failovers = [], None, 0
        for r in recs:
            a = {"attempt": r.get("attempt"), "replica": r.get("replica"),
                 "outcome": r.get("outcome"), "wall_s": r.get("wall_s"),
                 "traces": r.get("traces"), "query": r.get("query"),
                 "ts": r.get("ts"), "failover_from": None}
            for k in ("error", "reason", "stuck"):
                if r.get(k) is not None:
                    a[k] = r[k]
            if (prev is not None and prev["outcome"] not in _JOURNEY_OK
                    and a["replica"] != prev["replica"]):
                a["failover_from"] = prev["replica"]
                failovers += 1
            attempts.append(a)
            prev = a
        ts = [a["ts"] for a in attempts if a["ts"] is not None]
        out.append({
            "journey": jid,
            "attempts": attempts,
            "failovers": failovers,
            "outcome": attempts[-1]["outcome"],
            "replicas": sorted({a["replica"] for a in attempts
                                if a["replica"]}),
            "span_s": round(max(ts) - min(ts), 4) if ts else None,
        })
    out.sort(key=lambda j: min((a["ts"] or 0.0) for a in j["attempts"]))
    total = len(out)
    return {
        "journeys": out,
        "total": total,
        "served": sum(1 for j in out if j["outcome"] in _JOURNEY_OK),
        "failovers": sum(j["failovers"] for j in out),
        "slo_breaches": breaches,
    }


def render_journeys(analysis: dict) -> str:
    L = [f"{analysis['total']} journeys, {analysis['served']} served, "
         f"{analysis['failovers']} failovers, "
         f"{analysis['slo_breaches']} SLO breaches", ""]
    for j in analysis["journeys"]:
        span = f", span {j['span_s']}s" if j["span_s"] is not None else ""
        L.append(f"== journey {j['journey']} — "
                 f"{len(j['attempts'])} attempt(s), "
                 f"{j['failovers']} failover(s), "
                 f"outcome {j['outcome']}{span} ==")
        for a in j["attempts"]:
            parts = [f"  attempt {a['attempt']}",
                     f"replica {a['replica']}",
                     f"outcome {a['outcome']}"]
            if a["wall_s"] is not None:
                parts.append(f"wall_s {a['wall_s']}")
            if a["traces"] is not None:
                parts.append(f"traces {a['traces']}")
            if a["query"]:
                parts.append(f"query {a['query']}")
            if a.get("error"):
                parts.append(f"error {a['error']}")
            if a.get("reason"):
                parts.append(f"reason {a['reason']}")
            if a.get("stuck"):
                parts.append("stuck")
            line = "  ".join(parts)
            if a["failover_from"]:
                line += f"   <- failover from {a['failover_from']}"
            L.append(line)
        L.append("")
    return "\n".join(L)


def journey_main(args) -> int:
    records, violations = [], []
    for path in args.eventlog:
        recs, vio = load_log(path)
        records.extend(recs)
        violations.extend(vio)
    rc = 0
    if violations:
        for v in violations:
            print(f"SCHEMA VIOLATION: {v}", file=sys.stderr)
        rc = 1
    analysis = analyze_journeys(records)
    if args.journey:
        analysis["journeys"] = [j for j in analysis["journeys"]
                                if j["journey"] == args.journey]
        if not analysis["journeys"]:
            print(f"ERROR: journey {args.journey} not found",
                  file=sys.stderr)
            rc = 1
    if not analysis["journeys"] and not args.journey:
        print("ERROR: no query.journey records in "
              + ", ".join(args.eventlog), file=sys.stderr)
        rc = 1
    if args.json:
        analysis["violations"] = violations
        print(json.dumps(analysis, indent=2, default=str))
    else:
        print(render_journeys(analysis))
    return rc


def analyze_fleet(fleet_dir: str) -> dict:
    """Read a fleet membership directory into the fleet roster: live
    replica-*.json lease records (liveness judged against each record's
    own embedded lease_timeout_s vs the file mtime — the lease stamp) and
    departed-*.json tombstones carrying a dead replica's final state."""
    now = time.time()
    replicas = []
    try:
        names = sorted(os.listdir(fleet_dir))
    except OSError as e:
        raise SystemExit(f"ERROR: cannot read fleet dir {fleet_dir}: {e}")
    for n in names:
        if not n.endswith(".json"):
            continue
        live = n.startswith("replica-")
        if not live and not n.startswith("departed-"):
            continue
        p = os.path.join(fleet_dir, n)
        try:
            mtime = os.stat(p).st_mtime
            with open(p, encoding="utf-8") as f:
                rec = json.load(f)
        except (OSError, ValueError):
            continue   # swept / torn mid-read by a live fleet
        age = now - mtime
        timeout = rec.get("lease_timeout_s")
        if live:
            expired = (isinstance(timeout, (int, float))
                       and age > float(timeout))
            status = "expired" if expired else "live"
        else:
            status = "departed"
        replicas.append({**rec, "status": status, "age_s": round(age, 1)})
    order = {"live": 0, "expired": 1, "departed": 2}
    replicas.sort(key=lambda r: (order[r["status"]],
                                 str(r.get("replica"))))
    return {
        "dir": fleet_dir,
        "replicas": replicas,
        "live": sum(1 for r in replicas if r["status"] == "live"),
        "expired": sum(1 for r in replicas if r["status"] == "expired"),
        "departed": sum(1 for r in replicas if r["status"] == "departed"),
    }


def render_fleet(analysis: dict) -> str:
    L = [f"== fleet roster {analysis['dir']} — {analysis['live']} live, "
         f"{analysis['expired']} expired, "
         f"{analysis['departed']} departed ==", ""]
    slo_rows = []
    for r in analysis["replicas"]:
        h = r.get("health") or {}
        L.append(f"replica {r.get('replica')}  [{r['status']}]  "
                 f"pid {r.get('pid')}  age {r['age_s']}s")
        if r["status"] == "departed":
            by = r.get("adopted_by") or "?"
            L.append(f"  adopted by {by}")
        cells = [f"active_queries {h.get('active_queries', '-')}"]
        if h.get("hbm_watermark_bytes"):
            cells.append(
                f"hbm_watermark {_fmt_bytes(h['hbm_watermark_bytes'])}")
        rc_ = h.get("result_cache")
        if rc_:
            cells.append(f"result_cache {rc_.get('hits', 0)}h/"
                         f"{rc_.get('misses', 0)}m")
        fuse = h.get("fuse") or {}
        if fuse:
            cells.append(f"fuse traces {fuse.get('traces', 0)} "
                         f"dispatches {fuse.get('dispatches', 0)}")
        L.append("  last health: " + "  ".join(cells)
                 if h else "  last health: (none recorded)")
        res = h.get("resilience") or {}
        if res:
            L.append("  resilience: " + "  ".join(
                f"{k}={v}" for k, v in sorted(res.items())))
        if r.get("blackbox"):
            L.append(f"  blackbox: {r['blackbox']}")
        slo = h.get("slo")
        if slo:
            slo_rows.append((r.get("replica"), slo))
        L.append("")
    if slo_rows:
        L.append("== SLO ==")
        L.append(f"{'replica':40s} {'target_s':>9s} {'served':>7s} "
                 f"{'breaches':>9s} {'avail':>7s}")
        for rid, slo in slo_rows:
            avail = slo.get("availability")
            L.append(f"{str(rid):40s} {slo.get('target_s', 0):>9} "
                     f"{slo.get('served', 0):>7} "
                     f"{slo.get('breaches', 0):>9} "
                     f"{('-' if avail is None else f'{avail:.4f}'):>7}")
        L.append("")
    return "\n".join(L)


def fleet_main(args) -> int:
    analysis = analyze_fleet(args.fleetdir)
    rc = 0
    if not analysis["replicas"]:
        print(f"ERROR: no membership records or tombstones in "
              f"{args.fleetdir}", file=sys.stderr)
        rc = 1
    if args.json:
        print(json.dumps(analysis, indent=2, default=str))
    else:
        print(render_fleet(analysis))
    return rc


# ---------------------------------------------------------------------------
# streaming: the epoch journal + stream events
# ---------------------------------------------------------------------------

def analyze_streaming(state_dir: str, eventlogs=()) -> dict:
    """One stream's epoch timeline: the journal document (schema-validated
    by the journal's OWN validator, so the enforced schema cannot drift
    from what this tool accepts) plus the stream.* event counts of any
    replica event logs passed alongside."""
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from spark_rapids_tpu.streaming import journal as J
    path = os.path.join(state_dir, J.FILE)
    out = {"journal": path, "violations": [], "log_violations": [],
           "doc": None, "events": {}}
    try:
        with open(path, encoding="utf-8") as f:
            doc = json.load(f)
    except OSError as e:
        out["violations"].append(f"journal unreadable: {e}")
        return out
    except ValueError as e:
        out["violations"].append(f"journal is not JSON: {e}")
        return out
    out["doc"] = doc
    out["violations"] = J.validate_doc(doc)
    counts = {}
    for lp in eventlogs:
        recs, vio = load_log(lp)
        out["log_violations"].extend(vio)
        for rec in recs:
            ev = rec.get("event", "")
            if ev.startswith("stream."):
                counts[ev] = counts.get(ev, 0) + 1
    out["events"] = counts
    return out


def render_streaming(analysis: dict) -> str:
    lines = [f"== epoch journal {analysis['journal']} =="]
    doc = analysis.get("doc")
    if doc:
        lines.append(
            f"source {doc.get('source') or '?'}  committed epoch "
            f"{doc.get('committed_epoch')}  consumed batches "
            f"{len(doc.get('consumed') or [])}")
        pending = doc.get("begin")
        if pending:
            lines.append(
                f"PENDING epoch {pending.get('epoch')} attempt "
                f"{pending.get('attempt')} over "
                f"{len(pending.get('batch_ids') or [])} batch(es) — "
                f"a crashed run; the next coordinator replays it")
        commits = doc.get("commits") or []
        if commits:
            lines.append(f"{'epoch':>6} {'att':>4} {'batches':>8} "
                         f"{'rows_in':>8} {'state_rows':>10} "
                         f"{'state_bytes':>11} {'retired':>8} "
                         f"{'watermark':>10} {'compiles':>8}")
            for rec in commits:
                lines.append(
                    f"{rec.get('epoch'):>6} {rec.get('attempt'):>4} "
                    f"{len(rec.get('batch_ids') or []):>8} "
                    f"{rec.get('rows_in'):>8} {rec.get('state_rows'):>10} "
                    f"{rec.get('state_bytes'):>11} "
                    f"{rec.get('retired_rows'):>8} "
                    f"{str(rec.get('watermark')):>10} "
                    f"{str(rec.get('compiles', '?')):>8}")
    if analysis.get("events"):
        lines.append("-- stream events --")
        for ev in sorted(analysis["events"]):
            lines.append(f"  {ev}: {analysis['events'][ev]}")
    for v in analysis.get("violations", []):
        lines.append(f"JOURNAL VIOLATION: {v}")
    return "\n".join(lines)


def streaming_main(args) -> int:
    analysis = analyze_streaming(args.statedir, args.eventlog or ())
    rc = 1 if (analysis["violations"] or analysis["log_violations"]) else 0
    for v in analysis["log_violations"]:
        print(f"SCHEMA VIOLATION: {v}", file=sys.stderr)
    for v in analysis["violations"]:
        print(f"JOURNAL VIOLATION: {v}", file=sys.stderr)
    if args.json:
        print(json.dumps(analysis, indent=2, default=str))
    else:
        print(render_streaming(analysis))
    return rc


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="profiler.py", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    rep = sub.add_parser("report", help="analyze one event log")
    rep.add_argument("eventlog")
    rep.add_argument("--compare", metavar="OTHER",
                     help="second event log; print a diff of the two runs")
    rep.add_argument("--json", action="store_true",
                     help="machine-readable analysis instead of text")
    rep.add_argument("--top", type=int, default=15,
                     help="operator table rows per query")
    tr = sub.add_parser(
        "trace", help="merge span files into Chrome-trace JSON + critical "
                      "path (Perfetto)")
    tr.add_argument("logdir", help="directory holding spans-*.jsonl files "
                                   "(spark.rapids.tpu.trace.dir)")
    tr.add_argument("--query", default=None,
                    help="trace id to export (a query id is its own trace "
                         "id); default: the most recent trace")
    tr.add_argument("--out", default=None,
                    help="Chrome-trace JSON output path "
                         "(default <logdir>/trace.json)")
    tr.add_argument("--top", type=int, default=15,
                    help="critical-path chain segments to print")
    mm = sub.add_parser(
        "memory", help="heap-snapshot tables, watermark timeline and leak "
                       "detections from the memory observability plane")
    mm.add_argument("eventlog")
    mm.add_argument("--diff", metavar="OTHER",
                    help="second event log; print per-site deltas between "
                         "the two final heap snapshots")
    mm.add_argument("--json", action="store_true",
                    help="machine-readable analysis instead of text")
    mm.add_argument("--top", type=int, default=15,
                    help="sites / timeline samples per table")
    st = sub.add_parser(
        "stats", help="runtime statistics plane: footprint estimate error, "
                      "per-node dispatch/transfer ledger, shuffle skew")
    st.add_argument("eventlog")
    st.add_argument("--json", action="store_true",
                    help="machine-readable analysis instead of text")
    st.add_argument("--top", type=int, default=15,
                    help="node-ledger rows per query")
    mv = sub.add_parser(
        "movement", help="data-movement plane: source->dest byte matrix, "
                         "top flows, loopback-vs-remote split and per-query "
                         "movement amplification")
    mv.add_argument("eventlog", nargs="+",
                    help="one or more event logs (pass every per-process "
                         "events-*.jsonl of a cluster run to merge them)")
    mv.add_argument("--json", action="store_true",
                    help="machine-readable analysis instead of text")
    mv.add_argument("--top", type=int, default=15,
                    help="flow rows in the top-flows table")
    jn = sub.add_parser(
        "journey", help="cross-replica query journeys: merge replica event "
                        "logs into per-submission failover timelines")
    jn.add_argument("eventlog", nargs="+",
                    help="one or more replica event logs (pass every "
                         "replica's events-*.jsonl to merge the fleet)")
    jn.add_argument("--journey", default=None,
                    help="render only this journey id")
    jn.add_argument("--json", action="store_true",
                    help="machine-readable analysis instead of text")
    fl = sub.add_parser(
        "fleet", help="fleet roster: live lease records with embedded "
                      "health, departed tombstones, SLO breach table")
    fl.add_argument("fleetdir",
                    help="fleet membership directory "
                         "(spark.rapids.tpu.fleet.dir)")
    fl.add_argument("--json", action="store_true",
                    help="machine-readable analysis instead of text")
    sm = sub.add_parser(
        "streaming", help="continuous-ingestion plane: epoch journal "
                          "timeline (commits, attempts, watermark, state "
                          "size, compiles) validated against the journal "
                          "schema, plus stream.* event counts")
    sm.add_argument("statedir",
                    help="stream state directory holding epoch_journal.json "
                         "(the coordinator's state_dir, by default "
                         "<stream>/_state)")
    sm.add_argument("--eventlog", nargs="*", default=[],
                    help="replica event logs to count stream.* events from")
    sm.add_argument("--json", action="store_true",
                    help="machine-readable analysis instead of text")
    args = p.parse_args(argv)

    if args.cmd == "trace":
        return trace_main(args)
    if args.cmd == "memory":
        return memory_main(args)
    if args.cmd == "stats":
        return stats_main(args)
    if args.cmd == "movement":
        return movement_main(args)
    if args.cmd == "journey":
        return journey_main(args)
    if args.cmd == "fleet":
        return fleet_main(args)
    if args.cmd == "streaming":
        return streaming_main(args)

    records, violations = load_log(args.eventlog)
    analysis = analyze(records)
    rc = 0
    if violations:
        for v in violations:
            print(f"SCHEMA VIOLATION: {v}", file=sys.stderr)
        rc = 1
    if not any(q["operators"] for q in analysis["queries"]):
        print("ERROR: no query with a non-empty operator breakdown in "
              f"{args.eventlog}", file=sys.stderr)
        rc = 1

    if args.compare:
        other_records, other_violations = load_log(args.compare)
        if other_violations:
            for v in other_violations:
                print(f"SCHEMA VIOLATION: {v}", file=sys.stderr)
            rc = 1
        other = analyze(other_records)
        print(render_compare(analysis, other, args.eventlog, args.compare))
        return rc
    if args.json:
        analysis["violations"] = violations
        print(json.dumps(analysis, indent=2, default=str))
    else:
        print(render(analysis, top=args.top))
    return rc


if __name__ == "__main__":
    try:
        sys.exit(main())
    except BrokenPipeError:
        # downstream closed early (e.g. piped into head): not an error
        devnull = os.open(os.devnull, os.O_WRONLY)
        os.dup2(devnull, sys.stdout.fileno())
        sys.exit(0)
