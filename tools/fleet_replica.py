"""One serving-fleet replica as a standalone process.

Spawns a TpuSession + QueryEndpoint wired into a shared fleet directory
(runtime/fleet.py) and the shared warm-state stores (compiled-stage cache,
plan-history), prints ``READY <port>`` once the endpoint is listening, and
serves until SIGTERM (graceful drain) — or SIGKILL, which is the point: the
parent harness (tools/fleet_chaos.py, tests/test_fleet.py, bench.py
--replicas) kills replicas mid-stream to drive the failover/adoption
contracts.

Data catalog, one of:
  --data-dir DIR [--sf F]   TPC-H views from (pre-generated) parquet
  --synthetic N             one deterministic in-memory table 't'
                            (k=i%%50 int64, v=i float64, 2 partitions) —
                            identical in every replica, so results are
                            bit-identical across the fleet

Usage:
  python tools/fleet_replica.py --fleet-dir D --synthetic 200 \
      [--port 0] [--stage-cache-dir D] [--history-dir D] [--eventlog-dir D]
      [--lease-timeout 3] [--heartbeat 0.5] [--request-timeout 0]
      [--slo-target 0]
      [--max-concurrent 4] [--result-cache] [--faults SPEC [--faults-seed N]]
"""

from __future__ import annotations

import argparse
import pathlib
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="fleet_replica.py", description=__doc__)
    p.add_argument("--fleet-dir", required=True)
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--data-dir")
    p.add_argument("--sf", type=float, default=0.01)
    p.add_argument("--synthetic", type=int, default=0,
                   help="rows of the deterministic synthetic table 't'")
    p.add_argument("--stage-cache-dir")
    p.add_argument("--history-dir")
    p.add_argument("--eventlog-dir")
    p.add_argument("--lease-timeout", type=float, default=3.0)
    p.add_argument("--heartbeat", type=float, default=0.5)
    p.add_argument("--request-timeout", type=float, default=0.0)
    p.add_argument("--slo-target", type=float, default=0.0,
                   help="endpoint.slo.latencyTargetSeconds: latency SLO "
                        "accounted per served query (0 disables)")
    p.add_argument("--max-concurrent", type=int, default=4)
    p.add_argument("--result-cache", action="store_true")
    p.add_argument("--stream-source", action="append", default=[],
                   metavar="NAME:DIR",
                   help="register a streaming source (streaming/source.py) "
                        "over the shared batch-log DIR; repeatable. Clients "
                        "APPEND through any replica and query through any "
                        "other — the shared fleet catalog epoch keeps every "
                        "replica's result cache honest")
    p.add_argument("--faults", default=None,
                   help="chaos fault spec armed in THIS replica "
                        "(runtime/faults.py), e.g. slow:agg.update:8")
    p.add_argument("--faults-seed", type=int, default=3)
    p.add_argument("--drain-grace", type=float, default=30.0)
    args = p.parse_args(argv)
    if not args.data_dir and not args.synthetic:
        p.error("one of --data-dir / --synthetic is required")

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    from spark_rapids_tpu.runtime import eventlog
    from spark_rapids_tpu.session import TpuSession

    conf = {
        "spark.rapids.tpu.sql.format.parquet.reader.type": "COALESCING",
        "spark.rapids.tpu.pipeline.enabled": True,
        "spark.rapids.tpu.scheduler.maxConcurrent": args.max_concurrent,
        "spark.rapids.tpu.fleet.dir": args.fleet_dir,
        "spark.rapids.tpu.fleet.lease.timeoutSeconds": args.lease_timeout,
        "spark.rapids.tpu.fleet.heartbeat.intervalSeconds": args.heartbeat,
        "spark.rapids.tpu.endpoint.requestTimeoutSeconds":
            args.request_timeout,
        "spark.rapids.tpu.endpoint.slo.latencyTargetSeconds":
            args.slo_target,
        "spark.rapids.tpu.endpoint.drain.graceSeconds": args.drain_grace,
    }
    if args.stage_cache_dir:
        conf["spark.rapids.tpu.sql.stage.cache.enabled"] = True
        conf["spark.rapids.tpu.sql.stage.cache.dir"] = args.stage_cache_dir
    if args.history_dir:
        conf["spark.rapids.tpu.stats.history.dir"] = args.history_dir
    if args.eventlog_dir:
        conf["spark.rapids.tpu.eventLog.dir"] = args.eventlog_dir
    if args.result_cache:
        conf["spark.rapids.tpu.endpoint.resultCache.enabled"] = True
    spark = TpuSession(conf)

    if args.data_dir:
        from spark_rapids_tpu.benchmarks import tpch
        paths = tpch.generate(args.sf, args.data_dir)
        tpch.load(spark, paths, files_per_partition=4)
    else:
        import pyarrow as pa
        n = args.synthetic
        tbl = pa.table({"k": pa.array([i % 50 for i in range(n)],
                                      type=pa.int64()),
                        "v": pa.array([float(i) for i in range(n)],
                                      type=pa.float64())})
        spark.create_or_replace_temp_view(
            "t", spark.create_dataframe(tbl, num_partitions=2))

    for spec in args.stream_source:
        name, _, sdir = spec.partition(":")
        if not sdir:
            p.error(f"--stream-source wants NAME:DIR, got {spec!r}")
        spark.create_stream_source(name, sdir)

    if args.faults:
        from spark_rapids_tpu.runtime import faults
        faults.configure(args.faults, seed=args.faults_seed)

    ep = spark.serve(host=args.host, port=args.port)
    ep.install_signal_handlers(grace_s=args.drain_grace)
    print(f"READY {ep.port}", flush=True)
    # serve until the SIGTERM drain closes the listener (SIGKILL never
    # reaches this loop — that replica's lease expires and a peer adopts it)
    while ep._thread.is_alive():
        time.sleep(0.1)
    eventlog.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
