"""Remote SQL client for the Arrow-over-TCP query endpoint.

The CLI front for spark_rapids_tpu.runtime.endpoint.EndpointClient: submit
one SQL statement to a running QueryEndpoint (see TpuSession.serve()),
stream the Arrow result back, and honor the serving contract — a retryable
QueryRejectedError (overload shed / graceful drain) is retried after its
server-supplied ``backoff_hint_s``; non-retryable typed errors exit with
the error class named.

Usage:
  python tools/tpu_client.py --port 8765 --sql "select count(*) c from t"
  python tools/tpu_client.py --port 8765 --sql-file q.sql --priority 5 \
      --deadline 30 --retries 8 --quiet
  python tools/tpu_client.py --port 8765 stats      # live serving metrics
  python tools/tpu_client.py \
      --addresses 127.0.0.1:8765,127.0.0.1:8766 --sql "..."   # replica fleet

``--addresses`` names a replica fleet (comma-separated host:port list):
any retryable failure — connection refused, a replica dying mid-stream, a
shed/drain/replica_timeout rejection — rotates to the next replica with
jitter before retrying, so failover needs nothing beyond listing the
replicas.

``stats`` (or --stats) fetches the endpoint's live serving-metrics snapshot
— a Prometheus-style text exposition of admission/shed/cancel/deadline
counters, the resilience registry, HBM/spill/queue gauges and per-priority
latency histograms — without submitting a query. With ``--addresses`` it
fans out across the WHOLE replica list (one section per replica), never
just the first reachable one.

``fleet-stats`` merges every replica's snapshot into the fleet rollup:
per-replica sections plus the fleet-aggregate counter families, where
every aggregate counter equals the sum of the per-replica values.

``append`` ships one streaming-source batch (a parquet file, read locally)
as a CRC-stamped Arrow-IPC APPEND frame::

  python tools/tpu_client.py --port 8765 append --source clicks \
      --batch b-0042 --file clicks.parquet

The ack is a durability receipt (the server persisted the batch before
replying). Retries ride the same fleet rotation as SQL submissions and are
always safe: APPEND is idempotent by (source, batch id) — a replica that
died after persisting but before acking turns the retry into a
``duplicate`` ack.

Exit codes: 0 ok, 2 rejected/unreachable after all retries, 3 query error.
For stats/fleet-stats, 2 means NO replica was reachable — partial fleets
still report with the dead replicas marked UNREACHABLE.
"""

from __future__ import annotations

import argparse
import pathlib
import sys


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="tpu_client.py", description=__doc__)
    p.add_argument("command", nargs="?",
                   choices=["stats", "fleet-stats", "append"],
                   help="'stats' fetches every replica's live "
                        "serving-metrics snapshot; 'fleet-stats' merges "
                        "them with fleet-aggregate counter families; "
                        "'append' ships one streaming-source batch")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int)
    p.add_argument("--addresses", default=None,
                   help="comma-separated replica list host:port,host:port "
                        "(replaces --host/--port; retryable failures rotate "
                        "to the next replica)")
    p.add_argument("--sql", help="SQL text (or use --sql-file / stdin '-')")
    p.add_argument("--sql-file", help="read the SQL text from this file")
    p.add_argument("--stats", action="store_true",
                   help="fetch the live serving-metrics snapshot (alias of "
                        "the 'stats' command)")
    p.add_argument("--trace", default=None,
                   help="distributed trace id attached to this submission "
                        "(server-side spans merge into it)")
    p.add_argument("--priority", type=int, default=None,
                   help="admission priority (scheduler.priority)")
    p.add_argument("--deadline", type=float, default=None,
                   help="per-query deadline seconds (queue wait included)")
    p.add_argument("--queue-timeout", type=float, default=None,
                   help="seconds to wait for admission before the server "
                        "sheds this submission")
    p.add_argument("--retries", type=int, default=5,
                   help="max attempts across shed/transport retries")
    p.add_argument("--timeout", type=float, default=120.0,
                   help="socket timeout seconds (per frame gap)")
    p.add_argument("--quiet", action="store_true",
                   help="print only the summary line, not the rows")
    p.add_argument("--source", help="append: target stream source name")
    p.add_argument("--batch", help="append: batch id (the idempotence key; "
                                   "re-sending the same id is always safe)")
    p.add_argument("--file", help="append: local parquet file to ship")
    args = p.parse_args(argv)

    if not args.addresses and args.port is None:
        p.error("one of --port / --addresses is required")
    stats_mode = args.stats or args.command == "stats"
    fleet_stats_mode = args.command == "fleet-stats"
    append_mode = args.command == "append"
    if append_mode and not (args.source and args.batch and args.file):
        p.error("append requires --source, --batch and --file")
    sql = args.sql
    if sql is None and args.sql_file:
        sql = (sys.stdin.read() if args.sql_file == "-"
               else pathlib.Path(args.sql_file).read_text())
    if not sql and not stats_mode and not fleet_stats_mode and \
            not append_mode:
        p.error("one of --sql / --sql-file / stats / fleet-stats / append "
                "is required")

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from spark_rapids_tpu.runtime.endpoint import (EndpointClient,
                                                   render_fleet_stats)
    from spark_rapids_tpu.runtime.scheduler import (QueryCancelledError,
                                                    QueryRejectedError)
    from spark_rapids_tpu.shuffle.transport import TransportError

    address = args.addresses or (args.host, args.port)
    cli = EndpointClient(address, timeout_s=args.timeout)

    if fleet_stats_mode:
        fs = cli.fleet_stats()
        print(render_fleet_stats(fs), end="")
        return 0 if fs["live"] else 2

    if stats_mode:
        # fan out across the WHOLE replica list: one replica's death (or the
        # client happening to target it) must not hide the others' metrics
        reachable, failed = 0, []
        for addr, text in cli.stats_all().items():
            if len(cli.addresses) > 1:
                print(f"== replica {addr} ==")
            if isinstance(text, BaseException):
                failed.append((addr, text))
                print(f"UNREACHABLE {type(text).__name__}: {text}")
            else:
                print(text, end="")
                reachable += 1
        if not reachable:
            for addr, e in failed:
                print(f"{addr}: {type(e).__name__}: {e}", file=sys.stderr)
            return 2
        return 0

    def on_retry(attempt, delay):
        target = f" via {cli.address[0]}:{cli.address[1]}" \
            if len(cli.addresses) > 1 else ""
        print(f"retry {attempt}/{args.retries} in {delay:.2f}s "
              f"(server backoff hint honored){target}", file=sys.stderr)

    if append_mode:
        import pyarrow.parquet as pq
        try:
            ack = cli.append_with_retry(
                args.source, args.batch, pq.read_table(args.file),
                max_attempts=max(1, args.retries), on_retry=on_retry)
        except (QueryRejectedError, TransportError) as e:
            print(f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
        except Exception as e:  # noqa: BLE001 — server-marshalled typed error
            print(f"{type(e).__name__}: {e}", file=sys.stderr)
            return 3
        dup = " duplicate" if ack.get("duplicate") else ""
        print(f"OK append source={ack.get('source')} "
              f"batch={ack.get('batch')} rows={ack.get('rows')} "
              f"epoch={ack.get('epoch')} replica={ack.get('replica')}{dup}",
              file=sys.stderr)
        return 0

    try:
        table = cli.submit_with_retry(
            sql, max_attempts=max(1, args.retries), on_retry=on_retry,
            priority=args.priority, deadline_s=args.deadline,
            queue_timeout_s=args.queue_timeout, trace=args.trace,
            description="tpu_client")
    except (QueryRejectedError, TransportError) as e:
        print(f"{type(e).__name__}: {e}", file=sys.stderr)
        return 2
    except QueryCancelledError as e:
        print(f"{type(e).__name__} ({e.reason}): {e}", file=sys.stderr)
        return 3
    except Exception as e:   # noqa: BLE001 — server-marshalled typed error
        print(f"{type(e).__name__}: {e}", file=sys.stderr)
        return 3

    if not args.quiet:
        for row in table.to_pylist():
            print(row)
    s = cli.last_summary or {}
    print(f"OK query={s.get('query')} rows={table.num_rows} "
          f"batches={s.get('batches')} wall_s={s.get('wall_s')}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
