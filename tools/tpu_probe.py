"""Probe the accelerator backend and append a dated line to docs/perf_notes.md.

Round-4 protocol (VERDICT.md r3, next-round item 1): probe FIRST, probe often,
log every attempt with a timestamp so a wedged tunnel is documented evidence
rather than a round-end surprise. The probe runs a trivial add in a SHORT
subprocess (a wedged tunnel hangs even `jnp.ones((8,)).sum()` — killing the
subprocess before any real dispatch is safe; killing a real dispatch is what
wedges the chip in the first place).

Usage: python tools/tpu_probe.py [--note TEXT] [--timeout SECONDS]
Exit code 0 = backend usable, 1 = unavailable (logged either way).
"""

import argparse
import datetime
import os
import pathlib
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
LOG = REPO / "docs" / "perf_notes.md"
MARKER = "## Round-4 TPU probe log"

PROBE_CODE = (
    "import jax; d = jax.devices(); "
    "import jax.numpy as jnp; "
    "x = jnp.ones((8,)) + 1; x.block_until_ready(); "
    "import numpy as np; "
    "print('PROBE_OK', float(np.asarray(x).sum()), d[0].platform, "
    "getattr(d[0], 'device_kind', '?'))"
)


def probe(timeout_s: float):
    """Returns (ok, detail). Never raises."""
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PROBE_CODE], env=dict(os.environ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=timeout_s)
        out = (proc.stdout or "").strip()
        elapsed = time.time() - t0
        if proc.returncode == 0 and "PROBE_OK" in out:
            line = [l for l in out.splitlines() if "PROBE_OK" in l][-1]
            return True, f"{line} ({elapsed:.1f}s)"
        return False, f"rc={proc.returncode} ({elapsed:.1f}s): {out[-200:]}"
    except subprocess.TimeoutExpired:
        return False, f"timeout after {timeout_s:.0f}s (tunnel wedged)"


def log_result(ok: bool, detail: str, note: str = ""):
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M UTC")
    status = "OK" if ok else "UNAVAILABLE"
    entry = f"- `{stamp}` **{status}** — {detail}"
    if note:
        entry += f" _({note})_"
    text = LOG.read_text() if LOG.exists() else "# Perf notes\n"
    if MARKER not in text:
        text = text.rstrip() + f"\n\n{MARKER}\n\n"
    text = text.rstrip() + "\n" + entry + "\n"
    LOG.write_text(text)
    print(entry)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--note", default="")
    ap.add_argument("--timeout", type=float, default=240.0)
    args = ap.parse_args()
    ok, detail = probe(args.timeout)
    log_result(ok, detail, args.note)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
