"""Probe the accelerator backend and append a dated line to docs/perf_notes.md.

Round-4 protocol (VERDICT.md r3, next-round item 1): probe FIRST, probe often,
log every attempt with a timestamp so a wedged tunnel is documented evidence
rather than a round-end surprise. The probe runs a trivial add in a SHORT
subprocess (a wedged tunnel hangs even `jnp.ones((8,)).sum()` — killing the
subprocess before any real dispatch is safe; killing a real dispatch is what
wedges the chip in the first place).

Usage: python tools/tpu_probe.py [--note TEXT] [--timeout SECONDS]
Exit code 0 = backend usable, 1 = unavailable (logged either way).
"""

import argparse
import datetime
import os
import pathlib
import re
import subprocess
import sys
import time

REPO = pathlib.Path(__file__).resolve().parent.parent
LOG = REPO / "docs" / "perf_notes.md"
MARKER = "## Round-5 TPU probe log"

# Matches both a single UNAVAILABLE entry and a collapsed run
# (`first` → `last` **UNAVAILABLE ×N**). Used to fold consecutive
# identical failures into one line (VERDICT r4 weak #8: bounded log).
_UNAVAIL_RE = re.compile(
    r"^- `(?P<first>[0-9: -]+UTC)`(?: → `(?P<last>[0-9: -]+UTC)`)?"
    r" \*\*UNAVAILABLE(?: ×(?P<n>\d+))?\*\* — (?P<detail>.*?)"
    r"(?: _\((?P<note>.*)\)_)?$")

PROBE_CODE = (
    "import jax; d = jax.devices(); "
    "import jax.numpy as jnp; "
    "x = jnp.ones((8,)) + 1; x.block_until_ready(); "
    "import numpy as np; "
    "print('PROBE_OK', float(np.asarray(x).sum()), d[0].platform, "
    "getattr(d[0], 'device_kind', '?'))"
)


def probe(timeout_s: float):
    """Returns (ok, detail). Never raises."""
    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c", PROBE_CODE], env=dict(os.environ),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            timeout=timeout_s)
        out = (proc.stdout or "").strip()
        elapsed = time.time() - t0
        if proc.returncode == 0 and "PROBE_OK" in out:
            line = [l for l in out.splitlines() if "PROBE_OK" in l][-1]
            return True, f"{line} ({elapsed:.1f}s)"
        return False, f"rc={proc.returncode} ({elapsed:.1f}s): {out[-200:]}"
    except subprocess.TimeoutExpired:
        return False, f"timeout after {timeout_s:.0f}s (tunnel wedged)"


def log_result(ok: bool, detail: str, note: str = ""):
    # watcher + manual probes can overlap: serialize the read-modify-write
    import fcntl
    lockf = open(LOG.parent / ".probe_log.lock", "w")
    fcntl.flock(lockf, fcntl.LOCK_EX)
    try:
        _log_result_locked(ok, detail, note)
    finally:
        fcntl.flock(lockf, fcntl.LOCK_UN)
        lockf.close()


def _log_result_locked(ok: bool, detail: str, note: str):
    stamp = datetime.datetime.now(datetime.timezone.utc).strftime(
        "%Y-%m-%d %H:%M UTC")
    status = "OK" if ok else "UNAVAILABLE"
    entry = f"- `{stamp}` **{status}** — {detail}"
    if note:
        entry += f" _({note})_"
    text = LOG.read_text() if LOG.exists() else "# Perf notes\n"
    if MARKER not in text:
        text = text.rstrip() + f"\n\n{MARKER}\n\n"
    text = text.rstrip()
    # Bounded log: any run of consecutive UNAVAILABLE entries collapses into
    # one `first → last ×N` line instead of appending forever. The run keeps
    # the FIRST failure's detail; a differing latest detail is noted once.
    lines = text.splitlines()
    if not ok and lines and MARKER in text[:text.rfind(lines[-1])]:
        m = _UNAVAIL_RE.match(lines[-1])
        if m:
            first = m.group("first")
            n = int(m.group("n") or 1) + 1
            base = re.sub(r" \(latest: .*\)$", "", m.group("detail") or "")
            d = base if base == detail else f"{base} (latest: {detail})"
            entry = f"- `{first}` → `{stamp}` **UNAVAILABLE ×{n}** — {d}"
            if note:
                entry += f" _(latest: {note})_"
            text = "\n".join(lines[:-1])
    text = text + "\n" + entry + "\n"
    LOG.write_text(text)
    print(entry)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--note", default="")
    ap.add_argument("--timeout", type=float, default=240.0)
    args = ap.parse_args()
    ok, detail = probe(args.timeout)
    log_result(ok, detail, args.note)
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
