"""TPU-numeric-regime correctness subset (VERDICT r3 item 2).

Runs a marked subset of the equivalence suite ON THE ACCELERATOR — cast edge
cases, Spark murmur3 hashing, float64 aggregation, join keys with
NaN/subnormals — and records the MEASURED float64-emulation divergence
(the real chip emulates f64 as f32 pairs, ~49-bit mantissa; see
docs/compatibility.md) instead of predictions.

Protocol (tunnel-wedge safe, docs/perf_notes.md):
- probe first with a short-timeout subprocess; never dispatch if it hangs;
- tiny shapes only (batch cap <= 2048) — nothing here can run away;
- the whole subset runs in ONE child process with a generous budget and is
  never killed mid-dispatch (the parent waits without a timeout once the
  probe has passed).

Usage: python tools/tpu_correctness.py [--out TPU_CORRECTNESS.json]
Exit 0 and writes the artifact on success; exit 1 if the backend is
unavailable (logged to the probe log either way).
"""

import argparse
import json
import os
import pathlib
import subprocess
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent


def child_main():
    import numpy as np
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the axon site hook re-selects TPU regardless of env; override it
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import pyarrow as pa
    import spark_rapids_tpu  # noqa: F401  (x64)
    from spark_rapids_tpu.session import TpuSession
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu import types as T

    dev = jax.devices()[0]
    results = {"platform": dev.platform,
               "device_kind": getattr(dev, "device_kind", "?"),
               "checks": {}}

    def record(name, ok, detail=""):
        results["checks"][name] = {"ok": bool(ok), "detail": str(detail)[:300]}
        print(f"  {'OK ' if ok else 'FAIL'} {name}: {detail}")

    spark = TpuSession()

    # 1. int64 arithmetic is exact on TPU (ints are not emulated)
    t = pa.table({"x": pa.array([2**53 + 1, -2**53 - 1, 2**62, -(2**62)],
                                pa.int64())})
    got = spark.create_dataframe(t).select(
        (F.col("x") + 1).alias("y")).collect().column("y").to_pylist()
    exp = [2**53 + 2, -2**53, 2**62 + 1, -(2**62) + 1]
    record("int64_exact", got == exp, f"{got} vs {exp}")

    # 2. Spark murmur3 hash — bit-exact integers end-to-end
    t = pa.table({"k": pa.array([0, 1, -1, 2**31 - 1, None], pa.int32()),
                  "s": pa.array(["", "a", "spark", "é中", None])})
    df = spark.create_dataframe(t).select(
        F.hash(F.col("k")).alias("hk"), F.hash(F.col("s")).alias("hs"))
    got = df.collect()
    exp = df.collect_host()
    record("murmur3_bit_exact", got.equals(exp),
           f"{got.to_pylist()} vs {exp.to_pylist()}")

    # 3. cast edge cases: float->int truncation + JVM saturation + NaN->0
    t = pa.table({"f": pa.array([1.9, -1.9, 3e19, -3e19, float("nan")])})
    got = spark.create_dataframe(t).select(
        F.cast(F.col("f"), T.LONG).alias("i")).collect().column(
        "i").to_pylist()
    exp = [1, -1, 9223372036854775807, -9223372036854775808, 0]
    record("cast_double_to_long_edges", got == exp, f"{got} vs {exp}")

    # 4. float64 aggregation divergence (the emulated-f64 measurement)
    rng = np.random.default_rng(7)
    vals = rng.uniform(-1e6, 1e6, 1500)
    t = pa.table({"g": pa.array((np.arange(1500) % 7).astype(np.int64)),
                  "v": pa.array(vals)})
    df = (spark.create_dataframe(t).group_by(F.col("g"))
          .agg(F.sum(F.col("v")).alias("s"), F.avg(F.col("v")).alias("a")))
    got = {r["g"]: (r["s"], r["a"]) for r in df.collect().to_pylist()}
    host = {}
    for g in range(7):
        sel = vals[np.arange(1500) % 7 == g]
        host[g] = (sel.sum(), sel.mean())
    max_ulps = 0.0
    for g in range(7):
        for a, b in zip(got[g], host[g]):
            ulp = abs(a - b) / max(np.spacing(abs(b)), 5e-324)
            max_ulps = max(max_ulps, ulp)
    # f64-emulation (~49-bit mantissa) can diverge ~2^4 ulps on summation
    results["f64_sum_max_ulps_vs_host"] = max_ulps
    record("f64_aggregation_divergence", max_ulps < 1e6,
           f"max {max_ulps:.1f} ulps vs host numpy")

    # 5. join keys with NaN / subnormal / -0.0 (Spark: NaN==NaN, -0.0==0.0;
    #    subnormals flush to zero on TPU — measure whether they still match)
    sub = 5e-324
    lt = pa.table({"k": pa.array([float("nan"), -0.0, sub, 1.0]),
                   "lv": pa.array([0, 1, 2, 3], pa.int32())})
    rt = pa.table({"k2": pa.array([float("nan"), 0.0, sub]),
                   "rv": pa.array([10, 11, 12], pa.int32())})
    from spark_rapids_tpu.plan import nodes as NN
    from spark_rapids_tpu.expr import core as EE
    from spark_rapids_tpu.session import DataFrame
    jn = NN.JoinNode(spark.create_dataframe(lt)._plan,
                     spark.create_dataframe(rt)._plan,
                     [EE.col("k")], [EE.col("k2")], "inner", None)
    got = sorted((r["lv"], r["rv"])
                 for r in DataFrame(jn, spark).collect().to_pylist())
    # hard Spark semantics: NaN==NaN and -0.0==0.0 match; 1.0 matches nothing
    core_ok = ((0, 10) in got and (1, 11) in got
               and not any(lv == 3 for lv, _ in got))
    # subnormal handling is a MEASUREMENT (the device join key path may
    # quantize 5e-324 to 0.0; on TPU subnormals flush in hardware)
    sub_matches_zero = (2, 11) in got
    results["join_subnormal_matches_zero"] = sub_matches_zero
    record("join_nan_negzero_core", core_ok,
           f"{got} (subnormal==0.0: {sub_matches_zero})")

    # 6. TPC-DS q3 end-to-end tiny on the accelerator vs host oracle
    from spark_rapids_tpu.benchmarks import tpcds
    paths = tpcds.generate(0.003, "/tmp/tpcds_tpu_sf0.003")
    dfs = tpcds.load(spark, paths)
    tb = tpcds.load_np(paths)
    got = [tuple(r.values()) for r in tpcds.QUERIES["q3"](dfs)
           .collect().to_pylist()]
    exp = [tuple(r) for r in tpcds.NP_QUERIES["q3"](tb)]
    try:
        tpcds.check_rows(got, exp, tpcds.FLOAT_COLS["q3"], rel=1e-6)
        record("tpcds_q3_end_to_end", True, f"{len(got)} rows, rel 1e-6")
    except AssertionError as e:
        record("tpcds_q3_end_to_end", False, e)

    # 7. round-5 SQL surfaces, tiny + bounded: set operations (null-safe
    #    semi/anti + row_number ALL forms), the general multi-DISTINCT
    #    Expand rewrite, grouping sets, and exact decimal multiply/divide —
    #    each device result vs the host interpreter
    t = pa.table({"x": pa.array([1, 1, 2, 3, None, None], pa.int64()),
                  "y": pa.array(["a", "a", "b", "c", "d", None])})
    spark.create_or_replace_temp_view("r5a", spark.create_dataframe(t))
    t2 = pa.table({"x": pa.array([1, 2, 2, None, 5], pa.int64()),
                   "y": pa.array(["a", "b", "b", None, "e"])})
    spark.create_or_replace_temp_view("r5b", spark.create_dataframe(t2))
    r5 = [
        "select x, y from r5a intersect select x, y from r5b",
        "select x, y from r5a except select x, y from r5b",
        "select x, y from r5a intersect all select x, y from r5b",
        "select x, y from r5a except all select x, y from r5b",
        "select count(distinct x) cx, count(distinct y) cy, sum(x) s "
        "from r5a",
        "select y, count(distinct x) c from r5a group by rollup (y)",
        "select cast(1 as decimal(5,2)) / cast(3 as decimal(5,2)) v, "
        "cast(1.5 as decimal(5,2)) * cast(2.5 as decimal(5,2)) w",
    ]
    ok_all, detail = True, []
    for q in r5:
        # per-statement try: one device-side failure must record a FAIL,
        # not abort the child and lose checks 1-6's measurements
        try:
            df = spark.sql(q)
            g = sorted((tuple(r.values())
                        for r in df.collect().to_pylist()), key=repr)
            e = sorted((tuple(r.values())
                        for r in df.collect_host().to_pylist()), key=repr)
            if g != e:
                ok_all = False
                detail.append(f"{q[:40]}: {g} vs {e}")
        except Exception as exc:  # noqa: BLE001
            ok_all = False
            detail.append(f"{q[:40]}: {exc!r:.120}")
    record("r5_setops_distinct_decimal", ok_all,
           "; ".join(detail) if detail else f"{len(r5)} statements match")

    results["ok"] = all(c["ok"] for c in results["checks"].values())
    print(json.dumps(results))
    return results


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(REPO / "TPU_CORRECTNESS.json"))
    ap.add_argument("--probe-timeout", type=float, default=120.0)
    ap.add_argument("--dryrun-cpu", action="store_true",
                    help="CI gate (VERDICT r4 next #1a): run the EXACT "
                         "parent->child subprocess path on the CPU platform, "
                         "skipping the probe, so an import/PYTHONPATH/API "
                         "regression can never meet the chip first")
    args = ap.parse_args()
    sys.path.insert(0, str(REPO / "tools"))
    from tpu_probe import probe, log_result
    if args.dryrun_cpu:
        log_result = lambda *a, **k: None  # noqa: E731 — no probe-log noise
    else:
        ok, detail = probe(args.probe_timeout)
        log_result(ok, detail, "correctness-subset probe")
        if not ok:
            sys.exit(1)
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    if args.dryrun_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--child"],
        env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True)
    out = proc.stdout or ""
    print(out[-3000:])
    for ln in reversed(out.splitlines()):
        if ln.startswith("{"):
            results = json.loads(ln)
            pathlib.Path(args.out).write_text(json.dumps(results, indent=1))
            log_result(results["ok"],
                       f"correctness subset platform={results['platform']} "
                       f"{sum(c['ok'] for c in results['checks'].values())}"
                       f"/{len(results['checks'])} checks ok",
                       "device-ring subset")
            sys.exit(0 if results["ok"] else 1)
    log_result(False, f"child rc={proc.returncode}: {out[-200:]}",
               "correctness subset crashed")
    sys.exit(1)


if __name__ == "__main__":
    if "--child" in sys.argv:
        # the parent spawns this script by PATH, so the child's sys.path[0]
        # is tools/ — the package under REPO is not importable without this
        sys.path.insert(0, str(REPO))
        child_main()
    else:
        main()
