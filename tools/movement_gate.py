"""movement_gate — CI gate for the data-movement observability plane.

Runs one TPC-H query on an N-executor MiniCluster with the event log on
and asserts the movement-ledger contract (runtime/movement.py) end to end:

  - coverage: the shuffle.recv payload bytes summed across every process's
    LAST movement.sample cover >=90% (and <=115%) of the map-output bytes
    the driver registered (the stage.map.end partition-size records) — the
    ledger sees what the block store served;
  - link honesty: a same-host MiniCluster moves ZERO cross-host ``tcp``
    bytes — every transport byte classifies ``loopback`` and every
    short-circuited local-store fetch ``local``, so the cross-host ledger
    can never be inflated by loopback traffic (the misattribution
    regression this plane fixes);
  - no-faults cleanliness: the shuffle.retry edge is exactly zero;
  - single-process invariant: after a ledger reset, a no-shuffle local
    query records exactly zero bytes on every network-capable edge
    (movement.NETWORK_EDGES) while still metering its h2d/d2h traffic.

Must be a real script file, not a ``python -`` heredoc: the spawn-based
executor bootstrap re-imports __main__, and stdin cannot be re-imported.

Usage:
  python tools/movement_gate.py --data-dir /tmp/tpch_sf0.01 \
      --eventlog-dir DIR [--query q18] [--scale 0.01] [--executors 3]
"""

from __future__ import annotations

import argparse
import glob
import json
import pathlib
import sys


def _last_samples(eventlog_dir: str) -> tuple[dict, int]:
    """(last movement.sample per pid, driver-registered map-output bytes)
    parsed from every per-process event file in the directory."""
    samples: dict = {}
    registered = 0
    for path in glob.glob(eventlog_dir + "/events-*.jsonl"):
        with open(path, encoding="utf-8") as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                rec = json.loads(ln)
                if rec.get("event") == "movement.sample":
                    samples[rec.get("pid")] = rec
                elif rec.get("event") == "stage.map.end" \
                        and rec.get("partition_sizes"):
                    registered += sum(rec["partition_sizes"])
    return samples, registered


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="movement_gate.py", description=__doc__)
    p.add_argument("--data-dir", required=True)
    p.add_argument("--eventlog-dir", required=True)
    p.add_argument("--query", default="q18")
    p.add_argument("--scale", type=float, default=0.01)
    p.add_argument("--executors", type=int, default=3)
    args = p.parse_args(argv)

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pyarrow as pa
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.cluster import MiniCluster
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.runtime import eventlog
    from spark_rapids_tpu.runtime import movement as MV
    from spark_rapids_tpu.session import TpuSession

    paths = tpch.generate(args.scale, args.data_dir)
    settings = {
        "spark.rapids.tpu.eventLog.dir": args.eventlog_dir,
        # small interval: mid-task threshold emissions exercised too, not
        # only the forced end-of-task flushes
        "spark.rapids.tpu.movement.sample.intervalBytes": "64k"}
    spark = TpuSession(settings)
    dfs = tpch.load(spark, paths, files_per_partition=4)
    df = tpch.QUERIES[args.query](dfs)

    # the executors need the settings too (their bootstrap configures the
    # event log + ledger from the cluster conf, not the driver session)
    with MiniCluster(n_executors=args.executors, conf=RapidsConf(settings),
                     platform="cpu") as c:
        c.collect(df)

    # single-process invariant, same driver process: a no-shuffle local
    # query must leave every network-capable edge at exactly zero while
    # its host<->device traffic is still metered
    MV.reset()
    local = spark.create_dataframe(pa.table({
        "k": list(range(100)), "v": [float(i) for i in range(100)]}))
    local.filter(F.col("k") < F.lit(50)).select("k", "v").collect()
    snap = MV.snapshot()
    net = {k: v for k, v in snap.items() if k[0] in MV.NETWORK_EDGES
           and (v["bytes"] or v["payload_bytes"])}
    assert not net, f"no-shuffle local query touched network edges: {net}"
    pcie = sum(v["bytes"] for k, v in snap.items() if k[0] in ("h2d", "d2h"))
    assert pcie > 0, f"local query metered no h2d/d2h traffic: {snap}"

    eventlog.shutdown()

    samples, registered = _last_samples(args.eventlog_dir)
    assert registered > 0, "driver log carries no stage.map.end sizes"
    assert len(samples) >= 2, \
        f"expected driver + executor movement samples, got {sorted(samples)}"
    by_edge_link: dict = {}
    for rec in samples.values():
        for f in rec.get("flows") or []:
            k = (f["edge"], f["link"])
            c = by_edge_link.setdefault(
                k, {"bytes": 0, "payload_bytes": 0})
            c["bytes"] += f["bytes"]
            c["payload_bytes"] += f["payload_bytes"]

    recv = sum(c["payload_bytes"] for (e, _lk), c in by_edge_link.items()
               if e == "shuffle.recv")
    cov = recv / registered
    assert 0.90 <= cov <= 1.15, \
        (f"shuffle.recv payload {recv}B vs registered {registered}B "
         f"({cov:.2f}x) outside [0.90, 1.15]")
    tcp = sum(c["bytes"] for (_e, lk), c in by_edge_link.items()
              if lk == "tcp")
    loop = sum(c["bytes"] for (_e, lk), c in by_edge_link.items()
               if lk == "loopback")
    assert tcp == 0, \
        f"same-host cluster inflated the cross-host ledger: tcp={tcp}B"
    assert loop > 0, f"no loopback transport bytes metered: {by_edge_link}"
    retry = sum(c["bytes"] + c["payload_bytes"]
                for (e, _lk), c in by_edge_link.items()
                if e == "shuffle.retry")
    assert retry == 0, f"no-faults run left retry-edge bytes: {retry}"

    print(f"movement gate ok [{args.query}, {args.executors} executors]: "
          f"recv payload {recv}B covers {cov:.2f}x of {registered}B "
          f"registered, tcp=0B loopback={loop}B, retry=0, "
          f"{len(samples)} process ledgers, local no-shuffle edges clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
