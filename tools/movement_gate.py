"""movement_gate — CI gate for the data-movement observability plane.

Runs one TPC-H query on an N-executor MiniCluster with the event log on
and asserts the movement-ledger contract (runtime/movement.py) end to end:

  - coverage: the shuffle.recv payload bytes summed across every process's
    LAST movement.sample cover >=90% (and <=115%) of the map-output bytes
    the driver registered (the stage.map.end partition-size records) — the
    ledger sees what the block store served;
  - link honesty: a same-host MiniCluster moves ZERO cross-host ``tcp``
    bytes — every transport byte classifies ``loopback`` and every
    short-circuited local-store fetch ``local``, so the cross-host ledger
    can never be inflated by loopback traffic (the misattribution
    regression this plane fixes);
  - no-faults cleanliness: the shuffle.retry edge is exactly zero;
  - single-process invariant: after a ledger reset, a no-shuffle local
    query records exactly zero bytes on every network-capable edge
    (movement.NETWORK_EDGES) while still metering its h2d/d2h traffic.

Two more gate modes ride the same script:

  - ``--two-level-compare``: runs the mesh-cluster q18 twice in child
    processes (twoLevel off, then on — separate processes so neither
    ledger/eventlog state bleeds) and asserts the two-level exchange
    contract: loopback/TCP shuffle payload bytes drop >=2x, the delta
    appears on the ``ici.collective`` edge, results bit-identical;
  - ``--ooc-smoke``: one out-of-core completion run (hbm.limitBytes
    shrunk below the working set) of the two-level plane on >=2
    executors — completes, spills to the host/disk tiers, bit-stable
    digest printed.

Must be a real script file, not a ``python -`` heredoc: the spawn-based
executor bootstrap re-imports __main__, and stdin cannot be re-imported.

Usage:
  python tools/movement_gate.py --data-dir /tmp/tpch_sf0.01 \
      --eventlog-dir DIR [--query q18] [--scale 0.01] [--executors 3]
  python tools/movement_gate.py --data-dir D --eventlog-dir DIR \
      --two-level-compare [--executors 2]
  python tools/movement_gate.py --data-dir D --eventlog-dir DIR \
      --ooc-smoke [--scale 1.0] [--ooc-limit 256m]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import pathlib
import subprocess
import sys


def _last_samples(eventlog_dir: str) -> tuple[dict, int]:
    """(last movement.sample per pid, driver-registered map-output bytes)
    parsed from every per-process event file in the directory."""
    samples: dict = {}
    registered = 0
    for path in glob.glob(eventlog_dir + "/events-*.jsonl"):
        with open(path, encoding="utf-8") as f:
            for ln in f:
                ln = ln.strip()
                if not ln:
                    continue
                rec = json.loads(ln)
                if rec.get("event") == "movement.sample":
                    samples[rec.get("pid")] = rec
                elif rec.get("event") == "stage.map.end" \
                        and rec.get("partition_sizes"):
                    registered += sum(rec["partition_sizes"])
    return samples, registered


def _flows(samples: dict) -> dict:
    """(edge, link) -> {bytes, payload_bytes} summed over process ledgers."""
    out: dict = {}
    for rec in samples.values():
        for f in rec.get("flows") or []:
            c = out.setdefault((f["edge"], f["link"]),
                               {"bytes": 0, "payload_bytes": 0})
            c["bytes"] += f["bytes"]
            c["payload_bytes"] += f["payload_bytes"]
    return out


def _load_multisplit(spark, paths):
    """Load each table as an explicit sorted file list (one file per
    split): directory loads collapse to a single FilePartition, leaving
    nothing for a mesh task group to exchange."""
    dfs = {}
    for name, p in paths.items():
        if os.path.isdir(p):
            fs = sorted(os.path.join(p, f) for f in os.listdir(p)
                        if f.endswith(".parquet"))
            dfs[name] = spark.read_parquet(fs, files_per_partition=1)
        else:
            dfs[name] = spark.read_parquet(p)
        spark.create_or_replace_temp_view(name, dfs[name])
    return dfs


def _mesh_run(args, two_level: bool, extra: dict | None = None) -> int:
    """Child-process body: one mesh-cluster run of the query with the
    two-level exchange on/off; digest + mesh stats land in
    <eventlog-dir>/result.json for the comparing parent."""
    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import hashlib
    import pyarrow as pa
    import spark_rapids_tpu  # noqa: F401
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.cluster import MiniCluster
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.runtime import eventlog
    from spark_rapids_tpu.session import TpuSession

    settings = {
        "spark.rapids.tpu.eventLog.dir": args.eventlog_dir,
        "spark.rapids.tpu.movement.sample.intervalBytes": "64k",
        "spark.rapids.tpu.cluster.mesh.enabled": "true",
        "spark.rapids.tpu.cluster.mesh.devicesPerExecutor": "4",
        "spark.rapids.tpu.cluster.mesh.exchange.twoLevel":
            "true" if two_level else "false",
        **(extra or {})}
    spark = TpuSession(settings)
    paths = tpch.generate(args.scale, args.data_dir)
    dfs = _load_multisplit(spark, paths)
    df = tpch.QUERIES[args.query](dfs)
    with MiniCluster(n_executors=args.executors, conf=RapidsConf(settings),
                     platform="cpu") as c:
        out = c.collect(df)
        mesh_stats = dict(c.mesh_stats)
        placement = dict(c.placement_stats)
    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, out.schema) as w:
        w.write_table(out)
    digest = hashlib.sha256(sink.getvalue().to_pybytes()).hexdigest()
    eventlog.shutdown()
    with open(os.path.join(args.eventlog_dir, "result.json"), "w") as f:
        json.dump({"digest": digest, "rows": out.num_rows,
                   "mesh_stats": mesh_stats, "placement": placement}, f)
    print(f"mesh run ok [{args.query}, twoLevel={two_level}]: "
          f"{out.num_rows} rows, digest {digest[:16]}, {mesh_stats}")
    return 0


def _child(args, mode: str, eventlog_dir: str) -> dict:
    """Run one --two-level-run child and return its parsed result.json +
    summed ledger flows."""
    cmd = [sys.executable, str(pathlib.Path(__file__).resolve()),
           "--data-dir", args.data_dir, "--eventlog-dir", eventlog_dir,
           "--query", args.query, "--scale", str(args.scale),
           "--executors", str(args.executors), "--two-level-run", mode]
    subprocess.run(cmd, check=True)
    samples, _ = _last_samples(eventlog_dir)
    with open(os.path.join(eventlog_dir, "result.json")) as f:
        res = json.load(f)
    res["flows"] = _flows(samples)
    return res


def two_level_compare(args) -> int:
    """Parent body of --two-level-compare: the acceptance assertion for
    the two-level exchange, straight from the movement ledgers."""
    off_dir = os.path.join(args.eventlog_dir, "twolevel-off")
    on_dir = os.path.join(args.eventlog_dir, "twolevel-on")
    for d in (off_dir, on_dir):
        os.makedirs(d, exist_ok=True)
    off = _child(args, "off", off_dir)
    on = _child(args, "on", on_dir)

    def shuffle_wire_payload(flows, links):
        w = sum(c["bytes"] for (e, lk), c in flows.items()
                if e.startswith("shuffle.") and lk in links)
        pb = sum(c["payload_bytes"] for (e, lk), c in flows.items()
                 if e.startswith("shuffle.") and lk in links)
        return w, pb

    def ici(flows):
        return tuple(sum(c[k] for (e, _lk), c in flows.items()
                         if e == "ici.collective")
                     for k in ("bytes", "payload_bytes"))

    assert on["digest"] == off["digest"], \
        f"two-level result differs: {on['digest']} vs {off['digest']}"
    _, off_pb = shuffle_wire_payload(off["flows"], ("loopback", "tcp"))
    _, on_pb = shuffle_wire_payload(on["flows"], ("loopback", "tcp"))
    assert off_pb > 0, f"baseline moved no shuffle bytes: {off['flows']}"
    ratio = off_pb / max(on_pb, 1)
    assert ratio >= 2.0, \
        (f"two-level exchange saved only {ratio:.2f}x loopback/tcp shuffle "
         f"payload ({off_pb}B -> {on_pb}B), need >=2x")
    off_ici_w, _ = ici(off["flows"])
    on_ici_w, on_ici_pb = ici(on["flows"])
    assert on_ici_w > off_ici_w and on_ici_pb > 0, \
        (f"saved bytes did not appear on the ici edge: wire "
         f"{off_ici_w}B -> {on_ici_w}B, payload {on_ici_pb}B")
    assert on["mesh_stats"].get("ici_rows", 0) > 0, on["mesh_stats"]
    assert on["mesh_stats"].get("degraded", 0) == 0, on["mesh_stats"]
    print(f"two-level movement gate ok [{args.query}, {args.executors} "
          f"executors]: loopback/tcp shuffle payload {off_pb}B -> {on_pb}B "
          f"({ratio:.1f}x saved), ici wire {off_ici_w}B -> {on_ici_w}B "
          f"(payload {on_ici_pb}B), {on['mesh_stats']['ici_rows']} rows "
          f"over ICI, digests identical ({on['digest'][:16]})")
    return 0


def ooc_smoke(args) -> int:
    """--ooc-smoke body: the two-level plane completes OUT-OF-CORE — the
    device budget shrunk below the working set forces the spill tiers —
    on >=2 executors, and the ledgers prove spilling actually happened."""
    # both tiers shrunk: device pressure spills to host, host pressure on
    # to disk — spill.write/read are the DISK tier's (metered) edges, so
    # this is what makes "completed out-of-core" assertable
    rc = _mesh_run(args, two_level=True, extra={
        "spark.rapids.tpu.memory.hbm.limitBytes": args.ooc_limit,
        "spark.rapids.tpu.memory.host.spillStorageSize": args.ooc_limit})
    samples, _ = _last_samples(args.eventlog_dir)
    flows = _flows(samples)
    spilled = sum(c["bytes"] for (e, _lk), c in flows.items()
                  if e in ("spill.write", "spill.read"))
    assert spilled > 0, \
        (f"out-of-core run never touched the spill tiers under "
         f"hbm.limitBytes={args.ooc_limit}: {sorted(flows)}")
    with open(os.path.join(args.eventlog_dir, "result.json")) as f:
        res = json.load(f)
    print(f"ooc smoke ok [{args.query}, sf{args.scale:g}, {args.executors} "
          f"executors, hbm.limitBytes={args.ooc_limit}]: completed with "
          f"{spilled}B on the spill edges, {res['rows']} rows, digest "
          f"{res['digest'][:16]}")
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="movement_gate.py", description=__doc__)
    p.add_argument("--data-dir", required=True)
    p.add_argument("--eventlog-dir", required=True)
    p.add_argument("--query", default="q18")
    p.add_argument("--scale", type=float, default=0.01)
    p.add_argument("--executors", type=int, default=3)
    p.add_argument("--two-level-compare", action="store_true")
    p.add_argument("--two-level-run", choices=("on", "off"),
                   help="(internal) one child run of the compare mode")
    p.add_argument("--ooc-smoke", action="store_true")
    p.add_argument("--ooc-limit", default="256m",
                   help="hbm.limitBytes for the --ooc-smoke run")
    args = p.parse_args(argv)
    if args.two_level_run:
        return _mesh_run(args, two_level=args.two_level_run == "on")
    if args.two_level_compare:
        return two_level_compare(args)
    if args.ooc_smoke:
        return ooc_smoke(args)

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    import jax
    jax.config.update("jax_platforms", "cpu")
    import pyarrow as pa
    import spark_rapids_tpu  # noqa: F401  (enables x64)
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu.benchmarks import tpch
    from spark_rapids_tpu.cluster import MiniCluster
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.runtime import eventlog
    from spark_rapids_tpu.runtime import movement as MV
    from spark_rapids_tpu.session import TpuSession

    paths = tpch.generate(args.scale, args.data_dir)
    settings = {
        "spark.rapids.tpu.eventLog.dir": args.eventlog_dir,
        # small interval: mid-task threshold emissions exercised too, not
        # only the forced end-of-task flushes
        "spark.rapids.tpu.movement.sample.intervalBytes": "64k"}
    spark = TpuSession(settings)
    dfs = tpch.load(spark, paths, files_per_partition=4)
    df = tpch.QUERIES[args.query](dfs)

    # the executors need the settings too (their bootstrap configures the
    # event log + ledger from the cluster conf, not the driver session)
    with MiniCluster(n_executors=args.executors, conf=RapidsConf(settings),
                     platform="cpu") as c:
        c.collect(df)

    # single-process invariant, same driver process: a no-shuffle local
    # query must leave every network-capable edge at exactly zero while
    # its host<->device traffic is still metered
    MV.reset()
    local = spark.create_dataframe(pa.table({
        "k": list(range(100)), "v": [float(i) for i in range(100)]}))
    local.filter(F.col("k") < F.lit(50)).select("k", "v").collect()
    snap = MV.snapshot()
    net = {k: v for k, v in snap.items() if k[0] in MV.NETWORK_EDGES
           and (v["bytes"] or v["payload_bytes"])}
    assert not net, f"no-shuffle local query touched network edges: {net}"
    pcie = sum(v["bytes"] for k, v in snap.items() if k[0] in ("h2d", "d2h"))
    assert pcie > 0, f"local query metered no h2d/d2h traffic: {snap}"

    eventlog.shutdown()

    samples, registered = _last_samples(args.eventlog_dir)
    assert registered > 0, "driver log carries no stage.map.end sizes"
    assert len(samples) >= 2, \
        f"expected driver + executor movement samples, got {sorted(samples)}"
    by_edge_link: dict = {}
    for rec in samples.values():
        for f in rec.get("flows") or []:
            k = (f["edge"], f["link"])
            c = by_edge_link.setdefault(
                k, {"bytes": 0, "payload_bytes": 0})
            c["bytes"] += f["bytes"]
            c["payload_bytes"] += f["payload_bytes"]

    recv = sum(c["payload_bytes"] for (e, _lk), c in by_edge_link.items()
               if e == "shuffle.recv")
    cov = recv / registered
    assert 0.90 <= cov <= 1.15, \
        (f"shuffle.recv payload {recv}B vs registered {registered}B "
         f"({cov:.2f}x) outside [0.90, 1.15]")
    tcp = sum(c["bytes"] for (_e, lk), c in by_edge_link.items()
              if lk == "tcp")
    loop = sum(c["bytes"] for (_e, lk), c in by_edge_link.items()
               if lk == "loopback")
    assert tcp == 0, \
        f"same-host cluster inflated the cross-host ledger: tcp={tcp}B"
    assert loop > 0, f"no loopback transport bytes metered: {by_edge_link}"
    retry = sum(c["bytes"] + c["payload_bytes"]
                for (e, _lk), c in by_edge_link.items()
                if e == "shuffle.retry")
    assert retry == 0, f"no-faults run left retry-edge bytes: {retry}"

    print(f"movement gate ok [{args.query}, {args.executors} executors]: "
          f"recv payload {recv}B covers {cov:.2f}x of {registered}B "
          f"registered, tcp=0B loopback={loop}B, retry=0, "
          f"{len(samples)} process ledgers, local no-shuffle edges clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
