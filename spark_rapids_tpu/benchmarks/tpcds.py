"""TPC-DS subset benchmark: deterministic generator, star-join queries via
the session API, and independent single-core NumPy oracles.

Reference role: BASELINE.md config-3 (TPC-DS 10-query subset with the
accelerated shuffle over ICI) and config-5 (full sweep); the reference's
own nightly runs the analogous qa_nightly_select_test.py sweep
(integration_tests). Queries follow the official TPC-DS text restricted to
this schema subset: q3, q42, q52, q55 (date×item star aggregates), q7
(demographics + promotion), q19 (brand revenue where customer and store
zips differ).

The generator is pure vectorized numpy with dense surrogate keys; group
cardinalities and join selectivities track the spec closely enough for
kernel benchmarking (same design stance as benchmarks/tpch.py).
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

N_DATES = 366 * 5            # 1998..2002
FIRST_YEAR = 1998
CATEGORIES = ["Home", "Books", "Electronics", "Music", "Sports", "Shoes",
              "Jewelry", "Men", "Women", "Children"]
GENDERS = ["M", "F"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]


def generate(sf: float, outdir: str, files_per_table: int = 4) -> dict:
    """Generate the subset at scale factor `sf` (SF1 ≈ 2.9M store_sales).
    Returns {table: dir}. Idempotent per table."""
    os.makedirs(outdir, exist_ok=True)
    rng = np.random.default_rng(20260730)
    n_ss = int(2_880_000 * sf)
    n_item = max(int(18_000 * sf), 2000)
    n_cust = max(int(100_000 * sf), 100)
    n_addr = max(n_cust // 2, 50)
    n_store = max(int(12 * max(sf, 1)), 2)
    n_cd = 7 * 5 * 2 * 4     # education x marital x gender x dep buckets
    n_promo = max(int(300 * sf), 10)

    paths = {}

    def write(name, table, nfiles=files_per_table):
        from spark_rapids_tpu.benchmarks.common import write_partitioned
        write_partitioned(outdir, name, table, nfiles, paths)

    # separate stream for round-5 additions (catalog/web facts, preferred
    # flag) so the original tables stay byte-identical with earlier rounds
    rng5 = np.random.default_rng(20260731)

    # date_dim: one row per day, d_date_sk dense from 1
    sk = np.arange(1, N_DATES + 1, dtype=np.int64)
    doy = (sk - 1) % 366
    moy = (doy // 31 + 1).astype(np.int32)
    base_days = int((np.datetime64(f"{FIRST_YEAR}-01-01")
                     - np.datetime64("1970-01-01")) // np.timedelta64(1, "D"))
    write("date_dim", pa.table({
        "d_date_sk": pa.array(sk),
        "d_date": pa.array((base_days + sk - 1).astype(np.int32),
                           pa.int32()).cast(pa.date32()),
        # month sequence from 1200 (the official queries' param range)
        "d_month_seq": pa.array(
            (1200 + ((sk - 1) // 366) * 12 + (moy - 1)).astype(np.int32)),
        "d_year": pa.array((FIRST_YEAR + (sk - 1) // 366).astype(np.int32)),
        "d_moy": pa.array(moy),
        "d_dom": pa.array((doy % 31 + 1).astype(np.int32)),
        "d_qoy": pa.array(((moy - 1) // 3 + 1).astype(np.int32)),
        "d_dow": pa.array((doy % 7).astype(np.int32)),
    }), 1)

    # time_dim: one row per minute of day
    tsk = np.arange(1, 24 * 60 + 1, dtype=np.int64)
    write("time_dim", pa.table({
        "t_time_sk": pa.array(tsk),
        "t_hour": pa.array(((tsk - 1) // 60).astype(np.int32)),
        "t_minute": pa.array(((tsk - 1) % 60).astype(np.int32)),
    }), 1)

    # household_demographics: dep x vehicle x buy-potential cross
    n_hd = 10 * 6 * 3
    hd_sk = np.arange(1, n_hd + 1, dtype=np.int64)
    write("household_demographics", pa.table({
        "hd_demo_sk": pa.array(hd_sk),
        "hd_dep_count": pa.array(((hd_sk - 1) % 10).astype(np.int32)),
        "hd_vehicle_count": pa.array(
            (((hd_sk - 1) // 10) % 6 - 1).astype(np.int32)),
        "hd_buy_potential": pa.array(
            np.array([">10000", "5001-10000", "Unknown"])[
                ((hd_sk - 1) // 60) % 3]),
    }), 1)

    # item
    isk = np.arange(1, n_item + 1, dtype=np.int64)
    cat_id = rng.integers(0, len(CATEGORIES), n_item)
    brand_id = (cat_id + 1) * 1000 + rng.integers(1, 100, n_item)
    class_id = rng.integers(1, 17, n_item)
    write("item", pa.table({
        "i_item_sk": pa.array(isk),
        "i_item_id": pa.array([f"ITEM{k:08d}" for k in isk]),
        "i_item_desc": pa.array([f"desc {k} words" for k in isk]),
        "i_brand_id": pa.array(brand_id.astype(np.int32)),
        "i_brand": pa.array([f"brand#{b}" for b in brand_id]),
        "i_class_id": pa.array(class_id.astype(np.int32)),
        "i_class": pa.array([f"class{c}" for c in class_id]),
        "i_category_id": pa.array((cat_id + 1).astype(np.int32)),
        "i_category": pa.array(np.array(CATEGORIES)[cat_id]),
        "i_current_price": pa.array(
            np.round(rng.uniform(0.5, 100.0, n_item), 2)),
        "i_manufact_id": pa.array(
            rng.integers(1, 140, n_item).astype(np.int32)),
        "i_manager_id": pa.array(
            rng.integers(1, 100, n_item).astype(np.int32)),
        "i_color": pa.array(np.array(
            ["slate", "blanched", "burnished", "floral", "honeydew",
             "salmon", "powder", "peru"])[rng5.integers(0, 8, n_item)]),
    }), 1)

    # customer_demographics: full cross of the filter dimensions
    cd_sk = np.arange(1, n_cd + 1, dtype=np.int64)
    write("customer_demographics", pa.table({
        "cd_demo_sk": pa.array(cd_sk),
        "cd_gender": pa.array(np.array(GENDERS)[(cd_sk - 1) % 2]),
        "cd_marital_status": pa.array(
            np.array(MARITAL)[((cd_sk - 1) // 2) % 5]),
        "cd_education_status": pa.array(
            np.array(EDUCATION)[((cd_sk - 1) // 10) % 7]),
        "cd_dep_count": pa.array(((cd_sk - 1) // 70).astype(np.int32)),
        "cd_purchase_estimate": pa.array(
            (rng5.integers(1, 21, n_cd) * 500).astype(np.int32)),
        "cd_credit_rating": pa.array(np.array(
            ["Low Risk", "Good", "High Risk", "Unknown"])[
                rng5.integers(0, 4, n_cd)]),
    }), 1)

    # promotion
    psk = np.arange(1, n_promo + 1, dtype=np.int64)
    write("promotion", pa.table({
        "p_promo_sk": pa.array(psk),
        "p_channel_email": pa.array(
            np.where(rng.random(n_promo) < 0.5, "N", "Y")),
        "p_channel_event": pa.array(
            np.where(rng.random(n_promo) < 0.5, "N", "Y")),
        "p_channel_dmail": pa.array(
            np.where(rng5.random(n_promo) < 0.5, "N", "Y")),
        "p_channel_tv": pa.array(
            np.where(rng5.random(n_promo) < 0.5, "N", "Y")),
    }), 1)

    # customer_address / store (zips overlap so q19's <> filter selects)
    zips = rng.integers(10000, 10100, n_addr)
    cities = np.array(["Midway", "Fairview", "Oakland", "Salem", "Georgetown",
                       "Ashland", "Marion", "Union", "Clinton", "Greenfield"])
    states = np.array(["CA", "TX", "NY", "GA", "OH", "WA", "IL", "MI"])
    write("customer_address", pa.table({
        "ca_address_sk": pa.array(np.arange(1, n_addr + 1, dtype=np.int64)),
        "ca_zip": pa.array([f"{z:05d}" for z in zips]),
        "ca_city": pa.array(cities[rng.integers(0, len(cities), n_addr)]),
        "ca_state": pa.array(states[rng.integers(0, len(states), n_addr)]),
        "ca_country": pa.array(np.repeat("United States", n_addr)),
        "ca_county": pa.array(
            [f"{c} County" for c in
             cities[rng5.integers(0, len(cities), n_addr)]]),
        "ca_gmt_offset": pa.array(
            rng.choice([-5.0, -6.0, -7.0, -8.0], n_addr)),
    }), 1)
    szips = rng.integers(10000, 10100, n_store)
    write("store", pa.table({
        "s_store_sk": pa.array(np.arange(1, n_store + 1, dtype=np.int64)),
        "s_store_name": pa.array([f"store{k}" for k in range(n_store)]),
        "s_zip": pa.array([f"{z:05d}" for z in szips]),
        "s_city": pa.array(cities[rng.integers(0, len(cities), n_store)]),
        "s_county": pa.array(
            [f"{c} County" for c in
             cities[rng.integers(0, len(cities), n_store)]]),
        "s_state": pa.array(states[rng.integers(0, len(states), n_store)]),
        "s_number_employees": pa.array(
            rng.integers(200, 300, n_store).astype(np.int32)),
        "s_gmt_offset": pa.array(
            rng5.choice([-5.0, -6.0, -7.0, -8.0], n_store)),
    }), 1)

    # customer
    write("customer", pa.table({
        "c_customer_sk": pa.array(np.arange(1, n_cust + 1, dtype=np.int64)),
        "c_current_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, n_cust).astype(np.int64)),
        "c_first_name": pa.array([f"First{k % 500}" for k in range(n_cust)]),
        "c_last_name": pa.array([f"Last{k % 700}" for k in range(n_cust)]),
        "c_preferred_cust_flag": pa.array(
            np.where(rng5.random(n_cust) < 0.5, "Y", "N")),
        "c_birth_year": pa.array(
            rng5.integers(1924, 1993, n_cust).astype(np.int32)),
        "c_birth_month": pa.array(
            rng5.integers(1, 13, n_cust).astype(np.int32)),
        "c_current_cdemo_sk": pa.array(
            rng5.integers(1, n_cd + 1, n_cust).astype(np.int64)),
    }), 1)

    # store_sales (fact). Money columns that TPC-DS declares decimal(7,2)
    # ride as decimal128(7,2) — the decimal-heavy queries aggregate them
    # exactly on device (scaled-int64 backing).
    def dec72(arr):
        from decimal import Decimal
        cents = np.round(np.asarray(arr) * 100).astype(np.int64)
        return pa.array([Decimal(int(v)).scaleb(-2) for v in cents],
                        pa.decimal128(7, 2))

    # basket structure: a TICKET is one visit — one customer, household,
    # date, store, and address per ticket (row counts per ticket span 1..25
    # so q34's 15-20 band and q73's 1-5 band both select)
    n_tk = max(n_ss // 13, 1)
    tk_sizes = rng.integers(1, 26, n_tk)
    ticket = np.repeat(np.arange(1, n_tk + 1, dtype=np.int64), tk_sizes)
    if len(ticket) < n_ss:
        ticket = np.concatenate(
            [ticket, np.full(n_ss - len(ticket), n_tk, np.int64)])
    ticket = ticket[:n_ss]
    tk_cust = rng.integers(1, n_cust + 1, n_tk + 1).astype(np.int64)
    tk_hd = rng.integers(1, n_hd + 1, n_tk + 1).astype(np.int64)
    tk_date = rng.integers(1, N_DATES + 1, n_tk + 1).astype(np.int64)
    tk_store = rng.integers(1, n_store + 1, n_tk + 1).astype(np.int64)
    tk_addr = rng.integers(1, n_addr + 1, n_tk + 1).astype(np.int64)
    write("store_sales", pa.table({
        "ss_sold_date_sk": pa.array(tk_date[ticket - 1]),
        "ss_sold_time_sk": pa.array(
            rng.integers(1, 24 * 60 + 1, n_ss).astype(np.int64)),
        "ss_item_sk": pa.array(
            rng.integers(1, n_item + 1, n_ss).astype(np.int64)),
        "ss_customer_sk": pa.array(tk_cust[ticket - 1]),
        "ss_cdemo_sk": pa.array(
            rng.integers(1, n_cd + 1, n_ss).astype(np.int64)),
        "ss_hdemo_sk": pa.array(tk_hd[ticket - 1]),
        "ss_addr_sk": pa.array(tk_addr[ticket - 1]),
        "ss_promo_sk": pa.array(
            rng.integers(1, n_promo + 1, n_ss).astype(np.int64)),
        "ss_store_sk": pa.array(tk_store[ticket - 1]),
        "ss_ticket_number": pa.array(ticket),
        "ss_quantity": pa.array(
            rng.integers(1, 100, n_ss).astype(np.int32)),
        "ss_list_price": pa.array(
            np.round(rng.uniform(1.0, 200.0, n_ss), 2)),
        "ss_sales_price": pa.array(
            np.round(rng.uniform(1.0, 200.0, n_ss), 2)),
        "ss_ext_sales_price": pa.array(
            np.round(rng.uniform(1.0, 20000.0, n_ss), 2)),
        "ss_ext_list_price": pa.array(
            np.round(rng.uniform(1.0, 20000.0, n_ss), 2)),
        "ss_ext_tax": pa.array(
            np.round(rng.uniform(0.0, 1800.0, n_ss), 2)),
        "ss_coupon_amt": pa.array(
            np.round(rng.uniform(0.0, 50.0, n_ss), 2)),
        "ss_wholesale_cost": pa.array(
            np.round(rng.uniform(1.0, 100.0, n_ss), 2)),
        "ss_net_paid": dec72(rng.uniform(0.0, 20000.0, n_ss)),
        "ss_net_profit": dec72(rng.uniform(-5000.0, 15000.0, n_ss)),
        "ss_ext_wholesale_cost": dec72(rng.uniform(1.0, 10000.0, n_ss)),
    }))

    # catalog_sales / web_sales (round 5): the cross-channel facts q38/q87's
    # INTERSECT/EXCEPT and q14's shapes join against. Spec row ratios are
    # roughly ss : cs : ws = 2 : 1 : 0.5; half of each channel's
    # (customer, date) pairs ECHO store_sales visits so cross-channel
    # set operations select a meaningful overlap (spec customers shop in
    # several channels; independent draws would make the intersect ~empty).
    ss_date, ss_cust = tk_date[ticket - 1], tk_cust[ticket - 1]

    def channel(prefix, n_rows):
        take = rng5.integers(0, n_ss, n_rows)
        echo = rng5.random(n_rows) < 0.5
        date = np.where(echo, ss_date[take],
                        rng5.integers(1, N_DATES + 1, n_rows)).astype(np.int64)
        cust = np.where(echo, ss_cust[take],
                        rng5.integers(1, n_cust + 1, n_rows)).astype(np.int64)
        return pa.table({
            f"{prefix}_sold_date_sk": pa.array(date),
            f"{prefix}_bill_customer_sk": pa.array(cust),
            f"{prefix}_item_sk": pa.array(
                rng5.integers(1, n_item + 1, n_rows).astype(np.int64)),
            f"{prefix}_quantity": pa.array(
                rng5.integers(1, 100, n_rows).astype(np.int32)),
            f"{prefix}_list_price": pa.array(
                np.round(rng5.uniform(1.0, 200.0, n_rows), 2)),
            f"{prefix}_sales_price": pa.array(
                np.round(rng5.uniform(1.0, 200.0, n_rows), 2)),
            f"{prefix}_ext_sales_price": pa.array(
                np.round(rng5.uniform(1.0, 20000.0, n_rows), 2)),
            f"{prefix}_bill_addr_sk": pa.array(
                rng5.integers(1, n_addr + 1, n_rows).astype(np.int64)),
            f"{prefix}_bill_cdemo_sk": pa.array(
                rng5.integers(1, n_cd + 1, n_rows).astype(np.int64)),
            f"{prefix}_promo_sk": pa.array(
                rng5.integers(1, n_promo + 1, n_rows).astype(np.int64)),
            f"{prefix}_coupon_amt": pa.array(
                np.round(rng5.uniform(0.0, 50.0, n_rows), 2)),
            f"{prefix}_net_profit": pa.array(
                np.round(rng5.uniform(-5000.0, 15000.0, n_rows), 2)),
        })

    write("catalog_sales", channel("cs", max(n_ss // 2, 10)))
    write("web_sales", channel("ws", max(n_ss // 4, 10)))

    # inventory (round 5): weekly quantity-on-hand snapshots for a sampled
    # item subset (q22's rollup; the spec snapshots weekly per warehouse —
    # one warehouse keeps the subset fact compact)
    inv_dates = np.arange(1, N_DATES + 1, 7, dtype=np.int64)
    inv_items = np.arange(1, n_item + 1, max(1, n_item // 1000),
                          dtype=np.int64)
    dgrid, igrid = np.meshgrid(inv_dates, inv_items, indexing="ij")
    n_inv = dgrid.size
    write("inventory", pa.table({
        "inv_date_sk": pa.array(dgrid.ravel()),
        "inv_item_sk": pa.array(igrid.ravel()),
        "inv_warehouse_sk": pa.array(np.ones(n_inv, np.int64)),
        "inv_quantity_on_hand": pa.array(
            rng5.integers(0, 1000, n_inv).astype(np.int32)),
    }))
    return paths


def load(spark, paths: dict, files_per_partition: int = 2) -> dict:
    from spark_rapids_tpu.benchmarks.common import load as _load
    return _load(spark, paths, files_per_partition)


# -- queries (session API; official TPC-DS text over this subset) -------------

def _star(dfs, moy, year=None):
    """store_sales ⋈ date_dim ⋈ item — the q3/q42/q52/q55 spine. q3 filters
    only the month (it groups by d_year); the others pin one year too."""
    import spark_rapids_tpu.functions as F
    c = F.col
    cond = c("d_moy") == F.lit(moy)
    if year is not None:
        cond = (c("d_year") == F.lit(year)) & cond
    dd = (dfs["date_dim"].filter(cond)
          .select(c("d_date_sk").alias("ss_sold_date_sk"), c("d_year")))
    return (dfs["store_sales"]
            .select(c("ss_sold_date_sk"), c("ss_item_sk"),
                    c("ss_ext_sales_price"))
            .join(dd, on="ss_sold_date_sk")
            .select(c("ss_item_sk").alias("i_item_sk"), c("d_year"),
                    c("ss_ext_sales_price")))


def q3(dfs):
    """Brand revenue by year for manufacturer 128 in November (official
    TPC-DS q3: d_moy = 11 and i_manufact_id = 128, grouped by d_year)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"].filter(c("i_manufact_id") == F.lit(128))
            .select(c("i_item_sk"), c("i_brand_id"), c("i_brand")))
    j = _star(dfs, 11).join(item, on="i_item_sk")
    return (j.group_by(c("d_year"), c("i_brand_id"), c("i_brand"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("sum_agg"))
            .sort(c("d_year"), c("sum_agg"), c("i_brand_id"),
                  ascending=[True, False, True])
            .limit(100))


def q42(dfs):
    """Category revenue for one manager's items, one month (official TPC-DS
    q42: i_manager_id = 1, d_year = 2000, d_moy = 11)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"].filter(c("i_manager_id") == F.lit(1))
            .select(c("i_item_sk"), c("i_category_id"), c("i_category")))
    j = _star(dfs, 11, 2000).join(item, on="i_item_sk")
    return (j.group_by(c("d_year"), c("i_category_id"), c("i_category"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("sum_agg"))
            .sort(c("sum_agg"), c("d_year"), c("i_category_id"),
                  ascending=[False, True, True])
            .limit(100))


def q52(dfs):
    """Brand revenue for one manager's items, one month (official TPC-DS
    q52: i_manager_id = 1, d_year = 2000, d_moy = 11)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"].filter(c("i_manager_id") == F.lit(1))
            .select(c("i_item_sk"), c("i_brand_id"), c("i_brand")))
    j = _star(dfs, 11, 2000).join(item, on="i_item_sk")
    return (j.group_by(c("d_year"), c("i_brand_id"), c("i_brand"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("ext_price"))
            .sort(c("d_year"), c("ext_price"), c("i_brand_id"),
                  ascending=[True, False, True])
            .limit(100))


def q55(dfs):
    """Brand revenue for one manager's items, one month (TPC-DS q55)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"].filter(c("i_manager_id") == F.lit(28))
            .select(c("i_item_sk"), c("i_brand_id"), c("i_brand")))
    j = _star(dfs, 11, 1999).join(item, on="i_item_sk")
    return (j.group_by(c("i_brand_id"), c("i_brand"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("ext_price"))
            .sort(c("ext_price"), c("i_brand_id"), ascending=[False, True])
            .limit(100))


def q7(dfs):
    """Average quantities for one demographic + non-event promos (TPC-DS q7)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    cd = (dfs["customer_demographics"]
          .filter((c("cd_gender") == F.lit("M"))
                  & (c("cd_marital_status") == F.lit("S"))
                  & (c("cd_education_status") == F.lit("College")))
          .select(c("cd_demo_sk").alias("ss_cdemo_sk")))
    promo = (dfs["promotion"]
             .filter((c("p_channel_email") == F.lit("N"))
                     | (c("p_channel_event") == F.lit("N")))
             .select(c("p_promo_sk").alias("ss_promo_sk")))
    dd = (dfs["date_dim"].filter(c("d_year") == F.lit(2000))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    item = dfs["item"].select(c("i_item_sk").alias("ss_item_sk"),
                              c("i_item_id"))
    j = (dfs["store_sales"]
         .join(cd, on="ss_cdemo_sk")
         .join(promo, on="ss_promo_sk")
         .join(dd, on="ss_sold_date_sk")
         .join(item, on="ss_item_sk"))
    return (j.group_by(c("i_item_id"))
            .agg(F.avg(c("ss_quantity")).alias("agg1"),
                 F.avg(c("ss_list_price")).alias("agg2"),
                 F.avg(c("ss_coupon_amt")).alias("agg3"),
                 F.avg(c("ss_sales_price")).alias("agg4"))
            .sort(c("i_item_id"))
            .limit(100))


def q19(dfs):
    """Brand revenue where customer zip differs from store zip (TPC-DS q19)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    dd = (dfs["date_dim"]
          .filter((c("d_year") == F.lit(1999)) & (c("d_moy") == F.lit(11)))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    item = (dfs["item"].filter(c("i_manager_id") == F.lit(8))
            .select(c("i_item_sk").alias("ss_item_sk"), c("i_brand_id"),
                    c("i_brand"), c("i_manufact_id")))
    cust = dfs["customer"].select(c("c_customer_sk").alias("ss_customer_sk"),
                                  c("c_current_addr_sk").alias("ca_address_sk"))
    addr = dfs["customer_address"].select(c("ca_address_sk"), c("ca_zip"))
    store = dfs["store"].select(c("s_store_sk").alias("ss_store_sk"),
                                c("s_zip"))
    j = (dfs["store_sales"]
         .select(c("ss_sold_date_sk"), c("ss_item_sk"), c("ss_customer_sk"),
                 c("ss_store_sk"), c("ss_ext_sales_price"))
         .join(dd, on="ss_sold_date_sk")
         .join(item, on="ss_item_sk")
         .join(cust, on="ss_customer_sk")
         .join(addr, on="ca_address_sk")
         .join(store, on="ss_store_sk")
         .filter(c("ca_zip") != c("s_zip")))
    return (j.group_by(c("i_brand_id"), c("i_brand"), c("i_manufact_id"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("ext_price"))
            .sort(c("ext_price"), c("i_brand_id"), ascending=[False, True])
            .limit(100))


def _win_avg(df, value_col, part_cols, out_name):
    """value avg over (partition by part_cols) with a full-partition frame —
    the q53/q63/q89 window shape."""
    from spark_rapids_tpu.expr import core as E
    from spark_rapids_tpu.expr import windows as WX
    from spark_rapids_tpu.expr.aggregates import Average, Sum
    spec = WX.WindowSpec(tuple(E.col(p) for p in part_cols), (),
                         WX.WindowFrame("rows", None, None))
    return df.window([E.Alias(
        WX.WindowExpression(Average(E.col(value_col)), spec), out_name)])


def _win_sum(df, value_col, part_cols, out_name):
    from spark_rapids_tpu.expr import core as E
    from spark_rapids_tpu.expr import windows as WX
    from spark_rapids_tpu.expr.aggregates import Sum
    spec = WX.WindowSpec(tuple(E.col(p) for p in part_cols), (),
                         WX.WindowFrame("rows", None, None))
    return df.window([E.Alias(
        WX.WindowExpression(Sum(E.col(value_col)), spec), out_name)])


def q53(dfs):
    """Quarterly manufacturer sales vs their window average (TPC-DS q53:
    sum by manufact x quarter, avg OVER (PARTITION BY i_manufact_id),
    keep quarters deviating >10%)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"]
            .filter(c("i_category").isin("Books", "Home", "Electronics"))
            .select(c("i_item_sk").alias("ss_item_sk"), c("i_manufact_id")))
    dd = (dfs["date_dim"].filter(c("d_year") == F.lit(2000))
          .select(c("d_date_sk").alias("ss_sold_date_sk"), c("d_qoy")))
    store = dfs["store"].select(c("s_store_sk").alias("ss_store_sk"))
    base = (dfs["store_sales"]
            .select(c("ss_item_sk"), c("ss_sold_date_sk"), c("ss_store_sk"),
                    c("ss_sales_price"))
            .join(item, on="ss_item_sk").join(dd, on="ss_sold_date_sk")
            .join(store, on="ss_store_sk")
            .group_by(c("i_manufact_id"), c("d_qoy"))
            .agg(F.sum(c("ss_sales_price")).alias("sum_sales")))
    w = _win_avg(base, "sum_sales", ["i_manufact_id"], "avg_quarterly_sales")
    return (w.filter((c("avg_quarterly_sales") > F.lit(0.0))
                     & (F.abs(c("sum_sales") - c("avg_quarterly_sales"))
                        / c("avg_quarterly_sales") > F.lit(0.1)))
            .select(c("i_manufact_id"), c("sum_sales"),
                    c("avg_quarterly_sales"))
            .sort(c("avg_quarterly_sales"), c("sum_sales"),
                  c("i_manufact_id"))
            .limit(100))


def q63(dfs):
    """Monthly manager sales vs their window average (TPC-DS q63 — q53's
    shape with i_manager_id and d_moy)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"]
            .filter(c("i_category").isin("Books", "Home", "Electronics"))
            .select(c("i_item_sk").alias("ss_item_sk"), c("i_manager_id")))
    dd = (dfs["date_dim"].filter(c("d_year") == F.lit(2000))
          .select(c("d_date_sk").alias("ss_sold_date_sk"), c("d_moy")))
    base = (dfs["store_sales"]
            .select(c("ss_item_sk"), c("ss_sold_date_sk"),
                    c("ss_sales_price"))
            .join(item, on="ss_item_sk").join(dd, on="ss_sold_date_sk")
            .group_by(c("i_manager_id"), c("d_moy"))
            .agg(F.sum(c("ss_sales_price")).alias("sum_sales")))
    w = _win_avg(base, "sum_sales", ["i_manager_id"], "avg_monthly_sales")
    return (w.filter((c("avg_monthly_sales") > F.lit(0.0))
                     & (F.abs(c("sum_sales") - c("avg_monthly_sales"))
                        / c("avg_monthly_sales") > F.lit(0.1)))
            .select(c("i_manager_id"), c("sum_sales"),
                    c("avg_monthly_sales"))
            .sort(c("i_manager_id"), c("avg_monthly_sales"), c("sum_sales"))
            .limit(100))


def q89(dfs):
    """Monthly class sales per store vs the (category, brand, store) window
    average (TPC-DS q89)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"]
            .filter(c("i_category").isin("Books", "Electronics", "Sports"))
            .select(c("i_item_sk").alias("ss_item_sk"), c("i_category"),
                    c("i_class"), c("i_brand")))
    dd = (dfs["date_dim"].filter(c("d_year") == F.lit(1999))
          .select(c("d_date_sk").alias("ss_sold_date_sk"), c("d_moy")))
    store = dfs["store"].select(c("s_store_sk").alias("ss_store_sk"),
                                c("s_store_name"))
    base = (dfs["store_sales"]
            .select(c("ss_item_sk"), c("ss_sold_date_sk"), c("ss_store_sk"),
                    c("ss_sales_price"))
            .join(item, on="ss_item_sk").join(dd, on="ss_sold_date_sk")
            .join(store, on="ss_store_sk")
            .group_by(c("i_category"), c("i_class"), c("i_brand"),
                      c("s_store_name"), c("d_moy"))
            .agg(F.sum(c("ss_sales_price")).alias("sum_sales")))
    w = _win_avg(base, "sum_sales",
                 ["i_category", "i_brand", "s_store_name"],
                 "avg_monthly_sales")
    return (w.filter((c("avg_monthly_sales") != F.lit(0.0))
                     & (F.abs(c("sum_sales") - c("avg_monthly_sales"))
                        / c("avg_monthly_sales") > F.lit(0.1)))
            .select(c("i_category"), c("i_class"), c("i_brand"),
                    c("s_store_name"), c("d_moy"), c("sum_sales"),
                    c("avg_monthly_sales"))
            .sort((c("sum_sales") - c("avg_monthly_sales")).alias("_d"),
                  c("s_store_name"), c("i_class"), c("d_moy"))
            .limit(100))


def q98(dfs):
    """Class revenue ratio (TPC-DS q98): item revenue and its share of the
    class total via SUM OVER (PARTITION BY i_class)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"]
            .filter(c("i_category").isin("Sports", "Books", "Home"))
            .select(c("i_item_sk").alias("ss_item_sk"), c("i_item_id"),
                    c("i_item_desc"), c("i_category"), c("i_class"),
                    c("i_current_price")))
    dd = (dfs["date_dim"]
          .filter((c("d_year") == F.lit(1999)) & (c("d_moy") == F.lit(2)))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    base = (dfs["store_sales"]
            .select(c("ss_item_sk"), c("ss_sold_date_sk"),
                    c("ss_ext_sales_price"))
            .join(item, on="ss_item_sk").join(dd, on="ss_sold_date_sk")
            .group_by(c("i_item_id"), c("i_item_desc"), c("i_category"),
                      c("i_class"), c("i_current_price"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("itemrevenue")))
    w = _win_sum(base, "itemrevenue", ["i_class"], "class_revenue")
    return (w.select(c("i_item_id"), c("i_item_desc"), c("i_category"),
                     c("i_class"), c("i_current_price"), c("itemrevenue"),
                     (c("itemrevenue") * F.lit(100.0) / c("class_revenue"))
                     .alias("revenueratio"))
            .sort(c("i_category"), c("i_class"), c("i_item_id"),
                  c("i_item_desc"), c("revenueratio")))


def q43(dfs):
    """Store sales by day of week (TPC-DS q43: one conditional sum per
    weekday)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    dd = (dfs["date_dim"].filter(c("d_year") == F.lit(2000))
          .select(c("d_date_sk").alias("ss_sold_date_sk"), c("d_dow")))
    store = dfs["store"].select(c("s_store_sk").alias("ss_store_sk"),
                                c("s_store_name"))
    j = (dfs["store_sales"]
         .select(c("ss_sold_date_sk"), c("ss_store_sk"),
                 c("ss_sales_price"))
         .join(dd, on="ss_sold_date_sk").join(store, on="ss_store_sk"))
    days = ["sun", "mon", "tue", "wed", "thu", "fri", "sat"]
    aggs = [F.sum(F.when(c("d_dow") == F.lit(i), c("ss_sales_price")))
            .alias(f"{d}_sales")
            for i, d in enumerate(days)]
    return (j.group_by(c("s_store_name")).agg(*aggs)
            .sort(c("s_store_name")).limit(100))


def q96(dfs):
    """Count of evening high-dependent-count sales at one store
    (TPC-DS q96)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    hd = (dfs["household_demographics"]
          .filter(c("hd_dep_count") == F.lit(5))
          .select(c("hd_demo_sk").alias("ss_hdemo_sk")))
    td = (dfs["time_dim"]
          .filter((c("t_hour") == F.lit(20)) & (c("t_minute") >= F.lit(30)))
          .select(c("t_time_sk").alias("ss_sold_time_sk")))
    store = (dfs["store"].filter(c("s_store_name") == F.lit("store0"))
             .select(c("s_store_sk").alias("ss_store_sk")))
    j = (dfs["store_sales"]
         .select(c("ss_hdemo_sk"), c("ss_sold_time_sk"), c("ss_store_sk"))
         .join(hd, on="ss_hdemo_sk").join(td, on="ss_sold_time_sk")
         .join(store, on="ss_store_sk"))
    return j.agg(F.count().alias("cnt"))


def _ticket_counts(dfs, dep_lo, dep_hi, cnt_lo, cnt_hi, years):
    """The q34/q73 spine: tickets by customer with household filters and a
    HAVING on the per-ticket row count."""
    import spark_rapids_tpu.functions as F
    c = F.col
    dd = (dfs["date_dim"]
          .filter(c("d_year").isin(*years)
                  & ((c("d_dom") >= F.lit(1)) & (c("d_dom") <= F.lit(3))
                     | (c("d_dom") >= F.lit(25)) & (c("d_dom") <= F.lit(28))))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    hd = (dfs["household_demographics"]
          .filter((c("hd_dep_count") >= F.lit(dep_lo))
                  & (c("hd_dep_count") <= F.lit(dep_hi))
                  & (c("hd_buy_potential") != F.lit("Unknown")))
          .select(c("hd_demo_sk").alias("ss_hdemo_sk")))
    grouped = (dfs["store_sales"]
               .select(c("ss_sold_date_sk"), c("ss_hdemo_sk"),
                       c("ss_customer_sk"), c("ss_ticket_number"))
               .join(dd, on="ss_sold_date_sk").join(hd, on="ss_hdemo_sk")
               .group_by(c("ss_ticket_number"), c("ss_customer_sk"))
               .agg(F.count().alias("cnt"))
               .filter((c("cnt") >= F.lit(cnt_lo))
                       & (c("cnt") <= F.lit(cnt_hi))))
    cust = dfs["customer"].select(c("c_customer_sk").alias("ss_customer_sk"),
                                  c("c_first_name"), c("c_last_name"))
    return (grouped.join(cust, on="ss_customer_sk")
            .select(c("c_last_name"), c("c_first_name"),
                    c("ss_ticket_number"), c("cnt")))


def q34(dfs):
    """Large-ticket frequent shoppers (TPC-DS q34: 15-20 items/ticket)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    return (_ticket_counts(dfs, 2, 9, 15, 20, (1999, 2000, 2001))
            .sort(c("c_last_name"), c("c_first_name"),
                  c("ss_ticket_number"), c("cnt"),
                  ascending=[True, True, True, False]))


def q73(dfs):
    """Small-ticket shoppers (TPC-DS q73: 1-5 items/ticket)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    # official text orders by (cnt desc, last name) only; the extra
    # first-name/ticket keys make tie order deterministic for the oracle
    return (_ticket_counts(dfs, 1, 9, 1, 5, (1999, 2000, 2001))
            .sort(c("cnt"), c("c_last_name"), c("c_first_name"),
                  c("ss_ticket_number"),
                  ascending=[False, True, True, True])
            .limit(1000))


def q79(dfs):
    """Per-ticket coupon amount and net profit for big stores on Mondays
    (TPC-DS q79; ss_net_profit is decimal(7,2) — exact sums)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    dd = (dfs["date_dim"]
          .filter((c("d_dow") == F.lit(1))
                  & c("d_year").isin(1998, 1999, 2000))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    hd = (dfs["household_demographics"]
          .filter((c("hd_dep_count") == F.lit(6))
                  | (c("hd_vehicle_count") > F.lit(2)))
          .select(c("hd_demo_sk").alias("ss_hdemo_sk")))
    store = (dfs["store"]
             .filter((c("s_number_employees") >= F.lit(200))
                     & (c("s_number_employees") <= F.lit(295)))
             .select(c("s_store_sk").alias("ss_store_sk"), c("s_city")))
    grouped = (dfs["store_sales"]
               .select(c("ss_sold_date_sk"), c("ss_hdemo_sk"),
                       c("ss_store_sk"), c("ss_customer_sk"),
                       c("ss_ticket_number"), c("ss_coupon_amt"),
                       c("ss_net_profit"))
               .join(dd, on="ss_sold_date_sk").join(hd, on="ss_hdemo_sk")
               .join(store, on="ss_store_sk")
               .group_by(c("ss_ticket_number"), c("ss_customer_sk"),
                         c("s_city"))
               .agg(F.sum(c("ss_coupon_amt")).alias("amt"),
                    F.sum(c("ss_net_profit")).alias("profit")))
    cust = dfs["customer"].select(c("c_customer_sk").alias("ss_customer_sk"),
                                  c("c_last_name"), c("c_first_name"))
    return (grouped.join(cust, on="ss_customer_sk")
            .select(c("c_last_name"), c("c_first_name"), c("s_city"),
                    c("profit"), c("ss_ticket_number"), c("amt"))
            .sort(c("c_last_name"), c("c_first_name"), c("s_city"),
                  c("profit"))
            .limit(100))


def q48(dfs):
    """Quantity sum under OR'd demographic/address/price-band predicates
    (TPC-DS q48; the ss_net_profit bands hit the decimal column)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    dd = (dfs["date_dim"].filter(c("d_year") == F.lit(2000))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    cd = (dfs["customer_demographics"]
          .select(c("cd_demo_sk").alias("ss_cdemo_sk"),
                  c("cd_marital_status"), c("cd_education_status")))
    ca = (dfs["customer_address"]
          .filter(c("ca_country") == F.lit("United States"))
          .select(c("ca_address_sk").alias("ss_addr_sk"), c("ca_state")))
    j = (dfs["store_sales"]
         .select(c("ss_sold_date_sk"), c("ss_cdemo_sk"), c("ss_addr_sk"),
                 c("ss_quantity"), c("ss_sales_price"), c("ss_net_profit"))
         .join(dd, on="ss_sold_date_sk").join(cd, on="ss_cdemo_sk")
         .join(ca, on="ss_addr_sk"))
    price = c("ss_sales_price")
    md = (((c("cd_marital_status") == F.lit("M"))
           & (c("cd_education_status") == F.lit("4 yr Degree"))
           & (price >= F.lit(100.0)) & (price <= F.lit(150.0)))
          | ((c("cd_marital_status") == F.lit("D"))
             & (c("cd_education_status") == F.lit("2 yr Degree"))
             & (price >= F.lit(50.0)) & (price <= F.lit(100.0)))
          | ((c("cd_marital_status") == F.lit("S"))
             & (c("cd_education_status") == F.lit("College"))
             & (price >= F.lit(150.0)) & (price <= F.lit(200.0))))
    profit = c("ss_net_profit")
    geo = ((c("ca_state").isin("CA", "TX", "OH")
            & (profit >= F.lit(0)) & (profit <= F.lit(2000)))
           | (c("ca_state").isin("NY", "GA", "WA")
              & (profit >= F.lit(150)) & (profit <= F.lit(3000)))
           | (c("ca_state").isin("IL", "MI")
              & (profit >= F.lit(50)) & (profit <= F.lit(25000))))
    return j.filter(md & geo).agg(F.sum(c("ss_quantity")).alias("total"))


def q27(dfs):
    """Item averages by state for one demographic slice (TPC-DS q27's base
    grouping — the subset omits the ROLLUP levels)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    cd = (dfs["customer_demographics"]
          .filter((c("cd_gender") == F.lit("F"))
                  & (c("cd_marital_status") == F.lit("W"))
                  & (c("cd_education_status") == F.lit("Primary")))
          .select(c("cd_demo_sk").alias("ss_cdemo_sk")))
    dd = (dfs["date_dim"].filter(c("d_year") == F.lit(1999))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    store = (dfs["store"].filter(c("s_state").isin("CA", "TX", "NY", "OH"))
             .select(c("s_store_sk").alias("ss_store_sk"), c("s_state")))
    item = dfs["item"].select(c("i_item_sk").alias("ss_item_sk"),
                              c("i_item_id"))
    j = (dfs["store_sales"]
         .join(cd, on="ss_cdemo_sk").join(dd, on="ss_sold_date_sk")
         .join(store, on="ss_store_sk").join(item, on="ss_item_sk"))
    return (j.group_by(c("i_item_id"), c("s_state"))
            .agg(F.avg(c("ss_quantity")).alias("agg1"),
                 F.avg(c("ss_list_price")).alias("agg2"),
                 F.avg(c("ss_coupon_amt")).alias("agg3"),
                 F.avg(c("ss_sales_price")).alias("agg4"))
            .sort(c("i_item_id"), c("s_state"))
            .limit(100))


def q46(dfs):
    """Weekend city shoppers whose bought-city differs from home city
    (TPC-DS q46)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    dd = (dfs["date_dim"]
          .filter(c("d_dow").isin(0, 6) & c("d_year").isin(1999, 2000, 2001))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    hd = (dfs["household_demographics"]
          .filter((c("hd_dep_count") == F.lit(5))
                  | (c("hd_vehicle_count") == F.lit(3)))
          .select(c("hd_demo_sk").alias("ss_hdemo_sk")))
    store = (dfs["store"]
             .filter(c("s_city").isin("Midway", "Fairview", "Oakland"))
             .select(c("s_store_sk").alias("ss_store_sk")))
    sale_addr = dfs["customer_address"].select(
        c("ca_address_sk").alias("ss_addr_sk"),
        c("ca_city").alias("bought_city"))
    grouped = (dfs["store_sales"]
               .select(c("ss_sold_date_sk"), c("ss_hdemo_sk"),
                       c("ss_store_sk"), c("ss_addr_sk"),
                       c("ss_customer_sk"), c("ss_ticket_number"),
                       c("ss_coupon_amt"), c("ss_ext_sales_price"))
               .join(dd, on="ss_sold_date_sk").join(hd, on="ss_hdemo_sk")
               .join(store, on="ss_store_sk").join(sale_addr, on="ss_addr_sk")
               .group_by(c("ss_ticket_number"), c("ss_customer_sk"),
                         c("bought_city"))
               .agg(F.sum(c("ss_coupon_amt")).alias("amt"),
                    F.sum(c("ss_ext_sales_price")).alias("profit")))
    cust = dfs["customer"].select(
        c("c_customer_sk").alias("ss_customer_sk"), c("c_first_name"),
        c("c_last_name"), c("c_current_addr_sk").alias("ca_address_sk"))
    home = dfs["customer_address"].select(c("ca_address_sk"),
                                          c("ca_city"))
    return (grouped.join(cust, on="ss_customer_sk")
            .join(home, on="ca_address_sk")
            .filter(c("ca_city") != c("bought_city"))
            .select(c("c_last_name"), c("c_first_name"), c("ca_city"),
                    c("bought_city"), c("ss_ticket_number"), c("amt"),
                    c("profit"))
            .sort(c("c_last_name"), c("c_first_name"), c("ca_city"),
                  c("bought_city"), c("ss_ticket_number"))
            .limit(100))


def q68(dfs):
    """q46's shape over ext list price / ext tax (TPC-DS q68)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    dd = (dfs["date_dim"]
          .filter((c("d_dom") >= F.lit(1)) & (c("d_dom") <= F.lit(2))
                  & c("d_year").isin(1998, 1999, 2000))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    hd = (dfs["household_demographics"]
          .filter((c("hd_dep_count") == F.lit(4))
                  | (c("hd_vehicle_count") == F.lit(3)))
          .select(c("hd_demo_sk").alias("ss_hdemo_sk")))
    store = (dfs["store"]
             .filter(c("s_city").isin("Midway", "Fairview"))
             .select(c("s_store_sk").alias("ss_store_sk")))
    sale_addr = dfs["customer_address"].select(
        c("ca_address_sk").alias("ss_addr_sk"),
        c("ca_city").alias("bought_city"))
    grouped = (dfs["store_sales"]
               .select(c("ss_sold_date_sk"), c("ss_hdemo_sk"),
                       c("ss_store_sk"), c("ss_addr_sk"),
                       c("ss_customer_sk"), c("ss_ticket_number"),
                       c("ss_ext_sales_price"), c("ss_ext_list_price"),
                       c("ss_ext_tax"))
               .join(dd, on="ss_sold_date_sk").join(hd, on="ss_hdemo_sk")
               .join(store, on="ss_store_sk").join(sale_addr, on="ss_addr_sk")
               .group_by(c("ss_ticket_number"), c("ss_customer_sk"),
                         c("bought_city"))
               .agg(F.sum(c("ss_ext_sales_price")).alias("extended_price"),
                    F.sum(c("ss_ext_list_price")).alias("list_price"),
                    F.sum(c("ss_ext_tax")).alias("extended_tax")))
    cust = dfs["customer"].select(
        c("c_customer_sk").alias("ss_customer_sk"), c("c_first_name"),
        c("c_last_name"), c("c_current_addr_sk").alias("ca_address_sk"))
    home = dfs["customer_address"].select(c("ca_address_sk"), c("ca_city"))
    return (grouped.join(cust, on="ss_customer_sk")
            .join(home, on="ca_address_sk")
            .filter(c("ca_city") != c("bought_city"))
            .select(c("c_last_name"), c("c_first_name"), c("ca_city"),
                    c("bought_city"), c("ss_ticket_number"),
                    c("extended_price"), c("extended_tax"), c("list_price"))
            .sort(c("c_last_name"), c("ss_ticket_number"))
            .limit(100))


def q88(dfs):
    """Half-hour traffic counts 8:30-12:30 (TPC-DS q88: eight filtered
    counts cross-joined into one row)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    hd = (dfs["household_demographics"]
          .filter(((c("hd_dep_count") == F.lit(3))
                   & (c("hd_vehicle_count") <= F.lit(5)))
                  | ((c("hd_dep_count") == F.lit(0))
                     & (c("hd_vehicle_count") <= F.lit(2)))
                  | ((c("hd_dep_count") == F.lit(1))
                     & (c("hd_vehicle_count") <= F.lit(3))))
          .select(c("hd_demo_sk").alias("ss_hdemo_sk")))
    store = (dfs["store"].filter(c("s_store_name") == F.lit("store0"))
             .select(c("s_store_sk").alias("ss_store_sk")))
    base = (dfs["store_sales"]
            .select(c("ss_hdemo_sk"), c("ss_sold_time_sk"), c("ss_store_sk"))
            .join(hd, on="ss_hdemo_sk").join(store, on="ss_store_sk"))

    td = dfs["time_dim"]
    out = None
    for i in range(8):
        hour = 8 + (i + 1) // 2
        lo_min = 30 if i % 2 == 0 else 0
        t = (td.filter((c("t_hour") == F.lit(hour))
                       & (c("t_minute") >= F.lit(lo_min))
                       & (c("t_minute") < F.lit(lo_min + 30)))
             .select(c("t_time_sk").alias("ss_sold_time_sk")))
        cnt = (base.join(t, on="ss_sold_time_sk")
               .agg(F.count().alias(f"h{i}")))
        out = cnt if out is None else out.join(cnt, how="cross")
    return out


def q6(dfs):
    """Customer states buying items priced over 1.2x their category average
    (TPC-DS q6; the correlated avg subquery is planned as a category-average
    join, as Spark itself rewrites it)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    cat_avg = (dfs["item"]
               .group_by(c("i_category"))
               .agg(F.avg(c("i_current_price")).alias("cat_avg")))
    item = (dfs["item"]
            .select(c("i_item_sk").alias("ss_item_sk"), c("i_category"),
                    c("i_current_price"))
            .join(cat_avg, on="i_category")
            .filter(c("i_current_price") > F.lit(1.2) * c("cat_avg"))
            .select(c("ss_item_sk")))
    dd = (dfs["date_dim"]
          .filter((c("d_year") == F.lit(2000)) & (c("d_moy") == F.lit(1)))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    cust = dfs["customer"].select(
        c("c_customer_sk").alias("ss_customer_sk"),
        c("c_current_addr_sk").alias("ca_address_sk"))
    addr = dfs["customer_address"].select(c("ca_address_sk"), c("ca_state"))
    j = (dfs["store_sales"]
         .select(c("ss_sold_date_sk"), c("ss_item_sk"), c("ss_customer_sk"))
         .join(dd, on="ss_sold_date_sk").join(item, on="ss_item_sk")
         .join(cust, on="ss_customer_sk").join(addr, on="ca_address_sk"))
    return (j.group_by(c("ca_state"))
            .agg(F.count().alias("cnt"))
            .filter(c("cnt") >= F.lit(10))
            .sort(c("cnt"), c("ca_state"))
            .limit(100))


def q65(dfs):
    """Store items whose revenue is at most 10% of the store's average item
    revenue (TPC-DS q65: two aggregations joined)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    dd = (dfs["date_dim"].filter(c("d_year") == F.lit(2000))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    per_item = (dfs["store_sales"]
                .select(c("ss_sold_date_sk"), c("ss_store_sk"),
                        c("ss_item_sk"), c("ss_sales_price"))
                .join(dd, on="ss_sold_date_sk")
                .group_by(c("ss_store_sk"), c("ss_item_sk"))
                .agg(F.sum(c("ss_sales_price")).alias("revenue")))
    per_store = (per_item.group_by(c("ss_store_sk"))
                 .agg(F.avg(c("revenue")).alias("ave")))
    store = dfs["store"].select(c("s_store_sk").alias("ss_store_sk"),
                                c("s_store_name"))
    item = dfs["item"].select(c("i_item_sk").alias("ss_item_sk"),
                              c("i_item_desc"), c("i_current_price"))
    return (per_item.join(per_store, on="ss_store_sk")
            .filter(c("revenue") <= F.lit(0.1) * c("ave"))
            .join(store, on="ss_store_sk").join(item, on="ss_item_sk")
            .select(c("s_store_name"), c("i_item_desc"), c("revenue"),
                    c("i_current_price"))
            .sort(c("s_store_name"), c("i_item_desc"))
            .limit(100))


QUERIES = {"q3": q3, "q42": q42, "q52": q52, "q55": q55, "q7": q7,
           "q19": q19, "q6": q6, "q27": q27, "q34": q34, "q43": q43,
           "q46": q46, "q48": q48, "q53": q53, "q63": q63, "q65": q65,
           "q68": q68, "q73": q73, "q79": q79, "q88": q88, "q89": q89,
           "q96": q96, "q98": q98}


# -- independent NumPy oracles ------------------------------------------------

def load_np(paths: dict) -> dict:
    from spark_rapids_tpu.benchmarks.common import load_np as _load_np
    return _load_np(paths)


def _lex_top(rows, keys, ascending, limit):
    """Sort list-of-tuples rows by (key index, asc) spec, take limit."""
    import functools

    def cmp(a, b):
        for k, asc in zip(keys, ascending):
            if a[k] != b[k]:
                lt = a[k] < b[k]
                return (-1 if lt else 1) if asc else (1 if lt else -1)
        return 0
    return sorted(rows, key=functools.cmp_to_key(cmp))[:limit]


def _star_np(tb, moy, year=None):
    """Filtered fact rows: (item_sk, d_year, price) after the date join."""
    dd = tb["date_dim"]
    keep_d = dd["d_moy"] == moy
    if year is not None:
        keep_d &= dd["d_year"] == year
    year_of = dict(zip(dd["d_date_sk"][keep_d], dd["d_year"][keep_d]))
    ss = tb["store_sales"]
    out = []
    for dsk, isk, p in zip(ss["ss_sold_date_sk"], ss["ss_item_sk"],
                           ss["ss_ext_sales_price"]):
        y = year_of.get(dsk)
        if y is not None:
            out.append((isk, int(y), p))
    return out


def _rollup(tb, item_keep, moy, year, key_of):
    """Sum price grouped by (d_year, key_of(item_row)) over the star spine."""
    it = tb["item"]
    idx = {k: i for i, k in enumerate(it["i_item_sk"])}
    sums = {}
    for isk, y, p in _star_np(tb, moy, year):
        i = idx[isk]
        if not item_keep[i]:
            continue
        key = (y,) + key_of(it, i)
        sums[key] = sums.get(key, 0.0) + p
    return [key + (v,) for key, v in sums.items()]


def _brand_key(it, i):
    return (int(it["i_brand_id"][i]), it["i_brand"][i])


def np_q3(tb):
    keep = tb["item"]["i_manufact_id"] == 128
    rows = _rollup(tb, keep, 11, None, _brand_key)
    return _lex_top(rows, [0, 3, 1], [True, False, True], 100)


def np_q42(tb):
    keep = tb["item"]["i_manager_id"] == 1
    rows = _rollup(tb, keep, 11, 2000,
                   lambda it, i: (int(it["i_category_id"][i]),
                                  it["i_category"][i]))
    return _lex_top(rows, [3, 0, 1], [False, True, True], 100)


def np_q52(tb):
    keep = tb["item"]["i_manager_id"] == 1
    rows = _rollup(tb, keep, 11, 2000, _brand_key)
    return _lex_top(rows, [0, 3, 1], [True, False, True], 100)


def np_q55(tb):
    keep = tb["item"]["i_manager_id"] == 28
    rows = _rollup(tb, keep, 11, 1999, _brand_key)
    rows = [(bid, b, v) for (_y, bid, b, v) in rows]
    return _lex_top(rows, [2, 0], [False, True], 100)


def _np_demo_promo(tb, fact, dcol, icol, cdcol, prcol, qcol, lpcol,
                   cacol, spcol):
    """q7/q26 skeleton: per-item averages for single/College males on
    non-email-or-non-event promotions in year 2000."""
    cd = tb["customer_demographics"]
    cd_ok = set(cd["cd_demo_sk"][(cd["cd_gender"] == "M")
                                 & (cd["cd_marital_status"] == "S")
                                 & (cd["cd_education_status"] == "College")])
    pr = tb["promotion"]
    pr_ok = set(pr["p_promo_sk"][(pr["p_channel_email"] == "N")
                                 | (pr["p_channel_event"] == "N")])
    dd_ok = _d(tb, d_year=lambda y: y == 2000)
    it = tb["item"]
    item_id = dict(zip(it["i_item_sk"], it["i_item_id"]))
    f = tb[fact]
    acc = {}
    for cdk, prk, ddk, ik, q, lp, ca, sp in zip(
            f[cdcol], f[prcol], f[dcol], f[icol], f[qcol], f[lpcol],
            f[cacol], f[spcol]):
        if cdk in cd_ok and prk in pr_ok and ddk in dd_ok:
            a = acc.setdefault(item_id[ik], [0, 0.0, 0.0, 0.0, 0.0])
            a[0] += 1
            a[1] += q
            a[2] += lp
            a[3] += ca
            a[4] += sp
    rows = [(iid, a[1] / a[0], a[2] / a[0], a[3] / a[0], a[4] / a[0])
            for iid, a in acc.items()]
    return _lex_top(rows, [0], [True], 100)


def np_q7(tb):
    return _np_demo_promo(tb, "store_sales", "ss_sold_date_sk",
                          "ss_item_sk", "ss_cdemo_sk", "ss_promo_sk",
                          "ss_quantity", "ss_list_price", "ss_coupon_amt",
                          "ss_sales_price")


def np_q19(tb):
    dd = tb["date_dim"]
    dd_ok = set(dd["d_date_sk"][(dd["d_year"] == 1999)
                                & (dd["d_moy"] == 11)])
    it = tb["item"]
    it_info = {k: (int(b), br, int(m)) for k, b, br, m, mg in zip(
        it["i_item_sk"], it["i_brand_id"], it["i_brand"],
        it["i_manufact_id"], it["i_manager_id"]) if mg == 8}
    cu = tb["customer"]
    cust_addr = dict(zip(cu["c_customer_sk"], cu["c_current_addr_sk"]))
    ca = tb["customer_address"]
    zip_of = dict(zip(ca["ca_address_sk"], ca["ca_zip"]))
    st = tb["store"]
    szip = dict(zip(st["s_store_sk"], st["s_zip"]))
    ss = tb["store_sales"]
    sums = {}
    for ddk, ik, ck, sk, p in zip(
            ss["ss_sold_date_sk"], ss["ss_item_sk"], ss["ss_customer_sk"],
            ss["ss_store_sk"], ss["ss_ext_sales_price"]):
        if ddk not in dd_ok or ik not in it_info:
            continue
        if zip_of[cust_addr[ck]] == szip[sk]:
            continue
        key = it_info[ik]
        sums[key] = sums.get(key, 0.0) + p
    rows = [(bid, b, m, s) for (bid, b, m), s in sums.items()]
    return _lex_top(rows, [3, 0], [False, True], 100)


NP_QUERIES = {"q3": np_q3, "q42": np_q42, "q52": np_q52, "q55": np_q55,
              "q7": np_q7, "q19": np_q19, "q6": None, "q27": None,
              "q34": None, "q43": None, "q46": None, "q48": None,
              "q53": None, "q63": None, "q65": None, "q68": None,
              "q73": None, "q79": None, "q88": None, "q89": None,
              "q96": None, "q98": None}


def _late_bind_oracles():
    """The breadth oracles are defined below NP_QUERIES; bind by name."""
    for name in list(NP_QUERIES):
        if NP_QUERIES[name] is None:
            NP_QUERIES[name] = globals()[f"np_{name}"]


# -- oracles for the round-3 breadth queries ---------------------------------

def _d(tb, **conds):
    """date_dim selector: {d_date_sk} passing all column conditions."""
    dd = tb["date_dim"]
    keep = np.ones(len(dd["d_date_sk"]), bool)
    for col, fn in conds.items():
        keep &= fn(dd[col])
    return set(dd["d_date_sk"][keep])


def _window_dev(groups, part_of, thresh=0.1, zero_ok=False):
    """q53/q63/q89 tail: per-partition mean over the AGGREGATED rows, keep
    rows deviating more than `thresh` from it. groups: {key: sum}. Returns
    [(key..., sum, avg)]."""
    parts = {}
    for key, s in groups.items():
        parts.setdefault(part_of(key), []).append(s)
    means = {p: sum(v) / len(v) for p, v in parts.items()}
    out = []
    for key, s in groups.items():
        a = means[part_of(key)]
        cond = (a != 0.0) if zero_ok else (a > 0.0)
        if cond and abs(s - a) / a > thresh:
            out.append(key + (s, a))
    return out


def np_q53(tb):
    it = tb["item"]
    ok_cat = np.isin(it["i_category"], ["Books", "Home", "Electronics"])
    manu = {k: int(m) for k, m, o in zip(it["i_item_sk"], it["i_manufact_id"],
                                         ok_cat) if o}
    dd = tb["date_dim"]
    keep = dd["d_year"] == 2000
    qoy_of = dict(zip(dd["d_date_sk"][keep], dd["d_qoy"][keep]))
    ss = tb["store_sales"]
    groups = {}
    for ddk, ik, p in zip(ss["ss_sold_date_sk"], ss["ss_item_sk"],
                          ss["ss_sales_price"]):
        q = qoy_of.get(ddk)
        m = manu.get(ik)
        if q is None or m is None:
            continue
        key = (m, int(q))
        groups[key] = groups.get(key, 0.0) + p
    dev = _window_dev(groups, lambda k: k[0])
    rows = [(d[0], d[-2], d[-1]) for d in dev]
    return _lex_top(rows, [2, 1, 0], [True, True, True], 100)


def np_q63(tb):
    it = tb["item"]
    ok_cat = np.isin(it["i_category"], ["Books", "Home", "Electronics"])
    mgr = {k: int(m) for k, m, o in zip(it["i_item_sk"], it["i_manager_id"],
                                        ok_cat) if o}
    dd = tb["date_dim"]
    keep = dd["d_year"] == 2000
    moy_of = dict(zip(dd["d_date_sk"][keep], dd["d_moy"][keep]))
    ss = tb["store_sales"]
    groups = {}
    for ddk, ik, p in zip(ss["ss_sold_date_sk"], ss["ss_item_sk"],
                          ss["ss_sales_price"]):
        mo = moy_of.get(ddk)
        m = mgr.get(ik)
        if mo is None or m is None:
            continue
        key = (m, int(mo))
        groups[key] = groups.get(key, 0.0) + p
    dev = _window_dev(groups, lambda k: k[0])
    rows = [(d[0], d[-2], d[-1]) for d in dev]
    return _lex_top(rows, [0, 2, 1], [True, True, True], 100)


def np_q89(tb):
    it = tb["item"]
    ok = np.isin(it["i_category"], ["Books", "Electronics", "Sports"])
    info = {k: (cat, cl, br) for k, cat, cl, br, o in zip(
        it["i_item_sk"], it["i_category"], it["i_class"], it["i_brand"], ok)
        if o}
    dd = tb["date_dim"]
    keep = dd["d_year"] == 1999
    moy_of = dict(zip(dd["d_date_sk"][keep], dd["d_moy"][keep]))
    st = tb["store"]
    sname = dict(zip(st["s_store_sk"], st["s_store_name"]))
    ss = tb["store_sales"]
    groups = {}
    for ddk, ik, sk, p in zip(ss["ss_sold_date_sk"], ss["ss_item_sk"],
                              ss["ss_store_sk"], ss["ss_sales_price"]):
        mo = moy_of.get(ddk)
        inf = info.get(ik)
        if mo is None or inf is None:
            continue
        key = (inf[0], inf[1], inf[2], sname[sk], int(mo))
        groups[key] = groups.get(key, 0.0) + p
    dev = _window_dev(groups, lambda k: (k[0], k[2], k[3]), zero_ok=True)
    rows = [d + (d[-2] - d[-1],) for d in dev]       # append sum-avg key
    rows = _lex_top(rows, [7, 3, 1, 4], [True, True, True, True], 100)
    return [r[:-1] for r in rows]


def np_q98(tb):
    """q98 = the revenue-ratio skeleton over store_sales, no LIMIT."""
    rows = _np_revenue_ratio(tb, "store_sales", "ss_sold_date_sk",
                             "ss_item_sk", "ss_ext_sales_price", None)
    return rows


def np_q43(tb):
    ok_d = tb["date_dim"]
    keep = ok_d["d_year"] == 2000
    dow_of = dict(zip(ok_d["d_date_sk"][keep], ok_d["d_dow"][keep]))
    st = tb["store"]
    sname = dict(zip(st["s_store_sk"], st["s_store_name"]))
    ss = tb["store_sales"]
    sums = {}
    for ddk, sk, p in zip(ss["ss_sold_date_sk"], ss["ss_store_sk"],
                          ss["ss_sales_price"]):
        dow = dow_of.get(ddk)
        if dow is None:
            continue
        # Spark sum over an empty/never-hit day is NULL, not 0.0
        row = sums.setdefault(sname[sk], [None] * 7)
        row[int(dow)] = (row[int(dow)] or 0.0) + p
    rows = [(n,) + tuple(v) for n, v in sums.items()]
    return _lex_top(rows, [0], [True], 100)


def np_q96(tb):
    hd = tb["household_demographics"]
    ok_hd = set(hd["hd_demo_sk"][hd["hd_dep_count"] == 5])
    td = tb["time_dim"]
    ok_t = set(td["t_time_sk"][(td["t_hour"] == 20)
                               & (td["t_minute"] >= 30)])
    st = tb["store"]
    ok_s = set(st["s_store_sk"][st["s_store_name"] == "store0"])
    ss = tb["store_sales"]
    n = 0
    for h, t, s in zip(ss["ss_hdemo_sk"], ss["ss_sold_time_sk"],
                       ss["ss_store_sk"]):
        if h in ok_hd and t in ok_t and s in ok_s:
            n += 1
    return [(n,)]


def _np_tickets(tb, dep_lo, dep_hi, cnt_lo, cnt_hi, years):
    ok_d = _d(tb, d_year=lambda y: np.isin(y, years),
              d_dom=lambda d: ((d >= 1) & (d <= 3)) | ((d >= 25) & (d <= 28)))
    hd = tb["household_demographics"]
    ok_hd = set(hd["hd_demo_sk"][
        (hd["hd_dep_count"] >= dep_lo) & (hd["hd_dep_count"] <= dep_hi)
        & (hd["hd_buy_potential"] != "Unknown")])
    ss = tb["store_sales"]
    counts = {}
    for ddk, h, ck, tk in zip(ss["ss_sold_date_sk"], ss["ss_hdemo_sk"],
                              ss["ss_customer_sk"], ss["ss_ticket_number"]):
        if ddk in ok_d and h in ok_hd:
            key = (int(tk), int(ck))
            counts[key] = counts.get(key, 0) + 1
    cu = tb["customer"]
    fn = dict(zip(cu["c_customer_sk"], cu["c_first_name"]))
    ln = dict(zip(cu["c_customer_sk"], cu["c_last_name"]))
    return [(ln[ck], fn[ck], tk, n) for (tk, ck), n in counts.items()
            if cnt_lo <= n <= cnt_hi]


def np_q34(tb):
    rows = _np_tickets(tb, 2, 9, 15, 20, (1999, 2000, 2001))
    return _lex_top(rows, [0, 1, 2, 3], [True, True, True, False],
                    len(rows))


def np_q73(tb):
    rows = _np_tickets(tb, 1, 9, 1, 5, (1999, 2000, 2001))
    return _lex_top(rows, [3, 0, 1, 2], [False, True, True, True], 1000)


def np_q79(tb):
    from decimal import Decimal
    ok_d = _d(tb, d_dow=lambda d: d == 1,
              d_year=lambda y: np.isin(y, (1998, 1999, 2000)))
    hd = tb["household_demographics"]
    ok_hd = set(hd["hd_demo_sk"][(hd["hd_dep_count"] == 6)
                                 | (hd["hd_vehicle_count"] > 2)])
    st = tb["store"]
    ok_s = {k: c for k, c, n in zip(st["s_store_sk"], st["s_city"],
                                    st["s_number_employees"])
            if 200 <= n <= 295}
    ss = tb["store_sales"]
    sums = {}
    for ddk, h, sk, ck, tk, amt, prof in zip(
            ss["ss_sold_date_sk"], ss["ss_hdemo_sk"], ss["ss_store_sk"],
            ss["ss_customer_sk"], ss["ss_ticket_number"],
            ss["ss_coupon_amt"], ss["ss_net_profit"]):
        if ddk not in ok_d or h not in ok_hd or sk not in ok_s:
            continue
        key = (int(tk), int(ck), ok_s[sk])
        cur = sums.get(key)
        if cur is None:
            sums[key] = [amt, prof]
        else:
            cur[0] += amt
            cur[1] += prof
    cu = tb["customer"]
    fn = dict(zip(cu["c_customer_sk"], cu["c_first_name"]))
    ln = dict(zip(cu["c_customer_sk"], cu["c_last_name"]))
    rows = [(ln[ck], fn[ck], city, v[1], tk, v[0])
            for (tk, ck, city), v in sums.items()]
    return _lex_top(rows, [0, 1, 2, 3], [True, True, True, True], 100)


def np_q48(tb):
    ok_d = _d(tb, d_year=lambda y: y == 2000)
    cd = tb["customer_demographics"]
    cd_info = {k: (m, e) for k, m, e in zip(
        cd["cd_demo_sk"], cd["cd_marital_status"],
        cd["cd_education_status"])}
    ca = tb["customer_address"]
    st_of = dict(zip(ca["ca_address_sk"], ca["ca_state"]))
    ss = tb["store_sales"]
    total = 0
    for ddk, cdk, ak, q, sp, prof in zip(
            ss["ss_sold_date_sk"], ss["ss_cdemo_sk"], ss["ss_addr_sk"],
            ss["ss_quantity"], ss["ss_sales_price"], ss["ss_net_profit"]):
        if ddk not in ok_d:
            continue
        m, e = cd_info[cdk]
        p = float(sp)
        md = ((m == "M" and e == "4 yr Degree" and 100.0 <= p <= 150.0)
              or (m == "D" and e == "2 yr Degree" and 50.0 <= p <= 100.0)
              or (m == "S" and e == "College" and 150.0 <= p <= 200.0))
        if not md:
            continue
        state = st_of[ak]
        pr = float(prof)
        geo = ((state in ("CA", "TX", "OH") and 0 <= pr <= 2000)
               or (state in ("NY", "GA", "WA") and 150 <= pr <= 3000)
               or (state in ("IL", "MI") and 50 <= pr <= 25000))
        if geo:
            total += int(q)
    return [(total,)]


def np_q27(tb):
    cd = tb["customer_demographics"]
    ok_cd = set(cd["cd_demo_sk"][(cd["cd_gender"] == "F")
                                 & (cd["cd_marital_status"] == "W")
                                 & (cd["cd_education_status"] == "Primary")])
    ok_d = _d(tb, d_year=lambda y: y == 1999)
    st = tb["store"]
    s_state = {k: s for k, s in zip(st["s_store_sk"], st["s_state"])
               if s in ("CA", "TX", "NY", "OH")}
    it = tb["item"]
    iid = dict(zip(it["i_item_sk"], it["i_item_id"]))
    ss = tb["store_sales"]
    acc = {}
    for ddk, cdk, sk, ik, q, lp, cam, sp in zip(
            ss["ss_sold_date_sk"], ss["ss_cdemo_sk"], ss["ss_store_sk"],
            ss["ss_item_sk"], ss["ss_quantity"], ss["ss_list_price"],
            ss["ss_coupon_amt"], ss["ss_sales_price"]):
        if ddk not in ok_d or cdk not in ok_cd or sk not in s_state:
            continue
        key = (iid[ik], s_state[sk])
        cur = acc.setdefault(key, [0.0, 0.0, 0.0, 0.0, 0])
        cur[0] += q
        cur[1] += lp
        cur[2] += cam
        cur[3] += sp
        cur[4] += 1
    rows = [key + tuple(v / c[4] for v in c[:4])
            for key, c in acc.items()]
    return _lex_top(rows, [0, 1], [True, True], 100)


def _np_city_tickets(tb, dfilter, hd_pred, cities, val_cols):
    ok_d = dfilter
    hd = tb["household_demographics"]
    ok_hd = set(hd["hd_demo_sk"][hd_pred(hd)])
    st = tb["store"]
    ok_s = set(k for k, cty in zip(st["s_store_sk"], st["s_city"])
               if cty in cities)
    ca = tb["customer_address"]
    city_of = dict(zip(ca["ca_address_sk"], ca["ca_city"]))
    ss = tb["store_sales"]
    sums = {}
    for i, (ddk, h, sk, ak, ck, tk) in enumerate(zip(
            ss["ss_sold_date_sk"], ss["ss_hdemo_sk"], ss["ss_store_sk"],
            ss["ss_addr_sk"], ss["ss_customer_sk"],
            ss["ss_ticket_number"])):
        if ddk not in ok_d or h not in ok_hd or sk not in ok_s:
            continue
        key = (int(tk), int(ck), city_of[ak])
        cur = sums.setdefault(key, [0.0] * len(val_cols))
        for j, colname in enumerate(val_cols):
            cur[j] += ss[colname][i]
    cu = tb["customer"]
    fn = dict(zip(cu["c_customer_sk"], cu["c_first_name"]))
    ln = dict(zip(cu["c_customer_sk"], cu["c_last_name"]))
    addr_of = dict(zip(cu["c_customer_sk"], cu["c_current_addr_sk"]))
    rows = []
    for (tk, ck, bought), v in sums.items():
        home = city_of[addr_of[ck]]
        if home == bought:
            continue
        rows.append((ln[ck], fn[ck], home, bought, tk) + tuple(v))
    return rows


def np_q46(tb):
    ok_d = _d(tb, d_dow=lambda d: np.isin(d, (0, 6)),
              d_year=lambda y: np.isin(y, (1999, 2000, 2001)))
    rows = _np_city_tickets(
        tb, ok_d,
        lambda hd: (hd["hd_dep_count"] == 5) | (hd["hd_vehicle_count"] == 3),
        ("Midway", "Fairview", "Oakland"),
        ["ss_coupon_amt", "ss_ext_sales_price"])
    return _lex_top(rows, [0, 1, 2, 3, 4], [True] * 5, 100)


def np_q68(tb):
    ok_d = _d(tb, d_dom=lambda d: (d >= 1) & (d <= 2),
              d_year=lambda y: np.isin(y, (1998, 1999, 2000)))
    rows = _np_city_tickets(
        tb, ok_d,
        lambda hd: (hd["hd_dep_count"] == 4) | (hd["hd_vehicle_count"] == 3),
        ("Midway", "Fairview"),
        ["ss_ext_sales_price", "ss_ext_tax", "ss_ext_list_price"])
    return _lex_top(rows, [0, 4], [True, True], 100)


def np_q88(tb):
    hd = tb["household_demographics"]
    ok_hd = set(hd["hd_demo_sk"][
        ((hd["hd_dep_count"] == 3) & (hd["hd_vehicle_count"] <= 5))
        | ((hd["hd_dep_count"] == 0) & (hd["hd_vehicle_count"] <= 2))
        | ((hd["hd_dep_count"] == 1) & (hd["hd_vehicle_count"] <= 3))])
    st = tb["store"]
    ok_s = set(st["s_store_sk"][st["s_store_name"] == "store0"])
    td = tb["time_dim"]
    hour_of = dict(zip(td["t_time_sk"],
                       zip(td["t_hour"], td["t_minute"])))
    counts = [0] * 8
    ss = tb["store_sales"]
    for h, t, s in zip(ss["ss_hdemo_sk"], ss["ss_sold_time_sk"],
                       ss["ss_store_sk"]):
        if h not in ok_hd or s not in ok_s:
            continue
        hh, mm = hour_of[t]
        for i in range(8):
            hour = 8 + (i + 1) // 2
            lo = 30 if i % 2 == 0 else 0
            if hh == hour and lo <= mm < lo + 30:
                counts[i] += 1
                break
    return [tuple(counts)]


def np_q6(tb):
    it = tb["item"]
    cat_sums = {}
    for cat, p in zip(it["i_category"], it["i_current_price"]):
        cur = cat_sums.setdefault(cat, [0.0, 0])
        cur[0] += p
        cur[1] += 1
    cat_avg = {c: s / n for c, (s, n) in cat_sums.items()}
    ok_item = set(
        k for k, cat, p in zip(it["i_item_sk"], it["i_category"],
                               it["i_current_price"])
        if p > 1.2 * cat_avg[cat])
    ok_d = _d(tb, d_year=lambda y: y == 2000, d_moy=lambda m: m == 1)
    cu = tb["customer"]
    addr_of = dict(zip(cu["c_customer_sk"], cu["c_current_addr_sk"]))
    ca = tb["customer_address"]
    state_of = dict(zip(ca["ca_address_sk"], ca["ca_state"]))
    ss = tb["store_sales"]
    counts = {}
    for ddk, ik, ck in zip(ss["ss_sold_date_sk"], ss["ss_item_sk"],
                           ss["ss_customer_sk"]):
        if ddk not in ok_d or ik not in ok_item:
            continue
        s = state_of[addr_of[ck]]
        counts[s] = counts.get(s, 0) + 1
    rows = [(s, n) for s, n in counts.items() if n >= 10]
    return _lex_top(rows, [1, 0], [True, True], 100)


def np_q65(tb):
    ok_d = _d(tb, d_year=lambda y: y == 2000)
    ss = tb["store_sales"]
    rev = {}
    for ddk, sk, ik, p in zip(ss["ss_sold_date_sk"], ss["ss_store_sk"],
                              ss["ss_item_sk"], ss["ss_sales_price"]):
        if ddk not in ok_d:
            continue
        key = (int(sk), int(ik))
        rev[key] = rev.get(key, 0.0) + p
    per_store = {}
    for (sk, ik), r in rev.items():
        cur = per_store.setdefault(sk, [0.0, 0])
        cur[0] += r
        cur[1] += 1
    ave = {sk: s / n for sk, (s, n) in per_store.items()}
    st = tb["store"]
    sname = dict(zip(st["s_store_sk"], st["s_store_name"]))
    it = tb["item"]
    idesc = dict(zip(it["i_item_sk"], it["i_item_desc"]))
    iprice = dict(zip(it["i_item_sk"], it["i_current_price"]))
    rows = [(sname[sk], idesc[ik], r, iprice[ik])
            for (sk, ik), r in rev.items() if r <= 0.1 * ave[sk]]
    return _lex_top(rows, [0, 1], [True, True], 100)


_late_bind_oracles()


# Per-query float-tolerance column indexes shared by the test suite and
# bench.py's recorded sweep: both must count a query "ok" under VALUE equality
# (exact on keys/ints, rel-1e-9 on float slots) — row-count alone overstated
# verification in BENCH_r03 (VERDICT r3 weak #3).
FLOAT_COLS = {
    "q3": {3}, "q42": {3}, "q52": {3}, "q55": {2}, "q7": {1, 2, 3, 4},
    "q19": {3}, "q6": set(), "q27": {2, 3, 4, 5}, "q34": set(),
    "q43": {1, 2, 3, 4, 5, 6, 7}, "q46": {5, 6}, "q48": set(),
    "q53": {1, 2}, "q63": {1, 2}, "q65": {2, 3}, "q68": {5, 6, 7},
    "q73": set(), "q79": {5}, "q88": set(), "q89": {5, 6}, "q96": set(),
    "q98": {4, 5, 6},
}


def check_rows(got, exp, float_cols, rel=1e-9):
    """Value-equality check (no pytest dependency). Raises AssertionError with
    the first mismatching row pair. Explicit raises (not bare asserts): the
    exception IS the contract, and must survive `python -O`."""
    import math as _math
    if len(got) != len(exp):
        raise AssertionError((len(got), len(exp)))
    for g, e in zip(got, exp):
        if len(g) != len(e):
            raise AssertionError((g, e))
        for i, (a, b) in enumerate(zip(g, e)):
            if i in float_cols and a is not None and b is not None:
                if not _math.isclose(a, b, rel_tol=rel, abs_tol=1e-12):
                    raise AssertionError((g, e))
            elif a != b:   # exact slot, or a NULL in a float slot
                raise AssertionError((g, e))


def np_q27_rollup(tb):
    """Official q27 shape: GROUP BY ROLLUP (i_item_id, s_state) with
    grouping(s_state), ordered nulls-first asc (Spark default)."""
    cd = tb["customer_demographics"]
    ok_cd = set(cd["cd_demo_sk"][(cd["cd_gender"] == "F")
                                 & (cd["cd_marital_status"] == "W")
                                 & (cd["cd_education_status"] == "Primary")])
    ok_d = _d(tb, d_year=lambda y: y == 1999)
    st = tb["store"]
    s_state = {k: s for k, s in zip(st["s_store_sk"], st["s_state"])
               if s in ("CA", "TX", "NY", "OH")}
    it = tb["item"]
    iid = dict(zip(it["i_item_sk"], it["i_item_id"]))
    ss = tb["store_sales"]
    acc = {}
    for ddk, cdk, sk, ik, q, lp, cam, sp in zip(
            ss["ss_sold_date_sk"], ss["ss_cdemo_sk"], ss["ss_store_sk"],
            ss["ss_item_sk"], ss["ss_quantity"], ss["ss_list_price"],
            ss["ss_coupon_amt"], ss["ss_sales_price"]):
        if ddk not in ok_d or cdk not in ok_cd or sk not in s_state:
            continue
        for key, g in (((iid[ik], s_state[sk]), 0),
                       ((iid[ik], None), 1), ((None, None), 3)):
            cur = acc.setdefault((key, g), [0.0, 0.0, 0.0, 0.0, 0])
            cur[0] += q
            cur[1] += lp
            cur[2] += cam
            cur[3] += sp
            cur[4] += 1
    rows = [(k[0], k[1], g & 1) + tuple(v / c[4] for v in c[:4])
            for (k, g), c in acc.items()]
    # asc with nulls first on (i_item_id, s_state)
    rows.sort(key=lambda r: ((r[0] is not None, r[0] or ""),
                             (r[1] is not None, r[1] or "")))
    return rows[:100]


def np_q13(tb):
    """Official q13 (SQL-only; states fitted to the generator domain)."""
    ok_d = _d(tb, d_year=lambda y: y == 2001)
    cd = tb["customer_demographics"]
    cd_ms = dict(zip(cd["cd_demo_sk"], cd["cd_marital_status"]))
    cd_ed = dict(zip(cd["cd_demo_sk"], cd["cd_education_status"]))
    hd = tb["household_demographics"]
    hd_dep = dict(zip(hd["hd_demo_sk"], hd["hd_dep_count"]))
    ca = tb["customer_address"]
    ca_st = {k: s for k, s, c in zip(ca["ca_address_sk"], ca["ca_state"],
                                     ca["ca_country"])
             if c == "United States"}
    ss = tb["store_sales"]
    n = cnt = 0
    sq = sp = sw = 0.0
    st_tab = tb["store"]
    ok_s = set(st_tab["s_store_sk"])
    for ddk, sk2, cdk, hdk, ak, q, spr, esp, ewc, npf in zip(
            ss["ss_sold_date_sk"], ss["ss_store_sk"], ss["ss_cdemo_sk"],
            ss["ss_hdemo_sk"], ss["ss_addr_sk"], ss["ss_quantity"],
            ss["ss_sales_price"], ss["ss_ext_sales_price"],
            ss["ss_ext_wholesale_cost"], ss["ss_net_profit"]):
        if ddk not in ok_d or sk2 not in ok_s:
            continue
        ms, ed, dep = cd_ms.get(cdk), cd_ed.get(cdk), hd_dep.get(hdk)
        demo = ((ms == "M" and ed == "Advanced Degree"
                 and 100.0 <= spr <= 200.0 and dep == 3)
                or (ms == "S" and ed == "College"
                    and 50.0 <= spr <= 150.0 and dep == 1)
                or (ms == "W" and ed == "2 yr Degree"
                    and 1.0 <= spr <= 100.0 and dep == 1))
        if not demo:
            continue
        st = ca_st.get(ak)
        prof = float(npf)
        geo = ((st in ("CA", "TX", "OH") and 0 <= prof <= 2000)
               or (st in ("NY", "GA", "WA") and 150 <= prof <= 3000)
               or (st in ("IL", "MI", "CA") and 50 <= prof <= 2500))
        if not geo:
            continue
        cnt += 1
        sq += int(q)
        sp += float(esp)
        sw += float(ewc)
    if cnt == 0:
        return []   # loud vacuity (the test asserts a non-empty oracle)
    return [(sq / cnt, sp / cnt, sw / cnt, sw)]


def np_q36(tb):
    """Official q36: gross-margin rollup over (i_category, i_class) with
    rank-within-parent (SQL-only)."""
    ok_d = _d(tb, d_year=lambda y: y == 2001)
    it = tb["item"]
    icat = dict(zip(it["i_item_sk"], it["i_category"]))
    icls = dict(zip(it["i_item_sk"], it["i_class"]))
    st = tb["store"]
    ok_s = set(st["s_store_sk"])     # all 8 generator states pass the filter
    ss = tb["store_sales"]
    acc = {}
    for ddk, ik, sk2, npf, esp in zip(
            ss["ss_sold_date_sk"], ss["ss_item_sk"], ss["ss_store_sk"],
            ss["ss_net_profit"], ss["ss_ext_sales_price"]):
        if ddk not in ok_d or sk2 not in ok_s:
            continue
        for key in ((icat[ik], icls[ik]), (icat[ik], None), (None, None)):
            cur = acc.setdefault(key, [0.0, 0.0])
            cur[0] += float(npf)
            cur[1] += float(esp)
    rows = []
    for (cat, cls), (np_s, sp_s) in acc.items():
        loch = (0 if cls is not None else 1 if cat is not None else 2)
        rows.append([np_s / sp_s, cat, cls, loch])
    # rank within (lochierarchy, parent category) by margin asc
    from collections import defaultdict
    parts = defaultdict(list)
    for r in rows:
        parts[(r[3], r[1] if r[3] == 0 else None)].append(r)
    for rs in parts.values():
        rs.sort(key=lambda r: r[0])
        rank, prev = 0, None
        for i, r in enumerate(rs):
            if prev is None or r[0] != prev:
                rank = i + 1
            r.append(rank)
            prev = r[0]
    def skey(r):
        margin, cat, cls, loch, rk = r
        case_cat = cat if loch == 0 else None
        return (-loch,
                (0, "") if case_cat is None else (1, case_cat),
                rk,
                (0, "") if cat is None else (1, cat),
                (0, "") if cls is None else (1, cls))
    rows.sort(key=skey)
    return [tuple(r) for r in rows[:100]]


def np_q28(tb):
    """q28 oracle: six list-price buckets (avg / count / count distinct of
    ss_list_price under quantity + price/coupon/wholesale disjunctions),
    cross-joined into one row. Official default substitution parameters."""
    ss = tb["store_sales"]
    lp = ss["ss_list_price"]
    qty = ss["ss_quantity"]
    cp = ss["ss_coupon_amt"]
    wc = ss["ss_wholesale_cost"]
    params = [(0, 5, 8, 459, 57), (6, 10, 90, 2323, 31),
              (11, 15, 142, 12214, 79), (16, 20, 135, 6071, 38),
              (21, 25, 122, 836, 17), (26, 30, 154, 7326, 7)]
    row = []
    for qlo, qhi, lp0, cp0, wc0 in params:
        m = ((qty >= qlo) & (qty <= qhi)
             & (((lp >= lp0) & (lp <= lp0 + 10))
                | ((cp >= cp0) & (cp <= cp0 + 1000))
                | ((wc >= wc0) & (wc <= wc0 + 20))))
        vals = lp[m]
        row.append(float(vals.mean()) if len(vals) else None)
        row.append(int(len(vals)))
        row.append(int(len(np.unique(vals))))
    return [tuple(row)]


def _names_dates(tb, fact, date_col, cust_col, lo=1200, hi=1211):
    """{(c_last_name, c_first_name, d_date)} for one sales channel within a
    d_month_seq window — the q38/q87 arm."""
    dd = tb["date_dim"]
    sel = (dd["d_month_seq"] >= lo) & (dd["d_month_seq"] <= hi)
    dmap = dict(zip(dd["d_date_sk"][sel].tolist(),
                    dd["d_date"][sel].tolist()))
    cu = tb["customer"]
    fn = dict(zip(cu["c_customer_sk"], cu["c_first_name"]))
    ln = dict(zip(cu["c_customer_sk"], cu["c_last_name"]))
    f = tb[fact]
    out = set()
    for dk, ck in zip(f[date_col].tolist(), f[cust_col].tolist()):
        d = dmap.get(dk)
        if d is not None:
            out.add((ln[ck], fn[ck], d))
    return out


def np_q38(tb):
    s = (_names_dates(tb, "store_sales", "ss_sold_date_sk", "ss_customer_sk")
         & _names_dates(tb, "catalog_sales", "cs_sold_date_sk",
                        "cs_bill_customer_sk")
         & _names_dates(tb, "web_sales", "ws_sold_date_sk",
                        "ws_bill_customer_sk"))
    return [(len(s),)]


def np_q87(tb):
    s = (_names_dates(tb, "store_sales", "ss_sold_date_sk", "ss_customer_sk")
         - _names_dates(tb, "catalog_sales", "cs_sold_date_sk",
                        "cs_bill_customer_sk")
         - _names_dates(tb, "web_sales", "ws_sold_date_sk",
                        "ws_bill_customer_sk"))
    return [(len(s),)]


_Q8_ZIPS = {"10000", "10005", "10010", "10015", "10020", "10025", "10030",
            "10035", "10040", "10045", "10050", "10055", "10060", "10065",
            "10070", "10075", "10080", "10085", "10090", "10095"}


def np_q8(tb):
    """Official q8: store net profit for stores whose 2-digit zip prefix
    matches a V1 zip — V1 = (literal zip list) INTERSECT (zips with > 4
    preferred customers). The inner join against V1 multiplies each sale by
    the number of matching V1 zips (official semantics)."""
    from collections import Counter
    ca, cu, st = tb["customer_address"], tb["customer"], tb["store"]
    z1 = {z for z in ca["ca_zip"] if z in _Q8_ZIPS}
    azip = dict(zip(ca["ca_address_sk"], ca["ca_zip"]))
    pref = cu["c_preferred_cust_flag"] == "Y"
    cnt = Counter(azip[a] for a in cu["c_current_addr_sk"][pref].tolist())
    v1 = z1 & {z for z, n in cnt.items() if n > 4}
    ok_d = _d(tb, d_qoy=lambda q: q == 2, d_year=lambda y: y == 1998)
    mult = {sk: sum(1 for z in v1 if z[:2] == zp[:2])
            for sk, zp in zip(st["s_store_sk"], st["s_zip"])}
    name = dict(zip(st["s_store_sk"], st["s_store_name"]))
    ss = tb["store_sales"]
    sums = {}
    for dk, sk, prof in zip(ss["ss_sold_date_sk"], ss["ss_store_sk"],
                            ss["ss_net_profit"]):
        m = mult.get(sk, 0)
        if dk not in ok_d or not m:
            continue
        key = name[sk]
        sums[key] = sums.get(key, 0) + prof * m
    return [(k, sums[k]) for k in sorted(sums)][:100]


def np_q14(tb):
    """Official q14 (iceberg, first variant): cross_items = items whose
    (brand, class, category) sold in ALL THREE channels in 1999-2001
    (INTERSECT), avg_sales = global q*lp mean over the channels (UNION ALL),
    per-channel Nov-2001 group sums over cross_items with an iceberg HAVING
    against avg_sales, then ROLLUP over (channel, brand, class, category)."""
    it = tb["item"]
    trip = {sk: (int(b), int(cl), int(ca)) for sk, b, cl, ca in zip(
        it["i_item_sk"], it["i_brand_id"], it["i_class_id"],
        it["i_category_id"])}
    ok_d = _d(tb, d_year=lambda y: (y >= 1999) & (y <= 2001))
    chans = [
        ("store", "store_sales", "ss_sold_date_sk", "ss_item_sk",
         "ss_quantity", "ss_list_price"),
        ("catalog", "catalog_sales", "cs_sold_date_sk", "cs_item_sk",
         "cs_quantity", "cs_list_price"),
        ("web", "web_sales", "ws_sold_date_sk", "ws_item_sk",
         "ws_quantity", "ws_list_price"),
    ]
    trips_sold, tot, n_all = [], 0.0, 0
    for _, t, dcol, icol, qcol, pcol in chans:
        f = tb[t]
        m = np.isin(f[dcol], list(ok_d))
        trips_sold.append({trip[sk] for sk in f[icol][m].tolist()})
        qp = f[qcol][m].astype(np.float64) * f[pcol][m]
        tot += float(qp.sum())
        n_all += len(qp)
    cross_trips = trips_sold[0] & trips_sold[1] & trips_sold[2]
    cross_sk = {sk for sk, tr in trip.items() if tr in cross_trips}
    avg_sales = tot / n_all
    ok_d2 = _d(tb, d_year=lambda y: y == 2001, d_moy=lambda m_: m_ == 11)
    base = []
    for ch, t, dcol, icol, qcol, pcol in chans:
        f = tb[t]
        groups = {}
        for dk, sk, q, p in zip(f[dcol].tolist(), f[icol].tolist(),
                                f[qcol].tolist(), f[pcol].tolist()):
            if dk in ok_d2 and sk in cross_sk:
                cur = groups.setdefault(trip[sk], [0.0, 0])
                cur[0] += q * p
                cur[1] += 1
        for g, (s, n) in groups.items():
            if s > avg_sales:
                base.append((ch, g[0], g[1], g[2], s, n))
    agg = {}
    for ch, b, cl, ca, s, n in base:
        for lvl in range(5):          # rollup levels (), (ch), ... (all 4)
            key = tuple(v if i < lvl else None
                        for i, v in enumerate((ch, b, cl, ca)))
            cur = agg.setdefault(key, [0.0, 0])
            cur[0] += s
            cur[1] += n
    rows = [k + (v[0], v[1]) for k, v in agg.items()]
    rows.sort(key=lambda r: tuple((x is not None, x) for x in r[:4]))
    return rows[:100]


_Q15_ZIPS = {"10005", "10010", "10020", "10035", "10040", "10055", "10070",
             "10085", "10090"}


def np_q15(tb):
    """Official q15: catalog sales by customer zip — zip-list OR state OR
    high-price disjunction, Q2/2001."""
    cu, ca, cs = tb["customer"], tb["customer_address"], tb["catalog_sales"]
    azip = dict(zip(ca["ca_address_sk"], ca["ca_zip"]))
    astate = dict(zip(ca["ca_address_sk"], ca["ca_state"]))
    caddr = dict(zip(cu["c_customer_sk"], cu["c_current_addr_sk"]))
    ok_d = _d(tb, d_qoy=lambda q: q == 2, d_year=lambda y: y == 2001)
    sums = {}
    for dk, ck, p in zip(cs["cs_sold_date_sk"], cs["cs_bill_customer_sk"],
                         cs["cs_sales_price"]):
        if dk not in ok_d:
            continue
        a = caddr[ck]
        z, st = azip[a], astate[a]
        if z in _Q15_ZIPS or st in ("CA", "WA", "GA") or p > 150:
            sums[z] = sums.get(z, 0.0) + p
    return [(z, sums[z]) for z in sorted(sums)][:100]


def np_q45(tb):
    """Official q45: web sales by (zip, city) — zip-list OR item-id-subquery
    disjunction, Q2/2001."""
    cu, ca, ws, it = (tb["customer"], tb["customer_address"],
                      tb["web_sales"], tb["item"])
    azip = dict(zip(ca["ca_address_sk"], ca["ca_zip"]))
    acity = dict(zip(ca["ca_address_sk"], ca["ca_city"]))
    caddr = dict(zip(cu["c_customer_sk"], cu["c_current_addr_sk"]))
    iid = dict(zip(it["i_item_sk"], it["i_item_id"]))
    want_ids = {iid[k] for k in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)
                if k in iid}
    ok_d = _d(tb, d_qoy=lambda q: q == 2, d_year=lambda y: y == 2001)
    sums = {}
    for dk, ck, ik, p in zip(ws["ws_sold_date_sk"],
                             ws["ws_bill_customer_sk"], ws["ws_item_sk"],
                             ws["ws_sales_price"]):
        if dk not in ok_d:
            continue
        a = caddr[ck]
        z = azip[a]
        if z in _Q15_ZIPS or iid[ik] in want_ids:
            key = (z, acity[a])
            sums[key] = sums.get(key, 0.0) + p
    return [k + (sums[k],) for k in sorted(sums)][:100]


def np_q61(tb):
    """Official q61: promoted vs total Books revenue at gmt -6, Nov 2000;
    output (promotions, total, 100*promotions/total as decimal)."""
    from decimal import Decimal, ROUND_HALF_UP
    ss, st, pr, cu, ca, it = (tb["store_sales"], tb["store"],
                              tb["promotion"], tb["customer"],
                              tb["customer_address"], tb["item"])
    ok_d = _d(tb, d_year=lambda y: y == 2000, d_moy=lambda m: m == 11)
    ok_s = set(st["s_store_sk"][st["s_gmt_offset"] == -6.0])
    ok_ca = set(ca["ca_address_sk"][ca["ca_gmt_offset"] == -6.0])
    ok_i = set(it["i_item_sk"][it["i_category"] == "Books"])
    ok_p = set(pr["p_promo_sk"][(pr["p_channel_dmail"] == "Y")
                                | (pr["p_channel_email"] == "Y")
                                | (pr["p_channel_tv"] == "Y")])
    caddr = dict(zip(cu["c_customer_sk"], cu["c_current_addr_sk"]))
    promo = total = 0.0
    for dk, sk, pk, ck, ik, v in zip(
            ss["ss_sold_date_sk"], ss["ss_store_sk"], ss["ss_promo_sk"],
            ss["ss_customer_sk"], ss["ss_item_sk"],
            ss["ss_ext_sales_price"]):
        if dk not in ok_d or sk not in ok_s or ik not in ok_i \
                or caddr[ck] not in ok_ca:
            continue
        total += v
        if pk in ok_p:
            promo += v
    # cast(double as decimal(15,4)) twice, then (15,4)/(15,4) -> the
    # engine's DECIMAL64-adjusted (18,6) HALF_UP division, then *100 at
    # the same scale (docs/compatibility.md decimal arithmetic rules);
    # Spark: sum over an empty relation is NULL
    if total == 0.0:
        return [(None, None, None)]
    li = int(Decimal(repr(float(promo))).scaleb(4)
             .to_integral_value(ROUND_HALF_UP))
    ri = int(Decimal(repr(float(total))).scaleb(4)
             .to_integral_value(ROUND_HALF_UP))
    import math as _m
    q = float(li) / float(ri) * 1e6
    vals = int(_m.floor(q + 0.5) if q >= 0 else _m.ceil(q - 0.5))
    ratio = Decimal(vals * 100).scaleb(-6)
    return [(float(promo), float(total), ratio)]


def np_q97(tb):
    """Official q97: distinct (customer, item) pairs per channel over the
    month window; full-outer overlap counts."""
    lo, hi = 1200, 1211
    dd = tb["date_dim"]
    ok_d = set(dd["d_date_sk"][(dd["d_month_seq"] >= lo)
                               & (dd["d_month_seq"] <= hi)])
    ss, cs = tb["store_sales"], tb["catalog_sales"]
    s = {(c, i) for d, c, i in zip(ss["ss_sold_date_sk"],
                                   ss["ss_customer_sk"], ss["ss_item_sk"])
         if d in ok_d}
    c = {(cc, i) for d, cc, i in zip(cs["cs_sold_date_sk"],
                                     cs["cs_bill_customer_sk"],
                                     cs["cs_item_sk"]) if d in ok_d}
    return [(len(s - c), len(c - s), len(s & c))]


def _np_three_channel(tb, key_col, key_filter_col, key_filter_vals,
                      year, moy):
    """q33/q56 skeleton: per-channel sums by an item attribute, restricted
    to items whose `key_filter_col` is in `key_filter_vals` and buyers at
    gmt -5, summed across channels."""
    it, ca = tb["item"], tb["customer_address"]
    keep_keys = {k for k, v in zip(it[key_col], it[key_filter_col])
                 if v in key_filter_vals}
    attr = {sk: k for sk, k in zip(it["i_item_sk"], it[key_col])}
    ok_ca = set(ca["ca_address_sk"][ca["ca_gmt_offset"] == -5.0])
    ok_d = _d(tb, d_year=lambda y_: y_ == year, d_moy=lambda m: m == moy)
    chans = [("store_sales", "ss_sold_date_sk", "ss_item_sk", "ss_addr_sk",
              "ss_ext_sales_price"),
             ("catalog_sales", "cs_sold_date_sk", "cs_item_sk",
              "cs_bill_addr_sk", "cs_ext_sales_price"),
             ("web_sales", "ws_sold_date_sk", "ws_item_sk",
              "ws_bill_addr_sk", "ws_ext_sales_price")]
    sums = {}
    for t, dcol, icol, acol, vcol in chans:
        f = tb[t]
        for dk, ik, ak, v in zip(f[dcol], f[icol], f[acol], f[vcol]):
            k = attr[ik]
            if dk in ok_d and ak in ok_ca and k in keep_keys:
                sums[k] = sums.get(k, 0.0) + v
    rows = sorted(((k, s) for k, s in sums.items()),
                  key=lambda r: (r[1], r[0]))
    return rows[:100]


def np_q33(tb):
    """Official q33: Electronics manufacturers across the three channels."""
    return _np_three_channel(tb, "i_manufact_id", "i_category",
                             {"Electronics"}, 1998, 5)


def np_q56(tb):
    """Official q56: slate/blanched/burnished item ids across channels."""
    return _np_three_channel(tb, "i_item_id", "i_color",
                             {"slate", "blanched", "burnished"}, 2001, 2)


def _np_revenue_ratio(tb, fact, dcol, icol, vcol, limit):
    """q98/q12/q20 skeleton: item revenue + class-partition revenue ratio."""
    it = tb["item"]
    ok = np.isin(it["i_category"], ["Sports", "Books", "Home"])
    info = {k: (iid, d, cat, cl, float(p)) for k, iid, d, cat, cl, p, o in
            zip(it["i_item_sk"], it["i_item_id"], it["i_item_desc"],
                it["i_category"], it["i_class"], it["i_current_price"], ok)
            if o}
    ok_d = _d(tb, d_year=lambda y: y == 1999, d_moy=lambda m: m == 2)
    f = tb[fact]
    groups = {}
    for ddk, ik, p in zip(f[dcol], f[icol], f[vcol]):
        inf = info.get(ik)
        if ddk not in ok_d or inf is None:
            continue
        groups[inf] = groups.get(inf, 0.0) + p
    cls_total = {}
    for key, s in groups.items():
        cls_total[key[3]] = cls_total.get(key[3], 0.0) + s
    rows = [key + (s, s * 100.0 / cls_total[key[3]])
            for key, s in groups.items()]
    return _lex_top(rows, [2, 3, 0, 1, 6],
                    [True, True, True, True, True], limit)


def np_q12(tb):
    """Official q12: q98's revenue-ratio shape over web_sales."""
    return _np_revenue_ratio(tb, "web_sales", "ws_sold_date_sk",
                             "ws_item_sk", "ws_ext_sales_price", 100)


def np_q20(tb):
    """Official q20: q98's revenue-ratio shape over catalog_sales."""
    return _np_revenue_ratio(tb, "catalog_sales", "cs_sold_date_sk",
                             "cs_item_sk", "cs_ext_sales_price", 100)


def np_q26(tb):
    """Official q26: q7's demographics/promotion shape over catalog_sales."""
    return _np_demo_promo(tb, "catalog_sales", "cs_sold_date_sk",
                          "cs_item_sk", "cs_bill_cdemo_sk", "cs_promo_sk",
                          "cs_quantity", "cs_list_price", "cs_coupon_amt",
                          "cs_sales_price")


def sql_suite_oracles():
    """{name: (oracle_fn, float_cols)} for every official SQL text in
    sql/tpcds_queries.py — shared by tests/test_sql_tpcds.py and bench.py's
    SQL-suite sweep (reference qa_nightly_sql.py role). Most queries reuse
    the DataFrame suite's oracles; the SQL-only ones have their own."""
    sql_only = {
        "q13": (np_q13, {0, 1, 2, 3}),
        "q36": (np_q36, {0}),
        "q27": (np_q27_rollup, {3, 4, 5, 6}),
        "q28": (np_q28, {0, 3, 6, 9, 12, 15}),
        "q8": (np_q8, set()),
        "q38": (np_q38, set()),
        "q87": (np_q87, set()),
        "q14": (np_q14, {4}),
        "q15": (np_q15, {1}),
        "q45": (np_q45, {2}),
        "q61": (np_q61, {0, 1, 2}),
        "q97": (np_q97, set()),
        "q33": (np_q33, {1}),
        "q56": (np_q56, {1}),
        "q12": (np_q12, {4, 5, 6}),
        "q20": (np_q20, {4, 5, 6}),
        "q26": (np_q26, {1, 2, 3, 4}),
        # q18: exact decimal averages (engine-mirrored int arithmetic)
        "q18": (np_q18, set()),
        # q69: EXISTS + two NOT EXISTS over the three channels
        "q69": (np_q69, set()),
        # q22: inventory rollup; qoh average is float
        "q22": (np_q22, {4}),
    }
    from spark_rapids_tpu.sql.tpcds_queries import SQL_QUERIES
    out = {}
    for name in SQL_QUERIES:
        if name in sql_only:
            out[name] = sql_only[name]
        else:
            out[name] = (NP_QUERIES[name], FLOAT_COLS[name])
    return out


def np_q18(tb):
    """Official q18: 7 decimal averages over catalog buyers (female,
    education Unknown, birth-month set) rolled up over
    (item, country, state, county). Mirrors the engine's exact integer
    decimal arithmetic: each value casts to decimal(12,2) via float64
    HALF_UP (expr/cast.py float->decimal), sums stay int, and the average
    divides at +4 scale with integer HALF_UP (expr/aggregates.Average)."""
    import math as _m
    from decimal import Decimal

    def to_cents(v):                      # cast(x as decimal(12,2)) mirror
        scaled = float(v) * 100.0
        r = _m.floor(abs(scaled) + 0.5)
        return -r if scaled < 0 else r

    cd = tb["customer_demographics"]
    cd_ok = {k: int(dep) for k, g, e, dep in zip(
        cd["cd_demo_sk"], cd["cd_gender"], cd["cd_education_status"],
        cd["cd_dep_count"]) if g == "F" and e == "Unknown"}
    cu = tb["customer"]
    c_info = {k: (int(by), int(bm), int(ad)) for k, by, bm, ad in zip(
        cu["c_customer_sk"], cu["c_birth_year"], cu["c_birth_month"],
        cu["c_current_addr_sk"])}
    ca = tb["customer_address"]
    ca_info = {k: (co, st, cty) for k, co, st, cty in zip(
        ca["ca_address_sk"], ca["ca_country"], ca["ca_state"],
        ca["ca_county"])}
    states = {"CA", "TX", "NY", "GA", "OH", "WA"}
    months = {1, 6, 8, 9, 12, 2}
    ok_d = _d(tb, d_year=lambda y: y == 1998)
    iid_col = tb["item"]["i_item_id"]       # dense sks from 1
    cs = tb["catalog_sales"]
    acc = {}
    for dk, ik, cdk, ck, q, lp, cam, sp, npf in zip(
            cs["cs_sold_date_sk"], cs["cs_item_sk"],
            cs["cs_bill_cdemo_sk"], cs["cs_bill_customer_sk"],
            cs["cs_quantity"], cs["cs_list_price"], cs["cs_coupon_amt"],
            cs["cs_sales_price"], cs["cs_net_profit"]):
        dep = cd_ok.get(cdk)
        if dk not in ok_d or dep is None:
            continue
        by, bm, ad = c_info[ck]
        if bm not in months:
            continue
        country, state, county = ca_info[ad]
        if state not in states:
            continue
        iid = iid_col[ik - 1]
        vals = [to_cents(q), to_cents(lp), to_cents(cam), to_cents(sp),
                to_cents(npf), to_cents(by), to_cents(dep)]
        full = (iid, country, state, county)
        for lvl in range(5):                    # rollup levels
            key = tuple(v if i < lvl else None
                        for i, v in enumerate(full))
            a = acc.setdefault(key, [0] + [0] * 7)
            a[0] += 1
            for j, v in enumerate(vals):
                a[1 + j] += v
    rows = []
    for key, a in acc.items():
        cnt = a[0]
        avgs = []
        for j in range(7):                      # engine decimal avg mirror
            num = a[1 + j] * 10 ** 4
            qm = (abs(num) + cnt // 2) // cnt
            avgs.append(Decimal(-qm if num < 0 else qm).scaleb(-6))
        rows.append(key + tuple(avgs))
    rows.sort(key=lambda r: tuple((v is not None, v) for v in
                                  (r[1], r[2], r[3], r[0])))
    return rows[:100]


def np_q69(tb):
    """Official q69: demographics of customers (in-state) who bought in
    store but neither web nor catalog in Q2-2001 (EXISTS + two NOT
    EXISTS). cs_bill_customer_sk substitutes cs_ship_customer_sk (subset
    schema, header rule 2)."""
    dd_ok = _d(tb, d_year=lambda y: y == 2001,
               d_moy=lambda m: (m >= 4) & (m <= 6))

    def buyers(fact, dcol, ccol):
        f = tb[fact]
        return {c for d, c in zip(f[dcol], f[ccol]) if d in dd_ok}
    ss_b = buyers("store_sales", "ss_sold_date_sk", "ss_customer_sk")
    ws_b = buyers("web_sales", "ws_sold_date_sk", "ws_bill_customer_sk")
    cs_b = buyers("catalog_sales", "cs_sold_date_sk",
                  "cs_bill_customer_sk")
    ca = tb["customer_address"]
    ok_ca = set(ca["ca_address_sk"][np.isin(ca["ca_state"],
                                            ["CA", "TX", "NY"])])
    cd = tb["customer_demographics"]
    cd_info = {k: (g, m, e, int(pe), cr) for k, g, m, e, pe, cr in zip(
        cd["cd_demo_sk"], cd["cd_gender"], cd["cd_marital_status"],
        cd["cd_education_status"], cd["cd_purchase_estimate"],
        cd["cd_credit_rating"])}
    cu = tb["customer"]
    counts = {}
    for ck, ad, cdk in zip(cu["c_customer_sk"], cu["c_current_addr_sk"],
                           cu["c_current_cdemo_sk"]):
        if ad not in ok_ca or ck not in ss_b or ck in ws_b or ck in cs_b:
            continue
        g, m, e, pe, cr = cd_info[cdk]
        key = (g, m, e, pe, cr)
        counts[key] = counts.get(key, 0) + 1
    rows = [(g, m, e, n, pe, n, cr, n)
            for (g, m, e, pe, cr), n in counts.items()]
    rows.sort(key=lambda r: (r[0], r[1], r[2], r[4], r[6]))
    return rows[:100]


def np_q22(tb):
    """Official q22: average quantity on hand rolled up over the item
    hierarchy for a 12-month-seq window (i_item_id substitutes
    i_product_name — subset schema, header rule 2)."""
    dd = tb["date_dim"]
    ok_d = set(dd["d_date_sk"][(dd["d_month_seq"] >= 1200)
                               & (dd["d_month_seq"] <= 1211)])
    it = tb["item"]
    info = {k: (iid, b, cl, ca) for k, iid, b, cl, ca in zip(
        it["i_item_sk"], it["i_item_id"], it["i_brand"], it["i_class"],
        it["i_category"])}
    inv = tb["inventory"]
    acc = {}
    for dk, ik, q in zip(inv["inv_date_sk"], inv["inv_item_sk"],
                         inv["inv_quantity_on_hand"]):
        if dk not in ok_d:
            continue
        full = info[ik]
        for lvl in range(5):
            key = tuple(v if i < lvl else None
                        for i, v in enumerate(full))
            a = acc.setdefault(key, [0, 0])
            a[0] += 1
            a[1] += int(q)
    rows = [key + (a[1] / a[0],) for key, a in acc.items()]
    rows.sort(key=lambda r: (r[4],) + tuple((v is not None, v)
                                            for v in r[:4]))
    return rows[:100]
