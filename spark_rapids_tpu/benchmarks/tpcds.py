"""TPC-DS subset benchmark: deterministic generator, star-join queries via
the session API, and independent single-core NumPy oracles.

Reference role: BASELINE.md config-3 (TPC-DS 10-query subset with the
accelerated shuffle over ICI) and config-5 (full sweep); the reference's
own nightly runs the analogous qa_nightly_select_test.py sweep
(integration_tests). Queries follow the official TPC-DS text restricted to
this schema subset: q3, q42, q52, q55 (date×item star aggregates), q7
(demographics + promotion), q19 (brand revenue where customer and store
zips differ).

The generator is pure vectorized numpy with dense surrogate keys; group
cardinalities and join selectivities track the spec closely enough for
kernel benchmarking (same design stance as benchmarks/tpch.py).
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

N_DATES = 366 * 5            # 1998..2002
FIRST_YEAR = 1998
CATEGORIES = ["Home", "Books", "Electronics", "Music", "Sports", "Shoes",
              "Jewelry", "Men", "Women", "Children"]
GENDERS = ["M", "F"]
MARITAL = ["M", "S", "D", "W", "U"]
EDUCATION = ["Primary", "Secondary", "College", "2 yr Degree", "4 yr Degree",
             "Advanced Degree", "Unknown"]


def generate(sf: float, outdir: str, files_per_table: int = 4) -> dict:
    """Generate the subset at scale factor `sf` (SF1 ≈ 2.9M store_sales).
    Returns {table: dir}. Idempotent per table."""
    os.makedirs(outdir, exist_ok=True)
    rng = np.random.default_rng(20260730)
    n_ss = int(2_880_000 * sf)
    n_item = max(int(18_000 * sf), 2000)
    n_cust = max(int(100_000 * sf), 100)
    n_addr = max(n_cust // 2, 50)
    n_store = max(int(12 * max(sf, 1)), 2)
    n_cd = 7 * 5 * 2 * 4     # education x marital x gender x dep buckets
    n_promo = max(int(300 * sf), 10)

    paths = {}

    def write(name, table, nfiles=files_per_table):
        from spark_rapids_tpu.benchmarks.common import write_partitioned
        write_partitioned(outdir, name, table, nfiles, paths)

    # date_dim: one row per day, d_date_sk dense from 1
    sk = np.arange(1, N_DATES + 1, dtype=np.int64)
    doy = (sk - 1) % 366
    write("date_dim", pa.table({
        "d_date_sk": pa.array(sk),
        "d_year": pa.array((FIRST_YEAR + (sk - 1) // 366).astype(np.int32)),
        "d_moy": pa.array((doy // 31 + 1).astype(np.int32)),
        "d_dom": pa.array((doy % 31 + 1).astype(np.int32)),
    }), 1)

    # item
    isk = np.arange(1, n_item + 1, dtype=np.int64)
    cat_id = rng.integers(0, len(CATEGORIES), n_item)
    brand_id = (cat_id + 1) * 1000 + rng.integers(1, 100, n_item)
    write("item", pa.table({
        "i_item_sk": pa.array(isk),
        "i_item_id": pa.array([f"ITEM{k:08d}" for k in isk]),
        "i_brand_id": pa.array(brand_id.astype(np.int32)),
        "i_brand": pa.array([f"brand#{b}" for b in brand_id]),
        "i_category_id": pa.array((cat_id + 1).astype(np.int32)),
        "i_category": pa.array(np.array(CATEGORIES)[cat_id]),
        "i_manufact_id": pa.array(
            rng.integers(1, 140, n_item).astype(np.int32)),
        "i_manager_id": pa.array(
            rng.integers(1, 100, n_item).astype(np.int32)),
    }), 1)

    # customer_demographics: full cross of the filter dimensions
    cd_sk = np.arange(1, n_cd + 1, dtype=np.int64)
    write("customer_demographics", pa.table({
        "cd_demo_sk": pa.array(cd_sk),
        "cd_gender": pa.array(np.array(GENDERS)[(cd_sk - 1) % 2]),
        "cd_marital_status": pa.array(
            np.array(MARITAL)[((cd_sk - 1) // 2) % 5]),
        "cd_education_status": pa.array(
            np.array(EDUCATION)[((cd_sk - 1) // 10) % 7]),
    }), 1)

    # promotion
    psk = np.arange(1, n_promo + 1, dtype=np.int64)
    write("promotion", pa.table({
        "p_promo_sk": pa.array(psk),
        "p_channel_email": pa.array(
            np.where(rng.random(n_promo) < 0.5, "N", "Y")),
        "p_channel_event": pa.array(
            np.where(rng.random(n_promo) < 0.5, "N", "Y")),
    }), 1)

    # customer_address / store (zips overlap so q19's <> filter selects)
    zips = rng.integers(10000, 10100, n_addr)
    write("customer_address", pa.table({
        "ca_address_sk": pa.array(np.arange(1, n_addr + 1, dtype=np.int64)),
        "ca_zip": pa.array([f"{z:05d}" for z in zips]),
    }), 1)
    szips = rng.integers(10000, 10100, n_store)
    write("store", pa.table({
        "s_store_sk": pa.array(np.arange(1, n_store + 1, dtype=np.int64)),
        "s_store_name": pa.array([f"store{k}" for k in range(n_store)]),
        "s_zip": pa.array([f"{z:05d}" for z in szips]),
    }), 1)

    # customer
    write("customer", pa.table({
        "c_customer_sk": pa.array(np.arange(1, n_cust + 1, dtype=np.int64)),
        "c_current_addr_sk": pa.array(
            rng.integers(1, n_addr + 1, n_cust).astype(np.int64)),
    }), 1)

    # store_sales (fact)
    write("store_sales", pa.table({
        "ss_sold_date_sk": pa.array(
            rng.integers(1, N_DATES + 1, n_ss).astype(np.int64)),
        "ss_item_sk": pa.array(
            rng.integers(1, n_item + 1, n_ss).astype(np.int64)),
        "ss_customer_sk": pa.array(
            rng.integers(1, n_cust + 1, n_ss).astype(np.int64)),
        "ss_cdemo_sk": pa.array(
            rng.integers(1, n_cd + 1, n_ss).astype(np.int64)),
        "ss_promo_sk": pa.array(
            rng.integers(1, n_promo + 1, n_ss).astype(np.int64)),
        "ss_store_sk": pa.array(
            rng.integers(1, n_store + 1, n_ss).astype(np.int64)),
        "ss_quantity": pa.array(
            rng.integers(1, 100, n_ss).astype(np.int32)),
        "ss_list_price": pa.array(
            np.round(rng.uniform(1.0, 200.0, n_ss), 2)),
        "ss_sales_price": pa.array(
            np.round(rng.uniform(1.0, 200.0, n_ss), 2)),
        "ss_ext_sales_price": pa.array(
            np.round(rng.uniform(1.0, 20000.0, n_ss), 2)),
        "ss_coupon_amt": pa.array(
            np.round(rng.uniform(0.0, 50.0, n_ss), 2)),
    }))
    return paths


def load(spark, paths: dict, files_per_partition: int = 2) -> dict:
    from spark_rapids_tpu.benchmarks.common import load as _load
    return _load(spark, paths, files_per_partition)


# -- queries (session API; official TPC-DS text over this subset) -------------

def _star(dfs, moy, year=None):
    """store_sales ⋈ date_dim ⋈ item — the q3/q42/q52/q55 spine. q3 filters
    only the month (it groups by d_year); the others pin one year too."""
    import spark_rapids_tpu.functions as F
    c = F.col
    cond = c("d_moy") == F.lit(moy)
    if year is not None:
        cond = (c("d_year") == F.lit(year)) & cond
    dd = (dfs["date_dim"].filter(cond)
          .select(c("d_date_sk").alias("ss_sold_date_sk"), c("d_year")))
    return (dfs["store_sales"]
            .select(c("ss_sold_date_sk"), c("ss_item_sk"),
                    c("ss_ext_sales_price"))
            .join(dd, on="ss_sold_date_sk")
            .select(c("ss_item_sk").alias("i_item_sk"), c("d_year"),
                    c("ss_ext_sales_price")))


def q3(dfs):
    """Brand revenue by year for manufacturer 128 in November (official
    TPC-DS q3: d_moy = 11 and i_manufact_id = 128, grouped by d_year)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"].filter(c("i_manufact_id") == F.lit(128))
            .select(c("i_item_sk"), c("i_brand_id"), c("i_brand")))
    j = _star(dfs, 11).join(item, on="i_item_sk")
    return (j.group_by(c("d_year"), c("i_brand_id"), c("i_brand"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("sum_agg"))
            .sort(c("d_year"), c("sum_agg"), c("i_brand_id"),
                  ascending=[True, False, True])
            .limit(100))


def q42(dfs):
    """Category revenue for one manager's items, one month (official TPC-DS
    q42: i_manager_id = 1, d_year = 2000, d_moy = 11)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"].filter(c("i_manager_id") == F.lit(1))
            .select(c("i_item_sk"), c("i_category_id"), c("i_category")))
    j = _star(dfs, 11, 2000).join(item, on="i_item_sk")
    return (j.group_by(c("d_year"), c("i_category_id"), c("i_category"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("sum_agg"))
            .sort(c("sum_agg"), c("d_year"), c("i_category_id"),
                  ascending=[False, True, True])
            .limit(100))


def q52(dfs):
    """Brand revenue for one manager's items, one month (official TPC-DS
    q52: i_manager_id = 1, d_year = 2000, d_moy = 11)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"].filter(c("i_manager_id") == F.lit(1))
            .select(c("i_item_sk"), c("i_brand_id"), c("i_brand")))
    j = _star(dfs, 11, 2000).join(item, on="i_item_sk")
    return (j.group_by(c("d_year"), c("i_brand_id"), c("i_brand"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("ext_price"))
            .sort(c("d_year"), c("ext_price"), c("i_brand_id"),
                  ascending=[True, False, True])
            .limit(100))


def q55(dfs):
    """Brand revenue for one manager's items, one month (TPC-DS q55)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    item = (dfs["item"].filter(c("i_manager_id") == F.lit(28))
            .select(c("i_item_sk"), c("i_brand_id"), c("i_brand")))
    j = _star(dfs, 11, 1999).join(item, on="i_item_sk")
    return (j.group_by(c("i_brand_id"), c("i_brand"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("ext_price"))
            .sort(c("ext_price"), c("i_brand_id"), ascending=[False, True])
            .limit(100))


def q7(dfs):
    """Average quantities for one demographic + non-event promos (TPC-DS q7)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    cd = (dfs["customer_demographics"]
          .filter((c("cd_gender") == F.lit("M"))
                  & (c("cd_marital_status") == F.lit("S"))
                  & (c("cd_education_status") == F.lit("College")))
          .select(c("cd_demo_sk").alias("ss_cdemo_sk")))
    promo = (dfs["promotion"]
             .filter((c("p_channel_email") == F.lit("N"))
                     | (c("p_channel_event") == F.lit("N")))
             .select(c("p_promo_sk").alias("ss_promo_sk")))
    dd = (dfs["date_dim"].filter(c("d_year") == F.lit(2000))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    item = dfs["item"].select(c("i_item_sk").alias("ss_item_sk"),
                              c("i_item_id"))
    j = (dfs["store_sales"]
         .join(cd, on="ss_cdemo_sk")
         .join(promo, on="ss_promo_sk")
         .join(dd, on="ss_sold_date_sk")
         .join(item, on="ss_item_sk"))
    return (j.group_by(c("i_item_id"))
            .agg(F.avg(c("ss_quantity")).alias("agg1"),
                 F.avg(c("ss_list_price")).alias("agg2"),
                 F.avg(c("ss_coupon_amt")).alias("agg3"),
                 F.avg(c("ss_sales_price")).alias("agg4"))
            .sort(c("i_item_id"))
            .limit(100))


def q19(dfs):
    """Brand revenue where customer zip differs from store zip (TPC-DS q19)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    dd = (dfs["date_dim"]
          .filter((c("d_year") == F.lit(1999)) & (c("d_moy") == F.lit(11)))
          .select(c("d_date_sk").alias("ss_sold_date_sk")))
    item = (dfs["item"].filter(c("i_manager_id") == F.lit(8))
            .select(c("i_item_sk").alias("ss_item_sk"), c("i_brand_id"),
                    c("i_brand"), c("i_manufact_id")))
    cust = dfs["customer"].select(c("c_customer_sk").alias("ss_customer_sk"),
                                  c("c_current_addr_sk").alias("ca_address_sk"))
    addr = dfs["customer_address"].select(c("ca_address_sk"), c("ca_zip"))
    store = dfs["store"].select(c("s_store_sk").alias("ss_store_sk"),
                                c("s_zip"))
    j = (dfs["store_sales"]
         .select(c("ss_sold_date_sk"), c("ss_item_sk"), c("ss_customer_sk"),
                 c("ss_store_sk"), c("ss_ext_sales_price"))
         .join(dd, on="ss_sold_date_sk")
         .join(item, on="ss_item_sk")
         .join(cust, on="ss_customer_sk")
         .join(addr, on="ca_address_sk")
         .join(store, on="ss_store_sk")
         .filter(c("ca_zip") != c("s_zip")))
    return (j.group_by(c("i_brand_id"), c("i_brand"), c("i_manufact_id"))
            .agg(F.sum(c("ss_ext_sales_price")).alias("ext_price"))
            .sort(c("ext_price"), c("i_brand_id"), ascending=[False, True])
            .limit(100))


QUERIES = {"q3": q3, "q42": q42, "q52": q52, "q55": q55, "q7": q7, "q19": q19}


# -- independent NumPy oracles ------------------------------------------------

def load_np(paths: dict) -> dict:
    from spark_rapids_tpu.benchmarks.common import load_np as _load_np
    return _load_np(paths)


def _lex_top(rows, keys, ascending, limit):
    """Sort list-of-tuples rows by (key index, asc) spec, take limit."""
    import functools

    def cmp(a, b):
        for k, asc in zip(keys, ascending):
            if a[k] != b[k]:
                lt = a[k] < b[k]
                return (-1 if lt else 1) if asc else (1 if lt else -1)
        return 0
    return sorted(rows, key=functools.cmp_to_key(cmp))[:limit]


def _star_np(tb, moy, year=None):
    """Filtered fact rows: (item_sk, d_year, price) after the date join."""
    dd = tb["date_dim"]
    keep_d = dd["d_moy"] == moy
    if year is not None:
        keep_d &= dd["d_year"] == year
    year_of = dict(zip(dd["d_date_sk"][keep_d], dd["d_year"][keep_d]))
    ss = tb["store_sales"]
    out = []
    for dsk, isk, p in zip(ss["ss_sold_date_sk"], ss["ss_item_sk"],
                           ss["ss_ext_sales_price"]):
        y = year_of.get(dsk)
        if y is not None:
            out.append((isk, int(y), p))
    return out


def _rollup(tb, item_keep, moy, year, key_of):
    """Sum price grouped by (d_year, key_of(item_row)) over the star spine."""
    it = tb["item"]
    idx = {k: i for i, k in enumerate(it["i_item_sk"])}
    sums = {}
    for isk, y, p in _star_np(tb, moy, year):
        i = idx[isk]
        if not item_keep[i]:
            continue
        key = (y,) + key_of(it, i)
        sums[key] = sums.get(key, 0.0) + p
    return [key + (v,) for key, v in sums.items()]


def _brand_key(it, i):
    return (int(it["i_brand_id"][i]), it["i_brand"][i])


def np_q3(tb):
    keep = tb["item"]["i_manufact_id"] == 128
    rows = _rollup(tb, keep, 11, None, _brand_key)
    return _lex_top(rows, [0, 3, 1], [True, False, True], 100)


def np_q42(tb):
    keep = tb["item"]["i_manager_id"] == 1
    rows = _rollup(tb, keep, 11, 2000,
                   lambda it, i: (int(it["i_category_id"][i]),
                                  it["i_category"][i]))
    return _lex_top(rows, [3, 0, 1], [False, True, True], 100)


def np_q52(tb):
    keep = tb["item"]["i_manager_id"] == 1
    rows = _rollup(tb, keep, 11, 2000, _brand_key)
    return _lex_top(rows, [0, 3, 1], [True, False, True], 100)


def np_q55(tb):
    keep = tb["item"]["i_manager_id"] == 28
    rows = _rollup(tb, keep, 11, 1999, _brand_key)
    rows = [(bid, b, v) for (_y, bid, b, v) in rows]
    return _lex_top(rows, [2, 0], [False, True], 100)


def np_q7(tb):
    cd = tb["customer_demographics"]
    cd_ok = set(cd["cd_demo_sk"][(cd["cd_gender"] == "M")
                                 & (cd["cd_marital_status"] == "S")
                                 & (cd["cd_education_status"] == "College")])
    pr = tb["promotion"]
    pr_ok = set(pr["p_promo_sk"][(pr["p_channel_email"] == "N")
                                 | (pr["p_channel_event"] == "N")])
    dd = tb["date_dim"]
    dd_ok = set(dd["d_date_sk"][dd["d_year"] == 2000])
    it = tb["item"]
    item_id = {k: v for k, v in zip(it["i_item_sk"], it["i_item_id"])}
    ss = tb["store_sales"]
    acc = {}
    for cdk, prk, ddk, ik, q, lp, ca, sp in zip(
            ss["ss_cdemo_sk"], ss["ss_promo_sk"], ss["ss_sold_date_sk"],
            ss["ss_item_sk"], ss["ss_quantity"], ss["ss_list_price"],
            ss["ss_coupon_amt"], ss["ss_sales_price"]):
        if cdk in cd_ok and prk in pr_ok and ddk in dd_ok:
            a = acc.setdefault(item_id[ik], [0, 0.0, 0.0, 0.0, 0.0])
            a[0] += 1
            a[1] += q
            a[2] += lp
            a[3] += ca
            a[4] += sp
    rows = [(iid, a[1] / a[0], a[2] / a[0], a[3] / a[0], a[4] / a[0])
            for iid, a in acc.items()]
    return _lex_top(rows, [0], [True], 100)


def np_q19(tb):
    dd = tb["date_dim"]
    dd_ok = set(dd["d_date_sk"][(dd["d_year"] == 1999)
                                & (dd["d_moy"] == 11)])
    it = tb["item"]
    it_info = {k: (int(b), br, int(m)) for k, b, br, m, mg in zip(
        it["i_item_sk"], it["i_brand_id"], it["i_brand"],
        it["i_manufact_id"], it["i_manager_id"]) if mg == 8}
    cu = tb["customer"]
    cust_addr = dict(zip(cu["c_customer_sk"], cu["c_current_addr_sk"]))
    ca = tb["customer_address"]
    zip_of = dict(zip(ca["ca_address_sk"], ca["ca_zip"]))
    st = tb["store"]
    szip = dict(zip(st["s_store_sk"], st["s_zip"]))
    ss = tb["store_sales"]
    sums = {}
    for ddk, ik, ck, sk, p in zip(
            ss["ss_sold_date_sk"], ss["ss_item_sk"], ss["ss_customer_sk"],
            ss["ss_store_sk"], ss["ss_ext_sales_price"]):
        if ddk not in dd_ok or ik not in it_info:
            continue
        if zip_of[cust_addr[ck]] == szip[sk]:
            continue
        key = it_info[ik]
        sums[key] = sums.get(key, 0.0) + p
    rows = [(bid, b, m, s) for (bid, b, m), s in sums.items()]
    return _lex_top(rows, [3, 0], [False, True], 100)


NP_QUERIES = {"q3": np_q3, "q42": np_q42, "q52": np_q52, "q55": np_q55,
              "q7": np_q7, "q19": np_q19}
