"""Shared helpers for the benchmark data generators/loaders (tpch, tpcds)."""

from __future__ import annotations

import os

import pyarrow as pa
import pyarrow.parquet as pq


def write_partitioned(outdir: str, name: str, table: pa.Table,
                      nfiles: int, paths: dict) -> None:
    """Write `table` as `nfiles` parquet parts under outdir/name; idempotent
    (skips a table directory that already holds parquet parts)."""
    d = os.path.join(outdir, name)
    paths[name] = d
    if os.path.isdir(d):
        parts = sorted(f for f in os.listdir(d) if f.endswith(".parquet"))
        if parts:
            # schema-evolution guard: a generator that grew a column or
            # changed a dtype must regenerate stale cached dirs, not
            # silently serve the old shape
            old = pq.read_schema(os.path.join(d, parts[0]))
            if old.equals(table.schema):
                return
            for f in parts:
                os.unlink(os.path.join(d, f))
    os.makedirs(d, exist_ok=True)
    n = table.num_rows
    per = max((n + nfiles - 1) // nfiles, 1)
    for i in range(max(nfiles, 1)):
        sl = table.slice(i * per, per)
        if sl.num_rows == 0 and i > 0:
            break
        pq.write_table(sl, os.path.join(d, f"part-{i:04d}.parquet"))


def load(spark, paths: dict, files_per_partition: int = 2) -> dict:
    dfs = {name: spark.read_parquet(p,
                                    files_per_partition=files_per_partition)
           for name, p in paths.items()}
    for name, df in dfs.items():     # make the tables visible to session.sql
        spark.create_or_replace_temp_view(name, df)
    return dfs


def read_np(path, columns=None):
    """Read a table dir/file into {col: np.ndarray}; date32 → epoch-day i32."""
    t = pq.read_table(path, columns=columns)
    out = {}
    for name in t.column_names:
        col = t.column(name)
        if pa.types.is_date32(col.type):
            out[name] = col.cast(pa.int32()).to_numpy()
        else:
            out[name] = col.to_numpy(zero_copy_only=False)
    return out


def load_np(paths: dict) -> dict:
    return {name: read_np(p) for name, p in paths.items()}
