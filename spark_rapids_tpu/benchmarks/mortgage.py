"""Mortgage ETL benchmark app — the reference's real-dataset workload shape.

Reference: integration_tests/src/main/scala/com/nvidia/spark/rapids/tests/
mortgage/MortgageSpark.scala:23 — the FannieMae single-family loan ETL the
reference ships as its end-to-end application benchmark: read pipe-delimited
acquisition + performance CSVs with explicit schemas, derive per-loan
ever-delinquent flags from the performance records, join with acquisition,
project features, and write parquet. No public dataset is reachable from
this environment, so the generator produces FannieMae-SHAPED data (same
columns/delimiters/cardinalities the ETL exercises) and a NumPy oracle
checks the pipeline end to end — the same stance as the TPC generators.

Pipeline (etl): csv scan ×2 → filter/parse → group-by (max delinquency,
ever_30/90/180) → equi-join → categorical features → summary aggregate →
optional parquet write.
"""

from __future__ import annotations

import os

import numpy as np

CHANNELS = ["R", "C", "B"]
SELLERS = ["BANK OF AMER", "WELLS FARGO", "QUICKEN", "OTHER", "PENNYMAC"]
STATES = ["CA", "TX", "NY", "FL", "IL", "OH", "WA", "GA"]


def generate(sf: float, outdir: str) -> dict:
    """FannieMae-shaped pipe-delimited CSVs. SF1 ≈ 200k loans / 2.4M
    performance rows (the real dataset is ~wider; the ETL's join/group
    shapes are what matter). Idempotent."""
    os.makedirs(outdir, exist_ok=True)
    acq_path = os.path.join(outdir, "acq.csv")
    perf_path = os.path.join(outdir, "perf.csv")
    paths = {"acquisition": acq_path, "performance": perf_path}
    if os.path.exists(acq_path) and os.path.exists(perf_path):
        return paths
    rng = np.random.default_rng(20260731)
    n_loans = max(int(200_000 * sf), 200)

    loan_id = np.arange(100000000, 100000000 + n_loans, dtype=np.int64)
    channel = rng.integers(0, len(CHANNELS), n_loans)
    seller = rng.integers(0, len(SELLERS), n_loans)
    rate = np.round(rng.uniform(2.5, 7.5, n_loans), 3)
    upb = rng.integers(50, 800, n_loans) * 1000
    term = rng.choice([180, 240, 360], n_loans)
    ltv = rng.integers(40, 98, n_loans)
    dti = rng.integers(10, 50, n_loans)
    score = rng.integers(580, 840, n_loans)
    state = rng.integers(0, len(STATES), n_loans)

    def _lines(cols):
        # vectorized '|' join (row-by-row f.write was ~10x slower at SF1)
        parts = [np.asarray(c).astype(str) for c in cols]
        out = parts[0]
        for p_ in parts[1:]:
            out = np.char.add(np.char.add(out, "|"), p_)
        return "\n".join(out.tolist()) + "\n"

    with open(acq_path, "w") as f:
        f.write("loan_id|orig_channel|seller_name|orig_interest_rate|"
                "orig_upb|orig_loan_term|orig_ltv|dti|"
                "borrower_credit_score|property_state\n")
        f.write(_lines([loan_id, np.array(CHANNELS)[channel],
                        np.array(SELLERS)[seller], rate, upb, term,
                        ltv, dti, score, np.array(STATES)[state]]))

    # performance: ~12 monthly rows per loan; delinquency status is a
    # string ("00".."06", "X" for unknown — the real feed's quirk)
    per_loan = rng.integers(6, 19, n_loans)
    p_loan = np.repeat(loan_id, per_loan)
    n_perf = len(p_loan)
    age = np.concatenate([np.arange(k) for k in per_loan]).astype(np.int64)
    cur_upb = np.round(np.repeat(upb, per_loan)
                       * (1.0 - 0.002 * age) , 2)
    # delinquency: mostly current, some loans go 30/90/180+ days late
    base = rng.random(n_loans)
    max_dq = np.where(base < 0.80, 0,
                      np.where(base < 0.92, 1,
                               np.where(base < 0.97, 3, 6)))
    dq = np.minimum(rng.integers(0, 7, n_perf),
                    np.repeat(max_dq, per_loan))
    dq_str = np.where(rng.random(n_perf) < 0.002, "X",
                      np.char.zfill(dq.astype(str), 2))
    with open(perf_path, "w") as f:
        f.write("loan_id|loan_age|current_actual_upb|"
                "current_loan_delinquency_status\n")
        f.write(_lines([p_loan, age, cur_upb, dq_str]))
    return paths


ACQ_SCHEMA = [
    ("loan_id", "long"), ("orig_channel", "string"),
    ("seller_name", "string"), ("orig_interest_rate", "double"),
    ("orig_upb", "long"), ("orig_loan_term", "int"), ("orig_ltv", "int"),
    ("dti", "int"), ("borrower_credit_score", "int"),
    ("property_state", "string"),
]
PERF_SCHEMA = [
    ("loan_id", "long"), ("loan_age", "int"),
    ("current_actual_upb", "double"),
    ("current_loan_delinquency_status", "string"),
]


def _schema(spec):
    from spark_rapids_tpu import types as T
    m = {"long": T.LONG, "int": T.INT, "double": T.DOUBLE,
         "string": T.STRING}
    return T.StructType([T.StructField(n, m[t], True) for n, t in spec])


def etl(spark, paths: dict, write_dir: str | None = None):
    """The MortgageSpark ETL shape on the session API; returns the summary
    DataFrame (and optionally writes the joined feature table as parquet)."""
    import spark_rapids_tpu.functions as F
    c = F.col
    acq = spark.read_csv(paths["acquisition"], schema=_schema(ACQ_SCHEMA),
                         delimiter="|")
    perf = spark.read_csv(paths["performance"], schema=_schema(PERF_SCHEMA),
                          delimiter="|")
    # parse delinquency: "XX" strings -> int, "X" (unknown) -> -1
    dq = F.if_(c("current_loan_delinquency_status") == F.lit("X"),
               F.lit(-1),
               F.cast(c("current_loan_delinquency_status"), _int()))
    flags = (perf
             .select(c("loan_id"), c("current_actual_upb"),
                     dq.alias("dq"))
             .group_by(c("loan_id"))
             .agg(F.max(c("dq")).alias("max_dq"),
                  F.min(c("current_actual_upb")).alias("min_upb")))
    ever30 = F.cast(c("max_dq") >= F.lit(1), _int()).alias("ever_30")
    ever90 = F.cast(c("max_dq") >= F.lit(3), _int()).alias("ever_90")
    ever180 = F.cast(c("max_dq") >= F.lit(6), _int()).alias("ever_180")
    joined = (acq.join(flags, on="loan_id")
              .select(c("loan_id"), c("orig_channel"), c("seller_name"),
                      c("orig_interest_rate"), c("orig_upb"),
                      c("borrower_credit_score"), c("property_state"),
                      c("max_dq"), c("min_upb"), ever30, ever90, ever180))
    if write_dir is not None:
        joined.write_parquet(write_dir, mode="overwrite")
    return (joined
            .group_by(c("orig_channel"))
            .agg(F.count().alias("loans"),
                 F.sum(c("ever_30")).alias("n30"),
                 F.sum(c("ever_90")).alias("n90"),
                 F.sum(c("ever_180")).alias("n180"),
                 F.avg(c("orig_interest_rate")).alias("avg_rate"),
                 F.sum(c("orig_upb")).alias("total_upb"))
            .sort(c("orig_channel")))


def _int():
    from spark_rapids_tpu import types as T
    return T.INT


def np_oracle(paths: dict):
    """Independent single-pass oracle over the raw CSV text."""
    import csv
    dq_max: dict = {}
    with open(paths["performance"]) as f:
        rd = csv.reader(f, delimiter="|")
        next(rd)
        for lid, _age, _upb, s in rd:
            d = -1 if s == "X" else int(s)
            k = int(lid)
            if d > dq_max.get(k, -10**9):
                dq_max[k] = d
    acc: dict = {}
    with open(paths["acquisition"]) as f:
        rd = csv.reader(f, delimiter="|")
        next(rd)
        for row in rd:
            lid, ch = int(row[0]), row[1]
            if lid not in dq_max:
                continue
            m = dq_max[lid]
            a = acc.setdefault(ch, [0, 0, 0, 0, 0.0, 0])
            a[0] += 1
            a[1] += 1 if m >= 1 else 0
            a[2] += 1 if m >= 3 else 0
            a[3] += 1 if m >= 6 else 0
            a[4] += float(row[3])
            a[5] += int(row[4])
    return [(ch, a[0], a[1], a[2], a[3], a[4] / a[0], a[5])
            for ch, a in sorted(acc.items())]
