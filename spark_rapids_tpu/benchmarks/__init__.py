"""Benchmark applications (reference integration_tests/.../mortgage/Benchmarks.scala
role: runnable end-to-end workloads with external oracles)."""
