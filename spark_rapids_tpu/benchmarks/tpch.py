"""TPC-H benchmark: deterministic data generator, q1/q3/q5 via the session
API, and independent single-core NumPy oracles.

Reference role: integration_tests mortgage app + BASELINE.md config-2 (TPC-H
SF>=0.1 q1/q3/q5 — scan+filter+agg+join on one TPU VM). The NumPy oracles are
the "CPU Spark" stand-in for vs_baseline AND the correctness check: bench runs
refuse to report a time for a wrong answer.

Data layout follows dbgen's schema subset needed by q1/q3/q5; keys are dense
(1..n) rather than dbgen's sparse permutations — join selectivity and group
cardinalities match the spec closely enough for kernel benchmarking, and the
generator is pure vectorized numpy (SF0.1 ≈ 600k lineitem rows in ~1s).
"""

from __future__ import annotations

import datetime
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq


EPOCH = datetime.date(1970, 1, 1)


def _days(y, m, d):
    return (datetime.date(y, m, d) - EPOCH).days


START = _days(1992, 1, 1)
END = _days(1998, 8, 2)

NATIONS = ["ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
           "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ", "JAPAN",
           "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU", "CHINA",
           "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA", "UNITED KINGDOM",
           "UNITED STATES"]
NATION_REGION = [0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4, 2, 4, 0, 0, 0, 1, 2, 3,
                 4, 2, 3, 3, 1]
REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY", "HOUSEHOLD"]


def generate(sf: float, outdir: str, files_per_table: int = 4) -> dict:
    """Generate the q1/q3/q5 table subset at scale factor `sf` as parquet.
    Returns {table: path}. Idempotent: skips tables already on disk."""
    os.makedirs(outdir, exist_ok=True)
    rng = np.random.default_rng(20260729)
    n_orders = int(1_500_000 * sf)
    n_cust = max(int(150_000 * sf), 1)
    n_supp = max(int(10_000 * sf), 1)

    paths = {}

    def write(name, table, nfiles=files_per_table):
        from spark_rapids_tpu.benchmarks.common import write_partitioned
        write_partitioned(outdir, name, table, nfiles, paths)

    # customer
    write("customer", pa.table({
        "c_custkey": pa.array(np.arange(1, n_cust + 1, dtype=np.int64)),
        "c_mktsegment": pa.array(
            np.array(SEGMENTS)[rng.integers(0, 5, n_cust)]),
        "c_nationkey": pa.array(rng.integers(0, 25, n_cust).astype(np.int32)),
    }), 1)

    # supplier
    write("supplier", pa.table({
        "s_suppkey": pa.array(np.arange(1, n_supp + 1, dtype=np.int64)),
        "s_nationkey": pa.array(rng.integers(0, 25, n_supp).astype(np.int32)),
    }), 1)

    # nation / region
    write("nation", pa.table({
        "n_nationkey": pa.array(np.arange(25, dtype=np.int32)),
        "n_name": pa.array(NATIONS),
        "n_regionkey": pa.array(np.array(NATION_REGION, dtype=np.int32)),
    }), 1)
    write("region", pa.table({
        "r_regionkey": pa.array(np.arange(5, dtype=np.int32)),
        "r_name": pa.array(REGIONS),
    }), 1)

    # orders. o_totalprice (q18) is DERIVED from o_orderkey, not rng-drawn:
    # inserting an rng draw here would shift every later lineitem draw and
    # silently desync cached lineitem dirs from regenerated orders dirs
    # (write() only regenerates on schema change).
    o_orderkey = np.arange(1, n_orders + 1, dtype=np.int64)
    o_orderdate = rng.integers(START, END - 150, n_orders).astype(np.int32)
    orders = pa.table({
        "o_orderkey": pa.array(o_orderkey),
        "o_custkey": pa.array(
            rng.integers(1, n_cust + 1, n_orders).astype(np.int64)),
        "o_orderdate": pa.array(o_orderdate, pa.int32()).cast(pa.date32()),
        "o_shippriority": pa.array(
            np.zeros(n_orders, dtype=np.int32)),
        "o_totalprice": pa.array(np.round(
            857.71 + (o_orderkey * 9973 % 45000000) / 100.0, 2)),
    })
    write("orders", orders)

    # lineitem: 1..7 lines per order (mean 4 → ~6M lines/SF1)
    nlines = rng.integers(1, 8, n_orders)
    l_orderkey = np.repeat(o_orderkey, nlines)
    l_orderdate = np.repeat(o_orderdate, nlines)
    n_li = len(l_orderkey)
    l_shipdate = (l_orderdate + rng.integers(1, 122, n_li)).astype(np.int32)
    l_receiptdate = (l_shipdate + rng.integers(1, 31, n_li)).astype(np.int32)
    cutoff = _days(1995, 6, 17)
    returnflag = np.where(l_receiptdate <= cutoff,
                          np.where(rng.random(n_li) < 0.5, "R", "A"), "N")
    linestatus = np.where(l_shipdate > cutoff, "O", "F")
    lineitem = pa.table({
        "l_orderkey": pa.array(l_orderkey),
        "l_suppkey": pa.array(
            rng.integers(1, n_supp + 1, n_li).astype(np.int64)),
        "l_quantity": pa.array(
            rng.integers(1, 51, n_li).astype(np.float64)),
        "l_extendedprice": pa.array(
            np.round(rng.uniform(900.0, 105000.0, n_li), 2)),
        "l_discount": pa.array(
            np.round(rng.integers(0, 11, n_li) * 0.01, 2)),
        "l_tax": pa.array(np.round(rng.integers(0, 9, n_li) * 0.01, 2)),
        "l_returnflag": pa.array(returnflag),
        "l_linestatus": pa.array(linestatus),
        "l_shipdate": pa.array(l_shipdate, pa.int32()).cast(pa.date32()),
    })
    write("lineitem", lineitem)
    return paths


def load(spark, paths: dict, files_per_partition: int = 2) -> dict:
    from spark_rapids_tpu.benchmarks.common import load as _load
    return _load(spark, paths, files_per_partition)


# -- queries (session API) ---------------------------------------------------

def q1(dfs):
    """Pricing summary report (TPC-H q1)."""
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu import types as T
    li = dfs["lineitem"]
    cut = F.cast(F.lit("1998-09-02"), T.DATE)
    c = F.col
    return (li.filter(c("l_shipdate") <= cut)
            .select(c("l_returnflag"), c("l_linestatus"), c("l_quantity"),
                    c("l_extendedprice"), c("l_discount"),
                    (c("l_extendedprice") * (F.lit(1.0) - c("l_discount")))
                    .alias("disc_price"),
                    (c("l_extendedprice") * (F.lit(1.0) - c("l_discount"))
                     * (F.lit(1.0) + c("l_tax"))).alias("charge"))
            .group_by(c("l_returnflag"), c("l_linestatus"))
            .agg(F.sum(c("l_quantity")).alias("sum_qty"),
                 F.sum(c("l_extendedprice")).alias("sum_base_price"),
                 F.sum(c("disc_price")).alias("sum_disc_price"),
                 F.sum(c("charge")).alias("sum_charge"),
                 F.avg(c("l_quantity")).alias("avg_qty"),
                 F.avg(c("l_extendedprice")).alias("avg_price"),
                 F.avg(c("l_discount")).alias("avg_disc"),
                 F.count(c("l_quantity")).alias("count_order"))
            .sort(c("l_returnflag"), c("l_linestatus")))


def q3(dfs):
    """Shipping priority (TPC-H q3): top-10 unshipped orders by revenue."""
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu import types as T
    c = F.col
    date = F.cast(F.lit("1995-03-15"), T.DATE)
    cust = dfs["customer"].filter(c("c_mktsegment") == F.lit("BUILDING"))
    orders = dfs["orders"].filter(c("o_orderdate") < date).select(
        c("o_orderkey"), c("o_custkey"), c("o_orderdate"), c("o_shippriority"))
    li = dfs["lineitem"].filter(c("l_shipdate") > date).select(
        c("l_orderkey"), c("l_extendedprice"), c("l_discount"))
    j = (cust.select(c("c_custkey").alias("o_custkey"))
         .join(orders, on="o_custkey")
         .select(c("o_orderkey").alias("l_orderkey"), c("o_orderdate"),
                 c("o_shippriority"))
         .join(li, on="l_orderkey"))
    return (j.select(c("l_orderkey"), c("o_orderdate"), c("o_shippriority"),
                     (c("l_extendedprice") * (F.lit(1.0) - c("l_discount")))
                     .alias("volume"))
            .group_by(c("l_orderkey"), c("o_orderdate"), c("o_shippriority"))
            .agg(F.sum(c("volume")).alias("revenue"))
            .sort(c("revenue"), c("o_orderdate"), ascending=[False, True])
            .limit(10))


def q5(dfs):
    """Local supplier volume (TPC-H q5): revenue by nation in ASIA."""
    import spark_rapids_tpu.functions as F
    from spark_rapids_tpu import types as T
    c = F.col
    d0 = F.cast(F.lit("1994-01-01"), T.DATE)
    d1 = F.cast(F.lit("1995-01-01"), T.DATE)
    asia = dfs["region"].filter(c("r_name") == F.lit("ASIA")).select(
        c("r_regionkey").alias("n_regionkey"))
    nations = (dfs["nation"].join(asia, on="n_regionkey")
               .select(c("n_nationkey"), c("n_name")))
    supp = (dfs["supplier"]
            .select(c("s_suppkey").alias("l_suppkey"),
                    c("s_nationkey").alias("n_nationkey"))
            .join(nations, on="n_nationkey"))
    orders = (dfs["orders"]
              .filter((c("o_orderdate") >= d0) & (c("o_orderdate") < d1))
              .select(c("o_orderkey").alias("l_orderkey"),
                      c("o_custkey").alias("c_custkey")))
    cust = dfs["customer"].select(c("c_custkey"),
                                  c("c_nationkey"))
    co = orders.join(cust, on="c_custkey")
    li = dfs["lineitem"].select(c("l_orderkey"), c("l_suppkey"),
                                c("l_extendedprice"), c("l_discount"))
    j = (li.join(co, on="l_orderkey")
         .join(supp, on="l_suppkey")
         # q5's extra equality: the customer must share the supplier's nation
         .filter(c("c_nationkey") == c("n_nationkey")))
    return (j.select(c("n_name"),
                     (c("l_extendedprice") * (F.lit(1.0) - c("l_discount")))
                     .alias("volume"))
            .group_by(c("n_name"))
            .agg(F.sum(c("volume")).alias("revenue"))
            .sort(c("revenue"), ascending=False))


def q18(dfs):
    """Large volume customer (TPC-H q18, adapted to the generator's schema
    subset: c_name is absent, so the output keys on c_custkey). The
    join-canary shape VERDICT weak #7 asked for: a 150k-group sum over
    lineitem, a HAVING filter, then joins back through orders and customer."""
    import spark_rapids_tpu.functions as F
    c = F.col
    li = dfs["lineitem"]
    big = (li.group_by(c("l_orderkey"))
           .agg(F.sum(c("l_quantity")).alias("sum_qty"))
           .filter(c("sum_qty") > F.lit(300.0)))
    orders = dfs["orders"].select(
        c("o_orderkey").alias("l_orderkey"), c("o_custkey"),
        c("o_orderdate"), c("o_totalprice"))
    cust = dfs["customer"].select(c("c_custkey").alias("o_custkey"))
    j = big.join(orders, on="l_orderkey").join(cust, on="o_custkey")
    return (j.select(c("o_custkey").alias("c_custkey"),
                     c("l_orderkey").alias("o_orderkey"),
                     c("o_orderdate"), c("o_totalprice"), c("sum_qty"))
            .sort(c("o_totalprice"), c("o_orderdate"), c("o_orderkey"),
                  ascending=[False, True, True])
            .limit(100))


QUERIES = {"q1": q1, "q3": q3, "q5": q5, "q18": q18}


# -- independent NumPy oracles (single core, the CPU-Spark stand-in) ---------

def load_np(paths: dict) -> dict:
    from spark_rapids_tpu.benchmarks.common import load_np as _load_np
    return _load_np(paths)


def np_q1(tb):
    li = tb["lineitem"]
    keep = li["l_shipdate"] <= _days(1998, 9, 2)
    rf, ls = li["l_returnflag"][keep], li["l_linestatus"][keep]
    qty = li["l_quantity"][keep]
    price = li["l_extendedprice"][keep]
    disc = li["l_discount"][keep]
    tax = li["l_tax"][keep]
    disc_price = price * (1.0 - disc)
    charge = disc_price * (1.0 + tax)
    key = np.char.add(rf.astype("U1"), ls.astype("U1"))
    order = np.argsort(key, kind="stable")
    key, qty, price, disc, disc_price, charge = (
        a[order] for a in (key, qty, price, disc, disc_price, charge))
    uniq, start = np.unique(key, return_index=True)
    rows = []
    for g, s in enumerate(start):
        e = start[g + 1] if g + 1 < len(start) else len(key)
        n = e - s
        rows.append((uniq[g][0], uniq[g][1],
                     qty[s:e].sum(), price[s:e].sum(), disc_price[s:e].sum(),
                     charge[s:e].sum(), qty[s:e].sum() / n,
                     price[s:e].sum() / n, disc[s:e].sum() / n, n))
    return rows


def np_q3(tb):
    cust = tb["customer"]
    orders = tb["orders"]
    li = tb["lineitem"]
    date = _days(1995, 3, 15)
    ck = cust["c_custkey"][cust["c_mktsegment"] == "BUILDING"]
    om = (orders["o_orderdate"] < date) & np.isin(orders["o_custkey"], ck)
    okeys = orders["o_orderkey"][om]
    odate = orders["o_orderdate"][om]
    oprio = orders["o_shippriority"][om]
    lm = (li["l_shipdate"] > date) & np.isin(li["l_orderkey"], okeys)
    lkey = li["l_orderkey"][lm]
    vol = li["l_extendedprice"][lm] * (1.0 - li["l_discount"][lm])
    order = np.argsort(lkey, kind="stable")
    lkey, vol = lkey[order], vol[order]
    uk, start = np.unique(lkey, return_index=True)
    rev = np.add.reduceat(vol, start)
    osort = np.argsort(okeys, kind="stable")
    pos = osort[np.searchsorted(okeys, uk, sorter=osort)]
    rows = sorted(zip(uk, odate[pos], oprio[pos], rev),
                  key=lambda r: (-r[3], r[1], r[0]))[:10]
    return [(int(k), int(d), int(p), float(r)) for k, d, p, r in rows]


def np_q18(tb):
    li = tb["lineitem"]
    order = np.argsort(li["l_orderkey"], kind="stable")
    lk, q = li["l_orderkey"][order], li["l_quantity"][order]
    uk, start = np.unique(lk, return_index=True)
    sums = np.add.reduceat(q, start)
    keep = sums > 300.0
    big, bsum = uk[keep], sums[keep]
    orders = tb["orders"]
    osort = np.argsort(orders["o_orderkey"], kind="stable")
    pos = osort[np.searchsorted(orders["o_orderkey"], big, sorter=osort)]
    # every o_custkey exists in customer (dense 1..n), so the customer
    # inner join filters nothing
    rows = sorted(zip(orders["o_custkey"][pos], big,
                      orders["o_orderdate"][pos],
                      orders["o_totalprice"][pos], bsum),
                  key=lambda r: (-r[3], r[2], r[1]))[:100]
    return [(int(c), int(o), int(d), float(t), float(s))
            for c, o, d, t, s in rows]


def np_q5(tb):
    date0, date1 = _days(1994, 1, 1), _days(1995, 1, 1)
    region = tb["region"]
    nation = tb["nation"]
    asia = region["r_regionkey"][region["r_name"] == "ASIA"]
    nmask = np.isin(nation["n_regionkey"], asia)
    nkeys = nation["n_nationkey"][nmask]
    nnames = nation["n_name"][nmask]
    supp = tb["supplier"]
    smask = np.isin(supp["s_nationkey"], nkeys)
    # supplier key → nation (dense s_suppkey 1..n)
    s_nation = np.full(int(supp["s_suppkey"].max()) + 1, -1, dtype=np.int64)
    s_nation[supp["s_suppkey"][smask]] = supp["s_nationkey"][smask]
    cust = tb["customer"]
    c_nation = np.full(int(cust["c_custkey"].max()) + 1, -2, dtype=np.int64)
    c_nation[cust["c_custkey"]] = cust["c_nationkey"]
    orders = tb["orders"]
    om = (orders["o_orderdate"] >= date0) & (orders["o_orderdate"] < date1)
    o_cnation = np.full(int(orders["o_orderkey"].max()) + 1, -3,
                        dtype=np.int64)
    o_cnation[orders["o_orderkey"][om]] = c_nation[orders["o_custkey"][om]]
    li = tb["lineitem"]
    lsn = s_nation[li["l_suppkey"]]
    lcn = o_cnation[li["l_orderkey"]]
    keep = (lsn >= 0) & (lsn == lcn)
    vol = li["l_extendedprice"][keep] * (1.0 - li["l_discount"][keep])
    nat = lsn[keep]
    name_of = {int(k): n for k, n in zip(nkeys, nnames)}
    out = {}
    for k in np.unique(nat):
        out[name_of[int(k)]] = float(vol[nat == k].sum())
    return sorted(out.items(), key=lambda kv: -kv[1])
