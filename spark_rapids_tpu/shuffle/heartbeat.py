"""Shuffle liveness: executor registration + heartbeats.

Reference (SURVEY.md #35): RapidsShuffleHeartbeatManager (driver side) +
RapidsShuffleHeartbeatEndpoint (executor side), wired in Plugin.scala:140-166,197
— executors RPC-register with the driver so every peer learns new shuffle
executors (elasticity: late joiners see existing peers, existing peers learn of
late joiners on their next beat)."""

from __future__ import annotations

import threading
import time

from spark_rapids_tpu.runtime import eventlog as EL
from spark_rapids_tpu.runtime import tracing


class PeerInfo:
    __slots__ = ("executor_id", "host", "port", "last_seen")

    def __init__(self, executor_id: str, host: str, port: int):
        self.executor_id = executor_id
        self.host = host
        self.port = port
        self.last_seen = time.monotonic()

    @property
    def address(self):
        return (self.host, self.port)


class RapidsShuffleHeartbeatManager:
    """Driver-side registry (reference RapidsShuffleHeartbeatManager)."""

    def __init__(self, timeout_s: float = 60.0):
        self._lock = threading.Lock()
        self._peers: dict[str, PeerInfo] = {}
        self.timeout_s = timeout_s

    def register(self, executor_id: str, host: str, port: int) -> list:
        """Register an executor; returns all CURRENT peers so a late joiner
        learns existing executors immediately."""
        with self._lock:
            self._peers[executor_id] = PeerInfo(executor_id, host, port)
            return [p for eid, p in self._peers.items() if eid != executor_id]

    def heartbeat(self, executor_id: str) -> list:
        """Refresh liveness; returns peers registered since (simplified: all
        live peers — the reference returns deltas)."""
        with self._lock:
            p = self._peers.get(executor_id)
            if p is None:
                raise KeyError(f"unregistered executor {executor_id}")
            p.last_seen = time.monotonic()
            return [q for eid, q in self._peers.items() if eid != executor_id]

    def deregister(self, executor_id: str) -> None:
        """Forget an executor the driver REPLACED on purpose (MiniCluster
        respawn): the dead incarnation must not fire a spurious
        heartbeat-loss expiry after its slot is already healthy again."""
        with self._lock:
            self._peers.pop(executor_id, None)

    def live_peers(self) -> list:
        now = time.monotonic()
        with self._lock:
            return [p for p in self._peers.values()
                    if now - p.last_seen < self.timeout_s]

    def expire_dead(self) -> list:
        """Drop executors that missed their heartbeats (failure detection);
        returns the expired peers so shuffles can be invalidated → recompute."""
        now = time.monotonic()
        with self._lock:
            dead = [p for p in self._peers.values()
                    if now - p.last_seen >= self.timeout_s]
            for p in dead:
                del self._peers[p.executor_id]
        for p in dead:
            tracing.span_event("heartbeat.loss", executor=p.executor_id,
                               last_seen_age_s=round(now - p.last_seen, 3))
        return dead


class RapidsShuffleHeartbeatEndpoint:
    """Executor-side periodic beat (reference RapidsShuffleHeartbeatEndpoint)."""

    def __init__(self, manager: RapidsShuffleHeartbeatManager, executor_id: str,
                 host: str, port: int, interval_s: float = 5.0):
        self.manager = manager
        self.executor_id = executor_id
        self.peers: dict[str, PeerInfo] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self.interval_s = interval_s
        self._update(manager.register(executor_id, host, port))
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"heartbeat-{executor_id}")
        self._thread.start()

    def _update(self, peers):
        with self._lock:
            for p in peers:
                self.peers[p.executor_id] = p

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self._update(self.manager.heartbeat(self.executor_id))
            except Exception:
                pass  # driver unreachable: keep trying; Spark handles real death
            # the beat thread doubles as the executor health sampler
            # (HBM used/free + spill-catalog tiers) when the event log is on
            try:
                EL.emit_health(executor=self.executor_id)
            except Exception:
                pass  # sampling must never kill liveness

    def beat_now(self):
        self._update(self.manager.heartbeat(self.executor_id))

    def known_peers(self) -> list:
        with self._lock:
            return list(self.peers.values())

    def close(self):
        self._stop.set()
        self._thread.join(timeout=2)
