"""Table compression codecs for shuffle/spill buffers.

Reference (SURVEY.md #34): TableCompressionCodec.scala:41,107 (codec registry +
per-buffer codec descriptors), BatchedTableCompressor:137 (batched windows),
NvcompLZ4CompressionCodec.scala (device LZ4), CopyCompressionCodec (test codec).
TPU stance: compression runs on the host CPU beside the NIC/disk (serialized
frames), with the LZ4 kernel in native C++ (native/lz4.cpp)."""

from __future__ import annotations

import concurrent.futures as futures
import struct
import zlib

# magic, codec id, uncompressed len, crc32 of uncompressed payload (LZ4 block
# format itself has no checksum; network frames need one)
_CODEC_HEADER = struct.Struct("<4sBQI")
_MAGIC = b"TPUC"
CODEC_NONE = 0
CODEC_COPY = 1
CODEC_LZ4 = 2

_NAMES = {"none": CODEC_NONE, "copy": CODEC_COPY, "lz4": CODEC_LZ4}


class TableCompressionCodec:
    codec_id = CODEC_NONE
    name = "none"

    def compress(self, data: bytes) -> bytes:
        return data

    def decompress(self, data: bytes, n: int) -> bytes:
        return data

    # -- framing -------------------------------------------------------------
    def encode(self, data: bytes) -> bytes:
        if self.codec_id == CODEC_NONE:
            return data
        crc = zlib.crc32(data) & 0xFFFFFFFF
        return (_CODEC_HEADER.pack(_MAGIC, self.codec_id, len(data), crc)
                + self.compress(data))

    @staticmethod
    def decode(blob: bytes) -> bytes:
        """Self-describing decode: plain frames pass through (reference reads the
        codec id from the per-buffer BufferMeta descriptor)."""
        if len(blob) >= _CODEC_HEADER.size:
            magic, cid, n, crc = _CODEC_HEADER.unpack_from(blob, 0)
            if magic == _MAGIC:
                codec = _BY_ID[cid]
                data = codec.decompress(blob[_CODEC_HEADER.size:], n)
                if (zlib.crc32(data) & 0xFFFFFFFF) != crc:
                    raise ValueError("corrupt compressed frame (crc mismatch)")
                return data
        return blob


class CopyCodec(TableCompressionCodec):
    """Identity codec with the full framing path — the reference's COPY test
    codec (TableCompressionCodec.scala)."""
    codec_id = CODEC_COPY
    name = "copy"

    def compress(self, data):
        return data

    def decompress(self, data, n):
        assert len(data) == n
        return data


class Lz4Codec(TableCompressionCodec):
    codec_id = CODEC_LZ4
    name = "lz4"

    def compress(self, data):
        from spark_rapids_tpu.native import lz4_compress
        return lz4_compress(data)

    def decompress(self, data, n):
        from spark_rapids_tpu.native import lz4_decompress
        return lz4_decompress(data, n)


_BY_ID = {CODEC_NONE: TableCompressionCodec(), CODEC_COPY: CopyCodec(),
          CODEC_LZ4: Lz4Codec()}


def get_codec(name: str) -> TableCompressionCodec:
    try:
        return _BY_ID[_NAMES[name.lower()]]
    except KeyError:
        raise ValueError(f"unknown compression codec {name!r}") from None


class BatchedTableCompressor:
    """Compress many frames concurrently on a persistent thread pool (reference
    BatchedTableCompressor:137 batches device buffers through nvcomp)."""

    def __init__(self, codec: TableCompressionCodec, num_threads: int = 4):
        self.codec = codec
        self.num_threads = num_threads
        self._pool = None

    def _get_pool(self):
        if self._pool is None:
            self._pool = futures.ThreadPoolExecutor(
                self.num_threads, thread_name_prefix="table-codec")
        return self._pool

    def compress_all(self, frames: list) -> list:
        if self.codec.codec_id == CODEC_NONE or len(frames) <= 1:
            return [self.codec.encode(f) for f in frames]
        return list(self._get_pool().map(self.codec.encode, frames))

    def decompress_all(self, frames: list) -> list:
        if len(frames) <= 1:
            return [TableCompressionCodec.decode(f) for f in frames]
        return list(self._get_pool().map(TableCompressionCodec.decode, frames))

    def close(self):
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None
