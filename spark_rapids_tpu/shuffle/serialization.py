"""Columnar batch (de)serialization — the host-shuffle / broadcast wire format.

Reference (SURVEY.md component #36): GpuColumnarBatchSerializer.scala:50 over cudf
JCudfSerialization — header + host-buffer framing used by the fallback Spark shuffle
path, broadcast, and the disk tier. Here the frame is:

  magic 'TPUB' | version u32 | num_rows u32 | num_cols u32 | schema json |
  per column: dtype code u8 | has_dict u8 | data nbytes u64 | data |
              validity bitpacked | [dict arrow-IPC stream]

Fixed-width column payloads are raw little-endian numpy bytes trimmed to num_rows (the
padded capacity is NOT shipped — receivers re-pad to their own bucket), validity is
bit-packed 8:1, and string dictionaries travel as Arrow IPC. The same frame feeds the
native LZ4 block codec (native/ — the nvcomp analog) when shuffle compression is on.
"""

from __future__ import annotations

import io
import json
import struct

import numpy as np
import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity

_MAGIC = b"TPUB"
_VERSION = 1


def _write_dict(buf: io.BytesIO, arr: pa.Array):
    sink = pa.BufferOutputStream()
    t = pa.table({"d": arr})
    with pa.ipc.new_stream(sink, t.schema) as w:
        w.write_table(t)
    payload = sink.getvalue().to_pybytes()
    buf.write(struct.pack("<Q", len(payload)))
    buf.write(payload)


def _read_dict(view: memoryview, off: int):
    (n,) = struct.unpack_from("<Q", view, off)
    off += 8
    t = pa.ipc.open_stream(pa.BufferReader(view[off:off + n])).read_all()
    return t["d"].combine_chunks(), off + n


def serialize_batch(batch: ColumnarBatch) -> bytes:
    n = batch.num_rows
    buf = io.BytesIO()
    schema_json = json.dumps(batch.schema.to_json() if batch.schema is not None else None)
    sj = schema_json.encode()
    buf.write(_MAGIC)
    buf.write(struct.pack("<IIII", _VERSION, n, batch.num_cols, len(sj)))
    buf.write(sj)
    for c in batch.columns:
        vals, valid = c.to_host(n)
        code = T.type_code(c.dtype)
        has_dict = 1 if c.dictionary is not None else 0
        data = np.ascontiguousarray(vals).tobytes()
        buf.write(struct.pack("<IBQ", code, has_dict, len(data)))
        buf.write(data)
        buf.write(np.packbits(valid, bitorder="little").tobytes())
        if has_dict:
            _write_dict(buf, c.dictionary)
    return buf.getvalue()


def deserialize_batch(data: bytes) -> ColumnarBatch:
    import jax.numpy as jnp
    view = memoryview(data)
    assert view[:4] == _MAGIC, "bad shuffle frame magic"
    version, n, ncols, sjlen = struct.unpack_from("<IIII", view, 4)
    assert version == _VERSION
    off = 20
    schema_json = json.loads(bytes(view[off:off + sjlen]).decode())
    schema = T.StructType.from_json(schema_json) if schema_json is not None else None
    off += sjlen
    cap = bucket_capacity(n)
    cols = []
    for _ in range(ncols):
        code, has_dict, nbytes = struct.unpack_from("<IBQ", view, off)
        off += struct.calcsize("<IBQ")
        dtype = T.type_from_code(code)
        np_dt = T.to_numpy_dtype(dtype)
        vals = np.frombuffer(view[off:off + nbytes], dtype=np_dt)
        off += nbytes
        vbytes = (n + 7) // 8
        valid = np.unpackbits(np.frombuffer(view[off:off + vbytes], dtype=np.uint8),
                              bitorder="little")[:n].astype(bool)
        off += vbytes
        dictionary = None
        if has_dict:
            dictionary, off = _read_dict(view, off)
        dvals = np.zeros(cap, dtype=np_dt)
        dvals[:n] = vals
        dvalid = np.zeros(cap, dtype=bool)
        dvalid[:n] = valid
        dvals[~dvalid] = dtype.default_value()
        cols.append(TpuColumnVector(dtype, jnp.asarray(dvals), jnp.asarray(dvalid),
                                    dictionary))
    return ColumnarBatch(cols, n, schema)
