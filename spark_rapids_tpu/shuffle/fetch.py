"""Fetch-failure recovery: transport retries, failover, then recompute.

Reference: RapidsShuffleIterator.scala:82,153 — a TransferError from the UCX
client surfaces as a FetchFailedException, Spark retries the fetch and
ultimately recomputes the map stage. Two complementary layers here:

- THIS module is the peer/network ladder for transport-backed reads
  (cross-process fetches over shuffle/transport.py): retry the same peer with
  a fresh connection, fail over to replica peers, finally call a recompute
  callback.
- exec/exchange.py owns the STAGE ladder for its local reads: a failed read
  invalidates the map outputs and re-runs the map stage (Spark's
  FetchFailed → stage retry), bounded by spark.rapids.tpu.shuffle.fetch.maxRetries.

Retries back off EXPONENTIALLY with jitter and a hard cap (a linear,
jitter-free backoff synchronizes a fleet of failed fetchers into retry
stampedes against a recovering peer), and every retry/failover/recompute is
counted into the process-wide resilience registry
(runtime/metrics.global_registry) so chaos tests and bench.py can assert on
them.
"""

from __future__ import annotations

import random
import time

from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.shuffle.transport import TransportError


class ShuffleFetchIterator:
    """Iterate one reduce partition's batches with retry → failover →
    recompute (RapidsShuffleIterator analog)."""

    def __init__(self, client_factories: list, shuffle_id: int, reduce_id: int,
                 recompute=None, max_retries: int = 2,
                 retry_backoff_s: float = 0.05,
                 retry_backoff_max_s: float = 2.0, jitter=None):
        """client_factories: zero-arg callables, each returning a FRESH
        ShuffleClient for one peer (a dead connection must not be reused).
        recompute: zero-arg callable yielding the partition's batches by
        re-running the map-side work; raises if it cannot.
        max_retries: EXTRA attempts per peer beyond the first.
        retry_backoff_s / retry_backoff_max_s: base and cap of the jittered
        exponential backoff between same-peer attempts.
        jitter: optional random.Random override; the default seeds from the
        (shuffle, reduce) ids so a schedule is reproducible per partition
        while staying decorrelated across partitions."""
        self.client_factories = client_factories
        self.shuffle_id = shuffle_id
        self.reduce_id = reduce_id
        self.recompute = recompute
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.retry_backoff_max_s = retry_backoff_max_s
        self._rng = jitter or random.Random(
            0x5F37 ^ (shuffle_id << 16) ^ reduce_id)
        self.errors: list[str] = []

    def _backoff(self, attempt: int) -> float:
        """Jittered exponential delay: base·2^attempt capped, scaled by a
        uniform [0.5, 1.0) factor (decorrelates concurrent fetchers)."""
        d = min(self.retry_backoff_s * (2 ** attempt),
                self.retry_backoff_max_s)
        return d * (0.5 + self._rng.random() / 2)

    def __iter__(self):
        g = M.global_registry()
        for pi, factory in enumerate(self.client_factories):
            for attempt in range(self.max_retries + 1):
                batches = []
                try:
                    # chaos checkpoint, shared site name with the stage
                    # ladder in exec/exchange.py ("transport:fetch:N")
                    F.maybe_inject("transport", "fetch")
                    client = factory()
                    for b in client.fetch_blocks(self.shuffle_id,
                                                 self.reduce_id):
                        # buffer before yielding: a mid-stream failure must
                        # not emit a partial partition twice
                        batches.append(b)
                except TransportError as e:
                    self.errors.append(
                        f"peer {pi} attempt {attempt}: {e}")
                    tracing.span_event("fetch.error", peer=pi,
                                       attempt=attempt, error=str(e)[:120])
                    if attempt < self.max_retries:  # no sleep before failover
                        g.metric(M.FETCH_RETRIES).add(1)
                        tracing.span_event("fetch.retry", peer=pi,
                                           attempt=attempt,
                                           shuffle=self.shuffle_id,
                                           reduce=self.reduce_id)
                        time.sleep(self._backoff(attempt))
                    continue
                yield from batches
                return
            if pi < len(self.client_factories) - 1:
                g.metric(M.FETCH_FAILOVERS).add(1)
                tracing.span_event("fetch.failover", from_peer=pi,
                                   shuffle=self.shuffle_id,
                                   reduce=self.reduce_id)
        if self.recompute is None:
            raise TransportError(
                "all peers failed for shuffle %d reduce %d: %s"
                % (self.shuffle_id, self.reduce_id, "; ".join(self.errors)))
        g.metric(M.FETCH_RECOMPUTES).add(1)
        tracing.span_event("fetch.recompute", shuffle=self.shuffle_id,
                           reduce=self.reduce_id)
        yield from self.recompute()
