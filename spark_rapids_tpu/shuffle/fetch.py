"""Fetch-failure recovery: transport retries, failover, then recompute.

Reference: RapidsShuffleIterator.scala:82,153 — a TransferError from the UCX
client surfaces as a FetchFailedException, Spark retries the fetch and
ultimately recomputes the map stage. Two complementary layers here:

- THIS module is the peer/network ladder for transport-backed reads
  (cross-process fetches over shuffle/transport.py): retry the same peer with
  a fresh connection, fail over to replica peers, finally call a recompute
  callback.
- exec/exchange.py owns the STAGE ladder for its local reads: a failed read
  invalidates the map outputs and re-runs the map stage (Spark's
  FetchFailed → stage retry), bounded by spark.rapids.tpu.shuffle.fetch.maxRetries.

Retries back off EXPONENTIALLY with jitter and a hard cap (a linear,
jitter-free backoff synchronizes a fleet of failed fetchers into retry
stampedes against a recovering peer), and every retry/failover/recompute is
counted into the process-wide resilience registry
(runtime/metrics.global_registry) so chaos tests and bench.py can assert on
them.
"""

from __future__ import annotations

import random
import time

from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import movement as MV
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.runtime.memory import SpillCorruptionError
from spark_rapids_tpu.shuffle.transport import _NO_KEY, TransportError


class ShuffleFetchIterator:
    """Iterate one reduce partition's batches with retry → failover →
    recompute (RapidsShuffleIterator analog)."""

    def __init__(self, client_factories: list, shuffle_id: int, reduce_id: int,
                 recompute=None, max_retries: int = 2,
                 retry_backoff_s: float = 0.05,
                 retry_backoff_max_s: float = 2.0, jitter=None):
        """client_factories: zero-arg callables, each returning a FRESH
        ShuffleClient for one peer (a dead connection must not be reused).
        recompute: zero-arg callable yielding the partition's batches by
        re-running the map-side work; raises if it cannot.
        max_retries: EXTRA attempts per peer beyond the first.
        retry_backoff_s / retry_backoff_max_s: base and cap of the jittered
        exponential backoff between same-peer attempts.
        jitter: optional random.Random override; the default seeds from the
        (shuffle, reduce) ids so a schedule is reproducible per partition
        while staying decorrelated across partitions."""
        self.client_factories = client_factories
        self.shuffle_id = shuffle_id
        self.reduce_id = reduce_id
        self.recompute = recompute
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.retry_backoff_max_s = retry_backoff_max_s
        self._rng = jitter or random.Random(
            0x5F37 ^ (shuffle_id << 16) ^ reduce_id)
        self.errors: list[str] = []

    def _backoff(self, attempt: int) -> float:
        """Jittered exponential delay: base·2^attempt capped, scaled by a
        uniform [0.5, 1.0) factor (decorrelates concurrent fetchers)."""
        d = min(self.retry_backoff_s * (2 ** attempt),
                self.retry_backoff_max_s)
        return d * (0.5 + self._rng.random() / 2)

    def __iter__(self):
        for _, b in self.iter_keyed():
            yield b

    def iter_keyed(self):
        """The retry→failover→recompute ladder, yielding (sort_key, batch)
        via the clients' keyed fetch API: sort_key is the block's
        (map_split, seq) wire key so a multi-peer union reader can merge
        several peers' disjoint block sets into one canonical order
        (recomputed batches carry the sort-last sentinel)."""
        from spark_rapids_tpu.runtime import scheduler as SCHED
        for pi, factory in enumerate(self.client_factories):
            for attempt in range(self.max_retries + 1):
                # a cancelled query must not grind through the whole
                # retry -> failover -> recompute ladder first
                SCHED.check_cancel()
                batches = []
                # movement-ledger attempt scope: bytes this attempt pulls
                # land on shuffle.recv; a failed attempt discards its
                # buffered batches below, so abort_attempt moves exactly
                # those bytes onto the shuffle.retry edge (re-fetching must
                # not double-count the recv ledger against partition sizes)
                tok = MV.begin_attempt()
                try:
                    # chaos checkpoint, shared site name with the stage
                    # ladder in exec/exchange.py ("transport:fetch:N")
                    F.maybe_inject("transport", "fetch")
                    client = factory()
                    keyed_fetch = getattr(client, "fetch_blocks_with_keys",
                                          None)
                    if keyed_fetch is not None:
                        stream = keyed_fetch(self.shuffle_id, self.reduce_id)
                    else:
                        # duck-typed client without the keyed API: sentinel
                        # keys keep per-client arrival order
                        stream = ((_NO_KEY, b) for b in client.fetch_blocks(
                            self.shuffle_id, self.reduce_id))
                    for kb in stream:
                        # buffer before yielding: a mid-stream failure must
                        # not emit a partial partition twice
                        batches.append(kb)
                except (TransportError, SpillCorruptionError) as e:
                    MV.abort_attempt(tok)
                    # a CRC mismatch — on the wire (TransportError from the
                    # TCP client) or in a peer's spilled block (unspill
                    # verification) — IS a fetch failure: retry, fail over,
                    # recompute; never decode corrupt rows
                    self.errors.append(
                        f"peer {pi} attempt {attempt}: {e}")
                    tracing.span_event("fetch.error", peer=pi,
                                       attempt=attempt, error=str(e)[:120])
                    if attempt < self.max_retries:  # no sleep before failover
                        M.resilience_add(M.FETCH_RETRIES)
                        tracing.span_event("fetch.retry", peer=pi,
                                           attempt=attempt,
                                           shuffle=self.shuffle_id,
                                           reduce=self.reduce_id)
                        SCHED.check_cancel()   # don't sleep a dead query
                        time.sleep(self._backoff(attempt))
                    continue
                except BaseException:
                    # cancellation or an unexpected error: nothing retries
                    # these bytes, keep them on shuffle.recv
                    MV.commit_attempt(tok)
                    raise
                MV.commit_attempt(tok)
                yield from batches
                return
            if pi < len(self.client_factories) - 1:
                M.resilience_add(M.FETCH_FAILOVERS)
                tracing.span_event("fetch.failover", from_peer=pi,
                                   shuffle=self.shuffle_id,
                                   reduce=self.reduce_id)
        if self.recompute is None:
            raise TransportError(
                "all peers failed for shuffle %d reduce %d: %s"
                % (self.shuffle_id, self.reduce_id, "; ".join(self.errors)))
        M.resilience_add(M.FETCH_RECOMPUTES)
        tracing.span_event("fetch.recompute", shuffle=self.shuffle_id,
                           reduce=self.reduce_id)
        for b in self.recompute():
            yield _NO_KEY, b


def iter_union_blocks(peer_factories: list, shuffle_id: int, reduce_id: int,
                      max_retries: int = 2, epoch: int | None = None):
    """Fetch one reduce partition as the UNION of every peer's blocks (the
    MiniCluster data layout: each mapper parked its buckets locally, so
    peers hold DISJOINT block sets — failing over between them would lose
    data, unlike the replica semantics of ShuffleFetchIterator). Each peer
    gets its own same-peer retry ladder with jittered backoff; a peer that
    stays unreachable raises TransportError so the driver can classify the
    loss and run a lineage-scoped recompute. `epoch` tags the retry events
    with the map-output epoch the fetch was planned under.

    The union is merged into canonical (map_split, seq) key order, NOT
    concatenated in peer order: after a partial stage recompute a map
    split's blocks live on a DIFFERENT peer than in a clean run, and
    order-sensitive consumers (float aggregation, limit) must still see a
    bit-identical stream. Untagged blocks carry the sort-last sentinel and
    keep their (peer, arrival) order."""
    keyed = []
    # task-level movement attempt: when one peer stays unreachable the
    # WHOLE reduce task fails and the driver's recompute re-fetches every
    # peer — the bytes the healthy peers already delivered to this failed
    # attempt must move to the shuffle.retry edge (inner per-peer aborts
    # already deducted their share from this outer token)
    union_tok = MV.begin_attempt()
    try:
        for pi, factory in enumerate(peer_factories):
            it = ShuffleFetchIterator([factory], shuffle_id, reduce_id,
                                      recompute=None,
                                      max_retries=max_retries,
                                      jitter=random.Random(
                                          0x7A11 ^ (shuffle_id << 16)
                                          ^ (reduce_id << 4) ^ pi))
            try:
                for key, batch in it.iter_keyed():
                    keyed.append((key, pi, len(keyed), batch))
            except TransportError as e:
                raise TransportError(
                    f"peer {pi} unreachable for shuffle {shuffle_id} reduce "
                    f"{reduce_id} (epoch {epoch}): {e}") from e
    except TransportError:
        MV.abort_attempt(union_tok)
        raise
    except BaseException:
        MV.commit_attempt(union_tok)
        raise
    MV.commit_attempt(union_tok)
    keyed.sort(key=lambda t: (t[0], t[1], t[2]))
    for _, _, _, batch in keyed:
        yield batch
