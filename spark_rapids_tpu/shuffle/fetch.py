"""Fetch-failure recovery: transport retries, failover, then recompute.

Reference: RapidsShuffleIterator.scala:82,153 — a TransferError from the UCX
client surfaces as a FetchFailedException, Spark retries the fetch and
ultimately recomputes the map stage. Two complementary layers here:

- THIS module is the peer/network ladder for transport-backed reads
  (cross-process fetches over shuffle/transport.py): retry the same peer with
  a fresh connection, fail over to replica peers, finally call a recompute
  callback.
- exec/exchange.py owns the STAGE ladder for its local reads: a failed read
  invalidates the map outputs and re-runs the map stage (Spark's
  FetchFailed → stage retry), bounded by spark.rapids.tpu.shuffle.fetch.maxRetries.
"""

from __future__ import annotations

import time

from spark_rapids_tpu.shuffle.transport import TransportError


class ShuffleFetchIterator:
    """Iterate one reduce partition's batches with retry → failover →
    recompute (RapidsShuffleIterator analog)."""

    def __init__(self, client_factories: list, shuffle_id: int, reduce_id: int,
                 recompute=None, max_retries: int = 2,
                 retry_backoff_s: float = 0.05):
        """client_factories: zero-arg callables, each returning a FRESH
        ShuffleClient for one peer (a dead connection must not be reused).
        recompute: zero-arg callable yielding the partition's batches by
        re-running the map-side work; raises if it cannot.
        max_retries: EXTRA attempts per peer beyond the first."""
        self.client_factories = client_factories
        self.shuffle_id = shuffle_id
        self.reduce_id = reduce_id
        self.recompute = recompute
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        self.errors: list[str] = []

    def __iter__(self):
        for factory in self.client_factories:
            for attempt in range(self.max_retries + 1):
                batches = []
                try:
                    client = factory()
                    for b in client.fetch_blocks(self.shuffle_id,
                                                 self.reduce_id):
                        # buffer before yielding: a mid-stream failure must
                        # not emit a partial partition twice
                        batches.append(b)
                except TransportError as e:
                    self.errors.append(
                        f"peer attempt {attempt}: {e}")
                    if attempt < self.max_retries:  # no sleep before failover
                        time.sleep(self.retry_backoff_s * (attempt + 1))
                    continue
                yield from batches
                return
        if self.recompute is None:
            raise TransportError(
                "all peers failed for shuffle %d reduce %d: %s"
                % (self.shuffle_id, self.reduce_id, "; ".join(self.errors)))
        yield from self.recompute()
