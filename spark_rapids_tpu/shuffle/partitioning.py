"""Device-side partitioning — hash / range / round-robin / single.

Reference (SURVEY.md component #28): GpuHashPartitioning.scala (cudf murmur3 matching
Spark's Murmur3Hash with seed 42), GpuRangePartitioner.scala (host reservoir sample +
sort to pick bounds), GpuRoundRobinPartitioning.scala, GpuSinglePartitioning.scala,
GpuPartitioning.scala:169 (slice device batch into contiguous per-partition pieces).

TPU shape: partition ids are computed on device in one fused program, rows are
stable-sorted by partition id (one XLA sort), and per-partition counts come back in a
single device→host sync at the exchange boundary — the same one sync the reference
needs to build its slice offsets.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
from spark_rapids_tpu.expr.core import Col, EvalContext, bind_references
from spark_rapids_tpu.ops import hashing as H
from spark_rapids_tpu.ops.filtering import gather_cols
from spark_rapids_tpu.ops.sorting import SortOrder, _key_arrays

SPARK_HASH_SEED = 42  # HashPartitioning's Murmur3Hash seed


def murmur3_row_hash(cols: list[Col], capacity: int, seed: int = SPARK_HASH_SEED,
                     dict_words: dict | None = None):
    """Per-row Spark Murmur3Hash over `cols`, chaining each column's hash into the
    next column's seed; null cells leave the running hash unchanged (Spark
    HashExpression.eval semantics, mirrored by the reference's cudf murmur3)."""
    h = jnp.full((capacity,), jnp.int32(seed))
    for ci, c in enumerate(cols):
        dt = c.dtype
        if isinstance(dt, T.StringType):
            words, lens = dict_words[ci]
            row_words = words[c.values]      # (capacity, W)
            row_lens = lens[c.values]
            nh = H.hash_string_words(row_words, row_lens, h)
        elif isinstance(dt, (T.LongType, T.TimestampType)):
            nh = H.hash_long(c.values, h)
        elif isinstance(dt, T.DecimalType):
            nh = H.hash_long(c.values.astype(jnp.int64), h)
        elif isinstance(dt, T.DoubleType):
            nh = H.hash_double(c.values, h)
        elif isinstance(dt, T.FloatType):
            nh = H.hash_float(c.values, h)
        elif isinstance(dt, T.BooleanType):
            nh = H.hash_int(c.values.astype(jnp.int32), h)
        else:  # byte/short/int/date widen to int32
            nh = H.hash_int(c.values.astype(jnp.int32), h)
        h = jnp.where(c.validity, nh, h)
    return h


def range_part_ids(keys: list[Col], bounds: list[Col], orders, capacity: int):
    """Partition id per row given `n-1` sorted bound rows: number of bounds the
    row compares strictly greater than (lexicographic, Spark null/NaN ordering
    via _key_arrays). Shared by the host RangePartitioner and the mesh exchange
    (the mesh path passes keys/bounds already in one global dictionary space)."""
    keys = list(keys)
    bounds = list(bounds)
    # align string dictionaries between keys and bounds so codes compare
    for i, (k, b) in enumerate(zip(keys, bounds)):
        if k.is_string and k.dictionary is not b.dictionary:
            from spark_rapids_tpu.ops.strings import union_dictionaries
            k2, b2 = union_dictionaries(k, b)
            keys[i], bounds[i] = k2, b2
    nb = bounds[0].values.shape[0]
    row_keys = [ka for k, o in zip(keys, orders)
                for ka in _key_arrays(k, o)]
    bound_keys = [ka for b, o in zip(bounds, orders)
                  for ka in _key_arrays(b, o)]
    ids = jnp.zeros((capacity,), jnp.int32)
    for j in range(nb):
        gt = jnp.zeros((capacity,), jnp.bool_)
        tie = jnp.ones((capacity,), jnp.bool_)
        for rk, bk in zip(row_keys, bound_keys):
            bj = bk[j]
            gt = gt | (tie & (rk > bj))
            tie = tie & (rk == bj)
        ids = ids + gt.astype(jnp.int32)
    return ids


def slice_into_partitions(batch: ColumnarBatch, part_ids, num_partitions: int):
    """Stable-sort rows by partition id and slice into per-partition batches.
    Returns list[(part, ColumnarBatch)] for non-empty partitions
    (reference GpuPartitioning.sliceInternalOnGpu)."""
    cap = batch.capacity
    n = batch.num_rows
    live = jnp.arange(cap, dtype=jnp.int32) < n
    ids = jnp.where(live, part_ids.astype(jnp.int32), jnp.int32(num_partitions))
    # radix-rank kernel when latched, stable argsort otherwise; padding rows
    # sink to the end via the sentinel id either way
    from spark_rapids_tpu.ops.sorting import partition_permutation
    perm = partition_permutation(part_ids, num_partitions, n, cap)
    cols = [Col.from_vector(c) for c in batch.columns]
    sorted_cols = gather_cols(cols, perm, live[perm])
    counts = np.asarray(jnp.bincount(ids, length=num_partitions + 1))[:num_partitions]
    offsets = np.concatenate([[0], np.cumsum(counts)])
    out = []
    for p in range(num_partitions):
        cnt = int(counts[p])
        if cnt == 0:
            continue
        lo = int(offsets[p])
        pcap = bucket_capacity(cnt)
        pcols = []
        for c in sorted_cols:
            vals = c.values[lo:lo + pcap]
            valid = c.validity[lo:lo + pcap]
            default = jnp.asarray(c.dtype.default_value(), dtype=vals.dtype)
            if vals.shape[0] < pcap:  # partition tail ran past the padded capacity
                pad = pcap - vals.shape[0]
                vals = jnp.concatenate([vals, jnp.full((pad,), default)])
                valid = jnp.concatenate([valid, jnp.zeros((pad,), jnp.bool_)])
            idx = jnp.arange(pcap) < cnt
            valid = valid & idx
            pcols.append(TpuColumnVector(c.dtype, jnp.where(valid, vals, default),
                                         valid, c.dictionary))
        out.append((p, ColumnarBatch(pcols, cnt, batch.schema)))
    return out


class Partitioner:
    """Base: `partition(batch, split) -> list[(part_id, ColumnarBatch)]`."""

    num_partitions: int

    def bind(self, schema):
        return self

    def partition(self, batch: ColumnarBatch, split: int = 0):
        raise NotImplementedError


class SinglePartitioner(Partitioner):
    """Reference GpuSinglePartitioning.scala."""

    num_partitions = 1

    def partition(self, batch, split=0):
        return [(0, batch)] if batch.num_rows else []


class HashPartitioner(Partitioner):
    """Reference GpuHashPartitioning.scala — bit-exact with Spark's
    HashPartitioning(pmod(murmur3(keys, 42), n))."""

    def __init__(self, key_exprs: list, num_partitions: int):
        self.key_exprs = list(key_exprs)
        self.num_partitions = num_partitions

    def bind(self, schema):
        self.key_exprs = [bind_references(e, schema) for e in self.key_exprs]
        return self

    def part_ids(self, batch: ColumnarBatch):
        from spark_rapids_tpu.expr.core import BoundReference
        ctx = EvalContext.from_batch(batch)
        keys = [e.eval(ctx) for e in self.key_exprs]
        dict_words = {}
        for i, (e, k) in enumerate(zip(self.key_exprs, keys)):
            if not k.is_string:
                continue
            if isinstance(e, BoundReference):
                # reuse the batch vector's cached dictionary packing instead of
                # repacking the dictionary for every batch
                dict_words[i] = batch.column(e.ordinal).dictionary_words()
            else:
                dict_words[i] = k.to_vector().dictionary_words()
        h = murmur3_row_hash(keys, batch.capacity, dict_words=dict_words)
        return H.pmod(h, self.num_partitions)

    def partition(self, batch, split=0):
        return slice_into_partitions(batch, self.part_ids(batch), self.num_partitions)


class RoundRobinPartitioner(Partitioner):
    """Reference GpuRoundRobinPartitioning.scala: rows dealt onto partitions in order,
    starting at a position derived from the input split so outputs stay balanced."""

    def __init__(self, num_partitions: int):
        self.num_partitions = num_partitions

    def partition(self, batch, split=0):
        cap = batch.capacity
        start = split % self.num_partitions
        ids = (jnp.arange(cap, dtype=jnp.int32) + start) % self.num_partitions
        return slice_into_partitions(batch, ids, self.num_partitions)


class RangePartitioner(Partitioner):
    """Reference GpuRangePartitioner.scala + GpuRangePartitioning.scala: sample rows
    (reservoir, host), sort the sample to choose `n-1` bounds, then place each row by
    lexicographic comparison against the bounds on device."""

    def __init__(self, sort_exprs: list, orders: list, num_partitions: int):
        self.sort_exprs = list(sort_exprs)
        self.orders = list(orders)
        self.num_partitions = num_partitions
        self._bounds: list[ColumnarBatch] | None = None

    def bind(self, schema):
        self.sort_exprs = [bind_references(e, schema) for e in self.sort_exprs]
        return self

    def set_bounds_from_sample(self, sample_batches: list[ColumnarBatch]):
        """Compute bounds from sampled batches (driver-side, reference
        GpuRangePartitioner.createRangeBounds)."""
        from spark_rapids_tpu.ops.concat import concat_batches
        from spark_rapids_tpu.ops.sorting import sort_permutation
        sample = concat_batches(sample_batches)
        ctx = EvalContext.from_batch(sample)
        keys = [e.eval(ctx) for e in self.sort_exprs]
        perm = sort_permutation(keys, self.orders, sample.num_rows, sample.capacity)
        n = sample.num_rows
        live = jnp.arange(sample.capacity, dtype=jnp.int32) < n
        skeys = gather_cols(keys, perm, live[perm])
        # n-1 evenly spaced bound rows
        nb = self.num_partitions - 1
        if n == 0 or nb == 0:
            self._bounds = None
            return
        pos = np.minimum(((np.arange(1, nb + 1) * n) // self.num_partitions),
                         max(n - 1, 0)).astype(np.int32)
        self._bounds = [
            Col(c.values[jnp.asarray(pos)], c.validity[jnp.asarray(pos)], c.dtype,
                c.dictionary) for c in skeys]

    def part_ids(self, batch: ColumnarBatch):
        if self._bounds is None:
            return jnp.zeros((batch.capacity,), jnp.int32)
        ctx = EvalContext.from_batch(batch)
        keys = [e.eval(ctx) for e in self.sort_exprs]
        return range_part_ids(keys, self._bounds, self.orders, batch.capacity)

    def partition(self, batch, split=0):
        return slice_into_partitions(batch, self.part_ids(batch), self.num_partitions)
