"""P2P shuffle transport: transport-agnostic core + TCP data plane.

Reference (SURVEY.md #31-33): RapidsShuffleTransport.scala:328 (pluggable trait,
makeTransport:558), RapidsShuffleClient:98 (doFetch:194, issueBufferReceives:300),
RapidsShuffleServer:71, BufferSendState/BufferReceiveState + WindowedBlockIterator
(bounce-buffer windowing), AddressLengthTag:38, with UCX RDMA as the production
data plane (shuffle-plugin). FlatBuffers carry the control plane.

TPU realization: intra-slice dense exchange rides ICI collectives inside jit (see
__graft_entry__.dryrun_multichip / the exchange layer); THIS module is the
cross-host / sparse-fetch data plane the reference runs over UCX — here over TCP
sockets with the same structure: a metadata round-trip, then windowed
bounce-buffer-sized chunk transfers bounded by an inflight-bytes throttle.
Transports stay pluggable by classname (`spark.rapids.tpu.shuffle.transport.class`,
reference RapidsConf.scala:925)."""

from __future__ import annotations

import io
import socket
import socketserver
import struct
import threading

from spark_rapids_tpu import config as CFG
from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import movement as MV
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.shuffle.compression import (CODEC_NONE,
                                                  BatchedTableCompressor,
                                                  TableCompressionCodec,
                                                  get_codec)
from spark_rapids_tpu.shuffle.manager import ShuffleBlockStore
from spark_rapids_tpu.shuffle import serialization as ser

# control-plane message ids (the FlatBuffers schema analog, component #33)
MSG_METADATA_REQ = 1
MSG_METADATA_RESP = 2
MSG_TRANSFER_REQ = 3
MSG_BLOCK_CHUNK = 4
MSG_ERROR = 5

_FRAME = struct.Struct("<BI")            # msg type, payload length


class TransportError(RuntimeError):
    """Fetch failure → the caller turns this into a recompute, the way
    TransferError becomes FetchFailedException (RapidsShuffleIterator.scala:82).
    ``retryable`` marks it safe to resubmit at the serving boundary (the
    recompute/failover ladders already ran server-side); pickles losslessly
    so the query endpoint can ship it to a remote client typed."""

    retryable = True


# frame-length sanity bound (transport.maxFrameBytes): a corrupt or hostile
# length prefix must raise a typed error BEFORE any allocation, not attempt
# a multi-GB read. Process-global like the codec registry; TcpTransport and
# the query endpoint apply their conf value at construction.
DEFAULT_MAX_FRAME_BYTES = 1 << 30
_max_frame_bytes = DEFAULT_MAX_FRAME_BYTES


def set_max_frame_bytes(n: int) -> None:
    global _max_frame_bytes
    _max_frame_bytes = int(n) if n and int(n) > 0 else DEFAULT_MAX_FRAME_BYTES


def max_frame_bytes() -> int:
    return _max_frame_bytes


def configure_socket(sock, *, timeout_s: "float | None" = None) -> None:
    """Shared socket discipline for every long-lived data-plane connection
    (shuffle fetch, query endpoint): SO_KEEPALIVE so the OS detects dead
    peers instead of only heartbeat expiry, TCP_NODELAY so small control
    frames are not nagled behind bulk data, aggressive keepalive probes
    where the platform exposes them, and an optional blocking timeout."""
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_KEEPALIVE, 1)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    # probe after 30s idle, every 10s, declare dead after 3 misses — only
    # where the platform exposes the knobs (Linux); the portable SO_KEEPALIVE
    # default (2h) still beats no detection at all
    for opt, val in (("TCP_KEEPIDLE", 30), ("TCP_KEEPINTVL", 10),
                     ("TCP_KEEPCNT", 3)):
        if hasattr(socket, opt):
            try:
                sock.setsockopt(socket.IPPROTO_TCP, getattr(socket, opt), val)
            except OSError:
                pass
    if timeout_s is not None:
        sock.settimeout(timeout_s)


def _send_frame(sock, msg_type: int, payload: bytes):
    # chaos hook: an injected "transport:transport.send" fault models a peer
    # dying mid-stream (the write side never completes the frame)
    F.maybe_inject("transport", "transport.send")
    sock.sendall(_FRAME.pack(msg_type, len(payload)) + payload)


def _recv_exact(sock, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise TransportError("peer closed connection")
        buf.extend(chunk)
    return bytes(buf)


def _recv_frame(sock, max_bytes: "int | None" = None):
    # chaos hook: an injected "transport:transport.recv" fault models a
    # truncated/NEVER-arriving frame on the read side
    F.maybe_inject("transport", "transport.recv")
    hdr = _recv_exact(sock, _FRAME.size)
    msg_type, length = _FRAME.unpack(hdr)
    limit = max_bytes if max_bytes is not None else _max_frame_bytes
    if length > limit:
        raise TransportError(
            f"frame length {length} exceeds transport.maxFrameBytes={limit} "
            "(corrupt or truncated length prefix)")
    return msg_type, _recv_exact(sock, length)


# public aliases: the query endpoint (runtime/endpoint.py) speaks the same
# length-prefixed frame protocol over its own message-id space
send_frame = _send_frame
recv_frame = _recv_frame


# -- trace-context propagation over the wire ---------------------------------
# Request payloads (MSG_METADATA_REQ / MSG_TRANSFER_REQ) carry the fetching
# query's trace id as trailing UTF-8 bytes after their fixed-width fields, so
# spans the SERVING process emits while a reducer pulls (D2H serialize,
# compress, chunked send) land on the same merged timeline as the reducer's
# own spans. Absent bytes (an empty suffix) mean no ambient trace.

def _trace_suffix() -> bytes:
    tid = tracing.current_trace_id()
    return tid.encode("utf-8") if tid else b""


def _decode_trace(payload: bytes, offset: int) -> "str | None":
    if len(payload) <= offset:
        return None
    return payload[offset:].decode("utf-8", "replace")


class BlockMeta:
    """TableMeta analog: (block index, serialized+compressed size)."""

    __slots__ = ("index", "size")

    def __init__(self, index: int, size: int):
        self.index = index
        self.size = size


# blocks written without a seq tag sort after every tagged block (the
# store's _ordered contract); on the wire that is a sentinel key
_NO_KEY = (1 << 62, 1 << 62)
# wire sentinel for "no checksum" (shuffle.checksum.enabled=false on the
# serving side): a real CRC fits 32 bits, so this value can never collide
_NO_CRC = 1 << 62


def _encode_seq(seq) -> tuple:
    """Normalize a store seq tag to the fixed two-int wire key."""
    if (isinstance(seq, tuple) and 1 <= len(seq) <= 2
            and all(isinstance(x, int) and 0 <= x < _NO_KEY[0] for x in seq)):
        return (seq[0], seq[1] if len(seq) == 2 else 0)
    return _NO_KEY


class RapidsShuffleTransport:
    """Trait: make a server for local blocks + clients for peers
    (reference RapidsShuffleTransport:328)."""

    def make_client(self, peer_address) -> "ShuffleClient":
        raise NotImplementedError

    def shutdown(self):
        pass

    @staticmethod
    def make_transport(conf) -> "RapidsShuffleTransport":
        """Instantiate by conf classname (reference makeTransport:558)."""
        import importlib
        clsname = conf.get(CFG.SHUFFLE_TRANSPORT_CLASS)
        mod, _, name = clsname.rpartition(".")
        cls = getattr(importlib.import_module(mod), name)
        return cls(conf)


class ShuffleClient:
    def fetch_blocks(self, shuffle_id: int, reduce_id: int):
        """Yield deserialized ColumnarBatches for one reduce partition."""
        raise NotImplementedError

    def fetch_blocks_with_keys(self, shuffle_id: int, reduce_id: int):
        """Yield (sort_key, batch): sort_key is the block's (map_split,
        seq) wire key so a multi-peer reducer can merge the union into one
        canonical order. Default keeps per-client order with the no-key
        sentinel (single-peer readers never need the merge)."""
        for b in self.fetch_blocks(shuffle_id, reduce_id):
            yield _NO_KEY, b


# ---------------------------------------------------------------------------
# Local (loopback) transport — reference's short-circuit RapidsCachingReader
# ---------------------------------------------------------------------------

class LocalTransport(RapidsShuffleTransport):
    def __init__(self, conf=None):
        self.store = ShuffleBlockStore.get()

    def make_client(self, peer_address=None):
        store = self.store

        class _Local(ShuffleClient):
            def fetch_blocks(self, shuffle_id, reduce_id):
                for _, b in self.fetch_blocks_with_keys(shuffle_id,
                                                        reduce_id):
                    yield b

            def fetch_blocks_with_keys(self, shuffle_id, reduce_id):
                for seq, b in store.read_partition_with_keys(shuffle_id,
                                                             reduce_id):
                    # in-process store read: zero network bytes, payload
                    # units only, under the `local` link
                    MV.record("shuffle.recv", 0, link="local",
                              site="transport.local",
                              payload_bytes=b.device_memory_size())
                    yield _encode_seq(seq), b
        return _Local()


# ---------------------------------------------------------------------------
# TCP transport — the UCX stand-in (windowed chunks + inflight throttle)
# ---------------------------------------------------------------------------

class _ServerHandler(socketserver.BaseRequestHandler):
    def handle(self):
        server: TcpShuffleServer = self.server.owner  # type: ignore
        sock = self.request
        # movement ledger link class of this connection's peer, classified
        # once per connection (a fetcher on this host is loopback, not tcp)
        try:
            self._link = MV.classify_peer(sock.getpeername())
        except OSError:
            self._link = "loopback"
        # the whole connection is served on this thread, so the link class
        # can steer per-link policy (compress only genuinely-tcp peers)
        # without changing the serialized_blocks patch-point signature
        server._serving_link.link = self._link
        try:
            while True:
                try:
                    msg_type, payload = _recv_frame(sock)
                except TransportError:
                    return
                if msg_type == MSG_METADATA_REQ:
                    self._metadata(server, sock, payload)
                elif msg_type == MSG_TRANSFER_REQ:
                    self._transfer(server, sock, payload)
                else:
                    _send_frame(sock, MSG_ERROR,
                                f"bad message {msg_type}".encode())
        except (ConnectionError, BrokenPipeError, TransportError):
            # a transport fault mid-dispatch (incl. injected chaos faults)
            # drops the connection — the client observes peer death
            return

    def _blocks(self, server, shuffle_id, reduce_id):
        blobs = server.serialized_blocks(shuffle_id, reduce_id)
        return blobs

    def _metadata(self, server, sock, payload):
        shuffle_id, reduce_id = struct.unpack_from("<II", payload, 0)
        with tracing.trace_context(_decode_trace(payload, 8)), \
                tracing.span("shuffle.serve.metadata", shuffle=shuffle_id,
                             reduce=reduce_id):
            try:
                # first fetcher pays the D2H serialize + compress here —
                # the span makes that cost visible on the serving process
                blobs = self._blocks(server, shuffle_id, reduce_id)
                keys = server.block_keys(shuffle_id, reduce_id)
                crcs = server.block_crcs(shuffle_id, reduce_id)
            except KeyError:
                _send_frame(sock, MSG_ERROR,
                            f"unknown shuffle {shuffle_id}".encode())
                return
        # per block: size + the store's (map_split, seq) key, so a reducer
        # merging several peers can reconstruct one canonical block order,
        # plus the block's CRC (the sentinel below = checksums disabled)
        if len(keys) != len(blobs):       # raced a concurrent write: re-read
            keys = (keys + [None] * len(blobs))[:len(blobs)]
        if len(crcs) != len(blobs):
            crcs = (crcs + [_NO_CRC] * len(blobs))[:len(blobs)]
        out = io.BytesIO()
        out.write(struct.pack("<I", len(blobs)))
        for b, k, c in zip(blobs, keys, crcs):
            k0, k1 = _encode_seq(k)
            out.write(struct.pack("<QQQQ", len(b), k0, k1, c))
        _send_frame(sock, MSG_METADATA_RESP, out.getvalue())

    def _transfer(self, server, sock, payload):
        shuffle_id, reduce_id, index, chunk = struct.unpack_from(
            "<IIIQ", payload, 0)
        with tracing.trace_context(_decode_trace(payload, 20)), \
                tracing.span("shuffle.serve.block", shuffle=shuffle_id,
                             reduce=reduce_id, index=index):
            try:
                blobs, payload_sizes = server.serve_entry(shuffle_id,
                                                          reduce_id)
                blob = blobs[index]
            except (KeyError, IndexError):
                _send_frame(sock, MSG_ERROR, b"unknown block")
                return
            import time as _time
            t0 = _time.perf_counter()
            # windowed send: bounce-buffer-sized chunks
            # (WindowedBlockIterator)
            for off in range(0, len(blob), chunk):
                piece = blob[off:off + chunk]
                hdr = struct.pack("<IIQ", index,
                                  1 if off + chunk >= len(blob) else 0, off)
                _send_frame(sock, MSG_BLOCK_CHUNK, hdr + piece)
            MV.record("shuffle.send", len(blob),
                      link=getattr(self, "_link", "loopback"),
                      site="transport.serve",
                      payload_bytes=payload_sizes[index],
                      seconds=_time.perf_counter() - t0)


class TcpShuffleServer:
    """Serves local shuffle blocks to peers (reference RapidsShuffleServer:71).
    Device-resident blocks are serialized (D2H) once on first request and the
    frames cached for subsequent fetchers.

    With ``tcp_only`` (the compression.tcpOnly knob) the codec is applied per
    connection LINK CLASS: only genuinely cross-host (``tcp``) peers get
    compressed frames — loopback fetchers on the same box pay the raw wire,
    which is free, instead of an lz4 round-trip, which is not. Frames are
    cached per (shuffle, reduce, compressed?) variant so a mixed audience
    never sees a frame built for the other link class."""

    def __init__(self, store: ShuffleBlockStore, codec: TableCompressionCodec,
                 port: int = 0, num_threads: int = 4, checksum: bool = True,
                 tcp_only: bool = True):
        self.store = store
        self.codec = codec
        self.checksum = checksum
        self.tcp_only = tcp_only
        self.compressor = BatchedTableCompressor(codec, num_threads)
        # per-connection-thread link class, set by _ServerHandler.handle();
        # lets serialized_blocks keep its (sid, rid) signature (tests and
        # fault injectors patch it) while still serving per-link variants
        self._serving_link = threading.local()
        self._cache_lock = threading.Lock()
        self._frame_cache: dict = {}
        # per-block store-unit sizes (device_memory_size of the block as
        # registered — the unit partition_sizes speaks), cached alongside
        # the frames so the movement ledger's shuffle.send payload column
        # cross-checks against map-output statistics
        self._payload_cache: dict = {}
        # drop cached frames when the shuffle itself is unregistered
        store.add_unregister_listener(self.invalidate)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
        self._srv = _Server(("127.0.0.1", port), _ServerHandler)
        self._srv.owner = self
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True, name="shuffle-server")
        self._thread.start()

    def _compress_serving(self) -> bool:
        """Whether frames built for the CURRENT connection thread should be
        codec-compressed: never for the none codec, always when tcpOnly is
        off, otherwise only when the peer classified as cross-host tcp."""
        if self.codec.codec_id == CODEC_NONE:
            return False
        if not self.tcp_only:
            return True
        return getattr(self._serving_link, "link", None) == "tcp"

    def serialized_blocks(self, shuffle_id: int, reduce_id: int) -> list:
        compress = self._compress_serving()
        key = (shuffle_id, reduce_id, compress)
        with self._cache_lock:
            if key in self._frame_cache:
                return self._frame_cache[key][0]
        keys, frames, payloads = [], [], []
        for seq, b in self.store.read_partition_with_keys(shuffle_id,
                                                          reduce_id):
            keys.append(seq)
            payloads.append(b.device_memory_size())
            frames.append(ser.serialize_batch(b))
        if compress:
            frames = self.compressor.compress_all(frames)
        if self.checksum:
            from spark_rapids_tpu.runtime.checksum import block_checksum
            crcs = [block_checksum(f) for f in frames]
        else:
            crcs = [_NO_CRC] * len(frames)
        with self._cache_lock:
            self._frame_cache[key] = (frames, keys, crcs)
            self._payload_cache[key] = payloads
        return frames

    def block_keys(self, shuffle_id: int, reduce_id: int) -> list:
        """Ordered seq tags matching serialized_blocks' frame order (served
        from the same cache; falls back to the store for patched/uncached
        paths)."""
        key = (shuffle_id, reduce_id, self._compress_serving())
        with self._cache_lock:
            if key in self._frame_cache:
                return self._frame_cache[key][1]
        return self.store.partition_keys(shuffle_id, reduce_id)

    def block_crcs(self, shuffle_id: int, reduce_id: int) -> list:
        """Per-frame CRCs matching serialized_blocks' order (the sentinel
        when checksums are off or the cache was raced)."""
        key = (shuffle_id, reduce_id, self._compress_serving())
        with self._cache_lock:
            if key in self._frame_cache:
                return self._frame_cache[key][2]
        return []

    def block_payload_sizes(self, shuffle_id: int, reduce_id: int) -> list:
        """Store-unit bytes per served block, matching serialized_blocks'
        frame order (empty when the cache was invalidated mid-serve)."""
        key = (shuffle_id, reduce_id, self._compress_serving())
        with self._cache_lock:
            return self._payload_cache.get(key, [])

    def serve_entry(self, shuffle_id: int, reduce_id: int) -> tuple:
        """Frames plus their matching store-unit payload sizes, snapshotted
        as one consistent pair BEFORE the frames are served. Frame lookup
        goes through serialized_blocks (the fault-injection patch point);
        if invalidate() races between the build and the payload snapshot
        the pair is rebuilt, so a served block is never metered with
        payload_bytes=0 just because its shuffle was unregistered mid-send."""
        key = (shuffle_id, reduce_id, self._compress_serving())
        blobs: list = []
        for _ in range(2):
            blobs = self.serialized_blocks(shuffle_id, reduce_id)
            with self._cache_lock:
                payloads = self._payload_cache.get(key)
            if payloads is not None and len(payloads) == len(blobs):
                return blobs, payloads
        return blobs, [0] * len(blobs)

    def invalidate(self, shuffle_id: int):
        with self._cache_lock:
            for key in [k for k in self._frame_cache if k[0] == shuffle_id]:
                del self._frame_cache[key]
                self._payload_cache.pop(key, None)

    def close(self):
        self._srv.shutdown()
        self._srv.server_close()
        self.compressor.close()


class TcpShuffleClient(ShuffleClient):
    """Fetch remote blocks with windowing + inflight-bytes throttle
    (reference RapidsShuffleClient.doFetch:194 / issueBufferReceives:300,
    throttle UCXShuffleTransport.scala:51-56)."""

    def __init__(self, address, bounce_bytes: int,
                 throttle: "InflightThrottle"):
        self.address = address
        self.bounce_bytes = bounce_bytes
        self.throttle = throttle
        # loopback vs cross-host, decided once from the peer address
        self.link = MV.classify_peer(address)

    def _decoded(self, blob):
        """Decode one wire frame and meter its block-store-unit size into
        the movement ledger (payload-only follow-up to the wire-bytes
        record _fetch_serialized already made — the ledger cell carries
        both units)."""
        batch = ser.deserialize_batch(TableCompressionCodec.decode(blob))
        MV.record("shuffle.recv", 0, link=self.link, site="transport.fetch",
                  payload_bytes=batch.device_memory_size(), transfers=0)
        return batch

    def fetch_blocks(self, shuffle_id, reduce_id):
        for blob in self.fetch_serialized(shuffle_id, reduce_id):
            yield self._decoded(blob)

    def fetch_blocks_with_keys(self, shuffle_id, reduce_id):
        for key, blob in self.fetch_serialized_with_keys(shuffle_id,
                                                         reduce_id):
            yield key, self._decoded(blob)

    def fetch_serialized(self, shuffle_id, reduce_id):
        for _, blob in self.fetch_serialized_with_keys(shuffle_id, reduce_id):
            yield blob

    def fetch_serialized_with_keys(self, shuffle_id, reduce_id):
        # every socket failure — refused connect, reset/broken pipe mid-
        # stream, timeout — must surface as TransportError: the exchange's
        # recompute ladder (and the reference's TransferError→
        # FetchFailedException mapping) keys on it, and a raw OSError would
        # escape the retry entirely
        try:
            yield from self._fetch_serialized(shuffle_id, reduce_id)
        except TransportError:
            raise
        except OSError as e:
            raise TransportError(
                f"peer {self.address} fetch failed: {e}") from e

    def _fetch_serialized(self, shuffle_id, reduce_id):
        sock = socket.create_connection(self.address, timeout=30)
        # keepalive + nodelay + timeout: a peer that died without closing is
        # detected by the OS probes / the socket timeout, not only by the
        # heartbeat manager's (much slower) expiry ladder
        configure_socket(sock, timeout_s=30)
        trace = _trace_suffix()
        try:
            _send_frame(sock, MSG_METADATA_REQ,
                        struct.pack("<II", shuffle_id, reduce_id) + trace)
            msg_type, payload = _recv_frame(sock)
            if msg_type == MSG_ERROR:
                raise TransportError(payload.decode())
            (n_blocks,) = struct.unpack_from("<I", payload, 0)
            metas = [struct.unpack_from("<QQQQ", payload, 4 + 32 * i)
                     for i in range(n_blocks)]
            for index, (size, k0, k1, crc) in enumerate(metas):
                with self.throttle.acquire(size):
                    import time as _time
                    t0 = _time.perf_counter()
                    # span scoped to the wire transfer only — the trailing
                    # yield suspends this generator at the consumer's pace,
                    # which must not inflate the fetch span
                    with tracing.span("shuffle.fetch.block",
                                      shuffle=shuffle_id, reduce=reduce_id,
                                      index=index, bytes=size):
                        _send_frame(sock, MSG_TRANSFER_REQ,
                                    struct.pack("<IIIQ", shuffle_id,
                                                reduce_id, index,
                                                self.bounce_bytes) + trace)
                        buf = bytearray()
                        while True:
                            msg_type, payload = _recv_frame(sock)
                            if msg_type == MSG_ERROR:
                                raise TransportError(payload.decode())
                            assert msg_type == MSG_BLOCK_CHUNK, msg_type
                            bidx, last, off = struct.unpack_from(
                                "<IIQ", payload, 0)
                            buf.extend(payload[16:])
                            if last:
                                break
                    # wire bytes crossed the link even when the CRC check
                    # below rejects the block — the fetch ladder's abort
                    # then reclassifies them onto the shuffle.retry edge
                    MV.record("shuffle.recv", len(buf), link=self.link,
                              site="transport.fetch", payload_bytes=0,
                              seconds=_time.perf_counter() - t0)
                    if len(buf) != size:
                        raise TransportError(
                            f"short block: got {len(buf)} want {size}")
                    # chaos checkpoint ("corrupt:transport.corrupt:N"): flip
                    # a byte of the reassembled block so the CRC below must
                    # catch it — proving mismatch → TransportError → the
                    # fetch retry/failover/recompute ladder, end to end
                    block = F.maybe_corrupt("transport.corrupt", bytes(buf))
                    if crc != _NO_CRC:
                        from spark_rapids_tpu.runtime.checksum import \
                            block_checksum
                        got = block_checksum(block)
                        if got != crc:
                            raise TransportError(
                                f"shuffle {shuffle_id} reduce {reduce_id} "
                                f"block {index} checksum mismatch (sent "
                                f"{crc:#x}, got {got:#x}, {size}B)")
                    yield (k0, k1), block
        finally:
            sock.close()


class InflightThrottle:
    """Bound total bytes in flight across all fetches
    (reference UCXShuffleTransport.scala:51-56)."""

    def __init__(self, max_bytes: int):
        self.max_bytes = max_bytes
        self._lock = threading.Condition()
        self._inflight = 0

    class _Token:
        def __init__(self, outer, n):
            self.outer = outer
            self.n = n

        def __enter__(self):
            with self.outer._lock:
                while (self.outer._inflight > 0
                       and self.outer._inflight + self.n > self.outer.max_bytes):
                    self.outer._lock.wait()
                self.outer._inflight += self.n
            return self

        def __exit__(self, *exc):
            with self.outer._lock:
                self.outer._inflight -= self.n
                self.outer._lock.notify_all()
            return False

    def acquire(self, n: int) -> "_Token":
        return self._Token(self, n)


class TcpTransport(RapidsShuffleTransport):
    """Server + client factory over TCP (the UCXShuffleTransport analog)."""

    def __init__(self, conf=None):
        from spark_rapids_tpu.config import RapidsConf
        conf = conf or RapidsConf()
        codec = get_codec(conf.get(CFG.SHUFFLE_COMPRESSION_CODEC))
        set_max_frame_bytes(conf.get(CFG.TRANSPORT_MAX_FRAME_BYTES))
        self.store = ShuffleBlockStore.get()
        self.server = TcpShuffleServer(
            self.store, codec, checksum=conf.get(CFG.SHUFFLE_CHECKSUM),
            tcp_only=conf.get(CFG.SHUFFLE_COMPRESSION_TCP_ONLY))
        self.bounce_bytes = conf.get(CFG.SHUFFLE_BOUNCE_BUFFER_SIZE)
        self.throttle = InflightThrottle(conf.get(CFG.SHUFFLE_MAX_INFLIGHT_BYTES))

    @property
    def port(self):
        return self.server.port

    def make_client(self, peer_address) -> ShuffleClient:
        return TcpShuffleClient(peer_address, self.bounce_bytes, self.throttle)

    def shutdown(self):
        self.server.close()
