"""Catalog-backed shuffle manager — device-resident shuffle with spillable blocks.

Reference (SURVEY.md components #29/#30/#36):
- RapidsShuffleInternalManagerBase.scala:200 — a ShuffleManager whose writer caches
  shuffle output in the spill-store catalog instead of writing Spark files
  (`RapidsCachingWriter`:73), and whose reader short-circuits local blocks from the
  catalog (`RapidsCachingReader`).
- ShuffleBufferCatalog.scala — maps (shuffle, map, reduce) block ids to buffers.
- GpuColumnarBatchSerializer.scala:50 — serializing fallback for the vanilla path.

Here the "cluster" is the local task scheduler (exec/base.py) plus the distributed
Mesh path (distributed/); this manager is the single-process block store both use.
Blocks are registered spillable at OUTPUT_FOR_SHUFFLE priority so shuffle data is
evicted from HBM first, exactly like the reference's SpillPriorities contract.
"""

from __future__ import annotations

import itertools
import threading

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.runtime import memory as mem
from spark_rapids_tpu.shuffle import serialization as ser


class ShuffleBlockStore:
    """Process-wide shuffle block registry (ShuffleBufferCatalog analog)."""

    _instance = None
    _ilock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._shuffle_ids = itertools.count(0)
        # shuffle_id -> reduce_id -> list[SpillableColumnarBatch]
        self._blocks: dict[int, dict[int, list]] = {}
        self._serialized_mode: dict[int, bool] = {}
        # notified on unregister AND on any block mutation (write into an
        # existing shuffle, drop_map_output) so transports drop their
        # serialized-frame caches: after a partial stage recompute adds a
        # lost split's blocks to a SURVIVING executor, a reducer re-fetch
        # must not be served the stale pre-recompute frames
        self._unregister_listeners: list = []

    def add_unregister_listener(self, cb) -> None:
        with self._lock:
            self._unregister_listeners.append(cb)

    @classmethod
    def get(cls) -> "ShuffleBlockStore":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = ShuffleBlockStore()
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._ilock:
            if cls._instance is not None:
                cls._instance.clear_all()
            cls._instance = None

    def register_shuffle(self, serialized: bool = False) -> int:
        with self._lock:
            sid = next(self._shuffle_ids)
            self._blocks[sid] = {}
            self._serialized_mode[sid] = serialized
            return sid

    def ensure_shuffle(self, shuffle_id: int, serialized: bool = False):
        """Register a DRIVER-assigned shuffle id (MiniCluster executors must
        agree on ids across processes, so the local counter cannot be used)."""
        with self._lock:
            self._blocks.setdefault(shuffle_id, {})
            self._serialized_mode.setdefault(shuffle_id, serialized)

    # -- write side (RapidsCachingWriter.write:90) ---------------------------
    def write_block(self, shuffle_id: int, reduce_id: int,
                    batch: ColumnarBatch, seq=None):
        """`seq` (any ordered tuple, e.g. (map_split, batch_index)) pins
        this block's position within the reduce partition independent of
        WRITE order — concurrent map tasks (thread pool + pipeline stages)
        finish in scheduler order, but order-sensitive consumers (first/
        last aggregates) need a stable stream. None appends in arrival
        order after all seq-tagged blocks (the pre-pipeline behavior)."""
        serialized = self._serialized_mode[shuffle_id]
        if serialized:
            blob = ser.serialize_batch(batch)
        else:
            # heap-profiler attribution: inherit the retry ladder's scope
            # ("exchange.write") when the exchange exec drives this; direct
            # writers (tests, recompute paths) fall back to a named site
            # instead of the unattributed bucket
            from spark_rapids_tpu.runtime import faults as F
            with mem.alloc_site(F.current_scope() or "exchange.block"):
                blob = mem.SpillableColumnarBatch(
                    batch, priority=mem.OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY)
        with self._lock:
            lst = self._blocks[shuffle_id].setdefault(reduce_id, [])
            lst.append((seq, len(lst), blob))
            listeners = list(self._unregister_listeners)
        for cb in listeners:
            cb(shuffle_id)

    @staticmethod
    def _ordered(entries):
        return sorted(entries, key=lambda e: (
            (0, e[0]) if e[0] is not None else (1,), e[1]))

    # -- read side (RapidsCachingReader / RapidsShuffleIterator) -------------
    def read_partition(self, shuffle_id: int, reduce_id: int):
        for _, batch in self.read_partition_with_keys(shuffle_id, reduce_id):
            yield batch

    def read_partition_with_keys(self, shuffle_id: int, reduce_id: int):
        """Yield (seq, batch) in the partition's pinned order. The seq key
        crosses the transport so a reducer can merge blocks from SEVERAL
        peers into one canonical (map_split, seq) order — after a partial
        stage recompute moves a map split to a different executor, the
        reduce-side stream must still be bit-identical to a clean run."""
        with self._lock:
            entries = self._ordered(self._blocks[shuffle_id].get(reduce_id, ()))
        for seq, _, blob in entries:
            if isinstance(blob, bytes):
                yield seq, ser.deserialize_batch(blob)
            else:
                yield seq, blob.get_batch()

    def partition_keys(self, shuffle_id: int, reduce_id: int) -> list:
        """Just the ordered seq tags of one partition's blocks (no blob
        access) — the transport metadata path ships these alongside sizes."""
        with self._lock:
            entries = self._ordered(self._blocks[shuffle_id].get(reduce_id,
                                                                 ()))
        return [seq for seq, _, _ in entries]

    def partition_sizes(self, shuffle_id: int, num_partitions: int) -> list:
        """Bytes per reduce partition — the map-output statistics AQE's
        coalescing decision reads (Spark MapOutputStatistics analog)."""
        with self._lock:
            parts = self._blocks.get(shuffle_id, {})
            out = []
            for pid in range(num_partitions):
                total = 0
                for _, _, b in parts.get(pid, ()):
                    total += len(b) if isinstance(b, bytes) else b.size
                out.append(total)
            return out

    def split_partition_sizes(self, shuffle_id: int, num_partitions: int,
                              map_split: int) -> list:
        """Bytes per reduce partition written by ONE map split (seq tuples
        lead with the map split — the MiniCluster writer contract). This is
        the per-split map-output statistic the driver's MapOutputTracker
        records for movement-aware reduce placement: after a partial
        recompute moves a split to another executor, the tracker re-adds
        these sizes under the new host and placement follows the bytes."""
        with self._lock:
            parts = self._blocks.get(shuffle_id, {})
            out = []
            for pid in range(num_partitions):
                total = 0
                for seq, _, b in parts.get(pid, ()):
                    if (isinstance(seq, tuple) and seq
                            and seq[0] == map_split):
                        total += len(b) if isinstance(b, bytes) else b.size
                out.append(total)
            return out

    def drop_map_output(self, shuffle_id: int, map_split: int) -> int:
        """Discard every block one map split wrote across all reduce
        partitions of `shuffle_id` (seq tuples lead with the map split —
        the MiniCluster writer contract). Used to evict a speculation
        LOSER's duplicate output so the winning attempt's blocks are the
        only copy; returns the number of blocks dropped."""
        dropped = []
        with self._lock:
            parts = self._blocks.get(shuffle_id)
            if parts is None:
                return 0
            for rid, entries in parts.items():
                keep = []
                for e in entries:
                    seq = e[0]
                    if (isinstance(seq, tuple) and seq
                            and seq[0] == map_split):
                        dropped.append(e)
                    else:
                        keep.append(e)
                parts[rid] = keep
            listeners = list(self._unregister_listeners)
        for _, _, b in dropped:
            if not isinstance(b, bytes):
                b.close()
        if dropped:
            for cb in listeners:
                cb(shuffle_id)
        return len(dropped)

    def unregister_shuffle(self, shuffle_id: int):
        with self._lock:
            parts = self._blocks.pop(shuffle_id, {})
            self._serialized_mode.pop(shuffle_id, None)
            listeners = list(self._unregister_listeners)
        for entries in parts.values():
            for _, _, b in entries:
                if not isinstance(b, bytes):
                    b.close()
        for cb in listeners:
            cb(shuffle_id)

    def clear_all(self):
        for sid in list(self._blocks):
            self.unregister_shuffle(sid)
