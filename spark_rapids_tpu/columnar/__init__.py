from spark_rapids_tpu.columnar.vector import (  # noqa: F401
    TpuColumnVector, bucket_capacity,
)
from spark_rapids_tpu.columnar.batch import ColumnarBatch  # noqa: F401
from spark_rapids_tpu.columnar import arrow as arrow_interop  # noqa: F401
