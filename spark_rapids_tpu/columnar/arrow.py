"""Arrow ↔ device conversion — the HostColumnarToGpu / arrow-import analog.

Reference: GpuColumnVector.from(ArrowColumnVector) and HostColumnarToGpu.scala:249
copy Arrow buffers into cudf device columns. Here pyarrow is the host columnar layer:
fixed-width buffers go to device as padded jax arrays; strings are dictionary-encoded
with an order-preserving (sorted) dictionary so device code-compares equal string
compares; decimals (p<=18) travel as scaled int64.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pyarrow as pa
import pyarrow.compute as pc

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
from spark_rapids_tpu.columnar.batch import ColumnarBatch


def _validity_of(arr: pa.Array) -> np.ndarray:
    return pc.is_valid(arr).to_numpy(zero_copy_only=False)


def _decimal_unscaled_int64(arr: pa.Array) -> np.ndarray:
    """Low 64 bits of the two's-complement decimal128 storage; exact for p<=18."""
    buf = arr.buffers()[1]
    words = np.frombuffer(buf, dtype=np.int64)
    off = arr.offset
    return words[off * 2:(off + len(arr)) * 2:2].copy()


def string_array_to_device(arr, capacity: int | None = None) -> TpuColumnVector:
    """Dictionary-encode a string array with a sorted dictionary, codes to device."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if pa.types.is_dictionary(arr.type):
        dict_vals, codes_arr = arr.dictionary, arr.indices
    else:
        enc = pc.dictionary_encode(arr.cast(pa.string()))
        dict_vals, codes_arr = enc.dictionary, enc.indices
    dict_vals = dict_vals.cast(pa.string())
    validity = _validity_of(arr)
    codes = codes_arr.fill_null(0).to_numpy(zero_copy_only=False).astype(np.int32)
    if len(dict_vals):
        order = pc.array_sort_indices(dict_vals)
        sorted_dict = dict_vals.take(order)
        rank = np.empty(len(dict_vals), dtype=np.int32)
        rank[order.to_numpy(zero_copy_only=False)] = np.arange(len(dict_vals), dtype=np.int32)
        codes = rank[codes]
    else:
        sorted_dict = dict_vals
    codes[~validity] = 0
    cv = TpuColumnVector.from_numpy(T.STRING, codes, validity, capacity)
    return cv.with_dictionary(sorted_dict)


def list_array_to_device(arr: pa.Array, dtype: T.ArrayType,
                         capacity: int | None = None):
    """List column → ListVector: flatten non-null lists into one padded flat
    element vector on device; row offsets stay host metadata (the same
    data/metadata split as string dictionaries)."""
    from spark_rapids_tpu.columnar.vector import ListVector
    validity = _validity_of(arr)
    lengths = pc.list_value_length(arr).fill_null(0).to_numpy(
        zero_copy_only=False).astype(np.int64)
    offsets = np.concatenate([[0], np.cumsum(lengths)])
    flat_arr = arr.flatten()  # elements of non-null lists, in row order
    flat = array_to_device(flat_arr, dtype.element_type,
                           bucket_capacity(len(flat_arr)))
    cap = capacity or bucket_capacity(len(arr))
    return ListVector(dtype, flat, offsets, validity, cap)


def array_to_device(arr, dtype: T.DataType | None = None,
                    capacity: int | None = None) -> TpuColumnVector:
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    dtype = dtype or T.from_arrow_type(arr.type)
    if isinstance(dtype, T.ArrayType):
        return list_array_to_device(arr, dtype, capacity)
    if isinstance(dtype, T.StringType):
        return string_array_to_device(arr, capacity)
    validity = _validity_of(arr)
    if isinstance(dtype, T.DecimalType):
        vals = _decimal_unscaled_int64(arr)
    elif isinstance(dtype, T.DateType):
        vals = arr.cast(pa.int32()).fill_null(0).to_numpy(zero_copy_only=False)
    elif isinstance(dtype, T.TimestampType):
        # normalize any source unit (s/ms/us/ns) to Spark's micros before the raw
        # int64 view; naive timestamps are taken as UTC
        us = pa.timestamp("us", tz=getattr(arr.type, "tz", None))
        vals = arr.cast(us).cast(pa.int64()).fill_null(0).to_numpy(zero_copy_only=False)
    elif isinstance(dtype, T.NullType):
        vals = np.zeros(len(arr), dtype=np.int8)
        validity = np.zeros(len(arr), dtype=bool)
    else:
        np_dt = T.to_numpy_dtype(dtype)
        vals = arr.fill_null(dtype.default_value()).to_numpy(
            zero_copy_only=False).astype(np_dt, copy=False)
    return TpuColumnVector.from_numpy(dtype, vals, validity, capacity)


def table_to_device(table, schema: T.StructType | None = None,
                    capacity: int | None = None) -> ColumnarBatch:
    if isinstance(table, pa.RecordBatch):
        table = pa.Table.from_batches([table])
    if schema is None:
        schema = T.StructType.from_arrow(table.schema)
    n = table.num_rows
    cap = capacity or bucket_capacity(n)
    cols = [array_to_device(table.column(i), schema[i].data_type, cap)
            for i in range(table.num_columns)]
    return ColumnarBatch(cols, n, schema)
