"""Fixed-width binary row format — the CudfUnsafeRow / row↔columnar
codegen analog (SURVEY.md #9).

Reference: GpuRowToColumnarExec.scala:788 + GeneratedUnsafeRowToCudfRowIterator
(:635) generate Janino code that copies UnsafeRow fixed-width fields into
packed device rows, and CudfUnsafeRow (java, 399 LoC) defines the packed
layout; GpuColumnarToRowExec:341 goes the other way. The point of the
codegen is to avoid per-row/per-field interpretation for FIXED-WIDTH
schemas. The TPU build's analog of "generate code per schema" is
"compute a strided layout per schema and execute it as whole-column numpy
ops": zero per-row Python, one pass per column.

Layout (UnsafeRow-flavored): each row is 8-byte words —
  [null bitset words][one 8-byte slot per field]
bools/ints zero-extended into their slot, floats/doubles bit-cast,
dates/timestamps as their integer representation. Variable-width columns
(strings) are out of the fast path, exactly like CudfUnsafeRow's
fixed-width restriction — callers fall back to arrow for those schemas.
"""

from __future__ import annotations

import numpy as np

from spark_rapids_tpu import types as T

_FIXED = (T.BooleanType, T.IntegerType, T.LongType, T.FloatType,
          T.DoubleType, T.DateType, T.TimestampType, T.DecimalType)


def is_fixed_width(schema) -> bool:
    return all(isinstance(f.data_type, _FIXED) for f in schema.fields)


def row_layout(schema):
    """(null_words, total_words): the per-schema 'generated code'."""
    nf = len(schema.fields)
    if nf > 64 * 8:
        raise NotImplementedError("more than 512 fields")
    null_words = max(1, -(-nf // 64))
    return null_words, null_words + nf


def _col_bits(dtype, data: np.ndarray) -> np.ndarray:
    """Column values → int64 slot bit patterns (vectorized)."""
    if isinstance(dtype, (T.FloatType,)):
        return np.ascontiguousarray(data.astype(np.float32)).view(
            np.int32).astype(np.int64) & 0xFFFFFFFF
    if isinstance(dtype, T.DoubleType):
        return np.ascontiguousarray(data.astype(np.float64)).view(np.int64)
    return data.astype(np.int64)


def _bits_to_col(dtype, words: np.ndarray):
    if isinstance(dtype, T.FloatType):
        return words.astype(np.int64).astype(np.uint64).astype(
            np.uint32).view(np.float32)
    if isinstance(dtype, T.DoubleType):
        return words.view(np.float64)
    if isinstance(dtype, T.BooleanType):
        return words.astype(bool)
    if isinstance(dtype, T.IntegerType) or isinstance(dtype, T.DateType):
        return words.astype(np.int32)
    return words.copy()


def pack_rows(batch) -> np.ndarray:
    """ColumnarBatch (fixed-width schema) → (n, total_words) int64 row
    buffer. One vectorized store per column; null bits packed per word."""
    schema = batch.schema
    if not is_fixed_width(schema):
        raise NotImplementedError("variable-width schema: use arrow")
    null_words, total = row_layout(schema)
    n = batch.num_rows
    out = np.zeros((n, total), np.int64)
    for j, f in enumerate(schema.fields):
        col = batch.column(j)
        data = np.asarray(col.data)[:n]
        valid = np.asarray(col.validity)[:n]
        out[:, null_words + j] = np.where(valid, _col_bits(f.data_type, data),
                                          0)
        w, bit = j // 64, j % 64
        out[:, w] |= np.where(valid, np.int64(0),
                              np.int64(1) << np.int64(bit))
    return out


def unpack_rows(rows: np.ndarray, schema):
    """(n, total_words) int64 row buffer → ColumnarBatch on device."""
    import jax.numpy as jnp
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity

    null_words, total = row_layout(schema)
    if rows.ndim != 2 or rows.shape[1] != total:
        raise ValueError(f"row buffer shape {rows.shape} != (*, {total})")
    n = rows.shape[0]
    cap = bucket_capacity(max(n, 1))
    cols = []
    for j, f in enumerate(schema.fields):
        w, bit = j // 64, j % 64
        null = (rows[:, w] >> np.int64(bit)) & 1
        valid_np = (null == 0)
        data_np = _bits_to_col(f.data_type, rows[:, null_words + j])
        want = f.data_type.jnp_dtype
        padded = np.zeros(cap, dtype=want)
        padded[:n] = np.where(valid_np, data_np,
                              f.data_type.default_value()).astype(want)
        vmask = np.zeros(cap, bool)
        vmask[:n] = valid_np
        cols.append(TpuColumnVector(f.data_type, jnp.asarray(padded),
                                    jnp.asarray(vmask)))
    return ColumnarBatch(cols, n, schema)


def pack_arrow(tbl, schema) -> np.ndarray:
    """Arrow table (fixed-width schema) → row buffer, host-only — no device
    round-trip (the session collect() result is already host arrow)."""
    import pyarrow as pa
    if not is_fixed_width(schema):
        raise NotImplementedError("variable-width schema: use arrow")
    null_words, total = row_layout(schema)
    n = tbl.num_rows
    out = np.zeros((n, total), np.int64)
    for j, f in enumerate(schema.fields):
        arr = tbl.column(j).combine_chunks()
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.chunk(0) if arr.num_chunks else pa.nulls(0, arr.type)
        valid = np.asarray(pa.compute.is_valid(arr))
        dt = f.data_type
        if isinstance(dt, T.DateType):
            arr = arr.cast(pa.int32())
        elif isinstance(dt, T.TimestampType):
            arr = arr.cast(pa.int64())
        if isinstance(dt, T.DecimalType):
            # engine/device repr is the scaled int64 (DECIMAL64); Decimal
            # objects carry it exactly
            data = np.array([0 if v is None else int(v.scaleb(dt.scale))
                             for v in arr.to_pylist()], np.int64)
        else:
            # fill nulls BEFORE to_numpy: a nullable int column would
            # otherwise come back as float64 and corrupt values > 2^53;
            # valid NaN floats must survive (fill_null only touches nulls)
            fill = (False if isinstance(dt, T.BooleanType)
                    else 0.0 if isinstance(dt, (T.FloatType, T.DoubleType))
                    else 0)
            filled = pa.compute.fill_null(arr, fill)
            data = filled.to_numpy(zero_copy_only=False)
            if isinstance(dt, T.BooleanType):
                data = data.astype(np.int64)
        out[:, null_words + j] = np.where(valid, _col_bits(dt, data), 0)
        w, bit = j // 64, j % 64
        out[:, w] |= np.where(valid, np.int64(0),
                              np.int64(1) << np.int64(bit))
    return out


def unpack_rows_arrow(rows: np.ndarray, schema):
    """Row buffer → arrow table, host-only (scan execution does the one
    real H2D upload later)."""
    import pyarrow as pa
    null_words, total = row_layout(schema)
    if rows.ndim != 2 or rows.shape[1] != total:
        raise ValueError(f"row buffer shape {rows.shape} != (*, {total})")
    cols, names = [], []
    for j, f in enumerate(schema.fields):
        w, bit = j // 64, j % 64
        valid = ((rows[:, w] >> np.int64(bit)) & 1) == 0
        data = _bits_to_col(f.data_type, rows[:, null_words + j])
        if isinstance(f.data_type, T.DecimalType):
            import decimal
            sc = f.data_type.scale
            vals = [None if not v else decimal.Decimal(int(x)).scaleb(-sc)
                    for x, v in zip(data, valid)]
            # scaleb of 0 keeps exponent 0; quantize for uniform scale
            q = decimal.Decimal(1).scaleb(-sc)
            vals = [None if v is None else v.quantize(q) for v in vals]
            cols.append(pa.array(vals, T.to_arrow_type(f.data_type)))
        else:
            cols.append(pa.array(data, T.to_arrow_type(f.data_type),
                                 mask=~valid))
        names.append(f.name)
    return pa.table(dict(zip(names, cols)))


# -- variable-width rows ------------------------------------------------------
# Reference: full UnsafeRow/CudfUnsafeRow semantics — a string field's 8-byte
# slot holds (offset << 32) | byteLength with offset relative to the row
# base, and the UTF-8 bytes live in the row's variable region after the
# fixed slots; rows stay 8-byte aligned. Because rows vary in length the
# buffer is (flat int64 words, int64 row offsets in words) instead of a 2-D
# matrix. Packing stays fully vectorized: one ragged byte-scatter built from
# arrow's own offsets buffers — zero per-row Python (the "codegen" stance of
# the fixed-width path, extended to strings; reference
# GpuRowToColumnarExec.scala:635 generated converter).

_VAR = (T.StringType,)


def is_packable(schema) -> bool:
    """Fixed-width or string columns — the full UnsafeRow surface."""
    return all(isinstance(f.data_type, _FIXED + _VAR) for f in schema.fields)


def _string_parts(arr):
    import pyarrow as pa
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    arr = arr.cast(pa.string())
    valid = np.asarray(pa.compute.is_valid(arr))
    # offsets/data straight from the arrow buffers (int32 offsets)
    bufs = arr.buffers()
    off = np.frombuffer(bufs[1], np.int32)[arr.offset:arr.offset + len(arr) + 1]
    data = np.frombuffer(bufs[2], np.uint8) if bufs[2] is not None else \
        np.zeros(0, np.uint8)
    lens = (off[1:] - off[:-1]).astype(np.int64)
    lens[~valid] = 0
    return valid, off[:-1].astype(np.int64), lens, data


def pack_arrow_var(tbl, schema):
    """Arrow table (fixed-width + string schema) → (words int64[total],
    row_offsets int64[n+1] in WORDS)."""
    import pyarrow as pa
    if not is_packable(schema):
        raise NotImplementedError(f"unsupported types in {schema}")
    null_words, base = row_layout(schema)
    n = tbl.num_rows
    var_cols = {}
    var_bytes = np.zeros(n, np.int64)
    for j, f in enumerate(schema.fields):
        if isinstance(f.data_type, T.StringType):
            parts = _string_parts(tbl.column(j))
            var_cols[j] = parts
            var_bytes += parts[2]
    row_words = base + ((var_bytes + 7) >> 3)
    offsets = np.zeros(n + 1, np.int64)
    np.cumsum(row_words, out=offsets[1:])
    words = np.zeros(int(offsets[-1]), np.int64)
    rows0 = offsets[:-1]

    # fixed slots + null bits (strided scatters, same as the 2-D path)
    for j, f in enumerate(schema.fields):
        w, bit = j // 64, j % 64
        if j in var_cols:
            continue
        arr = tbl.column(j).combine_chunks()
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.chunk(0) if arr.num_chunks else pa.nulls(0, arr.type)
        valid = np.asarray(pa.compute.is_valid(arr))
        dt = f.data_type
        if isinstance(dt, T.DateType):
            arr = arr.cast(pa.int32())
        elif isinstance(dt, T.TimestampType):
            arr = arr.cast(pa.int64())
        if isinstance(dt, T.DecimalType):
            data = np.array([0 if v is None else int(v.scaleb(dt.scale))
                             for v in arr.to_pylist()], np.int64)
        else:
            fill = (False if isinstance(dt, T.BooleanType)
                    else 0.0 if isinstance(dt, (T.FloatType, T.DoubleType))
                    else 0)
            data = pa.compute.fill_null(arr, fill).to_numpy(
                zero_copy_only=False)
            if isinstance(dt, T.BooleanType):
                data = data.astype(np.int64)
        words[rows0 + null_words + j] = np.where(
            valid, _col_bits(dt, data), 0)
        words[rows0 + w] |= np.where(valid, np.int64(0),
                                     np.int64(1) << np.int64(bit))

    # variable region: per-row running byte cursor across string columns
    bytes_view = words.view(np.uint8)   # little-endian words
    cursor = np.full(n, base * 8, np.int64)   # byte offset from row base
    for j, f in enumerate(schema.fields):
        if j not in var_cols:
            continue
        w, bit = j // 64, j % 64
        valid, src_off, lens, data = var_cols[j]
        slot = np.where(valid, (cursor << 32) | lens, 0)
        words[rows0 + null_words + j] = slot
        words[rows0 + w] |= np.where(valid, np.int64(0),
                                     np.int64(1) << np.int64(bit))
        total = int(lens.sum())
        if total:
            dst0 = rows0 * 8 + cursor            # absolute byte start per row
            starts = np.zeros(n, np.int64)
            np.cumsum(lens[:-1], out=starts[1:])
            within = np.arange(total, dtype=np.int64) - np.repeat(starts,
                                                                  lens)
            bytes_view[np.repeat(dst0, lens) + within] = \
                data[np.repeat(src_off, lens) + within]
        cursor += lens
    return words, offsets


def unpack_rows_arrow_var(words: np.ndarray, offsets: np.ndarray, schema):
    """(words, row_offsets) → arrow table (inverse of pack_arrow_var)."""
    import pyarrow as pa
    null_words, base = row_layout(schema)
    n = len(offsets) - 1
    rows0 = offsets[:-1]
    bytes_view = np.ascontiguousarray(words).view(np.uint8)
    cols, names = [], []
    for j, f in enumerate(schema.fields):
        w, bit = j // 64, j % 64
        valid = ((words[rows0 + w] >> np.int64(bit)) & 1) == 0
        slot = words[rows0 + null_words + j]
        if isinstance(f.data_type, T.StringType):
            lens = np.where(valid, slot & 0xFFFFFFFF, 0)
            rel = np.where(valid, slot >> 32, 0)
            src0 = rows0 * 8 + rel
            total = int(lens.sum())
            out_bytes = np.zeros(total, np.uint8)
            if total:
                starts = np.zeros(n, np.int64)
                np.cumsum(lens[:-1], out=starts[1:])
                within = np.arange(total, dtype=np.int64) - np.repeat(
                    starts, lens)
                out_bytes = bytes_view[np.repeat(src0, lens) + within]
            out_off = np.zeros(n + 1, np.int64)
            out_off[1:] = np.cumsum(lens)
            arr = pa.StringArray.from_buffers(
                n, pa.py_buffer(out_off.astype(np.int32).tobytes()),
                pa.py_buffer(out_bytes.tobytes()),
                pa.py_buffer(np.packbits(valid, bitorder="little").tobytes()))
            cols.append(arr)
        elif isinstance(f.data_type, T.DecimalType):
            import decimal
            sc = f.data_type.scale
            q = decimal.Decimal(1).scaleb(-sc)
            vals = [None if not v else
                    decimal.Decimal(int(x)).scaleb(-sc).quantize(q)
                    for x, v in zip(slot, valid)]
            cols.append(pa.array(vals, T.to_arrow_type(f.data_type)))
        else:
            data = _bits_to_col(f.data_type, slot)
            cols.append(pa.array(data, T.to_arrow_type(f.data_type),
                                 mask=~valid))
        names.append(f.name)
    return pa.table(dict(zip(names, cols)))
