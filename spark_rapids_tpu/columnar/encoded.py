"""Encoded-on-device column vectors — the H2D payload of the scan-side chain.

The device-decode scan path (io/parquet_native.py) used to expand every page
to a dense column in its own fused program before any consumer ran. With
encoded upload the scan ships the ENCODED page — bit-packed dictionary
indices, definition levels, and the dictionary — and the expansion happens
lazily inside the first consuming kernel (exec/aggregate.py's scan-fused
partial agg), so PCIe carries encoded bytes instead of dense columns. The
expansion body is ops/parquet_decode.decode_page_cols — the same trace the
standalone decode kernel runs — so encoded-vs-dense results are bit-identical
by construction.

Two layers:

- ``EncodedCol``: the pytree that crosses jit boundaries. Children are the
  device buffers (packed bytes/words, dictionary, def levels, count scalars);
  aux is the static ``EncodedPageSpec`` + dtype + DictRef'd host dictionary.
  ``decode()`` is traceable and returns an expr ``Col``.
- ``EncodedColumnVector``: the batch-level vector. Pretends to be a normal
  ``TpuColumnVector`` — ``data``/``validity`` are lazy properties that run
  the fused decode on first touch — so every consumer that does NOT fuse the
  prologue still sees a correct dense column (degraded, never wrong).
"""

from __future__ import annotations

import jax

from spark_rapids_tpu.columnar.vector import TpuColumnVector
from spark_rapids_tpu.ops import parquet_decode as PD


@jax.tree_util.register_pytree_node_class
class EncodedCol:
    """One encoded data page as a jit-crossable value."""

    __slots__ = ("packed", "dict_dev", "dl", "n_present_t", "n_t",
                 "spec", "dtype", "dictionary")

    def __init__(self, packed, dict_dev, dl, n_present_t, n_t,
                 spec: PD.EncodedPageSpec, dtype, dictionary=None):
        self.packed = packed            # padded bytes (or pallas words)
        self.dict_dev = dict_dev        # device dictionary / sorted-rank map
        self.dl = dl                    # def levels as bool, (capacity,)
        self.n_present_t = n_present_t  # int32 scalar, device
        self.n_t = n_t                  # int32 scalar, device (live rows)
        self.spec = spec
        self.dtype = dtype
        self.dictionary = dictionary    # host sorted pa.Array for strings

    def tree_flatten(self):
        d = self.dictionary
        if d is not None:
            from spark_rapids_tpu.runtime.fuse import DictRef
            d = DictRef(d)
        return ((self.packed, self.dict_dev, self.dl, self.n_present_t,
                 self.n_t), (self.spec, self.dtype, d))

    @classmethod
    def tree_unflatten(cls, aux, children):
        d = aux[2]
        if d is not None and type(d).__name__ == "DictRef":
            d = d.arr
        return cls(*children, aux[0], aux[1], d)

    def decode(self):
        """Traceable expansion to a dense expr Col (values, validity)."""
        from spark_rapids_tpu.expr.core import Col
        v, m = PD.decode_page_cols(self.spec, self.packed, self.dict_dev,
                                   self.dl, self.n_present_t, self.n_t)
        return Col(v, m, self.dtype, self.dictionary)


def densify_cols(cols):
    """Traceable prologue for fused kernels that accept mixed dense/encoded
    inputs: expand every EncodedCol to a dense expr Col in-trace (the page
    decode fuses into the consumer's program), pass everything else through.
    Kernels keep their semantic cache key — jit's argument structure and the
    fuse-layer signature both distinguish encoded from dense pytrees."""
    return [c.decode() if isinstance(c, EncodedCol) else c for c in cols]


class EncodedColumnVector(TpuColumnVector):
    """A TpuColumnVector whose dense arrays are built lazily by the fused
    page-decode kernel. ``capacity``/``device_memory_size`` answer without
    materializing; any read of ``data``/``validity`` expands once and caches.
    NOTE: runtime/pipeline.py's spill registration requires ``type(c) is
    TpuColumnVector`` exactly, so encoded vectors never spill mid-decode."""

    __slots__ = ("_enc", "_mat")

    def __init__(self, enc: EncodedCol):
        # parent __init__ would assign through the data/validity properties;
        # set the remaining parent slots directly instead
        self.dtype = enc.dtype
        self.dictionary = enc.dictionary
        self._dict_device = None
        self._enc = enc
        self._mat = None

    @property
    def encoded(self) -> "EncodedCol | None":
        """The encoded payload while still unexpanded, else None (a consumer
        that already forced `data` gains nothing from re-fusing the decode)."""
        return None if self._mat is not None else self._enc

    def _materialize(self):
        if self._mat is None:
            from spark_rapids_tpu.runtime import fuse
            e = self._enc
            spec = e.spec
            key = ("pq_page_decode", spec)

            def build():
                def kernel(packed_d, dict_d, dl_d, np_t, n_t):
                    return PD.decode_page_cols(spec, packed_d, dict_d, dl_d,
                                               np_t, n_t)
                return kernel

            args = (e.packed, e.dict_dev, e.dl, e.n_present_t, e.n_t)
            v, m = fuse.call_fused(key, "ParquetScan.decode", build, args,
                                   lambda: build()(*args))
            self._mat = (v, m)
        return self._mat

    @property
    def data(self):
        return self._materialize()[0]

    @property
    def validity(self):
        return self._materialize()[1]

    @property
    def capacity(self) -> int:
        return self._enc.spec.capacity

    def device_memory_size(self) -> int:
        """Bytes this vector actually put on the device: the encoded payload
        while unexpanded (this is what the h2d ledger should price), the
        dense arrays once someone forced them."""
        if self._mat is not None:
            sz = self._mat[0].nbytes + self._mat[1].nbytes
        else:
            e = self._enc
            sz = (e.packed.nbytes + e.dl.nbytes
                  + e.n_present_t.nbytes + e.n_t.nbytes)
        sz += self._enc.dict_dev.nbytes
        if self._dict_device is not None:
            sz += sum(a.nbytes for a in self._dict_device)
        return sz

    def encoded_payload_bytes(self) -> int:
        """H2D bytes of the encoded page (what crossed PCIe), independent of
        whether a consumer has since expanded it."""
        e = self._enc
        return (e.packed.nbytes + e.dl.nbytes + e.dict_dev.nbytes
                + e.n_present_t.nbytes + e.n_t.nbytes)

    def __repr__(self):
        state = "dense" if self._mat is not None else "encoded"
        return (f"EncodedColumnVector({self.dtype}, "
                f"cap={self.capacity}, {state})")
