"""Device column vectors — the GpuColumnVector analog, TPU-first.

Reference: sql-plugin/src/main/java/com/nvidia/spark/rapids/GpuColumnVector.java (1033
LoC) adapts cudf device columns to Spark ColumnarBatch. Here a column is:

- ``data``: a padded 1-D jax array on the accelerator. Capacities are bucketed to powers
  of two so a single jit-compiled kernel serves every batch in the bucket (XLA's
  static-shape regime — cudf has dynamic sizes, XLA must not).
- ``validity``: a padded bool jax array; padded tail slots are always invalid. Invalid
  slots hold the type's canonical default value so padding never perturbs hashes, sorts,
  or reductions (cudf instead carries a bit mask into every kernel).
- strings: ``data`` holds int32 codes into a **host-side sorted dictionary** (pyarrow
  StringArray). Codes are order-preserving (dictionary sorted at encode time), so device
  comparisons over codes ARE string comparisons; per-entry murmur3 hashes are computed
  once per dictionary so hash partitioning of strings also stays on device.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import pyarrow as pa
import pyarrow.compute as pc

from spark_rapids_tpu import types as T

_MIN_CAPACITY = 8


def bucket_capacity(n: int) -> int:
    """Smallest power-of-two capacity >= n (>= 8). Bounds the jit compile-cache."""
    cap = _MIN_CAPACITY
    while cap < n:
        cap <<= 1
    return cap


class TpuColumnVector:
    """One device column. Immutable once built (functional style, unlike cudf's
    refcounted mutable columns — XLA arrays are immutable so RAII shrinks to buffer
    accounting, see runtime/arm.py)."""

    __slots__ = ("dtype", "data", "validity", "dictionary", "_dict_device")

    def __init__(self, dtype: T.DataType, data, validity, dictionary: pa.Array | None = None,
                 dict_device=None):
        self.dtype = dtype
        self.data = data
        self.validity = validity
        self.dictionary = dictionary  # host pyarrow StringArray, sorted, for StringType
        # lazy (words int32 (D,W), lengths int32 (D,)) device packing of the dictionary's
        # UTF-8 bytes, shared by hashing and byte-level string kernels
        self._dict_device = dict_device

    # -- construction -------------------------------------------------------
    @staticmethod
    def from_numpy(dtype: T.DataType, values: np.ndarray, validity: np.ndarray | None = None,
                   capacity: int | None = None, dictionary: pa.Array | None = None):
        n = len(values)
        cap = capacity or bucket_capacity(n)
        np_dt = T.to_numpy_dtype(dtype)
        data = np.zeros(cap, dtype=np_dt)
        data[:n] = values
        valid = np.zeros(cap, dtype=bool)
        if validity is None:
            valid[:n] = True
        else:
            valid[:n] = validity
            # canonicalize nulls so padded/invalid slots are deterministic
            data[~valid] = dtype.default_value()
        return TpuColumnVector(dtype, jnp.asarray(data), jnp.asarray(valid), dictionary)

    @staticmethod
    def from_pylist(dtype: T.DataType, values, capacity: int | None = None):
        """Convenience for tests: None entries become nulls."""
        if isinstance(dtype, T.StringType):
            arr = pa.array(values, type=pa.string())
            from spark_rapids_tpu.columnar import arrow as ai
            return ai.string_array_to_device(arr, capacity=capacity)
        validity = np.array([v is not None for v in values], dtype=bool)
        np_dt = T.to_numpy_dtype(dtype)
        vals = np.array([v if v is not None else dtype.default_value() for v in values],
                        dtype=np_dt)
        return TpuColumnVector.from_numpy(dtype, vals, validity, capacity)

    @staticmethod
    def all_null(dtype: T.DataType, capacity: int):
        data = jnp.full((capacity,), dtype.default_value(), dtype=dtype.jnp_dtype)
        return TpuColumnVector(dtype, data, jnp.zeros((capacity,), dtype=jnp.bool_))

    # -- properties ---------------------------------------------------------
    @property
    def capacity(self) -> int:
        return int(self.data.shape[0])

    @property
    def is_string(self) -> bool:
        return isinstance(self.dtype, T.StringType)

    def device_memory_size(self) -> int:
        sz = self.data.nbytes + self.validity.nbytes
        if self._dict_device is not None:
            sz += sum(a.nbytes for a in self._dict_device)
        return sz

    # -- dictionary support -------------------------------------------------
    def dictionary_words(self):
        """Device packing of the dictionary's UTF-8 bytes as (words (D,W) int32,
        lengths (D,) int32), built once per dictionary. Byte-level device kernels
        (murmur3 with chained seeds, substring/length/like) gather rows from this
        matrix by code — the on-TPU stand-in for cudf's string columns."""
        if self._dict_device is None:
            from spark_rapids_tpu.ops.hashing import pack_utf8_words
            assert self.dictionary is not None
            strs = self.dictionary.to_pylist()
            words, lens = pack_utf8_words(strs)
            if words.shape[0] == 0:
                words = np.zeros((1, 1), dtype=np.int32)
                lens = np.zeros(1, dtype=np.int32)
            self._dict_device = (jnp.asarray(words), jnp.asarray(lens))
        return self._dict_device

    def with_dictionary(self, dictionary, data=None, validity=None):
        return TpuColumnVector(self.dtype, self.data if data is None else data,
                               self.validity if validity is None else validity,
                               dictionary)

    # -- host transfer ------------------------------------------------------
    def to_host(self, num_rows: int):
        """Copy the first num_rows to host numpy (values, validity)."""
        return (np.asarray(self.data[:num_rows]), np.asarray(self.validity[:num_rows]))

    def to_arrow(self, num_rows: int) -> pa.Array:
        vals, valid = self.to_host(num_rows)
        if self.is_string:
            codes = pa.array(vals.astype(np.int32), type=pa.int32())
            # all-null string columns (e.g. outer-join null extension) have no dict
            has_dict = self.dictionary is not None and len(self.dictionary)
            taken = self.dictionary.take(codes) if has_dict else pa.nulls(
                num_rows, pa.string())
            return pc.if_else(pa.array(valid), taken, pa.nulls(num_rows, pa.string()))
        if isinstance(self.dtype, T.DecimalType):
            # rebuild decimal128 from scaled int64 (low word + sign extension)
            words = np.zeros((num_rows, 2), dtype=np.int64)
            words[:, 0] = vals
            words[:, 1] = vals >> 63
            buf = pa.py_buffer(words.tobytes())
            mask = np.packbits(valid, bitorder="little")
            arr = pa.Array.from_buffers(
                pa.decimal128(self.dtype.precision, self.dtype.scale), num_rows,
                [pa.py_buffer(mask.tobytes()), buf])
            return arr
        at = T.to_arrow_type(self.dtype)
        arr = pa.array(vals, type=at if not isinstance(self.dtype, (T.DateType, T.TimestampType)) else None)
        if isinstance(self.dtype, T.DateType):
            arr = pa.array(vals.astype("int32")).cast(pa.date32())
        elif isinstance(self.dtype, T.TimestampType):
            arr = pa.array(vals.astype("int64")).cast(pa.timestamp("us", tz="UTC"))
        if not valid.all():
            arr = pc.if_else(pa.array(valid), arr, pa.nulls(num_rows, arr.type))
        return arr

    def block_until_ready(self):
        jax.block_until_ready(self.data)
        return self

    def __repr__(self):
        return (f"TpuColumnVector({self.dtype}, cap={self.capacity}"
                f"{', dict=' + str(len(self.dictionary)) if self.dictionary is not None else ''})")


class ListVector(TpuColumnVector):
    """Arrow-layout list column on device: a FLAT padded element vector plus
    host row offsets (list structure is metadata, elements are the data — the
    same split the I/O layer uses for string dictionaries).

    Exists only between the arrow bridge and GenerateExec (explode): every
    other exec's TypeSig rejects ArrayType, so the planner pins those to host
    (reference GpuGenerateExec.scala consumes cudf LIST columns the same way —
    the list column never survives past the generate).

    ``data`` holds per-row element counts (int32, nulls count 0) so device
    programs can expand without touching host metadata again; ``offsets`` is
    the host-side prefix (len num_rows+1) into ``flat``.
    """

    __slots__ = ("flat", "offsets", "host_validity")

    def __init__(self, dtype: T.DataType, flat: TpuColumnVector,
                 offsets: np.ndarray, validity: np.ndarray, capacity: int):
        n = len(offsets) - 1
        lengths = np.zeros(capacity, dtype=np.int32)
        lengths[:n] = np.diff(offsets)
        valid = np.zeros(capacity, dtype=bool)
        valid[:n] = validity
        super().__init__(dtype, jnp.asarray(lengths), jnp.asarray(valid))
        self.flat = flat
        self.offsets = np.asarray(offsets, dtype=np.int64)
        self.host_validity = np.asarray(validity, dtype=bool)

    @property
    def element_dtype(self) -> T.DataType:
        return self.dtype.element_type

    @property
    def total_elements(self) -> int:
        return int(self.offsets[-1])

    def device_memory_size(self) -> int:
        return self.data.nbytes + self.validity.nbytes + \
            self.flat.device_memory_size()

    def to_arrow(self, num_rows: int) -> pa.Array:
        flat_arr = self.flat.to_arrow(self.total_elements)
        off = self.offsets[:num_rows + 1]
        # a null slot in the offsets array marks a null list (pyarrow API)
        off_list = [None if (i < num_rows and not self.host_validity[i])
                    else int(off[i]) for i in range(num_rows + 1)]
        return pa.ListArray.from_arrays(pa.array(off_list, pa.int32()),
                                        flat_arr)

    def __repr__(self):
        return (f"ListVector({self.dtype}, cap={self.capacity}, "
                f"elems={self.total_elements})")
