"""ColumnarBatch — a set of device columns plus a row count.

Reference: Spark's ColumnarBatch wrapped by GpuColumnVector.from(Table)
(GpuColumnVector.java). TPU twist: ``num_rows`` may be a *device scalar* while a fused
XLA stage is in flight (e.g. a filter's surviving-row count), and is only synced to a
host int at stage boundaries — cudf syncs after every kernel, we sync once per stage.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity


class ColumnarBatch:
    __slots__ = ("columns", "_num_rows", "schema", "metadata")

    def __init__(self, columns, num_rows, schema: T.StructType | None = None,
                 metadata: dict | None = None):
        self.columns = list(columns)
        self._num_rows = num_rows
        self.schema = schema
        # scan provenance (input file path/offsets) for the metadata
        # expressions (input_file_name family); None off the scan path
        self.metadata = metadata
        if self.columns:
            cap = self.columns[0].capacity
            assert all(c.capacity == cap for c in self.columns), \
                "all columns in a batch must share one padded capacity"

    @property
    def num_rows(self) -> int:
        """Host row count; forces a device sync if the count is still a device scalar."""
        if not isinstance(self._num_rows, int):
            self._num_rows = int(self._num_rows)
        return self._num_rows

    @property
    def lazy_num_rows(self):
        """Row count without forcing a sync (may be a jax scalar)."""
        return self._num_rows

    @property
    def num_cols(self) -> int:
        return len(self.columns)

    @property
    def capacity(self) -> int:
        return self.columns[0].capacity if self.columns else bucket_capacity(self.num_rows)

    def column(self, i: int) -> TpuColumnVector:
        return self.columns[i]

    def device_memory_size(self) -> int:
        return sum(c.device_memory_size() for c in self.columns)

    def with_columns(self, columns, schema=None):
        return ColumnarBatch(columns, self._num_rows, schema or self.schema,
                             metadata=self.metadata)

    # -- host interop -------------------------------------------------------
    def to_arrow(self):
        import pyarrow as pa
        from spark_rapids_tpu.runtime import movement as _MV
        n = self.num_rows
        names = (self.schema.names if self.schema is not None
                 else [f"c{i}" for i in range(self.num_cols)])
        # device bytes crossing to the host at this boundary: one call
        # feeds the per-node stats ledger (d2hBytes) AND the movement
        # ledger's d2h/pcie edge (runtime/movement.py)
        _MV.record_d2h(self.device_memory_size())
        # from_arrays, not a dict: Spark allows duplicate output column names
        return pa.Table.from_arrays(
            [col.to_arrow(n) for col in self.columns], names=list(names))

    @staticmethod
    def from_arrow(table, schema: T.StructType | None = None) -> "ColumnarBatch":
        from spark_rapids_tpu.columnar import arrow as ai
        from spark_rapids_tpu.runtime import movement as _MV
        batch = ai.table_to_device(table, schema=schema)
        _MV.record_h2d(batch.device_memory_size())
        return batch

    @staticmethod
    def empty(schema: T.StructType) -> "ColumnarBatch":
        cap = bucket_capacity(0)
        cols = [TpuColumnVector.all_null(f.data_type, cap) for f in schema]
        return ColumnarBatch(cols, 0, schema)

    def __repr__(self):
        n = self._num_rows if isinstance(self._num_rows, int) else "<device>"
        return f"ColumnarBatch(rows={n}, cols={self.num_cols}, cap={self.capacity})"
