"""SQL front-end: text → AST → engine plan.

Reference role: the reference is a plugin inside Spark *SQL* — its whole test
surface is SQL text (reference integration_tests qa_nightly_sql.py; the
sql-plugin hooks Catalyst's physical planning). This framework is standalone,
so it ships the front-end itself: a recursive-descent parser over the SQL
subset the TPC-DS/TPC-H workloads exercise (SELECT / FROM comma+explicit
joins / WHERE / GROUP BY [ROLLUP] / HAVING / window OVER / ORDER BY / LIMIT /
scalar subqueries / derived tables / CASE / IN / BETWEEN / LIKE / CAST),
lowered onto plan/nodes.py, with the same analysis moves Catalyst makes
(filter pushdown into the join graph, equi-key extraction, aggregate/window
separation, rollup → Expand).
"""

from spark_rapids_tpu.sql.parser import parse_sql
from spark_rapids_tpu.sql.lower import lower_sql

__all__ = ["parse_sql", "lower_sql"]
