"""SQL AST → engine plan lowering (the Catalyst-analyzer role).

Pipeline per SELECT block, mirroring the moves Spark's analyzer/optimizer
makes before the reference plugin ever sees a plan:

1. FROM: resolve tables (catalog views / CTEs / derived tables), then plan
   the join graph — single-relation WHERE conjuncts push down as pre-join
   filters, two-relation equi conjuncts become hash-join keys (greedy
   connected-component join order), everything else lands in a post-join
   filter. Explicit JOIN ... ON splits its condition the same way.
2. Aggregation: distinct AggregateFunction subtrees (keyed by the fuse
   module's structural expr keys) become AggregateNode columns; GROUP BY
   ROLLUP lowers through ExpandNode with a grouping-id column exactly like
   Spark's Expand (reference GpuExpandExec role).
3. Window: post-aggregation WindowNode per distinct OVER expression.
4. HAVING → Filter; SELECT → Project; DISTINCT → group-by-all; ORDER BY
   resolves output names/aliases/ordinals (hidden sort columns are projected
   in and dropped after the sort); LIMIT → LimitNode.

Scalar subqueries execute eagerly at lowering time (expr/misc.ScalarSubquery
— same contract as Spark's pre-executed subquery stages).
"""

from __future__ import annotations

from spark_rapids_tpu import types as T
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr.aggregates import (
    AggregateFunction, Average, Count, Max, Min, StddevPop, StddevSamp, Sum,
    VariancePop, VarianceSamp, First, Last,
)
from spark_rapids_tpu.plan import nodes as NN
from spark_rapids_tpu.runtime import fuse
from spark_rapids_tpu.sql import parser as P


class SqlAnalysisError(ValueError):
    pass


def _special_datetime(s: str, to):
    """Spark's special datetime strings (epoch/now/today/yesterday/
    tomorrow) as a plan-time Literal, or None. Spark binds now/today to
    query-start time; this engine binds to plan time (UTC-only)."""
    import datetime as _dt
    name = s.strip().lower()
    if name not in ("epoch", "now", "today", "yesterday", "tomorrow"):
        return None
    now = _dt.datetime.now(_dt.timezone.utc)
    epoch = _dt.datetime(1970, 1, 1, tzinfo=_dt.timezone.utc)
    day = {"epoch": _dt.date(1970, 1, 1), "now": now.date(),
           "today": now.date(),
           "yesterday": now.date() - _dt.timedelta(days=1),
           "tomorrow": now.date() + _dt.timedelta(days=1)}[name]
    if isinstance(to, T.DateType):
        return E.Literal((day - _dt.date(1970, 1, 1)).days, T.DATE)
    if name == "now":
        micros = (now - epoch) // _dt.timedelta(microseconds=1)
    else:
        midnight = _dt.datetime(day.year, day.month, day.day,
                                tzinfo=_dt.timezone.utc)
        micros = (midnight - epoch) // _dt.timedelta(microseconds=1)
    return E.Literal(micros, T.TIMESTAMP)


# -- scopes -------------------------------------------------------------------

class Scope:
    """Columns of the current relation: (qualifier, name, dtype, nullable)
    per output position."""

    def __init__(self, cols):
        self.cols = list(cols)

    @classmethod
    def for_relation(cls, plan, qualifier):
        return cls([(qualifier, f.name, f.data_type, f.nullable)
                    for f in plan.output])

    def concat(self, other: "Scope") -> "Scope":
        return Scope(self.cols + other.cols)

    def find(self, parts) -> list:
        """Matching positions for a (possibly qualified) identifier."""
        if len(parts) == 1:
            name = parts[0].lower()
            return [i for i, (_, n, _, _) in enumerate(self.cols)
                    if n.lower() == name]
        qual, name = parts[0].lower(), parts[1].lower()
        return [i for i, (q, n, _, _) in enumerate(self.cols)
                if q is not None and q.lower() == qual and n.lower() == name]

    def resolve(self, parts) -> E.BoundReference:
        hits = self.find(parts)
        if not hits:
            raise SqlAnalysisError(f"column not found: {'.'.join(parts)}")
        if len(hits) > 1:
            raise SqlAnalysisError(f"ambiguous column: {'.'.join(parts)}")
        i = hits[0]
        _, name, dtype, nullable = self.cols[i]
        return E.BoundReference(i, dtype, nullable, name)

    def rel_of(self, parts, rel_ranges) -> int | None:
        """Which relation (by index into rel_ranges: [(lo, hi), ...]) a
        resolved column belongs to."""
        hits = self.find(parts)
        if len(hits) != 1:
            return None
        for ri, (lo, hi) in enumerate(rel_ranges):
            if lo <= hits[0] < hi:
                return ri
        return None


_TYPE_MAP = {
    "int": T.INT, "integer": T.INT, "smallint": T.SHORT, "tinyint": T.BYTE,
    "bigint": T.LONG, "long": T.LONG, "float": T.FLOAT, "real": T.FLOAT,
    "double": T.DOUBLE, "string": T.STRING, "date": T.DATE,
    "timestamp": T.TIMESTAMP, "boolean": T.BOOLEAN,
}


def _sql_type(name: str, args: tuple) -> T.DataType:
    if name in _TYPE_MAP:
        return _TYPE_MAP[name]
    if name in ("decimal", "numeric"):
        p = int(args[0]) if args else 10
        s = int(args[1]) if len(args) > 1 else 0
        return T.DecimalType(p, s)
    if name in ("char", "varchar"):
        return T.STRING
    raise SqlAnalysisError(f"unsupported cast type {name}")


_AGG_FUNCS = {
    "sum": Sum, "min": Min, "max": Max, "avg": Average,
    "stddev_samp": StddevSamp, "stddev": StddevSamp, "stddev_pop": StddevPop,
    "var_samp": VarianceSamp, "variance": VarianceSamp,
    "var_pop": VariancePop, "first": First, "last": Last,
}


class _Grouping(E.Expression):
    """Placeholder for GROUPING(col) until rollup lowering rewrites it to a
    grouping-id bit test; reaching eval means rollup wasn't in effect."""

    def __init__(self, ref: E.Expression):
        self.children = [ref]

    @property
    def dtype(self):
        return T.INT

    def with_children(self, children):
        return _Grouping(children[0])

    def eval(self, ctx):
        raise SqlAnalysisError("grouping() outside GROUP BY ROLLUP")


class _DistinctAgg(AggregateFunction):
    """Marker for fn(DISTINCT x); _aggregate rewrites it two-level (Spark's
    RewriteDistinctAggregates role: inner GROUP BY (keys, x) dedupes, outer
    re-aggregates) — reaching eval means the rewrite didn't run."""

    def __init__(self, fn_cls, child):
        super().__init__(child)
        self.fn_cls = fn_cls

    def make(self, ref):
        return self.fn_cls(ref)

    @property
    def dtype(self):
        return self.fn_cls(self.child).dtype

    def with_children(self, children):
        return _DistinctAgg(self.fn_cls, children[0])

    @property
    def state_types(self):
        raise SqlAnalysisError("DISTINCT aggregate outside rewrite")


# -- expression conversion ----------------------------------------------------

class _ExprConverter:
    def __init__(self, scope: Scope, lowerer: "_Lowerer"):
        self.scope = scope
        self.lowerer = lowerer

    def convert(self, a) -> E.Expression:
        c = self.convert
        if isinstance(a, P.Lit):
            return E.Literal(a.value)
        if isinstance(a, P.Ident):
            return self.scope.resolve(a.parts)
        if isinstance(a, P.UnOp):
            if a.op == "-":
                from spark_rapids_tpu.expr.arithmetic import UnaryMinus
                inner = c(a.operand)
                if isinstance(inner, E.Literal) and isinstance(
                        inner.value, (int, float)) and not isinstance(
                        inner.value, bool):
                    return E.Literal(-inner.value, inner.dtype)
                return UnaryMinus(inner)
            from spark_rapids_tpu.expr.predicates import Not
            return Not(c(a.operand))
        if isinstance(a, P.BinOp):
            from spark_rapids_tpu.expr import arithmetic as AR
            from spark_rapids_tpu.expr import predicates as PR
            from spark_rapids_tpu.expr.strings import Concat
            if isinstance(a.right, P.IntervalAst) and a.op in ("+", "-"):
                return _date_interval(c(a.left), a.right, a.op)
            l, r = c(a.left), c(a.right)
            table = {
                "+": AR.Add, "-": AR.Subtract, "*": AR.Multiply,
                "/": AR.Divide, "%": AR.Remainder,
                "=": PR.EqualTo, "<": PR.LessThan, "<=": PR.LessThanOrEqual,
                ">": PR.GreaterThan, ">=": PR.GreaterThanOrEqual,
                "<>": PR.NotEqual, "!=": PR.NotEqual,
                "and": PR.And, "or": PR.Or,
            }
            if a.op == "||":
                return Concat(l, r)
            return table[a.op](l, r)
        if isinstance(a, P.CaseAst):
            from spark_rapids_tpu.expr.conditional import CaseWhen
            from spark_rapids_tpu.expr.predicates import EqualTo
            if a.operand is not None:
                op = c(a.operand)
                branches = [(EqualTo(op, c(w)), c(v)) for w, v in a.branches]
            else:
                branches = [(c(w), c(v)) for w, v in a.branches]
            # typed NULL literals: give else/then NULLs the branch type
            else_e = c(a.else_) if a.else_ is not None else None
            branches, else_e = self._retype_nulls(branches, else_e)
            return CaseWhen(branches, else_e)
        if isinstance(a, P.CastAst):
            from spark_rapids_tpu.expr.cast import Cast
            to = _sql_type(a.type_name, a.type_args)
            # typed literals (DATE '...', TIMESTAMP '...') fold to constants
            # at plan time — Spark's Literal parsing. Explicit cast() keeps
            # its runtime Spark cast semantics (lenient parse, NULL on bad
            # input) — the two share an AST node but not behavior.
            if isinstance(a.expr, P.Lit) and isinstance(a.expr.value, str) \
                    and isinstance(to, (T.DateType, T.TimestampType)):
                # special datetime strings (epoch/now/today/...): typed
                # literals keep them on EVERY generation; plain casts only
                # on 3.0/3.1 shims (SPARK-35581 removed them in 3.2)
                sp = _special_datetime(a.expr.value, to)
                if sp is not None:
                    from spark_rapids_tpu.shims import shim_for
                    if a.typed_literal or shim_for(
                            self.lowerer.session.conf
                            ).special_datetime_strings:
                        return sp
            if a.typed_literal and isinstance(a.expr, P.Lit) \
                    and isinstance(a.expr.value, str):
                import datetime as _dt
                s = a.expr.value.strip()
                try:
                    if isinstance(to, T.DateType):
                        d = _dt.date.fromisoformat(s)
                        return E.Literal((d - _dt.date(1970, 1, 1)).days,
                                         T.DATE)
                    if isinstance(to, T.TimestampType):
                        # Engine is UTC-only: Spark resolves TIMESTAMP
                        # literals in spark.sql.session.timeZone; this build
                        # fixes the session zone to UTC (docs/compatibility.md:
                        # "session-timezone-dependent expressions assume
                        # UTC"), so the fold pins UTC explicitly.
                        ts = _dt.datetime.fromisoformat(s).replace(
                            tzinfo=_dt.timezone.utc)
                        epoch = _dt.datetime(1970, 1, 1,
                                             tzinfo=_dt.timezone.utc)
                        micros = (ts - epoch) // _dt.timedelta(microseconds=1)
                        return E.Literal(micros, T.TIMESTAMP)
                except ValueError as e:
                    raise P.SqlParseError(
                        f"invalid {a.type_name} literal {s!r}: {e}") from e
            return Cast(c(a.expr), to)
        if isinstance(a, P.BetweenAst):
            from spark_rapids_tpu.expr.predicates import (
                And, GreaterThanOrEqual, LessThanOrEqual, Not)
            e = c(a.expr)
            cond = And(GreaterThanOrEqual(e, c(a.lo)),
                       LessThanOrEqual(e, c(a.hi)))
            return Not(cond) if a.negated else cond
        if isinstance(a, P.InAst):
            from spark_rapids_tpu.expr.predicates import InSet, Not
            if isinstance(a.values, (P.Select, P.SetOp)):
                # uncorrelated IN (subquery) reaching the expression layer
                # (NOT IN, or a position the conjunct planner didn't push to
                # a semi-join): evaluate eagerly like ScalarSubquery (Spark
                # runs subquery stages first) and fold into a literal-set
                # membership, widening both sides like Spark does
                from spark_rapids_tpu.expr.arithmetic import promote
                from spark_rapids_tpu.expr.cast import Cast
                key = ("in", repr(a.values))
                hit = self.lowerer._subq_cache.get(key)
                if hit is None:
                    df = self.lowerer.dataframe(a.values)
                    tbl = df.collect()
                    if tbl.num_columns != 1:
                        raise SqlAnalysisError(
                            "IN (subquery) must return exactly one column")
                    hit = (list(dict.fromkeys(tbl.column(0).to_pylist())),
                           df.schema.fields[0].data_type)
                    self.lowerer._subq_cache[key] = hit
                vals, sub_dt = hit
                lhs = c(a.expr)
                if lhs.dtype != sub_dt:
                    target = promote(lhs.dtype, sub_dt)
                    if target != lhs.dtype:
                        lhs = Cast(lhs, target)
                    if isinstance(target, (T.DoubleType, T.FloatType)):
                        vals = [None if v is None else float(v)
                                for v in vals]
                ins = InSet(lhs, vals)
                return Not(ins) if a.negated else ins
            vals = []
            for v in a.values:
                ve = c(v)
                if not isinstance(ve, E.Literal):
                    from spark_rapids_tpu.expr.predicates import In
                    ins = In(c(a.expr), [c(x) for x in a.values])
                    return Not(ins) if a.negated else ins
                vals.append(ve.value)
            ins = InSet(c(a.expr), vals)
            return Not(ins) if a.negated else ins
        if isinstance(a, P.LikeAst):
            from spark_rapids_tpu.expr.strings import Like
            from spark_rapids_tpu.expr.predicates import Not
            lk = Like(c(a.expr), E.Literal(a.pattern))
            return Not(lk) if a.negated else lk
        if isinstance(a, P.IsNullAst):
            from spark_rapids_tpu.expr.nullexprs import IsNotNull, IsNull
            return (IsNotNull if a.negated else IsNull)(c(a.expr))
        if isinstance(a, P.SubqueryExpr):
            from spark_rapids_tpu.expr.misc import ScalarSubquery
            key = ("scalar", repr(a.query))
            sub = self.lowerer._subq_cache.get(key)
            if sub is None:
                sub = ScalarSubquery.from_dataframe(
                    self.lowerer.dataframe(a.query))
                self.lowerer._subq_cache[key] = sub
            return sub
        if isinstance(a, P.FuncCall):
            return self.func(a)
        if isinstance(a, P.ExistsAst):
            raise SqlAnalysisError(
                "EXISTS is supported only as a top-level WHERE conjunct "
                "(where it lowers to a semi/anti join); rewrite this "
                "occurrence as a join")
        if isinstance(a, P.Star):
            raise SqlAnalysisError("* only allowed at select-list top level "
                                   "or in count(*)")
        raise SqlAnalysisError(f"unsupported SQL construct: {a!r}")

    @staticmethod
    def _retype_nulls(branches, else_e):
        ts = [v.dtype for _, v in branches
              if not (isinstance(v, E.Literal) and v.value is None)]
        if else_e is not None and not (
                isinstance(else_e, E.Literal) and else_e.value is None):
            ts.append(else_e.dtype)
        if not ts:
            return branches, else_e
        t0 = ts[0]
        fixed = [(p, E.Literal(None, t0)
                  if isinstance(v, E.Literal) and v.value is None else v)
                 for p, v in branches]
        if else_e is not None and isinstance(else_e, E.Literal) \
                and else_e.value is None:
            else_e = E.Literal(None, t0)
        return fixed, else_e

    def func(self, a: P.FuncCall) -> E.Expression:
        c = self.convert
        name = a.name
        if a.over is not None:
            return self._window(a)
        if name in _AGG_FUNCS:
            if len(a.args) != 1:
                raise SqlAnalysisError(f"{name} takes one argument")
            if a.distinct:
                if name in ("min", "max"):   # distinct-insensitive
                    return _AGG_FUNCS[name](c(a.args[0]))
                if name not in ("sum", "avg"):
                    raise SqlAnalysisError(
                        f"DISTINCT aggregate {name} not supported")
                return _DistinctAgg(_AGG_FUNCS[name], c(a.args[0]))
            return _AGG_FUNCS[name](c(a.args[0]))
        if name == "count":
            if not a.args or isinstance(a.args[0], P.Star):
                if a.distinct:
                    raise SqlAnalysisError("count(DISTINCT *) not supported")
                return Count(None)
            if a.distinct:
                if len(a.args) != 1:
                    raise SqlAnalysisError(
                        "count(DISTINCT a, b, ...) not supported")
                return _DistinctAgg(Count, c(a.args[0]))
            return Count(c(a.args[0]))
        if name in ("substr", "substring"):
            from spark_rapids_tpu.expr.strings import Substring
            args = [c(x) for x in a.args]
            return Substring(*args)
        if name == "coalesce":
            from spark_rapids_tpu.expr.nullexprs import Coalesce
            return Coalesce(*[c(x) for x in a.args])
        if name == "nullif":
            from spark_rapids_tpu.expr.conditional import If
            from spark_rapids_tpu.expr.predicates import EqualTo
            x, y = c(a.args[0]), c(a.args[1])
            return If(EqualTo(x, y), E.Literal(None, x.dtype), x)
        if name == "abs":
            from spark_rapids_tpu.expr.arithmetic import Abs
            return Abs(c(a.args[0]))
        if name == "grouping":
            return _Grouping(c(a.args[0]))
        if name in ("least", "greatest"):
            from spark_rapids_tpu.expr.conditional import Greatest, Least
            cls = Least if name == "least" else Greatest
            return cls(*[c(x) for x in a.args])
        if name in ("upper", "ucase"):
            from spark_rapids_tpu.expr.strings import Upper
            return Upper(c(a.args[0]))
        if name in ("lower", "lcase"):
            from spark_rapids_tpu.expr.strings import Lower
            return Lower(c(a.args[0]))
        if name == "length":
            from spark_rapids_tpu.expr.strings import Length
            return Length(c(a.args[0]))
        if name == "trim":
            from spark_rapids_tpu.expr.strings import Trim
            return Trim(c(a.args[0]))
        if name == "concat":
            from spark_rapids_tpu.expr.strings import Concat
            return Concat(*[c(x) for x in a.args])
        if name == "round":
            from spark_rapids_tpu.expr.mathexprs import Round
            args = [c(x) for x in a.args]
            scale = 0
            if len(args) > 1:
                assert isinstance(args[1], E.Literal)
                scale = int(args[1].value)
            return Round(args[0], scale)
        if name == "sqrt":
            from spark_rapids_tpu.expr.mathexprs import Sqrt
            return Sqrt(c(a.args[0]))
        if name in ("floor", "ceil", "ceiling"):
            from spark_rapids_tpu.expr import mathexprs as MM
            cls = MM.Floor if name == "floor" else MM.Ceil
            return cls(c(a.args[0]))
        if name in ("row_number", "rank", "dense_rank"):
            raise SqlAnalysisError(f"{name}() requires an OVER clause")
        # registered UDFs (session.udf.register — RapidsUDF analog): the
        # registry picks the device impl or the compile/worker fallback
        reg = getattr(self.lowerer.session, "udf", None)
        if reg is not None and name in reg:
            return reg.build(name, [c(x) for x in a.args])
        raise SqlAnalysisError(f"unknown function {name}")

    def _window(self, a: P.FuncCall) -> E.Expression:
        from spark_rapids_tpu.expr import windows as WX
        spec_ast = a.over
        if a.distinct:
            # the two-level distinct rewrite has no window form
            raise SqlAnalysisError(
                f"DISTINCT aggregate {a.name} in a window not supported")
        inner = P.FuncCall(a.name, a.args, a.distinct, None)
        name = a.name
        if name == "row_number":
            func = WX.RowNumber()
        elif name == "rank":
            func = WX.Rank()
        elif name == "dense_rank":
            func = WX.DenseRank()
        elif name in ("lead", "lag"):
            args = [self.convert(x) for x in a.args]
            off = int(args[1].value) if len(args) > 1 else 1
            default = args[2] if len(args) > 2 else None
            cls = WX.Lead if name == "lead" else WX.Lag
            func = cls(args[0], off, default)
        else:
            func = self.func(inner)
            if not isinstance(func, AggregateFunction):
                raise SqlAnalysisError(f"{name} is not a window function")
        parts = tuple(self.convert(p) for p in spec_ast.partition_by)
        orders = tuple((self.convert(e), asc,
                        asc if nf is None else nf)
                       for (e, asc, nf) in spec_ast.order_by)
        if spec_ast.frame is not None:
            ftype, lo, hi = spec_ast.frame
            frame = WX.WindowFrame(
                ftype,
                None if lo is None else -lo if lo < 0 else lo,
                None if hi is None else hi)
        elif orders:
            frame = WX.DEFAULT_FRAME
        else:
            frame = WX.FULL_FRAME     # no ORDER BY → whole partition
        return WX.WindowExpression(func, WX.WindowSpec(parts, orders, frame))


# -- lowering -----------------------------------------------------------------

def _flatten_and(a) -> list:
    if isinstance(a, P.BinOp) and a.op == "and":
        return _flatten_and(a.left) + _flatten_and(a.right)
    return [a]


def _flatten_or(a) -> list:
    if isinstance(a, P.BinOp) and a.op == "or":
        return _flatten_or(a.left) + _flatten_or(a.right)
    return [a]


def _and_of(conjs):
    out = conjs[0]
    for c in conjs[1:]:
        out = P.BinOp("and", out, c)
    return out


def _hoist_common_or_conjuncts(conj) -> list:
    """(a AND x) OR (a AND y) → [a, (x OR y)] — Catalyst's common-predicate
    extraction from disjunctions. Without it, queries like TPC-DS q48 whose
    equi-join conditions live inside every OR branch plan as cross joins
    (billions of rows) instead of hash joins."""
    if not (isinstance(conj, P.BinOp) and conj.op == "or"):
        return [conj]
    branch_conjs = [_flatten_and(b) for b in _flatten_or(conj)]
    common = [c for c in branch_conjs[0]
              if all(any(c == d for d in bc) for bc in branch_conjs[1:])]
    if not common:
        return [conj]
    residuals = []
    for bc in branch_conjs:
        rem = list(bc)
        for c in common:
            rem.remove(next(d for d in rem if d == c))
        residuals.append(rem)
    if any(not rem for rem in residuals):
        return common    # one branch became TRUE → the OR is implied
    ors = [_and_of(rem) for rem in residuals]
    out = ors[0]
    for o in ors[1:]:
        out = P.BinOp("or", out, o)
    return common + [out]


def _ast_idents(a) -> list:
    """All column identifiers in an AST expression (not descending into
    subqueries — those resolve in their own scope)."""
    out = []

    def walk(x):
        if isinstance(x, P.Ident):
            out.append(x)
        elif isinstance(x, (P.SubqueryExpr, P.ExistsAst)):
            return
        elif isinstance(x, P.FuncCall):
            for ar in x.args:
                walk(ar)
            if x.over:
                for p_ in x.over.partition_by:
                    walk(p_)
                for (e_, _, _) in x.over.order_by:
                    walk(e_)
        elif isinstance(x, P.BinOp):
            walk(x.left)
            walk(x.right)
        elif isinstance(x, P.UnOp):
            walk(x.operand)
        elif isinstance(x, P.CaseAst):
            if x.operand is not None:
                walk(x.operand)
            for w, v in x.branches:
                walk(w)
                walk(v)
            if x.else_ is not None:
                walk(x.else_)
        elif isinstance(x, P.CastAst):
            walk(x.expr)
        elif isinstance(x, P.BetweenAst):
            walk(x.expr)
            walk(x.lo)
            walk(x.hi)
        elif isinstance(x, P.InAst):
            walk(x.expr)
            if isinstance(x.values, list):
                for v in x.values:
                    walk(v)
        elif isinstance(x, (P.LikeAst, P.IsNullAst)):
            walk(x.expr)
    walk(a)
    return out


def _date_interval(date_expr, iv, op: str):
    """date ± INTERVAL literal → DateAddInterval / AddMonths (Spark lowers
    calendar intervals the same way; day/week are fixed-length, month/year
    are calendar adds)."""
    from spark_rapids_tpu.expr import core as E
    from spark_rapids_tpu.expr.datetime import AddMonths, DateAddInterval
    try:
        n = int(iv.value)
    except ValueError as e:
        raise P.SqlParseError(f"invalid interval value {iv.value!r}") from e
    if op == "-":
        n = -n
    unit = iv.unit
    if unit in ("day", "week"):
        days = n * (7 if unit == "week" else 1)
        return DateAddInterval(date_expr, E.Literal(days, T.INT))
    if unit in ("month", "year"):
        months = n * (12 if unit == "year" else 1)
        return AddMonths(date_expr, E.Literal(months, T.INT))
    raise P.SqlParseError(f"unsupported interval unit {iv.unit!r}")


class _Relation:
    """One FROM item during join planning."""

    def __init__(self, plan, scope: Scope):
        self.plan = plan
        self.scope = scope


class _Lowerer:
    def __init__(self, session, views: dict):
        self.session = session
        self.views = dict(views)
        # eager-subquery memo: q14 references the same CTE-backed IN
        # (subquery) / scalar subquery from several UNION ALL arms; one
        # execution serves them all (keyed structurally — uncorrelated
        # subqueries resolve only against this lowerer's views)
        self._subq_cache: dict = {}

    # public: full query → plan
    def lower(self, q):
        for name, cte in q.ctes:
            self.views = dict(self.views)
            self.views[name] = self.dataframe(cte)
        return self._query(q)

    def _query(self, q):
        return self._setop(q) if isinstance(q, P.SetOp) else self._select(q)

    def dataframe(self, q):
        from spark_rapids_tpu.session import DataFrame
        sub = _Lowerer(self.session, self.views)
        return DataFrame(sub.lower(q), self.session)

    # -- set operations -------------------------------------------------------
    def _setop(self, s: P.SetOp):
        """UNION [ALL] / INTERSECT [ALL] / EXCEPT [ALL] (Spark lowers these
        in ResolveSetOperations; the reference executes the resulting
        union/join/aggregate plans on device — GpuUnionExec, GpuHashJoin).

        - UNION: UnionNode (+ group-by-all dedup for the distinct form)
        - INTERSECT: dedup(left) LEFT-SEMI join right on all columns,
          null-safely (set-op NULLs compare equal, unlike join keys)
        - EXCEPT: dedup(left) LEFT-ANTI join right, null-safe
        - INTERSECT/EXCEPT ALL: each side numbers its duplicates with
          row_number() over (partition by all columns); inner/anti join on
          (columns, n) then yields exactly min(cl,cr) / (cl-cr) copies —
          existing window + join machinery, no bespoke replicate exec."""
        def arm(q):
            # a parenthesized arm may carry its own WITH clause — lower it
            # through a sub-lowerer so its CTEs register (review catch)
            if getattr(q, "ctes", None):
                return self.dataframe(q)._plan
            return self._query(q)
        left, right = self._align_setop(arm(s.left), arm(s.right), s.op)
        if s.op == "union":
            plan = NN.UnionNode(left, right)
            if not s.all:
                plan = self._dedup(plan)
        elif not s.all:
            jt = "leftsemi" if s.op == "intersect" else "leftanti"
            dl = self._dedup(left)
            lkeys, rkeys = self._nullsafe_keys(dl, right)
            plan = NN.JoinNode(dl, right, lkeys, rkeys, jt, None)
        else:
            plan = self._setop_all(left, right, s.op)
        if s.order_by:
            plan = self._order_union(plan, s.order_by)
        if s.limit is not None:
            plan = NN.LimitNode(s.limit, plan, global_limit=True)
        return plan

    def _align_setop(self, left, right, op):
        """Spark WidenSetOperationTypes: equal arity, per-column least
        common type (cast arms that differ)."""
        from spark_rapids_tpu.expr.arithmetic import promote
        from spark_rapids_tpu.expr.cast import Cast
        lo, ro = left.output, right.output
        if len(lo) != len(ro):
            raise SqlAnalysisError(
                f"{op.upper()} arms have {len(lo)} vs {len(ro)} columns")
        targets = []
        for lf, rf in zip(lo.fields, ro.fields):
            if lf.data_type == rf.data_type:
                targets.append(lf.data_type)
            else:
                try:
                    targets.append(promote(lf.data_type, rf.data_type))
                except Exception as e:
                    raise SqlAnalysisError(
                        f"{op.upper()} column {lf.name}: incompatible types "
                        f"{lf.data_type} vs {rf.data_type}") from e

        def cast_arm(plan, out):
            if all(f.data_type == t for f, t in zip(out.fields, targets)):
                return plan
            proj = []
            for i, (f, t) in enumerate(zip(out.fields, targets)):
                r = E.BoundReference(i, f.data_type, f.nullable, f.name)
                proj.append(E.Alias(r if f.data_type == t else Cast(r, t),
                                    f.name))
            return NN.ProjectNode(proj, plan)
        return cast_arm(left, lo), cast_arm(right, ro)

    def _dedup(self, plan):
        """DISTINCT via group-by-all (Spark ReplaceDistinctWithAggregate)."""
        keys = [E.BoundReference(i, f.data_type, f.nullable, f.name)
                for i, f in enumerate(plan.output)]
        return NN.AggregateNode(keys, [], plan)

    @staticmethod
    def _nullsafe_zero(dt):
        if isinstance(dt, T.StringType):
            return ""
        if isinstance(dt, T.BooleanType):
            return False
        if isinstance(dt, (T.DoubleType, T.FloatType)):
            return 0.0
        return 0

    def _nullsafe_keys(self, left, right, extra=0):
        """Per-column join keys with set-op NULL semantics (NULL == NULL):
        a nullable column contributes (IS NULL, coalesce(col, zero)) — both
        keys non-null, so the engine's null-keys-never-match equi-join
        machinery compares null-safely (GpuEqualNullSafe's <=> role).
        `extra` trailing columns (e.g. a row_number) join as plain keys."""
        from spark_rapids_tpu.expr.nullexprs import Coalesce, IsNull
        lkeys, rkeys = [], []
        n = len(left.output) - extra
        # key lists must stay ALIGNED: expand a column on both sides when
        # EITHER arm is nullable (arms may disagree on nullability)
        nullable = [lf.nullable or rf.nullable
                    for lf, rf in zip(left.output.fields,
                                      right.output.fields)]
        for keys, out in ((lkeys, left.output), (rkeys, right.output)):
            for i, f in enumerate(out.fields):
                r = E.BoundReference(i, f.data_type, f.nullable, f.name)
                if i >= n or not nullable[i]:
                    keys.append(r)
                    continue
                keys.append(IsNull(r))
                keys.append(Coalesce(r, E.Literal(
                    self._nullsafe_zero(f.data_type), f.data_type)))
        return lkeys, rkeys

    def _number_duplicates(self, plan):
        """Append n = row_number() over (partition by all columns): the
        k-th copy of each distinct row gets k. Equal rows are interchangeable
        so any intra-partition order is correct."""
        from spark_rapids_tpu.expr.windows import (RowNumber, WindowExpression,
                                                   WindowSpec)
        refs = [E.BoundReference(i, f.data_type, f.nullable, f.name)
                for i, f in enumerate(plan.output)]
        spec = WindowSpec(tuple(refs), ((refs[0], True, True),))
        return NN.WindowNode(
            [E.Alias(WindowExpression(RowNumber(), spec), "_n")], plan)

    def _setop_all(self, left, right, op):
        ln = self._number_duplicates(left)
        rn = self._number_duplicates(right)
        lkeys, rkeys = self._nullsafe_keys(ln, rn, extra=1)
        jt = "leftsemi" if op == "intersect" else "leftanti"
        joined = NN.JoinNode(ln, rn, lkeys, rkeys, jt, None)
        # drop the helper row number
        proj = [E.Alias(E.BoundReference(i, f.data_type, f.nullable, f.name),
                        f.name)
                for i, f in enumerate(joined.output.fields[:-1])]
        return NN.ProjectNode(proj, joined)

    # -- FROM/join planning ---------------------------------------------------
    def _base_relation(self, item) -> _Relation:
        if isinstance(item, P.TableRef):
            if item.name not in self.views:
                raise SqlAnalysisError(f"table not found: {item.name}")
            df = self.views[item.name]
            qual = item.alias or item.name
            return _Relation(df._plan, Scope.for_relation(df._plan, qual))
        if isinstance(item, P.SubqueryRef):
            df = self.dataframe(item.query)
            return _Relation(df._plan,
                             Scope.for_relation(df._plan, item.alias))
        if isinstance(item, P.JoinRef):
            return self._explicit_join(item)
        raise SqlAnalysisError(f"unsupported FROM item {item!r}")

    def _explicit_join(self, j: P.JoinRef) -> _Relation:
        left = self._base_relation(j.left)
        right = self._base_relation(j.right)
        combined = left.scope.concat(right.scope)
        how = {"semi": "leftsemi", "anti": "leftanti"}.get(j.how, j.how)
        lkeys, rkeys, residual = [], [], []
        if j.using:
            for nm in j.using:
                lkeys.append(left.scope.resolve((nm,)))
                rkeys.append(right.scope.resolve((nm,)))
        elif j.on is not None:
            nl = len(left.scope.cols)
            for conj in _flatten_and(j.on):
                eq = self._as_equi(conj, left.scope, right.scope)
                if eq is not None:
                    lkeys.append(eq[0])
                    rkeys.append(eq[1])
                else:
                    residual.append(
                        _ExprConverter(combined, self).convert(conj))
        cond = None
        if residual:
            cond = residual[0]
            from spark_rapids_tpu.expr.predicates import And
            for r in residual[1:]:
                cond = And(cond, r)
        if how != "inner" or not lkeys:
            plan = NN.JoinNode(left.plan, right.plan, lkeys, rkeys,
                               "cross" if (how == "cross" or not lkeys)
                               else how, cond)
        else:
            plan = NN.JoinNode(left.plan, right.plan, lkeys, rkeys, "inner")
            if cond is not None:
                plan = NN.FilterNode(cond, plan)
        scope = (left.scope if how in ("leftsemi", "leftanti")
                 else combined)
        return _Relation(plan, scope)

    def _as_equi(self, conj, lscope: Scope, rscope: Scope):
        """conj as (left_key, right_key) bound to each side, or None."""
        if not (isinstance(conj, P.BinOp) and conj.op == "="):
            return None
        if not (isinstance(conj.left, P.Ident)
                and isinstance(conj.right, P.Ident)):
            return None
        a, b = conj.left.parts, conj.right.parts
        if len(lscope.find(a)) == 1 and len(rscope.find(b)) == 1:
            return lscope.resolve(a), rscope.resolve(b)
        if len(lscope.find(b)) == 1 and len(rscope.find(a)) == 1:
            return lscope.resolve(b), rscope.resolve(a)
        return None

    def _plan_from(self, q: P.Select):
        """Comma-list join graph → (plan, scope)."""
        rels = [self._base_relation(item) for item in q.from_]
        conjuncts = _flatten_and(q.where) if q.where is not None else []
        conjuncts = [h for c in conjuncts
                     for h in _hoist_common_or_conjuncts(c)]

        # [NOT] EXISTS conjuncts apply as semi/anti joins over the COMPLETE
        # join graph (the correlation may reference several outer relations)
        exists_list = []
        rest = []
        for c in conjuncts:
            if isinstance(c, P.ExistsAst):
                exists_list.append((c.query, c.negated))
            elif isinstance(c, P.UnOp) and c.op == "not" \
                    and isinstance(c.operand, P.ExistsAst):
                exists_list.append((c.operand.query,
                                    not c.operand.negated))
            else:
                rest.append(c)
        conjuncts = rest

        # which relations does each conjunct touch? (by unique column name
        # or qualifier match, at AST level — before any join order exists)
        def rel_ids_of(conj):
            ids = set()
            for ident in _ast_idents(conj):
                hit = None
                for ri, rel in enumerate(rels):
                    k = len(rel.scope.find(ident.parts))
                    if k:
                        if hit is not None and hit != ri:
                            return None   # ambiguous name across relations
                        hit = ri
                if hit is None:
                    return None           # e.g. select-alias reference
                ids.add(hit)
            return ids

        single = {}      # rel id -> [conjunct]
        edges = []       # (rid_a, rid_b, conj)
        leftover = []
        for conj in conjuncts:
            ids = rel_ids_of(conj)
            if ids is None:
                leftover.append(conj)
            elif len(ids) <= 1:
                single.setdefault(ids.pop() if ids else 0, []).append(conj)
            elif len(ids) == 2 and self._is_equi_ast(conj):
                a, b = sorted(ids)
                edges.append((a, b, conj))
            else:
                leftover.append(conj)

        # push single-relation filters down before joining; a non-negated
        # `expr IN (subquery)` conjunct becomes a LEFT-SEMI join against the
        # subquery plan (Spark RewritePredicateSubquery; the reference
        # executes it as a broadcast semi-join) instead of an eagerly
        # collected literal set that scales device comparisons with the
        # subquery's row count
        for ri, conjs in single.items():
            rel = rels[ri]
            conv = _ExprConverter(rel.scope, self)
            plain, semi = [], []
            for cj in conjs:
                if (isinstance(cj, P.InAst) and not cj.negated
                        and isinstance(cj.values, (P.Select, P.SetOp))):
                    semi.append(cj)
                else:
                    plain.append(cj)
            if plain:
                cond = conv.convert(plain[0])
                from spark_rapids_tpu.expr.predicates import And
                for cj in plain[1:]:
                    cond = And(cond, conv.convert(cj))
                rel.plan = NN.FilterNode(cond, rel.plan)
            for cj in semi:
                sub = self.dataframe(cj.values)._plan
                if len(sub.output) != 1:
                    raise SqlAnalysisError(
                        "IN (subquery) must return exactly one column")
                f0 = sub.output[0]
                rel.plan = NN.JoinNode(
                    rel.plan, sub, [conv.convert(cj.expr)],
                    [E.BoundReference(0, f0.data_type, f0.nullable,
                                      f0.name)], "leftsemi", None)

        # greedy join: start from the relation with the most edges (the fact
        # table in a star query), attach connected relations first
        n = len(rels)
        if n == 1:
            plan, scope = rels[0].plan, rels[0].scope
            if leftover:
                # unresolvable conjuncts must raise (typo'd column), never
                # silently drop the filter (review catch — the n>1 path
                # already routed these through the converter)
                conv = _ExprConverter(scope, self)
                cond = conv.convert(leftover[0])
                from spark_rapids_tpu.expr.predicates import And
                for cj in leftover[1:]:
                    cond = And(cond, conv.convert(cj))
                plan = NN.FilterNode(cond, plan)
            for sub_q, negated in exists_list:
                plan = self._apply_exists(plan, scope, sub_q, negated)
            return plan, scope
        degree = [0] * n
        for a, b, _ in edges:
            degree[a] += 1
            degree[b] += 1
        start = max(range(n), key=lambda i: degree[i])
        joined = {start}
        plan, scope = rels[start].plan, rels[start].scope
        remaining_edges = list(edges)
        while len(joined) < n:
            # pick the next relation connected to the joined set
            pick = None
            for a, b, _ in remaining_edges:
                if (a in joined) != (b in joined):
                    pick = b if a in joined else a
                    break
            if pick is None:    # disconnected → cross join the next one
                pick = next(i for i in range(n) if i not in joined)
            rel = rels[pick]
            lkeys, rkeys, rest = [], [], []
            for (a, b, conj) in remaining_edges:
                other = b if a in joined else a if b in joined else None
                if other != pick or (a in joined and b in joined):
                    rest.append((a, b, conj))
                    continue
                eq = self._as_equi_bound(conj, scope, rel.scope)
                if eq is None:
                    leftover.append(conj)
                else:
                    lkeys.append(eq[0])
                    rkeys.append(eq[1])
            remaining_edges = rest
            plan = NN.JoinNode(plan, rel.plan, lkeys, rkeys,
                               "inner" if lkeys else "cross")
            scope = scope.concat(rel.scope)
            joined.add(pick)
        # edges whose both endpoints joined via another path + leftovers
        for (a, b, conj) in remaining_edges:
            leftover.append(conj)
        if leftover:
            conv = _ExprConverter(scope, self)
            cond = conv.convert(leftover[0])
            from spark_rapids_tpu.expr.predicates import And
            for cj in leftover[1:]:
                cond = And(cond, conv.convert(cj))
            plan = NN.FilterNode(cond, plan)
        for sub_q, negated in exists_list:
            plan = self._apply_exists(plan, scope, sub_q, negated)
        return plan, scope

    def _apply_exists(self, plan, scope, q2, negated: bool):
        """[NOT] EXISTS (subquery) over the planned outer relation (Spark
        RewritePredicateSubquery; the reference executes the result as a
        broadcast semi/anti join). Correlation must be equality conjuncts
        in the subquery's WHERE referencing outer columns — those become
        the join keys; everything else must resolve inside the subquery.
        An uncorrelated EXISTS folds at plan time (non-empty check)."""
        if not isinstance(q2, P.Select) or q2.group_by or q2.having \
                or getattr(q2, "grouping_sets", None) or q2.ctes \
                or q2.limit == 0 \
                or any(self._ast_has_agg(it.expr) for it in q2.items
                       if not isinstance(it.expr, P.Star)):
            # an ungrouped aggregate select always yields one row — row
            # existence of its INPUT is the wrong question, so reject
            # rather than silently answer it
            raise SqlAnalysisError(
                "EXISTS subqueries support plain SELECT ... FROM ... WHERE "
                "shapes (no GROUP BY/HAVING/CTE/aggregates/LIMIT 0)")
        sub = _Lowerer(self.session, self.views)
        # scope-only pass: concat the base-relation scopes (no join tree —
        # the real plan is built once below, with the inner WHERE)
        iscope = None
        for item in q2.from_:
            s2 = sub._base_relation(item).scope
            iscope = s2 if iscope is None else iscope.concat(s2)
        pairs, inner_only = [], []      # [(outer parts, inner parts)]
        for cj in (_flatten_and(q2.where) if q2.where is not None else []):
            if isinstance(cj, P.BinOp) and cj.op == "=" \
                    and isinstance(cj.left, P.Ident) \
                    and isinstance(cj.right, P.Ident):
                li, ri = cj.left.parts, cj.right.parts
                l_in, r_in = len(iscope.find(li)), len(iscope.find(ri))
                # inner resolution wins when a name exists in both scopes
                # (Spark's inner-first rule)
                if l_in == 0 and r_in == 1 and len(scope.find(li)) == 1:
                    pairs.append((li, ri))
                    continue
                if r_in == 0 and l_in == 1 and len(scope.find(ri)) == 1:
                    pairs.append((ri, li))
                    continue
            if all(iscope.find(i.parts) for i in _ast_idents(cj)):
                inner_only.append(cj)
                continue
            raise SqlAnalysisError(
                "EXISTS: only equality correlation to the outer query "
                f"is supported (got {cj!r})")
        # REPLAN the subquery with its inner-only conjuncts as the WHERE so
        # _plan_from turns inner equi conjuncts into hash-join edges
        # (filtering a cross product after the fact would blow up on
        # multi-relation subqueries)
        iplan, iscope = _Lowerer(self.session, self.views)._plan_from(
            P.Select(q2.items, q2.from_,
                     _and_of(inner_only) if inner_only else None))
        lkeys = [scope.resolve(op) for op, _ in pairs]
        rkeys = [iscope.resolve(ip) for _, ip in pairs]
        if not lkeys:
            # uncorrelated: evaluate once at plan time, like scalar
            # subqueries (Spark's pre-executed subquery stages)
            from spark_rapids_tpu.session import DataFrame
            n = DataFrame(NN.LimitNode(1, iplan, global_limit=True),
                          self.session).collect().num_rows
            if (n > 0) != negated:
                return plan
            return NN.FilterNode(E.Literal(False, T.BOOLEAN), plan)
        return NN.JoinNode(plan, iplan, lkeys, rkeys,
                           "leftanti" if negated else "leftsemi", None)

    @staticmethod
    def _ast_has_agg(a) -> bool:
        """AST-level aggregate detection (pre-conversion): an ungrouped
        aggregate select yields one row regardless of input rows, which
        breaks EXISTS's row-existence reading of the subquery. Walks every
        AST shape _ast_idents walks (incl. CASE branches and IN lists)."""
        agg_names = set(_AGG_FUNCS) | {"count"}

        def walk(x):
            if isinstance(x, (P.SubqueryExpr, P.ExistsAst, P.Star)) \
                    or x is None:
                return False
            if isinstance(x, P.FuncCall):
                if x.over is None and x.name in agg_names:
                    return True
                return any(walk(ar) for ar in x.args)
            if isinstance(x, P.BinOp):
                return walk(x.left) or walk(x.right)
            if isinstance(x, P.UnOp):
                return walk(x.operand)
            if isinstance(x, P.CaseAst):
                return (walk(x.operand)
                        or any(walk(w) or walk(v) for w, v in x.branches)
                        or walk(x.else_))
            if isinstance(x, P.CastAst):
                return walk(x.expr)
            if isinstance(x, P.BetweenAst):
                return walk(x.expr) or walk(x.lo) or walk(x.hi)
            if isinstance(x, P.InAst):
                return walk(x.expr) or (isinstance(x.values, list)
                                        and any(walk(v) for v in x.values))
            if isinstance(x, (P.LikeAst, P.IsNullAst)):
                return walk(x.expr)
            return False
        return walk(a)

    @staticmethod
    def _is_equi_ast(conj):
        return (isinstance(conj, P.BinOp) and conj.op == "="
                and isinstance(conj.left, P.Ident)
                and isinstance(conj.right, P.Ident))

    def _as_equi_bound(self, conj, lscope, rscope):
        a, b = conj.left.parts, conj.right.parts
        if len(lscope.find(a)) == 1 and len(rscope.find(b)) == 1:
            return lscope.resolve(a), rscope.resolve(b)
        if len(lscope.find(b)) == 1 and len(rscope.find(a)) == 1:
            return lscope.resolve(b), rscope.resolve(a)
        return None

    # -- SELECT block ---------------------------------------------------------
    def _select(self, q: P.Select):
        if not q.from_:
            # SELECT <literals>: one-row relation
            import pyarrow as pa
            plan = NN.ScanNode([pa.table({"_one": pa.array([1])})])
            scope = Scope.for_relation(plan, None)
        else:
            plan, scope = self._plan_from(q)

        conv = _ExprConverter(scope, self)

        # expand stars, convert select items
        items = []       # (Expression, out_name)
        for i, it in enumerate(q.items):
            if isinstance(it.expr, P.Star):
                qual = it.expr.qualifier
                for ci, (cq, nm, dt, nb) in enumerate(scope.cols):
                    if qual is None or (cq or "").lower() == qual.lower():
                        items.append((E.BoundReference(ci, dt, nb, nm), nm))
                continue
            e = conv.convert(it.expr)
            nm = it.alias or self._auto_name(it.expr, len(items))
            items.append((e, nm))

        having_e = conv.convert(q.having) if q.having is not None else None
        group_es = [self._group_expr(g, conv, q, items) for g in q.group_by]

        # ORDER BY handled late (over output names); convert exprs lazily
        order_items = q.order_by

        has_agg = bool(group_es) or any(
            self._contains_agg(e) for e, _ in items) or (
            having_e is not None and self._contains_agg(having_e))

        windows = {}     # expr_key -> (WindowExpression, out_col_name)

        if has_agg:
            grouping = (q.grouping_sets if q.grouping_sets is not None
                        else q.rollup)
            plan, sub = self._aggregate(plan, scope, group_es, items,
                                        having_e, grouping, order_items, conv)
            items = [(sub(e), nm) for e, nm in items]
            having_e = sub(having_e) if having_e is not None else None
        else:
            def sub(e):
                return e

        # windows (post-agg): pull distinct window exprs into a WindowNode
        win_exprs = []
        for e, _ in items:
            self._collect_windows(e, win_exprs)
        if having_e is not None:
            self._collect_windows(having_e, win_exprs)
        if win_exprs:
            base_n = len(plan.output)
            named, keys = [], {}
            for w in win_exprs:
                k = fuse.expr_key(w)
                if k in keys:
                    continue
                nm = f"_w{len(named)}"
                keys[k] = (len(named) + base_n, nm, w.dtype)
                named.append(E.Alias(w, nm))
            plan = NN.WindowNode(named, plan)

            def wsub(e):
                if e is None:
                    return None
                k = fuse.expr_key(e)
                if k in keys:
                    idx, nm, dt = keys[k]
                    return E.BoundReference(idx, dt, True, nm)
                return e.with_children([wsub(c) for c in e.children]) \
                    if e.children else e
            items = [(wsub(e), nm) for e, nm in items]
            having_e = wsub(having_e)
            windows = keys
        else:
            def wsub(e):
                return e

        if having_e is not None:
            plan = NN.FilterNode(having_e, plan)

        proj = [E.Alias(e, nm) for e, nm in items]
        plan = NN.ProjectNode(proj, plan)

        if q.distinct:
            keys = [E.col(f.name) for f in plan.output]
            plan = NN.AggregateNode(keys, [], plan)

        if order_items:
            # output-position map: name AND substituted-expression structure
            out_names = [nm for _, nm in items]
            key_to_idx = {}
            for i, (e, _) in enumerate(items):
                key_to_idx.setdefault(fuse.expr_key(e), i)
            sort_exprs, hidden = [], []
            for (ast, asc, nf) in order_items:
                nulls_first = asc if nf is None else nf
                try:
                    e = self._resolve_order_item(ast, plan, out_names,
                                                 key_to_idx, conv, sub, wsub)
                except SqlAnalysisError:
                    # expression over the projected output (q89's
                    # `order by sum_sales - avg_monthly_sales`): carry it as
                    # a hidden column, sort, then drop it
                    out_conv = _ExprConverter(
                        Scope.for_relation(plan, None), self)
                    e = ("hidden", out_conv.convert(ast))
                    hidden.append(e[1])
                sort_exprs.append((e, asc, nulls_first))
            if hidden:
                n0 = len(plan.output)
                keep = [E.Alias(E.BoundReference(i, f.data_type, f.nullable,
                                                 f.name), f.name)
                        for i, f in enumerate(plan.output)]
                hcols = [E.Alias(h, f"_s{i}") for i, h in enumerate(hidden)]
                plan = NN.ProjectNode(keep + hcols, plan)
                hidx, fixed = n0, []
                for (e, asc, nf) in sort_exprs:
                    if isinstance(e, tuple):
                        f = plan.output[hidx]
                        e = E.BoundReference(hidx, f.data_type, f.nullable,
                                             f.name)
                        hidx += 1
                    fixed.append((e, asc, nf))
                plan = NN.SortNode(fixed, plan)
                plan = NN.ProjectNode(keep, plan)
            else:
                plan = NN.SortNode(sort_exprs, plan)
        if q.limit is not None:
            plan = NN.LimitNode(q.limit, plan, global_limit=True)
        return plan

    def _resolve_order_item(self, ast, plan, out_names, key_to_idx, conv,
                            sub, wsub):
        out = plan.output
        if isinstance(ast, P.Lit) and isinstance(ast.value, int):
            idx = ast.value - 1
            if not (0 <= idx < len(out)):
                raise SqlAnalysisError(
                    f"ORDER BY position {ast.value} out of range")
            f = out[idx]
            return E.BoundReference(idx, f.data_type, f.nullable, f.name)
        if isinstance(ast, P.Ident):
            nm = ast.parts[-1].lower()
            hits = [i for i, onm in enumerate(out_names)
                    if onm.lower() == nm]
            if len(hits) == 1:
                f = out[hits[0]]
                return E.BoundReference(hits[0], f.data_type, f.nullable,
                                        f.name)
        # expression: convert + substitute, then match a projected item
        raw = wsub(sub(conv.convert(ast)))
        k = fuse.expr_key(raw)
        if k in key_to_idx:
            i = key_to_idx[k]
            f = out[i]
            return E.BoundReference(i, f.data_type, f.nullable, f.name)
        raise SqlAnalysisError(
            f"ORDER BY item must reference an output column, alias, "
            f"ordinal, or a select-list expression (got {ast!r})")

    @staticmethod
    def _auto_name(ast, i):
        if isinstance(ast, P.Ident):
            return ast.parts[-1]
        if isinstance(ast, P.FuncCall):
            return f"{ast.name}"
        return f"col{i}"

    def _group_expr(self, g, conv, q, items):
        # GROUP BY <ordinal> / <select alias> / <expr>
        if isinstance(g, P.Lit) and isinstance(g.value, int):
            idx = g.value - 1
            if not (0 <= idx < len(items)):
                raise SqlAnalysisError(f"GROUP BY position {g.value} "
                                       "out of range")
            return items[idx][0]
        if isinstance(g, P.Ident) and len(g.parts) == 1:
            try:
                return conv.convert(g)
            except SqlAnalysisError:
                for e, nm in items:
                    if nm.lower() == g.parts[0].lower():
                        return e
                raise
        return conv.convert(g)

    @staticmethod
    def _contains_agg(e) -> bool:
        from spark_rapids_tpu.expr.windows import WindowExpression
        if isinstance(e, AggregateFunction):
            return True
        if isinstance(e, WindowExpression):
            # aggregate INPUTS to a window count (avg(sum(x)) over ...);
            # the window function itself does not
            return any(_Lowerer._contains_agg(c) for c in e.children)
        return any(_Lowerer._contains_agg(c) for c in e.children)

    @staticmethod
    def _collect_windows(e, out: list):
        from spark_rapids_tpu.expr.windows import WindowExpression
        if isinstance(e, WindowExpression):
            out.append(e)
            return
        for c in e.children:
            _Lowerer._collect_windows(c, out)

    @staticmethod
    def _fast_distinct_ok(aggs, rollup) -> bool:
        """True when the cheap no-Expand rewrite (_rewrite_distinct) applies:
        ONE distinct argument, and every non-distinct aggregate is either
        Min/Max or count/sum/avg over that same argument."""
        from spark_rapids_tpu.expr.aggregates import Average, Count, Max, Min
        if rollup:
            return False
        xkeys = {fuse.expr_key(a.child) for _, a in aggs
                 if isinstance(a, _DistinctAgg)}
        if len(xkeys) != 1:
            return False
        xkey = next(iter(xkeys))
        x = next(a.child for _, a in aggs if isinstance(a, _DistinctAgg))

        def same_col(a):
            return (isinstance(a, (Count, Sum, Average))
                    and a.child is not None
                    and fuse.expr_key(a.child) == xkey)
        others = [a for _, a in aggs if not isinstance(a, _DistinctAgg)
                  and not same_col(a)]
        if not all(isinstance(a, (Min, Max)) for a in others):
            return False
        need_cnt = any(same_col(a) for _, a in aggs
                       if not isinstance(a, _DistinctAgg))
        if need_cnt and isinstance(x.dtype, T.DecimalType):
            return False
        return True

    def _rewrite_distinct(self, plan, group_bound, aggs, rollup):
        """Spark RewriteDistinctAggregates (single distinct column form):
        inner GROUP BY (keys, x) dedupes x per group, the outer aggregate
        re-reduces. Mixes supported without Expand:

        - Min/Max over anything (distinct-insensitive; re-reduce partials);
        - count/sum/avg over the SAME column x (TPC-DS q28's shape): the
          inner also carries cnt = count(x) per (keys, x) group, and the
          outer re-derives count(x)=sum(cnt), sum(x)=sum(x*cnt),
          avg(x)=sum(x*cnt)/sum(cnt).

        Distinct aggregates over several different columns go through
        _rewrite_distinct_expand (Spark's general Expand form)."""
        from spark_rapids_tpu.expr.aggregates import Average, Count, Max, Min
        from spark_rapids_tpu.expr.arithmetic import Divide, Multiply
        from spark_rapids_tpu.expr.cast import Cast
        if rollup:
            raise SqlAnalysisError(
                "DISTINCT aggregates with ROLLUP not supported")
        xkeys = {fuse.expr_key(a.child) for _, a in aggs
                 if isinstance(a, _DistinctAgg)}
        if len(xkeys) != 1:
            raise SqlAnalysisError(
                "DISTINCT aggregates over several columns not supported")
        xkey = next(iter(xkeys))
        x = next(a.child for _, a in aggs if isinstance(a, _DistinctAgg))

        def same_col(a):
            return (isinstance(a, (Count, Sum, Average))
                    and a.child is not None
                    and fuse.expr_key(a.child) == xkey)

        others = [(k, a) for k, a in aggs if not isinstance(a, _DistinctAgg)
                  and not same_col(a)]
        if not all(isinstance(a, (Min, Max)) for _, a in others):
            raise SqlAnalysisError(
                "unsupported DISTINCT aggregate combination (one distinct "
                "column; mixes limited to min/max and count/sum/avg over "
                "that same column)")
        need_cnt = any(same_col(a) for _, a in aggs
                       if not isinstance(a, _DistinctAgg))
        inner_aggs = [E.Alias(a, f"_m{i}") for i, (_, a) in enumerate(others)]
        if need_cnt:
            inner_aggs.append(E.Alias(Count(x), "_cnt"))
        inner = NN.AggregateNode(list(group_bound) + [x], inner_aggs, plan)
        iout = inner.output
        ng = len(group_bound)

        def ref(j):
            return E.BoundReference(j, iout.fields[j].data_type, True,
                                    iout.fields[j].name)

        if need_cnt and isinstance(x.dtype, T.DecimalType):
            raise SqlAnalysisError(
                "mixed distinct/non-distinct over a DECIMAL column "
                "not supported")
        x_ref = ref(ng)
        other_pos = {k: ng + 1 + i for i, (k, _) in enumerate(others)}
        cnt_ref = ref(ng + 1 + len(others)) if need_cnt else None
        # outer aggregates are PRIMITIVE (AggregateNode's contract); an avg
        # re-derivation needs two of them + a division, so a final Project
        # maps each original aggregate to its value
        outer_aggs = []       # Alias(AggregateFunction)
        final = []            # per original agg: ordinal | ("div", i, j)
        memo = {}             # expr key -> ordinal (avg+count share Sum(cnt))

        def add(agg_fn):
            k = fuse.expr_key(agg_fn)
            if k in memo:
                return memo[k]
            outer_aggs.append(E.Alias(agg_fn, f"_o{len(outer_aggs)}"))
            memo[k] = len(outer_aggs) - 1
            return memo[k]

        for k, a in aggs:
            if isinstance(a, _DistinctAgg):
                final.append(add(a.make(x_ref)))
            elif isinstance(a, (Min, Max)):
                final.append(add(type(a)(ref(other_pos[k]))))
            elif isinstance(a, Count):       # count(x) = sum(cnt)
                final.append(add(Sum(cnt_ref)))
            elif isinstance(a, Average):     # avg(x) = sum(x*cnt)/sum(cnt)
                num = add(Sum(Multiply(Cast(x_ref, T.DOUBLE),
                                       Cast(cnt_ref, T.DOUBLE))))
                den = add(Sum(cnt_ref))
                final.append(("div", num, den))
            else:                            # sum(x) = sum(x*cnt)
                st = Sum(x_ref).dtype
                final.append(add(
                    Sum(Multiply(Cast(x_ref, st), Cast(cnt_ref, st)))))
        outer_groups = [E.BoundReference(i, f.data_type, f.nullable, f.name)
                        for i, f in enumerate(iout.fields[:ng])]
        agg_node = NN.AggregateNode(outer_groups, outer_aggs, inner)
        aout = agg_node.output
        proj = [E.BoundReference(i, f.data_type, f.nullable, f.name)
                for i, f in enumerate(aout.fields[:ng])]
        for i, spec in enumerate(final):
            if isinstance(spec, tuple):
                _, num, den = spec
                e = Divide(
                    E.BoundReference(ng + num, aout.fields[ng + num].data_type,
                                     True, "n"),
                    Cast(E.BoundReference(ng + den,
                                          aout.fields[ng + den].data_type,
                                          True, "d"), T.DOUBLE))
            else:
                j = ng + spec
                e = E.BoundReference(j, aout.fields[j].data_type, True,
                                     aout.fields[j].name)
                if isinstance(aggs[i][1], Count):
                    # count over an empty relation is 0, not the NULL an
                    # empty outer Sum(cnt) yields
                    from spark_rapids_tpu.expr.nullexprs import Coalesce
                    e = Coalesce(e, E.Literal(0, T.LONG))
            proj.append(E.Alias(e, f"_a{i}"))
        return NN.ProjectNode(proj, agg_node), ng

    def _rewrite_distinct_expand(self, plan, group_bound, aggs):
        """Spark RewriteDistinctAggregates, general (Expand) form — several
        DISTINCT arguments and/or arbitrary regular aggregates (reference
        inherits this whole plan shape from Catalyst and executes the Expand
        via GpuExpandExec; aggregate.scala:240 distinct modes).

        Expand emits one projection per distinct-argument group plus (when
        regular aggregates exist) one "regular" projection; a branch id
        disambiguates. Branch b for distinct argument x_i carries x_i and
        NULLs for every other distinct/regular input column; the regular
        branch carries the regular inputs and NULL x's. The inner aggregate
        GROUP BY (keys, bid, x_1..x_m) then dedupes each distinct argument
        per group while reducing regular partials (whose inputs are NULL on
        distinct branches, so they reduce neutrally), and the outer
        aggregate GROUP BY keys applies the original distinct functions to
        the deduped x columns and merges the regular partials. Composes
        with ROLLUP: `plan` may already be the rollup Expand, with its
        grouping id last in `group_bound`."""
        from spark_rapids_tpu.expr.aggregates import Average, Count, Max, Min
        from spark_rapids_tpu.expr.arithmetic import Divide
        from spark_rapids_tpu.expr.cast import Cast
        from spark_rapids_tpu.expr.nullexprs import Coalesce

        # distinct-argument groups, one per unique argument expression
        dkeys, dexpr = [], {}
        for _, a in aggs:
            if isinstance(a, _DistinctAgg):
                ck = fuse.expr_key(a.child)
                if ck not in dexpr:
                    dexpr[ck] = a.child
                    dkeys.append(ck)
        regulars = [(k, a) for k, a in aggs if not isinstance(a, _DistinctAgg)]
        for _, a in regulars:
            if not isinstance(a, (Min, Max, Count, Sum, Average)):
                raise SqlAnalysisError(
                    f"aggregate {a!r} cannot mix with DISTINCT aggregates")
            if isinstance(a, (Sum, Average)) and a.child is not None \
                    and isinstance(a.child.dtype, T.DecimalType):
                raise SqlAnalysisError(
                    "DECIMAL sum/avg mixed with DISTINCT aggregates "
                    "not supported")
        nk, m = len(group_bound), len(dkeys)
        # one input column per regular aggregate (count(*) counts a live 1)
        rcols = [E.Literal(1, T.INT) if a.child is None else a.child
                 for _, a in regulars]

        def null_of(e):
            return E.Literal(None, e.dtype)

        branches = ([("regular", None)] if regulars else []) \
            + [("distinct", i) for i in range(m)]
        projections = []
        for kind, di in branches:
            proj = list(group_bound)
            proj.append(E.Literal(len(projections), T.INT))
            for i, ck in enumerate(dkeys):
                e = dexpr[ck]
                proj.append(e if (kind == "distinct" and i == di)
                            else null_of(e))
            for rc in rcols:
                proj.append(rc if kind == "regular" else null_of(rc))
            projections.append(proj)
        out_fields = (
            [T.StructField(f"_k{i}", g.dtype, True)
             for i, g in enumerate(group_bound)]
            + [T.StructField("_bid", T.INT, False)]
            + [T.StructField(f"_x{i}", dexpr[ck].dtype, True)
               for i, ck in enumerate(dkeys)]
            + [T.StructField(f"_rc{j}", rc.dtype, True)
               for j, rc in enumerate(rcols)])
        expand = NN.ExpandNode(projections, out_fields, plan)
        eout = expand.output

        def eref(j):
            f = eout[j]
            return E.BoundReference(j, f.data_type, f.nullable, f.name)

        # inner: GROUP BY (keys, bid, x's); partial regular aggregates
        inner_groups = [eref(j) for j in range(nk + 1 + m)]
        inner_aggs = []
        partial = []     # per regular agg: [ordinal(s) into inner agg cols]

        def padd(fn):
            inner_aggs.append(E.Alias(fn, f"_p{len(inner_aggs)}"))
            return len(inner_aggs) - 1
        rbase = nk + 1 + m
        for j, (_, a) in enumerate(regulars):
            rc_ref = eref(rbase + j)
            if isinstance(a, (Min, Max)):
                partial.append([padd(type(a)(rc_ref))])
            elif isinstance(a, Count):
                partial.append([padd(Count(rc_ref))])
            elif isinstance(a, Sum):
                partial.append([padd(Sum(rc_ref))])
            else:                      # Average: sum+count partials
                partial.append([padd(Sum(Cast(rc_ref, T.DOUBLE))),
                                padd(Count(rc_ref))])
        inner = NN.AggregateNode(inner_groups, inner_aggs, expand)
        iout = inner.output

        def iref(j, nullable=True):
            f = iout[j]
            return E.BoundReference(j, f.data_type, nullable, f.name)

        outer_groups = [E.BoundReference(i, iout[i].data_type,
                                         iout[i].nullable, iout[i].name)
                        for i in range(nk)]
        x_pos = {ck: nk + 1 + i for i, ck in enumerate(dkeys)}
        pbase = nk + 1 + m
        outer_aggs, final, memo = [], [], {}

        def add(agg_fn):
            k = fuse.expr_key(agg_fn)
            if k not in memo:
                outer_aggs.append(E.Alias(agg_fn, f"_o{len(outer_aggs)}"))
                memo[k] = len(outer_aggs) - 1
            return memo[k]

        ri = iter(range(len(regulars)))
        for _, a in aggs:
            if isinstance(a, _DistinctAgg):
                final.append(add(a.make(iref(x_pos[fuse.expr_key(a.child)]))))
                continue
            j = next(ri)
            prefs = [iref(pbase + p) for p in partial[j]]
            if isinstance(a, (Min, Max)):
                final.append(add(type(a)(prefs[0])))
            elif isinstance(a, Count):       # count = sum of partial counts
                final.append(("cnt", add(Sum(prefs[0]))))
            elif isinstance(a, Sum):
                final.append(add(Sum(prefs[0])))
            else:                            # avg = sum(sums)/sum(counts)
                final.append(("div", add(Sum(prefs[0])),
                              add(Sum(prefs[1]))))
        agg_node = NN.AggregateNode(outer_groups, outer_aggs, inner)
        aout = agg_node.output

        def aref(j):
            f = aout[j]
            return E.BoundReference(j, f.data_type, True, f.name)

        proj = [E.BoundReference(i, f.data_type, f.nullable, f.name)
                for i, f in enumerate(aout.fields[:nk])]
        for i, spec in enumerate(final):
            a = aggs[i][1]
            if isinstance(spec, tuple) and spec[0] == "div":
                e = Divide(aref(nk + spec[1]),
                           Cast(aref(nk + spec[2]), T.DOUBLE))
            elif isinstance(spec, tuple):    # ("cnt", ord): empty → 0
                e = Coalesce(aref(nk + spec[1]), E.Literal(0, T.LONG))
            else:
                e = aref(nk + spec)
            if e.dtype != a.dtype:           # double-Sum widening (decimal-
                e = Cast(e, a.dtype)         # free here) back to Spark's type
            proj.append(E.Alias(e, f"_a{i}"))
        return NN.ProjectNode(proj, agg_node), nk

    def _aggregate(self, plan, scope, group_es, items, having_e, rollup,
                   order_items, conv):
        """Build (Expand→)Aggregate; return (plan, substitution fn)."""
        from spark_rapids_tpu.expr.windows import WindowExpression

        # collect distinct aggregates from every post-agg expression
        aggs = []        # [(key, AggregateFunction)]
        seen = {}

        def collect(e):
            if isinstance(e, AggregateFunction):
                k = fuse.expr_key(e)
                if k not in seen:
                    seen[k] = len(aggs)
                    aggs.append((k, e))
                return
            for c in e.children:
                collect(c)

        for e, _ in items:
            collect(e)
        if having_e is not None:
            collect(having_e)
        # ORDER BY expressions may reference aggregates textually
        order_bound = []
        for (ast, asc, nf) in (order_items or []):
            try:
                order_bound.append(conv.convert(ast))
            except SqlAnalysisError:
                order_bound.append(None)   # alias/ordinal — resolved later
        for ob in order_bound:
            if ob is not None:
                collect(ob)

        gid_ref = None
        if rollup:
            sets = rollup if isinstance(rollup, list) else None
            plan, group_refs, gid_ref = self._expand_rollup(plan, group_es,
                                                            sets)
            group_bound = group_refs + [gid_ref]
        else:
            group_bound = list(group_es)

        if any(isinstance(a, _DistinctAgg) for _, a in aggs):
            if self._fast_distinct_ok(aggs, rollup):
                agg_node, n_group = self._rewrite_distinct(plan, group_bound,
                                                           aggs, rollup)
            else:
                agg_node, n_group = self._rewrite_distinct_expand(
                    plan, group_bound, aggs)
        else:
            agg_named = [E.Alias(a, f"_a{i}")
                         for i, (_, a) in enumerate(aggs)]
            agg_node = NN.AggregateNode(group_bound, agg_named, plan)
            n_group = len(group_bound)
        out = agg_node.output

        group_keys = {fuse.expr_key(g): i for i, g in enumerate(group_es)}

        def sub(e):
            if e is None:
                return None
            if isinstance(e, _Grouping):
                if gid_ref is None:
                    raise SqlAnalysisError(
                        "grouping() outside GROUP BY ROLLUP")
                return self._grouping_bit(e, group_es, n_group, out)
            k = fuse.expr_key(e)
            if isinstance(e, AggregateFunction) and k in seen:
                i = seen[k]
                f = out[n_group + i]
                return E.BoundReference(n_group + i, f.data_type, True,
                                        f.name)
            if k in group_keys:
                i = group_keys[k]
                f = out[i]
                return E.BoundReference(i, f.data_type, f.nullable, f.name)
            if isinstance(e, WindowExpression):
                return e.with_children([sub(c) for c in e.children])
            if e.children:
                return e.with_children([sub(c) for c in e.children])
            if isinstance(e, (E.BoundReference, E.AttributeReference)):
                raise SqlAnalysisError(
                    f"column {e!r} is neither grouped nor aggregated")
            return e
        return agg_node, sub

    def _grouping_bit(self, g: _Grouping, group_es, n_group, out_schema):
        """grouping(col) → (gid >> bit) & 1 over the aggregate output's
        grouping-id column (Spark semantics: leftmost group col = MSB)."""
        from spark_rapids_tpu.expr.arithmetic import BitwiseAnd, ShiftRight
        from spark_rapids_tpu.expr.cast import Cast
        target = fuse.expr_key(g.children[0])
        pos = None
        for i, ge in enumerate(group_es):
            if fuse.expr_key(ge) == target:
                pos = i
                break
        if pos is None:
            raise SqlAnalysisError("grouping() argument must be a GROUP BY "
                                   "column")
        gid_idx = n_group - 1     # gid is the last group column
        f = out_schema[gid_idx]
        gid = E.BoundReference(gid_idx, f.data_type, False, f.name)
        bit = len(group_es) - 1 - pos
        shifted = ShiftRight(gid, E.Literal(bit)) if bit else gid
        return Cast(BitwiseAnd(shifted, E.Literal(1)), T.INT)

    def _expand_rollup(self, plan, group_es, sets=None):
        """Spark's Expand lowering of ROLLUP / CUBE / GROUPING SETS (shared
        with DataFrame.rollup: plan/nodes.py build_grouping_sets_expand).
        `sets` is a list of kept-key index lists, or None for ROLLUP."""
        for g in group_es:
            if not isinstance(g, (E.BoundReference, E.AttributeReference)):
                raise SqlAnalysisError(
                    "GROUP BY ROLLUP/CUBE/GROUPING SETS supports plain "
                    "columns only")
        if sets is None:
            return NN.build_rollup_expand(plan, group_es)
        return NN.build_grouping_sets_expand(plan, group_es, sets)

    # -- ORDER BY over a union (names/ordinals only) --------------------------
    def _order_union(self, plan, order_items):
        sort_exprs = []
        for (ast, asc, nf) in order_items:
            nulls_first = asc if nf is None else nf
            if isinstance(ast, P.Lit) and isinstance(ast.value, int):
                idx = ast.value - 1
            elif isinstance(ast, P.Ident) and len(ast.parts) == 1:
                idx = plan.output.index_of(ast.parts[-1])
            else:
                raise SqlAnalysisError(
                    "ORDER BY over UNION ALL supports output names/ordinals "
                    f"only (got {ast!r})")
            f = plan.output[idx]
            sort_exprs.append((E.BoundReference(idx, f.data_type, f.nullable,
                                                f.name), asc, nulls_first))
        return NN.SortNode(sort_exprs, plan)


def lower_sql(text: str, views: dict, session):
    """Parse + lower `text` against `views` ({name: DataFrame})."""
    q = P.parse_sql(text)
    return _Lowerer(session, views).lower(q)
