"""Official TPC-DS query text for the subset suite, run through session.sql().

These are the official TPC-DS templates (tpcds.org) with three kinds of
bounded substitutions, each forced by the test harness rather than by the SQL
front-end:

1. Parameter constants match the hand-built adaptations in
   benchmarks/tpcds.py so the same independent NumPy oracles check the rows.
2. Columns outside the generated subset schema substitute their subset
   equivalent (q43: d_day_name='Sunday' → d_dow=0; q34/q73: the
   household-demographics predicates the adaptation uses; q19/q89 drop output
   columns the generator doesn't carry, e.g. i_manufact).
3. ORDER BY carries the adaptations' deterministic tie-break keys where the
   official text under-specifies order (the spec permits any order among
   ties; the oracle comparison does not).

Structure — join shape, derived tables, CASE/BETWEEN/IN/HAVING, windows,
ROLLUP, set operations (q8/q14/q38/q87), IN-subqueries (q14/q45), FULL
OUTER JOIN (q97) — is the official text. q27 here is the FULL official
rollup form (the hand-built adaptation omits the rollup levels; SQL is the
more complete surface). Zip-list parameters substitute values from the
generated 10000-10099 domain and magnitude thresholds scale to the subset's
value ranges (rule 1); both are flagged inline.
"""

SQL_QUERIES = {}

SQL_QUERIES["q3"] = """
select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss_ext_sales_price) sum_agg
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manufact_id = 128
  and dt.d_moy = 11
group by dt.d_year, item.i_brand_id, item.i_brand
order by dt.d_year, sum_agg desc, brand_id
limit 100
"""

SQL_QUERIES["q42"] = """
select dt.d_year, item.i_category_id, item.i_category,
       sum(ss_ext_sales_price) sum_agg
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_category_id, item.i_category
order by sum_agg desc, dt.d_year, item.i_category_id
limit 100
"""

SQL_QUERIES["q52"] = """
select dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim dt, store_sales, item
where dt.d_date_sk = store_sales.ss_sold_date_sk
  and store_sales.ss_item_sk = item.i_item_sk
  and item.i_manager_id = 1
  and dt.d_moy = 11
  and dt.d_year = 2000
group by dt.d_year, item.i_brand_id, item.i_brand
order by dt.d_year, ext_price desc, brand_id
limit 100
"""

SQL_QUERIES["q55"] = """
select i_brand_id brand_id, i_brand brand,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 28
  and d_moy = 11
  and d_year = 1999
group by i_brand_id, i_brand
order by ext_price desc, brand_id
limit 100
"""

SQL_QUERIES["q7"] = """
select i_item_id,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, item, promotion
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_cdemo_sk = cd_demo_sk
  and ss_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

SQL_QUERIES["q19"] = """
select i_brand_id brand_id, i_brand brand, i_manufact_id,
       sum(ss_ext_sales_price) ext_price
from date_dim, store_sales, item, customer, customer_address, store
where d_date_sk = ss_sold_date_sk
  and ss_item_sk = i_item_sk
  and i_manager_id = 8
  and d_moy = 11
  and d_year = 1999
  and ss_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and substr(ca_zip, 1, 5) <> substr(s_zip, 1, 5)
  and ss_store_sk = s_store_sk
group by i_brand_id, i_brand, i_manufact_id
order by ext_price desc, brand_id
limit 100
"""

SQL_QUERIES["q43"] = """
select s_store_name,
       sum(case when (d_dow = 0) then ss_sales_price else null end) sun_sales,
       sum(case when (d_dow = 1) then ss_sales_price else null end) mon_sales,
       sum(case when (d_dow = 2) then ss_sales_price else null end) tue_sales,
       sum(case when (d_dow = 3) then ss_sales_price else null end) wed_sales,
       sum(case when (d_dow = 4) then ss_sales_price else null end) thu_sales,
       sum(case when (d_dow = 5) then ss_sales_price else null end) fri_sales,
       sum(case when (d_dow = 6) then ss_sales_price else null end) sat_sales
from date_dim, store_sales, store
where d_date_sk = ss_sold_date_sk
  and s_store_sk = ss_store_sk
  and d_year = 2000
group by s_store_name
order by s_store_name
limit 100
"""

SQL_QUERIES["q96"] = """
select count(*) cnt
from store_sales, household_demographics, time_dim, store
where ss_sold_time_sk = time_dim.t_time_sk
  and ss_hdemo_sk = household_demographics.hd_demo_sk
  and ss_store_sk = s_store_sk
  and time_dim.t_hour = 20
  and time_dim.t_minute >= 30
  and household_demographics.hd_dep_count = 5
  and store.s_store_name = 'store0'
order by count(*)
limit 100
"""

SQL_QUERIES["q34"] = """
select c_last_name, c_first_name, ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (date_dim.d_dom between 1 and 3
             or date_dim.d_dom between 25 and 28)
        and household_demographics.hd_buy_potential <> 'Unknown'
        and household_demographics.hd_dep_count between 2 and 9
        and date_dim.d_year in (1999, 2000, 2001)
      group by ss_ticket_number, ss_customer_sk
      having count(*) between 15 and 20) dn, customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, ss_ticket_number, cnt desc
"""

SQL_QUERIES["q73"] = """
select c_last_name, c_first_name, ss_ticket_number, cnt
from (select ss_ticket_number, ss_customer_sk, count(*) cnt
      from store_sales, date_dim, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (date_dim.d_dom between 1 and 3
             or date_dim.d_dom between 25 and 28)
        and household_demographics.hd_buy_potential <> 'Unknown'
        and household_demographics.hd_dep_count between 1 and 9
        and date_dim.d_year in (1999, 2000, 2001)
      group by ss_ticket_number, ss_customer_sk
      having count(*) between 1 and 5) dj, customer
where ss_customer_sk = c_customer_sk
order by cnt desc, c_last_name, c_first_name, ss_ticket_number
limit 1000
"""

SQL_QUERIES["q48"] = """
select sum(ss_quantity) total
from store_sales, store, customer_demographics, customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_year = 2000
  and ((cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M'
        and cd_education_status = '4 yr Degree'
        and ss_sales_price between 100.00 and 150.00)
       or (cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'D'
           and cd_education_status = '2 yr Degree'
           and ss_sales_price between 50.00 and 100.00)
       or (cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'S'
           and cd_education_status = 'College'
           and ss_sales_price between 150.00 and 200.00))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('CA', 'TX', 'OH')
        and ss_net_profit between 0 and 2000)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('NY', 'GA', 'WA')
           and ss_net_profit between 150 and 3000)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('IL', 'MI')
           and ss_net_profit between 50 and 25000))
"""

SQL_QUERIES["q53"] = """
select * from
  (select i_manufact_id, sum(ss_sales_price) sum_sales,
          avg(sum(ss_sales_price)) over (partition by i_manufact_id)
            avg_quarterly_sales
   from item, store_sales, date_dim, store
   where ss_item_sk = i_item_sk
     and ss_sold_date_sk = d_date_sk
     and ss_store_sk = s_store_sk
     and d_year = 2000
     and i_category in ('Books', 'Home', 'Electronics')
   group by i_manufact_id, d_qoy) tmp1
where avg_quarterly_sales > 0
  and case when avg_quarterly_sales > 0
           then abs(sum_sales - avg_quarterly_sales) / avg_quarterly_sales
           else null end > 0.1
order by avg_quarterly_sales, sum_sales, i_manufact_id
limit 100
"""

SQL_QUERIES["q63"] = """
select * from
  (select i_manager_id, sum(ss_sales_price) sum_sales,
          avg(sum(ss_sales_price)) over (partition by i_manager_id)
            avg_monthly_sales
   from item, store_sales, date_dim
   where ss_item_sk = i_item_sk
     and ss_sold_date_sk = d_date_sk
     and d_year = 2000
     and i_category in ('Books', 'Home', 'Electronics')
   group by i_manager_id, d_moy) tmp1
where avg_monthly_sales > 0
  and case when avg_monthly_sales > 0
           then abs(sum_sales - avg_monthly_sales) / avg_monthly_sales
           else null end > 0.1
order by i_manager_id, avg_monthly_sales, sum_sales
limit 100
"""

SQL_QUERIES["q89"] = """
select * from
  (select i_category, i_class, i_brand, s_store_name, d_moy,
          sum(ss_sales_price) sum_sales,
          avg(sum(ss_sales_price))
            over (partition by i_category, i_brand, s_store_name)
            avg_monthly_sales
   from item, store_sales, date_dim, store
   where ss_item_sk = i_item_sk
     and ss_sold_date_sk = d_date_sk
     and ss_store_sk = s_store_sk
     and d_year = 1999
     and i_category in ('Books', 'Electronics', 'Sports')
   group by i_category, i_class, i_brand, s_store_name, d_moy) tmp1
where avg_monthly_sales <> 0
  and abs(sum_sales - avg_monthly_sales) / avg_monthly_sales > 0.1
order by sum_sales - avg_monthly_sales, s_store_name, i_class, d_moy
limit 100
"""

SQL_QUERIES["q98"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ss_ext_sales_price) itemrevenue,
       sum(ss_ext_sales_price) * 100.0
         / sum(sum(ss_ext_sales_price)) over (partition by i_class)
         revenueratio
from store_sales, item, date_dim
where ss_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ss_sold_date_sk = d_date_sk
  and d_year = 1999
  and d_moy = 2
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
"""

SQL_QUERIES["q27"] = """
select i_item_id, s_state, grouping(s_state) g_state,
       avg(ss_quantity) agg1,
       avg(ss_list_price) agg2,
       avg(ss_coupon_amt) agg3,
       avg(ss_sales_price) agg4
from store_sales, customer_demographics, date_dim, store, item
where ss_sold_date_sk = d_date_sk
  and ss_item_sk = i_item_sk
  and ss_store_sk = s_store_sk
  and ss_cdemo_sk = cd_demo_sk
  and cd_gender = 'F'
  and cd_marital_status = 'W'
  and cd_education_status = 'Primary'
  and d_year = 1999
  and s_state in ('CA', 'TX', 'NY', 'OH')
group by rollup (i_item_id, s_state)
order by i_item_id, s_state
limit 100
"""

SQL_QUERIES["q65"] = """
select s_store_name, i_item_desc, sc.revenue, i_current_price
from store, item,
     (select ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
      from store_sales, date_dim
      where ss_sold_date_sk = d_date_sk and d_year = 2000
      group by ss_store_sk, ss_item_sk) sc,
     (select ss_store_sk, avg(revenue) ave
      from (select ss_store_sk, ss_item_sk, sum(ss_sales_price) revenue
            from store_sales, date_dim
            where ss_sold_date_sk = d_date_sk and d_year = 2000
            group by ss_store_sk, ss_item_sk) sa
      group by ss_store_sk) sb
where sb.ss_store_sk = sc.ss_store_sk
  and sc.revenue <= 0.1 * sb.ave
  and s_store_sk = sc.ss_store_sk
  and i_item_sk = sc.ss_item_sk
order by s_store_name, i_item_desc
limit 100
"""

SQL_QUERIES["q79"] = """
select c_last_name, c_first_name, s_city, profit, ss_ticket_number, amt
from (select ss_ticket_number, ss_customer_sk, store.s_city,
             sum(ss_coupon_amt) amt, sum(ss_net_profit) profit
      from store_sales, date_dim, store, household_demographics
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and (household_demographics.hd_dep_count = 6
             or household_demographics.hd_vehicle_count > 2)
        and date_dim.d_dow = 1
        and date_dim.d_year in (1998, 1999, 2000)
        and store.s_number_employees between 200 and 295
      group by ss_ticket_number, ss_customer_sk, store.s_city) ms, customer
where ss_customer_sk = c_customer_sk
order by c_last_name, c_first_name, s_city, profit
limit 100
"""

SQL_QUERIES["q46"] = """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       amt, profit
from (select ss_ticket_number, ss_customer_sk,
             ca_city bought_city,
             sum(ss_coupon_amt) amt, sum(ss_ext_sales_price) profit
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and store_sales.ss_addr_sk = customer_address.ca_address_sk
        and (household_demographics.hd_dep_count = 5
             or household_demographics.hd_vehicle_count = 3)
        and date_dim.d_dow in (6, 0)
        and date_dim.d_year in (1999, 2000, 2001)
        and store.s_city in ('Midway', 'Fairview', 'Oakland')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, c_first_name, current_addr.ca_city, bought_city,
         ss_ticket_number
limit 100
"""

SQL_QUERIES["q68"] = """
select c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       extended_price, extended_tax, list_price
from (select ss_ticket_number, ss_customer_sk,
             ca_city bought_city,
             sum(ss_ext_sales_price) extended_price,
             sum(ss_ext_list_price) list_price,
             sum(ss_ext_tax) extended_tax
      from store_sales, date_dim, store, household_demographics,
           customer_address
      where store_sales.ss_sold_date_sk = date_dim.d_date_sk
        and store_sales.ss_store_sk = store.s_store_sk
        and store_sales.ss_hdemo_sk = household_demographics.hd_demo_sk
        and store_sales.ss_addr_sk = customer_address.ca_address_sk
        and date_dim.d_dom between 1 and 2
        and (household_demographics.hd_dep_count = 4
             or household_demographics.hd_vehicle_count = 3)
        and date_dim.d_year in (1998, 1999, 2000)
        and store.s_city in ('Midway', 'Fairview')
      group by ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
where ss_customer_sk = c_customer_sk
  and customer.c_current_addr_sk = current_addr.ca_address_sk
  and current_addr.ca_city <> bought_city
order by c_last_name, ss_ticket_number
limit 100
"""

SQL_QUERIES["q88"] = """
select * from
 (select count(*) h8_30_to_9
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 8 and time_dim.t_minute >= 30
    and time_dim.t_minute < 60
    and ((household_demographics.hd_dep_count = 3
          and household_demographics.hd_vehicle_count <= 5)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2)
         or (household_demographics.hd_dep_count = 1
             and household_demographics.hd_vehicle_count <= 3))
    and store.s_store_name = 'store0') s1,
 (select count(*) h9_to_9_30
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 9 and time_dim.t_minute >= 0
    and time_dim.t_minute < 30
    and ((household_demographics.hd_dep_count = 3
          and household_demographics.hd_vehicle_count <= 5)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2)
         or (household_demographics.hd_dep_count = 1
             and household_demographics.hd_vehicle_count <= 3))
    and store.s_store_name = 'store0') s2,
 (select count(*) h9_30_to_10
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 9 and time_dim.t_minute >= 30
    and time_dim.t_minute < 60
    and ((household_demographics.hd_dep_count = 3
          and household_demographics.hd_vehicle_count <= 5)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2)
         or (household_demographics.hd_dep_count = 1
             and household_demographics.hd_vehicle_count <= 3))
    and store.s_store_name = 'store0') s3,
 (select count(*) h10_to_10_30
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 10 and time_dim.t_minute >= 0
    and time_dim.t_minute < 30
    and ((household_demographics.hd_dep_count = 3
          and household_demographics.hd_vehicle_count <= 5)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2)
         or (household_demographics.hd_dep_count = 1
             and household_demographics.hd_vehicle_count <= 3))
    and store.s_store_name = 'store0') s4,
 (select count(*) h10_30_to_11
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 10 and time_dim.t_minute >= 30
    and time_dim.t_minute < 60
    and ((household_demographics.hd_dep_count = 3
          and household_demographics.hd_vehicle_count <= 5)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2)
         or (household_demographics.hd_dep_count = 1
             and household_demographics.hd_vehicle_count <= 3))
    and store.s_store_name = 'store0') s5,
 (select count(*) h11_to_11_30
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 11 and time_dim.t_minute >= 0
    and time_dim.t_minute < 30
    and ((household_demographics.hd_dep_count = 3
          and household_demographics.hd_vehicle_count <= 5)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2)
         or (household_demographics.hd_dep_count = 1
             and household_demographics.hd_vehicle_count <= 3))
    and store.s_store_name = 'store0') s6,
 (select count(*) h11_30_to_12
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 11 and time_dim.t_minute >= 30
    and time_dim.t_minute < 60
    and ((household_demographics.hd_dep_count = 3
          and household_demographics.hd_vehicle_count <= 5)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2)
         or (household_demographics.hd_dep_count = 1
             and household_demographics.hd_vehicle_count <= 3))
    and store.s_store_name = 'store0') s7,
 (select count(*) h12_to_12_30
  from store_sales, household_demographics, time_dim, store
  where ss_sold_time_sk = time_dim.t_time_sk
    and ss_hdemo_sk = household_demographics.hd_demo_sk
    and ss_store_sk = s_store_sk
    and time_dim.t_hour = 12 and time_dim.t_minute >= 0
    and time_dim.t_minute < 30
    and ((household_demographics.hd_dep_count = 3
          and household_demographics.hd_vehicle_count <= 5)
         or (household_demographics.hd_dep_count = 0
             and household_demographics.hd_vehicle_count <= 2)
         or (household_demographics.hd_dep_count = 1
             and household_demographics.hd_vehicle_count <= 3))
    and store.s_store_name = 'store0') s8
"""

# -- SQL-only additions (no DataFrame adaptation exists; oracles in
# benchmarks/tpcds.py np_q13/np_q36). State lists substitute the generator's
# 8-state domain; q36 carries deterministic ORDER BY tie-breaks.

SQL_QUERIES["q13"] = """
select avg(ss_quantity) aq, avg(ss_ext_sales_price) ap,
       avg(ss_ext_wholesale_cost) aw, sum(ss_ext_wholesale_cost) sw
from store_sales, store, customer_demographics, household_demographics,
     customer_address, date_dim
where s_store_sk = ss_store_sk
  and ss_sold_date_sk = d_date_sk and d_year = 2001
  and ((ss_hdemo_sk = hd_demo_sk
        and cd_demo_sk = ss_cdemo_sk
        and cd_marital_status = 'M'
        and cd_education_status = 'Advanced Degree'
        and ss_sales_price between 100.00 and 200.00
        and hd_dep_count = 3)
       or (ss_hdemo_sk = hd_demo_sk
           and cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'S'
           and cd_education_status = 'College'
           and ss_sales_price between 50.00 and 150.00
           and hd_dep_count = 1)
       or (ss_hdemo_sk = hd_demo_sk
           and cd_demo_sk = ss_cdemo_sk
           and cd_marital_status = 'W'
           and cd_education_status = '2 yr Degree'
           and ss_sales_price between 1.00 and 100.00
           and hd_dep_count = 1))
  and ((ss_addr_sk = ca_address_sk
        and ca_country = 'United States'
        and ca_state in ('CA', 'TX', 'OH')
        and ss_net_profit between 0 and 2000)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('NY', 'GA', 'WA')
           and ss_net_profit between 150 and 3000)
       or (ss_addr_sk = ca_address_sk
           and ca_country = 'United States'
           and ca_state in ('IL', 'MI', 'CA')
           and ss_net_profit between 50 and 2500))
"""

SQL_QUERIES["q36"] = """
select sum(ss_net_profit) / sum(ss_ext_sales_price) gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) lochierarchy,
       rank() over (partition by grouping(i_category) + grouping(i_class),
                    case when grouping(i_class) = 0 then i_category end
                    order by sum(ss_net_profit) / sum(ss_ext_sales_price)
                    asc) rank_within_parent
from store_sales, date_dim d1, item, store
where d1.d_year = 2001
  and d1.d_date_sk = ss_sold_date_sk
  and i_item_sk = ss_item_sk
  and s_store_sk = ss_store_sk
  and s_state in ('CA', 'TX', 'NY', 'GA', 'OH', 'WA', 'IL', 'MI')
group by rollup (i_category, i_class)
order by lochierarchy desc,
         case when lochierarchy = 0 then i_category end,
         rank_within_parent, i_category, i_class
limit 100
"""

SQL_QUERIES["q28"] = """
select  *
from (select avg(ss_list_price) B1_LP
            ,count(ss_list_price) B1_CNT
            ,count(distinct ss_list_price) B1_CNTD
      from store_sales
      where ss_quantity between 0 and 5
        and (ss_list_price between 8 and 8+10
             or ss_coupon_amt between 459 and 459+1000
             or ss_wholesale_cost between 57 and 57+20)) B1,
     (select avg(ss_list_price) B2_LP
            ,count(ss_list_price) B2_CNT
            ,count(distinct ss_list_price) B2_CNTD
      from store_sales
      where ss_quantity between 6 and 10
        and (ss_list_price between 90 and 90+10
             or ss_coupon_amt between 2323 and 2323+1000
             or ss_wholesale_cost between 31 and 31+20)) B2,
     (select avg(ss_list_price) B3_LP
            ,count(ss_list_price) B3_CNT
            ,count(distinct ss_list_price) B3_CNTD
      from store_sales
      where ss_quantity between 11 and 15
        and (ss_list_price between 142 and 142+10
             or ss_coupon_amt between 12214 and 12214+1000
             or ss_wholesale_cost between 79 and 79+20)) B3,
     (select avg(ss_list_price) B4_LP
            ,count(ss_list_price) B4_CNT
            ,count(distinct ss_list_price) B4_CNTD
      from store_sales
      where ss_quantity between 16 and 20
        and (ss_list_price between 135 and 135+10
             or ss_coupon_amt between 6071 and 6071+1000
             or ss_wholesale_cost between 38 and 38+20)) B4,
     (select avg(ss_list_price) B5_LP
            ,count(ss_list_price) B5_CNT
            ,count(distinct ss_list_price) B5_CNTD
      from store_sales
      where ss_quantity between 21 and 25
        and (ss_list_price between 122 and 122+10
             or ss_coupon_amt between 836 and 836+1000
             or ss_wholesale_cost between 17 and 17+20)) B5,
     (select avg(ss_list_price) B6_LP
            ,count(ss_list_price) B6_CNT
            ,count(distinct ss_list_price) B6_CNTD
      from store_sales
      where ss_quantity between 26 and 30
        and (ss_list_price between 154 and 154+10
             or ss_coupon_amt between 7326 and 7326+1000
             or ss_wholesale_cost between 7 and 7+20)) B6
limit 100
"""

SQL_QUERIES["q8"] = """
select s_store_name, sum(ss_net_profit)
from store_sales, date_dim, store,
     (select ca_zip
      from (
       (select substr(ca_zip,1,5) ca_zip
        from customer_address
        where substr(ca_zip,1,5) in ('10000','10005','10010','10015',
              '10020','10025','10030','10035','10040','10045','10050',
              '10055','10060','10065','10070','10075','10080','10085',
              '10090','10095'))
       intersect
       (select ca_zip
        from (select substr(ca_zip,1,5) ca_zip, count(*) cnt
              from customer_address, customer
              where ca_address_sk = c_current_addr_sk and
                    c_preferred_cust_flag = 'Y'
              group by ca_zip
              having count(*) > 4) A1)) A2) V1
where ss_store_sk = s_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 1998
  and (substr(s_zip,1,2) = substr(V1.ca_zip,1,2))
group by s_store_name
order by s_store_name
limit 100
"""

SQL_QUERIES["q38"] = """
select count(*) from (
    select distinct c_last_name, c_first_name, d_date
    from store_sales, date_dim, customer
          where store_sales.ss_sold_date_sk = date_dim.d_date_sk
      and store_sales.ss_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200+11
  intersect
    select distinct c_last_name, c_first_name, d_date
    from catalog_sales, date_dim, customer
          where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
      and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200+11
  intersect
    select distinct c_last_name, c_first_name, d_date
    from web_sales, date_dim, customer
          where web_sales.ws_sold_date_sk = date_dim.d_date_sk
      and web_sales.ws_bill_customer_sk = customer.c_customer_sk
      and d_month_seq between 1200 and 1200+11
) hot_cust
limit 100
"""

SQL_QUERIES["q87"] = """
select count(*)
from ((select distinct c_last_name, c_first_name, d_date
       from store_sales, date_dim, customer
       where store_sales.ss_sold_date_sk = date_dim.d_date_sk
         and store_sales.ss_customer_sk = customer.c_customer_sk
         and d_month_seq between 1200 and 1200+11)
       except
      (select distinct c_last_name, c_first_name, d_date
       from catalog_sales, date_dim, customer
       where catalog_sales.cs_sold_date_sk = date_dim.d_date_sk
         and catalog_sales.cs_bill_customer_sk = customer.c_customer_sk
         and d_month_seq between 1200 and 1200+11)
       except
      (select distinct c_last_name, c_first_name, d_date
       from web_sales, date_dim, customer
       where web_sales.ws_sold_date_sk = date_dim.d_date_sk
         and web_sales.ws_bill_customer_sk = customer.c_customer_sk
         and d_month_seq between 1200 and 1200+11)
) cool_cust
"""

SQL_QUERIES["q14"] = """
with cross_items as
 (select i_item_sk ss_item_sk
 from item,
 (select iss.i_brand_id brand_id
     ,iss.i_class_id class_id
     ,iss.i_category_id category_id
 from store_sales, item iss, date_dim d1
 where ss_item_sk = iss.i_item_sk
   and ss_sold_date_sk = d1.d_date_sk
   and d1.d_year between 1999 and 1999 + 2
 intersect
 select ics.i_brand_id
     ,ics.i_class_id
     ,ics.i_category_id
 from catalog_sales, item ics, date_dim d2
 where cs_item_sk = ics.i_item_sk
   and cs_sold_date_sk = d2.d_date_sk
   and d2.d_year between 1999 and 1999 + 2
 intersect
 select iws.i_brand_id
     ,iws.i_class_id
     ,iws.i_category_id
 from web_sales, item iws, date_dim d3
 where ws_item_sk = iws.i_item_sk
   and ws_sold_date_sk = d3.d_date_sk
   and d3.d_year between 1999 and 1999 + 2) x
 where i_brand_id = brand_id
      and i_class_id = class_id
      and i_category_id = category_id
),
 avg_sales as
 (select avg(quantity*list_price) average_sales
  from (select ss_quantity quantity
             ,ss_list_price list_price
       from store_sales
           ,date_dim
       where ss_sold_date_sk = d_date_sk
         and d_year between 1999 and 1999 + 2
       union all
       select cs_quantity quantity
             ,cs_list_price list_price
       from catalog_sales
           ,date_dim
       where cs_sold_date_sk = d_date_sk
         and d_year between 1999 and 1999 + 2
       union all
       select ws_quantity quantity
             ,ws_list_price list_price
       from web_sales
           ,date_dim
       where ws_sold_date_sk = d_date_sk
         and d_year between 1999 and 1999 + 2) x)
select channel, i_brand_id,i_class_id,i_category_id,sum(sales) sum_sales,
       sum(number_sales) sum_number_sales
from(
       select 'store' channel, i_brand_id,i_class_id
             ,i_category_id,sum(ss_quantity*ss_list_price) sales
             ,count(*) number_sales
       from store_sales
           ,item
           ,date_dim
       where ss_item_sk in (select ss_item_sk from cross_items)
         and ss_item_sk = i_item_sk
         and ss_sold_date_sk = d_date_sk
         and d_year = 1999+2
         and d_moy = 11
       group by i_brand_id,i_class_id,i_category_id
       having sum(ss_quantity*ss_list_price) > (select average_sales from avg_sales)
       union all
       select 'catalog' channel, i_brand_id,i_class_id,i_category_id
             ,sum(cs_quantity*cs_list_price) sales
             ,count(*) number_sales
       from catalog_sales
           ,item
           ,date_dim
       where cs_item_sk in (select ss_item_sk from cross_items)
         and cs_item_sk = i_item_sk
         and cs_sold_date_sk = d_date_sk
         and d_year = 1999+2
         and d_moy = 11
       group by i_brand_id,i_class_id,i_category_id
       having sum(cs_quantity*cs_list_price) > (select average_sales from avg_sales)
       union all
       select 'web' channel, i_brand_id,i_class_id,i_category_id
             ,sum(ws_quantity*ws_list_price) sales
             ,count(*) number_sales
       from web_sales
           ,item
           ,date_dim
       where ws_item_sk in (select ss_item_sk from cross_items)
         and ws_item_sk = i_item_sk
         and ws_sold_date_sk = d_date_sk
         and d_year = 1999+2
         and d_moy = 11
       group by i_brand_id,i_class_id,i_category_id
       having sum(ws_quantity*ws_list_price) > (select average_sales from avg_sales)
 ) y
group by rollup (channel, i_brand_id, i_class_id, i_category_id)
order by channel,i_brand_id,i_class_id,i_category_id
limit 100
"""

SQL_QUERIES["q15"] = """
select ca_zip, sum(cs_sales_price)
from catalog_sales, customer, customer_address, date_dim
where cs_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and (substr(ca_zip,1,5) in ('10005','10010','10020','10035','10040',
                              '10055','10070','10085','10090')
       or ca_state in ('CA','WA','GA')
       or cs_sales_price > 150)
  and cs_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
group by ca_zip
order by ca_zip
limit 100
"""

SQL_QUERIES["q45"] = """
select ca_zip, ca_city, sum(ws_sales_price)
from web_sales, customer, customer_address, date_dim, item
where ws_bill_customer_sk = c_customer_sk
  and c_current_addr_sk = ca_address_sk
  and ws_item_sk = i_item_sk
  and (substr(ca_zip,1,5) in ('10005','10010','10020','10035','10040',
                              '10055','10070','10085','10090')
       or
       i_item_id in (select i_item_id
                     from item
                     where i_item_sk in (2, 3, 5, 7, 11, 13, 17, 19, 23, 29)
                    )
      )
  and ws_sold_date_sk = d_date_sk
  and d_qoy = 2 and d_year = 2001
group by ca_zip, ca_city
order by ca_zip, ca_city
limit 100
"""

SQL_QUERIES["q61"] = """
select promotions, total,
       cast(promotions as decimal(15,4))/cast(total as decimal(15,4))*100
from
  (select sum(ss_ext_sales_price) promotions
   from store_sales, store, promotion, date_dim, customer,
        customer_address, item
   where ss_sold_date_sk = d_date_sk
     and ss_store_sk = s_store_sk
     and ss_promo_sk = p_promo_sk
     and ss_customer_sk = c_customer_sk
     and ca_address_sk = c_current_addr_sk
     and ss_item_sk = i_item_sk
     and ca_gmt_offset = -6
     and i_category = 'Books'
     and (p_channel_dmail = 'Y' or p_channel_email = 'Y'
          or p_channel_tv = 'Y')
     and s_gmt_offset = -6
     and d_year = 2000
     and d_moy = 11) promotional_sales,
  (select sum(ss_ext_sales_price) total
   from store_sales, store, date_dim, customer, customer_address, item
   where ss_sold_date_sk = d_date_sk
     and ss_store_sk = s_store_sk
     and ss_customer_sk = c_customer_sk
     and ca_address_sk = c_current_addr_sk
     and ss_item_sk = i_item_sk
     and ca_gmt_offset = -6
     and i_category = 'Books'
     and s_gmt_offset = -6
     and d_year = 2000
     and d_moy = 11) all_sales
order by promotions, total
limit 100
"""

SQL_QUERIES["q97"] = """
with ssci as (
select ss_customer_sk customer_sk
      ,ss_item_sk item_sk
from store_sales,date_dim
where ss_sold_date_sk = d_date_sk
  and d_month_seq between 1200 and 1200 + 11
group by ss_customer_sk
        ,ss_item_sk),
csci as(
 select cs_bill_customer_sk customer_sk
      ,cs_item_sk item_sk
from catalog_sales,date_dim
where cs_sold_date_sk = d_date_sk
  and d_month_seq between 1200 and 1200 + 11
group by cs_bill_customer_sk
        ,cs_item_sk)
select sum(case when ssci.customer_sk is not null
                 and csci.customer_sk is null then 1 else 0 end) store_only
      ,sum(case when ssci.customer_sk is null
                 and csci.customer_sk is not null then 1 else 0 end)
           catalog_only
      ,sum(case when ssci.customer_sk is not null
                 and csci.customer_sk is not null then 1 else 0 end)
           store_and_catalog
from ssci full outer join csci on (ssci.customer_sk = csci.customer_sk
                               and ssci.item_sk = csci.item_sk)
limit 100
"""

SQL_QUERIES["q33"] = """
with ss as (
 select
          i_manufact_id,sum(ss_ext_sales_price) total_sales
 from
 	store_sales,
 	date_dim,
         customer_address,
         item
 where
         i_manufact_id in (select
  i_manufact_id
from
 item
where i_category in ('Electronics'))
 and     ss_item_sk              = i_item_sk
 and     ss_sold_date_sk         = d_date_sk
 and     d_year                  = 1998
 and     d_moy                   = 5
 and     ss_addr_sk              = ca_address_sk
 and     ca_gmt_offset           = -5
 group by i_manufact_id),
 cs as (
 select
          i_manufact_id,sum(cs_ext_sales_price) total_sales
 from
 	catalog_sales,
 	date_dim,
         customer_address,
         item
 where
         i_manufact_id               in (select
  i_manufact_id
from
 item
where i_category in ('Electronics'))
 and     cs_item_sk              = i_item_sk
 and     cs_sold_date_sk         = d_date_sk
 and     d_year                  = 1998
 and     d_moy                   = 5
 and     cs_bill_addr_sk         = ca_address_sk
 and     ca_gmt_offset           = -5
 group by i_manufact_id),
 ws as (
 select
          i_manufact_id,sum(ws_ext_sales_price) total_sales
 from
 	web_sales,
 	date_dim,
         customer_address,
         item
 where
         i_manufact_id               in (select
  i_manufact_id
from
 item
where i_category in ('Electronics'))
 and     ws_item_sk              = i_item_sk
 and     ws_sold_date_sk         = d_date_sk
 and     d_year                  = 1998
 and     d_moy                   = 5
 and     ws_bill_addr_sk         = ca_address_sk
 and     ca_gmt_offset           = -5
 group by i_manufact_id)
 select  i_manufact_id ,sum(total_sales) total_sales
 from  (select * from ss
        union all
        select * from cs
        union all
        select * from ws) tmp1
 group by i_manufact_id
 order by total_sales, i_manufact_id
limit 100
"""

SQL_QUERIES["q56"] = """
with ss as (
 select i_item_id,sum(ss_ext_sales_price) total_sales
 from
 	store_sales,
 	date_dim,
         customer_address,
         item
 where i_item_id in (select
     i_item_id
from item
where i_color in ('slate','blanched','burnished'))
 and     ss_item_sk              = i_item_sk
 and     ss_sold_date_sk         = d_date_sk
 and     d_year                  = 2001
 and     d_moy                   = 2
 and     ss_addr_sk              = ca_address_sk
 and     ca_gmt_offset           = -5
 group by i_item_id),
 cs as (
 select i_item_id,sum(cs_ext_sales_price) total_sales
 from
 	catalog_sales,
 	date_dim,
         customer_address,
         item
 where
         i_item_id               in (select
  i_item_id
from item
where i_color in ('slate','blanched','burnished'))
 and     cs_item_sk              = i_item_sk
 and     cs_sold_date_sk         = d_date_sk
 and     d_year                  = 2001
 and     d_moy                   = 2
 and     cs_bill_addr_sk         = ca_address_sk
 and     ca_gmt_offset           = -5
 group by i_item_id),
 ws as (
 select i_item_id,sum(ws_ext_sales_price) total_sales
 from
 	web_sales,
 	date_dim,
         customer_address,
         item
 where
         i_item_id               in (select
  i_item_id
from item
where i_color in ('slate','blanched','burnished'))
 and     ws_item_sk              = i_item_sk
 and     ws_sold_date_sk         = d_date_sk
 and     d_year                  = 2001
 and     d_moy                   = 2
 and     ws_bill_addr_sk         = ca_address_sk
 and     ca_gmt_offset           = -5
 group by i_item_id)
 select  i_item_id ,sum(total_sales) total_sales
 from  (select * from ss
        union all
        select * from cs
        union all
        select * from ws) tmp1
 group by i_item_id
 order by total_sales, i_item_id
limit 100
"""

SQL_QUERIES["q12"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(ws_ext_sales_price) itemrevenue,
       sum(ws_ext_sales_price) * 100.0
         / sum(sum(ws_ext_sales_price)) over (partition by i_class)
         revenueratio
from web_sales, item, date_dim
where ws_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and ws_sold_date_sk = d_date_sk
  and d_year = 1999
  and d_moy = 2
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

SQL_QUERIES["q20"] = """
select i_item_id, i_item_desc, i_category, i_class, i_current_price,
       sum(cs_ext_sales_price) itemrevenue,
       sum(cs_ext_sales_price) * 100.0
         / sum(sum(cs_ext_sales_price)) over (partition by i_class)
         revenueratio
from catalog_sales, item, date_dim
where cs_item_sk = i_item_sk
  and i_category in ('Sports', 'Books', 'Home')
  and cs_sold_date_sk = d_date_sk
  and d_year = 1999
  and d_moy = 2
group by i_item_id, i_item_desc, i_category, i_class, i_current_price
order by i_category, i_class, i_item_id, i_item_desc, revenueratio
limit 100
"""

SQL_QUERIES["q26"] = """
select i_item_id,
       avg(cs_quantity) agg1,
       avg(cs_list_price) agg2,
       avg(cs_coupon_amt) agg3,
       avg(cs_sales_price) agg4
from catalog_sales, customer_demographics, date_dim, item, promotion
where cs_sold_date_sk = d_date_sk
  and cs_item_sk = i_item_sk
  and cs_bill_cdemo_sk = cd_demo_sk
  and cs_promo_sk = p_promo_sk
  and cd_gender = 'M'
  and cd_marital_status = 'S'
  and cd_education_status = 'College'
  and (p_channel_email = 'N' or p_channel_event = 'N')
  and d_year = 2000
group by i_item_id
order by i_item_id
limit 100
"""

SQL_QUERIES["q18"] = """
select i_item_id,
       ca_country,
       ca_state,
       ca_county,
       avg( cast(cs_quantity as decimal(12,2))) agg1,
       avg( cast(cs_list_price as decimal(12,2))) agg2,
       avg( cast(cs_coupon_amt as decimal(12,2))) agg3,
       avg( cast(cs_sales_price as decimal(12,2))) agg4,
       avg( cast(cs_net_profit as decimal(12,2))) agg5,
       avg( cast(c_birth_year as decimal(12,2))) agg6,
       avg( cast(cd1.cd_dep_count as decimal(12,2))) agg7
 from catalog_sales, customer_demographics cd1,
      customer_demographics cd2, customer, customer_address, date_dim, item
 where cs_sold_date_sk = d_date_sk and
       cs_item_sk = i_item_sk and
       cs_bill_cdemo_sk = cd1.cd_demo_sk and
       cs_bill_customer_sk = c_customer_sk and
       cd1.cd_gender = 'F' and
       cd1.cd_education_status = 'Unknown' and
       c_current_cdemo_sk = cd2.cd_demo_sk and
       c_current_addr_sk = ca_address_sk and
       c_birth_month in (1,6,8,9,12,2) and
       d_year = 1998 and
       ca_state in ('CA','TX','NY','GA','OH','WA')
 group by rollup (i_item_id, ca_country, ca_state, ca_county)
 order by ca_country, ca_state, ca_county, i_item_id
 limit 100
"""

SQL_QUERIES["q69"] = """
select
  cd_gender,
  cd_marital_status,
  cd_education_status,
  count(*) cnt1,
  cd_purchase_estimate,
  count(*) cnt2,
  cd_credit_rating,
  count(*) cnt3
 from
  customer c, customer_address ca, customer_demographics
 where
  c.c_current_addr_sk = ca.ca_address_sk and
  ca_state in ('CA','TX','NY') and
  cd_demo_sk = c.c_current_cdemo_sk and
  exists (select *
          from store_sales, date_dim
          where c.c_customer_sk = ss_customer_sk and
                ss_sold_date_sk = d_date_sk and
                d_year = 2001 and
                d_moy between 4 and 4+2) and
   (not exists (select *
                from web_sales, date_dim
                where c.c_customer_sk = ws_bill_customer_sk and
                      ws_sold_date_sk = d_date_sk and
                      d_year = 2001 and
                      d_moy between 4 and 4+2) and
    not exists (select *
                from catalog_sales, date_dim
                where c.c_customer_sk = cs_bill_customer_sk and
                      cs_sold_date_sk = d_date_sk and
                      d_year = 2001 and
                      d_moy between 4 and 4+2))
 group by cd_gender, cd_marital_status, cd_education_status,
          cd_purchase_estimate, cd_credit_rating
 order by cd_gender, cd_marital_status, cd_education_status,
          cd_purchase_estimate, cd_credit_rating
 limit 100
"""

SQL_QUERIES["q22"] = """
select i_item_id,
       i_brand,
       i_class,
       i_category,
       avg(inv_quantity_on_hand) qoh
       from inventory, date_dim, item
       where inv_date_sk = d_date_sk
              and inv_item_sk = i_item_sk
              and d_month_seq between 1200 and 1200 + 11
       group by rollup(i_item_id, i_brand, i_class, i_category)
order by qoh, i_item_id, i_brand, i_class, i_category
limit 100
"""
