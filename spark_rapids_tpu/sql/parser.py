"""SQL lexer + recursive-descent parser producing a small AST.

The grammar is the subset the official TPC-DS/TPC-H query text needs (see
sql/__init__ docstring). The AST is engine-agnostic; sql/lower.py converts it
to plan nodes + expressions.
"""

from __future__ import annotations

import dataclasses
import typing


class SqlParseError(ValueError):
    pass


# -- tokens -------------------------------------------------------------------

_TWO_CHAR = ("<=", ">=", "<>", "!=", "||")
_ONE_CHAR = "+-*/%(),.<>=;"


@dataclasses.dataclass
class Token:
    kind: str        # kw | ident | num | str | op | end
    value: typing.Any
    pos: int


_KEYWORDS = {
    "select", "distinct", "from", "where", "group", "by", "having", "order",
    "limit", "as", "and", "or", "not", "in", "between", "like", "is", "null",
    "case", "when", "then", "else", "end", "cast", "join", "inner", "left",
    "right", "full", "outer", "cross", "on", "union", "all", "over",
    "partition", "rows", "range", "unbounded", "preceding", "following",
    "current", "row", "asc", "desc", "nulls", "first", "last", "rollup",
    "with", "exists", "intersect", "except", "semi", "anti", "using",
}


def tokenize(text: str) -> list:
    toks = []
    i, n = 0, len(text)
    while i < n:
        ch = text[i]
        if ch in " \t\r\n":
            i += 1
            continue
        if ch == "-" and text[i:i + 2] == "--":           # line comment
            j = text.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if ch == "/" and text[i:i + 2] == "/*":           # block comment
            j = text.find("*/", i)
            if j < 0:
                raise SqlParseError(f"unterminated comment at {i}")
            i = j + 2
            continue
        if ch == "'":                                      # string ('' escape)
            j, buf = i + 1, []
            while True:
                if j >= n:
                    raise SqlParseError(f"unterminated string at {i}")
                if text[j] == "'":
                    if text[j:j + 2] == "''":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(text[j])
                j += 1
            toks.append(Token("str", "".join(buf), i))
            i = j + 1
            continue
        if ch == '"':                                      # quoted identifier
            j = text.find('"', i + 1)
            if j < 0:
                raise SqlParseError(f"unterminated quoted identifier at {i}")
            toks.append(Token("ident", text[i + 1:j], i))
            i = j + 1
            continue
        if ch.isdigit() or (ch == "." and i + 1 < n and text[i + 1].isdigit()):
            j = i
            seen_dot = seen_exp = False
            while j < n:
                c = text[j]
                if c.isdigit():
                    j += 1
                elif c == "." and not seen_dot and not seen_exp:
                    # "1." followed by an ident char is `1 . ident` (unlikely
                    # in SQL); treat dot-digit only
                    seen_dot = True
                    j += 1
                elif c in "eE" and not seen_exp and j + 1 < n and (
                        text[j + 1].isdigit() or text[j + 1] in "+-"):
                    seen_exp = True
                    j += 2 if text[j + 1] in "+-" else 1
                else:
                    break
            s = text[i:j]
            toks.append(Token("num", float(s) if (seen_dot or seen_exp)
                              else int(s), i))
            i = j
            continue
        if ch.isalpha() or ch == "_":
            j = i
            while j < n and (text[j].isalnum() or text[j] == "_"):
                j += 1
            word = text[i:j]
            lw = word.lower()
            if lw in _KEYWORDS:
                toks.append(Token("kw", lw, i))
            else:
                toks.append(Token("ident", word, i))
            i = j
            continue
        if text[i:i + 2] in _TWO_CHAR:
            toks.append(Token("op", text[i:i + 2], i))
            i += 2
            continue
        if ch in _ONE_CHAR:
            toks.append(Token("op", ch, i))
            i += 1
            continue
        raise SqlParseError(f"unexpected character {ch!r} at {i}")
    toks.append(Token("end", None, n))
    return toks


# -- AST ----------------------------------------------------------------------

@dataclasses.dataclass
class Ident:
    parts: tuple     # ("col",) or ("tbl", "col")


@dataclasses.dataclass
class Lit:
    value: typing.Any


@dataclasses.dataclass
class Star:
    qualifier: str | None = None


@dataclasses.dataclass
class BinOp:
    op: str
    left: typing.Any
    right: typing.Any


@dataclasses.dataclass
class UnOp:
    op: str          # "-" | "not"
    operand: typing.Any


@dataclasses.dataclass
class FuncCall:
    name: str
    args: list
    distinct: bool = False
    over: "WindowSpecAst | None" = None


@dataclasses.dataclass
class CaseAst:
    operand: typing.Any          # CASE x WHEN v ... or None for searched CASE
    branches: list               # [(when_expr, then_expr)]
    else_: typing.Any


@dataclasses.dataclass
class IntervalAst:
    """INTERVAL '<n>' <unit> literal (TPC-H/DS date arithmetic)."""
    value: str
    unit: str


@dataclasses.dataclass
class CastAst:
    expr: typing.Any
    type_name: str
    type_args: tuple = ()
    #: True for DATE '...' / TIMESTAMP '...' typed literals — folded to
    #: constants at plan time; explicit cast() keeps Spark runtime semantics
    typed_literal: bool = False


@dataclasses.dataclass
class InAst:
    expr: typing.Any
    values: list                 # list of exprs, or a Select (subquery)
    negated: bool = False


@dataclasses.dataclass
class BetweenAst:
    expr: typing.Any
    lo: typing.Any
    hi: typing.Any
    negated: bool = False


@dataclasses.dataclass
class LikeAst:
    expr: typing.Any
    pattern: str
    negated: bool = False


@dataclasses.dataclass
class IsNullAst:
    expr: typing.Any
    negated: bool = False


@dataclasses.dataclass
class ExistsAst:
    query: "Select"
    negated: bool = False


@dataclasses.dataclass
class SubqueryExpr:
    query: "Select"


@dataclasses.dataclass
class WindowSpecAst:
    partition_by: list
    order_by: list               # [(expr, asc, nulls_first|None)]
    frame: tuple | None = None   # ("rows"|"range", lo, hi); None=unset


@dataclasses.dataclass
class TableRef:
    name: str
    alias: str | None = None


@dataclasses.dataclass
class SubqueryRef:
    query: "Select"
    alias: str = ""


@dataclasses.dataclass
class JoinRef:
    left: typing.Any
    right: typing.Any
    how: str                     # inner|left|right|full|cross|semi|anti
    on: typing.Any = None        # expr or None
    using: list | None = None    # [col names] for USING


@dataclasses.dataclass
class SelectItem:
    expr: typing.Any
    alias: str | None = None


@dataclasses.dataclass
class Select:
    items: list                  # [SelectItem] (Star allowed as expr)
    from_: list                  # [TableRef|SubqueryRef|JoinRef]; [] = no FROM
    where: typing.Any = None
    group_by: list = dataclasses.field(default_factory=list)
    rollup: bool = False         # legacy flag: GROUP BY ROLLUP(all group_by)
    having: typing.Any = None
    order_by: list = dataclasses.field(default_factory=list)
    limit: int | None = None
    distinct: bool = False
    ctes: list = dataclasses.field(default_factory=list)   # [(name, Select)]
    grouping_sets: list | None = None   # [[expr, ...], ...] (CUBE/ROLLUP/
    #                       GROUPING SETS normalize to explicit set lists)


@dataclasses.dataclass
class SetOp:
    """UNION/INTERSECT/EXCEPT tree over Select/SetOp arms. INTERSECT binds
    tighter than UNION/EXCEPT (standard precedence); trailing ORDER BY/LIMIT
    apply to the whole expression and ride the root node."""
    op: str                      # union|intersect|except
    all: bool
    left: typing.Any             # Select | SetOp
    right: typing.Any
    order_by: list = dataclasses.field(default_factory=list)
    limit: int | None = None
    ctes: list = dataclasses.field(default_factory=list)


# -- parser -------------------------------------------------------------------

class _Parser:
    def __init__(self, toks: list):
        self.toks = toks
        self.i = 0

    # token helpers
    def peek(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def at_kw(self, *kws) -> bool:
        t = self.peek()
        return t.kind == "kw" and t.value in kws

    def eat_kw(self, *kws) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw):
        if not self.eat_kw(kw):
            t = self.peek()
            raise SqlParseError(f"expected {kw.upper()} at pos {t.pos}, "
                                f"got {t.value!r}")

    def at_op(self, *ops) -> bool:
        t = self.peek()
        return t.kind == "op" and t.value in ops

    def eat_op(self, *ops) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op):
        if not self.eat_op(op):
            t = self.peek()
            raise SqlParseError(f"expected {op!r} at pos {t.pos}, "
                                f"got {t.value!r}")

    def ident(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            self.next()
            return t.value
        # soft keywords usable as identifiers/aliases in practice
        if t.kind == "kw" and t.value in ("first", "last", "row", "rows",
                                          "current", "range", "all"):
            self.next()
            return t.value
        raise SqlParseError(f"expected identifier at pos {t.pos}, "
                            f"got {t.value!r}")

    # -- query ---------------------------------------------------------------
    def parse_query(self):
        ctes = []
        if self.eat_kw("with"):
            while True:
                name = self.ident()
                self.expect_kw("as")
                self.expect_op("(")
                q = self.parse_query()
                self.expect_op(")")
                ctes.append((name, q))
                if not self.eat_op(","):
                    break
        q = self.parse_select()
        q.ctes = ctes
        return q

    def parse_select(self):
        """Select expression with standard set-op precedence: INTERSECT
        binds tighter than UNION/EXCEPT; trailing ORDER BY/LIMIT apply to
        the whole expression. Returns Select or SetOp."""
        q = self._setop_term()
        while True:
            if self.at_kw("union", "except"):
                op = self.next().value
            elif self.peek().kind == "ident" \
                    and self.peek().value.lower() == "minus":
                self.next()
                op = "except"     # Spark: MINUS is EXCEPT DISTINCT
            else:
                break
            all_ = self.eat_kw("all")
            if not all_:
                self.eat_kw("distinct")   # explicit DISTINCT is the default
            q = SetOp(op, all_, q, self._setop_term())
        if self.at_kw("order", "limit") and (q.order_by or
                                             q.limit is not None):
            # '(select ... order by a limit 5) order by b': the inner
            # clauses already bound inside the parens — wrap in a derived
            # table so the outer ORDER BY/LIMIT stack on top instead of
            # appending to (or overwriting) the inner ones
            q = Select([SelectItem(Star())], [SubqueryRef(q, "_sq")])
        self._order_limit_tail(q)
        return q

    def _setop_term(self):
        q = self._setop_primary()
        while self.eat_kw("intersect"):
            all_ = self.eat_kw("all")
            if not all_:
                self.eat_kw("distinct")
            q = SetOp("intersect", all_, q, self._setop_primary())
        return q

    def _setop_primary(self):
        if self.at_op("("):
            self.next()
            q = self.parse_query()     # parenthesized arm, may nest set ops
            self.expect_op(")")
            return q
        return self.parse_select_atom()

    def _order_limit_tail(self, sel):
        if self.eat_kw("order"):
            self.expect_kw("by")
            sel.order_by.append(self.parse_order_item())
            while self.eat_op(","):
                sel.order_by.append(self.parse_order_item())
        if self.eat_kw("limit"):
            t = self.next()
            if t.kind != "num" or not isinstance(t.value, int):
                raise SqlParseError(f"LIMIT needs an integer at pos {t.pos}")
            sel.limit = t.value

    def _group_expr_list(self) -> list:
        self.expect_op("(")
        out = []
        if not self.at_op(")"):       # GROUPING SETS allows the empty set ()
            out.append(self.parse_expr())
            while self.eat_op(","):
                out.append(self.parse_expr())
        self.expect_op(")")
        return out

    def parse_select_atom(self) -> Select:
        self.expect_kw("select")
        distinct = self.eat_kw("distinct")
        items = [self.parse_select_item()]
        while self.eat_op(","):
            items.append(self.parse_select_item())
        from_ = []
        if self.eat_kw("from"):
            from_ = [self.parse_table_ref()]
            while self.eat_op(","):
                from_.append(self.parse_table_ref())
        where = self.parse_expr() if self.eat_kw("where") else None
        group_by, rollup, gsets = [], False, None
        if self.eat_kw("group"):
            self.expect_kw("by")
            t = self.peek()
            soft = t.value.lower() if t.kind == "ident" else ""
            if self.eat_kw("rollup"):
                rollup = True
                group_by = self._group_expr_list()
            elif soft == "cube":
                self.next()
                group_by = self._group_expr_list()
                n = len(group_by)
                # all 2^n subsets, largest first (Spark emits gid ascending;
                # gid order is irrelevant to grouping correctness)
                gsets = [[i for i in range(n) if not (mask >> (n - 1 - i)) & 1]
                         for mask in range(1 << n)]
            elif soft == "grouping" and len(self.toks) > self.i + 1 \
                    and self.toks[self.i + 1].kind == "ident" \
                    and self.toks[self.i + 1].value.lower() == "sets":
                self.next()
                self.next()
                self.expect_op("(")
                sets_exprs = []
                while True:
                    if self.at_op("("):
                        sets_exprs.append(self._group_expr_list())
                    else:
                        sets_exprs.append([self.parse_expr()])
                    if not self.eat_op(","):
                        break
                self.expect_op(")")
                # normalize: group_by = deduped union of all set exprs (by
                # textual identity); each set lists indices into group_by
                keyed = []
                gsets = []
                for se in sets_exprs:
                    idxs = []
                    for e in se:
                        k = repr(e)
                        for j, (k2, _) in enumerate(keyed):
                            if k2 == k:
                                idxs.append(j)
                                break
                        else:
                            keyed.append((k, e))
                            idxs.append(len(keyed) - 1)
                    gsets.append(idxs)
                group_by = [e for _, e in keyed]
            else:
                group_by.append(self.parse_expr())
                while self.eat_op(","):
                    group_by.append(self.parse_expr())
        having = self.parse_expr() if self.eat_kw("having") else None
        return Select(items, from_, where, group_by, rollup, having,
                      distinct=distinct, grouping_sets=gsets)

    def parse_select_item(self) -> SelectItem:
        if self.at_op("*"):
            self.next()
            return SelectItem(Star())
        e = self.parse_expr()
        alias = None
        if self.eat_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident":
            alias = self.ident()
        return SelectItem(e, alias)

    def parse_order_item(self):
        e = self.parse_expr()
        asc = True
        if self.eat_kw("desc"):
            asc = False
        else:
            self.eat_kw("asc")
        nulls_first = None
        if self.eat_kw("nulls"):
            if self.eat_kw("first"):
                nulls_first = True
            else:
                self.expect_kw("last")
                nulls_first = False
        return (e, asc, nulls_first)

    # -- FROM ----------------------------------------------------------------
    def parse_table_ref(self):
        left = self.parse_table_primary()
        while True:
            how = None
            if self.eat_kw("cross"):
                self.expect_kw("join")
                how = "cross"
            elif self.at_kw("join", "inner", "left", "right", "full"):
                if self.eat_kw("inner"):
                    how = "inner"
                elif self.eat_kw("left"):
                    how = ("semi" if self.eat_kw("semi")
                           else "anti" if self.eat_kw("anti") else "left")
                    self.eat_kw("outer")
                elif self.eat_kw("right"):
                    how = "right"
                    self.eat_kw("outer")
                elif self.eat_kw("full"):
                    how = "full"
                    self.eat_kw("outer")
                else:
                    how = "inner"
                self.expect_kw("join")
            else:
                return left
            right = self.parse_table_primary()
            on = using = None
            if how != "cross":
                if self.eat_kw("using"):
                    self.expect_op("(")
                    using = [self.ident()]
                    while self.eat_op(","):
                        using.append(self.ident())
                    self.expect_op(")")
                else:
                    self.expect_kw("on")
                    on = self.parse_expr()
            left = JoinRef(left, right, how, on, using)

    def _query_ahead(self) -> bool:
        """At a '('-led position: does SELECT/WITH follow the open parens?
        A necessary (not sufficient) sign of a parenthesized query
        expression — '((select ...) except (select ...))'; the caller still
        backtracks if the full parse doesn't close cleanly, because
        '((select ...) a join ...)' starts identically but is a join tree."""
        j = self.i
        while j < len(self.toks) and self.toks[j].kind == "op" \
                and self.toks[j].value == "(":
            j += 1
        t = self.toks[j] if j < len(self.toks) else self.toks[-1]
        return t.kind == "kw" and t.value in ("select", "with")

    def parse_table_primary(self):
        if self.eat_op("("):
            if self.at_kw("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                self.eat_kw("as")
                alias = self.ident()
                return SubqueryRef(q, alias)
            if self._query_ahead():
                # '((select' is ambiguous: a set-op tree with parenthesized
                # arms, or a join tree whose first element is an aliased
                # subquery. Try the query-expression parse; backtrack to the
                # join tree unless it closes at our ')'.
                save = self.i
                q = None
                try:
                    q = self.parse_query()
                    if not self.at_op(")"):
                        q = None
                except SqlParseError:
                    q = None
                if q is not None:
                    self.next()          # the ')'
                    self.eat_kw("as")
                    alias = self.ident()
                    return SubqueryRef(q, alias)
                self.i = save
            # parenthesized join tree
            t = self.parse_table_ref()
            self.expect_op(")")
            return t
        name = self.ident()
        alias = None
        if self.eat_kw("as"):
            alias = self.ident()
        elif self.peek().kind == "ident" \
                and self.peek().value.lower() != "minus":
            # MINUS is the EXCEPT synonym, not an implicit alias
            alias = self.ident()
        return TableRef(name, alias)

    # -- expressions (precedence climbing) ------------------------------------
    def parse_expr(self):
        return self.parse_or()

    def parse_or(self):
        e = self.parse_and()
        while self.eat_kw("or"):
            e = BinOp("or", e, self.parse_and())
        return e

    def parse_and(self):
        e = self.parse_not()
        while self.eat_kw("and"):
            e = BinOp("and", e, self.parse_not())
        return e

    def parse_not(self):
        if self.eat_kw("not"):
            return UnOp("not", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self):
        if self.at_kw("exists"):
            self.next()
            self.expect_op("(")
            q = self.parse_query()
            self.expect_op(")")
            return ExistsAst(q)
        e = self.parse_additive()
        while True:
            if self.at_op("=", "<>", "!=", "<", "<=", ">", ">="):
                op = self.next().value
                e = BinOp("=" if op == "==" else op, e, self.parse_additive())
                continue
            negated = False
            save = self.i
            if self.eat_kw("not"):
                negated = True
            if self.eat_kw("between"):
                lo = self.parse_additive()
                self.expect_kw("and")
                hi = self.parse_additive()
                e = BetweenAst(e, lo, hi, negated)
                continue
            if self.eat_kw("in"):
                self.expect_op("(")
                if self.at_kw("select", "with"):
                    q = self.parse_query()
                    self.expect_op(")")
                    e = InAst(e, q, negated)
                else:
                    vals = [self.parse_expr()]
                    while self.eat_op(","):
                        vals.append(self.parse_expr())
                    self.expect_op(")")
                    e = InAst(e, vals, negated)
                continue
            if self.eat_kw("like"):
                t = self.next()
                if t.kind != "str":
                    raise SqlParseError(
                        f"LIKE needs a string literal at pos {t.pos}")
                e = LikeAst(e, t.value, negated)
                continue
            if negated:
                self.i = save   # bare NOT belongs to parse_not
                break
            if self.eat_kw("is"):
                neg = self.eat_kw("not")
                self.expect_kw("null")
                e = IsNullAst(e, neg)
                continue
            break
        return e

    def parse_additive(self):
        e = self.parse_multiplicative()
        while True:
            if self.at_op("+", "-"):
                op = self.next().value
                e = BinOp(op, e, self.parse_multiplicative())
            elif self.at_op("||"):
                self.next()
                e = BinOp("||", e, self.parse_multiplicative())
            else:
                return e

    def parse_multiplicative(self):
        e = self.parse_unary()
        while self.at_op("*", "/", "%"):
            op = self.next().value
            e = BinOp(op, e, self.parse_unary())
        return e

    def parse_unary(self):
        if self.eat_op("-"):
            return UnOp("-", self.parse_unary())
        if self.eat_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self):
        t = self.peek()
        if t.kind == "num":
            self.next()
            return Lit(t.value)
        if t.kind == "str":
            self.next()
            return Lit(t.value)
        if self.at_kw("null"):
            self.next()
            return Lit(None)
        if self.at_kw("case"):
            return self.parse_case()
        if self.at_kw("cast"):
            return self.parse_cast()
        if self.eat_op("("):
            if self.at_kw("select", "with"):
                q = self.parse_query()
                self.expect_op(")")
                return SubqueryExpr(q)
            e = self.parse_expr()
            self.expect_op(")")
            return e
        if t.kind in ("ident", "kw"):
            # typed literals: DATE '...', TIMESTAMP '...', INTERVAL 'n' unit
            low = str(t.value).lower()
            if low in ("date", "timestamp") and self.toks[self.i + 1].kind \
                    == "str":
                self.next()
                lit = self.next()
                return CastAst(Lit(lit.value), low, typed_literal=True)
            if low == "interval" and self.toks[self.i + 1].kind == "str":
                self.next()
                val = self.next().value
                unit = self.ident().lower().rstrip("s")
                return IntervalAst(val, unit)
            # function call or (qualified) identifier; soft keywords allowed
            name = self.ident()
            if self.at_op("("):
                return self.parse_func(name)
            parts = [name]
            while self.eat_op("."):
                if self.at_op("*"):
                    self.next()
                    return Star(qualifier=parts[0])
                parts.append(self.ident())
            return Ident(tuple(parts))
        raise SqlParseError(f"unexpected token {t.value!r} at pos {t.pos}")

    def parse_func(self, name: str):
        self.expect_op("(")
        distinct = self.eat_kw("distinct")
        args = []
        if self.at_op("*"):
            self.next()
            args.append(Star())
        elif not self.at_op(")"):
            args.append(self.parse_expr())
            while self.eat_op(","):
                args.append(self.parse_expr())
        self.expect_op(")")
        over = None
        if self.eat_kw("over"):
            over = self.parse_window_spec()
        return FuncCall(name.lower(), args, distinct, over)

    def parse_window_spec(self) -> WindowSpecAst:
        self.expect_op("(")
        parts, orders, frame = [], [], None
        if self.eat_kw("partition"):
            self.expect_kw("by")
            parts.append(self.parse_expr())
            while self.eat_op(","):
                parts.append(self.parse_expr())
        if self.eat_kw("order"):
            self.expect_kw("by")
            orders.append(self.parse_order_item())
            while self.eat_op(","):
                orders.append(self.parse_order_item())
        if self.at_kw("rows", "range"):
            ftype = self.next().value
            self.expect_kw("between")
            lo = self.parse_frame_bound()
            self.expect_kw("and")
            hi = self.parse_frame_bound()
            frame = (ftype, lo, hi)
        self.expect_op(")")
        return WindowSpecAst(parts, orders, frame)

    def parse_frame_bound(self):
        """None = unbounded; 0 = current row; +n following / -n preceding."""
        if self.eat_kw("unbounded"):
            if not self.eat_kw("preceding"):
                self.expect_kw("following")
            return None
        if self.eat_kw("current"):
            self.expect_kw("row")
            return 0
        t = self.next()
        if t.kind != "num":
            raise SqlParseError(f"bad frame bound at pos {t.pos}")
        if self.eat_kw("preceding"):
            return -int(t.value)
        self.expect_kw("following")
        return int(t.value)

    def parse_case(self):
        self.expect_kw("case")
        operand = None
        if not self.at_kw("when"):
            operand = self.parse_expr()
        branches = []
        while self.eat_kw("when"):
            w = self.parse_expr()
            self.expect_kw("then")
            v = self.parse_expr()
            branches.append((w, v))
        else_ = self.parse_expr() if self.eat_kw("else") else None
        self.expect_kw("end")
        return CaseAst(operand, branches, else_)

    def parse_cast(self):
        self.expect_kw("cast")
        self.expect_op("(")
        e = self.parse_expr()
        self.expect_kw("as")
        tname = self.ident().lower()
        targs = ()
        if self.eat_op("("):
            ts = [self.next().value]
            while self.eat_op(","):
                ts.append(self.next().value)
            self.expect_op(")")
            targs = tuple(ts)
        self.expect_op(")")
        return CastAst(e, tname, targs)


def parse_sql(text: str) -> Select:
    p = _Parser(tokenize(text))
    q = p.parse_query()
    p.eat_op(";")
    t = p.peek()
    if t.kind != "end":
        raise SqlParseError(f"trailing input at pos {t.pos}: {t.value!r}")
    return q
