"""Official TPC-H query text (q1/q3/q5 — the bench trio) run through
session.sql(); the DataFrame formulations live in benchmarks/tpch.py.

Text follows the TPC-H specification's qgen templates with the default
substitution parameters (the same constants the NumPy oracles encode).
"""

SQL_QUERIES = {
    "q1": """
select
    l_returnflag,
    l_linestatus,
    sum(l_quantity) as sum_qty,
    sum(l_extendedprice) as sum_base_price,
    sum(l_extendedprice * (1 - l_discount)) as sum_disc_price,
    sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) as sum_charge,
    avg(l_quantity) as avg_qty,
    avg(l_extendedprice) as avg_price,
    avg(l_discount) as avg_disc,
    count(*) as count_order
from
    lineitem
where
    l_shipdate <= date '1998-12-01' - interval '90' day
group by
    l_returnflag,
    l_linestatus
order by
    l_returnflag,
    l_linestatus
""",
    "q3": """
select
    l_orderkey,
    sum(l_extendedprice * (1 - l_discount)) as revenue,
    o_orderdate,
    o_shippriority
from
    customer,
    orders,
    lineitem
where
    c_mktsegment = 'BUILDING'
    and c_custkey = o_custkey
    and l_orderkey = o_orderkey
    and o_orderdate < date '1995-03-15'
    and l_shipdate > date '1995-03-15'
group by
    l_orderkey,
    o_orderdate,
    o_shippriority
order by
    revenue desc,
    o_orderdate
limit 10
""",
    "q5": """
select
    n_name,
    sum(l_extendedprice * (1 - l_discount)) as revenue
from
    customer,
    orders,
    lineitem,
    supplier,
    nation,
    region
where
    c_custkey = o_custkey
    and l_orderkey = o_orderkey
    and l_suppkey = s_suppkey
    and c_nationkey = s_nationkey
    and s_nationkey = n_nationkey
    and n_regionkey = r_regionkey
    and r_name = 'ASIA'
    and o_orderdate >= date '1994-01-01'
    and o_orderdate < date '1994-01-01' + interval '1' year
group by
    n_name
order by
    revenue desc
""",
}
