"""TpuSession + DataFrame — the user entry point.

Reference analogy: the reference is a plugin inside Spark — users keep the Spark
session/DataFrame API and the plugin rewrites plans underneath
(Plugin.scala:45-70, SURVEY.md #1). This framework is standalone, so it ships the
session facade itself: a DataFrame builds a CPU plan (plan/nodes.py); every
action runs it through TpuOverrides and executes the hybrid plan, exactly the
flow Spark would drive. `spark.rapids.tpu.*` conf keys keep their reference
meanings (config.py).

    from spark_rapids_tpu.session import TpuSession
    import spark_rapids_tpu.functions as F

    spark = TpuSession({"spark.rapids.tpu.sql.explain": "NONE"})
    df = spark.read_parquet("/data/sales")
    out = (df.filter(F.col("price") > 0)
             .group_by("region").agg(F.sum("price").alias("total"))
             .collect())
"""

from __future__ import annotations

import typing

import pyarrow as pa

from spark_rapids_tpu import types as T
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.expr import core as E
from spark_rapids_tpu.expr.aggregates import AggregateFunction
from spark_rapids_tpu.plan import nodes as NN
from spark_rapids_tpu.plan.overrides import TpuOverrides
from spark_rapids_tpu.plan.transitions import execute_hybrid


def _abort_execs(collector) -> None:
    """Query-death sweep: give every exec registered with the dead query's
    collector its `abort_query()` cleanup hook (shuffle exchanges free map
    outputs whose read-completion countdown can never finish — a cancelled
    or failed query's unvisited reduce splits have no reader to account
    them). Hooks must never mask the original error."""
    with collector._lock:
        nodes = list(collector._nodes.values())
    for node in nodes:
        hook = getattr(node, "abort_query", None)
        if hook is not None:
            try:
                hook()
            except Exception:   # noqa: BLE001 — cleanup must not mask
                pass


def _finish_query_memory(collector, conf, leak_check: bool = True):
    """Memory-plane epilogue of one action (runtime/memory.py): pop the
    query's allocation-site accounting into ``collector.memory`` (peak +
    per-site breakdown — bench.py and the query.end event embed it), run
    the end-of-query leak detector (event + resilience counter + reclaim)
    and emit a full heap snapshot into the event log. Idempotent per
    collector (success and error paths both call it; first wins) and a
    no-op when the device was never initialized (host-only plans).

    ``leak_check=False`` on the cancel/error paths: those drains are
    COOPERATIVE — worker threads may legitimately still be closing their
    buffers when the exception propagates, so a scan here would race them
    (PR-6's polling leak checks own those paths). Only a cleanly drained
    action can assert "still tagged == leaked". Returns the leak info
    dict, or None when clean/skipped."""
    from spark_rapids_tpu import config as CFG
    from spark_rapids_tpu.runtime import eventlog as EL
    from spark_rapids_tpu.runtime.memory import DeviceManager
    if getattr(collector, "_memory_done", False):
        return None
    collector._memory_done = True
    dm = DeviceManager._instance
    if dm is None:
        return None
    summary, leak = dm.catalog.finish_query(
        collector.query_id,
        leak_check=leak_check and conf.get(CFG.MEMORY_LEAK_CHECK))
    collector.memory = summary
    if EL.enabled():
        snap = dm.catalog.heap_snapshot()
        snap["sites"] = snap["sites"][:conf.get(CFG.MEMORY_PROFILE_TOPK)]
        EL.emit("memory.snapshot", query=collector.query_id, **snap)
    return leak


def _to_expr(c) -> E.Expression:
    if isinstance(c, E.Expression):
        return c
    if isinstance(c, str):
        return E.col(c)
    return E.lit(c)


class DataFrame:
    def __init__(self, plan: NN.PlanNode, session: "TpuSession"):
        self._plan = plan
        self.session = session
        self._last_collector = None   # QueryMetricsCollector of the last action

    # -- transformations (lazy: build plan nodes) ----------------------------
    def select(self, *cols) -> "DataFrame":
        return DataFrame(NN.ProjectNode([_to_expr(c) for c in cols],
                                        self._plan), self.session)

    def with_column(self, name: str, expr) -> "DataFrame":
        keep = [E.col(f.name) for f in self._plan.output
                if f.name != name]
        return DataFrame(NN.ProjectNode(
            keep + [E.Alias(_to_expr(expr), name)], self._plan), self.session)

    def filter(self, condition) -> "DataFrame":
        return DataFrame(NN.FilterNode(_to_expr(condition), self._plan),
                         self.session)

    where = filter

    def group_by(self, *keys) -> "GroupedData":
        return GroupedData([_to_expr(k) for k in keys], self)

    def rollup(self, *keys) -> "RollupData":
        """df.rollup(a, b).agg(...) — hierarchical subtotals via Expand with
        a grouping-id column, Spark's own lowering (the SQL front-end's
        GROUP BY ROLLUP takes the same path; reference GpuExpandExec role)."""
        return RollupData([_to_expr(k) for k in keys], self)

    def agg(self, *aggs) -> "DataFrame":
        return GroupedData([], self).agg(*aggs)

    def join(self, other: "DataFrame", on=None, how: str = "inner",
             condition=None) -> "DataFrame":
        jt = {"left_outer": "left", "right_outer": "right",
              "full_outer": "full", "outer": "full",
              "left_semi": "leftsemi", "semi": "leftsemi",
              "left_anti": "leftanti", "anti": "leftanti"}.get(how, how)
        if on is None:
            lk, rk = [], []
        else:
            names = [on] if isinstance(on, str) else list(on)
            lk = [E.col(n) for n in names]
            rk = [E.col(n) for n in names]
        jn = NN.JoinNode(self._plan, other._plan, lk, rk, jt, condition)
        if on is None or jt in ("leftsemi", "leftanti"):
            return DataFrame(jn, self.session)
        # USING join: one key column per name, Spark semantics — left key for
        # inner/left, right key for right, coalesce(left, right) for full;
        # the right-side duplicate is dropped
        from spark_rapids_tpu.expr.nullexprs import Coalesce
        lout, rout = self._plan.output, other._plan.output
        nl = len(lout.fields)
        proj = []
        for n in names:
            li, ri = lout.index_of(n), rout.index_of(n)
            lref = E.BoundReference(li, lout.fields[li].data_type)
            rref = E.BoundReference(nl + ri, rout.fields[ri].data_type)
            key = (rref if jt == "right"
                   else Coalesce(lref, rref) if jt == "full" else lref)
            proj.append(E.Alias(key, n))
        for i, f in enumerate(lout.fields):
            if f.name not in names:
                proj.append(E.Alias(E.BoundReference(i, f.data_type), f.name))
        for i, f in enumerate(rout.fields):
            if f.name not in names:
                proj.append(E.Alias(E.BoundReference(nl + i, f.data_type),
                                    f.name))
        return DataFrame(NN.ProjectNode(proj, jn), self.session)

    def union(self, other: "DataFrame") -> "DataFrame":
        return DataFrame(NN.UnionNode(self._plan, other._plan), self.session)

    def sort(self, *cols, ascending=True) -> "DataFrame":
        ascs = (ascending if isinstance(ascending, (list, tuple))
                else [ascending] * len(cols))
        # Spark default: nulls first when ascending, last when descending
        sort_exprs = [(_to_expr(c), bool(a), bool(a))
                      for c, a in zip(cols, ascs)]
        return DataFrame(NN.SortNode(sort_exprs, self._plan), self.session)

    order_by = sort

    def sort_within_partitions(self, *cols, ascending=True) -> "DataFrame":
        """Per-partition sort without a global exchange (Spark
        sortWithinPartitions)."""
        ascs = (ascending if isinstance(ascending, (list, tuple))
                else [ascending] * len(cols))
        sort_exprs = [(_to_expr(c), bool(a), bool(a))
                      for c, a in zip(cols, ascs)]
        return DataFrame(NN.SortNode(sort_exprs, self._plan,
                                     global_sort=False), self.session)

    def distinct(self) -> "DataFrame":
        """Spark distinct(): group by every column (device group-by kernel)."""
        keys = [E.col(f.name) for f in self._plan.output]
        return DataFrame(NN.AggregateNode(keys, [], self._plan), self.session)

    drop_duplicates = distinct

    def drop(self, *names) -> "DataFrame":
        drop_set = set(names)
        keep = [E.col(f.name) for f in self._plan.output
                if f.name not in drop_set]
        return DataFrame(NN.ProjectNode(keep, self._plan), self.session)

    def with_column_renamed(self, old: str, new: str) -> "DataFrame":
        proj = [E.Alias(E.col(f.name), new) if f.name == old
                else E.col(f.name) for f in self._plan.output]
        return DataFrame(NN.ProjectNode(proj, self._plan), self.session)

    def limit(self, n: int) -> "DataFrame":
        return DataFrame(NN.LimitNode(n, self._plan, global_limit=True),
                         self.session)

    def repartition(self, n: int, *keys) -> "DataFrame":
        if keys:
            return DataFrame(NN.ExchangeNode(
                self._plan, "hash", n, keys=[_to_expr(k) for k in keys]),
                self.session)
        return DataFrame(NN.ExchangeNode(self._plan, "roundrobin", n),
                         self.session)

    def window(self, window_exprs: list) -> "DataFrame":
        return DataFrame(NN.WindowNode(window_exprs, self._plan), self.session)

    def map_in_pandas(self, fn, schema) -> "DataFrame":
        """df.mapInPandas(fn, schema): fn(iterator[pandas.DataFrame]) ->
        iterator[pandas.DataFrame] over each partition (reference
        GpuMapInPandasExec)."""
        return DataFrame(NN.MapInPandasNode(fn, _to_schema(schema),
                                            self._plan), self.session)

    def explode(self, column: str, outer: bool = False,
                pos: bool = False) -> "DataFrame":
        """explode/posexplode an array column into one row per element
        (GpuGenerateExec analog; device path is one gather program)."""
        f = self._plan.output[column]
        if not isinstance(f.data_type, T.ArrayType):
            raise TypeError(
                f"explode: column '{column}' is {f.data_type}, not an array")
        return DataFrame(NN.GenerateNode(
            column, self._plan, outer=outer,
            element_type=f.data_type.element_type, pos=pos), self.session)

    def cache(self, serializer: str | None = None) -> "DataFrame":
        """Materialize-once cache (reference ParquetCachedBatchSerializer /
        the device spill-store cache; conf spark.rapids.tpu.sql.cache.serializer)."""
        from spark_rapids_tpu import config as CFG
        from spark_rapids_tpu.plan.cache import CacheNode
        ser = serializer or self.session.conf.get(CFG.CACHE_SERIALIZER)
        return DataFrame(CacheNode(self._plan, ser, self.session), self.session)

    def unpersist(self) -> "DataFrame":
        from spark_rapids_tpu.plan.cache import CacheNode
        if isinstance(self._plan, CacheNode):
            self._plan.unpersist()
            return DataFrame(self._plan.child, self.session)
        return self

    # -- metadata ------------------------------------------------------------
    @property
    def schema(self) -> T.StructType:
        return self._plan.output

    @property
    def columns(self) -> list:
        return [f.name for f in self._plan.output]

    def explain(self, all_nodes: bool = True, metrics: bool = False,
                stats: bool = False, fused: bool = False) -> str:
        from spark_rapids_tpu.plan.overrides import explain_plan
        if fused:
            # whole-stage view: the exec tree with Spark's `*(k)` stage
            # markers plus a per-stage summary of members and fused-in
            # operators; after an action the last collector's tree is reused
            # so per-node dispatch counts ride along
            from spark_rapids_tpu.plan.overrides import TpuOverrides
            from spark_rapids_tpu.plan.stages import explain_fused
            c = self._last_collector
            if c is not None and c.root is not None:
                return explain_fused(c.root, c)
            return explain_fused(
                TpuOverrides(self.session.conf).apply(self._plan))
        if metrics or stats:
            # SQL-UI analog: the executed plan tree annotated per node with
            # its metric snapshot — requires a completed action on this frame
            c = self._last_collector
            if c is None:
                return ("<no completed action on this DataFrame — run "
                        "collect()/count()/write first for "
                        f"explain({'stats' if stats else 'metrics'}=True)>\n"
                        + explain_plan(self._plan, self.session.conf,
                                       all_nodes))
            if stats:
                # stats plane: observed vs estimated rows per node plus the
                # per-node dispatch/transfer ledger and shuffle skew
                from spark_rapids_tpu.runtime import stats as STATS
                return STATS.annotated_stats_plan(c)
            return c.annotated_plan()
        return explain_plan(self._plan, self.session.conf, all_nodes)

    # -- actions -------------------------------------------------------------
    def _run_action(self, plan, run):
        """Execute one action under a fresh QueryMetricsCollector: plan
        conversion registers every exec node with it, `run(hybrid)` executes,
        and the finished collector (annotated plan, per-node metrics,
        query-scoped resilience deltas) lands on the DataFrame and the
        session for explain(metrics=True) / last_query_metrics(). Query
        lifecycle is mirrored to the structured event log when configured.

        Multi-tenant lifecycle (runtime/scheduler.py): the action is
        ADMITTED against the process-wide QueryScheduler before it executes
        (declared footprint from scan stats + plan shape), carries a
        CancelToken (+ optional scheduler.query.deadlineSeconds deadline)
        on its collector so session.cancel(query_id) reaches every worker
        thread, and releases its admission slot on every exit path. A shed
        submission raises QueryRejectedError (retryable, backoff hint); a
        cancellation/deadline classifies as query.cancelled/query.deadline
        in the event log, not query.error."""
        from spark_rapids_tpu import config as CFG
        from spark_rapids_tpu.runtime import eventlog as EL
        from spark_rapids_tpu.runtime import metrics as M
        from spark_rapids_tpu.runtime import scheduler as SCHED
        from spark_rapids_tpu.runtime import movement as MV
        from spark_rapids_tpu.runtime import tracing
        conf = self.session.conf
        MV.configure(
            sample_interval_bytes=conf.get(CFG.MOVEMENT_SAMPLE_INTERVAL),
            enabled=conf.get(CFG.MOVEMENT_ENABLED))
        collector = M.QueryMetricsCollector(description=type(plan).__name__)
        # cross-process trace id: a pending handoff (endpoint SUBMIT frame)
        # wins, then an explicit session override, else the query id — every
        # span this query emits, in every process it touches, carries it
        collector.trace_id = (tracing.take_pending_trace()
                              or conf.get(CFG.TRACE_ID_OVERRIDE)
                              or collector.query_id)
        deadline_s = conf.get(CFG.SCHEDULER_QUERY_DEADLINE)
        token = SCHED.CancelToken(
            collector.query_id,
            deadline_s=deadline_s if deadline_s > 0 else None)
        collector.cancel_token = token
        self._last_collector = collector
        self.session._last_collector = collector
        sched = SCHED.QueryScheduler.get()
        priority = conf.get(CFG.SCHEDULER_PRIORITY)

        def observe_latency():
            # end-to-end latency histogram per priority class (admission
            # wait included) — the serving tier's STATS/percentile source
            if collector.wall_s is not None:
                M.histogram(f"query.latency.priority{priority}").observe(
                    collector.wall_s)
        admitted = False
        with M.collector_context(collector), \
                tracing.span("query", query=collector.query_id):
            hybrid = TpuOverrides(conf).apply(plan)
            collector.set_root(hybrid)
            if EL.enabled():
                from spark_rapids_tpu.plan.stages import emit_stage_events
                emit_stage_events(hybrid, collector.query_id)
            try:
                queue_timeout = conf.get(CFG.SCHEDULER_QUEUE_TIMEOUT)
                # admission footprint: per-shape observed history when the
                # store has seen this plan's fingerprint, else the static
                # scan-bytes heuristic (stats plane; provenance kept on the
                # collector for plan.stats / bench / explain(stats=True))
                collector.footprint = SCHED.estimate_footprint_ex(plan, conf)
                sched.submit(
                    collector.query_id,
                    collector.footprint["estimate"],
                    priority=priority,
                    token=token,
                    timeout_s=queue_timeout if queue_timeout > 0 else None,
                    description=collector.description)
                admitted = True
                EL.emit("query.start", query=collector.query_id,
                        description=collector.description)
                out = run(hybrid)
                # end-of-query leak detection (memory observability plane):
                # the action has drained, so any device bytes still tagged
                # to this query are a leak — event + counter + reclaim,
                # escalated to a hard failure under memory.leak.strict
                leak = _finish_query_memory(collector, conf)
                if leak is not None and conf.get(CFG.MEMORY_LEAK_STRICT):
                    from spark_rapids_tpu.runtime.memory import \
                        MemoryLeakError
                    raise MemoryLeakError(
                        f"query {collector.query_id} leaked "
                        f"{leak['bytes']}B in {leak['buffers']} buffer(s): "
                        f"{leak['sites']}")
            except SCHED.QueryCancelledError as e:
                M.resilience_add(M.QUERIES_CANCELLED)
                if isinstance(e, SCHED.QueryDeadlineError):
                    M.counter_add("queries.deadline")
                collector.finish()
                observe_latency()
                _abort_execs(collector)
                _finish_query_memory(collector, conf, leak_check=False)
                EL.emit("query.deadline" if isinstance(
                            e, SCHED.QueryDeadlineError)
                        else "query.cancelled",
                        query=collector.query_id, reason=e.reason,
                        admitted=admitted, wall_s=collector.wall_s)
                raise
            except SCHED.QueryRejectedError:
                collector.finish()   # query.shed already emitted by submit()
                _finish_query_memory(collector, conf, leak_check=False)
                raise
            except BaseException as e:
                collector.finish()
                _abort_execs(collector)
                _finish_query_memory(collector, conf, leak_check=False)
                EL.emit("query.error", query=collector.query_id,
                        error=repr(e)[:200], wall_s=collector.wall_s)
                raise
            finally:
                if admitted:
                    sched.release(collector.query_id)
        collector.finish()
        observe_latency()
        # stats epilogue: build the per-node observed-stats payload, fold
        # this run into the plan-shape history store, publish the
        # estimate-error histogram (never raises)
        from spark_rapids_tpu.runtime import stats as STATS
        stats_payload = STATS.finish_query(collector, conf)
        compile_m = collector.compile_metrics()
        EL.emit("query.end", query=collector.query_id,
                description=collector.description,
                wall_s=collector.wall_s,
                compiles=compile_m["compiles"],
                dispatches=compile_m["dispatches"],
                resilience=collector.query_resilience(),
                memory=collector.memory,
                estimate_bytes=stats_payload.get("estimate_bytes"),
                history_hit=stats_payload.get("history_hit"),
                estimate_error=stats_payload.get("estimate_error"),
                nodes=collector.node_summaries(),
                # movement plane: this query's boundary-crossing bytes by
                # (edge, link) + amplification vs the result's Arrow size
                movement=MV.query_summary(
                    collector, result_bytes=getattr(out, "nbytes", None)))
        if EL.enabled():
            EL.emit("plan.stats", query=collector.query_id, **stats_payload)
        # flush the process ledger snapshot so short queries still leave a
        # movement.sample for the profiler even below the sample interval
        MV.maybe_emit(force=True)
        return out

    def collect(self) -> pa.Table:
        return self._run_action(self._plan, execute_hybrid)

    def collect_host(self) -> pa.Table:
        """CPU-only execution (the withCpuSparkSession analog for tests)."""
        return self._plan.collect_host()

    def collect_row_buffer(self):
        """Packed binary row collection (reference GpuColumnarToRowExec +
        CudfUnsafeRow, SURVEY.md #9). Fixed-width schemas return
        (rows int64[n, words], schema); schemas with strings return the
        UnsafeRow-style variable layout ((words, row_offsets), schema) —
        see columnar/rows.py pack_arrow_var."""
        from spark_rapids_tpu.columnar import rows as R
        schema = self._plan.output
        # host-only pack: collect() already materialized host arrow
        if R.is_fixed_width(schema):
            return R.pack_arrow(self.collect(), schema), schema
        if R.is_packable(schema):
            return R.pack_arrow_var(self.collect(), schema), schema
        raise NotImplementedError(
            f"nested types in {schema}: use collect()")

    def count(self) -> int:
        from spark_rapids_tpu.expr.aggregates import Count
        agg = NN.AggregateNode([], [E.Alias(Count(None), "count")], self._plan)
        out = self._run_action(agg, execute_hybrid)
        return out.column("count")[0].as_py()

    def to_pandas(self):
        return self.collect().to_pandas()

    def write_parquet(self, path: str, partition_by=None, mode="error"):
        return self._write(path, "parquet", partition_by, mode)

    def write_orc(self, path: str, partition_by=None, mode="error"):
        return self._write(path, "orc", partition_by, mode)

    def write_csv(self, path: str, mode="error"):
        return self._write(path, "csv", None, mode)

    def _write(self, path, fmt, partition_by, mode):
        from spark_rapids_tpu.io.writer import write_columnar
        return self._run_action(
            self._plan,
            lambda hybrid: write_columnar(hybrid, path, fmt,
                                          partition_by=partition_by,
                                          mode=mode, conf=self.session.conf))


class GroupedData:
    def __init__(self, keys: list, df: DataFrame):
        self.keys = keys
        self.df = df

    def _key_names(self) -> list:
        names = []
        for k in self.keys:
            if isinstance(k, (E.AttributeReference, E.Alias)):
                names.append(k.name)
            else:
                raise ValueError(
                    "pandas grouped operations need plain column keys, got "
                    f"{k!r}")
        return names

    def agg(self, *aggs) -> DataFrame:
        from spark_rapids_tpu.udf.pandas_exec import PandasAggUDF
        named = []
        pandas_udfs = []
        for i, a in enumerate(aggs):
            e = _to_expr(a)
            inner = e.child if isinstance(e, E.Alias) else e
            if isinstance(inner, PandasAggUDF):
                name = e.name if isinstance(e, E.Alias) else f"udf{i}"
                pandas_udfs.append((inner.fn, list(inner.input_cols), name,
                                    inner.return_type))
                continue
            assert isinstance(inner, AggregateFunction), \
                f"agg() requires aggregate expressions, got {e!r}"
            named.append(e)
        if pandas_udfs:
            if named:
                raise ValueError(
                    "cannot mix pandas aggregate UDFs with builtin "
                    "aggregates in one agg() (Spark AggregateInPandas "
                    "restriction)")
            return DataFrame(NN.AggregateInPandasNode(
                self._key_names(), pandas_udfs, self.df._plan),
                self.df.session)
        return DataFrame(NN.AggregateNode(self.keys, named, self.df._plan),
                         self.df.session)

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """groupBy(keys).applyInPandas(fn, schema): fn(pandas.DataFrame) ->
        pandas.DataFrame per group (keys included in the group frame)."""
        return DataFrame(NN.GroupedMapInPandasNode(
            self._key_names(), fn, _to_schema(schema), self.df._plan),
            self.df.session)

    def cogroup(self, other: "GroupedData") -> "CoGroupedData":
        """cogroup(df1.groupBy(k), df2.groupBy(k)) — Spark's cogroup."""
        return CoGroupedData(self, other)

    def count(self) -> DataFrame:
        from spark_rapids_tpu.expr.aggregates import Count
        return self.agg(E.Alias(Count(None), "count"))

    def pivot(self, pivot_col, values: list) -> "PivotedGroupedData":
        """df.group_by(k).pivot(p, values).agg(f(v)) — Spark's pivot.
        Lowered by If-guard expansion (one guarded aggregate per pivot
        value), which keeps every aggregate on the DEVICE kernels; the
        PivotFirst expression (expr/aggregates.py) is the reference-shaped
        host form for plans that carry it directly."""
        return PivotedGroupedData(self.keys, self.df, _to_expr(pivot_col),
                                  list(values))


class RollupData:
    """GROUP BY ROLLUP over plain columns (Expand + grouping-id, like the
    SQL lowering sql/lower.py _expand_rollup)."""

    def __init__(self, keys: list, df: DataFrame):
        for k in keys:
            if not isinstance(k, (E.AttributeReference, E.BoundReference)):
                raise ValueError("rollup supports plain columns only")
        self.keys = [E.bind_references(k, df._plan.output) for k in keys]
        self.df = df

    def agg(self, *aggs) -> DataFrame:
        named = []
        for a in aggs:
            e = _to_expr(a)
            inner = e.child if isinstance(e, E.Alias) else e
            if not isinstance(inner, AggregateFunction):
                raise ValueError(
                    f"rollup().agg() requires aggregate expressions, got {e!r}"
                    " (pandas aggregate UDFs are not supported under rollup)")
            named.append(e)
        expand, group_refs, gid_ref = NN.build_rollup_expand(
            self.df._plan, self.keys)
        group_named = [E.Alias(r, r.name) for r in group_refs]
        agg_node = NN.AggregateNode(group_named + [E.Alias(gid_ref, "_gid")],
                                    named, expand)
        # drop the grouping-id column from the visible output — POSITIONALLY
        # (an agg alias may collide with a key name)
        gid_pos = len(group_refs)
        keep = [E.Alias(E.BoundReference(i, f.data_type, f.nullable, f.name),
                        f.name)
                for i, f in enumerate(agg_node.output) if i != gid_pos]
        return DataFrame(NN.ProjectNode(keep, agg_node), self.df.session)


def _to_schema(schema) -> T.StructType:
    if isinstance(schema, T.StructType):
        return schema
    return T.StructType([T.StructField(n, dt, True) for n, dt in schema])


class CoGroupedData:
    """Pair of grouped frames for cogrouped applyInPandas (Spark
    PandasCogroupedOps)."""

    def __init__(self, left: GroupedData, right: GroupedData):
        if len(left.keys) != len(right.keys):
            raise ValueError("cogroup requires equal-arity grouping keys")
        self.left = left
        self.right = right

    def apply_in_pandas(self, fn, schema) -> DataFrame:
        """fn(left_group_df, right_group_df) -> pandas.DataFrame per key
        present on either side (the absent side gets an empty frame)."""
        return DataFrame(NN.CoGroupedMapInPandasNode(
            self.left._key_names(), self.right._key_names(), fn,
            _to_schema(schema), self.left.df._plan, self.right.df._plan),
            self.left.df.session)


class PivotedGroupedData:
    def __init__(self, keys: list, df: DataFrame, pivot_expr, values: list):
        self.keys = keys
        self.df = df
        self.pivot_expr = pivot_expr
        self.values = values

    def agg(self, *aggs) -> DataFrame:
        from spark_rapids_tpu.expr.aggregates import Count, First, Last
        from spark_rapids_tpu.expr.conditional import If
        named = []
        for a in aggs:
            e = _to_expr(a)
            inner = e.child if isinstance(e, E.Alias) else e
            assert isinstance(inner, AggregateFunction), \
                f"agg() requires aggregate expressions, got {e!r}"
            base_name = e.name if isinstance(e, E.Alias) else None
            for pv in self.values:
                child = inner.children[0] if inner.children else None
                if child is None:
                    # count(*) counts only the pivot value's rows (Spark
                    # lowers pivot by grouping on the pivot column)
                    guarded = Count(If(E.Literal(pv) == self.pivot_expr,
                                       E.Literal(1), E.Literal(None, T.INT)))
                else:
                    guard = If(E.Literal(pv) == self.pivot_expr, child,
                               E.Literal(None, child.dtype))
                    if isinstance(inner, (First, Last)):
                        # non-matching rows become nulls; they must not win
                        guarded = type(inner)(guard, ignore_nulls=True)
                    else:
                        guarded = inner.with_children([guard])
                col_name = (f"{pv}" if len(aggs) == 1 and base_name is None
                            else f"{pv}_{base_name or type(inner).__name__.lower()}")
                named.append(E.Alias(guarded, col_name))
        return DataFrame(NN.AggregateNode(self.keys, named, self.df._plan),
                         self.df.session)


class UDFRegistration:
    """Named-UDF registry (reference RapidsUDF + GpuUserDefinedFunction.scala:73
    + hiveUDFs.scala: a user function that SHIPS its own device implementation
    is routed to it by the planner; otherwise the usual ladder applies —
    bytecode-compile to device expressions, else the python worker pool).

        spark.udf.register("my_fn", fn=slow_row_fn, return_type=T.DOUBLE,
                           device_fn=lambda v: v * 2.0)
        spark.sql("select my_fn(x) from t")        # runs the jax impl, fused
    """

    def __init__(self, session: "TpuSession"):
        self._session = session
        self._fns: dict = {}

    def register(self, name: str, fn=None, return_type: T.DataType | None = None,
                 device_fn=None, null_aware: bool = False):
        if fn is None and device_fn is None:
            raise ValueError("register() needs fn and/or device_fn")
        self._fns[name] = (fn, return_type, device_fn, null_aware)

        def call(*cols):
            return self.build(name, [_to_expr(c) for c in cols])
        return call

    def __contains__(self, name: str) -> bool:
        return name in self._fns

    def build(self, name: str, args: list) -> E.Expression:
        """Expression for a registered UDF call: device impl > compiled
        bytecode > python worker (the reference's replacement-else-fallback
        contract)."""
        fn, return_type, device_fn, null_aware = self._fns[name]
        if device_fn is not None:
            from spark_rapids_tpu.udf.device_udf import JaxUDF
            if return_type is None:
                raise ValueError(f"UDF {name}: device_fn needs return_type")
            return JaxUDF(device_fn, args, return_type, null_aware, name=name)
        from spark_rapids_tpu.udf.compiler import compile_udf
        compiled = compile_udf(fn, args)
        if compiled is not None:
            return compiled
        from spark_rapids_tpu.udf.python_runtime import PythonUDF
        if return_type is None:
            raise ValueError(
                f"UDF {name} could not be compiled to device expressions; "
                "the python-worker fallback needs an explicit return_type")
        return PythonUDF(fn, args, return_type)


class TpuSession:
    """The SparkSession stand-in; owns the conf and the read API
    (reference RapidsDriverPlugin/SQLExecPlugin wiring, Plugin.scala:45-70)."""

    def __init__(self, conf: dict | RapidsConf | None = None):
        self.conf = (conf if isinstance(conf, RapidsConf)
                     else RapidsConf(conf or {}))
        self._views: dict = {}   # temp-view catalog for session.sql()
        # streaming sources (streaming/source.py): resolved to a FRESH
        # DataFrame on every sql() call — a file-scan plan freezes its file
        # list at construction, and a stream's whole point is that the
        # list grows
        self._stream_sources: dict = {}
        # bumped on every view (re)registration; the endpoint result cache
        # keys on it so results computed against a replaced catalog can
        # never be served again
        self._catalog_epoch = 0
        self.udf = UDFRegistration(self)
        from spark_rapids_tpu import config as CFG
        from spark_rapids_tpu.ops import pallas_kernels as PK
        # the Pallas dispatch is process-global (like the reference's
        # executor-plugin init): only an EXPLICIT conf setting touches it, so
        # constructing a default session never overrides another session's
        # explicit choice
        if CFG.PALLAS_ENABLED.key in self.conf.settings:
            PK.set_mode(None if self.conf.get(CFG.PALLAS_ENABLED) else False)
        # plugin bootstrap: config fixup/version check once per process;
        # eager device acquisition when conf'd (reference Plugin.scala flow)
        from spark_rapids_tpu import plugin as PL
        PL.bootstrap(self.conf)
        # tracing (NVTX analog): profiler annotations around hot regions,
        # optional whole-session XProf capture (reference nvtx_profiling.md)
        from spark_rapids_tpu.runtime import tracing
        # process-global like the Pallas switch: only an EXPLICIT setting
        # touches it, so a default session never clobbers another's choice
        if CFG.TRACE_ENABLED.key in self.conf.settings:
            tracing.set_enabled(self.conf.get(CFG.TRACE_ENABLED))
        pdir = self.conf.get(CFG.PROFILE_DIR)
        if pdir:
            tracing.start_profile(pdir)
        # distributed span plane (trace.dir): per-process JSONL span files
        # merged by tools/profiler.py trace — process-global like the
        # switches above; only an EXPLICIT setting opens (or closes, when
        # set empty) the sink. MiniCluster executors open their own from
        # the same conf key (cluster/minicluster._executor_main)
        if CFG.TRACE_DIR.key in self.conf.settings:
            tdir = self.conf.get(CFG.TRACE_DIR)
            if tdir:
                tracing.configure_spans(tdir, process="driver")
            else:
                tracing.shutdown_spans()
        # deterministic fault injection (chaos testing, runtime/faults.py):
        # process-global like the switches above — only an EXPLICIT setting
        # arms or re-seeds the injector
        if CFG.TEST_FAULTS.key in self.conf.settings:
            from spark_rapids_tpu.runtime import faults
            faults.configure(self.conf.get(CFG.TEST_FAULTS),
                             self.conf.get(CFG.TEST_FAULTS_SEED))
        # structured event log (Spark event-log analog, runtime/eventlog.py):
        # process-global like the switches above — only an EXPLICIT setting
        # opens (or closes, when set empty) the sink
        if CFG.EVENT_LOG_DIR.key in self.conf.settings:
            from spark_rapids_tpu.runtime import eventlog
            elog_dir = self.conf.get(CFG.EVENT_LOG_DIR)
            if elog_dir:
                eventlog.configure(
                    elog_dir, self.conf.get(CFG.EVENT_LOG_HEALTH_INTERVAL),
                    max_bytes=self.conf.get(CFG.EVENT_LOG_MAX_BYTES),
                    keep=self.conf.get(CFG.EVENT_LOG_KEEP_FILES))
            else:
                eventlog.shutdown()
        # black-box flight recorder (runtime/blackbox.py): the in-memory
        # ring runs at its default bound with no configuration; the dump
        # directory follows eventLog.dir, and an EXPLICIT maxEvents setting
        # resizes (0 disables) the process-global ring
        if any(k.key in self.conf.settings for k in (
                CFG.FLIGHT_RECORDER_MAX_EVENTS, CFG.EVENT_LOG_DIR)):
            from spark_rapids_tpu.runtime import blackbox
            blackbox.configure(
                max_events=self.conf.get(CFG.FLIGHT_RECORDER_MAX_EVENTS)
                if CFG.FLIGHT_RECORDER_MAX_EVENTS.key in self.conf.settings
                else None,
                directory=self.conf.get(CFG.EVENT_LOG_DIR) or None)
        # memory observability plane (runtime/memory.py): watermark sample
        # granularity + site top-K are process-global like the switches
        # above — only an EXPLICIT setting pushes them onto the (lazily
        # constructed) buffer catalog
        if any(k.key in self.conf.settings for k in (
                CFG.MEMORY_WATERMARK_INTERVAL, CFG.MEMORY_PROFILE_TOPK)):
            from spark_rapids_tpu.runtime import memory as MEM
            MEM.set_profile_options(
                self.conf.get(CFG.MEMORY_WATERMARK_INTERVAL),
                self.conf.get(CFG.MEMORY_PROFILE_TOPK))
        # plan-shape history store (stats plane, runtime/history.py):
        # process-global like the switches above — only an EXPLICIT setting
        # opens (or closes, when set empty) the store
        if any(k.key in self.conf.settings for k in (
                CFG.STATS_HISTORY_DIR, CFG.STATS_HISTORY_MAX_SHAPES)):
            from spark_rapids_tpu.runtime import history as HIST
            hdir = self.conf.get(CFG.STATS_HISTORY_DIR)
            if hdir:
                HIST.configure(hdir,
                               self.conf.get(CFG.STATS_HISTORY_MAX_SHAPES))
            else:
                HIST.shutdown()
        # persistent compiled-stage cache (runtime/stage_cache.py):
        # process-global like the switches above — only an EXPLICIT setting
        # opens (or closes, when disabled or the dir is empty) the store
        if any(k.key in self.conf.settings for k in (
                CFG.STAGE_CACHE_ENABLED, CFG.STAGE_CACHE_DIR,
                CFG.STAGE_CACHE_MAX_BYTES)):
            from spark_rapids_tpu.runtime import stage_cache
            sc_dir = self.conf.get(CFG.STAGE_CACHE_DIR)
            if self.conf.stage_cache_enabled and sc_dir:
                stage_cache.configure(
                    sc_dir, self.conf.get(CFG.STAGE_CACHE_MAX_BYTES))
            else:
                stage_cache.shutdown()
        # multi-tenant query scheduler (runtime/scheduler.py): STRUCTURAL
        # knobs (concurrency, queue depth, aging) are process-global like
        # the switches above — only an EXPLICIT setting reconfigures the
        # shared instance; per-query values (priority, deadline, queue
        # timeout, footprint estimate) are read from this session's conf at
        # every submission
        if any(k.key in self.conf.settings for k in (
                CFG.SCHEDULER_MAX_CONCURRENT, CFG.SCHEDULER_QUEUE_MAX_DEPTH,
                CFG.SCHEDULER_PRIORITY_AGING)):
            from spark_rapids_tpu.runtime.scheduler import QueryScheduler
            QueryScheduler.get().reconfigure(self.conf)
        self._last_collector = None

    def last_query_metrics(self):
        """QueryMetricsCollector of the most recently completed action on
        this session (None before any action): per-node metric snapshots,
        the annotated plan, wall time and query-scoped resilience deltas."""
        return self._last_collector

    def heap_snapshot(self) -> dict:
        """Live allocation-site heap snapshot of the process-wide buffer
        catalog (runtime/memory.py): per-site tier occupancy, plan nodes,
        owning queries, process-lifetime peak/cumulative traffic, plus the
        device high-water mark — the programmatic face of
        ``tools/profiler.py memory`` and the STATS memory gauges."""
        from spark_rapids_tpu.runtime.memory import DeviceManager
        return DeviceManager.get().catalog.heap_snapshot()

    # -- multi-tenant lifecycle (runtime/scheduler.py) -----------------------
    def cancel(self, query_id: str, reason: str = "cancelled") -> bool:
        """Cooperatively cancel a running OR queued query by id (ids come
        from active_queries(), or last_query_metrics().query_id on the
        submitting thread). The query observes the token at its next
        checkpoint — pipeline queue waits, per-batch operator pulls, fetch
        backoffs, the OOM retry ladder — and drains without leaking
        threads, device buffers, or semaphore permits. Returns False for
        an unknown/already-finished id."""
        from spark_rapids_tpu.runtime.scheduler import QueryScheduler
        return QueryScheduler.get().cancel(query_id, reason)

    def active_queries(self) -> list:
        """Every queued or running query on the process-wide scheduler:
        [{query, state, estimate_bytes, priority, waited_s|running_s,
        description}] — the serving endpoint's `ps`."""
        from spark_rapids_tpu.runtime.scheduler import QueryScheduler
        return QueryScheduler.get().active_queries()

    def serve(self, host: str | None = None, port: int | None = None):
        """Start the Arrow-over-TCP query endpoint on this session
        (runtime/endpoint.py): remote clients submit SQL over this
        session's temp views and stream Arrow-IPC result batches back,
        routed through the multi-tenant scheduler (admission, priority,
        deadline, shedding). Listening starts immediately; call
        ``.shutdown()`` (or use as a context manager) for a graceful
        drain. host/port default to endpoint.host / endpoint.port."""
        from spark_rapids_tpu.runtime.endpoint import QueryEndpoint
        return QueryEndpoint(self, host=host, port=port)

    # -- data sources --------------------------------------------------------
    def read_parquet(self, path, pushed_filter=None,
                     files_per_partition: int = 1) -> DataFrame:
        from spark_rapids_tpu import config as CFG
        from spark_rapids_tpu.io.filescan import FileScanNode, rewrite_scan_path
        # node-level default so host-fallback scans honor the conf too; the
        # device exec re-applies its conf value per execution
        opts = {"rebase_mode": self.conf.get(CFG.PARQUET_REBASE_MODE)}
        path = rewrite_scan_path(path, self.conf)
        return DataFrame(FileScanNode(path, "parquet",
                                      pushed_filter=pushed_filter,
                                      files_per_partition=files_per_partition,
                                      options=opts),
                         self)

    def read_orc(self, path, **kw) -> DataFrame:
        from spark_rapids_tpu.io.filescan import FileScanNode, rewrite_scan_path
        return DataFrame(FileScanNode(rewrite_scan_path(path, self.conf),
                                      "orc", **kw), self)

    def read_csv(self, path, schema: T.StructType | None = None,
                 header: bool = True, delimiter: str = ",") -> DataFrame:
        from spark_rapids_tpu.io.filescan import FileScanNode, rewrite_scan_path
        return DataFrame(FileScanNode(
            rewrite_scan_path(path, self.conf), "csv", schema=schema,
            options={"header": header, "delimiter": delimiter,
                     "schema": schema}), self)

    def create_dataframe_from_rows(self, rows, schema,
                                   num_partitions: int = 1,
                                   offsets=None) -> DataFrame:
        """Packed binary row buffer → DataFrame without per-row conversion
        (reference GpuRowToColumnarExec's codegen'd fast path). Pass
        `offsets` for the variable-width layout from pack_arrow_var; a
        (words, offsets) tuple in `rows` also works."""
        from spark_rapids_tpu.columnar import rows as R
        import numpy as np
        if offsets is None and isinstance(rows, tuple) and len(rows) == 2:
            rows, offsets = rows
        if offsets is not None:
            tbl = R.unpack_rows_arrow_var(np.asarray(rows),
                                          np.asarray(offsets), schema)
            return self.create_dataframe(tbl, num_partitions)
        rows = np.asarray(rows)
        n = rows.shape[0]
        per = -(-n // max(1, num_partitions)) if n else 1
        parts = []
        for i in range(max(1, num_partitions)):
            chunk = rows[i * per:(i + 1) * per]
            if chunk.shape[0] == 0 and i > 0:
                break
            parts.append(R.unpack_rows_arrow(chunk, schema))
        return DataFrame(NN.ScanNode(parts, schema), self)

    def create_dataframe(self, data, num_partitions: int = 1) -> DataFrame:
        """From a pyarrow table / pandas DataFrame / dict of columns."""
        if not isinstance(data, pa.Table):
            data = pa.table(data) if isinstance(data, dict) else \
                pa.Table.from_pandas(data)
        per = -(-data.num_rows // max(1, num_partitions))
        parts = ([data.slice(i * per, per) for i in range(num_partitions)]
                 if num_partitions > 1 else [data])
        return DataFrame(NN.ScanNode(parts), self)

    def range(self, start: int, end: int | None = None, step: int = 1,
              num_slices: int = 1) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(NN.RangeNode(start, end, step, num_slices), self)

    # -- SQL -----------------------------------------------------------------
    def create_or_replace_temp_view(self, name: str, df: DataFrame) -> None:
        """Register `df` under `name` for session.sql() (SparkSession
        createOrReplaceTempView analog). Bumps the catalog epoch, which
        invalidates every endpoint result-cache entry."""
        self._views[name] = df
        self._catalog_epoch += 1

    createOrReplaceTempView = create_or_replace_temp_view

    @property
    def catalog_epoch(self) -> int:
        """Monotonic catalog-staleness counter (the result-cache key): the
        local view-registration counter, plus — when this session belongs
        to a fleet — the shared fleet-wide counter, so a streaming APPEND
        processed by a PEER replica still invalidates this replica's
        cached results (the peer bumps the shared counter; this property
        folds it in on the next cache-key computation)."""
        epoch = self._catalog_epoch
        from spark_rapids_tpu import config as CFG
        fleet_dir = self.conf.get(CFG.FLEET_DIR)
        if fleet_dir:
            from spark_rapids_tpu.runtime import fleet as FL
            epoch += FL.shared_catalog_epoch(fleet_dir)
        return epoch

    # -- streaming ------------------------------------------------------------
    def create_stream_source(self, name: str, directory: str, schema=None):
        """Register a micro-batch streaming source (streaming/source.py):
        a durable batch log fed by directory tail and/or endpoint APPEND
        frames, queryable under `name` in session.sql() — re-resolved to a
        fresh scan on every sql() call, so queries always see every batch
        durable at plan time. `schema` (pyarrow) makes the empty source
        queryable and gates appends; omitted, it is adopted from the first
        batch."""
        from spark_rapids_tpu.streaming.source import StreamingSource
        src = StreamingSource(name, directory, schema=schema)
        self._stream_sources[name] = src
        self._catalog_epoch += 1
        return src

    def streaming_append(self, source: str, batch_id: str, table=None, *,
                         ipc_body: bytes | None = None,
                         crc: int | None = None) -> dict:
        """Durably append one batch to a registered stream source —
        idempotent by (source, batch_id). A FRESH append bumps the catalog
        epoch (and the fleet-shared epoch when fleet.dir is set), so no
        result cache in the fleet can serve a pre-append frame; a
        duplicate bumps nothing. Returns the APPEND ack fields."""
        src = self._stream_sources.get(source)
        if src is None:
            raise ValueError(f"unknown stream source {source!r} "
                             f"(create_stream_source first)")
        if ipc_body is not None:
            table, fresh = src.append_ipc(batch_id, ipc_body,
                                          int(crc or 0))
        else:
            fresh = src.append_table(batch_id, table)
        if fresh:
            self._catalog_epoch += 1
            from spark_rapids_tpu import config as CFG
            fleet_dir = self.conf.get(CFG.FLEET_DIR)
            if fleet_dir:
                from spark_rapids_tpu.runtime import fleet as FL
                FL.bump_shared_catalog_epoch(fleet_dir)
        return {"source": source, "batch": batch_id,
                "duplicate": not fresh, "rows": table.num_rows,
                "epoch": self.catalog_epoch}

    def _refresh_stream_views(self) -> None:
        """Re-resolve every stream source to a fresh DataFrame before SQL
        lowering (no epoch bump — freshness is data arriving, staleness is
        keyed by the APPEND-time bumps). A source that is still empty with
        no declared schema is skipped; querying it stays an unknown-view
        error until its first batch lands."""
        for name, src in self._stream_sources.items():
            try:
                self._views[name] = src.dataframe(self)
            except ValueError:
                self._views.pop(name, None)

    def sql(self, text: str) -> DataFrame:
        """Run a SQL query over the registered temp views (the reference's
        entire surface is SQL text — qa_nightly_sql.py; see sql/)."""
        from spark_rapids_tpu.sql import lower_sql
        if self._stream_sources:
            self._refresh_stream_views()
        return DataFrame(lower_sql(text, self._views, self), self)
