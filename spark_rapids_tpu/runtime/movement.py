"""Data-movement observability plane: the unified per-link byte ledger.

Reference analogy: the shuffle-plugin's UCX transport counts request/receive
bytes per peer (UCXShuffleTransport / RapidsShuffleServerOps metrics) and
Spark's MapOutputStatistics name shuffle volume — but neither names EVERY
byte a query moves. Theseus (PAPERS.md) argues distributed query throughput
is won by scheduling data movement across the memory/interconnect hierarchy;
before movement can be spent it must be metered. This module is the
movement analog of the PR-9 heap profiler: a lock-cheap process-wide
registry metering every byte that crosses a boundary, keyed by
``(edge, link, site)``:

  edge   what kind of crossing — ``shuffle.send``/``shuffle.recv`` (transport
         data plane), ``shuffle.retry`` (bytes fetched by a FAILED attempt,
         reclassified so retries never double-count the recv ledger),
         ``spill.write``/``spill.read`` (disk spill tier),
         ``h2d``/``d2h`` (Arrow boundary, unified with the PR-12 node meters),
         ``ici.collective`` (real mesh collective operand bytes),
         ``endpoint.egress`` (Arrow IPC result frames to serving clients)
  link   the physical lane — ``tcp`` (cross-host), ``loopback`` (same-host
         TCP), ``local`` (in-process short-circuit, zero network), ``disk``,
         ``pcie`` (host<->device), ``ici``, ``client`` (endpoint socket)
  site   the capture point ("transport.fetch", "spill.file", ...)

Each cell holds ``[bytes, payload_bytes, transfers, seconds]``. ``bytes``
are PHYSICAL link bytes (wire frames, disk writes, device transfer sizes);
``payload_bytes`` are block-store units (``device_memory_size()`` of the
decoded batch — the unit ``ShuffleBlockStore.partition_sizes`` speaks), so
the profiler's byte matrix can be cross-checked against map-output
statistics even though the wire trims padding that the store accounts.
Edges that have no store-unit distinction default payload == bytes.

Dual accounting follows the PR-6 scoped pattern: every record lands in the
process-global ledger AND the ambient ``QueryMetricsCollector``'s per-query
mirror (aggregated by ``(edge, link)`` — the ``query.end`` movement
section). Read-outs: a cumulative ``movement.sample`` event (threshold-based
like the memory watermark timeline) + a Chrome counter track per edge,
``srt_movement_bytes{edge=,link=}`` STATS gauges with transfer size/latency
histograms, and ``tools/profiler.py movement``.
"""

from __future__ import annotations

import threading

from spark_rapids_tpu.runtime import metrics as M

# edge -> (source, destination) of the movement matrix rendered by
# tools/profiler.py movement; shuffle.retry bytes were physically received
# and then discarded by the fetch ladder, so they flow net -> discard
EDGES = {
    "shuffle.send": ("host", "net"),
    "shuffle.recv": ("net", "host"),
    "shuffle.retry": ("net", "discard"),
    "spill.write": ("host", "disk"),
    "spill.read": ("disk", "host"),
    "h2d": ("host", "device"),
    "d2h": ("device", "host"),
    "ici.collective": ("device", "device"),
    "endpoint.egress": ("host", "client"),
}

# edges whose ledger rows stay exactly zero on the single-process,
# no-shuffle path (the ci.sh movement-gate invariant): everything except
# the host<->device and mesh edges a purely local query legitimately uses
NETWORK_EDGES = ("shuffle.send", "shuffle.recv", "shuffle.retry",
                 "spill.write", "spill.read", "endpoint.egress")

# transfer-size histogram bounds (bytes): 1KiB .. 1GiB, x8 per step
TRANSFER_BYTES_BOUNDS = (
    1 << 10, 1 << 13, 1 << 16, 1 << 19, 1 << 22,
    1 << 25, 1 << 28, 1 << 30)

_lock = threading.Lock()
# (edge, link, site) -> [bytes, payload_bytes, transfers, seconds]
_cells: dict = {}
_enabled = True
_sample_interval = 32 << 20
_since_sample = 0
_dirty = False       # anything recorded since the last emitted sample

# thread-local stack of fetch-attempt tokens: every shuffle.recv record on
# the thread is also noted into each open token, so an aborted attempt can
# move exactly its own bytes from shuffle.recv to shuffle.retry
_tls = threading.local()

_LOOPBACK_HOSTS = frozenset({"localhost", "::1", "0.0.0.0"})


def configure(sample_interval_bytes: "int | None" = None,
              enabled: "bool | None" = None) -> None:
    """Apply the movement.* conf knobs (session action prologue and the
    MiniCluster executor bootstrap both call this — the ledger itself is
    process-global, so the last configure wins, like the event log)."""
    global _sample_interval, _enabled
    with _lock:
        if sample_interval_bytes is not None and sample_interval_bytes > 0:
            _sample_interval = int(sample_interval_bytes)
        if enabled is not None:
            _enabled = bool(enabled)


def enabled() -> bool:
    return _enabled


def classify_peer(address) -> str:
    """Link class of a peer socket address: ``loopback`` for same-host TCP
    (loopback IPs or this process's own registered block-server host),
    ``tcp`` for a genuinely remote peer, ``local`` for no address at all
    (in-process reads never touch a socket). Keeping loopback out of the
    ``tcp`` row is what makes the cross-host ledger honest — a MiniCluster
    on one box moves plenty of TCP bytes but zero cross-host bytes."""
    if address is None:
        return "local"
    host = address[0] if isinstance(address, (tuple, list)) else str(address)
    host = str(host)
    if host in _LOOPBACK_HOSTS or host.startswith("127."):
        return "loopback"
    from spark_rapids_tpu.cluster import remote as R
    la = R.local_address()
    if la is not None and str(la[0]) == host:
        return "loopback"
    return "tcp"


def record(edge: str, nbytes: int, *, link: str = "local", site: str = "",
           payload_bytes: "int | None" = None, transfers: int = 1,
           seconds: "float | None" = None) -> None:
    """Meter one boundary crossing: `nbytes` physical link bytes (pass 0
    for a payload-only follow-up record), `payload_bytes` block-store-unit
    bytes (None = same as nbytes), `seconds` the wire/disk transfer time
    (feeds the size/latency histograms when present)."""
    if not _enabled:
        return
    n = int(nbytes)
    p = n if payload_bytes is None else int(payload_bytes)
    emit = False
    global _since_sample, _dirty
    with _lock:
        cell = _cells.get((edge, link, site))
        if cell is None:
            cell = _cells[(edge, link, site)] = [0, 0, 0, 0.0]
        cell[0] += n
        cell[1] += p
        cell[2] += transfers
        if seconds:
            cell[3] += seconds
        _dirty = True
        _since_sample += n
        if _since_sample >= _sample_interval:
            _since_sample = 0
            emit = True
    if edge == "shuffle.recv":
        for tok in getattr(_tls, "attempts", ()) or ():
            c = tok.setdefault((link, site), [0, 0, 0, 0.0])
            c[0] += n
            c[1] += p
            c[2] += transfers
            if seconds:
                c[3] += seconds
    col = M.current_collector()
    if col is not None:
        mv = getattr(col, "_movement", None)
        if mv is not None:
            with col._compile_lock:
                c = mv.setdefault((edge, link), [0, 0, 0])
                c[0] += n
                c[1] += p
                c[2] += transfers
    if seconds is not None:
        M.histogram("movement.transfer.bytes",
                    TRANSFER_BYTES_BOUNDS).observe(n)
        M.histogram("movement.transfer.latency").observe(seconds)
    if emit:
        maybe_emit(force=True)


def record_h2d(nbytes: int, site: str = "batch.from_arrow") -> None:
    """Host->device upload at the Arrow boundary: one call feeds BOTH the
    PR-12 per-node stats ledger (h2dBytes, attributed to the innermost
    operator frame) and the movement ledger's pcie edge — the meters can
    never drift apart."""
    M.stats_add("h2dBytes", nbytes)
    record("h2d", nbytes, link="pcie", site=site)


def record_d2h(nbytes: int, site: str = "batch.to_arrow") -> None:
    """Device->host download at the Arrow boundary (see record_h2d)."""
    M.stats_add("d2hBytes", nbytes)
    record("d2h", nbytes, link="pcie", site=site)


# ---------------------------------------------------------------------------
# fetch-attempt reclassification (the shuffle.retry edge)
# ---------------------------------------------------------------------------

def begin_attempt() -> dict:
    """Open a fetch-attempt scope on this thread: shuffle.recv bytes
    recorded while it is open are noted into the returned token. Tokens
    nest (the union fetch wraps per-peer retry ladders)."""
    stack = getattr(_tls, "attempts", None)
    if stack is None:
        stack = _tls.attempts = []
    tok: dict = {}
    stack.append(tok)
    return tok


def _pop_token(tok: dict) -> None:
    """Remove `tok` from this thread's attempt stack by IDENTITY. Nested
    tokens (the union token plus the first per-peer token) start as equal
    empty dicts and receive identical updates in record(), so value
    comparison (``tok in stack`` / ``list.remove``) can pop a sibling
    instead — leaking a zombie token that absorbs every future
    shuffle.recv note and corrupting the no-double-count invariant."""
    stack = getattr(_tls, "attempts", None)
    if not stack:
        return
    for i, t in enumerate(stack):
        if t is tok:
            del stack[i]
            return


def commit_attempt(tok: dict) -> None:
    """The attempt's batches were yielded downstream — its bytes stay on
    the shuffle.recv edge."""
    _pop_token(tok)


def abort_attempt(tok: dict) -> None:
    """The attempt failed after (possibly) receiving bytes: move exactly
    the bytes it noted from shuffle.recv to shuffle.retry, in the global
    ledger AND the ambient collector mirror, and deduct them from any
    still-open outer token so a task-level abort cannot move them twice.
    This is the no-double-count invariant the chaos ledger test asserts:
    total recv payload stays equal to the block store's partition sizes no
    matter how many attempts it took."""
    _pop_token(tok)
    if not tok:
        return
    col = M.current_collector()
    with _lock:
        for (link, site), (n, p, t, s) in tok.items():
            src = _cells.get(("shuffle.recv", link, site))
            if src is not None:
                src[0] -= n
                src[1] -= p
                src[2] -= t
                src[3] -= s
            dst = _cells.get(("shuffle.retry", link, site))
            if dst is None:
                dst = _cells[("shuffle.retry", link, site)] = [0, 0, 0, 0.0]
            dst[0] += n
            dst[1] += p
            dst[2] += t
            dst[3] += s
    if col is not None:
        mv = getattr(col, "_movement", None)
        if mv is not None:
            with col._compile_lock:
                for (link, _site), (n, p, t, _s) in tok.items():
                    src = mv.get(("shuffle.recv", link))
                    if src is not None:
                        src[0] -= n
                        src[1] -= p
                        src[2] -= t
                    dst = mv.setdefault(("shuffle.retry", link), [0, 0, 0])
                    dst[0] += n
                    dst[1] += p
                    dst[2] += t
    for outer in getattr(_tls, "attempts", ()) or ():
        for key, (n, p, t, s) in tok.items():
            c = outer.get(key)
            if c is not None:
                c[0] -= n
                c[1] -= p
                c[2] -= t
                c[3] -= s


# ---------------------------------------------------------------------------
# snapshots + read-outs
# ---------------------------------------------------------------------------

def snapshot() -> dict:
    """{(edge, link, site): {bytes, payload_bytes, transfers, seconds}}."""
    with _lock:
        return {k: {"bytes": v[0], "payload_bytes": v[1],
                    "transfers": v[2], "seconds": round(v[3], 6)}
                for k, v in _cells.items()}


def edge_link_totals() -> dict:
    """{(edge, link): {bytes, payload_bytes, transfers}} aggregated over
    capture sites — the STATS gauge family and movement.sample payload."""
    out: dict = {}
    with _lock:
        for (edge, link, _site), v in _cells.items():
            c = out.setdefault((edge, link), {"bytes": 0, "payload_bytes": 0,
                                              "transfers": 0})
            c["bytes"] += v[0]
            c["payload_bytes"] += v[1]
            c["transfers"] += v[2]
    return out


def total_bytes() -> int:
    with _lock:
        return sum(v[0] for v in _cells.values())


def reset() -> None:
    """Test hook (wired into metrics.reset_observability)."""
    global _since_sample, _dirty
    with _lock:
        _cells.clear()
        _since_sample = 0
        _dirty = False


def maybe_emit(force: bool = False) -> None:
    """Emit the cumulative movement.sample event (+ the Chrome bandwidth
    counter track, one series per edge). Threshold crossings in record()
    force it; the session's query epilogue and the executor task loop force
    a flush so short queries and freshly finished tasks are covered. The
    payload is a CUMULATIVE snapshot — the profiler takes each process's
    LAST sample and sums across processes, so emission frequency only
    affects resolution, never totals."""
    global _dirty
    from spark_rapids_tpu.runtime import eventlog as EL
    from spark_rapids_tpu.runtime import tracing as TR
    if not (EL.enabled() or TR.spans_enabled()):
        return
    with _lock:
        if not _dirty and not force:
            return
        if not _cells:
            return
        _dirty = False
    totals = edge_link_totals()
    flows = [{"edge": e, "link": lk, **c}
             for (e, lk), c in sorted(totals.items())]
    total = sum(c["bytes"] for c in totals.values())
    if EL.enabled():
        EL.emit("movement.sample", total_bytes=total, flows=flows)
    if TR.spans_enabled():
        by_edge: dict = {}
        for (e, _lk), c in totals.items():
            by_edge[e] = by_edge.get(e, 0) + c["bytes"]
        TR.counter("movement", by_edge)


def query_summary(collector, result_bytes: "int | None" = None) -> "dict | None":
    """The query.end movement section from the collector's per-query
    mirror: per-edge/per-link bytes plus the movement-amplification factor
    (total bytes moved per result byte) when the action's result size is
    known (pa.Table.nbytes); None when the query moved nothing."""
    mv = getattr(collector, "_movement", None)
    if mv is None:
        return None
    with collector._compile_lock:
        items = {k: list(v) for k, v in mv.items()}
    if not items:
        return None
    edges: dict = {}
    total = 0
    for (edge, link), (n, p, t) in sorted(items.items()):
        edges.setdefault(edge, {})[link] = {
            "bytes": n, "payload_bytes": p, "transfers": t}
        total += n
    out = {"total_bytes": total, "edges": edges}
    if result_bytes:
        out["result_bytes"] = int(result_bytes)
        out["amplification"] = round(total / int(result_bytes), 3)
    return out
