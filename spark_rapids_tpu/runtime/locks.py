"""Cross-process advisory file locks for the shared serving stores.

The stage cache, the plan-history store, and the fleet directory are plain
directories shared by N replica processes; ``advisory_lock`` is the one
primitive they serialize critical sections with — ``fcntl.flock`` on a
sidecar lock file, held for the duration of the ``with`` block.

Advisory semantics are exactly what the stores need: readers that tolerate
concurrent mutation (stage-cache loads racing a prune) never take the lock,
while read-merge-replace writers (history record, fleet sweep) do, so two
replicas can't silently drop each other's updates. On platforms without
``fcntl`` (no POSIX), the lock degrades to the process-local ``threading``
lock the stores already hold — single-process behavior is unchanged.
"""

from __future__ import annotations

import contextlib
import os

try:
    import fcntl
except ImportError:  # pragma: no cover - non-POSIX
    fcntl = None


@contextlib.contextmanager
def advisory_lock(path: str):
    """Hold an exclusive cross-process advisory lock on ``path``.

    The lock file is created if missing and never deleted by the holder
    (unlinking a locked file would let a late-coming process lock a fresh
    inode and run the critical section concurrently).
    """
    if fcntl is None:  # pragma: no cover - non-POSIX
        yield
        return
    fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o644)
    try:
        fcntl.flock(fd, fcntl.LOCK_EX)
        yield
    finally:
        # closing the fd releases the flock
        os.close(fd)
