"""Block checksums for shuffle frames and spill files.

Reference: Spark stamps shuffle blocks with checksums (SPARK-35275) so a
corrupted fetch is diagnosed as a fetch failure (recompute) instead of a
deserialization crash deep inside an operator; the reference plugin
inherits that via the Spark shuffle layer. Here the engine owns both data
planes, so this module is the shared primitive: the TCP transport stamps
each serialized block's checksum into the metadata response and the client
verifies after reassembly (shuffle/transport.py), and the buffer catalog
stamps disk-tier spill payloads and verifies on unspill
(runtime/memory.py). Both mismatches route through the existing
fetch-failure → recompute ladder.

CRC32C (Castagnoli) via the `crc32c` package when present; otherwise
zlib's CRC32 — the container bakes no crc32c wheel and the constraint is
deterministic corruption DETECTION within one process/cluster generation,
which either polynomial provides (every participant resolves the same
implementation, and the algorithm name travels with the checksum so a
mixed deployment would fail loudly rather than silently pass).
"""

from __future__ import annotations

import zlib

try:
    import crc32c as _crc32c_mod
    CHECKSUM_ALGO = "crc32c"

    def block_checksum(data, value: int = 0) -> int:
        """CRC of `data` (bytes-like), optionally chained from `value`."""
        return _crc32c_mod.crc32c(data, value)
except ImportError:                      # no crc32c wheel in the image
    CHECKSUM_ALGO = "crc32"

    def block_checksum(data, value: int = 0) -> int:
        """CRC of `data` (bytes-like), optionally chained from `value`."""
        return zlib.crc32(data, value) & 0xFFFFFFFF
