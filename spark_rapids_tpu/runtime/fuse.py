"""Whole-stage fusion: one jitted XLA program per (operator, shape bucket).

Reference contrast: the reference issues one cudf CUDA kernel per expression op
(GpuExpression columnarEval chains, SURVEY.md §1 L0/L4); kernel launches are
cheap on-node so that is fine there. On TPU every eager jax op is a separate
XLA program dispatch — through the axon tunnel each dispatch is a network
round-trip, and even locally the per-op Python/trace overhead dominates small
batches (round-2 profile: ~5.4k primitive binds per TPC-H q1 batch, ~99% of
hot-run wall time). The TPU-native answer is whole-stage compilation, the same
move Spark itself makes for codegen: trace the operator's ENTIRE per-batch
computation (expression eval -> sort/segment/compact kernels) once per input
shape bucket, then replay one compiled XLA program per batch.

Kernels are cached at module level keyed by a SEMANTIC key (operator class +
expression-tree structure + static config), because the planner rebuilds exec
instances on every collect() — a per-instance `jax.jit` would recompile every
run. `jax.jit`'s own cache then handles shape/dtype/dictionary variation
under each kernel.

Also the home of the compile/dispatch accounting the tuning story needs:
`stage_metrics()` reports traces (XLA compiles) vs dispatches (program
replays); a healthy query does O(stages) traces and O(batches) dispatches.
"""

from __future__ import annotations

import threading
import types as _types

import jax

from spark_rapids_tpu.runtime import metrics as _M

_lock = threading.Lock()
_kernels: dict = {}
_MAX_KERNELS = 2048
# XLA:CPU's LLVM JIT owns a bounded code-memory region; ~3000 live
# executables exhaust it and later compiles fail with "LLVM compilation
# error: Cannot allocate memory" or SEGFAULT inside backend_compile_and_load
# (measured on this box, docs/perf_notes.md r4). A kernel holds one
# executable PER SHAPE SIGNATURE, so the backstop must budget executables,
# not kernel objects.
_MAX_EXECUTABLES = 900
_inserts = 0
# the get_kernel eviction only ran on INSERTS, so a long-lived multi-shape
# stage kernel could accumulate executables between inserts and silently
# blow the LLVM code-memory backstop; traces are the event that actually
# grows the executable population, so sweeps are also trace-driven
_SWEEP_EVERY_TRACES = 32
_last_sweep_traces = 0

# counters are module-global (queries share kernels); reset via reset_metrics()
_counts = {"traces": 0, "dispatches": 0}

# SRT_FUSE_PROFILE=1: block on every kernel dispatch and record wall time per
# kernel name (kernel_profile()) — the steering tool for finding slow stages
import os as _os
_PROFILE = _os.environ.get("SRT_FUSE_PROFILE", "") == "1"
_profile: dict = {}


def kernel_profile() -> dict:
    """{kernel_name: (total_seconds, calls)} — only populated under
    SRT_FUSE_PROFILE=1."""
    with _lock:
        return dict(_profile)


def stage_metrics() -> dict:
    """{'traces': n_xla_compiles, 'dispatches': n_program_replays}."""
    with _lock:
        return dict(_counts)


def reset_metrics():
    global _last_sweep_traces
    with _lock:
        _counts["traces"] = 0
        _counts["dispatches"] = 0
        _last_sweep_traces = 0


class _Unset:
    __slots__ = ()

    def __repr__(self):
        return "<unset>"


_UNSET = _Unset()


class BatchKernel:
    """A jitted per-batch function with trace/dispatch accounting.

    The wrapped python body runs once per (shape, dtype, aux) signature —
    counting its executions counts XLA compiles; counting __call__ counts
    dispatches.

    When the persistent stage cache is configured (runtime/stage_cache.py)
    and the semantic key has a stable cross-process digest, compiled
    executables are looked up / saved through `jax.jit(...).lower().compile()`
    + serialize_executable instead of the in-process jit cache: a fresh
    process replays the stored XLA executable with ZERO Python traces. Any
    undigestable key or argument signature quietly falls back to the plain
    jit path — the cache is an accelerator, never a correctness gate."""

    __slots__ = ("name", "_jit", "_key", "_digest", "_compiled")

    # bound per-kernel: a fused multi-shape stage kernel may legitimately
    # hold many signatures, but FIFO-dropping the oldest keeps any one
    # kernel from monopolizing the executable budget
    _MAX_SIGS = 64

    def __init__(self, fn, name: str, key=None):
        self.name = name
        self._key = key
        self._digest = _UNSET       # lazily: hex str, or None (undigestable)
        self._compiled: dict = {}   # sig digest -> AOT-loaded executable

        def traced(*args):
            with _lock:
                _counts["traces"] += 1
            # per-query retrace attribution: the tracing thread runs inside
            # the query's collector scope, so the compile lands on the query
            # that paid for it (metrics.compile_add, the resilience pattern)
            _M.compile_add("compiles")
            return fn(*args)

        self._jit = jax.jit(traced)

    def cache_size(self) -> int:
        """Live compiled-executable count: one per traced shape signature in
        the jit cache PLUS one per AOT executable held for the persistent
        stage cache (a fused stage kernel can hold many — the budget must see
        them all, not just the jit side)."""
        n = len(self._compiled)
        try:
            return max(int(self._jit._cache_size()) + n, 1)
        except Exception:
            return max(n, 1)

    def _dispatch(self, args):
        from spark_rapids_tpu.runtime import stage_cache as _SC
        store = _SC.get()
        if store is not None:
            if self._digest is _UNSET:
                self._digest = (key_digest(self._key)
                                if self._key is not None else None)
            if self._digest is not None:
                sig = _sig_digest(args)
                if sig is not None:
                    return self._dispatch_persistent(store, sig, args)
        return self._jit(*args)

    def _dispatch_persistent(self, store, sig, args):
        exe = self._compiled.get(sig)
        if exe is None:
            # platform + jax version namespace the entry: a shared cache dir
            # must never hand a CPU executable to a TPU session (or a new
            # jax an old serialization format)
            entry = f"{_backend_tag()}-{self._digest}-{sig}"
            data = store.load(entry)
            if data is not None:
                try:
                    exe = _deserialize_executable(data)
                except Exception as e:  # noqa: BLE001 — corrupt entry:
                    # degrade to retrace-with-warning, never failure
                    store.invalidate(entry, repr(e))
                    exe = None
            if exe is None:
                # cold: AOT-compile through the counting wrapper (the trace
                # lands in the ledger exactly like a jit-path trace)
                exe = self._jit.lower(*args).compile()
                try:
                    data = _serialize_executable(exe)
                    # round-trip validation before the entry lands on disk:
                    # an executable rehydrated from jax's own persistent
                    # compile cache serializes WITHOUT its object code
                    # ("Symbols not found" on the next load) — better a
                    # memory-only kernel now than a corrupt entry later
                    _deserialize_executable(data)
                    store.save(entry, data)
                except Exception as e:  # noqa: BLE001 — unserializable
                    store.note_unserializable(entry, repr(e))
            with _lock:
                while len(self._compiled) >= self._MAX_SIGS:
                    self._compiled.pop(next(iter(self._compiled)))
                self._compiled[sig] = exe
        return exe(*args)

    def __call__(self, *args):
        global _last_sweep_traces
        do_sweep = False
        with _lock:
            _counts["dispatches"] += 1
            # trace-driven executable sweep (multi-shape stage kernels grow
            # the executable population WITHOUT get_kernel inserts)
            if _counts["traces"] - _last_sweep_traces >= _SWEEP_EVERY_TRACES:
                _last_sweep_traces = _counts["traces"]
                do_sweep = True
        if do_sweep:
            _sweep_executables()
        _M.compile_add("dispatches")
        if _PROFILE:
            import time
            t0 = time.perf_counter()
            out = jax.block_until_ready(self._dispatch(args))
            dt = time.perf_counter() - t0
            with _lock:
                tot, n = _profile.get(self.name, (0.0, 0))
                _profile[self.name] = (tot + dt, n + 1)
            return out
        return self._dispatch(args)


_backend_tag_memo = None

# BUMP whenever any kernel BODY changes behavior under an unchanged semantic
# key: persistent entries are keyed by (semantic key, arg signature), not by
# the traced HLO, so a stale store replaying an old program would be a silent
# wrong answer — the version tag turns it into a cache miss instead.
KERNEL_CACHE_VERSION = 1


def _backend_tag() -> str:
    global _backend_tag_memo
    if _backend_tag_memo is None:
        import spark_rapids_tpu as _pkg
        _backend_tag_memo = (f"{jax.devices()[0].platform}-{jax.__version__}-"
                             f"{_pkg.__version__}-k{KERNEL_CACHE_VERSION}")
    return _backend_tag_memo


def _serialize_executable(exe) -> bytes:
    import pickle
    from jax.experimental import serialize_executable as _se
    payload, in_tree, out_tree = _se.serialize(exe)
    return pickle.dumps((payload, in_tree, out_tree))


def _deserialize_executable(data: bytes):
    import pickle
    from jax.experimental import serialize_executable as _se
    payload, in_tree, out_tree = pickle.loads(data)
    return _se.deserialize_and_load(payload, in_tree, out_tree)


def _sweep_locked() -> list:
    """Evict oldest kernels (insertion order) until the live-executable total
    is comfortably under budget. Caller holds _lock; returns the evicted
    kernels so their destructors can run outside it."""
    evicted = []
    total = sum(kk.cache_size() for kk in _kernels.values()
                if isinstance(kk, BatchKernel))   # skip _EAGER
    if total > _MAX_EXECUTABLES or len(_kernels) >= _MAX_KERNELS:
        order = list(_kernels)
        while order and (total > int(_MAX_EXECUTABLES * 0.75)
                         or len(_kernels) >= _MAX_KERNELS):
            victim = _kernels.pop(order.pop(0))
            if isinstance(victim, BatchKernel):
                total -= victim.cache_size()
                evicted.append(victim)
    return evicted


def _sweep_executables():
    with _lock:
        evicted = _sweep_locked()
    del evicted   # destructors run outside the lock


def get_kernel(key, name: str, build) -> BatchKernel:
    """Fetch-or-create the kernel for semantic key `key`. `build()` returns the
    pure per-batch function (it may close over expression trees — the key must
    capture everything that affects the traced program)."""
    global _inserts
    with _lock:
        k = _kernels.get(key)
    if k is not None:
        return k
    k = BatchKernel(build(), name, key=key)
    evicted = []
    with _lock:
        _inserts += 1
        if len(_kernels) >= _MAX_KERNELS or _inserts % 32 == 0:
            evicted = _sweep_locked()
        out = _kernels.setdefault(key, k)
    del evicted   # destructors run outside the lock
    return out


def clear_kernels():
    with _lock:
        _kernels.clear()


_EAGER = "eager"  # sentinel cache entry: this key cannot be traced

_TRACE_ERRORS = tuple(
    e for e in (getattr(jax.errors, n, None) for n in
                ("ConcretizationTypeError", "TracerArrayConversionError",
                 "TracerBoolConversionError", "TracerIntegerConversionError"))
    if e is not None)


def call_fused(key, name: str, build, args, eager):
    """Run the kernel for `key` over `args`, falling back PERMANENTLY to
    `eager()` if the computation turns out to be untraceable (host sync /
    data-dependent Python control flow inside eval). The fallback latches per
    key so the failed trace is paid once. Keys containing UNKEYABLE fields
    (objects with no stable content key) are never cached — fusing them would
    key compiled programs on object addresses."""
    if not key_is_cacheable(key):
        return eager()
    with _lock:
        k = _kernels.get(key)
    if k is _EAGER:
        return eager()
    try:
        if k is None:
            k = get_kernel(key, name, build)
        return k(*args)
    except _TRACE_ERRORS:
        with _lock:
            _kernels[key] = _EAGER
        return eager()


# -- semantic keys over expression trees -------------------------------------

def expr_key(e):
    """Stable hashable key for an expression tree: class identity + every
    constructor-visible field, recursively. Two expressions with equal keys
    must trace to the same program over equal-signature inputs."""
    from spark_rapids_tpu.expr.core import Expression
    if isinstance(e, Expression):
        parts = [type(e).__module__, type(e).__qualname__]
        d = vars(e) if hasattr(e, "__dict__") else {
            s: getattr(e, s, None) for s in getattr(e, "__slots__", ())}
        for k in sorted(d):
            parts.append((k, _value_key(d[k])))
        return tuple(parts)
    return _value_key(e)


class _Unkeyable:
    """Marker embedded in a semantic key when some field has no stable content
    key (e.g. an arbitrary object whose repr would embed id()). call_fused
    treats any key containing it as uncacheable and runs eagerly — a fresh
    repr()-based key would either collide across distinct objects after
    address reuse or never be shared, so neither caching behavior is safe."""

    __slots__ = ()

    def __repr__(self):
        return "<unkeyable>"


UNKEYABLE = _Unkeyable()


_fn_key_active = threading.local()


def _fn_key(v):
    """Stable content key for a plain Python function: bytecode + consts +
    names + defaults + closure contents + the referenced module globals. Two
    content-equal UDFs share one compiled kernel; anything address-dependent
    (instance state, unkeyable globals) degrades to UNKEYABLE."""
    if hasattr(v, "__func__"):          # bound method: instance state matters
        return ("bound", _value_key(v.__self__), _fn_key(v.__func__))
    # mutually-recursive globals (def a(): b(); def b(): a()) would recurse
    # forever; on re-entry the participant's own bytecode already contributes
    # at the outer level, so a name marker suffices
    active = getattr(_fn_key_active, "ids", None)
    if active is None:
        active = _fn_key_active.ids = set()
    if id(v) in active:
        return ("recursive-fn", getattr(v, "__qualname__", "?"))
    active.add(id(v))
    try:
        return _fn_key_inner(v)
    finally:
        active.discard(id(v))


def _fn_key_inner(v):
    code = v.__code__
    consts = tuple(_value_key(c) for c in code.co_consts)
    defaults = tuple(_value_key(d) for d in (v.__defaults__ or ()))
    closure = tuple(_value_key(c.cell_contents)
                    for c in (v.__closure__ or ()))
    # a global read (`FACTOR`, `jnp`) is baked into the traced program just
    # like a const — key its VALUE, not just its name, else two modules with
    # different FACTORs collide on one kernel. Modules key by name; names not
    # in __globals__ are builtins/attribute names (stable / covered by the
    # object they're read from).
    fglobals = getattr(v, "__globals__", {}) or {}
    gparts = []
    for name in code.co_names:
        if name in fglobals:
            g = fglobals[name]
            gparts.append((name, ("mod", g.__name__)
                           if isinstance(g, _types.ModuleType)
                           else _value_key(g)))
    return ("fn", code.co_code, consts, code.co_names, code.co_varnames,
            defaults, closure, tuple(gparts))


def _value_key(v):
    from spark_rapids_tpu.expr.core import Expression
    from spark_rapids_tpu import types as T
    if isinstance(v, Expression):
        return expr_key(v)
    if isinstance(v, (list, tuple)):
        return tuple(_value_key(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _value_key(x)) for k, x in v.items()))
    if isinstance(v, (str, int, float, bool, bytes, type(None))):
        return (type(v).__name__, v)
    if isinstance(v, T.DataType):
        return v
    if isinstance(v, type):              # class-valued fields (strategy
        return ("class", v.__module__, v.__qualname__)  # selectors etc.)
    if isinstance(v, _types.CodeType):   # nested function consts
        return ("code", v.co_code, tuple(_value_key(c) for c in v.co_consts),
                v.co_names)
    if callable(v) and hasattr(v, "__code__"):
        try:
            return _fn_key(v)
        except (AttributeError, ValueError):
            return UNKEYABLE
    return UNKEYABLE


def key_is_cacheable(key) -> bool:
    """False if any component of a (nested-tuple) semantic key is UNKEYABLE."""
    if key is UNKEYABLE:
        return False
    if isinstance(key, tuple):
        return all(key_is_cacheable(p) for p in key)
    return True


def schema_key(schema) -> tuple:
    return tuple((f.name, f.data_type, f.nullable) for f in schema)


class DictRef:
    """Hashable identity for a host string dictionary crossing a jit cache
    boundary (pa.Array itself is unhashable). Equality is CONTENT equality so
    per-batch dictionary objects with equal values hit the same compiled
    program; the hash is cheap (length only) — buckets stay small because
    dictionaries recur."""

    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = arr

    def __hash__(self):
        return hash(len(self.arr))

    def __eq__(self, other):
        if not isinstance(other, DictRef):
            return NotImplemented
        if self.arr is other.arr:
            return True
        try:
            return self.arr.equals(other.arr)
        except (TypeError, AttributeError):
            return False

    def __repr__(self):
        return f"DictRef(len={len(self.arr)})"


# -- cross-process digests (persistent compiled-stage cache) ------------------
#
# The in-memory semantic keys above only need to be HASHABLE; the on-disk
# stage cache additionally needs keys that are STABLE ACROSS PROCESSES, so
# they are reduced to a sha256 over a canonical byte encoding. Anything
# without a stable content encoding (UNKEYABLE markers, foreign objects)
# makes the whole key undigestable and the kernel stays memory-only.

import hashlib as _hashlib


class _Undigestable(Exception):
    pass


def _hash_part(h, v):
    from spark_rapids_tpu import types as T
    if v is None or isinstance(v, (bool, int, float, str)):
        h.update(f"{type(v).__name__}:{v!r};".encode())
    elif isinstance(v, bytes):
        h.update(b"b:")
        h.update(v)
        h.update(b";")
    elif isinstance(v, tuple) or isinstance(v, list):
        h.update(f"t{len(v)}(".encode())
        for p in v:
            _hash_part(h, p)
        h.update(b")")
    elif isinstance(v, T.DataType):
        h.update(f"dt:{v!r};".encode())
    elif isinstance(v, DictRef):
        h.update(f"dr:{_dict_digest(v.arr)};".encode())
    elif v is _EAGER or isinstance(v, _Unkeyable):
        raise _Undigestable(v)
    else:
        raise _Undigestable(v)


def key_digest(key) -> str | None:
    """Stable cross-process hex digest of a semantic kernel key, or None when
    some component has no canonical byte encoding (those kernels never reach
    the persistent stage cache)."""
    h = _hashlib.sha256()
    try:
        _hash_part(h, key)
    except _Undigestable:
        return None
    return h.hexdigest()[:32]


# host string dictionaries recur across batches; content digests are memoized
# by (id, len) — the len guard keeps an address-reuse collision from pairing
# a freed array's digest with a different same-address dictionary of equal
# length (astronomically unlikely to ALSO hash-collide, and the persistent
# cache is advisory)
_dict_digest_memo: dict = {}


def _dict_digest(arr) -> str:
    k = (id(arr), len(arr))
    v = _dict_digest_memo.get(k)
    if v is None:
        h = _hashlib.sha256()
        for s in arr:
            h.update(repr(s).encode())
            h.update(b"\x00")
        v = h.hexdigest()[:16]
        if len(_dict_digest_memo) > 4096:
            _dict_digest_memo.clear()
        _dict_digest_memo[k] = v
    return v


def _sig_digest(args) -> str | None:
    """Per-call argument-signature digest: everything `jax.jit` keys its own
    cache on (pytree structure, array shapes/dtypes, static leaves) reduced
    to a stable string. Python scalars are weak-typed DYNAMIC jit arguments —
    their VALUE is not baked into the program, so they contribute type only.
    Returns None for unsupported leaves (that call falls back to plain jit)."""
    h = _hashlib.sha256()
    try:
        _sig_part(h, args)
    except _Undigestable:
        return None
    return h.hexdigest()[:32]


def _sig_part(h, v):
    from spark_rapids_tpu import types as T
    from spark_rapids_tpu.expr.core import Col
    from spark_rapids_tpu.columnar.encoded import EncodedCol
    if isinstance(v, Col):
        d = _dict_digest(v.dictionary) if v.dictionary is not None else None
        h.update(f"C:{v.dtype!r}:{v.values.shape}:{v.values.dtype}:"
                 f"{v.validity.shape}:{d};".encode())
    elif isinstance(v, EncodedCol):
        # aux (spec/dtype/dictionary) is STATIC — baked into the traced
        # program, so its VALUES discriminate signatures (via _hash_part);
        # children are ordinary dynamic arrays
        children, aux = v.tree_flatten()
        h.update(b"E(")
        _hash_part(h, aux)
        _sig_part(h, children)
        h.update(b")")
    elif isinstance(v, T.DataType):
        h.update(f"dt:{v!r};".encode())
    elif isinstance(v, DictRef):
        h.update(f"dr:{_dict_digest(v.arr)};".encode())
    elif isinstance(v, (tuple, list)):
        h.update(f"t{len(v)}(".encode())
        for p in v:
            _sig_part(h, p)
        h.update(b")")
    elif isinstance(v, bool) or isinstance(v, (int, float)):
        # weak-typed dynamic scalar: type matters, value does not
        h.update(f"s:{type(v).__name__};".encode())
    elif v is None:
        h.update(b"n;")
    elif hasattr(v, "shape") and hasattr(v, "dtype"):
        h.update(f"a:{v.shape}:{v.dtype};".encode())
    else:
        raise _Undigestable(v)
