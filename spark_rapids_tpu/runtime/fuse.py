"""Whole-stage fusion: one jitted XLA program per (operator, shape bucket).

Reference contrast: the reference issues one cudf CUDA kernel per expression op
(GpuExpression columnarEval chains, SURVEY.md §1 L0/L4); kernel launches are
cheap on-node so that is fine there. On TPU every eager jax op is a separate
XLA program dispatch — through the axon tunnel each dispatch is a network
round-trip, and even locally the per-op Python/trace overhead dominates small
batches (round-2 profile: ~5.4k primitive binds per TPC-H q1 batch, ~99% of
hot-run wall time). The TPU-native answer is whole-stage compilation, the same
move Spark itself makes for codegen: trace the operator's ENTIRE per-batch
computation (expression eval -> sort/segment/compact kernels) once per input
shape bucket, then replay one compiled XLA program per batch.

Kernels are cached at module level keyed by a SEMANTIC key (operator class +
expression-tree structure + static config), because the planner rebuilds exec
instances on every collect() — a per-instance `jax.jit` would recompile every
run. `jax.jit`'s own cache then handles shape/dtype/dictionary variation
under each kernel.

Also the home of the compile/dispatch accounting the tuning story needs:
`stage_metrics()` reports traces (XLA compiles) vs dispatches (program
replays); a healthy query does O(stages) traces and O(batches) dispatches.
"""

from __future__ import annotations

import threading
import types as _types

import jax

from spark_rapids_tpu.runtime import metrics as _M

_lock = threading.Lock()
_kernels: dict = {}
_MAX_KERNELS = 2048
# XLA:CPU's LLVM JIT owns a bounded code-memory region; ~3000 live
# executables exhaust it and later compiles fail with "LLVM compilation
# error: Cannot allocate memory" or SEGFAULT inside backend_compile_and_load
# (measured on this box, docs/perf_notes.md r4). A kernel holds one
# executable PER SHAPE SIGNATURE, so the backstop must budget executables,
# not kernel objects.
_MAX_EXECUTABLES = 900
_inserts = 0

# counters are module-global (queries share kernels); reset via reset_metrics()
_counts = {"traces": 0, "dispatches": 0}

# SRT_FUSE_PROFILE=1: block on every kernel dispatch and record wall time per
# kernel name (kernel_profile()) — the steering tool for finding slow stages
import os as _os
_PROFILE = _os.environ.get("SRT_FUSE_PROFILE", "") == "1"
_profile: dict = {}


def kernel_profile() -> dict:
    """{kernel_name: (total_seconds, calls)} — only populated under
    SRT_FUSE_PROFILE=1."""
    with _lock:
        return dict(_profile)


def stage_metrics() -> dict:
    """{'traces': n_xla_compiles, 'dispatches': n_program_replays}."""
    with _lock:
        return dict(_counts)


def reset_metrics():
    with _lock:
        _counts["traces"] = 0
        _counts["dispatches"] = 0


class BatchKernel:
    """A jitted per-batch function with trace/dispatch accounting.

    The wrapped python body runs once per (shape, dtype, aux) signature —
    counting its executions counts XLA compiles; counting __call__ counts
    dispatches."""

    __slots__ = ("name", "_jit")

    def __init__(self, fn, name: str):
        self.name = name

        def traced(*args):
            with _lock:
                _counts["traces"] += 1
            # per-query retrace attribution: the tracing thread runs inside
            # the query's collector scope, so the compile lands on the query
            # that paid for it (metrics.compile_add, the resilience pattern)
            _M.compile_add("compiles")
            return fn(*args)

        self._jit = jax.jit(traced)

    def cache_size(self) -> int:
        """Live compiled-executable count (one per traced shape signature)."""
        try:
            return max(int(self._jit._cache_size()), 1)
        except Exception:
            return 1

    def __call__(self, *args):
        with _lock:
            _counts["dispatches"] += 1
        _M.compile_add("dispatches")
        if _PROFILE:
            import time
            t0 = time.perf_counter()
            out = jax.block_until_ready(self._jit(*args))
            dt = time.perf_counter() - t0
            with _lock:
                tot, n = _profile.get(self.name, (0.0, 0))
                _profile[self.name] = (tot + dt, n + 1)
            return out
        return self._jit(*args)


def get_kernel(key, name: str, build) -> BatchKernel:
    """Fetch-or-create the kernel for semantic key `key`. `build()` returns the
    pure per-batch function (it may close over expression trees — the key must
    capture everything that affects the traced program)."""
    global _inserts
    with _lock:
        k = _kernels.get(key)
    if k is not None:
        return k
    k = BatchKernel(build(), name)
    evicted = []
    with _lock:
        _inserts += 1
        if len(_kernels) >= _MAX_KERNELS or _inserts % 32 == 0:
            total = sum(kk.cache_size() for kk in _kernels.values()
                        if isinstance(kk, BatchKernel))   # skip _EAGER
            if total > _MAX_EXECUTABLES or len(_kernels) >= _MAX_KERNELS:
                # evict oldest (insertion order) until comfortably under
                # budget; anything hot re-traces on next use
                order = list(_kernels)
                while order and (total > int(_MAX_EXECUTABLES * 0.75)
                                 or len(_kernels) >= _MAX_KERNELS):
                    victim = _kernels.pop(order.pop(0))
                    if isinstance(victim, BatchKernel):
                        total -= victim.cache_size()
                        evicted.append(victim)
        out = _kernels.setdefault(key, k)
    del evicted   # destructors run outside the lock
    return out


def clear_kernels():
    with _lock:
        _kernels.clear()


_EAGER = "eager"  # sentinel cache entry: this key cannot be traced

_TRACE_ERRORS = tuple(
    e for e in (getattr(jax.errors, n, None) for n in
                ("ConcretizationTypeError", "TracerArrayConversionError",
                 "TracerBoolConversionError", "TracerIntegerConversionError"))
    if e is not None)


def call_fused(key, name: str, build, args, eager):
    """Run the kernel for `key` over `args`, falling back PERMANENTLY to
    `eager()` if the computation turns out to be untraceable (host sync /
    data-dependent Python control flow inside eval). The fallback latches per
    key so the failed trace is paid once. Keys containing UNKEYABLE fields
    (objects with no stable content key) are never cached — fusing them would
    key compiled programs on object addresses."""
    if not key_is_cacheable(key):
        return eager()
    with _lock:
        k = _kernels.get(key)
    if k is _EAGER:
        return eager()
    try:
        if k is None:
            k = get_kernel(key, name, build)
        return k(*args)
    except _TRACE_ERRORS:
        with _lock:
            _kernels[key] = _EAGER
        return eager()


# -- semantic keys over expression trees -------------------------------------

def expr_key(e):
    """Stable hashable key for an expression tree: class identity + every
    constructor-visible field, recursively. Two expressions with equal keys
    must trace to the same program over equal-signature inputs."""
    from spark_rapids_tpu.expr.core import Expression
    if isinstance(e, Expression):
        parts = [type(e).__module__, type(e).__qualname__]
        d = vars(e) if hasattr(e, "__dict__") else {
            s: getattr(e, s, None) for s in getattr(e, "__slots__", ())}
        for k in sorted(d):
            parts.append((k, _value_key(d[k])))
        return tuple(parts)
    return _value_key(e)


class _Unkeyable:
    """Marker embedded in a semantic key when some field has no stable content
    key (e.g. an arbitrary object whose repr would embed id()). call_fused
    treats any key containing it as uncacheable and runs eagerly — a fresh
    repr()-based key would either collide across distinct objects after
    address reuse or never be shared, so neither caching behavior is safe."""

    __slots__ = ()

    def __repr__(self):
        return "<unkeyable>"


UNKEYABLE = _Unkeyable()


_fn_key_active = threading.local()


def _fn_key(v):
    """Stable content key for a plain Python function: bytecode + consts +
    names + defaults + closure contents + the referenced module globals. Two
    content-equal UDFs share one compiled kernel; anything address-dependent
    (instance state, unkeyable globals) degrades to UNKEYABLE."""
    if hasattr(v, "__func__"):          # bound method: instance state matters
        return ("bound", _value_key(v.__self__), _fn_key(v.__func__))
    # mutually-recursive globals (def a(): b(); def b(): a()) would recurse
    # forever; on re-entry the participant's own bytecode already contributes
    # at the outer level, so a name marker suffices
    active = getattr(_fn_key_active, "ids", None)
    if active is None:
        active = _fn_key_active.ids = set()
    if id(v) in active:
        return ("recursive-fn", getattr(v, "__qualname__", "?"))
    active.add(id(v))
    try:
        return _fn_key_inner(v)
    finally:
        active.discard(id(v))


def _fn_key_inner(v):
    code = v.__code__
    consts = tuple(_value_key(c) for c in code.co_consts)
    defaults = tuple(_value_key(d) for d in (v.__defaults__ or ()))
    closure = tuple(_value_key(c.cell_contents)
                    for c in (v.__closure__ or ()))
    # a global read (`FACTOR`, `jnp`) is baked into the traced program just
    # like a const — key its VALUE, not just its name, else two modules with
    # different FACTORs collide on one kernel. Modules key by name; names not
    # in __globals__ are builtins/attribute names (stable / covered by the
    # object they're read from).
    fglobals = getattr(v, "__globals__", {}) or {}
    gparts = []
    for name in code.co_names:
        if name in fglobals:
            g = fglobals[name]
            gparts.append((name, ("mod", g.__name__)
                           if isinstance(g, _types.ModuleType)
                           else _value_key(g)))
    return ("fn", code.co_code, consts, code.co_names, code.co_varnames,
            defaults, closure, tuple(gparts))


def _value_key(v):
    from spark_rapids_tpu.expr.core import Expression
    from spark_rapids_tpu import types as T
    if isinstance(v, Expression):
        return expr_key(v)
    if isinstance(v, (list, tuple)):
        return tuple(_value_key(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _value_key(x)) for k, x in v.items()))
    if isinstance(v, (str, int, float, bool, bytes, type(None))):
        return (type(v).__name__, v)
    if isinstance(v, T.DataType):
        return v
    if isinstance(v, type):              # class-valued fields (strategy
        return ("class", v.__module__, v.__qualname__)  # selectors etc.)
    if isinstance(v, _types.CodeType):   # nested function consts
        return ("code", v.co_code, tuple(_value_key(c) for c in v.co_consts),
                v.co_names)
    if callable(v) and hasattr(v, "__code__"):
        try:
            return _fn_key(v)
        except (AttributeError, ValueError):
            return UNKEYABLE
    return UNKEYABLE


def key_is_cacheable(key) -> bool:
    """False if any component of a (nested-tuple) semantic key is UNKEYABLE."""
    if key is UNKEYABLE:
        return False
    if isinstance(key, tuple):
        return all(key_is_cacheable(p) for p in key)
    return True


def schema_key(schema) -> tuple:
    return tuple((f.name, f.data_type, f.nullable) for f in schema)


class DictRef:
    """Hashable identity for a host string dictionary crossing a jit cache
    boundary (pa.Array itself is unhashable). Equality is CONTENT equality so
    per-batch dictionary objects with equal values hit the same compiled
    program; the hash is cheap (length only) — buckets stay small because
    dictionaries recur."""

    __slots__ = ("arr",)

    def __init__(self, arr):
        self.arr = arr

    def __hash__(self):
        return hash(len(self.arr))

    def __eq__(self, other):
        if not isinstance(other, DictRef):
            return NotImplemented
        if self.arr is other.arr:
            return True
        try:
            return self.arr.equals(other.arr)
        except (TypeError, AttributeError):
            return False

    def __repr__(self):
        return f"DictRef(len={len(self.arr)})"
