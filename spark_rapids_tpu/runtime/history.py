"""On-disk plan-shape history store.

Persists observed per-shape statistics (peak device bytes, output
cardinalities, shuffle skew) keyed by the canonical plan fingerprint
(plan/fingerprint.py), written at query end and read at submit. This is the
memory that turns the admission controller's static x3 decode heuristic into
an observed-footprint estimate on the second run of a shape — the Spark CBO
analog, except the statistics come from the runtime itself rather than
ANALYZE TABLE.

File format: one JSON document `plan_history.json` in the configured
directory — {"version": 1, "shapes": {fp: entry}} where entry carries
runs / peak_device_bytes / out_rows / per-node rows / skew / updated (a
monotonically increasing sequence, not wall clock, so LRU eviction is
deterministic). Writes are read-merge-replace under a cross-process advisory
lock (runtime/locks.py) and land via os.replace, so N replica processes
sharing the directory never observe a torn file AND never drop each other's
shapes — without the lock, two replicas' load/merge/replace windows overlap
and the later replace silently reverts the earlier replica's merge. A
corrupt or unreadable file degrades to an empty store with one warning —
history is an optimization, never a query-failure source.

Process-global wiring follows the eventlog pattern: a session that sets
`stats.history.dir` explicitly calls configure(); estimate_footprint and the
end-of-query writer use get().
"""

from __future__ import annotations

import json
import logging
import os
import threading

from spark_rapids_tpu.runtime.locks import advisory_lock

log = logging.getLogger("spark_rapids_tpu.history")

_FILE = "plan_history.json"
_VERSION = 1


class PlanHistoryStore:
    """Read/merge/write access to one history directory. Thread-safe; every
    write re-reads the file so sessions sharing a directory compose."""

    def __init__(self, directory: str, max_shapes: int = 256):
        self.directory = directory
        self.max_shapes = max(int(max_shapes), 1)
        self.path = os.path.join(directory, _FILE)
        self._lock = threading.Lock()
        self._warned = False
        os.makedirs(directory, exist_ok=True)

    # -- file I/O -------------------------------------------------------------

    def _load(self) -> dict:
        """{fp: entry}; corrupt/missing file -> {} (warn once, never raise)."""
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                doc = json.load(f)
            shapes = doc.get("shapes")
            if not isinstance(shapes, dict):
                raise ValueError("missing shapes map")
            return shapes
        except FileNotFoundError:
            return {}
        except (OSError, ValueError, TypeError, KeyError) as e:
            if not self._warned:
                self._warned = True
                log.warning(
                    "plan history %s unreadable (%s); starting empty — "
                    "footprint estimates fall back to the static heuristic",
                    self.path, e)
            return {}

    def _store(self, shapes: dict) -> None:
        if len(shapes) > self.max_shapes:
            victims = sorted(shapes, key=lambda fp: shapes[fp].get("updated", 0))
            for fp in victims[:len(shapes) - self.max_shapes]:
                del shapes[fp]
        # pid-unique intent file: two replicas writing the shared name would
        # race open/replace; a crashed replica's orphan is reclaimed by the
        # fleet sweeper (runtime/fleet.py) via this recognizable suffix
        tmp = f"{self.path}.tmp.{os.getpid()}"
        doc = {"version": _VERSION, "shapes": shapes}
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(doc, f, separators=(",", ":"))
        os.replace(tmp, self.path)

    # -- API ------------------------------------------------------------------

    def lookup(self, fingerprint: str) -> dict | None:
        with self._lock:
            entry = self._load().get(fingerprint)
        return dict(entry) if isinstance(entry, dict) else None

    def record(self, fingerprint: str, obs: dict) -> dict:
        """Merge one query's observations into the shape's entry and persist.
        `obs` carries peak_device_bytes / out_rows / nodes / shuffle_skew /
        estimate_bytes for this run; peaks keep the max, cardinalities keep
        the latest. Returns the merged entry. Never raises."""
        try:
            # threading lock orders writers inside this process; the advisory
            # lock closes the cross-process load→merge→replace window so two
            # replicas can't drop each other's shapes (last-writer-wins)
            with self._lock, advisory_lock(self.path + ".lock"):
                shapes = self._load()
                entry = shapes.get(fingerprint)
                if not isinstance(entry, dict):
                    entry = {"runs": 0}
                entry["runs"] = int(entry.get("runs", 0)) + 1
                peak = int(obs.get("peak_device_bytes") or 0)
                if peak:
                    entry["peak_device_bytes"] = max(
                        peak, int(entry.get("peak_device_bytes", 0)))
                for k in ("out_rows", "nodes", "shuffle_skew",
                          "estimate_bytes"):
                    if obs.get(k) is not None:
                        entry[k] = obs[k]
                entry["updated"] = 1 + max(
                    (int(e.get("updated", 0)) for e in shapes.values()),
                    default=0)
                shapes[fingerprint] = entry
                self._store(shapes)
                self._publish_gauges(len(shapes))
                return dict(entry)
        except OSError as e:
            if not self._warned:
                self._warned = True
                log.warning("plan history %s not writable (%s); observations "
                            "for this shape are dropped", self.path, e)
            return dict(obs)

    def shape_count(self) -> int:
        with self._lock:
            return len(self._load())

    def _publish_gauges(self, n: int) -> None:
        from spark_rapids_tpu.runtime import metrics as M
        M.set_gauge("history.shapes", n)


# -- process-global instance (eventlog-style explicit-switch wiring) ----------

_ilock = threading.Lock()
_instance: PlanHistoryStore | None = None


def configure(directory: str | None, max_shapes: int = 256) -> None:
    global _instance
    with _ilock:
        if not directory:
            _instance = None
            return
        if (_instance is not None and _instance.directory == directory
                and _instance.max_shapes == max(int(max_shapes), 1)):
            return
        _instance = PlanHistoryStore(directory, max_shapes)
    _instance._publish_gauges(_instance.shape_count())


def get() -> PlanHistoryStore | None:
    return _instance


def shutdown() -> None:
    configure(None)
