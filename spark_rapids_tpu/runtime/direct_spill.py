"""Direct-I/O batched spill store — the GDS spill analog.

Reference (SURVEY.md #7): RapidsGdsStore.scala:32 writes spilled device
buffers straight to NVMe through cuFile, and its BatchSpiller (:123)
coalesces small buffers into aligned batch files so tiny spills don't pay
per-file overhead. A TPU host has no device→NVMe DMA path, so the analog
is host-side O_DIRECT: page-aligned writes that bypass the OS page cache
(the point of GDS is exactly to avoid bouncing spill bytes through host
cache memory — under memory pressure the page cache is the enemy).

Design mirrored from the reference:
  * small buffers append into one OPEN batch file (fd held until the file
    seals — one open(2) per batch file, not per spill) at aligned offsets
    (BatchSpiller.addBuffer); handles are (file_id, offset, length);
  * a sealed batch file is unlinked when its last live buffer is deleted,
    and rotation unlinks the outgoing file immediately when every buffer
    in it already died (RapidsGdsStore refcounts batch blobs the same way);
  * O_DIRECT with an mmap bounce buffer (page-aligned by construction);
    transparent fallback to buffered I/O where O_DIRECT is unsupported
    (tmpfs, CI containers) — same behavior switch as gds-spilling.md's
    "best effort" mode.
"""

from __future__ import annotations

import mmap
import os
import threading
import time

from spark_rapids_tpu.runtime import movement as MV

ALIGN = 4096


class _BatchFile:
    def __init__(self, path: str):
        self.path = path
        self.size = 0
        self.live = 0      # live buffer count; unlink at zero (refcount)
        self.sealed = False


class DirectSpillStore:
    """Batched aligned spill writes; returns opaque handles."""

    def __init__(self, directory: str, batch_bytes: int = 64 << 20,
                 use_direct: bool = True):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.batch_bytes = max(batch_bytes, ALIGN)
        self._lock = threading.Lock()
        self._files: dict[int, _BatchFile] = {}
        self._next_file = 0
        self._current: int | None = None
        self._fd: int | None = None       # open fd for the current file
        self._fd_direct = False
        self._direct = use_direct
        self._direct_works: bool | None = None  # latched on first failure
        # reused page-aligned bounce buffer for O_DIRECT writes
        self._bounce = mmap.mmap(-1, ALIGN)

    # -- internals (all under self._lock) -------------------------------------

    def _open_fd(self, path: str) -> int:
        direct = (self._direct and self._direct_works is not False
                  and hasattr(os, "O_DIRECT"))
        flags = os.O_WRONLY | os.O_CREAT
        if direct:
            try:
                fd = os.open(path, flags | os.O_DIRECT, 0o600)
                self._fd_direct = True
                return fd
            except OSError:
                self._direct_works = False
        self._fd_direct = False
        return os.open(path, flags, 0o600)

    def _close_fd(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def _unlink_file(self, fid: int) -> None:
        bf = self._files.pop(fid, None)
        if bf is not None:
            try:
                os.unlink(bf.path)
            except OSError:
                pass

    def _rotate(self) -> int:
        """Seal the current batch file and open a fresh one."""
        old = self._current
        if old is not None:
            self._files[old].sealed = True
            self._close_fd()
            if self._files[old].live <= 0:
                self._unlink_file(old)  # every buffer already died
        fid = self._next_file
        self._next_file += 1
        bf = _BatchFile(os.path.join(self.dir, f"spill-batch-{fid}.bin"))
        self._files[fid] = bf
        self._current = fid
        self._fd = self._open_fd(bf.path)
        return fid

    def _write_aligned(self, fid: int, payload: bytes) -> int:
        """Append `payload` at an aligned offset via the open fd."""
        bf = self._files[fid]
        offset = bf.size
        padded = -(-len(payload) // ALIGN) * ALIGN
        if len(self._bounce) < padded:
            self._bounce.close()
            self._bounce = mmap.mmap(-1, padded)
        self._bounce.seek(0)
        self._bounce.write(payload)
        self._bounce.write(b"\0" * (padded - len(payload)))
        view = memoryview(self._bounce)[:padded]
        try:
            os.pwrite(self._fd, view, offset)
        except OSError:
            if not self._fd_direct:
                raise
            # filesystem accepted O_DIRECT at open but refused the write
            # (some FUSE/network mounts) — fall back for good
            self._direct_works = False
            self._close_fd()
            self._fd = self._open_fd(bf.path)
            os.pwrite(self._fd, view, offset)
        bf.size += padded
        return offset

    # -- public --------------------------------------------------------------

    def write(self, payload: bytes) -> tuple[int, int, int]:
        """Spill one serialized buffer; returns handle (file_id, offset, len).
        Buffers accumulate into the current batch file until it reaches
        batch_bytes, then a new file starts (BatchSpiller rotation)."""
        t0 = time.perf_counter()
        with self._lock:
            fid = self._current
            if fid is None or self._files[fid].size >= self.batch_bytes:
                fid = self._rotate()
            offset = self._write_aligned(fid, payload)
            self._files[fid].live += 1
        # movement ledger: physical bytes are the ALIGNED write (what the
        # disk actually absorbs), payload bytes the logical buffer
        MV.record("spill.write", -(-len(payload) // ALIGN) * ALIGN,
                  link="disk", site="direct_spill",
                  payload_bytes=len(payload),
                  seconds=time.perf_counter() - t0)
        return (fid, offset, len(payload))

    def read(self, handle: tuple[int, int, int]) -> bytes:
        fid, offset, length = handle
        t0 = time.perf_counter()
        with self._lock:
            path = self._files[fid].path
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(length)
        MV.record("spill.read", length, link="disk", site="direct_spill",
                  seconds=time.perf_counter() - t0)
        return data

    def delete(self, handle: tuple[int, int, int]) -> None:
        fid, _, _ = handle
        with self._lock:
            bf = self._files.get(fid)
            if bf is None:
                return
            bf.live -= 1
            # the open batch file keeps accepting writes even at live==0
            # (rotation reclaims it — matches the reference's pending blob)
            if bf.live <= 0 and bf.sealed:
                self._unlink_file(fid)

    def close(self) -> None:
        with self._lock:
            self._close_fd()
            for fid in list(self._files):
                self._unlink_file(fid)
            self._current = None
            self._bounce.close()

    @property
    def direct_active(self) -> bool:
        return bool(self._fd_direct)
