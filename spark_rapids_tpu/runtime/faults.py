"""Deterministic fault injection — the chaos layer for the retry spine.

Nothing in a single-process engine naturally exercises the OOM/fetch
recovery ladders, so faults are INJECTED: a seeded, config-driven registry
(`spark.rapids.tpu.test.faults`) arms named sites across memory and shuffle,
and `tests/test_retry_faults.py` proves end-to-end that injected failures
recover to bit-identical results. The reference tests the same ladders with
RmmSpark.forceRetryOOM / forceSplitAndRetryOOM task hooks; this is that
facility without a JNI layer underneath.

Spec grammar (comma-separated entries)::

    entry   := kind ":" site ":" trigger
    kind    := "oom" | "splitoom" | "transport" | "error" | "exec_kill"
             | "hang" | "cancel" | "slow" | "corrupt" | "leak" | "disk_full"
    trigger := COUNT | COUNT "@" SKIP | "p" PROB

``oom`` raises a retryable runtime.retry.DeviceOomError, ``splitoom`` a
SplitAndRetryOom, ``transport`` a shuffle TransportError, ``error`` a plain
RuntimeError (a fault NO recovery ladder absorbs — proves clean whole-query
failure paths), ``exec_kill`` SIGKILLs the process serving the
checkpoint — the MiniCluster executor chaos hook: the process dies mid-task
with all its shuffle blocks, exercising the driver's lineage-scoped
recovery (cluster/minicluster.py) — and ``hang`` sleeps forever at the
site (the wedged-executor simulation that exercises the driver's
``cluster.task.timeoutSeconds`` deadline). ``cancel`` flips the ambient
query's CancelToken at the site and raises the typed QueryCancelledError —
the race-pinning chaos hook for the multi-tenant lifecycle
(runtime/scheduler.py): it cancels a query at EXACTLY the checkpoint named,
where an external ``session.cancel()`` could only race it. ``slow`` sleeps
250ms at the site and continues (no raise) — widens race windows so
deadline/cancel races and scheduler queue timeouts become deterministic.
``corrupt`` never raises from the generic checkpoints; it arms
:func:`maybe_corrupt` sites (transport block reassembly, spill file write)
to flip one byte of the payload, proving the CRC detection → fetch-failure
ladders end to end. ``leak`` likewise never raises: it arms
:func:`should_leak` at buffer-release sites (SpillableColumnarBatch.close,
checked against the buffer's allocation site, e.g. "leak:joins.build:1") to
SKIP the catalog release, proving the end-of-query leak detector
(runtime/memory.py) catches, reports and reclaims what the operator
forgot. COUNT injects on that many eligible hits; ``@SKIP`` first
lets SKIP eligible hits pass ("oom:agg.update:1@3" skips three, injects
once); ``pPROB`` injects each hit with the given probability from a
PER-SITE seeded RNG — each (kind, site) entry draws from its own stream
seeded by (seed, kind, site), so one seed yields one deterministic
schedule per site regardless of how the pipeline's worker threads
interleave hits ACROSS sites (a process-global stream made chaos runs
irreproducible under concurrency).

Sites: with_retry/call_with_retry attempts check their ``scope`` label
("joins.build", "joins.gather", "agg.update", "agg.merge", "sort.sort",
"exchange.map", "exchange.write"); catalog registrations outside a scope
check "catalog.add_batch"; the shuffle data plane checks "transport.send" /
"transport.recv" (frame I/O) and "fetch" (per fetch attempt, both the peer
ladder in shuffle/fetch.py and the stage ladder in exec/exchange.py).
Pipeline queue boundaries (runtime/pipeline.py) check "pipeline.put" /
"pipeline.get" plus the edge-qualified "pipeline.put.<edge>" /
"pipeline.get.<edge>" via :func:`maybe_inject_any` — any armed kind fires
there, proving a worker-thread fault cancels the whole pipeline and
re-raises at the consumer. MiniCluster executors check "cluster.map" /
"cluster.result" per produced batch plus the executor-qualified
"cluster.map.<idx>" / "cluster.result.<idx>" (so one spec can SIGKILL
exactly one of N executors mid-task), and "cluster.map.begin" /
"cluster.result.begin" (+ ".<idx>") once at task START — the site that
still fires when a task's input produces zero batches; the driver disarms
faults on respawned replacement executors so a COUNT trigger cannot
re-fire forever. The unified mesh-cluster plane adds the mesh-collective
sites "cluster.mesh.begin" (+ ".<idx>", once at mesh bring-up inside a
mesh map task) and "cluster.mesh" (+ ".<idx>", per partition wave, INSIDE
the jitted collective region) — the mesh_kill/mesh_hang chaos hooks:
``exec_kill`` there dies mid-collective with partial blocks parked
(driver: executor loss → degraded TCP re-plan under a bumped epoch),
``hang`` there wedges the collective so ONLY the task deadline can
surface it, and ``error`` there proves the transparent mesh→TCP
degraded fallback without losing the process. ``disk_full`` raises a
retryable runtime.retry.SpillCapacityError at the disk-spill writer
("spill.write", runtime/memory.py) — the typed ENOSPC: it rides the OOM
recovery ladder (spill elsewhere / split / retry) instead of escaping as
a raw OSError. The query-serving endpoint (runtime/endpoint.py) checks
"endpoint.accept" (connection admitted), "endpoint.recv" (request frame
read) and "endpoint.send" (per result frame) via :func:`maybe_inject_any`
— any armed kind fires at the wire — and "endpoint.corrupt" is a
:func:`maybe_corrupt` payload site (result batch after its CRC is stamped,
so the client's verification must catch the flip).
The streaming plane (streaming/) checks "streaming.ingest" (before an
APPEND's first durable byte — a fault there must leave nothing the next
listing can see), "streaming.epoch.commit" (top of the journal's commit
write — ``exec_kill`` there dies with the epoch's work finished but
unjournaled, the exactly-once replay window), and "streaming.state" (the
state-snapshot writer) via :func:`maybe_inject_any`; "streaming.state" is
also a :func:`maybe_corrupt` payload site (snapshot bytes after the
checksum is taken, so recovery's verification must catch the flip and
rebuild from the batch log).
"""

from __future__ import annotations

import contextlib
import random
import re
import threading

_lock = threading.Lock()
_active = False
_entries: list = []
_injected: list = []
_tls = threading.local()

_KINDS = ("oom", "splitoom", "transport", "error", "exec_kill", "hang",
          "cancel", "slow", "corrupt", "leak", "disk_full")
_ENTRY_RE = re.compile(
    r"^(?P<kind>[a-z_]+):(?P<site>[A-Za-z0-9_.\-]+):"
    r"(?:(?P<count>\d+)(?:@(?P<skip>\d+))?|p(?P<prob>0?\.\d+|1(?:\.0*)?))$")


class _Entry:
    __slots__ = ("kind", "site", "count", "skip", "prob", "rng")

    def __init__(self, kind, site, count, skip, prob, seed=0):
        self.kind = kind
        self.site = site
        self.count = count
        self.skip = skip
        self.prob = prob
        # per-site stream: pPROB draws must not depend on which OTHER sites'
        # threads consumed a shared stream first (pipeline workers interleave
        # nondeterministically); str seeds hash via sha512, stable across
        # processes — one (seed, kind, site) is one schedule, always
        self.rng = random.Random(f"{seed}|{kind}|{site}")


def parse_spec(spec: str, seed: int = 0) -> list:
    entries = []
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        m = _ENTRY_RE.match(raw)
        if not m or m.group("kind") not in _KINDS:
            raise ValueError(
                f"bad fault spec entry {raw!r}; want kind:site:trigger with "
                f"kind in {_KINDS} and trigger COUNT[@SKIP] or pPROB")
        entries.append(_Entry(
            m.group("kind"), m.group("site"),
            int(m.group("count")) if m.group("count") else 0,
            int(m.group("skip") or 0),
            float(m.group("prob")) if m.group("prob") else None,
            seed=seed))
    return entries


def configure(spec: str | None, seed: int = 0) -> None:
    """Arm (or with None/empty, disarm) the process-wide injector."""
    global _active, _entries
    with _lock:
        _entries = parse_spec(spec, seed) if spec else []
        _injected.clear()
        _active = bool(_entries)


def reset() -> None:
    configure(None)


def is_active() -> bool:
    return _active


def injected_log() -> list:
    """[(kind, site), ...] in injection order — chaos tests assert the whole
    configured schedule actually fired."""
    with _lock:
        return list(_injected)


@contextlib.contextmanager
def scope(site: str | None):
    """Thread-local site label: catalog registrations inside the block
    attribute their injection checks to `site` instead of
    "catalog.add_batch"."""
    prev = getattr(_tls, "site", None)
    _tls.site = site
    try:
        yield
    finally:
        _tls.site = prev


def current_scope() -> str | None:
    return getattr(_tls, "site", None)


def _select(site: str, kind_ok) -> "str | None":
    """Shared trigger walk: find the first armed entry for `site` whose kind
    satisfies `kind_ok`, honor its COUNT/@SKIP/pPROB trigger; returns the
    firing kind (already logged) or None."""
    with _lock:
        for e in _entries:
            if not kind_ok(e.kind) or e.site != site:
                continue
            if e.prob is not None:
                if e.rng.random() < e.prob:
                    _injected.append((e.kind, site))
                    return e.kind
                return None
            if e.count <= 0:
                continue
            if e.skip > 0:
                e.skip -= 1
                return None
            e.count -= 1
            _injected.append((e.kind, site))
            return e.kind
    return None


def _select_and_fire(site: str, kind_ok) -> None:
    kind = _select(site, kind_ok)
    if kind is not None:
        _raise(kind, site)


def maybe_inject(kind: str, site: str) -> None:
    """Raise the configured fault for (kind, site) if one is armed; a no-op
    flag check when injection is off (the production fast path). A "cancel"
    entry also satisfies any checkpoint kind — cancellation races are worth
    pinning at every recovery-ladder site, not only the generic ones."""
    if not _active:
        return
    # an "oom" checkpoint arms both OOM flavors — splitoom is the same
    # fault class with a stronger recovery demand
    _select_and_fire(site, lambda k: k == kind
                     or (kind == "oom" and k == "splitoom")
                     or k in ("cancel", "slow"))


def maybe_inject_any(site: str) -> None:
    """Raise whatever fault is armed for `site`, regardless of kind — the
    pipeline queue put/get hooks use this so one chaos spec can drive any
    fault class through a stage boundary. ("corrupt", "leak" and
    "disk_full" entries stay silent here: corrupt only acts through
    maybe_corrupt's payload sites, leak only through should_leak's release
    sites, disk_full only at the spill-writer checkpoint.)"""
    if not _active:
        return
    _select_and_fire(site, lambda k: k not in ("corrupt", "leak",
                                               "disk_full"))


def should_leak(site: str) -> bool:
    """Release checkpoint: True when a "leak" entry is armed for `site` —
    the caller then SKIPS the buffer release it was about to perform
    (SpillableColumnarBatch.close keeps the catalog entry alive), modeling
    a refcount bug that the end-of-query leak detector
    (runtime/memory.BufferCatalog.finish_query) must catch, report and
    reclaim. Never raises; a no-op flag check when injection is off."""
    if not _active:
        return False
    return _select(site, lambda k: k == "leak") is not None


def maybe_corrupt(site: str, data: bytes) -> bytes:
    """Payload checkpoint: when a "corrupt" entry is armed for `site`, flip
    one byte of `data` (middle of the buffer) so the CRC verification on
    the other side of the wire/spill must catch it; otherwise return `data`
    unchanged. Sites: "transport.corrupt" (client-side block reassembly,
    shuffle/transport.py) and "spill.write" (disk-tier spill payload,
    runtime/memory.py) and "endpoint.corrupt" (result batch after CRC
    stamping, runtime/endpoint.py)."""
    if not _active or not data:
        return data
    if _select(site, lambda k: k == "corrupt") is None:
        return data
    flipped = bytearray(data)
    flipped[len(flipped) // 2] ^= 0xFF
    return bytes(flipped)


def _raise(kind: str, site: str):
    if kind == "slow":
        # widen the race window, then continue — no error: the site runs
        # 250ms later than it would have, which is what deadline/cancel
        # race tests and queue-timeout tests need to be deterministic
        import time
        time.sleep(0.25)
        return
    if kind == "cancel":
        # cancel the ambient query AT this exact checkpoint: the token flips
        # (so every other thread of the query drains cooperatively) and this
        # thread raises the typed error immediately
        from spark_rapids_tpu.runtime import scheduler as SCHED
        tok = SCHED.current_token()
        if tok is not None:
            tok.cancel(f"fault-injection at {site}")
            tok.check()
        raise SCHED.QueryCancelledError(
            f"[fault-injection] cancel at {site}")
    if kind == "exec_kill":
        # die the way a real executor crash does: no cleanup, no goodbye on
        # the driver pipe, shuffle blocks lost with the process
        import os
        import signal
        os.kill(os.getpid(), signal.SIGKILL)
    if kind == "hang":
        # wedge, don't die: the process stays alive and unresponsive so
        # only a task deadline (driver-side kill) can unstick the slot
        import time
        while True:
            time.sleep(3600)
    if kind == "transport":
        from spark_rapids_tpu.shuffle.transport import TransportError
        raise TransportError(f"[fault-injection] transport fault at {site}")
    if kind == "disk_full":
        from spark_rapids_tpu.runtime.retry import SpillCapacityError
        raise SpillCapacityError(
            f"[fault-injection] disk full (ENOSPC) at {site}", injected=True)
    if kind == "error":
        raise RuntimeError(f"[fault-injection] error at {site}")
    from spark_rapids_tpu.runtime.retry import DeviceOomError, SplitAndRetryOom
    cls = SplitAndRetryOom if kind == "splitoom" else DeviceOomError
    raise cls(f"[fault-injection] device OOM at {site}", injected=True)
