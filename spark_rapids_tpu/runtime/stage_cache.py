"""Persistent compiled-stage cache — XLA executables on disk across sessions.

Reference contrast: the reference pays cudf JIT/PTX compilation per process
and leans on the CUDA driver's own binary cache; here every fused stage is an
XLA program whose compile cost (seconds per shape signature on the remote
compiler path) recurs on EVERY fresh session. This store keeps the serialized
executables (jax AOT export, `jax.experimental.serialize_executable`) keyed
by the kernel's cross-process semantic-key digest + argument-signature digest
(runtime/fuse.key_digest / _sig_digest), so a fresh session's first run of a
known query shape replays stored programs with ZERO Python traces.

Failure posture mirrors runtime/history.py: a corrupt/unreadable entry is
deleted, logged once, surfaced as a `stage.cache.corrupt` event, and the
kernel silently retraces — the cache can only ever cost a recompile, never a
query. Writes are atomic (tmp + os.replace) with a pid-unique tmp suffix, so
N replica processes compiling the same shape never race on one tmp name; a
crashed replica's orphaned tmp is reclaimed by the fleet sweeper
(runtime/fleet.py). The directory is pruned to `maxBytes` by mtime LRU after
each save, per-file ENOENT-tolerant because a peer replica may prune
concurrently; an entry this process has seen that vanishes under a
concurrent prune is a WARNED retrace (`pruned_misses`,
`stage.cache.pruned_race` event) — degraded, never a query failure.

Wiring: TpuSession.__init__ configures the process-global store from the
`spark.rapids.tpu.sql.stage.cache.{enabled,dir,maxBytes}` knobs (explicit
settings only — the other process-global planes follow the same rule).
"""

from __future__ import annotations

import itertools
import os
import threading
import warnings

_SUFFIX = ".xc"
_tmp_seq = itertools.count()


class StageCacheStore:
    """One directory of serialized XLA executables, one file per
    (kernel-key digest, argument-signature digest) entry."""

    def __init__(self, directory: str, max_bytes: int = 256 << 20):
        self.directory = directory
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._warned = False
        # observability counters (tests + profiler read these)
        self.hits = 0
        self.misses = 0
        self.saves = 0
        self.corrupt = 0
        # a load that missed an entry this process saved or hit before: a
        # concurrent peer's LRU prune unlinked it — warned retrace, not error
        self.pruned_misses = 0
        self._seen: set = set()
        os.makedirs(directory, exist_ok=True)

    def _path(self, entry: str) -> str:
        return os.path.join(self.directory, entry + _SUFFIX)

    def load(self, entry: str) -> bytes | None:
        try:
            with open(self._path(entry), "rb") as f:
                data = f.read()
        except FileNotFoundError:
            with self._lock:
                self.misses += 1
                raced = entry in self._seen
                if raced:
                    self.pruned_misses += 1
            if raced:
                self._note_pruned_race(entry)
            return None
        except OSError as e:
            self._warn_once(f"unreadable stage-cache entry {entry}: {e!r}")
            with self._lock:
                self.misses += 1
            return None
        with self._lock:
            self.hits += 1
            self._seen.add(entry)
        return data

    def save(self, entry: str, data: bytes) -> None:
        if len(data) > self.max_bytes:
            return
        path = self._path(entry)
        # pid + sequence keeps tmp names unique across replicas AND across
        # threads in one replica compiling the same signature
        tmp = f"{path}.tmp.{os.getpid()}-{next(_tmp_seq)}"
        try:
            with open(tmp, "wb") as f:
                f.write(data)
            os.replace(tmp, path)
        except OSError as e:
            self._warn_once(f"stage-cache write failed: {e!r}")
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return
        with self._lock:
            self.saves += 1
            self._seen.add(entry)
        self._prune()

    def invalidate(self, entry: str, reason: str) -> None:
        """A stored executable failed to deserialize: delete it, log once,
        emit a warning event — the caller retraces (degraded, never fatal)."""
        with self._lock:
            self.corrupt += 1
        try:
            os.unlink(self._path(entry))
        except OSError:
            pass
        self._warn_once(
            f"corrupt stage-cache entry {entry} ({reason}); retracing")
        try:
            from spark_rapids_tpu.runtime import eventlog as EL
            if EL.enabled():
                EL.emit("stage.cache.corrupt", entry=entry, reason=reason)
        except Exception:  # noqa: BLE001 — observability must not fail a query
            pass

    def note_unserializable(self, entry: str, reason: str) -> None:
        """An executable compiled but would not serialize (backend-specific);
        the kernel keeps working memory-only."""
        self._warn_once(
            f"stage-cache entry {entry} not serializable ({reason}); "
            "kernel stays memory-only")

    def entries(self) -> list:
        try:
            return sorted(n[:-len(_SUFFIX)] for n in os.listdir(self.directory)
                          if n.endswith(_SUFFIX))
        except OSError:
            return []

    def total_bytes(self) -> int:
        total = 0
        try:
            names = os.listdir(self.directory)
        except OSError:
            return 0
        for n in names:
            if n.endswith(_SUFFIX):
                try:
                    total += os.path.getsize(os.path.join(self.directory, n))
                except OSError:
                    pass  # a peer replica pruned it mid-scan
        return total

    def _prune(self) -> None:
        """mtime-LRU down to max_bytes (oldest executables are the ones least
        likely to match a current plan shape). Per-file stat tolerance: a
        peer replica pruning concurrently unlinks entries mid-scan, which
        must skip that entry, not abort the whole prune."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        files = []
        for n in names:
            if not n.endswith(_SUFFIX):
                continue
            p = os.path.join(self.directory, n)
            try:
                st = os.stat(p)
            except OSError:
                continue
            files.append((st.st_mtime, st.st_size, p))
        total = sum(sz for _, sz, _ in files)
        if total <= self.max_bytes:
            return
        files.sort()
        for _, sz, p in files:
            if total <= self.max_bytes:
                break
            try:
                os.unlink(p)
                total -= sz
            except OSError:
                pass

    def _note_pruned_race(self, entry: str) -> None:
        """An entry this process had seen vanished: a concurrent peer's LRU
        prune won the race. The kernel retraces — degraded, never wrong."""
        self._warn_once(
            f"stage-cache entry {entry} pruned by a concurrent replica; "
            "retracing")
        try:
            from spark_rapids_tpu.runtime import eventlog as EL
            if EL.enabled():
                EL.emit("stage.cache.pruned_race", entry=entry)
        except Exception:  # noqa: BLE001 — observability must not fail a query
            pass

    def _warn_once(self, msg: str) -> None:
        with self._lock:
            if self._warned:
                return
            self._warned = True
        warnings.warn(f"spark_rapids_tpu stage cache: {msg}", RuntimeWarning,
                      stacklevel=3)


# -- process-global instance (the runtime/history.py configure idiom) --------

_ilock = threading.Lock()
_store: StageCacheStore | None = None


def _disable_jax_persistent_compile_cache() -> None:
    """An executable rehydrated from jax's own persistent compile cache
    serializes WITHOUT its object code — every store entry saved from one
    fails with "Symbols not found" in the next session. jax memoizes the
    cache-enabled check at the first compile, so the only reliable posture
    is to switch its cache off BEFORE anything compiles: the stage cache
    subsumes its role for fused stages (which dominate compile time), and
    fuse.py's save-time round-trip validation backstops late enables."""
    try:
        import jax
        jax.config.update("jax_enable_compilation_cache", False)
    except Exception:  # noqa: BLE001 — a missing knob must not fail a session
        pass


def configure(directory: str, max_bytes: int = 256 << 20) -> StageCacheStore:
    global _store
    with _ilock:
        if (_store is None or _store.directory != directory
                or _store.max_bytes != int(max_bytes)):
            _disable_jax_persistent_compile_cache()
            _store = StageCacheStore(directory, max_bytes)
        return _store


def get() -> StageCacheStore | None:
    return _store


def shutdown() -> None:
    global _store
    with _ilock:
        _store = None
