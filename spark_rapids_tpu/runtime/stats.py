"""Runtime statistics plane: per-query observed-stats aggregation.

The capture side lives where the data flows — exec/base.wrap_output (output
rows/batches/bytes), columnar/batch (host<->device transfer bytes),
runtime/fuse via metrics.compile_add (per-node compiles/dispatches),
exec/exchange + the mesh map stages (per-reduce-partition byte sizes). This
module is the read-out: it merges the collector's metric snapshots with the
stats ledger into one per-node table, derives selectivities and shuffle skew,
builds the `plan.stats` event payload, writes the plan-shape history entry at
query end, and renders `explain(stats=True)` (observed vs estimated rows per
node).

Everything here runs once per query at finish — per-batch cost stays in the
capture hooks, which are dict increments under the collector lock.
"""

from __future__ import annotations

from spark_rapids_tpu.runtime import metrics as M

# stats-ledger keys (capture hooks write these via metrics.stats_add)
OUTPUT_BYTES = "outputBytes"       # device bytes produced (wrap_output)
H2D_BYTES = "h2dBytes"             # host->device upload bytes (from_arrow)
D2H_BYTES = "d2hBytes"             # device->host bytes (to_arrow)

# history/payload node lists are bounded: a pathological plan cannot grow the
# event record or history file without bound
MAX_NODES = 64

_ERROR_BOUNDS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.0, 5.0, 10.0)


def estimate_error_histogram() -> "M.Histogram":
    """Process-wide histogram of |estimate - observed peak| / observed peak
    per finished device query (the admission-accuracy read-out on STATS)."""
    return M.histogram("footprint.estimate.error", _ERROR_BOUNDS)


def node_table(collector) -> list:
    """Per-node observed statistics in plan-tree preorder: metric snapshot
    rows/batches merged with the stats ledger (bytes, transfers, per-node
    compiles/dispatches) plus derived selectivity (out rows / sum of metered
    child out rows)."""
    summaries = collector.node_summaries()
    ledger = collector.node_stats()
    entries = []
    for s in summaries:
        m = s.get("metrics") or {}
        led = ledger.get(s["id"], {}) if s["id"] is not None else {}
        e = {
            "id": s["id"],
            "name": s["name"],
            "args": s["args"],
            "parent": s["parent"],
            "depth": s["depth"],
            "rows": m.get(M.NUM_OUTPUT_ROWS),
            "batches": m.get(M.NUM_OUTPUT_BATCHES),
            "in_rows": m.get(M.NUM_INPUT_ROWS),
            "output_bytes": led.get(OUTPUT_BYTES),
            "h2d_bytes": led.get(H2D_BYTES),
            "d2h_bytes": led.get(D2H_BYTES),
            "compiles": led.get("compiles"),
            "dispatches": led.get("dispatches"),
        }
        entries.append(e)
    # selectivity from the tree itself: children identified by parent id
    rows_by_id = {e["id"]: e["rows"] for e in entries if e["id"] is not None}
    kids: dict = {}
    for e in entries:
        if e["parent"] is not None and e["id"] is not None:
            kids.setdefault(e["parent"], []).append(e["id"])
    for e in entries:
        src = e["in_rows"]
        if src is None:
            metered = [rows_by_id[c] for c in kids.get(e["id"], ())
                       if rows_by_id.get(c) is not None]
            src = sum(metered) if metered else None
        if src and e["rows"] is not None:
            e["selectivity"] = round(e["rows"] / src, 6)
        else:
            e["selectivity"] = None
    return entries


def skew_summary(sizes) -> dict | None:
    """Reduce-partition skew: which partition is largest and by how much vs
    the mean of non-empty partitions (ratio 1.0 == perfectly even)."""
    sizes = [int(s) for s in sizes]
    total = sum(sizes)
    if not sizes or total <= 0:
        return None
    mean = total / len(sizes)
    mx = max(sizes)
    return {"partitions": len(sizes), "total_bytes": total,
            "max_partition": sizes.index(mx), "max_bytes": mx,
            "mean_bytes": int(mean), "skew_ratio": round(mx / mean, 3)}


def _shuffles(collector) -> list:
    out = []
    for e in collector.shuffle_stats():
        entry = dict(e)
        sk = skew_summary(e.get("partition_sizes") or ())
        if sk:
            entry.update(sk)
        out.append(entry)
    return out


def _root_rows(entries) -> int | None:
    for e in entries:   # preorder: first metered node is the plan root's
        if e["rows"] is not None:    # device side (collect() row count)
            return int(e["rows"])
    return None


def plan_stats_payload(collector) -> dict:
    """The plan.stats event-log record body (also session.last_query stats)."""
    fp = collector.footprint or {}
    entries = node_table(collector)
    peak = (collector.memory or {}).get("peak_device_bytes")
    estimate = fp.get("estimate")
    err = None
    if peak and estimate is not None:
        err = round(abs(int(estimate) - int(peak)) / int(peak), 6)
    nodes = []
    for e in entries[:MAX_NODES]:
        n = {k: e[k] for k in ("id", "name", "rows", "batches", "selectivity",
                               "output_bytes", "h2d_bytes", "d2h_bytes",
                               "compiles", "dispatches")
             if e[k] is not None or k in ("id", "name", "rows")}
        nodes.append(n)
    return {
        "fingerprint": fp.get("fingerprint"),
        "estimate_bytes": estimate,
        "static_estimate_bytes": fp.get("static"),
        "history_hit": bool(fp.get("history_hit")),
        "estimate_error": err,
        "peak_device_bytes": peak,
        "out_rows": _root_rows(entries),
        "nodes": nodes,
        "shuffles": _shuffles(collector),
    }


def finish_query(collector, conf=None) -> dict:
    """End-of-action stats epilogue: build the plan.stats payload, record the
    shape into the history store (when configured + enabled), and publish the
    estimate-error/history telemetry. Never raises — the stats plane must not
    turn a finished query into a failure."""
    try:
        payload = plan_stats_payload(collector)
        collector.stats = payload
        if payload["estimate_error"] is not None:
            estimate_error_histogram().observe(payload["estimate_error"])
        if _history_enabled(conf) and payload["fingerprint"]:
            from spark_rapids_tpu.runtime import history as H
            store = H.get()
            if store is not None:
                worst = max((s.get("skew_ratio", 0) for s in
                             payload["shuffles"]), default=None)
                store.record(payload["fingerprint"], {
                    "peak_device_bytes": payload["peak_device_bytes"],
                    "estimate_bytes": payload["estimate_bytes"],
                    "out_rows": payload["out_rows"],
                    "nodes": [{"name": n["name"], "rows": n.get("rows")}
                              for n in payload["nodes"]],
                    "shuffle_skew": worst,
                })
        return payload
    except Exception:   # noqa: BLE001
        import logging
        logging.getLogger("spark_rapids_tpu.stats").warning(
            "stats epilogue failed", exc_info=True)
        return collector.stats or {}


def _history_enabled(conf) -> bool:
    if conf is None:
        return True   # caller already gated; store presence decides
    from spark_rapids_tpu import config as CFG
    return bool(conf.get(CFG.STATS_HISTORY_ENABLED))


def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


def annotated_stats_plan(collector) -> str:
    """The explain(stats=True) rendering: the executed tree with observed vs
    estimated rows per node (estimates from the shape's history entry seen at
    submit; '-' on a cold shape), selectivity and the per-node
    dispatch/transfer ledger."""
    fp = collector.footprint or {}
    prior_nodes = (fp.get("prior") or {}).get("nodes") or []
    entries = node_table(collector)
    # match history rows to this run's metered nodes positionally (ids are
    # assigned in conversion order, deterministic for an equal shape)
    metered = [e for e in entries if e["id"] is not None]
    est_by_id = {}
    for i, e in enumerate(metered):
        if i < len(prior_nodes) and prior_nodes[i].get("name") == e["name"]:
            est_by_id[e["id"]] = prior_nodes[i].get("rows")
    head = [f"Query {collector.query_id} stats"
            + (f" [{collector.description}]" if collector.description else "")]
    if fp:
        peak = (collector.memory or {}).get("peak_device_bytes")
        head.append(
            f"  footprint: estimate={_fmt_bytes(fp.get('estimate'))} "
            f"observed_peak={_fmt_bytes(peak)} "
            f"history_hit={bool(fp.get('history_hit'))} "
            f"fingerprint={fp.get('fingerprint') or '-'}")
    lines = head
    for e in entries:
        pad = "  " * e["depth"]
        line = f"{pad}*{e['name']}"
        if e["id"] is None:
            lines.append(line)
            continue
        est = est_by_id.get(e["id"])
        bits = [f"id={e['id']}",
                f"rows={e['rows'] if e['rows'] is not None else '-'}",
                f"est={est if est is not None else '-'}"]
        if e["selectivity"] is not None:
            bits.append(f"sel={e['selectivity']:.4f}")
        if e["dispatches"]:
            bits.append(f"dispatches={e['dispatches']}")
        if e["compiles"]:
            bits.append(f"compiles={e['compiles']}")
        if e["output_bytes"]:
            bits.append(f"out={_fmt_bytes(e['output_bytes'])}")
        if e["h2d_bytes"]:
            bits.append(f"h2d={_fmt_bytes(e['h2d_bytes'])}")
        if e["d2h_bytes"]:
            bits.append(f"d2h={_fmt_bytes(e['d2h_bytes'])}")
        lines.append(line + "  [" + ", ".join(bits) + "]")
    for s in _shuffles(collector):
        if "skew_ratio" in s:
            lines.append(
                f"  shuffle {s['shuffle']} (node {s['node']}): "
                f"{s['partitions']} partitions, total="
                f"{_fmt_bytes(s['total_bytes'])}, max=p{s['max_partition']} "
                f"{_fmt_bytes(s['max_bytes'])} (skew x{s['skew_ratio']})")
    return "\n".join(lines)
