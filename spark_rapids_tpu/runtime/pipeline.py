"""Pipelined executor runtime — bounded, memory-budgeted producer/consumer
stages.

The reference engine gets stage overlap for free: CUDA kernel launches are
asynchronous on streams and UCX runs an async progress thread (SURVEY.md L0),
so its pull-based iterator chain still pipelines at the hardware level. Here
XLA dispatch is synchronous per program and host arrow decode shares the
query thread, so BENCH_r06 found the engine overhead-bound — parquet decode,
device compute and exchange serialization run strictly sequentially
(docs/perf_notes.md round-6). This module supplies the missing concurrency
EXPLICITLY: physical plans are cut into segments at the existing pipeline
breakers (scan, exchange map/reduce, join build, sort, final collect) and
each segment's batch loop runs on its own worker thread, connected by
:class:`BoundedBatchQueue` edges whose capacity is counted in BYTES as well
as batches. Queued device batches are registered as spillable with the
buffer catalog, so the task-scoped OOM ladder (runtime/retry.py) can steal
them under memory pressure exactly like any other on-deck batch.

Contracts:

- **Attribution** (the PR 3 pool-thread pattern, exec/base.py): the producer
  thread re-enters the creating query's metric scope, so operator frames
  executed there keep attributing self time to their plan nodes; the
  consumer's blocking waits ride a metric-less ``node_frame`` and are
  therefore SUBTRACTED from the consuming operator's selfTime (the producer
  charges its own work on its own thread — never both).
- **Observability**: every edge owns ``queueWaitTime:<edge>`` (consumer
  blocked on an empty queue), ``queueFullTime:<edge>`` (producer blocked on
  a full one) and ``queueDepthPeak:<edge>`` metrics on the consuming exec's
  registry, plus bounded ``pipeline.stall`` span events in the event log;
  tools/profiler.py aggregates both into a per-edge stall table.
- **Admission control**: a producer NEVER holds a TpuSemaphore permit while
  blocked on a full queue (the consumer may need that permit to drain it) —
  the permit is released before the wait and re-acquired by the operators'
  usual per-batch ``acquire_if_necessary`` calls.
- **Failure**: a producer-thread error (including injected faults from
  runtime/faults.py — the queue put/get hooks check the ``pipeline.put`` /
  ``pipeline.get`` sites) cancels the stage, drains and unregisters queued
  spillable batches, and re-raises the ORIGINAL exception at the consumer's
  position in the stream. Closing the consumer early (limit, downstream
  error) releases the producer instead of leaking it on a full queue.
"""

from __future__ import annotations

import collections
import threading
import time
import typing
import weakref

from spark_rapids_tpu import config as C
from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import tracing
from spark_rapids_tpu.runtime.scheduler import check_cancel as _check_cancel

# waits shorter than this are scheduling noise, not stalls; longer ones emit
# a pipeline.stall span event, capped per queue so a persistently starved
# edge cannot flood the event log
_STALL_EVENT_THRESHOLD_NS = 5_000_000
_STALL_EVENTS_PER_QUEUE = 32


def enabled(conf) -> bool:
    """Is the pipelined executor on (spark.rapids.tpu.pipeline.enabled)?"""
    return conf is not None and conf.get(C.PIPELINE_ENABLED)


def _size_of(item) -> int:
    """Bytes one queued item accounts for: arrow tables by nbytes, device
    batches by device footprint, spillable handles by registered size."""
    nb = getattr(item, "nbytes", None)
    if isinstance(nb, int):
        return nb
    if callable(nb):
        try:
            return int(nb())
        except Exception:
            return 0
    dm = getattr(item, "device_memory_size", None)
    if callable(dm):
        try:
            return int(dm())
        except Exception:
            return 0
    size = getattr(item, "size", None)
    return size if isinstance(size, int) else 0


class BoundedBatchQueue:
    """One pipeline edge: a bounded queue counted in items AND bytes.

    The byte budget has the same progress guarantee as the scan readahead it
    replaces: one oversized item is always accepted when the queue is empty,
    so a single huge batch can never deadlock the stage. ``close()`` is the
    consumer-side cancel — it unblocks the producer (put returns False) and
    drops queued items through a cleanup callback so spillable registrations
    never leak.
    """

    def __init__(self, edge: str, depth: int, max_bytes,
                 registry: "M.MetricsRegistry | None" = None,
                 stall_metric=None):
        self.edge = edge
        self.depth = max(1, int(depth))
        self.max_bytes = max_bytes  # None / inf = unbounded bytes
        self._cond = threading.Condition()
        self._items: collections.deque = collections.deque()
        self._bytes = 0
        self._done = False
        self._error: BaseException | None = None
        self._closed = False
        self.peak_bytes = 0
        self.peak_depth = 0
        self._stall_events_left = _STALL_EVENTS_PER_QUEUE
        if registry is not None:
            self._wait = registry.metric(f"{M.QUEUE_WAIT_TIME}:{edge}",
                                         M.MODERATE)
            self._full = registry.metric(f"{M.QUEUE_FULL_TIME}:{edge}",
                                         M.MODERATE)
            self._depth_gauge = registry.metric(
                f"{M.QUEUE_DEPTH_PEAK}:{edge}", M.MODERATE)
        else:
            self._wait = self._full = self._depth_gauge = None
        self._stall = stall_metric

    # -- producer side -------------------------------------------------------
    def put(self, item, nbytes: int | None = None) -> bool:
        """Enqueue one item; blocks while the queue is over depth or byte
        budget. Returns False when the consumer closed the stage (the
        producer must stop and discard `item`)."""
        F.maybe_inject_any(f"pipeline.put.{self.edge}")
        F.maybe_inject_any("pipeline.put")
        nb = _size_of(item) if nbytes is None else nbytes
        t0 = None
        with self._cond:
            while not self._closed and self._items and (
                    len(self._items) >= self.depth
                    or (self.max_bytes is not None
                        and self._bytes + nb > self.max_bytes)):
                # cooperative cancellation: a producer parked on a full edge
                # must observe session.cancel()/deadline expiry — the raise
                # propagates through produce()'s fail() path so the consumer
                # sees the SAME typed error (runtime/scheduler.py)
                _check_cancel()
                if t0 is None:
                    t0 = time.perf_counter_ns()
                    self._release_device_permit()
                self._cond.wait(0.05)
            if self._closed:
                return False
            self._items.append((item, nb))
            self._bytes += nb
            self.peak_bytes = max(self.peak_bytes, self._bytes)
            self.peak_depth = max(self.peak_depth, len(self._items))
            if self._depth_gauge is not None:
                self._depth_gauge.set(self.peak_depth)
            self._cond.notify_all()
        # live process-wide occupancy (the STATS endpoint's pipeline gauges);
        # outside the queue lock — the gauge has its own
        M.add_gauge("pipeline.queued.batches", 1)
        M.add_gauge("pipeline.queued.bytes", nb)
        if t0 is not None:
            dt = time.perf_counter_ns() - t0
            if self._full is not None:
                self._full.add(dt)
            self._maybe_stall_event("producer", dt)
        return True

    def finish(self) -> None:
        with self._cond:
            self._done = True
            self._cond.notify_all()

    def fail(self, exc: BaseException) -> None:
        """Producer error: queued items still drain in order, then the
        consumer's next get() re-raises `exc`."""
        with self._cond:
            self._error = exc
            self._done = True
            self._cond.notify_all()

    # -- consumer side -------------------------------------------------------
    def get(self):
        """('item', x) or ('done', None); re-raises the producer's error
        once every item queued before it was consumed."""
        F.maybe_inject_any(f"pipeline.get.{self.edge}")
        F.maybe_inject_any("pipeline.get")
        t0 = None
        err = None
        with self._cond:
            while (not self._items and not self._done and not self._closed):
                # symmetric to put(): a consumer starved on an empty queue
                # observes cancellation directly (its finally closes the
                # edge, which unblocks and stops the producer)
                _check_cancel()
                if t0 is None:
                    t0 = time.perf_counter_ns()
                    # symmetric to put(): a consumer blocked on an empty
                    # queue must not sit on a permit its producer needs
                    self._release_device_permit()
                self._cond.wait(0.05)
            if self._items:
                item, nb = self._items.popleft()
                self._bytes -= nb
                self._cond.notify_all()
                M.add_gauge("pipeline.queued.batches", -1)
                M.add_gauge("pipeline.queued.bytes", -nb)
                out = ("item", item)
            elif self._error is not None:
                err = self._error
                out = None
            else:
                out = ("done", None)
        if t0 is not None:
            dt = time.perf_counter_ns() - t0
            if self._wait is not None:
                self._wait.add(dt)
            if self._stall is not None:
                self._stall.add(dt)
            self._maybe_stall_event("consumer", dt)
        if out is None:
            raise err
        return out

    def close(self, cleanup=None) -> None:
        """Cancel the edge: producer puts start returning False and queued
        items are dropped through `cleanup` (idempotent)."""
        with self._cond:
            self._closed = True
            items = list(self._items)
            self._items.clear()
            self._bytes = 0
            self._cond.notify_all()
        for item, nb in items:
            M.add_gauge("pipeline.queued.batches", -1)
            M.add_gauge("pipeline.queued.bytes", -nb)
            if cleanup is not None:
                try:
                    cleanup(item)
                except Exception:   # noqa: BLE001 — cleanup must not mask
                    pass

    # -- internals -----------------------------------------------------------
    @staticmethod
    def _release_device_permit() -> None:
        # never block on a full queue holding a device permit: with
        # concurrentTpuTasks=N, N blocked producers would starve the very
        # consumers that must drain them (deadlock). Operators re-acquire
        # per batch via acquire_if_necessary, so dropping it here is safe.
        from spark_rapids_tpu.runtime.semaphore import TpuSemaphore
        TpuSemaphore.get().release_current()

    def _maybe_stall_event(self, side: str, dt_ns: int) -> None:
        if dt_ns < _STALL_EVENT_THRESHOLD_NS or self._stall_events_left <= 0:
            return
        self._stall_events_left -= 1
        tracing.span_event("pipeline.stall", edge=self.edge, side=side,
                           wait_ms=round(dt_ns / 1e6, 3))


def _spillable_ok(batch) -> bool:
    """Only plain fixed-layout device columns round-trip through the spill
    tiers; anything else (list vectors, host bridges) stays unregistered and
    is bounded by the queue's byte budget alone."""
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.vector import TpuColumnVector
    return (isinstance(batch, ColumnarBatch)
            and all(type(c) is TpuColumnVector for c in batch.columns))


def stage_iterator(gen, *, edge: str, conf=None, registry=None, node_id=None,
                   self_time_metric=None, stall_metric=None,
                   spillable: bool = False, depth: int | None = None,
                   max_bytes=None, _queue_cb=None) -> typing.Iterator:
    """Run `gen` on its own worker thread behind a BoundedBatchQueue and
    return an order-preserving iterator over its items.

    - `depth` / `max_bytes` default to pipeline.queueDepth /
      pipeline.maxQueueBytes (the byte cap additionally shrinks to the spill
      catalog's free host headroom — runtime/memory.host_prefetch_budget).
    - `spillable=True` registers device batches with the buffer catalog
      while queued (under the OOM split-retry ladder, so an over-budget
      registration spills others and may split the batch into pieces).
    - `node_id`/`self_time_metric`: plan-node attribution — producer work is
      charged there on the worker thread, consumer waits are subtracted from
      the enclosing operator frame.
    - `stall_metric`: extra metric accumulating consumer wait ns (the scan
      decode edge feeds readaheadStallTime through this).
    """
    from spark_rapids_tpu.exec.base import TaskContext

    if depth is None:
        depth = (conf.get(C.PIPELINE_QUEUE_DEPTH) if conf is not None
                 else C.PIPELINE_QUEUE_DEPTH.default)
    if max_bytes is None:
        cap = (conf.get(C.PIPELINE_MAX_QUEUE_BYTES) if conf is not None
               else C.PIPELINE_MAX_QUEUE_BYTES.default)
        from spark_rapids_tpu.runtime.memory import host_prefetch_budget
        max_bytes = host_prefetch_budget(cap)
    q = BoundedBatchQueue(edge, depth, max_bytes, registry=registry,
                          stall_metric=stall_metric)
    if _queue_cb is not None:
        _queue_cb(q)
    collector = M.current_collector()
    frame_producer = node_id is not None or self_time_metric is not None

    def produce():
        from spark_rapids_tpu.runtime import memory as mem
        from spark_rapids_tpu.runtime import retry as R
        it = iter(gen)
        try:
            # one span per segment run: the srt-pipe-<edge> thread becomes
            # its own lane in the merged Perfetto timeline (trace id via the
            # re-entered collector scope, or the executor's process trace)
            with M.collector_context(collector), TaskContext(), \
                    tracing.span(f"pipeline.{edge}"):
                while True:
                    # segment batch loops are the issue's canonical
                    # cancellation points: one check per produced item
                    _check_cancel()
                    if frame_producer:
                        with M.node_frame(node_id, self_time_metric):
                            try:
                                item = next(it)
                            except StopIteration:
                                break
                    else:
                        try:
                            item = next(it)
                        except StopIteration:
                            break
                    if spillable and _spillable_ok(item):
                        ok = True
                        # heap-profiler attribution: queued device batches
                        # are held by the queue edge, not the producing
                        # operator (which already closed its frame)
                        with mem.alloc_site("pipeline.queue"):
                            sbs = R.register_with_retry(
                                item, mem.ACTIVE_ON_DECK_PRIORITY, conf=conf)
                        for sb in sbs:
                            if ok:
                                ok = q.put(sb, sb.size)
                            if not ok:
                                sb.close()
                        if not ok:
                            return
                    elif not q.put(item):
                        return
                q.finish()
        except BaseException as e:   # noqa: BLE001 — re-raised at consumer
            q.fail(e)
        finally:
            # run the source generator's finalizers ON THIS THREAD even when
            # the consumer cancelled mid-stream (shuffle read accounting,
            # nested stage teardown, spillable closes all live in them)
            close = getattr(it, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:   # noqa: BLE001
                    pass

    t = threading.Thread(target=produce, daemon=True,
                         name=f"srt-pipe-{edge}")

    def consume():
        from spark_rapids_tpu.runtime.memory import SpillableColumnarBatch
        try:
            while True:
                # metric-less frame: the wait is charged by the producer's
                # own frames on its thread; the enclosing operator frame
                # subtracts this dt from its selfTime
                with M.node_frame(node_id, None):
                    kind, item = q.get()
                if kind == "done":
                    return
                if isinstance(item, SpillableColumnarBatch):
                    batch = item.get_batch()
                    item.close()
                    yield batch
                else:
                    yield item
        finally:
            q.close(_cleanup_item)

    out = consume()
    # a consumer that is never started (abandoned before the first next())
    # skips its finally block entirely — the GC finalizer still cancels the
    # queue so the producer can never idle forever against a full edge
    weakref.finalize(out, q.close, _cleanup_item)
    t.start()
    return out


def _cleanup_item(item) -> None:
    close = getattr(item, "close", None)
    if close is not None:
        close()
