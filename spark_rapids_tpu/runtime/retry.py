"""Task-scoped OOM retry — the RmmRapidsRetryIterator / withRetry analog.

Reference: RmmRapidsRetryIterator.scala — when a device allocation fails
mid-operator the reference does NOT kill the task: the operator rolls its
state back to a checkpoint (`withRestoreOnRetry` + the `Retryable` trait),
the allocator synchronously spills lower-priority buffers, and the attempt
re-runs; a `SplitAndRetryOOM` additionally splits the input batch in half
before retrying (`withRetry` + `splitSpillableInHalfByRows`).

TPU twist: XLA exposes no alloc-failure callback to trap (SURVEY.md §7), so
the "allocation failure" here is the proactive budget check in
runtime/memory.py raising `DeviceOomError` under strict mode
(spark.rapids.tpu.memory.hbm.strictBudget), or an injected fault from
runtime/faults.py. The recovery ladder per retryable OOM:

  1. record it (global resilience counters in runtime/metrics.py + an
     ``oom.retry`` span event in runtime/tracing.py),
  2. synchronously spill lower-priority buffers down to half the device
     budget,
  3. split the input batch in half and re-queue the halves — down to
     spark.rapids.tpu.memory.retry.splitFloorBytes / a 2-row floor and at
     most spark.rapids.tpu.memory.retry.maxSplits times per input,
  4. when unsplittable, allow ONE spill-only retry, then re-raise.

Splitting is EAGER (every retryable OOM on a splittable input splits): with
no rollback-to-checkpoint malloc underneath, halving the working set is the
one lever that reliably changes the outcome, and halves land in existing
power-of-two capacity buckets (columnar/vector.bucket_capacity) so no new
XLA programs compile. When nothing OOMs the framework is a try/except and a
fault-registry flag check per attempt — no measurable overhead.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector, bucket_capacity
from spark_rapids_tpu.runtime import metrics as M
from spark_rapids_tpu.runtime import tracing


def _rebuild_oom(cls, msg, requested, budget, spillable_bytes, pinned_bytes,
                 injected):
    return cls(msg, requested=requested, budget=budget,
               spillable_bytes=spillable_bytes, pinned_bytes=pinned_bytes,
               injected=injected)


class DeviceOomError(RuntimeError):
    """Device (HBM budget) OOM — the RetryOOM analog. ``retryable`` marks it
    recoverable by the with_retry ladder: release this attempt's work, spill,
    (maybe) split the input, re-run. Pickles losslessly (context fields and
    the concrete subclass preserved) so the serving endpoint can ship an
    unrecovered OOM to a remote client typed."""

    retryable = True

    def __init__(self, msg: str, *, requested: int = 0, budget: int = 0,
                 spillable_bytes: int = 0, pinned_bytes: int = 0,
                 injected: bool = False):
        super().__init__(msg)
        self.requested = requested
        self.budget = budget
        self.spillable_bytes = spillable_bytes
        self.pinned_bytes = pinned_bytes
        self.injected = injected

    def __reduce__(self):
        return (_rebuild_oom, (type(self), str(self), self.requested,
                               self.budget, self.spillable_bytes,
                               self.pinned_bytes, self.injected))


class SplitAndRetryOom(DeviceOomError):
    """Spilling alone cannot satisfy the attempt; the input must be split
    before the retry (reference SplitAndRetryOOM). Raised against an
    unsplittable input it propagates immediately."""


class SpillCapacityError(DeviceOomError):
    """The disk-spill tier ran out of capacity (ENOSPC from the spill
    writer, or the injected ``disk_full`` fault). Typed and RETRYABLE: a
    full disk mid-spill is memory pressure, not corruption — the with_retry
    ladder responds exactly as it does to a device OOM (release this
    attempt's buffers, spill what still fits elsewhere, split the input),
    instead of letting a raw OSError escape the operator. Pickles
    losslessly like its base so the serving endpoint can ship it typed."""

@contextlib.contextmanager
def with_restore_on_retry(*checkpointables):
    """Snapshot restorable operator state (objects with ``checkpoint()`` /
    ``restore()``) before an attempt; a retryable OOM rolls the state back
    before propagating to the surrounding with_retry ladder, so the re-run
    never double-applies side effects."""
    for c in checkpointables:
        c.checkpoint()
    try:
        yield
    except DeviceOomError as e:
        if getattr(e, "retryable", False):
            for c in checkpointables:
                c.restore()
        raise


# -- batch splitting ----------------------------------------------------------

def split_batch(batch: ColumnarBatch, floor_bytes: int = 0):
    """[first_half, second_half] by rows, or None when the batch cannot be
    split: fewer than 2 rows, halves would undershoot ``floor_bytes``, or a
    column type without row-slicing support (list vectors)."""
    n = batch.num_rows
    if n < 2:
        return None
    if batch.columns:
        if batch.device_memory_size() // 2 < floor_bytes:
            return None
        if any(type(c) is not TpuColumnVector for c in batch.columns):
            return None
    mid = n // 2
    return [_slice_rows(batch, 0, mid), _slice_rows(batch, mid, n)]


def _slice_rows(batch: ColumnarBatch, start: int, stop: int) -> ColumnarBatch:
    n = stop - start
    cap = bucket_capacity(n)
    idx = jnp.arange(cap, dtype=jnp.int32)
    cols = []
    for c in batch.columns:
        end = min(start + cap, c.capacity)
        v = c.data[start:end]
        m = c.validity[start:end]
        pad = cap - (end - start)
        if pad:
            v = jnp.concatenate(
                [v, jnp.full((pad,), c.dtype.default_value(), v.dtype)])
            m = jnp.concatenate([m, jnp.zeros((pad,), m.dtype)])
        m = m & (idx < n)
        cols.append(TpuColumnVector(c.dtype, v, m, c.dictionary))
    return ColumnarBatch(cols, n, batch.schema,
                         metadata=getattr(batch, "metadata", None))


# -- the ladder ---------------------------------------------------------------

def _default_catalog():
    from spark_rapids_tpu.runtime.memory import DeviceManager
    return DeviceManager.get().catalog


def _spill_for_retry(catalog=None) -> int:
    cat = catalog if catalog is not None else _default_catalog()
    spilled = cat.synchronous_spill(cat.device_budget // 2)
    if spilled:
        M.resilience_add(M.OOM_SPILL_BYTES, spilled)
    return spilled


def _record_oom(site, oom, batch=None):
    M.resilience_add(M.NUM_OOM_RETRIES)
    tracing.span_event(
        "oom.retry", site=site,
        rows=(batch.num_rows if batch is not None and batch.columns else None),
        injected=getattr(oom, "injected", False))
    # multi-tenant escalation hook (runtime/scheduler.py): fair-share
    # demotion of an over-share victim + bounded admission re-check, so one
    # query's OOM ladder leans on peers' SPILLABLE state instead of
    # splitting an under-share query's own batches
    from spark_rapids_tpu.runtime import scheduler as SCHED
    SCHED.on_oom_retry()


def _record_split(site, batch, halves):
    M.resilience_add(M.NUM_OOM_SPLIT_RETRIES)
    tracing.span_event("oom.split", site=site, rows=batch.num_rows,
                       into=[h.num_rows for h in halves])


def _attempt(site, call):
    """Run one attempt under the fault scope for `site` (so catalog
    registrations inside attribute to it) with an attempt-level injection
    checkpoint first — deterministic specs count attempts, not internal
    allocation calls."""
    from spark_rapids_tpu.runtime import faults as F
    if site is None:
        return call()
    with F.scope(site):
        F.maybe_inject("oom", site)
        return call()


def _resolve_limits(conf, max_splits, split_floor_bytes):
    from spark_rapids_tpu import config as C
    if conf is not None:
        if max_splits is None:
            max_splits = conf.get(C.RETRY_MAX_SPLITS)
        if split_floor_bytes is None:
            split_floor_bytes = conf.get(C.RETRY_SPLIT_FLOOR_BYTES)
    if max_splits is None:
        max_splits = C.RETRY_MAX_SPLITS.default
    if split_floor_bytes is None:
        split_floor_bytes = C.RETRY_SPLIT_FLOOR_BYTES.default
    return max_splits, split_floor_bytes


def with_retry(inputs, fn, *, conf=None, scope=None, splittable=True,
               max_splits=None, split_floor_bytes=None, catalog=None):
    """Generator: run ``fn`` over each input batch, recovering from retryable
    device OOMs by spill + split-and-retry. Yields fn's return values — one
    per input normally, several when an input was split (callers must accept
    piece-granularity results; every wired operator does: split probe/agg/
    partition pieces compose to the unsplit answer).

    ``inputs``: iterable of ColumnarBatch or SpillableColumnarBatch (a
    spillable input is acquired per attempt and closed after its last piece
    succeeds, keeping it spillable between attempts)."""
    from spark_rapids_tpu.runtime.memory import SpillableColumnarBatch
    max_splits, split_floor_bytes = _resolve_limits(conf, max_splits,
                                                    split_floor_bytes)
    site_default = scope
    from spark_rapids_tpu.runtime import scheduler as SCHED
    for item in inputs:
        pending = [(item, False)]   # (piece, already-spill-retried)
        splits_used = 0
        while pending:
            # a cancelled/deadlined query must not be kept alive by its own
            # recovery ladder: the check runs before every attempt so
            # cancellation wins over (and is never absorbed by) retries
            SCHED.check_cancel()
            cur, retried = pending.pop(0)
            spillable = isinstance(cur, SpillableColumnarBatch)
            batch = cur.get_batch() if spillable else cur
            try:
                result = _attempt(site_default, lambda: fn(batch))
            except DeviceOomError as oom:
                if not getattr(oom, "retryable", False):
                    raise
                from spark_rapids_tpu.runtime import faults as F
                site = site_default or F.current_scope() or "<unscoped>"
                _record_oom(site, oom, batch)
                _spill_for_retry(catalog)
                halves = None
                if splittable and splits_used < max_splits:
                    halves = split_batch(batch, floor_bytes=split_floor_bytes)
                if halves is not None:
                    splits_used += 1
                    _record_split(site, batch, halves)
                    if spillable:
                        cur.close()
                    pending[:0] = [(h, False) for h in halves]
                    continue
                if isinstance(oom, SplitAndRetryOom) or retried:
                    raise   # ladder exhausted
                pending.insert(0, (cur, True))   # one spill-only retry
                continue
            if spillable:
                cur.close()
            yield result


def call_with_retry(thunk, *, scope=None, max_retries=2, catalog=None):
    """Run a zero-arg callable under spill-only OOM retry — the
    withRetryNoSplit analog, for work that cannot be split: single-batch
    registration, merge aggregation of accumulated partials, a whole-batch
    total sort."""
    from spark_rapids_tpu.runtime import scheduler as SCHED
    attempt = 0
    while True:
        SCHED.check_cancel()   # cancellation wins over spill-only retries too
        try:
            return _attempt(scope, thunk)
        except DeviceOomError as oom:
            if not getattr(oom, "retryable", False) or attempt >= max_retries:
                raise
            attempt += 1
            from spark_rapids_tpu.runtime import faults as F
            _record_oom(scope or F.current_scope() or "<unscoped>", oom)
            _spill_for_retry(catalog)


def register_with_retry(batch, priority, *, conf=None, scope=None,
                        catalog=None, spill_callback=None, max_splits=None,
                        split_floor_bytes=None):
    """Register ``batch`` into the spill catalog as one or more
    SpillableColumnarBatch pieces, recovering from a strict-budget
    DeviceOomError by spilling and splitting (a failed registration rolls
    back cleanly in the catalog, so re-attempts are idempotent)."""
    from spark_rapids_tpu.runtime.memory import SpillableColumnarBatch

    def register(b):
        return SpillableColumnarBatch(b, priority, catalog=catalog,
                                      spill_callback=spill_callback)

    return list(with_retry([batch], register, conf=conf, scope=scope,
                           catalog=catalog, max_splits=max_splits,
                           split_floor_bytes=split_floor_bytes))
