"""Device & memory runtime — HBM budget, tiered spill stores, spillable batches.

Reference (SURVEY.md components #4-#7):
- GpuDeviceManager.scala:36,125,204 — acquire device, init RMM pool, pinned host pool.
- RapidsBufferCatalog.scala:40,156 / RapidsBufferStore.scala:41 — catalog keyed by
  buffer id over chained tiers device→host→disk with `synchronousSpill`:145.
- DeviceMemoryEventHandler.scala:42 — RMM alloc-failure callback triggering spill.
- SpillableColumnarBatch.scala:29 / SpillPriorities.scala:26.

TPU twist: XLA has no alloc-failure callback to trap (SURVEY.md §7 hard parts), so the
budget is enforced *proactively*: every batch registered with the catalog is counted
against an HBM budget, and registration spills lower-priority buffers synchronously
until the new buffer fits. Spill tiers are HBM → host numpy → disk pickle; "pinned"
staging is plain host RAM (TPU DMA runs from pageable host memory via PJRT).
"""

from __future__ import annotations

import contextlib
import dataclasses
import heapq
import itertools
import os
import pickle
import tempfile
import threading
import time
import typing

import numpy as np
import jax
import jax.numpy as jnp

from spark_rapids_tpu import config as C
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.vector import TpuColumnVector
from spark_rapids_tpu.runtime import eventlog as EL
from spark_rapids_tpu.runtime import faults as F
from spark_rapids_tpu.runtime import tracing as TR
from spark_rapids_tpu.runtime.arm import LeakTracker
from spark_rapids_tpu.runtime.retry import DeviceOomError, SpillCapacityError

# -- spill priorities (reference SpillPriorities.scala:26) ---------------------
# Lower value spills FIRST.
OUTPUT_FOR_SHUFFLE_INITIAL_PRIORITY = -1000.0   # shuffle output: spill early
ACTIVE_ON_DECK_PRIORITY = 100.0                 # batches queued for processing
# batches an operator is actively coalescing/probing spill LAST (reference:
# ACTIVE_BATCHING_PRIORITY = ACTIVE_ON_DECK_PRIORITY + 100)
ACTIVE_BATCHING_PRIORITY = 200.0


class TierEnum:
    DEVICE = "DEVICE"
    HOST = "HOST"
    DISK = "DISK"


# -- allocation-site attribution ----------------------------------------------
# Every catalogued buffer is tagged with the subsystem that registered it
# ("joins.build", "exchange.map", "pipeline.queue", ...) plus the ambient
# plan-node id, so the heap profiler can say WHO holds device memory, not
# just how much is held. The label resolves through a dedicated thread-local
# first (explicit alloc_site() blocks at registration call sites), then the
# fault-injection scope (runtime/retry.py already wraps every retry attempt
# in F.scope(site), which names exactly the subsystems we want), and only
# then the unattributed bucket.

UNATTRIBUTED_SITE = "catalog.add_batch"

_alloc_tls = threading.local()


@contextlib.contextmanager
def alloc_site(site: str, retained: bool = False):
    """Tag catalog registrations inside the block with allocation site
    `site`. ``retained=True`` marks the buffers as intentionally outliving
    their query (DataFrame cache partitions), exempting them from the
    end-of-query leak detector while keeping their query tag for the
    fair-share demotion accounting."""
    prev = getattr(_alloc_tls, "site", None)
    _alloc_tls.site = (site, retained)
    try:
        yield
    finally:
        _alloc_tls.site = prev


def current_alloc_site() -> "tuple[str, bool]":
    """(site, retained) for a registration happening now on this thread."""
    v = getattr(_alloc_tls, "site", None)
    if v is not None:
        return v
    s = F.current_scope()
    if s:
        return s, False
    return UNATTRIBUTED_SITE, False


class MemoryLeakError(RuntimeError):
    """The end-of-query leak detector found buffers still tagged to a
    finished query and ``memory.leak.strict`` is on. Non-strict mode only
    emits the ``memory.leak`` event + resilience counter and reclaims the
    buffers; strict mode additionally fails the query so tests can turn
    any leak into a hard failure."""


class BufferClosedError(RuntimeError):
    """A spillable buffer was acquired after close()/remove() — raised as a
    dedicated type so callers that legitimately race a concurrent release
    (broadcast host-bridge rebuild) can retry it without masking unrelated
    assertion failures."""


class SpillCorruptionError(RuntimeError):
    """A disk-tier spill payload failed its CRC on unspill
    (memory.spill.checksum.enabled). Shuffle readers treat this exactly
    like a fetch failure — invalidate the map outputs, recompute — instead
    of decoding silently corrupt rows (the Spark shuffle-checksum →
    FetchFailed contract, SPARK-35275 analog). ``retryable`` marks a
    resubmission safe at the serving boundary (the recompute ladder already
    ran server-side); single-arg construction keeps the default pickle
    round-trip lossless for the endpoint's error channel."""

    retryable = True


@dataclasses.dataclass
class HostColumn:
    """Host image of one TpuColumnVector (the RapidsHostColumnVector analog)."""
    dtype: T.DataType
    data: np.ndarray
    validity: np.ndarray
    dictionary: typing.Any  # pyarrow StringArray or None


@dataclasses.dataclass
class HostBatch:
    columns: list
    num_rows: int
    schema: typing.Any
    metadata: typing.Any = None   # scan provenance (input_file_name family)

    def nbytes(self) -> int:
        out = 0
        for c in self.columns:
            out += c.data.nbytes + c.validity.nbytes
            if c.dictionary is not None:
                out += c.dictionary.nbytes
        return out


def batch_to_host(batch: ColumnarBatch) -> HostBatch:
    cols = [HostColumn(c.dtype, np.asarray(c.data), np.asarray(c.validity), c.dictionary)
            for c in batch.columns]
    return HostBatch(cols, batch.num_rows, batch.schema,
                     getattr(batch, "metadata", None))


def host_to_batch(hb: HostBatch) -> ColumnarBatch:
    cols = [TpuColumnVector(c.dtype, jnp.asarray(c.data), jnp.asarray(c.validity),
                            c.dictionary) for c in hb.columns]
    return ColumnarBatch(cols, hb.num_rows, hb.schema,
                         metadata=getattr(hb, "metadata", None))


class RapidsBuffer:
    """One catalogued buffer; knows which tier currently holds it
    (reference RapidsBufferStore.RapidsBufferBase)."""

    __slots__ = ("buffer_id", "tier", "priority", "size", "_device", "_host",
                 "_path", "_handle", "spill_callback", "query", "_crc",
                 "site", "node", "retained", "_disk_len")

    def __init__(self, buffer_id: int, batch: ColumnarBatch, priority: float,
                 spill_callback=None, query: str | None = None,
                 site: str = UNATTRIBUTED_SITE, node: int | None = None,
                 retained: bool = False):
        self.buffer_id = buffer_id
        self.tier = TierEnum.DEVICE
        self.priority = priority
        self.size = batch.device_memory_size()
        self._device: ColumnarBatch | None = batch
        self._host: HostBatch | None = None
        self._path: str | None = None
        self._handle = None          # (file, offset, len) in the direct store
        self.spill_callback = spill_callback
        # owning query (ambient collector at registration): the multi-tenant
        # scheduler's per-query accounting + fair-share demotion key
        self.query = query
        self._crc = None             # disk-tier payload checksum
        # allocation-site attribution (heap profiler): subsystem label +
        # ambient plan-node id; retained buffers outlive their query on
        # purpose (cache partitions) and are exempt from leak detection
        self.site = site
        self.node = node
        self.retained = retained
        self._disk_len = 0           # bytes held in the disk tier


class _SiteStats:
    """Process-lifetime accounting for one allocation site: live device
    bytes (maintained across spill/unspill transitions), the site's own
    device high-water mark, and cumulative alloc/free traffic."""

    __slots__ = ("live_device", "peak_device", "cumulative", "allocs",
                 "frees")

    def __init__(self):
        self.live_device = 0
        self.peak_device = 0
        self.cumulative = 0
        self.allocs = 0
        self.frees = 0


class BufferCatalog:
    """Tiered buffer catalog with proactive budget-driven spill.

    Reference: RapidsBufferCatalog.scala:40 (registry) + RapidsBufferStore.scala:145
    (`synchronousSpill`) + DeviceMemoryEventHandler (OOM→spill). Here the device tier's
    budget check runs at registration time instead of inside a malloc callback.
    """

    def __init__(self, device_budget: int, host_budget: int, spill_dir: str | None = None,
                 unspill: bool = False, oom_dump_dir: str | None = None,
                 direct_spill: bool = False, direct_batch_bytes: int = 64 << 20,
                 strict_budget: bool = True, spill_checksum: bool = True,
                 watermark_interval_bytes: int = 16 << 20,
                 profile_top_k: int = 10):
        self.device_budget = device_budget
        self.host_budget = host_budget
        # CRC disk-tier spill payloads and verify on unspill
        # (memory.spill.checksum.enabled)
        self._spill_checksum = spill_checksum
        # strict: registration that cannot spill back under budget raises a
        # retryable DeviceOomError (spark.rapids.tpu.memory.hbm.strictBudget)
        # instead of silently leaving the device tier over budget
        self._strict = strict_budget
        self._spill_dir = spill_dir
        self._unspill = unspill
        self._oom_dump_dir = oom_dump_dir
        self._direct_spill = direct_spill
        self._direct_batch_bytes = direct_batch_bytes
        self._direct_store = None  # lazily created GDS-analog batch store
        self._lock = threading.RLock()
        self._buffers: dict[int, RapidsBuffer] = {}
        self._ids = itertools.count(1)
        self.device_bytes = 0
        self.host_bytes = 0
        # metrics (reference GpuMetric spill counters)
        self.spilled_to_host_bytes = 0
        self.spilled_to_disk_bytes = 0
        # allocation-site heap profiler: per-site process-lifetime stats,
        # per-query peak/cumulative breakdowns (popped by finish_query so
        # long-lived serving processes stay bounded), the process device
        # high-water mark, and the last watermark sample emitted into the
        # event log / Chrome counter track
        self.disk_bytes = 0
        self.watermark_bytes = 0
        self._watermark_interval = max(1, int(watermark_interval_bytes))
        self._top_k = max(1, int(profile_top_k))
        self._site_stats: dict[str, _SiteStats] = {}
        self._query_mem: dict[str, dict] = {}
        self._last_sample: "tuple | None" = None
        self._last_sample_watermark = 0

    # -- registration --------------------------------------------------------
    def add_batch(self, batch: ColumnarBatch, priority: float = ACTIVE_ON_DECK_PRIORITY,
                  spill_callback=None) -> int:
        # fault-injection checkpoint (runtime/faults.py): chaos specs target
        # either the ambient operator scope ("joins.build" …) or the bare
        # registration site
        F.maybe_inject("oom", F.current_scope() or "catalog.add_batch")
        from spark_rapids_tpu.runtime import metrics as M
        site, retained = current_alloc_site()
        with self._lock:
            bid = next(self._ids)
            buf = RapidsBuffer(bid, batch, priority, spill_callback,
                               query=M.current_query_id(), site=site,
                               node=M.current_node(), retained=retained)
            self._buffers[bid] = buf
            self.device_bytes += buf.size
            try:
                self._ensure_device_budget(exclude=bid, strict=self._strict)
            except DeviceOomError:
                # roll back: a failed registration must not leave a phantom
                # buffer charged against the budget — the retry framework
                # re-attempts registration from scratch
                del self._buffers[bid]
                self.device_bytes -= buf.size
                raise
            self._account_alloc(buf)
            return bid

    # -- allocation-site heap accounting (under self._lock) ------------------
    def _account_alloc(self, buf: RapidsBuffer):
        st = self._site_stats.get(buf.site)
        if st is None:
            st = self._site_stats[buf.site] = _SiteStats()
        st.live_device += buf.size
        if st.live_device > st.peak_device:
            st.peak_device = st.live_device
        st.cumulative += buf.size
        st.allocs += 1
        if buf.query is not None:
            qm = self._query_mem.get(buf.query)
            if qm is None:
                # bound the per-query map: queries finished through
                # session._run_action pop their entry; out-of-band
                # registrations (tests driving collectors by hand) must not
                # grow it forever in a long-lived process
                if len(self._query_mem) > 512:
                    self._query_mem.pop(next(iter(self._query_mem)))
                qm = self._query_mem[buf.query] = {
                    "live": 0, "peak": 0, "cum": 0, "allocs": 0, "sites": {}}
            qm["live"] += buf.size
            qm["peak"] = max(qm["peak"], qm["live"])
            qm["cum"] += buf.size
            qm["allocs"] += 1
            # per-(query, site): [live_device, peak_device, cumulative,
            # plan-node ids seen]
            s = qm["sites"].get(buf.site)
            if s is None:
                s = qm["sites"][buf.site] = [0, 0, 0, set()]
            s[0] += buf.size
            s[1] = max(s[1], s[0])
            s[2] += buf.size
            if buf.node is not None:
                s[3].add(buf.node)
        self._maybe_sample()

    def _account_device_delta(self, buf: RapidsBuffer, delta: int):
        """A buffer moved into (+) or out of (-) the device tier without
        being allocated or freed (spill, unspill)."""
        st = self._site_stats.get(buf.site)
        if st is not None:
            st.live_device += delta
            if delta > 0 and st.live_device > st.peak_device:
                st.peak_device = st.live_device
        if buf.query is not None:
            qm = self._query_mem.get(buf.query)
            if qm is not None:
                qm["live"] += delta
                if delta > 0:
                    qm["peak"] = max(qm["peak"], qm["live"])
                s = qm["sites"].get(buf.site)
                if s is not None:
                    s[0] += delta
                    if delta > 0:
                        s[1] = max(s[1], s[0])

    def _account_free(self, buf: RapidsBuffer):
        st = self._site_stats.get(buf.site)
        if st is not None:
            st.frees += 1
        if buf.tier == TierEnum.DEVICE:
            self._account_device_delta(buf, -buf.size)
        self._maybe_sample()

    def _maybe_sample(self):
        """Watermark-timeline sample (under self._lock): update the process
        device high-water mark, and when telemetry is on emit a
        ``memory.watermark`` event + a Chrome counter-track sample — on the
        first allocation, whenever the watermark grows by the configured
        interval, and whenever any tier's occupancy moved by the interval
        since the last sample. Bounded: monotone growth emits
        O(peak / interval) samples, not one per allocation."""
        if self.device_bytes > self.watermark_bytes:
            self.watermark_bytes = self.device_bytes
        if not (EL.enabled() or TR.spans_enabled()):
            return
        cur = (self.device_bytes, self.host_bytes, self.disk_bytes)
        if (self._last_sample is not None
                and self.watermark_bytes - self._last_sample_watermark
                < self._watermark_interval
                and all(abs(a - b) < self._watermark_interval
                        for a, b in zip(cur, self._last_sample))):
            return
        self._last_sample = cur
        self._last_sample_watermark = self.watermark_bytes
        top = sorted(((s, st.live_device)
                      for s, st in self._site_stats.items()
                      if st.live_device > 0),
                     key=lambda kv: -kv[1])[:self._top_k]
        if EL.enabled():
            EL.emit("memory.watermark", device_bytes=cur[0],
                    host_bytes=cur[1], disk_bytes=cur[2],
                    watermark_bytes=self.watermark_bytes,
                    budget=self.device_budget, sites=dict(top))
        TR.counter("memory", {"device_bytes": cur[0], "host_bytes": cur[1],
                              "disk_bytes": cur[2]})

    def _ensure_device_budget(self, exclude: int | None = None,
                              strict: bool = False):
        if self.device_bytes <= self.device_budget:
            return
        # spill lowest-priority device buffers first (reference spill-priority queue)
        heap = [(b.priority, b.buffer_id) for b in self._buffers.values()
                if b.tier == TierEnum.DEVICE and b.buffer_id != exclude]
        heapq.heapify(heap)
        while self.device_bytes > self.device_budget and heap:
            _, bid = heapq.heappop(heap)
            self._spill_device_buffer(self._buffers[bid])
        if self.device_bytes > self.device_budget:
            # nothing left to spill and still over budget: the OOM analog —
            # dump allocator state for postmortems (reference
            # spark.rapids.memory.gpu.oomDumpDir / DeviceMemoryEventHandler)
            self._dump_oom_state(exclude)
            if strict:
                spillable, pinned = self._device_breakdown(exclude)
                new_sz = (self._buffers[exclude].size
                          if exclude in self._buffers else 0)
                raise DeviceOomError(
                    f"device tier over budget after spill exhaustion: "
                    f"{self.device_bytes}B > budget {self.device_budget}B "
                    f"(new buffer {new_sz}B, other device buffers: "
                    f"spillable {spillable}B, pinned>=ACTIVE_BATCHING "
                    f"{pinned}B)",
                    requested=new_sz, budget=self.device_budget,
                    spillable_bytes=spillable, pinned_bytes=pinned)

    def _device_breakdown(self, exclude=None):
        """(spillable, pinned) device-tier byte totals excluding `exclude` —
        pinned counts ACTIVE_BATCHING_PRIORITY and above (batches an
        operator is actively consuming spill last)."""
        spillable = pinned = 0
        for b in self._buffers.values():
            if b.tier != TierEnum.DEVICE or b.buffer_id == exclude:
                continue
            if b.priority >= ACTIVE_BATCHING_PRIORITY:
                pinned += b.size
            else:
                spillable += b.size
        return spillable, pinned

    def _dump_oom_state(self, exclude):
        if not self._oom_dump_dir:
            return
        import datetime
        import os
        import time as _time
        # rate-limit: a workload stuck over budget would otherwise write a
        # file per allocation, under the catalog lock
        now = _time.monotonic()
        if now - getattr(self, "_last_oom_dump", -1e9) < 60.0:
            return
        self._last_oom_dump = now
        try:
            os.makedirs(self._oom_dump_dir, exist_ok=True)
            stamp = datetime.datetime.now().strftime("%Y%m%d-%H%M%S-%f")
            path = os.path.join(self._oom_dump_dir, f"hbm-oom-{stamp}.txt")
            with open(path, "w") as f:
                f.write(f"device_bytes={self.device_bytes} "
                        f"budget={self.device_budget} "
                        f"host_bytes={self.host_bytes} "
                        f"host_budget={self.host_budget} "
                        f"buffers={len(self._buffers)} "
                        f"over_budget_buffer={exclude}\n")
                # per-tier spillable vs pinned (>= ACTIVE_BATCHING_PRIORITY)
                # totals: the postmortem's "why couldn't spill free enough"
                for tier in (TierEnum.DEVICE, TierEnum.HOST, TierEnum.DISK):
                    spillable = pinned = 0
                    for b in self._buffers.values():
                        if b.tier != tier:
                            continue
                        if b.priority >= ACTIVE_BATCHING_PRIORITY:
                            pinned += b.size
                        else:
                            spillable += b.size
                    f.write(f"tier={tier} spillable_bytes={spillable} "
                            f"pinned_bytes={pinned}\n")
                # per-site live breakdown (heap profiler): the OOM names the
                # culprit SUBSYSTEM, not just tier totals. Derived from the
                # live registry (the over-budget buffer is registered but
                # not yet site-accounted at this point), joined with the
                # process-lifetime site stats where they exist
                live_by_site: dict = {}
                for b in self._buffers.values():
                    if b.tier == TierEnum.DEVICE:
                        live_by_site[b.site] = \
                            live_by_site.get(b.site, 0) + b.size
                f.write("top sites by live device bytes:\n")
                for site, live in sorted(live_by_site.items(),
                                         key=lambda kv: -kv[1])[:10]:
                    st = self._site_stats.get(site) or _SiteStats()
                    f.write(f"site={site} live_device={live} "
                            f"peak_device={max(st.peak_device, live)} "
                            f"cumulative={st.cumulative} "
                            f"allocs={st.allocs} frees={st.frees}\n")
                f.write("buffer_id\ttier\tsize\tpriority\tsite\tnode\t"
                        "query\n")
                for b in sorted(self._buffers.values(),
                                key=lambda x: -x.size):
                    f.write(f"{b.buffer_id}\t{b.tier}\t{b.size}\t"
                            f"{b.priority}\t{b.site}\t{b.node}\t"
                            f"{b.query}\n")
        except OSError:
            pass  # dumping must never turn an OOM into a crash

    def _spill_device_buffer(self, buf: RapidsBuffer):
        hb = batch_to_host(buf._device)
        # block so the device arrays can actually be freed before we drop the refs
        buf._host = hb
        buf._device = None
        buf.tier = TierEnum.HOST
        self.device_bytes -= buf.size
        self.host_bytes += hb.nbytes()
        self.spilled_to_host_bytes += buf.size
        self._account_device_delta(buf, -buf.size)
        if EL.enabled():
            EL.emit("spill", tier_from=TierEnum.DEVICE, tier_to=TierEnum.HOST,
                    bytes=buf.size, buffer=buf.buffer_id,
                    priority=buf.priority)
        # spill-tier transition as an instant on the trace timeline, next to
        # the memory counter lanes (span-file only; the event log line above
        # is the analysis copy)
        TR.instant("memory.spill", tier_from=TierEnum.DEVICE,
                   tier_to=TierEnum.HOST, bytes=buf.size, site=buf.site)
        if buf.spill_callback:
            buf.spill_callback(buf.size)
        self._maybe_sample()
        self._ensure_host_budget()

    def _ensure_host_budget(self):
        if self.host_bytes <= self.host_budget:
            return
        heap = [(b.priority, b.buffer_id) for b in self._buffers.values()
                if b.tier == TierEnum.HOST]
        heapq.heapify(heap)
        while self.host_bytes > self.host_budget and heap:
            _, bid = heapq.heappop(heap)
            self._spill_host_buffer(self._buffers[bid])

    def _spill_dir_path(self) -> str:
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="rapids_tpu_spill_")
        os.makedirs(self._spill_dir, exist_ok=True)
        return self._spill_dir

    def _get_direct_store(self):
        if self._direct_store is None:
            from spark_rapids_tpu.runtime.direct_spill import DirectSpillStore
            self._direct_store = DirectSpillStore(
                os.path.join(self._spill_dir_path(), "direct"),
                batch_bytes=self._direct_batch_bytes)
        return self._direct_store

    def _spill_host_buffer(self, buf: RapidsBuffer):
        hb = buf._host
        payload = pickle.dumps(hb, protocol=pickle.HIGHEST_PROTOCOL)
        # CRC the CLEAN payload, then the chaos checkpoint
        # ("corrupt:spill.write:N") may flip a byte of what actually lands
        # on disk — modeling bit rot between write and unspill, which the
        # read-side verification must DETECT rather than decode
        if self._spill_checksum:
            from spark_rapids_tpu.runtime.checksum import block_checksum
            buf._crc = block_checksum(payload)
        payload = F.maybe_corrupt("spill.write", payload)
        # disk-capacity checkpoint BEFORE any bytes land: the injected
        # ENOSPC ("disk_full:spill.write:N") and a real ENOSPC from the
        # writes below both surface as the typed, RETRYABLE
        # SpillCapacityError — the buffer stays intact in its host tier and
        # the OOM ladder (spill elsewhere / split / retry) absorbs it,
        # instead of a raw OSError escaping the operator mid-spill
        F.maybe_inject("disk_full", "spill.write")
        try:
            if self._direct_spill:
                # GDS-analog batched aligned store (reference RapidsGdsStore)
                # — the store itself meters its aligned I/O into the
                # movement ledger (site "direct_spill")
                buf._handle = self._get_direct_store().write(payload)
                buf._path = None
            else:
                path = os.path.join(self._spill_dir_path(),
                                    f"buffer-{buf.buffer_id}.spill")
                t0 = time.perf_counter()
                try:
                    with open(path, "wb") as f:
                        f.write(payload)
                except OSError:
                    # a partial file must not survive to be unspilled later
                    with contextlib.suppress(OSError):
                        os.unlink(path)
                    raise
                from spark_rapids_tpu.runtime import movement as MV
                MV.record("spill.write", len(payload), link="disk",
                          site="spill.file",
                          seconds=time.perf_counter() - t0)
                buf._path = path
                buf._handle = None
        except OSError as e:
            import errno
            buf._crc = None
            if e.errno == errno.ENOSPC:
                raise SpillCapacityError(
                    f"disk spill tier full writing buffer "
                    f"{buf.buffer_id} ({len(payload)} B): {e}") from e
            raise
        self.host_bytes -= hb.nbytes()
        self.spilled_to_disk_bytes += hb.nbytes()
        buf._disk_len = hb.nbytes()
        self.disk_bytes += buf._disk_len
        if EL.enabled():
            EL.emit("spill", tier_from=TierEnum.HOST, tier_to=TierEnum.DISK,
                    bytes=hb.nbytes(), buffer=buf.buffer_id,
                    priority=buf.priority)
        TR.instant("memory.spill", tier_from=TierEnum.HOST,
                   tier_to=TierEnum.DISK, bytes=hb.nbytes(), site=buf.site)
        buf._host = None
        buf.tier = TierEnum.DISK
        self._maybe_sample()

    # -- access --------------------------------------------------------------
    def acquire_batch(self, buffer_id: int) -> ColumnarBatch:
        """Materialize the buffer on device. If it was spilled and unspill is enabled
        it is re-registered in the device tier (reference unspill.enabled,
        RapidsBufferStore copy-back); otherwise the device copy is transient."""
        # (bytes, seconds) collected under the lock, metered after release:
        # a sample-interval crossing in MV.record emits event-log/tracing
        # I/O, which must not run under the hot buffer-catalog lock (same
        # split direct_spill.py uses for its write path)
        spill_read = None
        try:
            with self._lock:
                try:
                    buf = self._buffers[buffer_id]
                except KeyError:
                    raise BufferClosedError(
                        f"buffer {buffer_id} removed") from None
                if buf.tier == TierEnum.DEVICE:
                    return buf._device
                hb = buf._host
                if hb is None:
                    if buf._handle is not None:
                        payload = self._get_direct_store().read(buf._handle)
                    else:
                        t0 = time.perf_counter()
                        with open(buf._path, "rb") as f:
                            payload = f.read()
                        spill_read = (len(payload),
                                      time.perf_counter() - t0)
                    if buf._crc is not None:
                        from spark_rapids_tpu.runtime.checksum import \
                            block_checksum
                        got = block_checksum(payload)
                        if got != buf._crc:
                            raise SpillCorruptionError(
                                f"buffer {buffer_id} spill payload checksum "
                                f"mismatch on unspill (stored {buf._crc:#x}, "
                                f"read {got:#x}, {len(payload)}B)")
                    hb = pickle.loads(payload)
                batch = host_to_batch(hb)
                if self._unspill:
                    if buf.tier == TierEnum.HOST:
                        self.host_bytes -= hb.nbytes()
                    elif buf._handle is not None:
                        self._get_direct_store().delete(buf._handle)
                        buf._handle = None
                    else:
                        os.unlink(buf._path)
                        buf._path = None
                    if buf.tier == TierEnum.DISK:
                        self.disk_bytes -= buf._disk_len
                        buf._disk_len = 0
                    buf._host = None
                    buf._device = batch
                    buf.tier = TierEnum.DEVICE
                    self.device_bytes += buf.size
                    self._account_device_delta(buf, buf.size)
                    self._ensure_device_budget(exclude=buffer_id)
                    self._maybe_sample()
                return batch
        finally:
            if spill_read is not None:
                from spark_rapids_tpu.runtime import movement as MV
                MV.record("spill.read", spill_read[0], link="disk",
                          site="spill.file", seconds=spill_read[1])

    def get_tier(self, buffer_id: int) -> str:
        return self._buffers[buffer_id].tier

    def update_priority(self, buffer_id: int, priority: float):
        with self._lock:
            self._buffers[buffer_id].priority = priority

    def remove(self, buffer_id: int):
        with self._lock:
            buf = self._buffers.pop(buffer_id, None)
            if buf is None:
                return
            if buf.tier == TierEnum.DEVICE:
                self.device_bytes -= buf.size
            elif buf.tier == TierEnum.HOST:
                self.host_bytes -= buf._host.nbytes()
            else:
                self.disk_bytes -= buf._disk_len
                if buf._handle is not None:
                    self._get_direct_store().delete(buf._handle)
                elif buf._path:
                    try:
                        os.unlink(buf._path)
                    except OSError:
                        pass
            self._account_free(buf)

    def synchronous_spill(self, target_device_bytes: int) -> int:
        """Spill until the device tier holds <= target bytes; returns bytes spilled
        (reference RapidsBufferStore.synchronousSpill:145)."""
        with self._lock:
            before = self.device_bytes
            saved = self.device_budget
            try:
                self.device_budget = target_device_bytes
                self._ensure_device_budget()
            finally:
                self.device_budget = saved
            return before - self.device_bytes

    # -- per-query accounting (multi-tenant scheduler, runtime/scheduler.py) --
    def query_device_bytes(self) -> dict:
        """{query_id: device-tier bytes} for every owning query (None key =
        buffers registered outside any query scope) — the fair-share input
        of the scheduler's OOM demotion policy."""
        with self._lock:
            out: dict = {}
            for b in self._buffers.values():
                if b.tier == TierEnum.DEVICE:
                    out[b.query] = out.get(b.query, 0) + b.size
            return out

    def spill_query_device(self, query_id: str) -> int:
        """Demote ONE query's device tier: spill its spillable device
        buffers (below ACTIVE_BATCHING priority — a batch an operator is
        mid-consume stays pinned), lowest priority first; returns bytes
        spilled. The fair-share degradation path: an over-share peer pays
        a recoverable unspill instead of the under-share faulting query
        paying with batch splits."""
        with self._lock:
            victims = sorted(
                (b for b in self._buffers.values()
                 if b.tier == TierEnum.DEVICE and b.query == query_id
                 and b.priority < ACTIVE_BATCHING_PRIORITY),
                key=lambda b: b.priority)
            spilled = 0
            for b in victims:
                spilled += b.size
                self._spill_device_buffer(b)
            return spilled

    # -- allocation-site heap profiler read-out ------------------------------
    def buffer_site(self, buffer_id: int) -> str:
        with self._lock:
            buf = self._buffers.get(buffer_id)
            return buf.site if buf is not None else UNATTRIBUTED_SITE

    def heap_snapshot(self) -> dict:
        """Live heap structure by allocation site: per-site tier occupancy
        of the buffers alive right now (computed by scanning the registry —
        bounded by live buffer count), joined with the site's process-
        lifetime peak/cumulative/alloc/free stats. The programmatic face of
        ``tools/profiler.py memory`` (session.heap_snapshot())."""
        with self._lock:
            live: dict = {}
            for b in self._buffers.values():
                e = live.setdefault(b.site, {
                    "buffers": 0, "tiers": {}, "nodes": set(),
                    "queries": set(), "retained_bytes": 0})
                if b.tier == TierEnum.DEVICE:
                    sz = b.size
                elif b.tier == TierEnum.HOST:
                    sz = b._host.nbytes()
                else:
                    sz = b._disk_len
                e["buffers"] += 1
                e["tiers"][b.tier] = e["tiers"].get(b.tier, 0) + sz
                if b.node is not None:
                    e["nodes"].add(b.node)
                if b.query is not None:
                    e["queries"].add(b.query)
                if b.retained:
                    e["retained_bytes"] += sz
            sites = []
            for site, st in self._site_stats.items():
                e = live.get(site) or {"buffers": 0, "tiers": {},
                                       "nodes": set(), "queries": set(),
                                       "retained_bytes": 0}
                sites.append({
                    "site": site,
                    "buffers": e["buffers"],
                    "tiers": dict(e["tiers"]),
                    "live_bytes": sum(e["tiers"].values()),
                    "device_bytes": e["tiers"].get(TierEnum.DEVICE, 0),
                    "retained_bytes": e["retained_bytes"],
                    "nodes": sorted(e["nodes"]),
                    "queries": sorted(e["queries"]),
                    "peak_device_bytes": st.peak_device,
                    "cumulative_bytes": st.cumulative,
                    "allocs": st.allocs,
                    "frees": st.frees,
                })
            sites.sort(key=lambda s: (-s["device_bytes"], -s["live_bytes"],
                                      -s["cumulative_bytes"]))
            return {
                "device_bytes": self.device_bytes,
                "host_bytes": self.host_bytes,
                "disk_bytes": self.disk_bytes,
                "watermark_bytes": self.watermark_bytes,
                "device_budget": self.device_budget,
                "buffers": len(self._buffers),
                "sites": sites,
            }

    def query_memory(self, query_id: str) -> dict:
        """Per-query memory summary (peak/cumulative device bytes + the
        top-K sites by peak) without finishing the query's accounting."""
        with self._lock:
            qm = self._query_mem.get(query_id)
            return self._query_summary(qm)

    def _query_summary(self, qm) -> dict:
        ranked = sorted((qm or {}).get("sites", {}).items(),
                        key=lambda kv: -kv[1][1])[:self._top_k]
        return {
            "peak_device_bytes": qm["peak"] if qm else 0,
            "cumulative_bytes": qm["cum"] if qm else 0,
            "allocs": qm["allocs"] if qm else 0,
            "sites": {site: {"peak_bytes": v[1], "cumulative_bytes": v[2],
                             "nodes": sorted(v[3])}
                      for site, v in ranked},
        }

    def finish_query(self, query_id: str, leak_check: bool = True):
        """End-of-query epilogue: pop the query's memory accounting and
        return (summary, leak). When ``leak_check``, any non-retained
        buffer still tagged to the finished query is a LEAK — a
        ``memory.leak`` event + resilience counter fire with the per-site
        breakdown, and the buffers are reclaimed so one leaky operator
        cannot bleed the HBM budget across queries. ``leak`` is None on a
        clean query, else {bytes, buffers, sites}."""
        with self._lock:
            qm = self._query_mem.pop(query_id, None)
            summary = self._query_summary(qm)
            leaked = ([b for b in self._buffers.values()
                       if b.query == query_id and not b.retained]
                      if leak_check else [])
        if not leaked:
            return summary, None
        by_site: dict = {}
        total = 0
        for b in leaked:
            by_site[b.site] = by_site.get(b.site, 0) + b.size
            total += b.size
        leak = {"bytes": total, "buffers": len(leaked), "sites": by_site}
        from spark_rapids_tpu.runtime import metrics as M
        M.resilience_add(M.MEMORY_LEAKS, len(leaked))
        TR.span_event("memory.leak", bytes=total, buffers=len(leaked),
                      sites=by_site)
        # reclaim: the detector's report is the alarm; holding the bytes
        # hostage afterwards would punish every later tenant for it
        for b in leaked:
            self.remove(b.buffer_id)
        return summary, leak

    @property
    def num_buffers(self):
        return len(self._buffers)


# memory-profile knobs applied by a session that sets them EXPLICITLY
# (the process-global-switch pattern of tracing/faults/eventlog): the
# DeviceManager catalog is constructed lazily with default conf, so the
# session pushes the values onto the live catalog and remembers them for a
# catalog created later
_profile_override: "tuple[int, int] | None" = None


def set_profile_options(watermark_interval_bytes: int, top_k: int) -> None:
    global _profile_override
    _profile_override = (int(watermark_interval_bytes), int(top_k))
    dm = DeviceManager._instance
    if dm is not None:
        cat = dm.catalog
        with cat._lock:
            cat._watermark_interval = max(1, int(watermark_interval_bytes))
            cat._top_k = max(1, int(top_k))


def host_prefetch_budget(max_buffer_bytes: int) -> int:
    """Byte budget for prefetch buffering ahead of a consumer (scan
    readahead and every pipeline queue edge, runtime/pipeline.py): the
    configured cap, shrunk to the spill catalog's free host headroom so
    prefetched data never evicts spilled device buffers to disk. The floor
    guarantees a producer can always stage at least one typical reader
    batch (a zero budget would serialize decode behind compute again)."""
    cat = DeviceManager.get().catalog
    headroom = max(cat.host_budget - cat.host_bytes, 0)
    return max(min(max_buffer_bytes, headroom), 16 << 20)


# historical name (the scan readahead predates the generalized pipeline)
scan_readahead_budget = host_prefetch_budget


class SpillableColumnarBatch:
    """Handle over a catalogued batch; keeps data spillable while an operator holds it
    (reference SpillableColumnarBatch.scala:29,74)."""

    def __init__(self, batch: ColumnarBatch, priority: float = ACTIVE_ON_DECK_PRIORITY,
                 catalog: "BufferCatalog | None" = None, spill_callback=None):
        self.catalog = catalog or DeviceManager.get().catalog
        self.buffer_id = self.catalog.add_batch(batch, priority, spill_callback)
        self._site = self.catalog.buffer_site(self.buffer_id)
        self.num_rows = batch.num_rows
        self.schema = batch.schema
        self.size = batch.device_memory_size()
        self._closed = False
        self._leak = LeakTracker.track(f"SpillableColumnarBatch#{self.buffer_id}")

    def get_batch(self) -> ColumnarBatch:
        if self._closed:
            raise BufferClosedError(f"buffer {self.buffer_id} used after close")
        return self.catalog.acquire_batch(self.buffer_id)

    def set_priority(self, priority: float):
        self.catalog.update_priority(self.buffer_id, priority)

    def close(self):
        if not self._closed:
            self._closed = True
            LeakTracker.release(self._leak)
            # chaos hook ("leak:<site>:N", runtime/faults.py): model a
            # refcount bug — the handle closes normally but the catalog
            # entry is never freed, which the end-of-query leak detector
            # (BufferCatalog.finish_query) MUST catch and reclaim
            if F.should_leak(self._site):
                return
            self.catalog.remove(self.buffer_id)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


class DeviceManager:
    """Process-wide device state: the chosen device, the HBM budget, and the buffer
    catalog (reference GpuDeviceManager.scala:36 + RapidsBufferCatalog.init:177).

    One executor owns one TPU chip in the reference's model (GpuDeviceManager.scala:103);
    here the local runtime owns jax.devices()[0] and multi-chip execution goes through
    the Mesh path (distributed/), matching SURVEY.md §7's executor-per-chip decision.
    """

    _instance: "DeviceManager | None" = None
    _lock = threading.Lock()

    def __init__(self, conf: C.RapidsConf):
        self.conf = conf
        self.device = jax.devices()[0]
        limit = conf.get(C.DEVICE_MEMORY_LIMIT)
        if not limit:
            stats = None
            try:
                stats = self.device.memory_stats()
            except Exception:
                pass
            hbm = (stats or {}).get("bytes_limit", 0)
            if not hbm:
                hbm = 16 << 30  # CPU backend exposes no limit; assume one v5e chip's HBM
            limit = int(hbm * conf.get(C.DEVICE_MEMORY_FRACTION))
        spill_dirs = conf.get(C.SPILL_DIRS)
        self.catalog = BufferCatalog(
            device_budget=limit,
            host_budget=conf.get(C.HOST_SPILL_STORAGE_SIZE),
            spill_dir=spill_dirs.split(",")[0] if spill_dirs else None,
            unspill=conf.get(C.UNSPILL_ENABLED),
            oom_dump_dir=conf.get(C.OOM_DUMP_DIR),
            direct_spill=conf.get(C.DIRECT_SPILL_ENABLED),
            direct_batch_bytes=conf.get(C.DIRECT_SPILL_BATCH_BYTES),
            strict_budget=conf.get(C.STRICT_DEVICE_BUDGET),
            spill_checksum=conf.get(C.SPILL_CHECKSUM),
            watermark_interval_bytes=conf.get(C.MEMORY_WATERMARK_INTERVAL),
            profile_top_k=conf.get(C.MEMORY_PROFILE_TOPK),
        )
        if _profile_override is not None:
            self.catalog._watermark_interval = max(1, _profile_override[0])
            self.catalog._top_k = max(1, _profile_override[1])

    @classmethod
    def initialize(cls, conf: C.RapidsConf | None = None) -> "DeviceManager":
        with cls._lock:
            cls._instance = DeviceManager(conf or C.RapidsConf())
            return cls._instance

    @classmethod
    def get(cls) -> "DeviceManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = DeviceManager(C.RapidsConf())
            return cls._instance

    @classmethod
    def reset(cls):
        with cls._lock:
            cls._instance = None
