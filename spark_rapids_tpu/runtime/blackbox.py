"""Black-box flight recorder — the post-mortem record of a dying replica.

A SIGKILLed or wedged serving replica dies with no chance to write a
report: the survivor adopts its lease (runtime/fleet.py) but cannot explain
what the victim was doing. This module keeps a bounded in-memory ring of
the most recent event-log records (``flightRecorder.maxEvents``, default
on) at near-zero cost — eventlog.emit appends each record it writes, one
None check + deque append, no I/O — and flushes it to
``blackbox-<pid>.json`` when something goes wrong:

  - an **unhandled endpoint error** (an exception class the serving
    contract does not expect) escaping a query worker,
  - a **deadline hard-kill** — the endpoint's request-timeout or drain
    escalation cancelling an in-flight query,
  - a **stuck-query detection** from the fleet heartbeat's health
    provider: the endpoint's connection thread can be wedged (a hung send,
    a fault injection) and then cannot enforce its own deadline, but the
    heartbeat thread stays alive until the very SIGKILL — so the dump
    exists on disk *before* the process dies, and the adoption sweep can
    attach its path to the ``fleet.adopt`` event.

The dump is a single JSON object: process identity, the dump reason, the
in-flight queries at dump time (from the endpoint-registered provider:
query id, journey, attempt, SQL prefix, age), the event ring, and the
tracing span ring (runtime/tracing.recent_events). Dumps are atomic
(pid-unique tmp + os.replace) and per-reason throttled so a heartbeat-
driven detector cannot spam the disk. The dump directory defaults to
``eventLog.dir``; with no directory configured dump() is a no-op.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

DEFAULT_MAX_EVENTS = 512

_lock = threading.Lock()
_ring: collections.deque | None = None
_dir: str | None = None
_inflight_provider = None
_last_dump: dict = {}   # reason -> monotonic time of the last dump
_dump_count = 0


def _install_ring(max_events: int) -> None:
    global _ring
    from spark_rapids_tpu.runtime import eventlog as EL
    _ring = (collections.deque(maxlen=int(max_events))
             if max_events > 0 else None)
    EL.set_blackbox_ring(_ring)


# the recorder is on by default: the ring exists from first import so every
# configured event log feeds it without any bootstrap ordering concern
_install_ring(DEFAULT_MAX_EVENTS)


def configure(max_events: int | None = None,
              directory: str | None = None) -> None:
    """Resize (or disable, max_events=0) the ring and/or set the dump
    directory. Called by TpuSession for explicitly-set knobs; the ring
    itself needs no configuration to run at its default bound."""
    with _lock:
        if max_events is not None:
            _install_ring(int(max_events))
        if directory is not None:
            global _dir
            _dir = directory


def set_inflight_provider(fn) -> None:
    """Register the callable that names the process's in-flight queries at
    dump time (the endpoint registers one walking its active registry);
    None unregisters. Provider failures degrade to an empty list — the
    recorder must never make a bad situation worse."""
    global _inflight_provider
    _inflight_provider = fn


def enabled() -> bool:
    return _ring is not None


def ring_len() -> int:
    r = _ring
    return len(r) if r is not None else 0


def dump_path() -> str | None:
    """Where this process's dump lands (None when no directory is
    configured) — recorded into the fleet membership record so a survivor
    can name it on adoption."""
    return (os.path.join(_dir, f"blackbox-{os.getpid()}.json")
            if _dir else None)


def dump(reason: str, *, min_interval_s: float = 1.0) -> str | None:
    """Flush the ring + in-flight registry to blackbox-<pid>.json; returns
    the path, or None when disabled/unconfigured/throttled. Repeated dumps
    replace the file (the latest state is the post-mortem that matters);
    per-reason throttling bounds a repeating detector to one dump per
    ``min_interval_s``."""
    path = dump_path()
    if path is None or _ring is None:
        return None
    now = time.monotonic()
    with _lock:
        last = _last_dump.get(reason)
        if last is not None and now - last < min_interval_s:
            return None
        _last_dump[reason] = now
        global _dump_count
        _dump_count += 1
        seq = _dump_count
    inflight = []
    prov = _inflight_provider
    if prov is not None:
        try:
            inflight = list(prov())
        except Exception:   # noqa: BLE001 — a broken provider loses detail,
            inflight = []   # never the dump
    try:
        from spark_rapids_tpu.runtime import tracing
        spans = [{"name": n, **a} for n, a in tracing.recent_events()]
    except Exception:   # noqa: BLE001
        spans = []
    payload = {
        "pid": os.getpid(),
        "reason": reason,
        "ts": time.time(),
        "dump_seq": seq,
        "inflight": inflight,
        "events": list(_ring),
        "spans": spans,
    }
    try:
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(payload, f, separators=(",", ":"), default=str)
        os.replace(tmp, path)
    except OSError:
        return None
    try:
        from spark_rapids_tpu.runtime import eventlog as EL
        if EL.enabled():
            EL.emit("blackbox.dump", query=None, reason=reason, path=path,
                    inflight=len(inflight), events=len(payload["events"]))
    except Exception:   # noqa: BLE001 — observability must not fail serving
        pass
    return path


def reset() -> None:
    """Test hook: fresh ring at the current bound, throttles cleared."""
    global _last_dump, _dump_count, _inflight_provider
    with _lock:
        r = _ring
        _install_ring(r.maxlen if r is not None else 0)
        _last_dump = {}
        _dump_count = 0
        _inflight_provider = None
