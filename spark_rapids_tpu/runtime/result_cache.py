"""Parameterized-plan result cache for the serving endpoint.

Identical hot queries — the head of a serving workload's distribution — are
answered from memory without touching the scheduler, the executors, or the
device: the endpoint records the exact CRC-stamped Arrow-IPC frame payloads
it streamed for a query and replays them bit-identically on the next hit.

Keying is three-part, each part closing a distinct staleness/aliasing hole:

  - **catalog epoch** (session view-registration counter): any
    `create_or_replace_temp_view` bumps it, so results computed against a
    replaced view can never be served again;
  - **plan signature** (plan/fingerprint.plan_signature): the parameterized
    plan identity — shape plus literal VALUES — so `where v > 5` and
    `where v > 6` are distinct entries while remaining fingerprint-keyed
    for per-shape observability;
  - **SQL text digest**: plan signatures normalize scan data sources away
    (they are shape identities), so two same-shaped queries over different
    views would alias without it.

Admission-exempt by design: a hit never enters the scheduler queue, so a
saturated fleet still serves its hot set instantly (and sheds only genuinely
new work). Bounded by bytes AND entries with LRU eviction; a result larger
than the byte budget is simply not admitted.
"""

from __future__ import annotations

import collections
import hashlib
import threading


def sql_digest(sql: str) -> str:
    return hashlib.sha256(sql.strip().encode("utf-8")).hexdigest()[:16]


class ResultCache:
    """LRU over fully-materialized endpoint results (wire-frame payloads +
    the summary dict). Thread-safe; all methods are O(1) amortized."""

    def __init__(self, max_bytes: int = 64 << 20, max_entries: int = 64):
        self.max_bytes = int(max_bytes)
        self.max_entries = max(int(max_entries), 1)
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self._lock = threading.Lock()
        self.bytes = 0
        # observability counters (STATS frames + tests read these)
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0
        self.stale_drops = 0

    @staticmethod
    def key(epoch: int, signature: str, sql: str) -> tuple:
        return (int(epoch), signature, sql_digest(sql))

    def get(self, key: tuple) -> dict | None:
        """The cached result for `key` ({"frames": [bytes], "summary": dict})
        or None. A hit refreshes LRU recency."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry

    def put(self, key: tuple, frames: list, summary: dict) -> bool:
        """Admit one result; returns False when it exceeds the byte budget.
        Evicts LRU entries past either bound and drops entries from older
        catalog epochs (their results can never be served again)."""
        nbytes = sum(len(f) for f in frames)
        if nbytes > self.max_bytes:
            return False
        epoch = key[0]
        with self._lock:
            for k in [k for k in self._entries if k[0] != epoch]:
                self.bytes -= self._entries.pop(k)["nbytes"]
                self.stale_drops += 1
            old = self._entries.pop(key, None)
            if old is not None:
                self.bytes -= old["nbytes"]
            self._entries[key] = {"frames": list(frames),
                                  "summary": dict(summary),
                                  "nbytes": nbytes}
            self.bytes += nbytes
            while (self.bytes > self.max_bytes
                   or len(self._entries) > self.max_entries):
                _, victim = self._entries.popitem(last=False)
                self.bytes -= victim["nbytes"]
                self.evictions += 1
            self.inserts += 1
        return True

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._entries), "bytes": self.bytes,
                    "hits": self.hits, "misses": self.misses,
                    "inserts": self.inserts, "evictions": self.evictions,
                    "stale_drops": self.stale_drops}
