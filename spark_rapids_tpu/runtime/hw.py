"""Hardware-trait predicates shared by kernel-strategy choices.

Kernels with a formulation choice (scatter vs gather, direct table vs sort)
ask these predicates instead of re-encoding backend names at every call
site — the strategy stays consistent across the engine and a new backend is
reasoned about once.
"""

from __future__ import annotations

import jax


def scatters_cheap() -> bool:
    """Large 1:1 scatters are near-memcpy on CPU-class backends but
    SERIALIZE on the TPU (the reason ops/grouping.py uses scan-based segment
    reductions there). Gather/searchsorted formulations stay the TPU path."""
    return jax.default_backend() != "tpu"
