"""TPU admission control — the GpuSemaphore analog.

Reference: GpuSemaphore.scala:101: N tasks may hold the GPU concurrently
(spark.rapids.sql.concurrentGpuTasks); tasks acquire before first device use and
auto-release on completion; semaphore wait time is a first-class metric. Same design:
a counted semaphore keyed by task, re-entrant per task, with wait-time accounting."""

from __future__ import annotations

import threading
import time


class TpuSemaphore:
    _instance = None
    _lock = threading.Lock()

    def __init__(self, max_concurrent: int):
        self.max_concurrent = max_concurrent
        self._sem = threading.Semaphore(max_concurrent)
        self._holders: dict[int, int] = {}
        self._holders_lock = threading.Lock()

    @classmethod
    def initialize(cls, max_concurrent: int):
        with cls._lock:
            cls._instance = cls(max_concurrent)

    @classmethod
    def get(cls) -> "TpuSemaphore":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls(2)
            return cls._instance

    def acquire_if_necessary(self, task_id: int, wait_metric=None) -> None:
        """Idempotent per-task acquire: a task holds at most one permit no matter how
        many operators in its pipeline call this (reference acquireIfNecessary,
        GpuSemaphore.scala:74 — 'if this task has not already acquired')."""
        with self._holders_lock:
            if task_id in self._holders:
                return
        from spark_rapids_tpu.runtime.scheduler import check_cancel
        t0 = time.perf_counter_ns()
        # polled acquire: a cancelled/deadlined query must not camp on the
        # permit queue — every waiter is a cooperative cancellation point
        # (runtime/scheduler.py), and a raise here leaves no permit held
        while not self._sem.acquire(timeout=0.05):
            check_cancel()
        if wait_metric is not None:
            wait_metric.add(time.perf_counter_ns() - t0)
        with self._holders_lock:
            self._holders[task_id] = 1

    def release_current(self) -> None:
        """Release the CALLING thread's task permit if it holds one — used
        by pipeline stages (runtime/pipeline.py) before blocking on a full
        queue, so a held permit can never starve the consumer that must
        drain it (reference: the shuffle iterator releases while blocked,
        RapidsShuffleIterator.scala:300). Operators re-acquire per batch via
        acquire_if_necessary."""
        from spark_rapids_tpu.exec.base import _task_local
        tid = getattr(_task_local, "task_id", None)
        if tid is not None:
            self.release_if_necessary(tid)

    def release_if_necessary(self, task_id: int) -> None:
        """Release the task's permit entirely (reference completeAndRelease on task
        completion)."""
        with self._holders_lock:
            if self._holders.pop(task_id, None) is None:
                return
        self._sem.release()
