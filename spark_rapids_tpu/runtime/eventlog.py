"""Structured JSONL event log — the Spark event-log analog.

Reference: Spark writes SparkListenerEvent JSON lines that the RAPIDS
Profiling Tool (tools/profiling) post-processes into tuning reports; the
reference plugin's own metrics ride inside those events. Here the engine is
standalone, so this module IS the listener bus: query/stage/batch lifecycle,
spill, OOM-retry/split, fetch retry/failover/recompute, heartbeat loss and
periodic executor health gauges are appended as one JSON object per line to
``spark.rapids.tpu.eventLog.dir``, and tools/profiler.py replays the file
into an analysis report.

Overhead contract: when no directory is configured every emit() is a single
attribute load + None check — hot paths (per-batch lifecycle) additionally
pre-check enabled() so no event dict is even built.

Record schema (validated by validate_record(), enforced by the profiler):
  event  str   one of KNOWN_EVENTS
  ts     float unix wall-clock seconds — the CROSS-PROCESS ordering key once
               each process's clock offset is applied (ts + offset ≈ driver
               wall clock); `t` alone cannot order records from different
               processes (each process's monotonic clock has an arbitrary
               epoch)
  t      float monotonic seconds — strictly non-decreasing within one file
               (computed under the writer lock)
  pid    int   writing process (executor records merge with driver records)
  query  str|None  query id from the ambient QueryMetricsCollector
  node   int|None  plan-node id from the ambient node_frame stack
plus per-event payload fields, and `offset` (heartbeat-handshake-derived
clock correction toward the driver's clock, seconds) on records written by
a process whose offset was measured (set_clock_offset — MiniCluster
executors receive theirs from the driver's two-timestamp exchange).
"""

from __future__ import annotations

import datetime
import json
import os
import threading
import time

from spark_rapids_tpu.runtime import metrics as M

KNOWN_EVENTS = frozenset({
    # query lifecycle (emitted by DataFrame actions, session.py)
    "query.start", "query.end", "query.error",
    # multi-tenant lifecycle (runtime/scheduler.py): admission queueing and
    # grants, load shedding (queue full / queue timeout), cooperative
    # cancellation and deadline expiry, and fair-share demotion of a peer's
    # spillable device buffers during another query's OOM recovery
    "query.queued", "query.admitted", "query.shed",
    "query.cancelled", "query.deadline", "query.demoted",
    # stage/batch lifecycle
    "stage.map.start", "stage.map.end", "batch",
    # memory pressure (runtime/memory.py + runtime/retry.py via tracing)
    "spill", "oom.retry", "oom.split",
    # shuffle fetch ladder (shuffle/fetch.py + exec/exchange.py via tracing)
    "fetch.error", "fetch.retry", "fetch.failover", "fetch.recompute",
    # liveness (shuffle/heartbeat.py + the health sampler below)
    "heartbeat.loss", "executor.health",
    # cluster fault recovery (cluster/minicluster.py driver scheduler):
    # task retry/timeout/stale-epoch re-attempts, executor death and
    # blacklisting, lineage-scoped partial map-stage recompute, and
    # speculative-duplicate outcomes
    "task.attempt", "executor.lost", "executor.blacklisted",
    "stage.recompute.partial", "speculation.won", "speculation.lost",
    # unified mesh-cluster plane (cluster/minicluster.py): an executor's
    # local mesh attaching on the spawn handshake, detaching on loss or
    # degradation, a mesh task transparently re-planned onto the per-split
    # TCP path, a transient spawn-handshake failure retried, movement-aware
    # reduce placement demoted off an over-budget host, and a reduce-side
    # fetch short-circuited to the executor's own block store
    "mesh.attach", "mesh.detach", "mesh.degraded",
    "executor.spawn.retry", "placement.demoted", "fetch.local",
    # pipelined executor queue edges (runtime/pipeline.py): a producer or
    # consumer blocked past the stall threshold, bounded per queue
    "pipeline.stall",
    # query-serving endpoint (runtime/endpoint.py): listener lifecycle,
    # client connections, disconnect-driven cancellation, graceful drain
    "endpoint.start", "endpoint.stop",
    "client.connected", "client.disconnected", "server.drain",
    # memory observability plane (runtime/memory.py): watermark timeline
    # samples (per-tier occupancy + device high-water mark + top sites by
    # live bytes), full allocation-site heap snapshots at query end, and
    # end-of-query leak detections with their per-site breakdown
    "memory.watermark", "memory.snapshot", "memory.leak",
    # runtime statistics plane (runtime/stats.py): one end-of-query record
    # carrying the plan fingerprint, footprint estimate vs observed peak,
    # the per-node cardinality/dispatch/transfer ledger and per-shuffle
    # reduce-partition sizes with skew summaries
    "plan.stats",
    # whole-stage fusion plane (plan/stages.py + runtime/stage_cache.py):
    # one record per fused stage at plan time (members + absorbed logical
    # operators — the join key against plan.stats node dispatches), and a
    # persistent-cache entry that failed to deserialize and was dropped in
    # favor of a retrace
    "stage.fused", "stage.cache.corrupt",
    # data-movement observability plane (runtime/movement.py): cumulative
    # ledger snapshots — every flow as (edge, link, bytes, payload_bytes,
    # transfers) — emitted whenever a process has moved another
    # movement.sample.intervalBytes since its last sample, plus a forced
    # flush at query end and executor task completion. Deliberately NOT
    # query-scoped: executor processes meter task work outside any driver
    # query extent
    "movement.sample",
    # serving-fleet membership plane (runtime/fleet.py): a replica writing
    # its lease-stamped record, dropping it on clean shutdown, and a
    # survivor adopting an expired peer's lease (carrying the dead
    # replica's blackbox-dump path when its record named one)
    "fleet.register", "fleet.deregister", "fleet.adopt",
    # fleet observability plane (runtime/endpoint.py): one query.journey
    # record per endpoint submission at its terminal transition — the
    # cross-replica failover timeline's unit (see JOURNEY_OUTCOMES) —
    # plus SLO breach detections and black-box flight-recorder dumps
    "query.journey", "slo.breach", "blackbox.dump",
    # streaming plane (streaming/): one record per durable APPEND, one per
    # journaled epoch.begin, and one per epoch.commit carrying the epoch's
    # input rows, state rows/bytes, retired rows, watermark and state
    # checksum — the bounded-state timeline tools/profiler.py streaming
    # renders. Deliberately NOT query-scoped: the epoch's admitted query
    # emits its own query.* records; these mark the protocol transitions
    # around it
    "stream.append", "stream.epoch.begin", "stream.epoch.commit",
})

# terminal outcome of one endpoint submission attempt (the query.journey
# `outcome` field); profiler.py journey rejects records outside this set.
# `replica_timeout` is the fleet conversion of a request-timeout kill (the
# client re-routes); a solo endpoint's kill stays `timeout`. A failover is
# not an outcome — it is the profiler-derived label for a journey whose
# attempt N ended retryably and whose attempt N+1 exists on another replica
JOURNEY_OUTCOMES = frozenset({
    "served", "cached", "shed", "replica_timeout", "timeout",
    "error", "disconnect",
})

# events that only make sense inside a query's dynamic extent; the profiler
# flags them as schema violations when they carry no query id
QUERY_SCOPED_EVENTS = frozenset({
    "query.start", "query.end", "query.error", "batch",
    "stage.map.start", "stage.map.end",
    "query.queued", "query.admitted", "query.shed",
    "query.cancelled", "query.deadline", "query.demoted",
    "plan.stats", "stage.fused",
})

_lock = threading.Lock()
_writer: "EventLogWriter | None" = None
_sampler: "_HealthSampler | None" = None

# clock correction toward the driver's wall clock (seconds): measured by the
# driver's two-timestamp exchange on the executor spawn/heartbeat handshake
# and pushed to the executor, so its records (and span files —
# runtime/tracing reads this too) can be merged onto one timeline
_clock_offset = 0.0


def set_clock_offset(offset_s: float) -> None:
    global _clock_offset
    _clock_offset = float(offset_s)


def clock_offset() -> float:
    return _clock_offset


class EventLogWriter:
    """Append-only JSONL writer; one file per process per configure().

    ``max_bytes`` > 0 enables size-based rotation: when the active file
    crosses the bound it shifts to ``<path>.1`` (existing ``.N`` shift up,
    ``keep`` rotations retained, older deleted) and a fresh active file
    opens — long-lived serving sessions cannot grow one JSONL without
    bound. `t` stays monotonic ACROSS rotations (one logical stream)."""

    def __init__(self, path: str, max_bytes: int = 0, keep: int = 4):
        self.path = path
        self.max_bytes = max(0, int(max_bytes))
        self.keep = max(1, int(keep))
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()
        self._last_t = 0.0

    def write(self, record: dict) -> None:
        with self._lock:
            # stamp under the lock: `t` is the file's ordering key and must
            # never run backwards between adjacent lines
            t = time.monotonic()
            if t < self._last_t:
                t = self._last_t
            self._last_t = t
            record["t"] = t
            line = json.dumps(record, separators=(",", ":"), default=str)
            self._f.write(line + "\n")
            self._f.flush()
            if self.max_bytes and self._f.tell() >= self.max_bytes:
                self._rotate()

    def _rotate(self) -> None:
        # under self._lock. Shift events.jsonl.(keep-1) off the end, then
        # .N -> .N+1 descending, then the active file to .1, reopen fresh
        try:
            self._f.close()
            oldest = f"{self.path}.{self.keep}"
            if os.path.exists(oldest):
                os.unlink(oldest)
            for i in range(self.keep - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, f"{self.path}.1")
        except OSError:
            pass   # rotation must never crash the engine; keep appending
        self._f = open(self.path, "a", encoding="utf-8")

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


def configure(directory: str, health_interval_s: float = 0.0,
              max_bytes: int = 0, keep: int = 4) -> str:
    """Open an event log file under `directory` (created if missing) and make
    it the process-wide sink; returns the file path. health_interval_s > 0
    additionally starts the periodic executor-health sampler; max_bytes > 0
    enables size-based rotation keeping `keep` rotated files."""
    global _writer, _sampler
    os.makedirs(directory, exist_ok=True)
    # microsecond stamp: two configure() calls in the same process and
    # second (back-to-back sessions sharing a directory) must not silently
    # append to one file
    stamp = datetime.datetime.now().strftime("%Y%m%d-%H%M%S-%f")
    path = os.path.join(directory,
                        f"events-{os.getpid()}-{stamp}.jsonl")
    with _lock:
        if _writer is not None:
            _writer.close()
        _writer = EventLogWriter(path, max_bytes=max_bytes, keep=keep)
        if _sampler is not None:
            _sampler.stop()
            _sampler = None
        if health_interval_s > 0:
            _sampler = _HealthSampler(health_interval_s)
    return path


def shutdown() -> None:
    global _writer, _sampler
    with _lock:
        if _sampler is not None:
            _sampler.stop()
            _sampler = None
        if _writer is not None:
            _writer.close()
            _writer = None


# MiniCluster executor processes have no QueryMetricsCollector, so the
# ambient-collector query lookup below comes up empty there; runtime/tracing
# registers current_trace_id() here (a setter, to avoid the circular import)
# so query-scoped records written inside a shipped task still correlate —
# the task's trace id IS the query's cross-process identity
_query_fallback = None


def set_query_fallback(fn) -> None:
    global _query_fallback
    _query_fallback = fn


# black-box flight recorder (runtime/blackbox.py) registers its bounded
# deque here so every record the log sees is also retained in memory for a
# post-mortem dump — one None check + deque append on the emit path, and
# nothing at all when no event log is configured (the overhead contract
# above is unchanged)
_blackbox_ring = None


def set_blackbox_ring(ring) -> None:
    global _blackbox_ring
    _blackbox_ring = ring


def enabled() -> bool:
    return _writer is not None


def current_path() -> str | None:
    w = _writer
    return w.path if w is not None else None


def emit(event: str, *, query: str | None = None, node: int | None = None,
         **fields) -> None:
    """Append one event. `query`/`node` default to the ambient query scope
    (runtime/metrics collector + node_frame stack); a no-op when no event
    log is configured."""
    w = _writer
    if w is None:
        return
    q = query if query is not None else M.current_query_id()
    if q is None and _query_fallback is not None:
        q = _query_fallback()
    record = {
        "event": event,
        "ts": time.time(),
        "t": 0.0,   # stamped by the writer under its lock
        "pid": os.getpid(),
        "query": q,
        "node": node if node is not None else M.current_node(),
    }
    if _clock_offset:
        record["offset"] = _clock_offset
    record.update(fields)
    w.write(record)
    ring = _blackbox_ring
    if ring is not None:
        ring.append(record)


def health_payload() -> dict:
    """Executor health gauges: HBM budget/used/free plus per-tier
    spill-catalog occupancy, the process's fuse compile/dispatch counters
    (retrace visibility per heartbeat) and the live gauge registry
    (endpoint connection count, pipeline queue occupancy). Never forces
    device initialization — an unstarted DeviceManager reports empty
    memory gauges."""
    from spark_rapids_tpu.runtime import fuse
    from spark_rapids_tpu.runtime.memory import DeviceManager, TierEnum
    extra = {"fuse": fuse.stage_metrics()}
    gauges = M.gauges_snapshot()
    if gauges:
        extra["gauges"] = gauges
    dm = DeviceManager._instance
    if dm is None:
        return {"device_initialized": False, **extra}
    cat = dm.catalog
    tiers = {TierEnum.DEVICE: [0, 0], TierEnum.HOST: [0, 0],
             TierEnum.DISK: [0, 0]}
    with cat._lock:
        for b in cat._buffers.values():
            tiers[b.tier][0] += 1
            tiers[b.tier][1] += b.size
        # top allocation sites by live device bytes (heap profiler): who
        # holds the HBM right now, bounded to the configured top-K
        mem_sites = dict(sorted(
            ((s, st.live_device) for s, st in cat._site_stats.items()
             if st.live_device > 0),
            key=lambda kv: -kv[1])[:cat._top_k])
        out = {
            "device_initialized": True,
            "hbm_budget_bytes": cat.device_budget,
            "hbm_used_bytes": cat.device_bytes,
            "hbm_free_bytes": max(cat.device_budget - cat.device_bytes, 0),
            "hbm_watermark_bytes": cat.watermark_bytes,
            "memory_sites": mem_sites,
            "host_spill_budget_bytes": cat.host_budget,
            "host_spill_used_bytes": cat.host_bytes,
            "spilled_to_host_bytes": cat.spilled_to_host_bytes,
            "spilled_to_disk_bytes": cat.spilled_to_disk_bytes,
            "tiers": {t: {"buffers": n, "bytes": sz}
                      for t, (n, sz) in tiers.items()},
            **extra,
        }
    return out


def emit_health(executor: str | None = None) -> None:
    if _writer is None:
        return
    emit("executor.health", query=None, node=None,
         executor=executor, **health_payload())


class _HealthSampler:
    """Daemon thread emitting executor.health gauges on a fixed period (the
    local stand-in for the shuffle heartbeat thread's sampling duty when no
    transport endpoint is running)."""

    def __init__(self, interval_s: float):
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="srt-eventlog-health")
        self._thread.start()

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                emit_health()
            except Exception:   # noqa: BLE001 — sampling must never crash
                pass

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)


def validate_record(rec: dict) -> list:
    """Schema check for one parsed line; returns a list of violation strings
    (empty = valid). Shared by tools/profiler.py and the tests so the
    enforced schema cannot drift from the emitted one."""
    errs = []
    ev = rec.get("event")
    if not isinstance(ev, str):
        errs.append("missing 'event'")
        return errs
    if ev not in KNOWN_EVENTS:
        errs.append(f"unknown event {ev!r}")
    if not isinstance(rec.get("ts"), (int, float)):
        errs.append(f"{ev}: missing numeric 'ts'")
    if not isinstance(rec.get("t"), (int, float)):
        errs.append(f"{ev}: missing monotonic 't'")
    if "query" not in rec or "node" not in rec:
        errs.append(f"{ev}: missing query/node attribution keys")
    if ev in QUERY_SCOPED_EVENTS and not rec.get("query"):
        errs.append(f"{ev}: query-scoped event without a query id")
    if ev == "query.journey":
        # the journey plane's own schema: without these four fields the
        # cross-replica timeline cannot be assembled, so the profiler
        # treats their absence as a hard violation (journey rc != 0)
        if not rec.get("journey"):
            errs.append("query.journey: missing journey id")
        if not isinstance(rec.get("attempt"), int) or rec["attempt"] < 1:
            errs.append("query.journey: missing positive integer 'attempt'")
        if not rec.get("replica"):
            errs.append("query.journey: missing replica identity")
        if rec.get("outcome") not in JOURNEY_OUTCOMES:
            errs.append(f"query.journey: outcome {rec.get('outcome')!r} "
                        f"not in {sorted(JOURNEY_OUTCOMES)}")
    return errs
