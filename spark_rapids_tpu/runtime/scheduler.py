"""Multi-tenant query lifecycle — admission control, deadlines, cooperative
cancellation, and overload shedding.

The reference plugin leans on Spark's scheduler for this entire lifecycle:
queries queue in the fair scheduler, the driver admits them against executor
resources, and task cancellation propagates through TaskContext. This engine
is standalone, so this module IS that front door: a process-wide
:class:`QueryScheduler` multiplexes concurrent sessions onto the pipelined
executor with three guarantees.

**Admission control.** Every action declares an estimated device-memory
footprint (:func:`estimate_footprint` — scan bytes x a decode-expansion
factor, scaled by the plan's breaker count) and is admitted against the HBM
budget with ``scheduler.maxConcurrent`` concurrency and fair-share +
priority queues. Over-capacity submissions WAIT (fairness = strict
head-of-line on effective priority, where effective priority ages upward by
``scheduler.priority.agingSeconds`` of queue wait so low-priority tenants
cannot starve); a submission that would exceed ``scheduler.queue.maxDepth``
or waits past ``scheduler.queue.timeoutSeconds`` is SHED with a typed,
retryable :class:`QueryRejectedError` carrying a backoff hint — load
shedding at the front door instead of OOM cascades in the engine. The PR-2
OOM retry ladder makes mild over-admission recoverable, so one query is
always admitted when nothing is running (progress guarantee) even if its
estimate exceeds the budget.

**Cooperative cancellation + deadlines.** A :class:`CancelToken` rides the
query's metric collector (every pool/pipeline/broadcast thread already
re-enters that scope — the PR-3/PR-4 attribution pattern), so
:func:`check_cancel` is reachable from every blocking loop: pipeline queue
put/get waits, the scan readahead, semaphore acquisition, shuffle fetch
backoff sleeps, the exchange recompute ladder, the OOM retry ladder, and
every operator's per-batch ``wrap_output`` pull. ``session.cancel(qid)`` or
a ``scheduler.query.deadlineSeconds`` expiry flips the token; the whole
pipeline then drains through the PR-4 clean-cancellation machinery — queue
close callbacks unregister spillable batches, producers observe closed
queues and stop, TaskContext exits release semaphore permits — leaking
neither threads, nor device buffers, nor permits.

**Isolation under failure.** Catalog buffers are tagged with their owning
query; on a strict-budget OOM the retry ladder consults
:meth:`QueryScheduler.on_oom_retry`, which (a) re-checks admission — the
faulting query briefly waits for a peer to release when the scheduler is
over-committed — and (b) applies the fair-share degradation path: when the
faulting query is UNDER its fair share and a lower-priority peer is over
its own, the peer's spillable device buffers are demoted (spilled) instead
of the faulting query paying with splits — the victim chosen by (lowest
priority, most spillable device bytes).

Every transition is visible in the structured event log: query.queued /
query.admitted / query.shed / query.cancelled / query.deadline /
query.demoted, and tools/profiler.py renders an admission/lifecycle table
from them.
"""

from __future__ import annotations

import os
import threading
import time

from spark_rapids_tpu.runtime import metrics as M

# resilience counter names (registered in runtime/metrics.py)
QUERIES_SHED = M.QUERIES_SHED
QUERIES_CANCELLED = M.QUERIES_CANCELLED
QUERY_DEMOTIONS = M.QUERY_DEMOTIONS


# ---------------------------------------------------------------------------
# typed lifecycle errors
# ---------------------------------------------------------------------------

def _rebuild_rejected(msg, backoff_hint_s, query_id, reason, replica=None):
    return QueryRejectedError(msg, backoff_hint_s=backoff_hint_s,
                              query_id=query_id, reason=reason,
                              replica=replica)


class QueryRejectedError(RuntimeError):
    """The scheduler shed this submission (queue full, or queue wait past
    ``scheduler.queue.timeoutSeconds``). ``retryable`` marks it safe to
    resubmit; ``backoff_hint_s`` is the scheduler's estimate of when
    capacity frees up; ``replica`` names the fleet replica that shed (so a
    rotating client can record WHO rejected). Pickles losslessly so a
    serving endpoint can ship it back to a remote client with the hint
    intact."""

    retryable = True

    def __init__(self, msg: str, *, backoff_hint_s: float = 1.0,
                 query_id: str | None = None, reason: str = "shed",
                 replica: str | None = None):
        super().__init__(msg)
        self.backoff_hint_s = backoff_hint_s
        self.query_id = query_id
        self.reason = reason
        self.replica = replica

    def __reduce__(self):
        return (_rebuild_rejected, (str(self), self.backoff_hint_s,
                                    self.query_id, self.reason, self.replica))


def _rebuild_cancelled(cls, msg, query_id, reason):
    return cls(msg, query_id=query_id, reason=reason)


class QueryCancelledError(RuntimeError):
    """The query's CancelToken fired (session.cancel / a chaos ``cancel``
    fault). NOT retryable by the OOM ladder — cancellation must drain the
    pipeline, not re-run it. Pickles losslessly (subclass, query_id and
    reason preserved) so the serving endpoint can ship a drain/disconnect/
    deadline kill to a remote client typed."""

    retryable = False

    def __init__(self, msg: str, *, query_id: str | None = None,
                 reason: str = "cancelled"):
        super().__init__(msg)
        self.query_id = query_id
        self.reason = reason

    def __reduce__(self):
        return (_rebuild_cancelled, (type(self), str(self), self.query_id,
                                     self.reason))


class QueryDeadlineError(QueryCancelledError):
    """The query ran (or queued) past its deadline
    (``scheduler.query.deadlineSeconds``)."""

    def __init__(self, msg: str, *, query_id: str | None = None,
                 reason: str = "deadline"):
        super().__init__(msg, query_id=query_id, reason=reason)


# ---------------------------------------------------------------------------
# cancel token
# ---------------------------------------------------------------------------

class CancelToken:
    """Cooperative cancellation flag + optional deadline for one query.

    The token is carried on the query's QueryMetricsCollector, so every
    thread that re-enters the query's metric scope (pool tasks, pipeline
    stage workers, broadcast builds) can reach it via
    :func:`current_token` without extra plumbing. The deadline is evaluated
    lazily on every :meth:`check` — no watchdog thread."""

    __slots__ = ("query_id", "_event", "_reason", "_deadline")

    def __init__(self, query_id: str | None = None,
                 deadline_s: float | None = None):
        self.query_id = query_id
        self._event = threading.Event()
        self._reason = "cancelled"
        self._deadline = (time.monotonic() + deadline_s
                          if deadline_s and deadline_s > 0 else None)

    def cancel(self, reason: str = "cancelled") -> None:
        if not self._event.is_set():
            self._reason = reason
            self._event.set()

    @property
    def cancelled(self) -> bool:
        return (self._event.is_set()
                or (self._deadline is not None
                    and time.monotonic() >= self._deadline))

    @property
    def reason(self) -> str:
        return self._reason

    def remaining_s(self) -> float | None:
        """Seconds until the deadline (None = no deadline)."""
        if self._deadline is None:
            return None
        return self._deadline - time.monotonic()

    def check(self) -> None:
        """Raise the typed cancellation error if the token fired — the ONE
        call every cooperative blocking loop makes."""
        if self._event.is_set():
            cls = (QueryDeadlineError if self._reason == "deadline"
                   else QueryCancelledError)
            raise cls(f"query {self.query_id} {self._reason}",
                      query_id=self.query_id, reason=self._reason)
        if self._deadline is not None and time.monotonic() >= self._deadline:
            self.cancel("deadline")
            raise QueryDeadlineError(
                f"query {self.query_id} exceeded its deadline",
                query_id=self.query_id)


def current_token() -> CancelToken | None:
    """The ambient query's CancelToken (None outside any scheduled query)."""
    c = M.current_collector()
    return getattr(c, "cancel_token", None) if c is not None else None


def check_cancel() -> None:
    """Cooperative cancellation checkpoint: raises QueryCancelledError /
    QueryDeadlineError when the ambient query was cancelled. A thread-local
    read + None check when no token is armed — cheap enough for per-batch
    and per-wait-tick call sites."""
    tok = current_token()
    if tok is not None:
        tok.check()


# ---------------------------------------------------------------------------
# footprint estimation (admission input)
# ---------------------------------------------------------------------------

# defaults when no conf reaches the estimator; the knobs are
# scheduler.footprint.{decodeExpansion,floorBytes} (config.py). 3x is the
# round-number decode expansion BASELINE.md's scan measurements showed for
# TPC-H
_DECODE_EXPANSION = 3.0
# every pipeline breaker (join build / agg / sort / exchange) holds an extra
# working set of roughly one batch stream alongside the scan
_BREAKER_FACTOR = 0.5
_MIN_FOOTPRINT = 16 << 20


def _static_footprint(plan, conf=None) -> int:
    """The cold-start heuristic: sum of on-disk scan bytes x decode
    expansion, scaled by (1 + 0.5 x breaker count) for
    join-build/agg/sort/exchange working sets, floored (a scanless plan
    still stages batches)."""
    from spark_rapids_tpu import config as CFG
    expansion = (conf.get(CFG.SCHEDULER_FOOTPRINT_DECODE_EXPANSION)
                 if conf is not None else _DECODE_EXPANSION)
    floor = (conf.get(CFG.SCHEDULER_FOOTPRINT_FLOOR)
             if conf is not None else _MIN_FOOTPRINT)
    scan_bytes = 0
    breakers = 0
    seen = set()
    stack = [plan]
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        name = type(node).__name__
        if name in ("JoinNode", "AggregateNode", "SortNode", "ExchangeNode",
                    "WindowNode"):
            breakers += 1
        parts = getattr(node, "partitions", None)
        if parts is not None and name == "FileScanNode":
            for p in parts:
                for path in getattr(p, "paths", ()):
                    try:
                        scan_bytes += os.path.getsize(path)
                    except OSError:
                        pass
        stack.extend(getattr(node, "children", []) or [])
    est = int(scan_bytes * expansion * (1 + _BREAKER_FACTOR * breakers))
    return max(est, int(floor))


def estimate_footprint_ex(plan, conf=None) -> dict:
    """Estimated device-memory footprint of one query plus its provenance:
    {estimate, static, history_hit, fingerprint, prior}. When the plan-shape
    history store (runtime/history.py) holds an observed peak for this
    plan's fingerprint, the observation IS the estimate (floored) — observed
    beats modeled; the static heuristic remains the cold-start fallback.
    The estimate feeds admission only — the strict HBM budget + OOM ladder
    remain the hard enforcement, so a wrong estimate degrades fairness,
    never safety."""
    from spark_rapids_tpu import config as CFG
    static = _static_footprint(plan, conf)
    out = {"estimate": static, "static": static, "history_hit": False,
           "fingerprint": None, "prior": None}
    try:
        from spark_rapids_tpu.plan.fingerprint import plan_fingerprint
        out["fingerprint"] = plan_fingerprint(plan)
    except Exception:   # noqa: BLE001 — fingerprint is advisory, never fatal
        return out
    enabled = conf is None or conf.get(CFG.STATS_HISTORY_ENABLED)
    if not enabled:
        return out
    from spark_rapids_tpu.runtime import history as H
    store = H.get()
    if store is None:
        return out
    try:
        prior = store.lookup(out["fingerprint"])
    except Exception:   # noqa: BLE001 — history is advisory, never fatal
        return out
    if prior is None:
        return out
    out["prior"] = prior
    peak = int(prior.get("peak_device_bytes") or 0)
    if peak > 0:
        floor = (conf.get(CFG.SCHEDULER_FOOTPRINT_FLOOR)
                 if conf is not None else _MIN_FOOTPRINT)
        out["estimate"] = max(peak, int(floor))
        out["history_hit"] = True
        M.counter_add("history.hit")
    return out


def estimate_footprint(plan, conf=None) -> int:
    """int facade over estimate_footprint_ex (existing call sites/tests)."""
    return estimate_footprint_ex(plan, conf)["estimate"]


# ---------------------------------------------------------------------------
# the scheduler
# ---------------------------------------------------------------------------

class _Ticket:
    __slots__ = ("query_id", "estimate", "priority", "token", "enqueue_t",
                 "admitted_t", "state", "description")

    def __init__(self, query_id, estimate, priority, token, description):
        self.query_id = query_id
        self.estimate = estimate
        self.priority = priority
        self.token = token
        self.enqueue_t = time.monotonic()
        self.admitted_t = None
        self.state = "queued"
        self.description = description


class QueryScheduler:
    """Process-wide admission controller (the driver-side scheduler of
    ROADMAP item 2). Like the other process-global switches (Pallas, trace,
    faults), structural knobs are only reconfigured by a session that sets
    them EXPLICITLY; per-query values (priority, deadline, queue timeout,
    estimate) come from the submitting session's conf at submit time."""

    _instance: "QueryScheduler | None" = None
    _ilock = threading.Lock()

    def __init__(self, max_concurrent: int = 4, queue_max_depth: int = 32,
                 aging_s: float = 10.0):
        self.max_concurrent = max(1, int(max_concurrent))
        self.queue_max_depth = max(0, int(queue_max_depth))
        self.aging_s = float(aging_s)
        self._cond = threading.Condition()
        self._running: dict[str, _Ticket] = {}
        self._waiting: list[_Ticket] = []
        # lifetime counters (scheduler-scope observability; per-query shed/
        # cancel counts also land in the resilience registry)
        self.admitted = 0
        self.shed = 0
        self.demotions = 0

    # -- singleton -----------------------------------------------------------
    @classmethod
    def get(cls) -> "QueryScheduler":
        with cls._ilock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    @classmethod
    def reset(cls) -> None:
        with cls._ilock:
            cls._instance = None

    def reconfigure(self, conf) -> None:
        """Apply a session's EXPLICIT scheduler.* structural settings
        (process-global, like the Pallas/trace/faults switches)."""
        from spark_rapids_tpu import config as C
        with self._cond:
            self.max_concurrent = max(1, conf.get(C.SCHEDULER_MAX_CONCURRENT))
            self.queue_max_depth = max(0, conf.get(C.SCHEDULER_QUEUE_MAX_DEPTH))
            self.aging_s = conf.get(C.SCHEDULER_PRIORITY_AGING)
            self._cond.notify_all()

    # -- internals (under self._cond) ---------------------------------------
    @staticmethod
    def _device_budget() -> int:
        from spark_rapids_tpu.runtime.memory import DeviceManager
        dm = DeviceManager._instance
        if dm is None:
            # admission must not force device initialization; a fresh process
            # admits on concurrency alone until the device comes up
            return 1 << 62
        return dm.catalog.device_budget

    def _eff_priority(self, t: _Ticket, now: float) -> float:
        if self.aging_s <= 0:
            return float(t.priority)
        return t.priority + (now - t.enqueue_t) / self.aging_s

    def _head(self, now: float) -> "_Ticket | None":
        if not self._waiting:
            return None
        return min(self._waiting,
                   key=lambda t: (-self._eff_priority(t, now), t.enqueue_t))

    def _admitted_bytes(self) -> int:
        return sum(t.estimate for t in self._running.values())

    def _admissible(self, t: _Ticket) -> bool:
        if len(self._running) >= self.max_concurrent:
            return False
        if not self._running:
            return True   # progress guarantee: an idle engine admits anything
        return self._admitted_bytes() + t.estimate <= self._device_budget()

    def _backoff_hint(self, t: _Ticket, now: float) -> float:
        """Retry-after estimate for a shed query: half the mean admitted
        runtime so far per queue position ahead, floored at 250ms — crude,
        but monotone in load, which is what a client backoff needs."""
        ahead = sum(1 for w in self._waiting
                    if self._eff_priority(w, now) >= self._eff_priority(t, now)
                    and w is not t)
        run_s = [now - r.admitted_t for r in self._running.values()
                 if r.admitted_t is not None]
        mean_run = (sum(run_s) / len(run_s)) if run_s else 1.0
        return round(max(0.25, 0.5 * mean_run * (1 + ahead)), 3)

    # -- submission lifecycle -------------------------------------------------
    def submit(self, query_id: str, estimate: int, *, priority: int = 0,
               token: CancelToken | None = None,
               timeout_s: float | None = None,
               description: str = "") -> _Ticket:
        """Block until admitted; raises QueryRejectedError when shed (queue
        full / wait past timeout_s) and QueryCancelledError /
        QueryDeadlineError when the token fires while queued."""
        from spark_rapids_tpu.runtime import eventlog as EL
        t = _Ticket(query_id, max(0, int(estimate)), int(priority), token,
                    description)
        queued_emitted = False
        with self._cond:
            if len(self._waiting) >= self.queue_max_depth > 0:
                self.shed += 1
                M.resilience_add(QUERIES_SHED)
                hint = self._backoff_hint(t, time.monotonic())
                EL.emit("query.shed", query=query_id, reason="queue_full",
                        queue_depth=len(self._waiting),
                        backoff_hint_s=hint)
                raise QueryRejectedError(
                    f"query {query_id} shed: admission queue full "
                    f"({len(self._waiting)} >= "
                    f"scheduler.queue.maxDepth={self.queue_max_depth}); "
                    f"retry after ~{hint}s",
                    backoff_hint_s=hint, query_id=query_id,
                    reason="queue_full")
            self._waiting.append(t)
            try:
                while True:
                    now = time.monotonic()
                    if self._head(now) is t and self._admissible(t):
                        self._waiting.remove(t)
                        self._running[query_id] = t
                        t.state = "running"
                        t.admitted_t = now
                        self.admitted += 1
                        break
                    if token is not None and token.cancelled:
                        self._waiting.remove(t)
                        self._cond.notify_all()
                        token.check()   # raises the typed error
                    waited = now - t.enqueue_t
                    if timeout_s is not None and 0 < timeout_s <= waited:
                        self._waiting.remove(t)
                        self._cond.notify_all()
                        self.shed += 1
                        M.resilience_add(QUERIES_SHED)
                        hint = self._backoff_hint(t, now)
                        EL.emit("query.shed", query=query_id,
                                reason="queue_timeout",
                                waited_s=round(waited, 4),
                                backoff_hint_s=hint)
                        raise QueryRejectedError(
                            f"query {query_id} shed after queueing "
                            f"{waited:.2f}s (scheduler.queue.timeoutSeconds="
                            f"{timeout_s}); retry after ~{hint}s",
                            backoff_hint_s=hint, query_id=query_id,
                            reason="queue_timeout")
                    if not queued_emitted:
                        queued_emitted = True
                        EL.emit("query.queued", query=query_id,
                                estimate_bytes=t.estimate,
                                priority=t.priority,
                                running=len(self._running),
                                queue_depth=len(self._waiting))
                    self._cond.wait(0.05)
            except BaseException:
                self._cond.notify_all()
                raise
            waited = time.monotonic() - t.enqueue_t
            running = len(self._running)
        # admission queue-wait distribution (STATS histograms / bench
        # percentiles): observed once per admitted query
        M.histogram("admission.wait").observe(waited)
        EL.emit("query.admitted", query=query_id,
                estimate_bytes=t.estimate, priority=t.priority,
                waited_s=round(waited, 4), running=running,
                description=description)
        return t

    def release(self, query_id: str) -> None:
        with self._cond:
            self._running.pop(query_id, None)
            self._cond.notify_all()

    def cancel(self, query_id: str, reason: str = "cancelled") -> bool:
        """Flip the query's CancelToken (running or still queued); the query
        observes it at its next cooperative checkpoint. Returns False for an
        unknown/finished query id."""
        with self._cond:
            t = self._running.get(query_id)
            if t is None:
                t = next((w for w in self._waiting
                          if w.query_id == query_id), None)
            if t is None or t.token is None:
                return False
            t.token.cancel(reason)
            self._cond.notify_all()
        return True

    def stats(self) -> dict:
        """Lifetime counters + instantaneous queue state for the serving
        STATS snapshot (runtime/endpoint.py): admitted/shed/demotions since
        process start, plus running and queued right now."""
        with self._cond:
            return {"admitted": self.admitted, "shed": self.shed,
                    "demotions": self.demotions,
                    "running": len(self._running),
                    "queued": len(self._waiting),
                    "max_concurrent": self.max_concurrent}

    def active_queries(self) -> list:
        """[{query, state, estimate_bytes, priority, waited_s|running_s}]
        for every queued or running query — the serving endpoint's ps."""
        now = time.monotonic()
        with self._cond:
            out = []
            for t in self._running.values():
                out.append({"query": t.query_id, "state": "running",
                            "estimate_bytes": t.estimate,
                            "priority": t.priority,
                            "description": t.description,
                            "running_s": round(now - (t.admitted_t or now), 4)})
            for t in self._waiting:
                out.append({"query": t.query_id, "state": "queued",
                            "estimate_bytes": t.estimate,
                            "priority": t.priority,
                            "description": t.description,
                            "waited_s": round(now - t.enqueue_t, 4)})
            return out

    # -- OOM escalation hooks (called from runtime/retry.py) ------------------
    def on_oom_retry(self, query_id: str | None = None) -> int:
        """The retry ladder hit a retryable device OOM. Two duties:

        1. **Fair-share demotion**: when the faulting query is at/under its
           fair share (budget / running count) and a peer is over its own,
           spill the victim's spillable device buffers — the peer pays with
           a (recoverable) unspill, not the under-share faulting query with
           splits. Victim = (lowest priority, most device bytes).
        2. **Admission re-check**: when admitted estimates exceed the
           budget (over-admission), briefly wait for a peer to release
           before retrying — bounded to 1s and token-interruptible, so it
           can improve the retry's odds but never deadlock.

        Returns bytes demoted (0 when no rebalance applied)."""
        qid = query_id if query_id is not None else M.current_query_id()
        if qid is None:
            return 0
        from spark_rapids_tpu.runtime import eventlog as EL
        from spark_rapids_tpu.runtime.memory import DeviceManager
        dm = DeviceManager._instance
        victim = None
        with self._cond:
            me = self._running.get(qid)
            if me is None or len(self._running) <= 1 or dm is None:
                return 0
            cat = dm.catalog
            usage = cat.query_device_bytes()
            share = cat.device_budget / max(1, len(self._running))
            if usage.get(qid, 0) <= share:
                over = [t for t in self._running.values()
                        if t.query_id != qid
                        and usage.get(t.query_id, 0) > share
                        and t.priority <= me.priority]
                if over:
                    victim = min(over, key=lambda t: (
                        t.priority, -usage.get(t.query_id, 0)))
        demoted = 0
        if victim is not None:
            demoted = dm.catalog.spill_query_device(victim.query_id)
            if demoted:
                self.demotions += 1
                M.resilience_add(QUERY_DEMOTIONS)
                EL.emit("query.demoted", query=victim.query_id,
                        faulting_query=qid, bytes=demoted)
        # admission re-check: over-committed estimates → wait briefly for a
        # peer to finish so the retry runs against a lighter device tier
        deadline = time.monotonic() + 1.0
        with self._cond:
            while (len(self._running) > 1
                   and self._admitted_bytes() > self._device_budget()
                   and time.monotonic() < deadline):
                me = self._running.get(qid)
                if me is not None and me.token is not None:
                    me.token.check()
                self._cond.wait(0.05)
        return demoted


def on_oom_retry() -> int:
    """Module-level hook for runtime/retry.py: no-op (0) when no scheduler
    instance exists yet — the ladder must not conjure one mid-OOM."""
    sched = QueryScheduler._instance
    if sched is None:
        return 0
    return sched.on_oom_retry()
