"""Trace ranges — the NVTX analog.

Reference: NvtxWithMetrics.scala:42 couples an NVTX range with a timing metric;
ranges wrap every hot region (GpuSemaphore.scala:107, aggregate.scala:356) and are
viewed in Nsight. TPU equivalent: jax.profiler.TraceAnnotation ranges viewable in
Perfetto/XProf, coupled to GpuMetric timers, gated by spark.rapids.tpu.sql.trace.enabled."""

from __future__ import annotations

import collections
import contextlib
import time

from spark_rapids_tpu.runtime import metrics as _metrics

_enabled = False

# zero-duration span events (oom.retry / oom.split / fetch.recompute …): a
# bounded in-memory ring that chaos tests and postmortems read regardless of
# whether the profiler is capturing; with tracing enabled each event also
# lands as a profiler annotation, and with the event log configured it is
# appended there too (runtime/eventlog.py)
_events: "collections.deque" = collections.deque(maxlen=512)


def span_event(name: str, **attrs) -> None:
    # tag with the ambient query id so concurrent sessions/tests can filter
    # the process-global ring down to their own query (recent_events(query=))
    qid = _metrics.current_query_id()
    if qid is not None:
        attrs = dict(attrs, query=qid)
    _events.append((name, attrs))
    from spark_rapids_tpu.runtime import eventlog
    if eventlog.enabled():
        eventlog.emit(name, **attrs)
    if _enabled:
        import jax
        label = name + ("[" + ",".join(f"{k}={v}" for k, v in attrs.items())
                        + "]" if attrs else "")
        with jax.profiler.TraceAnnotation(label):
            pass


def recent_events(name: str | None = None, query: str | None = None) -> list:
    """Ring contents, optionally filtered by event name and/or the query id
    the event was tagged with (query=None returns every event regardless)."""
    evs = list(_events)
    if name is not None:
        evs = [e for e in evs if e[0] == name]
    if query is not None:
        evs = [e for e in evs if e[1].get("query") == query]
    return evs


def clear_events() -> None:
    _events.clear()


def set_enabled(v: bool):
    global _enabled
    _enabled = bool(v)


@contextlib.contextmanager
def trace_range(name: str, metric=None):
    """NvtxWithMetrics analog: profiler annotation + optional timing metric."""
    t0 = time.perf_counter_ns() if metric is not None else 0
    with contextlib.ExitStack() as stack:
        if _enabled:
            import jax
            stack.enter_context(jax.profiler.TraceAnnotation(name))
        try:
            yield
        finally:
            if metric is not None:
                metric.add(time.perf_counter_ns() - t0)


_profiling = False
_profile_dir = None


def start_profile(outdir: str) -> None:
    """Whole-session XProf capture (idempotent; stopped at interpreter
    exit — use stop_profile() to flush earlier in long-lived processes).
    Viewable in Perfetto/XProf — the Nsight-workflow analog."""
    global _profiling, _profile_dir
    if _profiling:
        if outdir != _profile_dir:
            import warnings
            warnings.warn(
                f"profiler already capturing to {_profile_dir}; "
                f"ignoring profile.dir={outdir}", stacklevel=2)
        return
    _profile_dir = outdir
    import atexit
    import jax
    jax.profiler.start_trace(outdir)
    _profiling = True

    atexit.register(stop_profile)


def stop_profile() -> None:
    """Flush and stop the capture (safe to call when not profiling). The
    atexit hook registered by start_profile is removed so repeated
    start/stop cycles don't stack handlers."""
    global _profiling
    if _profiling:
        import atexit
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _profiling = False
        atexit.unregister(stop_profile)
