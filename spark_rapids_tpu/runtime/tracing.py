"""Trace ranges — the NVTX analog, plus the cross-process span plane.

Reference: NvtxWithMetrics.scala:42 couples an NVTX range with a timing metric;
ranges wrap every hot region (GpuSemaphore.scala:107, aggregate.scala:356) and are
viewed in Nsight. TPU equivalent: jax.profiler.TraceAnnotation ranges viewable in
Perfetto/XProf, coupled to GpuMetric timers, gated by spark.rapids.tpu.sql.trace.enabled.

Distributed spans (spark.rapids.tpu.trace.dir): the reference views
whole-cluster execution in Nsight because NVTX ranges from every process land
in one capture. Here each process appends its ranges to its own JSONL span
file (``spans-<pid>-<stamp>.jsonl``) tagged with a per-query **trace id** that
propagates across every process boundary — the MiniCluster task protocol,
shuffle-transport frame headers, and the endpoint SUBMIT frame — so
``tools/profiler.py trace <dir>`` can merge them into one Chrome-trace
timeline (Perfetto) with per-process clock-offset correction
(runtime/eventlog.set_clock_offset, measured by the driver's two-timestamp
handshake exchange) and walk the critical path.

Span record schema (validate_span):
  name  str    range name (trace_range/span) or event name (span_event) or
               counter track name (counter)
  ph    "X"|"i"|"C"  complete span | zero-duration instant | counter sample
                     (args = {series: number}, the Chrome counter-track form
                     the memory plane uses for per-tier occupancy lanes)
  ts    float  wall-clock epoch seconds at span start (LOCAL clock)
  dur   float  seconds (ph == "X" only)
  pid   int    writing process
  proc  str    process label ("driver", "executor-N", ...)
  tid   str    thread name (pipeline edges appear as their srt-pipe-* lanes)
  trace str|None  the query's trace id (None for out-of-query spans)
  off   float  clock offset toward the driver (omitted when 0)
  args  dict   optional attributes
"""

from __future__ import annotations

import collections
import contextlib
import datetime
import json
import os
import threading
import time

from spark_rapids_tpu.runtime import eventlog as _eventlog
from spark_rapids_tpu.runtime import metrics as _metrics

_enabled = False

# zero-duration span events (oom.retry / oom.split / fetch.recompute …): a
# bounded in-memory ring that chaos tests and postmortems read regardless of
# whether the profiler is capturing; with tracing enabled each event also
# lands as a profiler annotation, and with the event log configured it is
# appended there too (runtime/eventlog.py)
_events: "collections.deque" = collections.deque(maxlen=512)


# ---------------------------------------------------------------------------
# trace context: which query's trace do spans on this thread belong to
# ---------------------------------------------------------------------------

_trace_tls = threading.local()
# per-process default (MiniCluster executors run one task at a time, so the
# task loop pins the whole process — including pipeline worker threads that
# never re-enter a collector scope — to the task's trace id)
_process_trace: "str | None" = None


def current_trace_id() -> "str | None":
    """The trace id spans on this thread are tagged with: an explicit
    thread-local trace_context() (transport server threads serving a remote
    fetch), else the ambient query collector's trace id (driver-side worker
    threads re-enter that scope), else the process default (executor task
    loops)."""
    tid = getattr(_trace_tls, "trace", None)
    if tid is not None:
        return tid
    c = _metrics.current_collector()
    if c is not None:
        return getattr(c, "trace_id", None) or c.query_id
    return _process_trace


@contextlib.contextmanager
def trace_context(trace_id: "str | None"):
    """Pin this thread's spans to `trace_id` (None = no-op passthrough to
    the ambient lookup)."""
    prev = getattr(_trace_tls, "trace", None)
    _trace_tls.trace = trace_id
    try:
        yield
    finally:
        _trace_tls.trace = prev


def set_process_trace(trace_id: "str | None") -> None:
    """Pin the whole PROCESS to `trace_id` (executor task loops: worker
    threads spawned by the pipelined executor inherit it without any
    collector plumbing)."""
    global _process_trace
    _process_trace = trace_id


# one-shot trace-id handoff into the next collector created on this thread
# (the endpoint worker thread sets the client's SUBMIT trace id here before
# running the action; session._run_action takes it)
def set_pending_trace(trace_id: "str | None") -> None:
    _trace_tls.pending = trace_id


def take_pending_trace() -> "str | None":
    t = getattr(_trace_tls, "pending", None)
    _trace_tls.pending = None
    return t


# executor-side event-log records fall back to the ambient trace id for
# their `query` tag (see eventlog.set_query_fallback) — registered at the
# bottom of this module once current_trace_id exists


def estimate_clock_offset(t_local_send: float, t_remote: float,
                          t_local_recv: float) -> float:
    """Two-timestamp offset estimate: assuming symmetric message latency,
    remote_clock + offset ≈ local_clock. Error is bounded by half the
    round-trip time."""
    return (t_local_send + t_local_recv) / 2.0 - t_remote


# ---------------------------------------------------------------------------
# span sink: per-process JSONL span files
# ---------------------------------------------------------------------------

class SpanWriter:
    """Append-only JSONL span sink, one file per process per configure."""

    def __init__(self, path: str, process: str):
        self.path = path
        self.process = process
        self._f = open(path, "a", encoding="utf-8")
        self._lock = threading.Lock()

    def write(self, rec: dict) -> None:
        line = json.dumps(rec, separators=(",", ":"), default=str)
        with self._lock:
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass


_span_writer: "SpanWriter | None" = None


def configure_spans(directory: str, process: "str | None" = None) -> str:
    """Open a span file under `directory` (created if missing) and make it
    this process's sink; returns the file path. `process` labels the
    Perfetto process lane ("driver", "executor-3", ...)."""
    global _span_writer
    os.makedirs(directory, exist_ok=True)
    # microsecond stamp: same collision guard as the event log's configure
    stamp = datetime.datetime.now().strftime("%Y%m%d-%H%M%S-%f")
    path = os.path.join(directory, f"spans-{os.getpid()}-{stamp}.jsonl")
    if _span_writer is not None:
        _span_writer.close()
    _span_writer = SpanWriter(path, process or f"pid{os.getpid()}")
    return path


def spans_enabled() -> bool:
    return _span_writer is not None


def span_path() -> "str | None":
    w = _span_writer
    return w.path if w is not None else None


def shutdown_spans() -> None:
    global _span_writer
    if _span_writer is not None:
        _span_writer.close()
        _span_writer = None


def _emit_span(name: str, ph: str, ts: float, dur: "float | None",
               attrs: "dict | None") -> None:
    w = _span_writer
    if w is None:
        return
    rec = {"name": name, "ph": ph, "ts": ts, "pid": os.getpid(),
           "proc": w.process, "tid": threading.current_thread().name,
           "trace": current_trace_id()}
    if dur is not None:
        rec["dur"] = dur
    off = _eventlog.clock_offset()
    if off:
        rec["off"] = off
    if attrs:
        rec["args"] = attrs
    w.write(rec)


@contextlib.contextmanager
def span(name: str, **attrs):
    """Explicit span-file range (the trace_range analog for regions that
    have no metric and no NVTX need: tasks, pipeline segments, fetches).
    Free when no span sink is configured."""
    if _span_writer is None:
        yield
        return
    ts = time.time()
    t0 = time.perf_counter_ns()
    try:
        yield
    finally:
        if _span_writer is not None:
            _emit_span(name, "X", ts, (time.perf_counter_ns() - t0) / 1e9,
                       attrs)


def instant(name: str, **attrs) -> None:
    """Span-file-only zero-duration instant (no event-log or ring
    forwarding — for records whose analysis copy is already emitted
    elsewhere, e.g. spill-tier transitions next to the memory counter
    lanes). Free when no span sink is configured."""
    if _span_writer is not None:
        _emit_span(name, "i", time.time(), None, attrs or None)


def counter(name: str, values: dict) -> None:
    """Chrome counter-track sample (ph "C"): `values` maps series name to a
    number; Perfetto renders one stacked counter lane per (process, name).
    The memory plane emits its per-tier occupancy here so HBM/host/disk
    levels plot alongside the span lanes. Free when no sink is
    configured."""
    if _span_writer is not None:
        _emit_span(name, "C", time.time(), None, dict(values))


def validate_span(rec: dict) -> list:
    """Schema check for one parsed span record; returns violation strings
    (empty = valid). Shared by tools/profiler.py trace and the tests."""
    errs = []
    if not isinstance(rec.get("name"), str):
        errs.append("missing 'name'")
        return errs
    name = rec["name"]
    if rec.get("ph") not in ("X", "i", "C"):
        errs.append(f"{name}: ph must be 'X', 'i' or 'C'")
    if not isinstance(rec.get("ts"), (int, float)):
        errs.append(f"{name}: missing numeric 'ts'")
    if rec.get("ph") == "X" and not isinstance(rec.get("dur"), (int, float)):
        errs.append(f"{name}: X span without numeric 'dur'")
    if rec.get("ph") == "C" and not isinstance(rec.get("args"), dict):
        errs.append(f"{name}: C counter sample without an args series dict")
    if not isinstance(rec.get("pid"), int):
        errs.append(f"{name}: missing int 'pid'")
    if not isinstance(rec.get("tid"), str):
        errs.append(f"{name}: missing thread name 'tid'")
    return errs


# ---------------------------------------------------------------------------
# span events + ranges
# ---------------------------------------------------------------------------

def span_event(name: str, **attrs) -> None:
    # tag with the ambient query id so concurrent sessions/tests can filter
    # the process-global ring down to their own query (recent_events(query=))
    qid = _metrics.current_query_id()
    if qid is not None:
        attrs = dict(attrs, query=qid)
    _events.append((name, attrs))
    if _eventlog.enabled():
        _eventlog.emit(name, **attrs)
    if _span_writer is not None:
        _emit_span(name, "i", time.time(), None, attrs)
    if _enabled:
        import jax
        # label construction stays behind the enable check: formatting every
        # attr dict on a disabled path costs real time at batch granularity
        label = name + ("[" + ",".join(f"{k}={v}" for k, v in attrs.items())
                        + "]" if attrs else "")
        with jax.profiler.TraceAnnotation(label):
            pass


def recent_events(name: str | None = None, query: str | None = None) -> list:
    """Ring contents, optionally filtered by event name and/or the query id
    the event was tagged with (query=None returns every event regardless)."""
    evs = list(_events)
    if name is not None:
        evs = [e for e in evs if e[0] == name]
    if query is not None:
        evs = [e for e in evs if e[1].get("query") == query]
    return evs


def clear_events() -> None:
    _events.clear()


def set_enabled(v: bool):
    global _enabled
    _enabled = bool(v)


@contextlib.contextmanager
def trace_range(name: str, metric=None):
    """NvtxWithMetrics analog: profiler annotation + optional timing metric
    + (when a span sink is configured) a span-file range, so every
    NVTX-wrapped hot region lands on the merged distributed timeline for
    free."""
    w = _span_writer
    need_t = metric is not None or w is not None
    t0 = time.perf_counter_ns() if need_t else 0
    ts = time.time() if w is not None else 0.0
    with contextlib.ExitStack() as stack:
        if _enabled:
            import jax
            stack.enter_context(jax.profiler.TraceAnnotation(name))
        try:
            yield
        finally:
            if need_t:
                dt = time.perf_counter_ns() - t0
                if metric is not None:
                    metric.add(dt)
                if w is not None and _span_writer is not None:
                    _emit_span(name, "X", ts, dt / 1e9, None)


_profiling = False
_profile_dir = None


def start_profile(outdir: str) -> None:
    """Whole-session XProf capture (idempotent; stopped at interpreter
    exit — use stop_profile() to flush earlier in long-lived processes).
    Viewable in Perfetto/XProf — the Nsight-workflow analog."""
    global _profiling, _profile_dir
    if _profiling:
        if outdir != _profile_dir:
            import warnings
            warnings.warn(
                f"profiler already capturing to {_profile_dir}; "
                f"ignoring profile.dir={outdir}", stacklevel=2)
        return
    _profile_dir = outdir
    import atexit
    import jax
    jax.profiler.start_trace(outdir)
    _profiling = True

    atexit.register(stop_profile)


def stop_profile() -> None:
    """Flush and stop the capture (safe to call when not profiling). The
    atexit hook registered by start_profile is removed so repeated
    start/stop cycles don't stack handlers."""
    global _profiling
    if _profiling:
        import atexit
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _profiling = False
        atexit.unregister(stop_profile)


_eventlog.set_query_fallback(current_trace_id)
