"""Trace ranges — the NVTX analog.

Reference: NvtxWithMetrics.scala:42 couples an NVTX range with a timing metric;
ranges wrap every hot region (GpuSemaphore.scala:107, aggregate.scala:356) and are
viewed in Nsight. TPU equivalent: jax.profiler.TraceAnnotation ranges viewable in
Perfetto/XProf, coupled to GpuMetric timers, gated by spark.rapids.tpu.sql.trace.enabled."""

from __future__ import annotations

import time
from contextlib import contextmanager

_enabled = False


def set_enabled(v: bool):
    global _enabled
    _enabled = bool(v)


@contextmanager
def trace_range(name: str, metric=None):
    """NvtxWithMetrics analog: profiler annotation + optional timing metric."""
    t0 = time.perf_counter_ns() if metric is not None else 0
    if _enabled:
        import jax
        with jax.profiler.TraceAnnotation(name):
            try:
                yield
            finally:
                if metric is not None:
                    metric.add(time.perf_counter_ns() - t0)
    else:
        try:
            yield
        finally:
            if metric is not None:
                metric.add(time.perf_counter_ns() - t0)
