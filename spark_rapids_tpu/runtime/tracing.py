"""Trace ranges — the NVTX analog.

Reference: NvtxWithMetrics.scala:42 couples an NVTX range with a timing metric;
ranges wrap every hot region (GpuSemaphore.scala:107, aggregate.scala:356) and are
viewed in Nsight. TPU equivalent: jax.profiler.TraceAnnotation ranges viewable in
Perfetto/XProf, coupled to GpuMetric timers, gated by spark.rapids.tpu.sql.trace.enabled."""

from __future__ import annotations

import collections
import time
from contextlib import contextmanager

_enabled = False

# zero-duration span events (oom.retry / oom.split / fetch.recompute …): a
# bounded in-memory ring that chaos tests and postmortems read regardless of
# whether the profiler is capturing; with tracing enabled each event also
# lands as a profiler annotation
_events: "collections.deque" = collections.deque(maxlen=512)


def span_event(name: str, **attrs) -> None:
    _events.append((name, attrs))
    if _enabled:
        import jax
        label = name + ("[" + ",".join(f"{k}={v}" for k, v in attrs.items())
                        + "]" if attrs else "")
        with jax.profiler.TraceAnnotation(label):
            pass


def recent_events(name: str | None = None) -> list:
    evs = list(_events)
    return evs if name is None else [e for e in evs if e[0] == name]


def clear_events() -> None:
    _events.clear()


def set_enabled(v: bool):
    global _enabled
    _enabled = bool(v)


@contextmanager
def trace_range(name: str, metric=None):
    """NvtxWithMetrics analog: profiler annotation + optional timing metric."""
    t0 = time.perf_counter_ns() if metric is not None else 0
    if _enabled:
        import jax
        with jax.profiler.TraceAnnotation(name):
            try:
                yield
            finally:
                if metric is not None:
                    metric.add(time.perf_counter_ns() - t0)
    else:
        try:
            yield
        finally:
            if metric is not None:
                metric.add(time.perf_counter_ns() - t0)


_profiling = False
_profile_dir = None


def start_profile(outdir: str) -> None:
    """Whole-session XProf capture (idempotent; stopped at interpreter
    exit — use stop_profile() to flush earlier in long-lived processes).
    Viewable in Perfetto/XProf — the Nsight-workflow analog."""
    global _profiling, _profile_dir
    if _profiling:
        if outdir != _profile_dir:
            import warnings
            warnings.warn(
                f"profiler already capturing to {_profile_dir}; "
                f"ignoring profile.dir={outdir}", stacklevel=2)
        return
    _profile_dir = outdir
    import atexit
    import jax
    jax.profiler.start_trace(outdir)
    _profiling = True

    atexit.register(stop_profile)


def stop_profile() -> None:
    """Flush and stop the capture (safe to call when not profiling)."""
    global _profiling
    if _profiling:
        import jax
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        _profiling = False
