"""Operator metrics — the GpuMetric analog.

Reference: GpuExec.scala:32-140: GpuMetric wraps SQLMetric with levels
ESSENTIAL/MODERATE/DEBUG gated by spark.rapids.sql.metrics.level; ~25 standard names
(NUM_OUTPUT_ROWS, OP_TIME, SEMAPHORE_WAIT_TIME, SPILL bytes per tier, …) and
makeSpillCallback feeding spill bytes back into the running operator's metrics."""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

_LEVELS = {"ESSENTIAL": ESSENTIAL, "MODERATE": MODERATE, "DEBUG": DEBUG}

# standard metric names (reference GpuExec.scala:42-67)
NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
OP_TIME = "opTime"
TOTAL_TIME = "totalTime"
SEMAPHORE_WAIT_TIME = "semaphoreWaitTime"
PEAK_DEVICE_MEMORY = "peakDevMemory"
SPILL_AMOUNT = "spillData"
SPILL_AMOUNT_DISK = "spillDisk"
SPILL_AMOUNT_HOST = "spillHost"
BUILD_TIME = "buildTime"
JOIN_TIME = "joinTime"
SORT_TIME = "sortTime"
AGG_TIME = "computeAggTime"
CONCAT_TIME = "concatTime"
READ_FS_TIME = "readFsTime"
WRITE_TIME = "writeTime"
PARTITION_TIME = "partitionTime"
COLLECT_TIME = "collectTime"
NUM_PARTITIONS = "partitions"

# resilience counters (reference: RmmRapidsRetryIterator retry/split counts
# surfaced through GpuMetric, RapidsShuffleIterator fetch-failure accounting)
NUM_OOM_RETRIES = "numOomRetries"
NUM_OOM_SPLIT_RETRIES = "numOomSplitRetries"
OOM_SPILL_BYTES = "oomRetrySpillBytes"
FETCH_RETRIES = "fetchRetries"
FETCH_FAILOVERS = "fetchFailovers"
FETCH_RECOMPUTES = "fetchRecomputes"

RESILIENCE_METRICS = (NUM_OOM_RETRIES, NUM_OOM_SPLIT_RETRIES, OOM_SPILL_BYTES,
                      FETCH_RETRIES, FETCH_FAILOVERS, FETCH_RECOMPUTES)


class GpuMetric:
    __slots__ = ("name", "level", "_value", "_lock", "_pending")

    def __init__(self, name: str, level: int = MODERATE):
        self.name = name
        self.level = level
        self._value = 0
        self._lock = threading.Lock()
        self._pending = []

    def add(self, v):
        with self._lock:
            self._value += int(v)

    def add_lazy(self, v):
        """Accumulate a possibly-device scalar WITHOUT forcing a host sync;
        pending scalars are folded into the value at read time (value())."""
        if isinstance(v, int):
            self.add(v)
            return
        with self._lock:
            self._pending.append(v)

    def set(self, v):
        with self._lock:
            self._value = int(v)

    @property
    def value(self):
        with self._lock:
            if self._pending:
                for v in self._pending:
                    self._value += int(v)
                self._pending = []
            return self._value

    @contextmanager
    def timed(self):
        """Time a region in nanoseconds (reference NvtxWithMetrics couples a trace
        range with a timing metric — see runtime/tracing.py for the range side)."""
        t0 = time.perf_counter_ns()
        try:
            yield
        finally:
            self.add(time.perf_counter_ns() - t0)

    def __repr__(self):
        return f"GpuMetric({self.name}={self._value})"


class _NoopMetric(GpuMetric):
    """Stand-in for metrics above the configured level: all updates are dropped."""

    def add(self, v):
        pass

    def set(self, v):
        pass


class MetricsRegistry:
    """Per-operator metric set filtered by the configured level."""

    def __init__(self, level_name: str = "MODERATE"):
        self.level = _LEVELS.get(level_name.upper(), MODERATE)
        self._metrics: dict[str, GpuMetric] = {}

    def metric(self, name: str, level: int = MODERATE) -> GpuMetric:
        if name not in self._metrics:
            cls = _NoopMetric if level > self.level else GpuMetric
            self._metrics[name] = cls(name, level)
        return self._metrics[name]

    def snapshot(self):
        return {n: m.value for n, m in self._metrics.items() if m.level <= self.level}


# -- process-wide resilience registry ----------------------------------------
# Retry/split/fetch-failover counts outlive any one operator's registry (a
# retry may span operator teardown), so they accumulate here; chaos tests
# (tests/test_retry_faults.py) and bench.py's `resilience` JSON field read
# whole-query totals from this registry.

_global_registry: "MetricsRegistry | None" = None
_global_lock = threading.Lock()


def global_registry() -> MetricsRegistry:
    global _global_registry
    with _global_lock:
        if _global_registry is None:
            _global_registry = MetricsRegistry("DEBUG")
        return _global_registry


def reset_global_registry() -> None:
    global _global_registry
    with _global_lock:
        _global_registry = None


def resilience_snapshot() -> dict:
    """All resilience counters (zeros included) — the shape bench.py records."""
    g = global_registry()
    return {name: g.metric(name).value for name in RESILIENCE_METRICS}
